"""Headline benchmark: anomaly-scored metrics/sec on one chip.

Measures the full per-record pipeline at steady state — fused device step
(encode -> SP -> TM -> raw score, chunked scan dispatches) plus the host-side
batched anomaly likelihood — over a synthetic cluster workload on the
cluster preset (BASELINE.md config 3/5 shape). Baseline is the north-star
target of 100k concurrent 1s-cadence streams scored on a single chip
(BASELINE.json), so vs_baseline = value / 100_000.

Prints exactly ONE JSON line on stdout; progress goes to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def run_bench(group_size: int, chunk_ticks: int, measure_chunks: int = 3) -> float:
    import jax

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.service.registry import StreamGroup

    cfg = cluster_preset()
    ids = [f"bench{i:06d}" for i in range(group_size)]
    grp = StreamGroup(cfg, ids, backend="tpu")

    rng = np.random.Generator(np.random.Philox(key=(2026, 7)))
    t_idx = np.arange(chunk_ticks)[:, None]
    base = 35.0 + 20.0 * np.sin(2 * np.pi * (t_idx + rng.integers(0, 86400, group_size)[None, :]) / 86400.0)
    vals = (base + rng.normal(0, 3.0, (chunk_ticks, group_size))).astype(np.float32)
    ts = (1_700_000_000 + t_idx + np.zeros((1, group_size))).astype(np.int64)

    # warmup: compile + one chunk of real stepping
    t0 = time.perf_counter()
    grp.run_chunk(vals, ts)
    log(f"warmup (compile + first chunk): {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    for i in range(measure_chunks):
        grp.run_chunk(vals, ts + (i + 1) * chunk_ticks)
    dt = time.perf_counter() - t0
    scored = measure_chunks * chunk_ticks * group_size
    return scored / dt


def main() -> None:
    target = 100_000.0  # metrics/sec/chip north star (BASELINE.json)
    attempts = [(2048, 64), (1024, 64), (256, 32), (64, 16)]
    value = None
    for group_size, chunk_ticks in attempts:
        try:
            log(f"bench attempt: G={group_size}, T={chunk_ticks}")
            value = run_bench(group_size, chunk_ticks)
            break
        except Exception as e:  # OOM / compile failure on small hosts: retry smaller
            log(f"G={group_size} failed: {type(e).__name__}: {str(e)[:200]}")
    if value is None:
        raise SystemExit("all bench configurations failed")
    print(
        json.dumps(
            {
                "metric": "anomaly_scored_metrics_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "metrics/s",
                "vs_baseline": round(value / target, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
