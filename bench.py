"""Headline benchmark: anomaly-scored metrics/sec on one chip.

Measures the full per-record pipeline at steady state — fused device step
(encode -> SP -> TM -> raw score, chunked scan dispatches) plus the host-side
batched anomaly likelihood — over a synthetic cluster workload on the
cluster preset (BASELINE.md config 3/5 shape). Baseline is the north-star
target of 100k concurrent 1s-cadence streams scored on a single chip
(BASELINE.json), so vs_baseline = value / 100_000.

Prints exactly ONE JSON line on stdout; progress goes to stderr.

Unkillable-by-design (round-2 postmortem: a single slow G=2048 compile
starved every fallback and the round ended with rc=124 and no number):

- every attempt runs in a SUBPROCESS with a hard wall-clock budget, so one
  hung compile or a wedged TPU tunnel can never eat the whole bench window;
- a guaranteed-cheap config runs FIRST, so a number exists within minutes;
- the persistent XLA compilation cache is enabled (``.jax_cache/``), so
  retries and later rounds skip recompilation;
- transient backend errors (UNAVAILABLE / tunnel flake) get one retry;
- SIGTERM/SIGINT print the best result so far before exiting — a driver
  timeout still yields the JSON line.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
TARGET = 100_000.0  # metrics/sec/chip north star (BASELINE.json)

# (group_size, chunk_ticks): the cheap anchor first, then ascending toward
# the HBM frontier. Attempt order is also failure-isolation order — a big-G
# OOM or compile stall costs only its own budget.
ATTEMPTS = [(256, 64), (2048, 64), (8192, 64), (16384, 64), (32768, 64)]


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------- child ----


def run_attempt(group_size: int, chunk_ticks: int, measure_chunks: int = 3) -> dict:
    import jax

    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.service.registry import StreamGroup

    cfg = cluster_preset()
    ids = [f"bench{i:06d}" for i in range(group_size)]
    t0 = time.perf_counter()
    grp = StreamGroup(cfg, ids, backend="tpu")
    log(f"  state init + device_put: {time.perf_counter() - t0:.1f}s")

    rng = np.random.Generator(np.random.Philox(key=(2026, 7)))
    t_idx = np.arange(chunk_ticks)[:, None]
    base = 35.0 + 20.0 * np.sin(
        2 * np.pi * (t_idx + rng.integers(0, 86400, group_size)[None, :]) / 86400.0
    )
    vals = (base + rng.normal(0, 3.0, (chunk_ticks, group_size))).astype(np.float32)
    ts = (1_700_000_000 + t_idx + np.zeros((1, group_size))).astype(np.int64)

    # warmup: compile + one chunk of real stepping
    t0 = time.perf_counter()
    grp.run_chunk(vals, ts)
    log(f"  warmup (compile + first chunk): {time.perf_counter() - t0:.1f}s")

    # steady state, pipelined: dispatch chunk i+1 before collecting chunk i so
    # host likelihood + fetch overlap device compute (SURVEY.md §7 hard part 3)
    t0 = time.perf_counter()
    pending = grp.dispatch_chunk(vals, ts + chunk_ticks)
    for i in range(1, measure_chunks):
        nxt = grp.dispatch_chunk(vals, ts + (i + 1) * chunk_ticks)
        grp.collect_chunk(pending)
        pending = nxt
    grp.collect_chunk(pending)
    dt = time.perf_counter() - t0
    scored = measure_chunks * chunk_ticks * group_size
    return {"value": scored / dt, "G": group_size, "T": chunk_ticks, "wall_s": round(dt, 2)}


# --------------------------------------------------------------- parent ----


def emit(best: dict | None) -> None:
    if best is None:
        return
    print(
        json.dumps(
            {
                "metric": "anomaly_scored_metrics_per_sec_per_chip",
                "value": round(best["value"], 1),
                "unit": "metrics/s",
                "vs_baseline": round(best["value"] / TARGET, 4),
            }
        ),
        flush=True,
    )


def main() -> None:
    budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    per_attempt = float(os.environ.get("BENCH_ATTEMPT_BUDGET_S", "330"))
    t_start = time.monotonic()
    best: dict | None = None
    done = False
    current_proc: list = [None]

    def on_signal(signum, frame):
        log(f"bench: signal {signum}, emitting best-so-far")
        if current_proc[0] is not None and current_proc[0].poll() is None:
            current_proc[0].kill()  # never orphan a TPU-holding child
        if not done:
            emit(best)
        sys.exit(0 if best is not None else 1)

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    os.makedirs(CACHE_DIR, exist_ok=True)
    for group_size, chunk_ticks in ATTEMPTS:
        remaining = budget - (time.monotonic() - t_start)
        # never start an attempt we can't give a meaningful slice of budget
        if remaining < 60:
            log(f"bench: {remaining:.0f}s left, stopping attempts")
            break
        for attempt in range(2):  # one retry on transient backend errors
            this_budget = min(per_attempt, budget - (time.monotonic() - t_start))
            if this_budget < 60:
                break
            log(f"bench attempt: G={group_size}, T={chunk_ticks} (budget {this_budget:.0f}s)")
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--attempt",
                 str(group_size), str(chunk_ticks)],
                stdout=subprocess.PIPE, stderr=sys.stderr, text=True,
            )
            current_proc[0] = proc
            try:
                out, _ = proc.communicate(timeout=this_budget)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                log(f"  G={group_size}: killed at budget ({this_budget:.0f}s)")
                break  # a timeout is not transient; don't retry, move on
            finally:
                current_proc[0] = None
            res = None
            if proc.returncode == 0:
                # last parseable stdout line wins; stray library prints must
                # never crash the parent and lose an earlier result
                for line in reversed(out.strip().splitlines()):
                    try:
                        cand = json.loads(line)
                        if isinstance(cand, dict) and "value" in cand:
                            res = cand
                            break
                    except ValueError:
                        continue
            if res is not None:
                log(f"  G={group_size}: {res['value']:.1f} metrics/s")
                if best is None or res["value"] > best["value"]:
                    best = res
                break
            transient = proc.returncode != 0 and attempt == 0
            log(f"  G={group_size}: attempt failed rc={proc.returncode}"
                + (", retrying once" if transient else ""))
            if not transient:
                break
    if best is None:
        raise SystemExit("all bench configurations failed")
    emit(best)
    done = True  # only after the line is out: a late signal must not double-emit


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--attempt":
        g, t = int(sys.argv[2]), int(sys.argv[3])
        print(json.dumps(run_attempt(g, t)), flush=True)
    else:
        main()
