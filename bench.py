"""Headline benchmark: anomaly-scored metrics/sec on one chip.

Measures the full per-record pipeline at steady state — fused device step
(encode -> SP -> TM -> raw score, chunked scan dispatches) plus the host-side
batched anomaly likelihood — over a synthetic cluster workload on the
cluster preset (BASELINE.md config 3/5 shape). Baseline is the north-star
target of 100k concurrent 1s-cadence streams scored on a single chip
(BASELINE.json), so vs_baseline = value / 100_000.

Prints exactly ONE JSON line on stdout; progress goes to stderr.

Unkillable-by-design (round-2 postmortem: a single slow G=2048 compile
starved every fallback and the round ended with rc=124 and no number):

- every attempt runs in a SUBPROCESS with a hard wall-clock budget, so one
  hung compile or a wedged TPU tunnel can never eat the whole bench window;
- a guaranteed-cheap config runs FIRST, so a number exists within minutes;
- the persistent XLA compilation cache is enabled (``.jax_cache/``), so
  retries and later rounds skip recompilation;
- transient backend errors (UNAVAILABLE / tunnel flake) get one retry;
- SIGTERM/SIGINT print the best result so far before exiting — a driver
  timeout still yields the JSON line.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
TARGET = 100_000.0  # metrics/sec/chip north star (BASELINE.json)
# Last-known-good hardware result (committed). The TPU tunnel oscillates —
# round 2 ended with NO number because it happened to be wedged at bench
# time. If every attempt fails now, the bench emits this prior on-silicon
# measurement, EXPLICITLY flagged {"cached": true, measured_at/commit}, so a
# dead tunnel degrades the result's freshness, never its existence.
LKG_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_LKG.json")

# (group_size, chunk_ticks, env_overrides): the cheap anchor first, then the
# round-4 kernel-strategy candidates at the measured-optimal rung, then the
# G/T exploration ladder. Attempt order is also failure-isolation order — an
# OOM or compile stall costs only its own budget (an OOM also skips every
# LATER rung that dominates the failed (G, T) point in both dims; smaller
# rungs still run). Measured on v5e (r3): throughput per chip FALLS with G
# (38,956 at G=256 vs 29,725 at G=8192 — the per-stream kernel cost dominates
# and big groups add nothing), and G=16384 is past the HBM frontier (XLA
# workspace temps on top of the 564 KB/stream state). So the ladder brackets
# the small-G peak and probes longer chunks to amortize per-dispatch
# overhead. The strategy candidates (all bit-identical to the default kernel
# — tests/parity/) ride the per-attempt subprocess env. First silicon A/B
# (2026-07-31, hw_results/): the CPU-drive signal INVERTED on TPU — indexed
# scatter loses big (18.1k vs matmul 28.1k metrics/s at G=1024) and Pallas
# loses too (24.3k), while flat layout wins (31.9k). So the ladder races the
# flat base plus the r4 learning-path cuts (compact punish/death sweep,
# forward-index dendrite) on TOP of flat/matmul, not the CPU-guess
# indexed base that round-3 shipped.
# NOTE: the process default is flat/matmul since the r4 flip, so `{}` IS the
# flat base; env overrides stay minimal because strat_key (the env tuple) is
# also the per-strategy OOM-dominance key — a redundant RTAP_TM_LAYOUT=flat
# would fragment dominance skipping across identical kernels.
# BENCH_LEARN_EVERY rides the same per-attempt env as the kernel strategies:
# the learning-cadence schedule (ModelConfig.learn_every, SCALING.md operating
# curve) measured k=4 at 86k and k=8 at 115k metrics/s/chip on silicon
# (hw_results/profile_cadence{4,8}.log) — k=8 is the first measured config
# past the 100k north star on one chip. The cadence rungs measure the mature
# steady state (cadence from tick 0, as profile_step does): the full-rate
# maturity window is a per-stream transient, not the steady state a
# throughput bench describes. The quality trade (f1 0.741 vs 0.853 at k=8)
# is documented in SCALING.md; the emitted line labels cadence rungs via
# "modes" so the headline is never mistaken for the full-rate default.
ATTEMPTS: list[tuple[int, int, dict]] = [
    (256, 64, {}),
    # scaled models (reports/model_size_quality.json, production fault
    # eval): 128 cols measures BETTER f1 than the preset at half the state
    # (0.804 vs 0.789); 64 cols holds 0.771 at a QUARTER (141 KB/stream —
    # analytically ~110k streams/chip u16). With k=2 cadence on top these
    # are the full-quality-class density stacks toward 100k/chip.
    (1024, 64, {"BENCH_COLUMNS": "128"}),
    (1024, 64, {"BENCH_COLUMNS": "128", "BENCH_LEARN_EVERY": "2"}),
    (1024, 64, {"BENCH_COLUMNS": "64"}),
    (1024, 64, {"BENCH_COLUMNS": "64", "BENCH_LEARN_EVERY": "2"}),
    (1024, 64, {"BENCH_COLUMNS": "32"}),  # best measured f1 (0.813) at 1/8 state
    # 32col learning is ~91% of the tick (profile_eighth.log), so k=2
    # projects ~126k/s — the first rung past the north star whose base
    # config BEATS the preset's quality (k=2 cost measured separately)
    (1024, 64, {"BENCH_COLUMNS": "32", "BENCH_LEARN_EVERY": "2"}),
    # k=4 at the density width: the 100k-live cadence candidate (r5 soak
    # ladder). Quality measured, not assumed: held-out family 0.3945 vs
    # k2's 0.4002 (reports/heldout_eval.json); diurnal-family number in
    # reports/model_size_quality.json (eighth_32col_k4)
    (1024, 64, {"BENCH_COLUMNS": "32", "BENCH_LEARN_EVERY": "4"}),
    (1024, 64, {"BENCH_LEARN_EVERY": "8"}),
    (1024, 64, {"BENCH_LEARN_EVERY": "4"}),
    (256, 64, {"RTAP_TM_LAYOUT": "aos"}),  # r3-default reference rung
    # (the r4 compact/forward candidate rungs were retired after the
    # 2026-08-01 window measured them -58%/-89% — hw_results/bench.log +
    # the profile postmortems are the committed evidence)
    # r6 candidate: the Pallas TM-learning megakernel (ops/pallas_tm.py,
    # parity-pinned). A Mosaic compile failure or VMEM overrun costs only
    # this attempt's subprocess budget — exactly the isolation the ladder
    # exists for; it cannot become a default without winning here.
    (256, 64, {"RTAP_TM_SCATTER": "pallas"}),
    (256, 256, {}),
    (512, 128, {}),
    (2048, 64, {}),
]


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


_STATE_BYTES: int | None = None  # set by state_bytes_gate; rides the emitted line


def state_bytes_gate() -> int:
    """Honest bytes/stream of one cluster-preset stream (u16 domain, summed
    over the REAL arrays) gated against the scaling-math static derivation —
    the same derivation that checks SCALING.md's capacity table. Drift means
    a layout change moved real bytes without moving the doc twin (or the
    derivation learned a layout the code doesn't have): fail the bench
    loudly instead of letting the capacity story rot (ISSUE 18). Runs on
    CPU before any TPU attempt; the figure rides the emitted JSON line as
    ``state_bytes_per_stream``."""
    global _STATE_BYTES
    import numpy as np

    from rtap_tpu.analysis.scalingmath import derived_stream_bytes
    from rtap_tpu.config import cluster_preset
    from rtap_tpu.models.state import init_state

    # fwd_* excluded on both sides: derived state, never checkpointed and
    # not part of the scaling-math layout model
    st = init_state(cluster_preset(perm_bits=16), include_fwd=False)
    measured = sum(int(np.asarray(v).nbytes) for v in st.values())
    derived = derived_stream_bytes(os.path.dirname(os.path.abspath(__file__)), 16)
    log(json.dumps({"state_bytes_per_stream": measured,
                    "scalingmath_derived": derived,
                    "state_bytes_gate": "pass" if measured == derived else "FAIL"}))
    if measured != derived:
        log("bench: state-bytes drift — models/state.py and the scaling-math "
            "derivation (rtap_tpu/analysis/scalingmath.py) disagree on the "
            "cluster preset's per-stream bytes; reconcile them and rerun "
            "scripts/scaling_law.py before benching")
        sys.exit(1)
    _STATE_BYTES = measured
    return measured


# ---------------------------------------------------------------- child ----


def run_attempt(group_size: int, chunk_ticks: int, measure_chunks: int = 3) -> dict:
    from rtap_tpu.utils.platform import (
        enable_compile_cache, init_backend_or_die, maybe_force_cpu,
    )

    maybe_force_cpu()  # RTAP_FORCE_CPU=1: deterministic CPU (tests/drives)
    init_backend_or_die()  # wedged tunnel: die at 120s, not the full budget
    import jax

    enable_compile_cache(os.path.dirname(os.path.abspath(__file__)))

    # The axon sitecustomize selects jax_platforms="axon,cpu": if the TPU
    # tunnel fast-fails at init, JAX silently falls back to CPU and this
    # process would report a CPU number as the chip benchmark. Refuse.
    # (BENCH_ALLOW_CPU=1 exists for driving the bench logic in tests.)
    backend = jax.default_backend()
    if backend == "cpu" and os.environ.get("BENCH_ALLOW_CPU") != "1":
        raise RuntimeError(
            "TPU backend unavailable (fell back to CPU); refusing to emit a "
            "CPU number as the per-chip benchmark"
        )
    log(f"  backend: {backend} ({jax.devices()[0].device_kind})")
    marker = os.environ.get("BENCH_INIT_MARKER")
    if marker:  # tell the parent the backend came up (hang triage)
        open(marker, "w").close()

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.service.registry import StreamGroup
    from rtap_tpu.utils.measure import make_sine_feed, measure_pipelined

    columns = int(os.environ.get("BENCH_COLUMNS", "0"))
    if columns:
        # half-size model: measured BETTER f1 than the preset at half the
        # state (reports/model_size_quality.json) — the bandwidth-bound
        # kernel should run ~2x; this rung measures that on silicon
        from rtap_tpu.config import scaled_cluster_preset

        cfg = scaled_cluster_preset(columns)
        log(f"  scaled preset: {columns} columns")
    else:
        cfg = cluster_preset()
    learn_every = int(os.environ.get("BENCH_LEARN_EVERY", "1"))
    if learn_every > 1:
        import dataclasses

        # mature steady state: cadence from tick 0 (learn_full_until stays
        # 0), the same measurement choice as profile_step --learn-every —
        # the full-rate maturity window is a transient, and the service
        # applies it per stream via ModelConfig.with_learn_every
        cfg = dataclasses.replace(cfg, learn_every=learn_every)
        log(f"  learning cadence: every {learn_every} ticks (mature steady state)")
    ids = [f"bench{i:06d}" for i in range(group_size)]
    t0 = time.perf_counter()
    grp = StreamGroup(cfg, ids, backend="tpu")
    log(f"  state init + device_put: {time.perf_counter() - t0:.1f}s")

    vals, ts, phase = make_sine_feed(group_size, chunk_ticks, key=(2026, 7))

    # warmup: compile + one chunk of real stepping
    t0 = time.perf_counter()
    grp.run_chunk(vals, ts)
    log(f"  warmup (compile + first chunk): {time.perf_counter() - t0:.1f}s")

    # steady state, pipelined (host likelihood + fetch overlap device compute)
    # with NOVEL values per measured chunk (genuine learning, r3 weak #8)
    value, dt = measure_pipelined(grp, vals, ts, measure_chunks, novel=((2026, 7), phase))
    from rtap_tpu.ops.tm_tpu import layout_mode, scatter_mode, sweep_mode

    modes = f"{layout_mode()}/{scatter_mode()}/{sweep_mode()}"
    if columns:
        modes += f"/cols={columns}"
    if learn_every > 1:
        modes += f"/learn_every={learn_every}"
    return {"value": value, "G": group_size, "T": chunk_ticks,
            "wall_s": round(dt, 2), "modes": modes}


# --------------------------------------------------------------- parent ----


_EMITTED: int | None = None  # exit code of the emitted line, once emitted

# Best result from a DEFAULT-config rung (empty env: full-rate learning on
# the default kernel). The headline takes the ladder max — which a cadence
# rung normally wins — so the full-rate number rides the emitted line as
# "full_rate_value": without it, a kernel regression in the default config
# would be invisible behind the unchanged cadence headline.
_BEST_FULL: dict | None = None

CACHED_EXIT = 4  # emitted-but-cached: distinct rc so exit-code-only consumers
# can tell a dead-tunnel LKG fallback from a fresh measurement (the JSON line
# also carries "cached": true; ADVICE.md round 3)

# Full-rate trend series (ISSUE 3 satellite): every fresh bench appends
# {round, full_rate, headline} here so a flat-since-r04 full-rate line is
# visible IN-REPO, not only in the verdict. Shares the artifact with
# scripts/trend_rung.py (which owns the like-for-like protocol study);
# this series lives under its "rounds" key.
TREND_PATH = os.environ.get("BENCH_TREND_PATH") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "reports", "trend_rung.json")


def _infer_round() -> str | None:
    """Round label for the trend entry: $BENCH_ROUND when the harness sets
    it, else one past the newest committed BENCH_rNN.json artifact (the
    driver's own numbering) — so unattended hw_session runs still label
    their entries instead of appending null-keyed rows."""
    env = os.environ.get("BENCH_ROUND")
    if env:
        return env
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    rounds = [int(m.group(1)) for f in os.listdir(here)
              if (m := re.fullmatch(r"BENCH_r(\d+)\.json", f))]
    return f"r{max(rounds) + 1:02d}" if rounds else None


def _append_trend(best: dict) -> None:
    """Append this run's {round, full_rate, headline} to the trend artifact
    (fresh results only — _finish gates on that; best-effort, a corrupt
    artifact or read-only FS must not kill the bench emission)."""
    if os.environ.get("BENCH_ALLOW_CPU") == "1" \
            and not os.environ.get("BENCH_TREND_PATH"):
        return  # CPU test drives must never pollute the committed series
    try:
        data = {}
        if os.path.exists(TREND_PATH):
            with open(TREND_PATH) as f:
                data = json.load(f)
        if not isinstance(data, dict):
            # a mangled artifact must not stop the series (or the bench):
            # start a fresh object; the old content is in git history
            data = {}
        data.setdefault("rounds", []).append({
            "round": _infer_round(),
            "headline": round(best["value"], 1),
            "headline_modes": best.get("modes"),
            "full_rate": (round(_BEST_FULL["value"], 1)
                          if _BEST_FULL is not None else None),
            # a None full_rate means every default-config rung failed this
            # run — the trend must show the hole, not silently skip it
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        })
        tmp = TREND_PATH + ".tmp"
        os.makedirs(os.path.dirname(TREND_PATH), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2)
        os.replace(tmp, TREND_PATH)
    except (OSError, ValueError) as e:
        # ValueError covers a corrupt JSON artifact: the trend is
        # best-effort bookkeeping and must never block the emission path
        # (this runs inside _finish, including the signal handler)
        log(f"bench: could not append trend entry: {e}")


def emit(best: dict | None) -> int | None:
    """Print the single result line; returns the process exit code (0 fresh,
    CACHED_EXIT for the LKG fallback) or None when nothing could be emitted.
    Idempotent — the flag flips BEFORE the print so a signal landing mid-emit
    can never produce a second line (stdout must carry exactly one JSON
    object). Falls back to the committed last-known-good hardware measurement
    (flagged "cached") when this run produced nothing."""
    global _EMITTED
    if _EMITTED is not None:
        return _EMITTED
    extra = {}
    if best is None:
        if os.environ.get("BENCH_ALLOW_CPU") == "1":
            return None  # CPU test drives must exercise the real failure
            # path, not mask it with the committed hardware measurement
        best, extra = _load_lkg()
        if best is None:
            return None
    _EMITTED = CACHED_EXIT if extra.get("cached") else 0
    # carry the winning configuration on the line: a cadence rung's headline
    # (modes ".../learn_every=k") must never read as the full-rate default
    for field in ("G", "T", "modes", "full_rate_value"):
        if best.get(field) is not None:
            extra.setdefault(field, best[field])
    if _BEST_FULL is not None:
        extra.setdefault("full_rate_value", round(_BEST_FULL["value"], 1))
    if _STATE_BYTES is not None:
        extra.setdefault("state_bytes_per_stream", _STATE_BYTES)
    print(
        json.dumps(
            {
                "metric": "anomaly_scored_metrics_per_sec_per_chip",
                "value": round(best["value"], 1),
                "unit": "metrics/s",
                "vs_baseline": round(best["value"] / TARGET, 4),
                **extra,
            }
        ),
        flush=True,
    )
    return _EMITTED


def _load_lkg() -> tuple[dict | None, dict]:
    try:
        with open(LKG_PATH) as f:
            lkg = json.load(f)
        log(f"bench: no fresh result; emitting last-known-good from {lkg.get('measured_at')}")
        return {"value": float(lkg["value"]), "G": lkg.get("G"), "T": lkg.get("T"),
                "modes": lkg.get("modes"),
                "full_rate_value": lkg.get("full_rate_value")}, {
            "cached": True,
            "measured_at": lkg.get("measured_at"),
            "cached_reason": "no attempt produced a fresh number this run "
                             "(TPU tunnel down or all configs failed)",
        }
    except Exception:  # noqa: BLE001 — any malformed LKG degrades to "none",
        # including from inside the SIGTERM handler
        return None, {}


def _store_lkg(best: dict) -> None:
    """Record a FRESH on-silicon result for future fallback (never a cached
    one — emit() only reaches _store via main()'s fresh path). Atomic-ish:
    temp + replace."""
    if os.environ.get("BENCH_ALLOW_CPU") == "1":
        return  # CPU test drives must never overwrite the hardware LKG
    try:
        tmp = LKG_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "value": round(best["value"], 1),
                    "G": best.get("G"),
                    "T": best.get("T"),
                    "modes": best.get("modes"),
                    **({"full_rate_value": round(_BEST_FULL["value"], 1)}
                       if _BEST_FULL is not None else {}),
                    "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                },
                f,
            )
        os.replace(tmp, LKG_PATH)
    except OSError as e:
        log(f"bench: could not store last-known-good: {e}")


def _finish(best: dict | None, tunnel_down: bool = False) -> None:
    """Single exit point: persist a fresh result, emit the line (fresh or
    LKG fallback), exit with the emit code (0 fresh / CACHED_EXIT cached /
    1 nothing). Shared by the signal handler and every abort path so their
    semantics can never drift.

    `tunnel_down=True` (init-failure-streak abort with nothing fresh
    measured): exit INIT_WATCHDOG_EXIT instead of CACHED_EXIT so harness
    loops (scripts/hw_watch.py) read the run as a tunnel-down probe — a
    cached emission caused by a wedged tunnel must not consume retry
    budget and park the bench step for the round. The stdout JSON still
    carries "cached": true either way."""
    if best is not None:
        _store_lkg(best)
        _append_trend(best)
    code = emit(best)
    if tunnel_down and best is None:
        # regardless of whether an LKG line could be emitted (code is
        # CACHED_EXIT or None): the run produced nothing because the
        # tunnel was down, and the harness must see exactly that
        from rtap_tpu.utils.platform import INIT_WATCHDOG_EXIT

        sys.exit(INIT_WATCHDOG_EXIT)
    sys.exit(1 if code is None else code)


def main() -> None:
    budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    per_attempt = float(os.environ.get("BENCH_ATTEMPT_BUDGET_S", "330"))
    state_bytes_gate()  # layout-vs-derivation drift fails before any attempt
    t_start = time.monotonic()
    best: dict | None = None
    current_proc: list = [None]

    def on_signal(signum, frame):
        log(f"bench: signal {signum}, emitting best-so-far")
        if current_proc[0] is not None and current_proc[0].poll() is None:
            current_proc[0].kill()  # never orphan a TPU-holding child
        _finish(best)

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    os.makedirs(CACHE_DIR, exist_ok=True)
    # OOM dominance is tracked PER kernel-strategy config: memory is monotone
    # in G (state) and T (feed/workspace) only with the kernel fixed — e.g.
    # the flat layout exists precisely to shrink the padded HBM footprint, so
    # an aos OOM must not veto the flat rungs
    oom_at: dict[tuple, tuple[int, int]] = {}
    init_fail_streak = 0  # consecutive children that died without backend init
    global _BEST_FULL
    for group_size, chunk_ticks, strategy_env in ATTEMPTS:
        # BENCH_LEARN_EVERY changes only the learning cadence, not state
        # layout or HBM footprint — memory-identical rungs must share one
        # OOM-dominance key or a frontier OOM re-burns budget per cadence
        strat_key = tuple(sorted(
            (k, v) for k, v in strategy_env.items() if k != "BENCH_LEARN_EVERY"
        ))
        if strat_key in oom_at and group_size >= oom_at[strat_key][0] \
                and chunk_ticks >= oom_at[strat_key][1]:
            log(f"bench: skipping G={group_size},T={chunk_ticks} "
                f"(dominates OOM point {oom_at[strat_key]} for {strat_key})")
            continue
        remaining = budget - (time.monotonic() - t_start)
        # never start an attempt we can't give a meaningful slice of budget
        if remaining < 60:
            log(f"bench: {remaining:.0f}s left, stopping attempts")
            break
        for attempt in range(2):  # one retry on transient backend errors
            this_budget = min(per_attempt, budget - (time.monotonic() - t_start))
            if this_budget < 60:
                break
            log(f"bench attempt: G={group_size}, T={chunk_ticks} "
                f"{strategy_env or ''} (budget {this_budget:.0f}s)")
            marker = os.path.join(CACHE_DIR, f".init_ok.{os.getpid()}")
            if os.path.exists(marker):
                os.unlink(marker)
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--attempt",
                 str(group_size), str(chunk_ticks)],
                stdout=subprocess.PIPE, stderr=sys.stderr, text=True,
                env={**os.environ, "BENCH_INIT_MARKER": marker, **strategy_env},
            )
            current_proc[0] = proc
            try:
                out, _ = proc.communicate(timeout=this_budget)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                log(f"  G={group_size}: killed at budget ({this_budget:.0f}s)")
                if os.path.exists(marker):
                    init_fail_streak = 0  # the backend DID come up this time
                if not os.path.exists(marker):
                    # the child never even initialized the backend: the TPU
                    # tunnel is hanging, and every further attempt would burn
                    # its full budget the same way — stop the ladder
                    log("bench: backend init hang detected, aborting attempts")
                    _finish(best, tunnel_down=True)
                break  # a timeout is not transient; don't retry, move on
            finally:
                current_proc[0] = None
            res = None
            oom = False
            # last parseable stdout line wins; stray library prints must
            # never crash the parent and lose an earlier result
            for line in reversed(out.strip().splitlines()):
                try:
                    cand = json.loads(line)
                except ValueError:
                    continue
                if isinstance(cand, dict) and cand.get("fatal") == "oom":
                    oom = True
                    break
                if isinstance(cand, dict) and "value" in cand and proc.returncode == 0:
                    res = cand
                    break
            if os.path.exists(marker):
                init_fail_streak = 0
            if oom:
                log(f"  G={group_size},T={chunk_ticks}: past the HBM frontier "
                    "(OOM); skipping same-strategy configs dominating this point")
                oom_at[strat_key] = (group_size, chunk_ticks)
                break
            if res is not None:
                log(f"  G={group_size}: {res['value']:.1f} metrics/s")
                if best is None or res["value"] > best["value"]:
                    best = res
                if not strategy_env and (
                        _BEST_FULL is None or res["value"] > _BEST_FULL["value"]):
                    _BEST_FULL = res
                break
            if proc.returncode != 0 and not os.path.exists(marker):
                # the child died without ever initializing the backend (e.g.
                # the init watchdog's 120s hard-exit on a wedged tunnel, or a
                # fast-fail CPU fallback). One flake gets a retry — the
                # tunnel oscillates (SCALING.md) — but two IN A ROW means
                # every further attempt would fail the same way.
                init_fail_streak += 1
                if init_fail_streak >= 2:
                    log("bench: backend init failure persisted, aborting attempts")
                    _finish(best, tunnel_down=True)
            transient = proc.returncode != 0 and attempt == 0
            log(f"  G={group_size}: attempt failed rc={proc.returncode}"
                + (", retrying once" if transient else ""))
            if not transient:
                break
    if best is None:
        log("bench: all configurations failed and no fresh result exists")
    _finish(best)  # single exit point — semantics shared with every abort path


def run_ingest_bench() -> None:
    """`bench.py --ingest-bench`: the host ingest-transport comparison.

    JSONL vs RB1 binary vs shm-ring rows/s on a scaled-down 1-core
    config, through the SAME harness as scripts/ingest_bench.py (the
    committed reports/ingest_r07.json artifact is the full-size run).
    Prints one JSON line; exits 1 when the CI floor is blown — the
    binary path regressing below the floor (or below the JSONL path it
    exists to replace) must fail loudly, like the --obs-bench gates.
    """
    import importlib.util

    here = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "_ingest_bench", os.path.join(here, "scripts", "ingest_bench.py"))
    ib = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ib)

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.service.registry import StreamGroupRegistry

    n_binary, n_jsonl, n_streams = 120_000, 40_000, 1024
    ids = [f"node{i // 4:04d}.m{i % 4}" for i in range(n_streams)]
    reg = StreamGroupRegistry(cluster_preset(), group_size=n_streams,
                              backend="cpu")
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()
    slot_map = reg.slot_map()
    payload = ib.make_payload(n_jsonl, ids)
    frames = ib.make_frames(n_binary, slot_map, ids, frame_rows=4096)
    try:
        jsonl = ib.socket_drive(True, payload, n_jsonl, ids)
        jsonl_lane = "native"
    except (OSError, subprocess.CalledProcessError, MemoryError):
        # no toolchain / build failure ONLY: any other native-lane
        # error must fail the gate, not silently soften the baseline
        # to the ~12x-slower Python lane
        jsonl = ib.socket_drive(False, payload, n_jsonl, ids)
        jsonl_lane = "python"
    binary = ib.binary_socket_drive(frames, n_binary, slot_map, ids)
    shm = ib.shm_drive(frames, n_binary, slot_map)
    # CI floors are deliberately conservative (a shared CI host can be
    # an order of magnitude slower than the tier-1 host's measured
    # multi-M rows/s): they catch the path going quadratic or a silent
    # fallback-to-Python, not percent-level drift
    floor_rows = 250_000
    floor_speedup = 2.0
    speedup = binary["records_per_sec"] / jsonl["records_per_sec"]
    res = {
        "metric": "ingest_bench",
        "jsonl_lane": jsonl_lane,
        "jsonl_rows_per_sec": jsonl["records_per_sec"],
        "binary_rows_per_sec": binary["records_per_sec"],
        "shm_rows_per_sec": shm["records_per_sec"],
        "binary_vs_jsonl": round(speedup, 1),
        "floor_rows_per_sec": floor_rows,
        "floor_speedup": floor_speedup,
        "pass_floor": binary["records_per_sec"] >= floor_rows
        and speedup >= floor_speedup,
    }
    print(json.dumps(res), flush=True)
    if not res["pass_floor"]:
        sys.exit(1)


def run_obs_bench() -> None:
    """`bench.py --obs-bench`: the telemetry-overhead self-benchmark.

    Table-driven over ``rtap_tpu.obs.selfbench.GATE_MEASURES`` (ISSUE 11
    satellite): every self-benchmarked instrument surface — registry
    metrics, span ring + flight recorder (ISSUE 4), write-ahead journal
    (ISSUE 5), model-health fold (ISSUE 6), incident-correlator storm
    ceiling (ISSUE 9), detection-latency sketches + SLO evaluation
    (ISSUE 11) — is one registry row gated against the shared
    ``GATE_BUDGET_FRAC`` (<= 1% of the tick budget, docs/TELEMETRY.md).
    A new instrument registers a row or never gets a gate; prints one
    JSON line per surface and exits 1 if any bar is blown (so CI/harness
    runs fail loudly).
    """
    from rtap_tpu.obs.selfbench import GATE_BUDGET_FRAC, GATE_MEASURES

    all_pass = True
    for name, fn in GATE_MEASURES:
        res = fn()
        res["budget_frac"] = GATE_BUDGET_FRAC
        res["pass_1pct_budget"] = \
            res["per_tick_overhead_frac"] <= GATE_BUDGET_FRAC
        all_pass = all_pass and res["pass_1pct_budget"]
        print(json.dumps({"metric": name, **res}), flush=True)
    if not all_pass:
        sys.exit(1)


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--obs-bench":
        run_obs_bench()
    elif len(sys.argv) >= 2 and sys.argv[1] == "--ingest-bench":
        run_ingest_bench()
    elif len(sys.argv) >= 2 and sys.argv[1] == "--attempt":
        g, t = int(sys.argv[2]), int(sys.argv[3])
        try:
            print(json.dumps(run_attempt(g, t)), flush=True)
        except Exception as e:  # noqa: BLE001 — classify for the parent
            if "RESOURCE_EXHAUSTED" in str(e) or "out of memory" in str(e).lower():
                # tell the parent this G is past the HBM frontier: no retry,
                # and no larger config can succeed either
                print(json.dumps({"fatal": "oom"}), flush=True)
                sys.exit(3)
            raise
    else:
        main()
