"""Service-level crash/resume drill (SURVEY.md §4 item 3, §5 checkpoint/
resume as elastic recovery; round-3 verdict missing #6).

A real subprocess runs a grouped replay with periodic atomic checkpoints and
is KILLED abruptly mid-stream (os._exit — no cleanup, no flush: the honest
crash). The parent then resumes the replay from the surviving checkpoint
directory and asserts the resumed tail scores are bit-identical to an
uninterrupted reference run — proving recovery end-to-end through the
registry, device state, and the sequential likelihood ring, not just the
state-dict round trip of tests/unit/test_checkpoint.py.
"""

import json
import os
import subprocess
import sys

import numpy as np

from rtap_tpu.config import cluster_preset
from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_cluster
from rtap_tpu.service.loop import replay_streams

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_NODES = 2  # x3 metrics = 6 streams
LENGTH = 640
CHUNK = 64

_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
from rtap_tpu.utils.platform import maybe_force_cpu
maybe_force_cpu()

from rtap_tpu.config import cluster_preset
from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_cluster
from rtap_tpu.service.loop import replay_streams
from rtap_tpu.service import registry

# crash injection: die abruptly right after the 6th collected chunk — two
# chunks past the checkpoint_every=4 save, so real scored progress is lost
# and resume MUST come from the checkpoint, not from luck
_collected = [0]
_orig = registry.StreamGroup.collect_chunk
def _dying_collect(self, handle):
    out = _orig(self, handle)
    _collected[0] += 1
    if _collected[0] == 6:
        os._exit(9)  # no atexit, no flush: a genuine crash
    return out
registry.StreamGroup.collect_chunk = _dying_collect

streams = generate_cluster({n_nodes}, cfg=SyntheticStreamConfig(
    length={length}, cadence_s=1.0, noise_phi=0.97, noise_scale=0.5), seed=7)
replay_streams(streams, cluster_preset(), backend="tpu", chunk_ticks={chunk},
               checkpoint_dir={ckdir!r}, checkpoint_every=4)
raise SystemExit("unreachable: the crash hook must fire")
"""


def test_crash_mid_replay_resumes_bit_identically(tmp_path):
    ckdir = str(tmp_path / "ck")
    scfg = SyntheticStreamConfig(length=LENGTH, cadence_s=1.0,
                                 noise_phi=0.97, noise_scale=0.5)
    streams = generate_cluster(N_NODES, cfg=scfg, seed=7)

    # 1. uninterrupted reference, same inputs
    ref = replay_streams(streams, cluster_preset(), backend="tpu", chunk_ticks=CHUNK)

    # 2. the doomed run, in its own process
    child = _CHILD.format(repo=REPO, n_nodes=N_NODES, length=LENGTH,
                          chunk=CHUNK, ckdir=ckdir)
    env = {**os.environ, "RTAP_FORCE_CPU": "1"}
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU child must not dial the tunnel
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, text=True, timeout=900)
    assert proc.returncode == 9, f"crash hook did not fire: rc={proc.returncode}\n{proc.stderr[-2000:]}"
    assert os.path.isdir(os.path.join(ckdir, "group0000")), "no checkpoint survived the crash"
    meta = json.loads(open(os.path.join(ckdir, "group0000", "meta.json")).read())
    assert 0 < meta["ticks"] < LENGTH, meta["ticks"]  # mid-stream, not done

    # 3. resume from the surviving checkpoint; only the tail is recomputed
    res = replay_streams(streams, cluster_preset(), backend="tpu", chunk_ticks=CHUNK,
                         checkpoint_dir=ckdir, checkpoint_every=4)
    boundary = res.throughput["resumed_from"]["group0"]
    assert boundary == meta["ticks"]
    assert np.isnan(res.raw[:boundary]).all()  # scored by the killed run, not re-run

    # 4. the resumed tail is bit-identical to the uninterrupted reference —
    # through raw scores, the likelihood ring, and alert decisions
    np.testing.assert_array_equal(res.raw[boundary:], ref.raw[boundary:])
    np.testing.assert_array_equal(
        res.log_likelihood[boundary:], ref.log_likelihood[boundary:]
    )
    np.testing.assert_array_equal(res.alerts[boundary:], ref.alerts[boundary:])
