"""Soak-harness orchestration smoke (scripts/live_soak.py) at tiny scale.

The soak script is the round-4 "realistic-G live serving" evidence path
(SURVEY.md §3.3; round-3 verdict weak #7): it launches the REAL
`python -m rtap_tpu serve` child, parses its listener line, attaches an
in-process TCP feeder, and commits a stats artifact. This test runs that
whole orchestration at smoke scale on the CPU platform — it exists because
the feeder's deferred `rtap_tpu` import was broken for script-style
invocation (`python scripts/live_soak.py` puts scripts/, not the repo, at
sys.path[0]) and nothing exercised the script end to end before a
hardware window would have.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_live_soak_smoke(tmp_path):
    out = tmp_path / "soak.json"
    env = {**os.environ, "RTAP_FORCE_CPU": "1"}
    # invoked exactly as hw_session/hw_watch invoke it: script path, repo cwd
    proc = subprocess.run(
        [sys.executable, "scripts/live_soak.py",
         "--streams", "8", "--ticks", "4", "--cadence", "0.5",
         "--backend", "tpu", "--startup-timeout", "240",
         "--out", str(out)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    art = json.loads(out.read_text())
    assert art["streams"] == 8
    assert art["forced_cpu"] is True
    # data actually flowed (rc==0 already implies the script's own
    # feeder-shortfall check passed; assert only the recorded facts)
    assert art["feeder_error"] is None
    assert art["ticks"] == 4
    assert "missed_deadlines" in art and "latency_p99_ms" in art
    # serve merges ingest health into its stats line (records_parsed is
    # present whenever the native parser is active; counters must be clean)
    assert art["parse_errors"] == 0 and art["unknown_ids"] == 0
    if art.get("native_active"):
        assert art["records_parsed"] > 0
