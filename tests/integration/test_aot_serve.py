"""AOT warm-up acceptance (ISSUE 3 satellite): zero cold compiles after
tick 0 — no XLA compile may occur inside a scored tick.

The check is on the REAL jit caches (ops/step.chunk_step._cache_size(),
the claim program's cache), not on the loop's bookkeeping: prewarm must
leave the caches in exactly the state the serve loop's dispatches find
them in, or a compile WOULD land inside a tick. The loop's own
cold_compiles_after_warmup stat (its single-flight keying vs the
prewarmed set) is asserted zero on top.

cluster_preset on the CPU test platform compiles in seconds at tiny G;
the programs are the same ones the soak dispatches (shapes differ, the
program ENUMERATION under test does not).
"""

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset
from rtap_tpu.service.aot import knowable_programs, prewarm
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroupRegistry

GROUP_SIZE = 3
N_STREAMS = 6  # two full groups
MICRO = 2


def _registry(n=N_STREAMS, stagger=False, learn_every=1, reserve=0):
    cfg = cluster_preset()
    if learn_every > 1:
        cfg = cfg.with_learn_every(learn_every)
    reg = StreamGroupRegistry(cfg, group_size=GROUP_SIZE, backend="tpu",
                              stagger_learn=stagger)
    for i in range(n):
        reg.add_stream(f"a{i}")
    reg.finalize(reserve=reserve)
    return reg


def _feed_for(reg):
    n = len(reg.dispatch_ids())

    def feed(k):
        rng = np.random.Generator(np.random.Philox(key=(41, k)))
        return (30 + 5 * rng.random(n)).astype(np.float32), 1_700_000_000 + k

    return feed


def test_knowable_program_enumeration():
    """Every chunk length 1..M, one entry per distinct group config,
    learn=False added exactly when a degradation ladder could flip it."""
    reg = _registry(stagger=True, learn_every=2)
    cfgs = {g.cfg for g in reg.groups}
    assert len(cfgs) == 2  # stagger_learn: distinct learn_phase per group
    progs = knowable_programs(reg.groups, MICRO, learn=True)
    assert {(m, lf) for m, _c, lf in progs} == {(1, True), (2, True)}
    assert len(progs) == 2 * MICRO

    class _Ladder:  # stand-in: presence alone widens the learn-flag set
        pass

    progs2 = knowable_programs(reg.groups, MICRO, learn=True,
                               degradation=_Ladder())
    assert {lf for _m, _c, lf in progs2} == {True, False}
    assert len(progs2) == 2 * MICRO * 2


def test_serve_has_zero_cold_compiles_after_tick0():
    from rtap_tpu.ops.step import chunk_step

    reg = _registry(stagger=True, learn_every=2)
    # prewarm is what live_loop(aot_warmup=True) runs before tick 0; doing
    # it here first lets the test snapshot the REAL cache state at the
    # "tick 0 is about to run" boundary
    pre = prewarm(reg.groups, MICRO, learn=True)
    assert len(pre) == 2 * MICRO
    cache_at_tick0 = chunk_step._cache_size()

    stats = live_loop(_feed_for(reg), reg, n_ticks=7, cadence_s=0.0,
                      micro_chunk=MICRO, chunk_stagger=True,
                      aot_warmup=True)
    # 7 ticks with M=2 stagger exercises ramp-in (m=1), steady state
    # (m=2) and the final-tick partial flush — all prewarmed lengths
    assert stats["ticks"] == 7
    assert stats["aot_programs_compiled"] == 2 * MICRO
    assert stats["cold_compiles_after_warmup"] == 0
    assert chunk_step._cache_size() == cache_at_tick0, (
        "a serve dispatch compiled a program the AOT warm-up missed"
    )


def test_prewarm_covers_first_claim_program():
    """The dynamic-claim realignment program (set_state_row) is part of
    the knowable set when claimable capacity exists: a claim after warm-up
    must hit a warm cache."""
    from rtap_tpu.ops.step import _set_row_jit

    reg = _registry(n=4, reserve=0)  # group-size rounding leaves 2 pads
    assert reg.free_slots > 0
    prewarm(reg.groups, 1, learn=True, include_claim=True)
    cache0 = _set_row_jit._cache_size()
    reg.add_stream("late-joiner")  # claims a pad slot -> set_state_row
    assert _set_row_jit._cache_size() == cache0, (
        "the first dynamic claim compiled set_state_row cold"
    )


def test_aot_counter_exposed(tmp_path):
    from rtap_tpu.obs import get_registry

    def val():
        for m in get_registry().snapshot()["metrics"]:
            if m["name"] == "rtap_obs_aot_programs_compiled_total":
                return m["value"]
        return 0

    before = val()
    reg = _registry(n=GROUP_SIZE)
    stats = live_loop(_feed_for(reg), reg, n_ticks=2, cadence_s=0.0,
                      aot_warmup=True)
    assert stats["aot_programs_compiled"] >= 1
    assert val() - before == stats["aot_programs_compiled"] + (
        1 if any(g.free_slot_count() for g in reg.groups) else 0
    )


def test_cpu_backend_prewarm_is_noop():
    """CPU-oracle groups have no device programs; prewarm must not
    fabricate warm-up work (or crash) for them."""
    reg = StreamGroupRegistry(cluster_preset(), group_size=2, backend="cpu")
    for i in range(2):
        reg.add_stream(f"c{i}")
    reg.finalize()
    assert prewarm(reg.groups, 3, learn=True) == set()
