"""ISSUE 2 acceptance: scripted chaos against the REAL live_loop.

The tier-1 chaos test drives the loop through injected source-timeout,
group-dispatch-exception, alert-sink OSError, and checkpoint-write-failure
faults and proves: the loop completes every tick, non-faulted groups'
scores are BIT-IDENTICAL to a fault-free run (groups are independent;
containment must not perturb the healthy fleet), quarantine/degradation/
recovery events land on the alert stream, and the rtap_obs_* counters
move. The registry is process-wide, so counter assertions are deltas.
"""

import json

import numpy as np

from rtap_tpu.config import cluster_preset
from rtap_tpu.obs import get_registry, summarize_snapshot
from rtap_tpu.resilience import (
    ChaosEngine,
    ChaosSpec,
    DegradationController,
    Fault,
)
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroupRegistry

G_TOTAL = 6
GROUP_SIZE = 2  # 3 groups: fault the middle one, bit-compare its neighbors
N_TICKS = 12


def _registry(threshold=-1e9):
    # threshold floor + debounce 1: every scored tick writes an alert
    # line, so the alert-sink fault path sees real traffic
    reg = StreamGroupRegistry(cluster_preset(), group_size=GROUP_SIZE,
                              backend="tpu", threshold=threshold, debounce=1)
    for i in range(G_TOTAL):
        reg.add_stream(f"s{i}")
    reg.finalize()
    return reg


def _feed(k):
    rng = np.random.Generator(np.random.Philox(key=(77, k)))
    return (30 + 5 * rng.random(G_TOTAL)).astype(np.float32), \
        1_700_000_000 + k


class _Recorder:
    """Delegating StreamGroup proxy that captures collect outputs — the
    bit-identity oracle needs per-tick scores, which only alerting lines
    would otherwise expose (and the sink is one of the faulted parts)."""

    def __init__(self, inner):
        self._inner = inner
        self.raw: list = []
        self.loglik: list = []

    def collect_chunk(self, handle):
        raw, loglik, alerts = self._inner.collect_chunk(handle)
        self.raw.append(np.array(raw, copy=True))
        self.loglik.append(np.array(loglik, copy=True))
        return raw, loglik, alerts

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _wrap(reg):
    recs = []
    for i, grp in enumerate(reg.groups):
        rec = _Recorder(grp)
        reg.groups[i] = rec
        recs.append(rec)
    return recs


def _summary():
    return summarize_snapshot(get_registry().snapshot())


def _events(path):
    return [json.loads(line) for line in open(path)
            if line.startswith('{"event"')]


def test_chaos_faults_are_contained_and_healthy_groups_bit_identical(
        tmp_path):
    spec = ChaosSpec(faults=[
        # one exporter (group 1's streams) times out: NaN inputs, still
        # scored — healthy groups' inputs untouched
        Fault(kind="source_timeout", tick=2, streams=(2, 3)),
        # group 1's dispatch raises: quarantine, everyone else unharmed
        Fault(kind="dispatch_exception", tick=5, group=1),
        # the alert disk "fills" for two ticks mid-run: at least three
        # emit batches fail (two healthy groups per tick), which opens
        # the sink breaker deterministically
        Fault(kind="alert_sink_oserror", tick=6, duration=2),
        # the checkpoint round at tick 7 fails for every group
        Fault(kind="checkpoint_oserror", tick=7),
    ])
    before = _summary()
    reg = _registry()
    recs = _wrap(reg)
    alerts_path = tmp_path / "alerts.jsonl"
    stats = live_loop(
        _feed, reg, n_ticks=N_TICKS, cadence_s=0.01,
        alert_path=str(alerts_path),
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=4,
        chaos=ChaosEngine(spec))
    # the loop completed all ticks despite every fault
    assert stats["ticks"] == N_TICKS
    # counter snapshot now: the fault-free reference run below re-enters
    # live_loop, which re-zeroes the quarantine gauge
    after = _summary()

    # ---- non-faulted groups bit-identical to a fault-free run
    ref_reg = _registry()
    ref_recs = _wrap(ref_reg)
    ref_stats = live_loop(_feed, ref_reg, n_ticks=N_TICKS, cadence_s=0.01)
    assert ref_stats["ticks"] == N_TICKS
    for gi in (0, 2):
        np.testing.assert_array_equal(
            np.concatenate(recs[gi].raw), np.concatenate(ref_recs[gi].raw),
            err_msg=f"group {gi} raw scores diverged from fault-free run")
        np.testing.assert_array_equal(
            np.concatenate(recs[gi].loglik),
            np.concatenate(ref_recs[gi].loglik),
            err_msg=f"group {gi} log-likelihood diverged")

    # ---- the faulted group was isolated, not silently dropped
    # quarantined at tick 5's dispatch: ticks 0..4 scored, 2 streams each
    assert stats["scored_by_group"] == [2 * N_TICKS, 2 * 5, 2 * N_TICKS]
    assert stats["quarantined"]["group1"]["phase"] == "dispatch"
    assert stats["quarantine_log"][0] == {
        "event": "group_quarantined", "group": 1, "tick": 5,
        "phase": "dispatch"}

    # ---- events on the alert stream (written BEFORE the sink fault:
    # the tick-6 sink fault fails 3 batches, which opens the sink breaker
    # — later event lines are deliberately dropped-and-counted, so the
    # checkpoint failures below are asserted via counters, not the file)
    events = _events(alerts_path)
    kinds = {e["event"] for e in events}
    assert "group_quarantined" in kinds
    q = next(e for e in events if e["event"] == "group_quarantined")
    assert q["group"] == 1 and q["tick"] == 5 and "chaos" in q["error"]
    # the checkpoint round at tick 7 failed for both healthy groups
    # (quarantined group 1 is skipped — its state is mid-fault and its
    # checkpoint is the restore source)
    assert stats["checkpoint_save_failures"] == 2

    # ---- counters moved (snapshot from right after the chaos run)
    def delta(key):
        b = before.get(key, 0)
        return after.get(key, 0) - b

    assert delta("rtap_obs_resilience_events_total{event=group_quarantined}") == 1
    assert delta("rtap_obs_resilience_events_total{event=checkpoint_save_failed}") == 2
    assert delta("rtap_obs_chaos_injected_total{kind=source_timeout}") == 1
    assert delta("rtap_obs_chaos_injected_total{kind=dispatch_exception}") == 1
    assert delta("rtap_obs_chaos_injected_total{kind=checkpoint_oserror}") >= 1
    assert delta("rtap_obs_chaos_injected_total{kind=alert_sink_oserror}") >= 1
    assert delta("rtap_obs_alert_sink_errors_total") >= 1
    assert delta("rtap_obs_alert_lines_dropped_total") >= 1
    # three failed batches at tick 6 opened the sink breaker: the sink
    # itself quarantined (and scoring demonstrably never noticed)
    assert delta("rtap_obs_resilience_events_total{event=alert_sink_quarantined}") == 1
    assert after["rtap_obs_groups_quarantined"] == 1
    # the previous checkpoints survived the failed round: group0's dir
    # still resumes (save atomicity — ISSUE 2 "a failed save must leave
    # the previous checkpoint intact")
    from rtap_tpu.service.checkpoint import load_group

    assert load_group(tmp_path / "ck" / "group0000").ticks > 0


def test_quarantined_group_restores_from_checkpoint(tmp_path):
    reg = _registry()
    alerts_path = tmp_path / "alerts.jsonl"
    stats = live_loop(
        _feed, reg, n_ticks=N_TICKS, cadence_s=0.01,
        alert_path=str(alerts_path),
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=3,
        quarantine_restore_after=3,
        chaos=ChaosEngine(ChaosSpec(faults=[
            Fault(kind="dispatch_exception", tick=5, group=1)])))
    assert stats["ticks"] == N_TICKS
    # saved at tick 2 (3 ticks run) -> quarantined at 5 -> restored at 8
    # from the tick-3 checkpoint -> scored 0..4 and 8..11
    assert stats["scored_by_group"] == [2 * N_TICKS, 2 * (5 + 4),
                                        2 * N_TICKS]
    assert "quarantined" not in stats  # nothing still quarantined at exit
    log = stats["quarantine_log"]
    assert [e["event"] for e in log] == ["group_quarantined",
                                        "group_restored"]
    assert log[1] == {"event": "group_restored", "group": 1, "tick": 8,
                      "resumed_from_tick": 3}
    events = _events(alerts_path)
    assert {e["event"] for e in events} >= {"group_quarantined",
                                            "group_restored"}
    # the registry's lookup index observes the restored instance
    grp, slot = reg.lookup("s2")
    assert grp is reg.groups[1] and slot == 0


def test_degradation_ladder_engages_under_sustained_misses(tmp_path):
    before = _summary()
    reg = _registry()
    alerts_path = tmp_path / "alerts.jsonl"
    ctl = DegradationController(window=4, degrade_after=2, recover_after=50,
                                thin_factor=2, widen_factor=2.0)
    # sub-ms cadence on a compiling backend: every tick misses, the
    # ladder must walk all the way down and SAY so
    stats = live_loop(_feed, reg, n_ticks=10, cadence_s=1e-4,
                      alert_path=str(alerts_path), degradation=ctl)
    assert stats["ticks"] == 10
    assert stats["degradation"]["max_level"] == 3
    assert stats["degradation"]["level"] == 3
    after = _summary()
    assert after["rtap_obs_degradation_level"] == 3.0
    assert after.get("rtap_obs_resilience_events_total{event=degraded}", 0) \
        - before.get("rtap_obs_resilience_events_total{event=degraded}", 0) \
        == 3
    degraded = [e for e in _events(alerts_path) if e["event"] == "degraded"]
    assert [e["step"] for e in degraded] == ["learn_thin", "score_only",
                                             "tick_widen"]
    # scoring never stopped while shedding
    assert stats["scored"] == 10 * G_TOTAL


def test_raising_source_and_backwards_timestamps_are_absorbed(tmp_path):
    before = _summary()
    reg = _registry()
    alerts_path = tmp_path / "alerts.jsonl"
    stats = live_loop(
        _feed, reg, n_ticks=8, cadence_s=0.01,
        alert_path=str(alerts_path),
        chaos=ChaosEngine(ChaosSpec(faults=[
            Fault(kind="source_conn_drop", tick=1),
            Fault(kind="source_malformed", tick=2),
            Fault(kind="source_backwards_ts", tick=4),
        ])))
    assert stats["ticks"] == 8
    # raising-source ticks score as whole-vector missing samples
    assert stats["scored"] == 8 * G_TOTAL
    after = _summary()
    assert after.get("rtap_obs_source_errors_total", 0) \
        - before.get("rtap_obs_source_errors_total", 0) == 2
    assert after.get("rtap_obs_source_time_regressions_total", 0) \
        - before.get("rtap_obs_source_time_regressions_total", 0) == 1
    kinds = {e["event"] for e in _events(alerts_path)}
    assert "source_error" in kinds and "source_time_regression" in kinds
