"""Predictive horizon through the real serve stack (ISSUE 16).

Acceptance tests: (1) serving with the predict reducer enabled is
BIT-EXACT against serving without it — final model state (minus the
predictor's own leaves) and the alert stream are byte-identical (the
reducer is a pure read); (2) GET /predict serves the fleet rollup +
scorecard schema (404 without a tracker); (3) a learned-calm ->
unpredictable-drift scenario pages a ``precursor`` onto the alert
stream and the flight recorder's bundle embeds the scorecard; (4) the
operator CLI surface (`serve --predict`) end to end, including the
usage-error sweep; (5) a journal-replay resume re-derives the same
precursor alert_id and SUPPRESSES it — exactly-once paging.
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from rtap_tpu.config import scaled_cluster_preset
from rtap_tpu.obs import ExpositionServer, FlightRecorder, validate_bundle
from rtap_tpu.predict import PredictTracker
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroupRegistry

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CFG = scaled_cluster_preset(32)
N_STREAMS = 6
GROUP_SIZE = 3
N_TICKS = 10
HORIZON = 2


def _registry(predict: int, backend: str = "tpu"):
    reg = StreamGroupRegistry(CFG, group_size=GROUP_SIZE, backend=backend,
                              threshold=0.0, debounce=1, predict=predict)
    for i in range(N_STREAMS):
        reg.add_stream(f"s{i}")
    reg.finalize()
    return reg


def _feed(k):
    rng = np.random.Generator(np.random.Philox(key=(83, k)))
    return (30 + 5 * rng.random(N_STREAMS)).astype(np.float32), \
        1_700_000_000 + k


def _drift_feed(k, n=N_STREAMS, calm_until=24):
    """Learnable constant, then an unpredictable jump walk: the TM's
    one-step prediction goes stale and the miss EWMA climbs."""
    if k < calm_until:
        return np.full(n, 30.0, np.float32), 1_700_000_000 + k
    rng = np.random.Generator(np.random.Philox(key=(97, k)))
    return (10 + 80 * rng.random(n)).astype(np.float32), 1_700_000_000 + k


def _alert_lines(path):
    with open(path) as f:
        return [ln for ln in f.read().splitlines()
                if ln and not ln.startswith('{"event"')]


def _event_lines(path, kind):
    with open(path) as f:
        return [json.loads(ln) for ln in f.read().splitlines()
                if ln.startswith('{"event"')
                and json.loads(ln).get("event") == kind]


@pytest.mark.quick
def test_predict_on_vs_off_bit_exact_state_and_alert_stream(tmp_path):
    """The neutrality bar: the reducer is a pure read — model state and
    the alert stream are provably unchanged with predict on."""
    finals = {}
    for k_on in (0, HORIZON):
        reg = _registry(predict=k_on)
        alerts = tmp_path / f"alerts_{k_on}.jsonl"
        pt = PredictTracker(horizon=HORIZON) if k_on else None
        stats = live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.005,
                          alert_path=str(alerts), micro_chunk=2,
                          predictor=pt)
        assert stats["ticks"] == N_TICKS
        finals[k_on] = [
            {k: np.asarray(v) for k, v in g.state.items()}
            for g in reg.groups
        ]
        if k_on:
            assert stats["predict"]["groups"] == len(reg.groups)
            assert stats["predict"]["ticks_folded"] == \
                N_TICKS * len(reg.groups)
    for g_off, g_on in zip(finals[0], finals[HORIZON]):
        # predict=k adds ONLY the pred_* leaves
        extra = sorted(set(g_on) - set(g_off))
        assert extra == ["pred_miss_ewma", "pred_ring", "pred_tick0"]
        for k in g_off:
            np.testing.assert_array_equal(g_off[k], g_on[k], err_msg=k)
    lines_off = _alert_lines(tmp_path / "alerts_0.jsonl")
    lines_on = _alert_lines(tmp_path / f"alerts_{HORIZON}.jsonl")
    assert lines_off and lines_off == lines_on


@pytest.mark.quick
def test_predict_route_serves_fleet_rollup_and_scorecards():
    reg = _registry(predict=HORIZON)
    pt = PredictTracker(horizon=HORIZON)
    live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.005, predictor=pt)
    with ExpositionServer(predict=pt) as srv:
        host, port = srv.address
        body = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/predict", timeout=10).read())
    fleet = body["fleet"]
    assert fleet["groups"] == len(reg.groups)
    assert fleet["ticks_folded"] == N_TICKS * len(reg.groups)
    assert fleet["horizon_ticks"] == HORIZON
    assert fleet["verdict"] in ("ok", "precursor")
    for g in body["groups"]:
        assert g["streams_scored"] >= 1  # past the tiny horizon by now
        assert g["miss_ewma"]["max"] is not None
        assert 0.0 <= g["miss_ewma"]["max"] <= 1.0
        assert g["verdict"]


@pytest.mark.quick
def test_predict_route_404_without_tracker():
    with ExpositionServer() as srv:
        host, port = srv.address
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"http://{host}:{port}/predict",
                                   timeout=10)
        assert e.value.code == 404


@pytest.mark.quick
def test_drift_pages_precursor_and_bundle_embeds_scorecard(tmp_path):
    """The paging path end to end: sustained predictive divergence emits
    a precursor onto the alert stream and the flight recorder dumps a
    bundle whose summary embeds the predict snapshot."""
    reg = _registry(predict=HORIZON)
    pm = tmp_path / "pm"
    fl = FlightRecorder(n_ticks=64, out_dir=str(pm))
    alerts = tmp_path / "alerts.jsonl"
    pt = PredictTracker(horizon=HORIZON, threshold=0.3, min_ticks=3,
                        warmup_ticks=4)
    stats = live_loop(_drift_feed, reg, n_ticks=60, cadence_s=0.002,
                      alert_path=str(alerts), flight=fl, predictor=pt)
    assert stats["predict"]["events"].get("precursor", 0) >= 1
    pre = _event_lines(alerts, "precursor")
    assert pre, "no precursor on the alert stream"
    ev = pre[0]
    assert ev["alert_id"] == f"precursor:{ev['stream']}:{ev['tick']}"
    assert ev["predicted_lead_ticks"] == HORIZON
    assert ev["miss_ewma"] >= 0.3
    assert ev["threshold"] == 0.3 and ev["horizon_ticks"] == HORIZON
    bundles = [d for d in pm.iterdir() if "precursor" in d.name]
    assert bundles, list(pm.iterdir())
    v = validate_bundle(str(bundles[0]))
    assert v["ok"], v
    summary = json.loads((bundles[0] / "summary.json").read_text())
    assert summary["reason"] == "precursor"
    assert summary["predict"]["fleet"]["streams_alarmed"] >= 1


@pytest.mark.quick
def test_journal_replay_suppresses_precursor_exactly_once(tmp_path):
    """Resume continuity: a journaled run that paged a precursor is
    replayed from scratch — the fold re-derives the SAME alert_id on the
    group-tick clock and the suppression set swallows it."""
    from rtap_tpu.resilience import TickJournal

    jdir = str(tmp_path / "journal")
    alerts = str(tmp_path / "alerts.jsonl")

    def mkpt():
        return PredictTracker(horizon=HORIZON, threshold=0.3, min_ticks=3,
                              warmup_ticks=4)

    reg = _registry(predict=HORIZON, backend="cpu")
    j = TickJournal(jdir)
    live_loop(_drift_feed, reg, n_ticks=40, cadence_s=0.0,
              alert_path=alerts, journal=j, predictor=mkpt())
    j.close()
    first = _event_lines(alerts, "precursor")
    assert first, "run 1 paged no precursor"

    # resume: no checkpoint — the whole journal replays through a fresh
    # registry and tracker; every precursor is re-derived and suppressed
    j2 = TickJournal(jdir)
    reg2 = _registry(predict=HORIZON, backend="cpu")
    pt2 = mkpt()
    stats = live_loop(_drift_feed, reg2, n_ticks=0, cadence_s=0.0,
                      alert_path=alerts, journal=j2, predictor=pt2)
    j2.close()
    assert stats["journal"]["replayed_ticks"] == 40
    assert pt2.events_suppressed >= len(first)
    after = _event_lines(alerts, "precursor")
    assert [e["alert_id"] for e in after] == \
        [e["alert_id"] for e in first]  # exactly-once
    # the tracker still latched the alarm state it replayed through
    assert pt2.stats()["streams_alarmed"] >= 1


@pytest.mark.quick
def test_predict_variant_is_aot_prewarmed():
    """The predict flag is a STATIC of the compiled step — and jit keys
    on how statics are passed. The AOT warm-up must dispatch the exact
    predict variant the loop will (explicit flag + predictor-sized
    scratch state) or every program recompiles inside a scored tick."""
    from rtap_tpu.ops.step import chunk_step
    from rtap_tpu.service.aot import prewarm

    reg = _registry(predict=HORIZON)
    pre = prewarm(reg.groups, 2, learn=True)
    assert pre
    cache_at_tick0 = chunk_step._cache_size()
    stats = live_loop(_feed, reg, n_ticks=6, cadence_s=0.0, micro_chunk=2,
                      aot_warmup=True, predictor=PredictTracker(HORIZON))
    assert stats["cold_compiles_after_warmup"] == 0
    assert chunk_step._cache_size() == cache_at_tick0, (
        "a predict-armed dispatch compiled a program the warm-up missed"
    )


@pytest.mark.quick
def test_serve_cli_predict_end_to_end(tmp_path):
    """`serve --predict` through the operator command: armed stderr,
    stats carry the predict block, and the snapshot carries the fold
    histogram + fleet gauges."""
    alerts = tmp_path / "alerts.jsonl"
    snap_path = tmp_path / "obs.jsonl"
    p = subprocess.run(
        [sys.executable, "-m", "rtap_tpu", "serve",
         "--streams", "a,b", "--group-size", "2",
         "--ticks", "4", "--cadence", "0.05", "--backend", "cpu",
         "--alerts", str(alerts), "--predict", "--predict-horizon", "2",
         "--obs-snapshot", str(snap_path)],
        cwd=REPO, env={**os.environ, "RTAP_FORCE_CPU": "1"},
        capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "predictive horizon armed (k=2 ticks" in p.stderr
    stats = json.loads(p.stdout.strip().splitlines()[-1])
    assert stats["predict"]["groups"] == 1
    assert stats["predict"]["ticks_folded"] == 4
    assert stats["predict"]["horizon_ticks"] == 2
    from rtap_tpu.obs import read_last_snapshot, summarize_snapshot

    s = summarize_snapshot(read_last_snapshot(str(snap_path)))
    assert s["rtap_obs_predict_fold_seconds"]["count"] >= 4
    assert "rtap_obs_predict_streams_alarmed" in s


@pytest.mark.quick
def test_serve_cli_predict_usage_errors():
    """The flag-gate sweep: every invalid combination is a usage error
    (exit 2) BEFORE any backend or listener comes up."""
    cases = [
        (["--streams", "a", "--predict-horizon", "4"],
         "add --predict"),
        (["--streams", "a", "--predict-threshold", "0.5"],
         "add --predict"),
        (["--streams", "a", "--predict-min-ticks", "6"],
         "add --predict"),
        (["--streams", "a", "--predict", "--predict-horizon", "0"],
         "--predict-horizon must be >= 1"),
        (["--streams", "a", "--predict", "--predict-min-ticks", "0"],
         "--predict-min-ticks must be >= 1"),
        (["--streams", "a", "--predict", "--predict-threshold", "1.5"],
         "bad --predict parameters"),
        (["--streams", "a", "--predict", "--replicate-to", "h:1",
          "--journal-dir", "j", "--lease-file", "l",
          "--checkpoint-dir", "c"],
         "--predict under replication is unsupported"),
    ]
    for extra, needle in cases:
        p = subprocess.run(
            [sys.executable, "-m", "rtap_tpu", "serve", *extra],
            cwd=REPO, env={**os.environ, "RTAP_FORCE_CPU": "1"},
            capture_output=True, text=True, timeout=600)
        assert p.returncode == 2, (extra, p.returncode, p.stderr[-500:])
        assert needle in p.stderr, (extra, p.stderr[-500:])
