"""scripts/chaos_soak.py through the real CLI: seeded schedule, live run,
machine-checked silent-gap verdict, reproducible digest (ISSUE 2
acceptance: `--seed N` is a full reproducer; a silently-unscored stream
exits non-zero)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
ENV = {**os.environ, "RTAP_FORCE_CPU": "1"}


def test_chaos_soak_runs_verified_and_digest_is_seed_stable(tmp_path):
    out = tmp_path / "report.json"
    p = subprocess.run(
        [sys.executable, "scripts/chaos_soak.py", "--seed", "3",
         "--streams", "6", "--group-size", "2", "--ticks", "40",
         "--cadence", "0.02", "--rate", "0.12", "--backend", "cpu",
         "--workdir", str(tmp_path / "wd"), "--out", str(out)],
        cwd=REPO, env=ENV, capture_output=True, text=True, timeout=420,
    )
    assert p.returncode == 0, p.stderr[-3000:]
    report = json.loads(out.read_text())
    assert report["verified"] and report["failures"] == []
    assert report["stats"]["ticks"] == 40
    # the digest is a pure function of the seed + shape: recompute it
    # here and pin the cross-process stability --seed promises
    from rtap_tpu.resilience import ChaosSpec

    expect = ChaosSpec.generate(seed=3, n_ticks=40, n_groups=3,
                                rate=0.12).digest()
    assert report["schedule_digest"] == expect
    # every scheduled fault that fired is logged with its tick
    for inj in report["faults_injected"]:
        assert 0 <= inj["tick"] < 40 and "kind" in inj
    # ---- flight-recorder verdict folded into the digest (ISSUE 4): the
    # soak flies armed, every dumped bundle validated (the script itself
    # fails on an invalid one, so verified=True implies valid==bundles),
    # and a quarantine without a bundle is a failure the script catches
    pm = report["postmortem"]
    assert pm["valid"] == len(pm["bundles"])
    assert pm["trace_records"] > 0
    quarantined = any(e["event"] == "group_quarantined"
                      for e in report["stats"].get("quarantine_log", []))
    if quarantined:
        assert pm["bundles"] and pm["spans"] > 0 and pm["events"] > 0
    if pm["bundles"]:
        # the bundles are real directories in the workdir, loadable
        from rtap_tpu.obs import validate_bundle

        for b in pm["bundles"]:
            assert validate_bundle(os.path.join(pm["dir"], b))["ok"]