"""ISSUE 9 integration: workload breadth end to end.

- serve CLI flag gates for the correlation/composite knobs (usage errors
  surface instantly, before backend init — the ingest/replication gate
  discipline);
- the tiny K=1 cascading-fault workload soak (scripts/workload_soak.py):
  one seeded multi-node burst -> exactly ONE cluster-level incident,
  identical across a kill-9 journal-replay resume;
- the new-modality scoring pipeline at miniature scale (categorical
  burst detection through replay_streams);
- ``GET /incidents`` on the obs server.

Named to sort after test_cli.py so the tier-1 870 s window's dot count
is untouched (ROADMAP verify note).
"""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
ENV = {**os.environ, "RTAP_FORCE_CPU": "1"}


def run_cli(*args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "rtap_tpu", *args],
        cwd=REPO, env=ENV, capture_output=True, text=True, timeout=timeout)


# ---------------------------------------------------- CLI flag gates
@pytest.mark.quick
def test_serve_rejects_correlate_knobs_without_topology():
    p = run_cli("serve", "--streams", "a", "--alerts", "/tmp/x.jsonl",
                "--correlate-window", "10")
    assert p.returncode == 2
    assert "--topology" in p.stderr
    p = run_cli("serve", "--streams", "a", "--alerts", "/tmp/x.jsonl",
                "--correlate-min-streams", "3")
    assert p.returncode == 2
    assert "--topology" in p.stderr


@pytest.mark.quick
def test_serve_rejects_topology_without_alerts():
    p = run_cli("serve", "--streams", "a", "--topology", "infer")
    assert p.returncode == 2
    assert "--alerts" in p.stderr


@pytest.mark.quick
def test_serve_rejects_degenerate_correlate_values():
    p = run_cli("serve", "--streams", "a", "--alerts", "/tmp/x.jsonl",
                "--topology", "infer", "--correlate-window", "0")
    assert p.returncode == 2 and "--correlate-window" in p.stderr
    p = run_cli("serve", "--streams", "a", "--alerts", "/tmp/x.jsonl",
                "--topology", "infer", "--correlate-min-streams", "1")
    assert p.returncode == 2 and "--correlate-min-streams" in p.stderr


@pytest.mark.quick
def test_serve_rejects_bad_topology_spec(tmp_path):
    bad = tmp_path / "topo.json"
    bad.write_text(json.dumps({"links": [["a", "b"]]}))  # no "services"
    p = run_cli("serve", "--streams", "a", "--alerts", "/tmp/x.jsonl",
                "--topology", str(bad))
    assert p.returncode == 2
    assert "bad --topology" in p.stderr


@pytest.mark.quick
def test_serve_rejects_topology_under_replication():
    p = run_cli("serve", "--streams", "a", "--alerts", "/tmp/x.jsonl",
                "--topology", "infer", "--replicate-to", "h:1",
                "--journal-dir", "/tmp/j", "--lease-file", "/tmp/l",
                "--checkpoint-dir", "/tmp/ck")
    assert p.returncode == 2
    assert "replication" in p.stderr


@pytest.mark.quick
def test_serve_rejects_columns_on_composite_presets():
    for preset in ("composite", "categorical"):
        p = run_cli("serve", "--streams", "a", "--preset", preset,
                    "--columns", "32")
        assert p.returncode == 2
        assert "cluster preset only" in p.stderr


# ------------------------------------------- the cascading-fault soak
def test_workload_soak_one_kill_one_incident(tmp_path):
    """K=1 smoke of the acceptance soak: the seeded cascade produces
    exactly one incident whose stream is identical across a kill-9
    resume; the soak's exit code IS the verdict (5 = violated)."""
    out = str(tmp_path / "report.json")
    env = dict(ENV)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU child must not dial out
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "workload_soak.py"),
         "--seed", "3", "--kills", "1", "--ticks", "180",
         "--cadence", "0.01", "--checkpoint-every", "12",
         "--backend", "cpu", "--workdir", str(tmp_path / "w"),
         "--out", out],
        env=env, capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, \
        f"workload soak rc={proc.returncode}\n{proc.stderr[-3000:]}"
    report = json.load(open(out))
    assert report["verified"], report["failures"]
    assert report["incidents_reference"] == 1
    assert report["incidents_crash_run"] == 1
    inc = report["incident"]
    assert sorted(inc["nodes"]) == sorted(report["burst_nodes"])
    assert inc["members"] >= 3


def test_chaos_topology_burst_pages_one_incident(tmp_path):
    """The --topology-burst chaos drill (ISSUE 9 satellite): a seeded
    correlated multi-group burst through the real chaos harness pages
    exactly ONE incident; exit code 5 = the verdict was violated."""
    out = str(tmp_path / "report.json")
    env = dict(ENV)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--seed", "1", "--topology-burst", "--backend", "cpu",
         "--cadence", "0.01", "--workdir", str(tmp_path / "w"),
         "--out", out],
        env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, \
        f"topology-burst drill rc={proc.returncode}\n{proc.stderr[-3000:]}"
    report = json.load(open(out))
    assert report["verified"], report["failures"]
    assert report["incidents"] == 1
    assert report["incident"]["nodes"] == report["burst_nodes"]
    assert len(report["burst_groups"]) >= 2
    assert any(e["kind"] == "topology_burst"
               for e in report["faults_injected"])


# ------------------------------------- new modalities score end to end
def test_categorical_burst_detected_at_miniature_scale():
    """The categorical modality's reason to exist, scored through the
    real replay pipeline at the 32-col tier-1 geometry: a novel-class
    burst drives the likelihood out of the steady band."""
    from rtap_tpu.data.synthetic import (
        SyntheticStreamConfig,
        generate_categorical_stream,
    )
    from rtap_tpu.eval.workload_eval import tiny_eval_configs
    from rtap_tpu.service.loop import replay_streams

    cat_cfg, _tiny, _comp = tiny_eval_configs()
    scfg = SyntheticStreamConfig(length=260, cadence_s=1.0, n_anomalies=1,
                                 inject_after_frac=0.5)
    # 2 steady classes: iid class draws are irreducibly surprising to a
    # sequence learner, so the 32-col miniature needs a low-entropy
    # steady mix to show clean contrast (the full-scale eval artifact
    # covers the 6-class default through the likelihood layer)
    streams = [generate_categorical_stream(f"ev{i}.class", scfg, seed=5,
                                           n_classes=2)
               for i in range(2)]
    res = replay_streams(streams, cat_cfg, backend="cpu", chunk_ticks=64)
    ll = res.log_likelihood
    for si, s in enumerate(streams):
        (w_lo, w_hi), = s.windows
        in_w = (res.timestamps >= w_lo) & (res.timestamps <= w_hi)
        assert ll[in_w, si].max() > ll[~in_w, si].max() + 0.01, \
            f"stream {si}: burst not separable from steady state"


def test_composite_preset_serves_multifield_records():
    """The composite twin runs through the real replay path (oracle
    backend) on {value, delta, event-class} rows without error and
    produces finite scores."""
    from rtap_tpu.data.synthetic import (
        LabeledStream,
        SyntheticStreamConfig,
        generate_stream,
    )
    from rtap_tpu.eval.workload_eval import tiny_eval_configs
    from rtap_tpu.service.loop import replay_streams

    _cat, _tiny, comp_cfg = tiny_eval_configs()
    scfg = SyntheticStreamConfig(length=120, n_anomalies=0)
    base = generate_stream("web-00.cpu", scfg, seed=1)
    rows = np.stack([base.values, base.values,
                     np.zeros_like(base.values)], axis=1)
    s = LabeledStream(base.stream_id, base.timestamps, rows, [], [])
    res = replay_streams([s], comp_cfg, backend="cpu", chunk_ticks=40)
    assert np.isfinite(res.log_likelihood).all()
    assert res.log_likelihood.shape[0] == 120


# ------------------------------------------------- GET /incidents
def test_obs_incidents_route():
    from rtap_tpu.correlate import IncidentCorrelator, TopologyMap
    from rtap_tpu.obs.expo import ExpositionServer
    from rtap_tpu.obs.metrics import TelemetryRegistry

    co = IncidentCorrelator(TopologyMap.infer(), window_s=5, min_streams=2,
                            sink=lambda _r: None,
                            registry=TelemetryRegistry())
    co.observe_alert("a1", "web-00.cpu", 100)
    co.observe_alert("a2", "web-01.cpu", 101)
    for t in range(102, 110):
        co.on_tick(t)
    srv = ExpositionServer(registry=TelemetryRegistry(),
                           correlator=co).start()
    try:
        host, port = srv.address
        body = urllib.request.urlopen(
            f"http://{host}:{port}/incidents", timeout=10).read()
        snap = json.loads(body)
        assert snap["incidents_emitted"] == 1
        assert len(snap["incidents"]) == 1
        assert snap["incidents"][0]["nodes"] == ["web-00", "web-01"]
        assert snap["topology"]["inferring"] is True
        # without a correlator the route 404s (feature off = no surface)
        bare = ExpositionServer(registry=TelemetryRegistry()).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://{bare.address[0]}:{bare.address[1]}/incidents",
                    timeout=10)
            assert ei.value.code == 404
        finally:
            bare.close()
    finally:
        srv.close()
