"""ISSUE 8 acceptance: hot-standby failover, end to end.

(Named to sort after test_durability_soak/test_cli so the tier-1 870 s
dot-count window is untouched — these drills pay real process restarts.)

1. the failover soak smoke — ``scripts/failover_soak.py`` SIGKILLs the
   CURRENT leader of a live replicated pair twice at seeded
   journal-observed ticks, runs a SIGSTOP fence round, and its own
   verdict machinery proves: final checkpoint state bit-identical to a
   fault-free run (every orbax leaf), the spliced alert stream
   exactly-once, every takeover detected within the 10-tick budget,
   and the woken zombie leader fenced out of the alert sink
   (rc FENCED_RC, zero appends);
2. the serve CLI pair — ``serve --replicate-to`` / ``serve --standby``
   wired end to end: the standby mirrors the leader's journal
   byte-identically and stops cleanly on SIGTERM;
3. the flag-consistency gates (usage errors before backend init).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.quick

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _env():
    env = {**os.environ, "RTAP_FORCE_CPU": "1"}
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU child must not dial a tunnel
    return env


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_failover_soak_two_kills_and_fence_round(tmp_path):
    """The in-tree acceptance smoke: 2 SIGKILLs + 1 SIGSTOP fence round;
    the soak's exit code IS the verdict (5 = availability violated)."""
    out = str(tmp_path / "report.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "failover_soak.py"),
         "--seed", "3", "--kills", "2", "--streams", "6",
         "--group-size", "3", "--ticks", "80", "--cadence", "0.25",
         "--checkpoint-every", "6", "--backend", "cpu",
         "--workdir", str(tmp_path / "w"), "--out", out],
        env=_env(), capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, \
        f"failover soak failed rc={proc.returncode}\n{proc.stderr[-4000:]}"
    report = json.load(open(out))
    assert report["verified"], report["failures"]
    assert len(report["kills"]) == 2
    # every SCHEDULED takeover inside the 10-tick detection budget —
    # report["verified"] above already enforced it per kill/fence
    # anchor; here just pin that all three takeovers left their record
    assert len(report["promotions"]) >= 3  # 2 kills + the fence round
    # exactly-once across every splice
    assert report["duplicated"] == 0 and report["lost"] == 0
    assert report["extra"] == 0
    assert report["alert_ids"] > 0
    # bit-identical final model state
    assert report["state_leaves_compared"] > 0
    # the fence proof: the paused old leader exited FENCED_RC and its
    # post-fence sink writes were refused (counted, never written)
    assert report["fence_round"] is not None
    assert report["fence_round"]["rc"] == 7
    assert report["fenced_exits"], "no child reported a fenced exit"
    assert all(s["fenced_line_drops"] >= 1 for s in report["fenced_exits"])


def test_serve_cli_leader_standby_pair(tmp_path):
    """serve --replicate-to / --standby end to end: the standby mirrors
    the leader's journal byte-range exactly and SIGTERM stops it with
    an orderly stats line. (No producer pushes: NaN ticks — journal
    shipping is exercised regardless, every tick appends.)"""
    from rtap_tpu.resilience import last_journal_tick

    w = tmp_path
    port = _free_port()
    lease = str(w / "lease")
    # 25 ticks at 0.3 s = a ~7.5 s serving window: the standby child
    # pays its own interpreter+backend init AFTER the leader's (the
    # 1-core tier-1 host serializes them), and the leader's sender must
    # still be alive to connect+backfill when the listener comes up —
    # a 10x0.2 s window raced that init and flaked with an empty mirror
    common = ["--streams", "a,b,c", "--backend", "cpu", "--ticks", "25",
              "--cadence", "0.3", "--group-size", "3",
              "--checkpoint-dir", str(w / "ck"),
              "--alerts", str(w / "alerts.jsonl"),
              "--lease-file", lease, "--lease-timeout", "30"]
    leader = subprocess.Popen(
        [sys.executable, "-m", "rtap_tpu", "serve", *common,
         "--journal-dir", str(w / "jl"),
         "--replicate-to", f"127.0.0.1:{port}"],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    # the standby joins once the leader holds the lease (a standby with
    # no lease at all would rightly promote itself)
    deadline = time.time() + 120
    while time.time() < deadline and not os.path.isfile(lease):
        time.sleep(0.05)
    assert os.path.isfile(lease), "leader never acquired the lease"
    standby = subprocess.Popen(
        [sys.executable, "-m", "rtap_tpu", "serve", *common, "--standby",
         "--journal-dir", str(w / "js"),
         "--replicate-listen", str(port)],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    lout, lerr = leader.communicate(timeout=300)
    assert leader.returncode == 0, f"leader failed:\n{lerr[-3000:]}"
    lstats = json.loads(lout.strip().splitlines()[-1])
    assert lstats["ticks"] == 25
    assert "replication" in lstats
    # give the mirror a beat to drain the tail, then stop the standby
    deadline = time.time() + 60
    while time.time() < deadline and \
            last_journal_tick(str(w / "js")) < 24:
        time.sleep(0.1)
    standby.send_signal(signal.SIGTERM)
    sout, serr = standby.communicate(timeout=300)
    assert standby.returncode == 0, f"standby failed:\n{serr[-3000:]}"
    sline = json.loads(sout.strip().splitlines()[-1])
    # either an orderly follow-stop, or (if the lease went stale first)
    # a zero-remaining promotion — both are clean exits with stats
    assert sline.get("stopped") or sline.get("promoted_from_standby")
    # the mirror reached the leader's last journaled tick
    assert last_journal_tick(str(w / "js")) == \
        last_journal_tick(str(w / "jl")) == 24


@pytest.mark.parametrize("argv,needle", [
    (["--standby"], "--standby needs"),
    (["--replicate-to", "127.0.0.1:1"], "add --journal-dir"),
    (["--journal-dir", "j", "--replicate-to", "127.0.0.1:1"],
     "needs --lease-file"),
    (["--journal-dir", "j", "--replicate-to", "127.0.0.1:1",
      "--lease-file", "l"], "needs --checkpoint-dir"),
    (["--replicate-listen", "7"], "add --standby"),
    (["--journal-dir", "j", "--replicate-to", "127.0.0.1:1",
      "--lease-file", "l", "--checkpoint-dir", "c",
      "--auto-register"], "FIXED fleet"),
    (["--journal-dir", "j", "--replicate-to", "127.0.0.1:1",
      "--lease-file", "l", "--checkpoint-dir", "c",
      "--alert-attribution"], "--alert-attribution under replication"),
])
def test_serve_replication_flag_gates(argv, needle):
    proc = subprocess.run(
        [sys.executable, "-m", "rtap_tpu", "serve", "--streams", "a",
         "--backend", "cpu", *argv],
        env=_env(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert needle in proc.stderr


def test_chaos_soak_replication_mode(tmp_path):
    """ISSUE 8 satellite: the seeded wire fault kinds (conn_drop,
    stall_socket, corrupt_bytes) against a live leader/standby pair —
    chaos_soak's own verdict proves the standby stays bit-identical."""
    out = str(tmp_path / "report.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--replication", "--seed", "2", "--streams", "6",
         "--group-size", "3", "--ticks", "48", "--cadence", "0.02",
         "--rate", "0.15", "--backend", "cpu", "--checkpoint-every", "8",
         "--workdir", str(tmp_path / "w"), "--out", out],
        env=_env(), capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, \
        f"replication chaos soak failed rc={proc.returncode}\n" \
        f"{proc.stderr[-3000:]}"
    report = json.load(open(out))
    assert report["verified"], report["failures"]
    kinds = {e["kind"] for e in report["faults_injected"]}
    assert kinds == {"conn_drop", "stall_socket", "corrupt_bytes"}
    assert report["standby"]["applied_ticks"] == 48
    assert report["state_leaves_compared"] > 0
