"""Detection-quality floors on the fault-injection eval (SURVEY.md §3.5).

The reference's evaluation method is fault injection against a monitored
cluster; this is the round-3 hardening of eval/fault_eval.py (round-2
verdict: "zero tests, unexercised"), with round-4 floors raised to the
quality-study results (reports/quality_study.json: the production streaming
config measures f1 0.853 / precision 0.831 / recall 0.875 on the 40-stream
fixture and 0.789/0.760/0.821 at the 120-stream artifact scale; the
window-mode fixture here stays the NuPIC-faithful comparison config). A
regression in the encoder/SP/TM/likelihood chain or in the preset tuning
trips the floors.

Note the floors certify the DEFAULT cluster preset, i.e. the quantized
u16 permanence domain — compression and quality are tested together.
"""

import numpy as np
import pytest

from rtap_tpu.data.synthetic import ANOMALY_KINDS
from rtap_tpu.eval.fault_eval import run_fault_eval

DETECTABLE = ("spike", "level_shift", "dropout")


@pytest.fixture(scope="module")
def report():
    return run_fault_eval(n_streams=40, length=1000, backend="tpu", chunk_ticks=128)


def test_overall_floors(report):
    b = report.at_best
    assert b["f1"] >= 0.60, b
    assert b["recall"] >= 0.80, b
    assert b["precision"] >= 0.50, b  # episode-level
    assert b["median_latency_s"] is not None and b["median_latency_s"] <= 10.0, b


def test_default_threshold_is_usable(report):
    """The shipped service default (0.5) must stay within sight of the swept
    optimum — if the sweep's best threshold drifts far from the default, the
    deployed alerting behavior has silently degraded."""
    d = report.at_default
    assert d["f1"] >= 0.55, d
    assert d["recall"] >= 0.70, d


def test_per_kind_recall_and_lead(report):
    for kind in DETECTABLE:
        k = report.per_kind[kind]
        assert k["events"] >= 10, (kind, k)  # the workload actually covers it
        assert k["recall"] >= 0.70, (kind, k)
        # early warning: alerts fire before the labeled window closes
        assert k["median_lead_s"] is not None and k["median_lead_s"] > 0, (kind, k)


def test_all_kinds_reported():
    """The --all-kinds path: drift/stuck are evaluated and reported per kind
    (their recall is allowed to be poor — gradual faults are near-invisible
    to a point-anomaly detector — but the measurement must exist)."""
    rep = run_fault_eval(
        n_streams=20, length=1000, kinds=ANOMALY_KINDS, backend="tpu",
        chunk_ticks=128,
    )
    seen = set(rep.per_kind)
    assert set(ANOMALY_KINDS) <= seen, seen
    for kind in ANOMALY_KINDS:
        assert rep.per_kind[kind]["events"] > 0, kind
    # detectable kinds keep working in the mixed workload
    det = [rep.per_kind[k] for k in DETECTABLE]
    got = sum(k["detected"] for k in det) / sum(k["events"] for k in det)
    assert got >= 0.6, rep.per_kind


def test_report_roundtrip(report, tmp_path):
    p = tmp_path / "report.json"
    p.write_text(report.to_json())
    import json

    loaded = json.loads(p.read_text())
    assert loaded["at_best"]["f1"] == report.at_best["f1"]
    assert loaded["n_streams"] == 40
    assert 0.05 <= loaded["best_threshold"] <= 0.95


def test_probation_alignment():
    """Injections land after the likelihood probation: a fault the detector
    cannot see by construction must not be scored as a miss."""
    from rtap_tpu.config import cluster_preset
    from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_stream

    cfg = cluster_preset()
    prob = cfg.likelihood.probationary_period
    scfg = SyntheticStreamConfig(
        length=1000, inject_after_frac=cfg.likelihood.safe_inject_frac(1000),
        kinds=DETECTABLE,
    )
    s = generate_stream("n0.cpu", scfg, seed=1)
    first_onset = min(ev.onset for ev in s.events) - int(s.timestamps[0])
    assert first_onset >= prob, (first_onset, prob)
    # too-short streams fail loudly instead of silently scoring probation
    with pytest.raises(ValueError, match="too short"):
        cfg.likelihood.safe_inject_frac(600)


@pytest.fixture(scope="module")
def streaming_report():
    """The PRODUCTION configuration (streaming likelihood, exactly as the
    preset, bench.py, and the 100k path run it) at 40x1000 — shared by the
    k=1 floors and the cadence comparison below."""
    from rtap_tpu.config import cluster_preset

    return run_fault_eval(n_streams=40, length=1000, cfg=cluster_preset(),
                          backend="tpu", chunk_ticks=128)


def test_streaming_mode_floors(streaming_report):
    """The production streaming config holds its own floors — measured
    this round: f1 0.853, episode precision 0.831, recall 0.875 at
    (thr 0.27, debounce 1) on this seed; 0.760/0.821 at the 120-stream
    artifact scale (reports/fault_eval.json, reports/quality_study.json).
    Floors are achieved-minus-margin per the r3 verdict item 4; the
    120-stream artifact also clears the verdict target (precision >= 0.70
    at recall >= 0.75)."""
    rep = streaming_report
    b = rep.at_best
    assert b["f1"] >= 0.80, b
    assert b["recall"] >= 0.82, b
    assert b["precision"] >= 0.77, b
    # the shipped default operating point (thr 0.5, debounce 2) leans
    # precision-first; it must stay a usable page-on-it default
    d = rep.at_default
    assert d["precision"] >= 0.85, d
    assert d["recall"] >= 0.45, d


def test_learn_cadence_quality_floor(streaming_report):
    """The documented k=2 point of the cadence operating curve (SCALING.md,
    reports/cadence/) holds its floors: measured f1 0.816 / P 0.833 /
    R 0.800 on this fixture. A kernel or schedule regression that degrades
    thinned-learning quality (e.g. the cadence silently not applying —
    the r4 registry bug) trips this before it reaches an operator."""
    from rtap_tpu.config import cluster_preset

    rep = run_fault_eval(
        n_streams=40, length=1000, cfg=cluster_preset().with_learn_every(2),
        backend="tpu", chunk_ticks=128,
    )
    b = rep.at_best
    assert b["f1"] >= 0.78, b
    assert b["recall"] >= 0.76, b
    assert b["precision"] >= 0.79, b
    # and the thinning must actually have happened: compare against the
    # SAME k=1 run (shared fixture) — identical scores would mean the
    # schedule is inert (the r4 registry-bug class this test exists for)
    assert b["f1"] < streaming_report.at_best["f1"], (
        "cadence apparently not applied", b, streaming_report.at_best,
    )
