"""The obs subsystem wired through the real serve stack (ISSUE 1
acceptance): a short CPU replay under live_loop must expose non-zero
rtap_obs_ticks_total and per-phase rtap_obs_phase_seconds histograms via
BOTH the JSONL snapshot and the Prometheus text endpoint — and the
ADVICE-r5 mid-chunk membership fix must survive an out-of-band registry
bump + source resize in plain micro_chunk mode.

The registry is process-wide (other tests may have run serve paths in this
process), so every assertion here is on DELTAS around this test's run.
"""

import json
import urllib.request

import numpy as np

from rtap_tpu.config import cluster_preset
from rtap_tpu.obs import (
    ExpositionServer,
    get_registry,
    read_last_snapshot,
    summarize_snapshot,
    write_snapshot,
)
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroupRegistry

G_TOTAL = 6
GROUP_SIZE = 4
N_TICKS = 8


def _registry():
    reg = StreamGroupRegistry(cluster_preset(), group_size=GROUP_SIZE,
                              backend="tpu")
    for i in range(G_TOTAL):
        reg.add_stream(f"s{i}")
    reg.finalize()
    return reg


def _feed(k):
    rng = np.random.Generator(np.random.Philox(key=(23, k)))
    return (30 + 5 * rng.random(G_TOTAL)).astype(np.float32), 1_700_000_000 + k


def _summary():
    return summarize_snapshot(get_registry().snapshot())


def test_live_loop_populates_registry_snapshot_and_endpoint(tmp_path):
    before = _summary()
    stats = live_loop(_feed, _registry(), n_ticks=N_TICKS, cadence_s=0.01)
    assert stats["ticks"] == N_TICKS

    # ---- JSONL snapshot surface
    snap_path = str(tmp_path / "obs.jsonl")
    write_snapshot(snap_path)
    snap = read_last_snapshot(snap_path)
    assert snap is not None
    s = summarize_snapshot(snap)
    assert s["rtap_obs_ticks_total"] - before.get("rtap_obs_ticks_total", 0) \
        == N_TICKS
    assert s["rtap_obs_scored_total"] - before.get("rtap_obs_scored_total", 0) \
        == N_TICKS * G_TOTAL
    assert s["rtap_obs_streams_active"] == G_TOTAL
    for phase in ("source", "membership", "dispatch", "collect", "emit",
                  "checkpoint"):
        key = "rtap_obs_phase_seconds{phase=%s}" % phase
        prev = before.get(key) or {"count": 0}
        assert s[key]["count"] - prev["count"] == N_TICKS, (phase, s[key])
    # the phases that always do real work must have accumulated wall time
    assert s["rtap_obs_phase_seconds{phase=dispatch}"]["sum"] > 0
    assert s["rtap_obs_tick_seconds"]["count"] >= N_TICKS

    # ---- Prometheus text endpoint surface
    with ExpositionServer() as srv:
        host, port = srv.address
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10).read().decode()
        http_snap = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/snapshot", timeout=10).read())
    assert "# TYPE rtap_obs_ticks_total counter" in body
    assert "# TYPE rtap_obs_phase_seconds histogram" in body
    ticks_line = [l for l in body.splitlines()
                  if l.startswith("rtap_obs_ticks_total ")]
    assert ticks_line and float(ticks_line[0].split()[-1]) >= N_TICKS
    assert 'rtap_obs_phase_seconds_bucket{phase="dispatch",le="+Inf"}' in body
    assert summarize_snapshot(http_snap)["rtap_obs_ticks_total"] \
        == s["rtap_obs_ticks_total"]


def test_watchdog_missed_ticks_flow_into_registry_and_alert_stream(tmp_path):
    """Sub-ms cadence on a compiling CPU backend misses its first deadline
    by construction: the miss must land in rtap_obs_missed_ticks_total AND
    as a structured missed_tick event line on the alert JSONL stream."""
    before = _summary()
    alerts = tmp_path / "alerts.jsonl"
    stats = live_loop(_feed, _registry(), n_ticks=4, cadence_s=1e-4,
                      alert_path=str(alerts))
    assert stats["missed_deadlines"] >= 1
    after = _summary()
    assert after["rtap_obs_missed_ticks_total"] \
        - before.get("rtap_obs_missed_ticks_total", 0) \
        == stats["missed_deadlines"]
    events = [json.loads(l) for l in alerts.read_text().splitlines()
              if "event" in json.loads(l)]
    missed = [e for e in events if e["event"] == "missed_tick"]
    assert len(missed) == stats["missed_deadlines"]
    assert all(e["elapsed_s"] > e["cadence_s"] for e in missed)


def test_external_membership_bump_mid_chunk_plain_micro_chunk():
    """ADVICE r5 (loop.py:690): an out-of-band registry claim + source
    resize observed with buffered rows in PLAIN micro_chunk mode used to
    defer the routing rebuild to the next natural boundary and die on the
    source-length check. The loop must now force a partial flush, rebuild
    routing, and keep serving — counted in rtap_obs_routing_rebuilds_total."""
    before = _summary()
    reg = _registry()  # group 1 holds 2 pad slots: claimable capacity
    n_ticks = 6

    def feed(k):
        ids = reg.dispatch_ids()
        if k == 1:
            # external actor: claims a slot mid-chunk (micro_chunk=3 means
            # rows for ticks 0..1 sit buffered when tick 2's membership
            # check observes the bump) and resizes the NEXT poll's vector
            reg.add_stream("late")
        rng = np.random.Generator(np.random.Philox(key=(29, k)))
        return (30 + 5 * rng.random(len(ids))).astype(np.float32), \
            1_700_000_000 + k

    stats = live_loop(feed, reg, n_ticks=n_ticks, cadence_s=0.01,
                      micro_chunk=3)
    assert stats["ticks"] == n_ticks
    # ticks 0-1 scored 6 streams, ticks 2+ scored 7 (the claimed one)
    assert stats["scored"] == 2 * G_TOTAL + (n_ticks - 2) * (G_TOTAL + 1)
    after = _summary()
    assert after["rtap_obs_routing_rebuilds_total"] \
        - before.get("rtap_obs_routing_rebuilds_total", 0) >= 1
    assert after["rtap_obs_streams_active"] == G_TOTAL + 1


def test_exposition_server_close_joins_http_thread():
    """ISSUE 13 resource-lifecycle regression: close() must join the
    HTTP thread (bounded) so no rtap-obs-http thread outlives the
    server object it served."""
    from rtap_tpu.obs.metrics import TelemetryRegistry

    srv = ExpositionServer(registry=TelemetryRegistry()).start()
    srv.close()
    assert not srv._thread.is_alive()
