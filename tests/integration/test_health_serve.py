"""Model-health observability through the real serve stack (ISSUE 6).

Acceptance tests: (1) serving with health reducers enabled is BIT-EXACT
against serving without them — final model state and the alert stream
are byte-identical (the reducers are pure reads); (2) GET /health on
the obs server serves the fleet rollup + per-group scorecard schema;
(3) a seeded drift scenario raises ``score_drift`` onto the incident
stream and auto-dumps a postmortem bundle whose summary embeds the
scorecard; (4) the operator CLI surface (`serve --health`) end to end.
"""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from rtap_tpu.config import scaled_cluster_preset
from rtap_tpu.obs import (
    ExpositionServer,
    FlightRecorder,
    HealthTracker,
    validate_bundle,
)
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroupRegistry

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CFG = scaled_cluster_preset(32)
N_STREAMS = 6
GROUP_SIZE = 4
N_TICKS = 8


def _registry(health: bool):
    reg = StreamGroupRegistry(CFG, group_size=GROUP_SIZE, backend="tpu",
                              threshold=0.0, debounce=1, health=health)
    for i in range(N_STREAMS):
        reg.add_stream(f"s{i}")
    reg.finalize()
    return reg


def _feed(k):
    rng = np.random.Generator(np.random.Philox(key=(61, k)))
    return (30 + 5 * rng.random(N_STREAMS)).astype(np.float32), \
        1_700_000_000 + k


def _alert_lines(path):
    with open(path) as f:
        return [ln for ln in f.read().splitlines()
                if ln and not ln.startswith('{"event"')]


@pytest.mark.quick
def test_health_on_vs_off_bit_exact_state_and_alert_stream(tmp_path):
    """The ISSUE 6 neutrality bar: the reducers are pure reads — model
    state and the alert stream are provably unchanged with health on."""
    finals = {}
    for mode in (False, True):
        reg = _registry(health=mode)
        alerts = tmp_path / f"alerts_{mode}.jsonl"
        ht = HealthTracker(CFG) if mode else None
        stats = live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.005,
                          alert_path=str(alerts), micro_chunk=2,
                          health=ht)
        assert stats["ticks"] == N_TICKS
        finals[mode] = [
            {k: np.asarray(v) for k, v in g.state.items()}
            for g in reg.groups
        ]
        if mode:
            assert stats["health"]["groups"] == len(reg.groups)
            assert stats["health"]["ticks_folded"] == \
                N_TICKS * len(reg.groups)
    for g_off, g_on in zip(finals[False], finals[True]):
        assert sorted(g_off) == sorted(g_on)
        for k in g_off:
            np.testing.assert_array_equal(g_off[k], g_on[k], err_msg=k)
    # threshold 0 + debounce 1: every (stream, tick) alerted — the
    # streams must agree byte for byte (scores AND likelihoods)
    lines_off = _alert_lines(tmp_path / "alerts_False.jsonl")
    lines_on = _alert_lines(tmp_path / "alerts_True.jsonl")
    assert lines_off and lines_off == lines_on


@pytest.mark.quick
def test_health_route_serves_fleet_rollup_and_scorecards():
    reg = _registry(health=True)
    ht = HealthTracker(CFG)
    live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.005, health=ht)
    with ExpositionServer(health=ht) as srv:
        host, port = srv.address
        body = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/health", timeout=10).read())
        # a tracker-less server must say so, not 500
        resp = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10)
        assert resp.status == 200
    fleet = body["fleet"]
    assert fleet["groups"] == len(reg.groups)
    assert fleet["ticks_folded"] == N_TICKS * len(reg.groups)
    assert fleet["verdict"] in ("ok", "attention")
    assert 0.0 <= fleet["pool_occupancy_max"] <= 1.0
    assert fleet["hit_rate"] is None or 0.0 <= fleet["hit_rate"] <= 1.0
    assert len(body["groups"]) == len(reg.groups)
    for g in body["groups"]:
        assert len(g["occupancy"]["hist"]) == g["occupancy"]["bins"]
        assert sum(g["occupancy"]["hist"]) == GROUP_SIZE if \
            g["group"] == 0 else True
        assert len(g["synapses"]["perm_hist"]) == g["synapses"]["bins"]
        assert 0.0 <= g["sparsity"]["active_col_frac"] <= 1.0
        assert g["sparsity"]["expected_active_frac"] == pytest.approx(
            CFG.sp.num_active_columns / CFG.sp.columns)
        q = g["score"]["quantiles"]
        assert set(q) == {"p50", "p90", "p99"}
        assert isinstance(g["score"]["drifting"], bool)
        assert g["verdict"]


@pytest.mark.quick
def test_health_route_404_without_tracker():
    with ExpositionServer() as srv:
        host, port = srv.address
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"http://{host}:{port}/health",
                                   timeout=10)
        assert e.value.code == 404


@pytest.mark.quick
def test_seeded_drift_dumps_postmortem_with_scorecard(tmp_path):
    """The incident path end to end: a mid-run score-distribution shift
    trips the EWMA detector; the event lands on the incident stream and
    the flight recorder dumps a valid bundle embedding the scorecard,
    which both renderers accept."""
    reg = _registry(health=True)
    pm = tmp_path / "pm"

    def feed(k):
        if k < 26:
            vals = np.full(N_STREAMS, 30.0, np.float32)  # learnable calm
        else:
            # violent alternation: raw scores jump to the top bins
            vals = np.full(N_STREAMS, 10.0 if k % 2 else 90.0, np.float32)
        return vals, 1_700_000_000 + k

    fl = FlightRecorder(n_ticks=64, out_dir=str(pm))
    ht = HealthTracker(CFG, drift_min_ticks=8, drift_threshold=0.2,
                       alpha_fast=0.5, alpha_slow=0.01, warmup_ticks=4)
    alerts = tmp_path / "alerts.jsonl"
    stats = live_loop(feed, reg, n_ticks=40, cadence_s=0.002,
                      alert_path=str(alerts), flight=fl, health=ht)
    assert stats["health"]["events"].get("score_drift", 0) >= 1
    events = [json.loads(ln) for ln in alerts.read_text().splitlines()
              if ln.startswith('{"event"')]
    drift = [e for e in events if e["event"] == "score_drift"]
    assert drift and drift[0]["tvd"] >= 0.2
    assert "quantiles" in drift[0] and "baseline_quantiles" in drift[0]
    bundles = [d for d in pm.iterdir() if "score_drift" in d.name]
    assert bundles, list(pm.iterdir())
    v = validate_bundle(str(bundles[0]))
    assert v["ok"], v
    summary = json.loads((bundles[0] / "summary.json").read_text())
    assert summary["reason"] == "score_drift"
    health = summary["health"]
    assert any(g["score"]["drifting"] for g in health["groups"])
    assert health["fleet"]["verdict"] == "attention"
    # both operator renderers accept the bundle
    for script in ("scripts/postmortem.py", "scripts/health_report.py"):
        p = subprocess.run(
            [sys.executable, script, str(bundles[0])],
            cwd=REPO, env={**os.environ, "RTAP_FORCE_CPU": "1"},
            capture_output=True, text=True, timeout=300)
        assert p.returncode == 0, (script, p.stderr[-2000:])
    p = subprocess.run(
        [sys.executable, "scripts/health_report.py", str(bundles[0])],
        cwd=REPO, env={**os.environ, "RTAP_FORCE_CPU": "1"},
        capture_output=True, text=True, timeout=300)
    assert "DRIFTING" in p.stdout or "attention" in p.stdout


@pytest.mark.quick
def test_serve_cli_health_end_to_end(tmp_path):
    """`serve --health` through the operator command: stats carry the
    health block, the snapshot carries the fleet gauges and the run
    epoch, and the epoch sidecar persists beside the incident stream."""
    alerts = tmp_path / "alerts.jsonl"
    snap_path = tmp_path / "obs.jsonl"
    p = subprocess.run(
        [sys.executable, "-m", "rtap_tpu", "serve",
         "--streams", "a,b", "--group-size", "2",
         "--ticks", "4", "--cadence", "0.05", "--backend", "cpu",
         "--alerts", str(alerts), "--health",
         "--obs-snapshot", str(snap_path)],
        cwd=REPO, env={**os.environ, "RTAP_FORCE_CPU": "1"},
        capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "model-health reducers armed" in p.stderr
    stats = json.loads(p.stdout.strip().splitlines()[-1])
    assert stats["health"]["groups"] == 1
    assert stats["health"]["ticks_folded"] == 4
    from rtap_tpu.obs import read_last_snapshot, summarize_snapshot

    s = summarize_snapshot(read_last_snapshot(str(snap_path)))
    assert s["rtap_obs_run_epoch"] == 1
    assert "rtap_obs_health_pool_occupancy_max" in s
    assert s["rtap_obs_health_fold_seconds"]["count"] >= 4
    assert json.loads(
        (tmp_path / "alerts.jsonl.epoch").read_text())["epoch"] == 1
