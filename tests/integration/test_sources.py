"""Live ingestion adapters (SURVEY.md C18) end-to-end: a real local HTTP
exporter / TCP producer feeding `live_loop` at cadence — the reference's
collector.poll() -> model.run() service loop (§3.3) with actual transports,
not just replay."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroup
from rtap_tpu.service.sources import HttpPollSource, TcpJsonlSource, send_jsonl

G = 4
IDS = [f"node{i}.cpu" for i in range(G)]


@pytest.fixture(scope="module")
def group():
    return StreamGroup(cluster_preset(), IDS, backend="tpu")


class _Exporter(BaseHTTPRequestHandler):
    """Minimal per-node stats endpoint: values wander with each poll."""

    polls = 0

    def do_GET(self):
        _Exporter.polls += 1
        metrics = {sid: 35.0 + 3.0 * np.sin(0.3 * _Exporter.polls + i)
                   for i, sid in enumerate(IDS)}
        del metrics[IDS[-1]]  # one exporter is always missing -> NaN path
        body = json.dumps({"ts": int(time.time()), "metrics": metrics}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # silence request logging
        pass


def test_http_poll_source_live_loop(group, tmp_path):
    server = HTTPServer(("127.0.0.1", 0), _Exporter)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}/metrics"
        src = HttpPollSource(url, IDS, timeout_s=1.0)
        alert_path = tmp_path / "alerts.jsonl"
        stats = live_loop(src, group, n_ticks=12, cadence_s=0.25,
                          alert_path=str(alert_path))
        assert stats["ticks"] == 12
        assert stats["missed_deadlines"] <= 2  # first tick compiles
        assert src.poll_failures == 0
        assert "latency_p50_ms" in stats
        assert stats["scored"] == 12 * G
        # during likelihood probation nothing crosses the alert threshold —
        # no ALERT records land in the JSONL sink (one line PER ALERT,
        # SURVEY.md C20). Watchdog events ("event" key — e.g. the compile
        # tick missing the deadline) may share the stream by design.
        assert stats["alerts"] == 0
        recs = [json.loads(l) for l in alert_path.read_text().splitlines() if l]
        assert [r for r in recs if "event" not in r] == []
        assert _Exporter.polls >= 12
    finally:
        server.shutdown()
        server.server_close()


def test_http_poll_source_survives_dead_endpoint():
    src = HttpPollSource("http://127.0.0.1:9/nothing", IDS, timeout_s=0.2)
    values, ts = src(0)
    assert np.isnan(values).all() and src.poll_failures == 1 and ts > 0


def test_tcp_jsonl_source_live_loop(group):
    with TcpJsonlSource(IDS) as src:
        send_jsonl(src.address, [
            {"id": sid, "value": 30.0 + i, "ts": 1_700_000_000 + i}
            for i, sid in enumerate(IDS)
        ])
        send_jsonl(src.address, [{"id": "unknown.metric", "value": 1.0},
                                 {"id": IDS[0]}])  # bad record: no value
        # each poll DRAINS the buffer, so accumulate across polls until all
        # producers' pushes have landed
        combined = np.full(G, np.nan, np.float32)
        deadline = time.time() + 2.0
        while time.time() < deadline:
            values, ts = src(0)
            combined = np.where(np.isnan(combined), values, combined)
            if not np.isnan(combined).any():
                break
            time.sleep(0.02)
        assert not np.isnan(combined).any(), combined
        np.testing.assert_allclose(combined, 30.0 + np.arange(G))
        assert ts == 1_700_000_000 + G - 1
        # the second connection's handler thread updates the error counters
        # asynchronously — wait for BOTH its records to be processed before
        # asserting (the round-3 flake: asserting as soon as the first
        # connection's values landed raced the second handler)
        deadline = time.time() + 2.0
        while time.time() < deadline and src.unknown_ids + src.parse_errors < 2:
            time.sleep(0.02)
        assert src.unknown_ids == 1 and src.parse_errors == 1
        # drained: with no new pushes the next tick reports missing samples
        values, _ = src(1)
        assert np.isnan(values).all()
        stats = live_loop(src, group, n_ticks=5, cadence_s=0.1)
        assert stats["ticks"] == 5 and stats["scored"] == 5 * G


class _DiscoveringExporter(BaseHTTPRequestHandler):
    """Exporter that starts reporting a NEW metric key mid-run — the
    reference's collector discovers a node's metrics from what the
    exporter reports (serve --auto-register over HTTP)."""

    polls = 0

    def do_GET(self):
        _DiscoveringExporter.polls += 1
        # version string and null: present every poll, must NEVER be
        # registered (no usable numeric value) nor poison the fill
        metrics = {"h0.cpu": 35.0, "h0.mem": 52.0,
                   "h0.version": "1.2.3-rc4", "h0.ghost": None}
        if _DiscoveringExporter.polls >= 3:
            metrics["h0.net"] = 12.0  # appears mid-run
        body = json.dumps({"ts": int(time.time()), "metrics": metrics}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def test_http_poll_discovers_new_metric():
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    _DiscoveringExporter.polls = 0
    server = HTTPServer(("127.0.0.1", 0), _DiscoveringExporter)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}/metrics"
        src = HttpPollSource(url, ["h0.cpu", "h0.mem"], timeout_s=1.0,
                             track_unknown=True)
        reg = StreamGroupRegistry(cluster_preset(), group_size=2,
                                  backend="tpu")
        for sid in ("h0.cpu", "h0.mem"):
            reg.add_stream(sid)
        reg.finalize(reserve=2)
        stats = live_loop(src, reg, n_ticks=8, cadence_s=0.05,
                          auto_register=True)
    finally:
        server.shutdown()
        server.server_close()
    assert stats["auto_registered"] == 1
    assert "h0.net" in reg
    assert "h0.version" not in reg and "h0.ghost" not in reg
    # the discovered stream scored from the tick after registration,
    # and the string/null metrics never broke the numeric fills
    assert stats["scored"] > 2 * 8
    assert stats.get("poll_failures", 0) == 0


def test_ingest_obs_counters_sum_across_source_instances():
    """The rtap_obs_ingest_* registry counters outlive any one source, so
    two TcpJsonlSource instances over a process lifetime (reconnect, or
    successive serves in one process) must SUM into them — a replacement
    source's from-zero tally must not be masked by its predecessor's total
    (a raise-to-total sync would make the global counter max, not sum)."""
    from rtap_tpu.obs import get_registry

    counter = get_registry().counter("rtap_obs_ingest_parse_errors_total")
    before = counter.value
    for _ in range(2):
        src = TcpJsonlSource(IDS, port=0).start()
        try:
            send_jsonl(src.address, [{"id": IDS[0]}])  # bad record: no value
            deadline = time.time() + 5.0
            while time.time() < deadline and src.parse_errors < 1:
                time.sleep(0.02)
            assert src.parse_errors == 1
            src(0)  # the per-tick snapshot performs the delta sync
        finally:
            src.close()
    assert counter.value - before == 2
