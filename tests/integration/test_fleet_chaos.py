"""ISSUE 20 acceptance: the control plane + the fleet-wide chaos drill.

(Named to sort after test_cli/test_failover so the tier-1 dot-count
window is untouched — the drill smoke pays real process restarts and is
marked slow; the epoch-recovery and usage-error pins are cheap and run
in tier 1.)

1. the kill-9 epoch pin — a control plane restarted from its
   write-ahead journal can NEVER grant an epoch <= one it already
   granted, including with a torn garbage tail on the journal;
2. the control CLI flag-consistency gates (usage errors before backend
   init, exit 2 + message — the same contract as every serve flag);
3. the fleet chaos drill smoke — ``scripts/fleet_chaos.py`` at tiny
   config: 2 leader SIGKILLs + 1 standby SIGKILL + 1 control-plane
   SIGKILL + 1 SIGSTOP fence round + 1 rolling drain, verdict through
   the fleet plane vs journal/lease ground truth.
"""

import json
import os
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.quick

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _env():
    env = {**os.environ, "RTAP_FORCE_CPU": "1"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


# ------------------------------------------------- epoch recovery pin --
def test_kill9_control_plane_never_regrants_an_epoch(tmp_path):
    """The acceptance regression: grants are journaled write-ahead
    (fsync before the reply), so a plane that dies WITHOUT any orderly
    shutdown and restarts from the same journal dir must floor its next
    grant STRICTLY ABOVE every epoch it ever handed out — a re-granted
    epoch would invert the fence for a zombie holding the original."""
    from rtap_tpu.fleet.control import ControlLease, ControlPlane
    from rtap_tpu.obs.metrics import TelemetryRegistry

    jdir = str(tmp_path / "ctrl")
    timeout_s = 0.2
    plane = ControlPlane(jdir, port=0, lease_timeout_s=timeout_s,
                         registry=TelemetryRegistry()).start()
    addr = plane.address

    a = ControlLease(addr, "A", shard=5, timeout_s=timeout_s,
                     registry=TelemetryRegistry())
    assert a.try_acquire() and a.epoch == 1
    a.release()
    b = ControlLease(addr, "B", shard=5, timeout_s=timeout_s,
                     registry=TelemetryRegistry())
    assert b.try_acquire() and b.epoch == 2

    # kill-9 semantics: no release, no orderly flush — the socket just
    # goes away with B's lease live in the table
    plane.close()

    # a torn tail (the plane died mid-append) must not poison recovery
    with open(os.path.join(jdir, "control.journal"), "ab") as f:
        f.write(b"\x13\x37torn-garbage")

    plane2 = ControlPlane(jdir, port=0, lease_timeout_s=timeout_s,
                          registry=TelemetryRegistry()).start()
    try:
        assert plane2.recovered_shards == 1
        # boot grace: a takeover straight after restart is DENIED until
        # one lease timeout has passed (the live holder gets a chance
        # to re-stamp before anyone steals)
        c = ControlLease(plane2.address, "C", shard=5,
                         timeout_s=timeout_s,
                         registry=TelemetryRegistry())
        assert not c.try_acquire()
        deadline = time.monotonic() + 20 * timeout_s
        while time.monotonic() < deadline and not c.try_acquire():
            time.sleep(timeout_s / 2)
        # THE invariant: strictly above every epoch ever granted,
        # even though the grant table itself died with the process
        assert c.epoch == 3, \
            f"restarted plane granted epoch {c.epoch}, expected 3"
    finally:
        plane2.close()


def test_control_journal_reader_reports_grants(tmp_path):
    """read_control_journal is the soak's ground truth: grants land in
    order with their epochs, and release/drain marks are recorded."""
    from rtap_tpu.fleet.control import (
        ControlLease,
        ControlPlane,
        control_drain,
        read_control_journal,
    )
    from rtap_tpu.obs.metrics import TelemetryRegistry

    jdir = str(tmp_path / "ctrl")
    plane = ControlPlane(jdir, port=0, lease_timeout_s=0.5,
                         registry=TelemetryRegistry()).start()
    try:
        a = ControlLease(plane.address, "A", shard=0, timeout_s=0.5,
                         registry=TelemetryRegistry())
        assert a.try_acquire()
        assert control_drain(plane.address, 0)
        a.release()
    finally:
        plane.close()
    kinds = [(r["kind"], r.get("epoch")) for r in
             read_control_journal(jdir)]
    assert kinds == [("grant", 1), ("drain", None), ("release", None)]


# ----------------------------------------------------- CLI usage gates --
def _cli(*args):
    return subprocess.run([sys.executable, "-m", "rtap_tpu", *args],
                          cwd=REPO, env=_env(), capture_output=True,
                          text=True, timeout=120)


def test_serve_control_flag_usage_errors(tmp_path):
    """Every --control-* gate fires BEFORE backend init (exit 2 +
    message), the same contract as the --fleet-* flags (ISSUE 19)."""
    p = _cli("serve", "--streams", "a", "--control-listen", "0")
    assert p.returncode == 2 and "--control-journal" in p.stderr
    p = _cli("serve", "--streams", "a",
             "--control-journal", str(tmp_path / "j"))
    assert p.returncode == 2 and "--control-listen" in p.stderr
    p = _cli("serve", "--control-only")
    assert p.returncode == 2 and "--control-listen" in p.stderr
    # --streams stays mandatory for every DATA-plane serve
    p = _cli("serve")
    assert p.returncode == 2 and "--streams is required" in p.stderr
    p = _cli("serve", "--streams", "a", "--control-join", "nocolon")
    assert p.returncode == 2 and "bad --control-join" in p.stderr
    p = _cli("serve", "--streams", "a", "--control-join", "host:99999")
    assert p.returncode == 2 and "bad --control-join" in p.stderr
    # one lease authority per process
    p = _cli("serve", "--streams", "a", "--control-join", ":9001",
             "--lease-file", str(tmp_path / "lease"))
    assert p.returncode == 2 and "exclusive" in p.stderr
    p = _cli("serve", "--streams", "a", "--control-grace", "5")
    assert p.returncode == 2 and "--control-join" in p.stderr
    p = _cli("serve", "--streams", "a", "--control-join", ":9001",
             "--control-grace", "0")
    assert p.returncode == 2 and "must be > 0" in p.stderr
    p = _cli("serve", "--streams", "a", "--shard", "-1")
    assert p.returncode == 2 and "--shard" in p.stderr


# ------------------------------------------------------- drill smoke --
@pytest.mark.slow
def test_fleet_chaos_drill_smoke(tmp_path):
    """The in-tree acceptance smoke at tiny config; the drill's exit
    code IS the verdict (5 = an availability/exactness bar failed)."""
    out = str(tmp_path / "report.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_chaos.py"),
         "--seed", "3", "--ticks", "120", "--cadence", "0.1",
         "--streams", "4", "--group-size", "2",
         "--workdir", str(tmp_path / "w"), "--out", out],
        env=_env(), capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, \
        f"fleet chaos failed rc={proc.returncode}\n{proc.stderr[-4000:]}"
    report = json.load(open(out))
    assert report["verified"], report["failures"]
    assert len(report["leader_kills"]) == 2
    assert report["standby_kill"] is not None
    assert report["control_outage"]["leaders_survived"]
    assert report["fence_round"]["rc"] == 7
    assert report["drain_round"]["rc"] == 0
    for s in report["shards_verdict"]:
        assert s["duplicated"] == 0 and s["lost"] == 0 and s["extra"] == 0
        assert s["alert_ids"] > 0 and s["state_leaves_compared"] > 0
    for eps in report["control_journal"]["grants_per_shard"].values():
        assert eps == sorted(set(eps)), eps
    assert report["degraded_ticks_stats"] > 0
