"""Benchmark config 4 (SURVEY.md §6): multivariate per-node fused-RDSE model.

One HTM model per node fuses cpu/mem/net into a single SDR
(`node_preset(n_metrics=3)`); the synthetic generator injects NODE-level
faults — some hitting all metrics at once (saturation shape), some exactly
one metric. The fused model must flag both shapes: a single-metric fault
perturbs that field's third of the SDR, which is enough to break the learned
joint pattern.
"""

import numpy as np
import pytest

from rtap_tpu.config import node_preset
from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_node
from rtap_tpu.models.htm_model import HTMModel

LENGTH = 1400
# injections start at tick 700: probation ends at 400 (cluster-preset
# likelihood), leaving >= 300 ticks of post-probation joint-pattern learning
# before the first fault — the same maturation the fault eval's floors assume
INJECT_FRAC = 0.5
# streaming-mode likelihood has ~3 s median detection latency (SCALING.md
# likelihood-mode table); scan windows with that allowance, NAB-style
LATENCY_TICKS = 15
SEED = 4


def _gen(seed=SEED):
    cfg = node_preset(3)
    cfg.likelihood.safe_inject_frac(LENGTH)  # raises if LENGTH can't be evaluated
    return generate_node(
        "node00042",
        SyntheticStreamConfig(
            length=LENGTH, cadence_s=1.0, n_anomalies=3,
            kinds=("spike", "level_shift", "dropout"), anomaly_magnitude=6.0,
            noise_phi=0.97, noise_scale=0.5, inject_after_frac=INJECT_FRAC,
        ),
        seed=seed,
    )


def test_generate_node_shape_and_determinism():
    node = _gen()
    T, F = node.values.shape
    assert (T, F) == (LENGTH, 3) and node.metrics == ("cpu", "mem", "net")
    assert len(node.windows) == len(node.events) == len(node.event_metrics) == 3
    for touched in node.event_metrics:
        assert set(touched) <= set(node.metrics) and len(touched) in (1, 3)
    again = _gen()
    np.testing.assert_array_equal(node.values, again.values)
    assert node.windows == again.windows

    # with 0.5 coupling and enough draws, both shapes appear across seeds
    shapes = set()
    for s in range(6):
        shapes |= {len(t) for t in _gen(seed=s).event_metrics}
    assert shapes == {1, 3}


def test_fused_model_detects_node_faults():
    """Every injected node fault is alertable: log-likelihood inside the
    window (+ measured latency) clears the fault eval's F1-optimal operating
    range (thresholds land in ~[0.20, 0.66) — eval/fault_eval.py sweep), and
    the windows stand out from a clean background (steady-state raw p50 is
    exactly 0 — the model fully learns the joint diurnal pattern)."""
    node = _gen()
    model = HTMModel(node_preset(3), seed=1, backend="cpu")
    raw = np.empty(LENGTH)
    loglik = np.empty(LENGTH)
    for i in range(LENGTH):
        r = model.run(int(node.timestamps[i]), node.values[i])
        raw[i], loglik[i] = r.raw_score, r.log_likelihood

    in_win = np.zeros(LENGTH, bool)
    for a, b in node.windows:
        in_win |= (node.timestamps >= a) & (node.timestamps <= b + LATENCY_TICKS)
    post = slice(int(0.45 * LENGTH), None)  # past probation + settling

    # the joint pattern is learned: quiet background (measured p50 = 0.0,
    # p99 ~ 0.3 on this seed; bars at achieved-plus-margin)
    assert np.median(raw[post][~in_win[post]]) <= 0.05
    # every fault produces an alertable response (measured mins on this
    # seed: 0.215 for the weakest — a 2-tick spike smeared by the 10-tick
    # likelihood averaging window)
    for (a, b), touched in zip(node.windows, node.event_metrics):
        w = (node.timestamps >= a) & (node.timestamps <= b + LATENCY_TICKS)
        assert loglik[w].max() > 0.15, (
            f"no likelihood response in window {(a, b)} (metrics {touched}); "
            f"max {loglik[w].max():.3f}"
        )
    background = np.median(loglik[post][~in_win[post]])
    assert loglik[in_win].max() > background + 0.15


def test_single_metric_fault_response_is_diluted_but_present():
    """The documented trade-off of field fusion: a fault in ONE of F fields
    perturbs ~1/F of the SDR, so the fused model's raw response is diluted
    to roughly burst/F (vs ~1.0 for the same fault on a per-metric model —
    the fault eval's measured regime). Deployments wanting full per-metric
    sensitivity use one stream per node-metric (generate_cluster, the
    reference's default shape); the fused node model trades that for 3x
    fewer streams and coupled-fault context. This test pins the diluted
    response: visible above the learned-quiet background, well short of a
    full burst."""
    # controlled injection: a clean node plus a deterministic +6-sigma bump
    # on mem only (mem's tight 55 +- 10 range makes an upward bump truly
    # out-of-distribution; the generator's own sign/duration lottery can
    # legitimately produce in-distribution faults, which is not what this
    # property test is about)
    node = generate_node(
        "node00007",
        SyntheticStreamConfig(
            length=LENGTH, cadence_s=1.0, n_anomalies=0,
            noise_phi=0.97, noise_scale=0.5,
        ),
        seed=11,
    )
    mem = list(node.metrics).index("mem")
    S, DUR = 900, 6
    node.values[S : S + DUR, mem] += 6.0 * 0.75  # 6 x (mem sigma 1.5 x 0.5)

    model = HTMModel(node_preset(3), seed=1, backend="cpu")
    raw = np.empty(LENGTH)
    for i in range(LENGTH):
        raw[i] = model.run(int(node.timestamps[i]), node.values[i]).raw_score

    post = slice(int(0.45 * LENGTH), None)
    in_win = np.zeros(LENGTH, bool)
    in_win[S : S + DUR + LATENCY_TICKS] = True
    quiet = raw[post][~in_win[post]]
    # background learned to near-silence...
    assert np.percentile(quiet, 99) <= 0.15, np.percentile(quiet, 99)
    # ...and the one-of-three-fields fault lifts raw clearly above it while
    # staying well short of a full burst — the ~1/F dilution signature
    resp = raw[S : S + DUR + LATENCY_TICKS].max()
    assert 0.15 <= resp <= 0.9, f"expected diluted response, got {resp:.2f}"


@pytest.mark.parametrize("n_fields", [2, 3])
def test_node_preset_device_parity(n_fields):
    """The fused multivariate step is bit-exact oracle-vs-device on the CPU
    test backend (the same guarantee every other config enjoys)."""
    cfg = node_preset(n_fields)
    node = _gen()
    cpu = HTMModel(cfg, seed=2, backend="cpu")
    dev = HTMModel(cfg, seed=2, backend="tpu")
    for i in range(0, 160):
        v = node.values[i, :n_fields]
        r1 = cpu.run(int(node.timestamps[i]), v).raw_score
        r2 = dev.run(int(node.timestamps[i]), v).raw_score
        assert r1 == pytest.approx(r2, abs=0.0), f"step {i}"
