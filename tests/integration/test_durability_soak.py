"""ISSUE 5 acceptance: crash-consistent durability, end to end.

Three drills against REAL process deaths (never mocks):

1. the 2-kill crash soak smoke — ``scripts/crash_soak.py`` SIGKILLs a
   journaled+checkpointed serve child twice at seeded journal-observed
   ticks under the real Supervisor, and its own verdict machinery proves
   final state bit-identical to the fault-free run with the alert stream
   exactly-once (zero duplicated / zero lost ``alert_id``s);
2. the supervised chaos soak — a seeded ``proc_exit`` fault (abrupt
   ``os._exit`` at a tick boundary) plus in-process faults, restarted by
   the Supervisor, journal recovery verified on the incident stream;
3. the checkpoint-save-residue x journal interplay — a child killed
   MID-CHECKPOINT (the state tree landed in the temp sibling, meta.json
   never did) resumes from the rolled-back previous checkpoint with a
   LONGER journal replay, still bit-identical and exactly-once.

Tiny configs + CPU-oracle backend keep each drill in seconds; quick tier.
"""

import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.quick

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _env():
    env = {**os.environ, "RTAP_FORCE_CPU": "1"}
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU child must not dial a tunnel
    return env


def test_crash_soak_two_kills_is_exactly_once(tmp_path):
    """The in-tree acceptance smoke: K=2 SIGKILLs; the soak's exit code
    IS the verdict (5 = durability violated)."""
    out = str(tmp_path / "report.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "crash_soak.py"),
         "--seed", "11", "--kills", "2", "--streams", "6",
         "--group-size", "3", "--ticks", "72", "--cadence", "0.005",
         "--checkpoint-every", "7", "--backend", "cpu",
         "--workdir", str(tmp_path / "w"), "--out", out],
        env=_env(), capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, \
        f"crash soak failed rc={proc.returncode}\n{proc.stderr[-3000:]}"
    report = json.load(open(out))
    assert report["verified"], report["failures"]
    assert report["deaths"] == 2
    assert report["kill_signals"] == [9, 9]
    assert report["duplicated"] == 0 and report["lost"] == 0
    assert report["alert_ids"] > 0
    assert report["state_leaves_compared"] > 0
    assert report["total_ticks_completed"] == 72
    # at least the final (completing) child replayed journal ticks
    assert any(c["replayed_ticks"] > 0 for c in report["catch_up"])


def test_chaos_soak_supervised_proc_exit(tmp_path):
    """Satellite: ChaosSpec's proc_exit kind under chaos_soak --supervise
    — the seeded abrupt death fires exactly once across restarts, the
    run completes its total budget, and journal recovery ran."""
    out = str(tmp_path / "report.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--supervise", "--seed", "4", "--kills", "1", "--streams", "6",
         "--group-size", "3", "--ticks", "48", "--cadence", "0.005",
         "--checkpoint-every", "8", "--backend", "cpu", "--rate", "0.06",
         "--workdir", str(tmp_path / "w"), "--out", out],
        env=_env(), capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, \
        f"supervised chaos soak failed rc={proc.returncode}\n" \
        f"{proc.stderr[-3000:]}"
    report = json.load(open(out))
    assert report["verified"], report["failures"]
    assert report["deaths"] == 1
    assert report["ticks_completed"] == 48
    assert report["journal_replay_events"] >= 1
    assert report["duplicated"] == 0


# ---- drill 3: kill DURING a checkpoint round -------------------------

N_STREAMS = 4
GROUP_SIZE = 2
TOTAL = 40
CK_EVERY = 6
SEED = 5

_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
from rtap_tpu.utils.platform import maybe_force_cpu
maybe_force_cpu()
import numpy as np
from rtap_tpu.config import cluster_preset
from rtap_tpu.resilience import TickJournal
from rtap_tpu.service import checkpoint
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroupRegistry

# die during the SECOND periodic checkpoint round, group0: the state
# tree has landed in the temp sibling but meta.json (the completeness
# marker) never will — then the process dies mid-save. On disk: the
# previous (tick-6) checkpoint intact + an incomplete .tmp residue.
calls = [0]
_orig = checkpoint.save_group
def dying_save(grp, path, **kw):
    calls[0] += 1
    if calls[0] == 3:
        import uuid
        from pathlib import Path
        p = Path(path).absolute()
        tmp = p.parent / (".{{}}.tmp-{{}}".format(p.name, uuid.uuid4().hex[:8]))
        (tmp / "state").mkdir(parents=True)
        os._exit(9)  # no atexit, no flush: a genuine crash
    return _orig(grp, path, **kw)
checkpoint.save_group = dying_save

def source(k):
    rng = np.random.Generator(np.random.Philox(key=({seed}, k)))
    return (30 + 5 * rng.random({n})).astype(np.float32), 1_700_000_000 + k

reg = StreamGroupRegistry(cluster_preset(), group_size={gs}, backend="cpu",
                          threshold=-1e9, debounce=1)
for i in range({n}):
    reg.add_stream("s%d" % i)
reg.finalize()
j = TickJournal({jdir!r})
live_loop(source, reg, n_ticks={total}, cadence_s=0.0, alert_path={alerts!r},
          checkpoint_dir={ckdir!r}, checkpoint_every={ck}, journal=j)
raise SystemExit("unreachable: the dying save must fire")
"""


def _mkreg():
    from rtap_tpu.config import cluster_preset
    from rtap_tpu.service.registry import StreamGroupRegistry

    reg = StreamGroupRegistry(cluster_preset(), group_size=GROUP_SIZE,
                              backend="cpu", threshold=-1e9, debounce=1)
    for i in range(N_STREAMS):
        reg.add_stream(f"s{i}")
    reg.finalize()
    return reg


def _feed(base=0):
    def source(k):
        g = base + k
        rng = np.random.Generator(np.random.Philox(key=(SEED, g)))
        return (30 + 5 * rng.random(N_STREAMS)).astype(np.float32), \
            1_700_000_000 + g
    return source


def _group_fingerprint(grp):
    out = {"ticks": grp.ticks, "alert_run": np.asarray(grp._alert_run)}
    for g, st in enumerate(grp._states):
        for k, v in st.items():
            out[f"s{g}/{k}"] = np.asarray(v)
    for k, v in grp.likelihood.state_dict().items():
        out[f"lik/{k}"] = np.asarray(v)
    return out


def _alert_records(path):
    recs = {}
    for line in open(path):
        if line.startswith('{"event"'):
            continue
        d = json.loads(line)
        assert d["alert_id"] not in recs, f"duplicate {d['alert_id']}"
        recs[d["alert_id"]] = d
    return recs


def test_kill_during_checkpoint_round_resumes_from_rolled_back(tmp_path):
    from rtap_tpu.resilience import TickJournal
    from rtap_tpu.service.loop import live_loop

    jdir = str(tmp_path / "journal")
    ckdir = str(tmp_path / "ck")
    alerts = str(tmp_path / "alerts.jsonl")

    # 1. the doomed run, in its own process — killed mid-save
    child = _CHILD.format(repo=REPO, seed=SEED, n=N_STREAMS, gs=GROUP_SIZE,
                          total=TOTAL, ck=CK_EVERY, jdir=jdir,
                          alerts=alerts, ckdir=ckdir)
    proc = subprocess.run([sys.executable, "-c", child], env=_env(),
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 9, \
        f"dying save did not fire: rc={proc.returncode}\n" \
        f"{proc.stderr[-2000:]}"
    # the rolled-back state: both groups' checkpoints at the FIRST round
    meta = json.load(open(os.path.join(ckdir, "group0000", "meta.json")))
    assert meta["ticks"] == CK_EVERY
    assert meta["journal_tick"] == CK_EVERY  # global == group tick here
    assert "alerts_offset" in meta
    residue = glob.glob(os.path.join(ckdir, ".group0000.tmp-*"))
    assert residue, "the interrupted save left no temp-sibling residue"

    # 2. resume in-process: rolled-back checkpoint + LONGER journal replay
    j = TickJournal(jdir)
    base = j.next_tick
    assert base == 2 * CK_EVERY  # the killing round's ticks are journaled
    reg = _mkreg()
    stats = live_loop(_feed(base), reg, n_ticks=TOTAL - base, cadence_s=0.0,
                      alert_path=alerts, checkpoint_dir=ckdir,
                      checkpoint_every=CK_EVERY, journal=j)
    j.close()
    # the replay spans checkpoint tick 6 .. journal tick 11 — the whole
    # post-rollback window, not just the save round
    assert stats["journal"]["replayed_ticks"] == CK_EVERY
    # every replayed alert was already delivered by the dead run
    # (flush-per-batch): all suppressed, none duplicated
    assert stats["journal"]["suppressed_alerts"] == CK_EVERY * N_STREAMS

    # 3. bit-identical to an uninterrupted run over the same feed
    ref_alerts = str(tmp_path / "ref_alerts.jsonl")
    ref = _mkreg()
    live_loop(_feed(0), ref, n_ticks=TOTAL, cadence_s=0.0,
              alert_path=ref_alerts)
    for grp, rgrp in zip(reg.groups, ref.groups):
        got, want = _group_fingerprint(grp), _group_fingerprint(rgrp)
        assert sorted(got) == sorted(want)
        for k in want:
            np.testing.assert_array_equal(
                np.asarray(got[k]), np.asarray(want[k]), err_msg=k)
    got_recs = _alert_records(alerts)
    want_recs = _alert_records(ref_alerts)
    assert got_recs == want_recs  # exactly-once AND content-identical

    # 4. the incomplete residue was swept by the resume's first good save
    assert not glob.glob(os.path.join(ckdir, ".group0000.tmp-*"))
