"""Detection-latency observability through the real serve stack (ISSUE 11).

Acceptance tests: (1) serving with latency tracking + SLOs armed is
BYTE/BIT-EXACT against serving without them — final model state and the
alert RECORDS are identical (the tracker is pure observation; only
``slo_*`` event lines may additionally appear), and flags-off equals
flagless trivially; (2) ``GET /latency`` / ``GET /slo`` serve the
tracker snapshots and ``GET /healthz`` honors the 200/503 liveness
contract; (3) a seeded burn raises ``slo_burn`` onto the alert stream
and auto-dumps a postmortem bundle whose summary embeds the waterfall;
(4) the serve CLI flag-validation sweep — malformed SLO specs and
knobs without their prerequisites are instant usage errors.
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from rtap_tpu.config import scaled_cluster_preset
from rtap_tpu.obs import (
    ExpositionServer,
    FlightRecorder,
    LatencyTracker,
    SloTracker,
    TelemetryRegistry,
    parse_slo,
    validate_bundle,
)
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroupRegistry

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
ENV = {**os.environ, "RTAP_FORCE_CPU": "1"}

CFG = scaled_cluster_preset(32)
N_STREAMS = 6
GROUP_SIZE = 3
N_TICKS = 8


def _registry():
    reg = StreamGroupRegistry(CFG, group_size=GROUP_SIZE, backend="tpu",
                              threshold=0.0, debounce=1)
    for i in range(N_STREAMS):
        reg.add_stream(f"s{i}")
    reg.finalize()
    return reg


def _feed(k):
    rng = np.random.Generator(np.random.Philox(key=(73, k)))
    return (30 + 5 * rng.random(N_STREAMS)).astype(np.float32), \
        1_700_000_000 + k


def _trackers(burn: bool = False):
    """A latency tracker + an SLO pair; ``burn=True`` declares a tick
    SLO no real tick can meet (1 ms@p99) with tiny burn windows, so the
    seeded run burns deterministically."""
    reg = TelemetryRegistry()
    lat = LatencyTracker(window_ticks=4, registry=reg)
    spec = "tick=1ms@p99" if burn else "tick=30s@p99"
    slo = SloTracker([parse_slo(spec)], fast_window=3, slow_window=6,
                     registry=reg, quantile_source=lat.quantile)
    return lat, slo


def _split_lines(path):
    alerts, events = [], []
    with open(path) as f:
        for ln in f.read().splitlines():
            if not ln:
                continue
            (events if ln.startswith('{"event"') else alerts).append(ln)
    return alerts, events


@pytest.mark.quick
def test_latency_on_vs_off_byte_exact_state_and_alert_records(tmp_path):
    """The ISSUE 11 neutrality bar (PR 6 health-flag discipline): the
    tracker observes, never perturbs — alert records and final model
    state are identical with the flags on or off."""
    finals = {}
    stats_by_mode = {}
    for mode in (False, True):
        reg = _registry()
        alerts = tmp_path / f"alerts_{mode}.jsonl"
        lat, slo = _trackers() if mode else (None, None)
        stats = live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.005,
                          alert_path=str(alerts), micro_chunk=2,
                          latency=lat, slo=slo)
        assert stats["ticks"] == N_TICKS
        stats_by_mode[mode] = stats
        finals[mode] = [
            {k: np.asarray(v) for k, v in g.state.items()}
            for g in reg.groups
        ]
    for g_off, g_on in zip(finals[False], finals[True]):
        assert sorted(g_off) == sorted(g_on)
        for k in g_off:
            np.testing.assert_array_equal(g_off[k], g_on[k], err_msg=k)
    # threshold 0 + debounce 1: every (stream, tick) alerted — the
    # alert RECORDS must agree byte for byte (events may differ: the
    # armed run may carry slo_* lines, the bare run cannot)
    rec_off, _ = _split_lines(tmp_path / "alerts_False.jsonl")
    rec_on, _ = _split_lines(tmp_path / "alerts_True.jsonl")
    assert rec_off and rec_off == rec_on
    # the armed run's stats carry the latency + SLO artifacts
    on = stats_by_mode[True]
    assert on["latency"]["ticks"] == N_TICKS
    assert on["latency"]["detect"]["count"] == N_TICKS * N_STREAMS
    assert on["latency"]["waterfall"]["tick"] == N_TICKS - 1
    assert on["slo"]["met"] is True
    assert on["slo"]["slos"][0]["samples"] == N_TICKS
    assert "latency" not in stats_by_mode[False]


@pytest.mark.quick
def test_latency_slo_healthz_routes(tmp_path):
    reg = _registry()
    lat, slo = _trackers()
    live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.005,
              alert_path=str(tmp_path / "a.jsonl"), latency=lat, slo=slo)
    with ExpositionServer(latency=lat, slo=slo) as srv:
        host, port = srv.address
        base = f"http://{host}:{port}"
        body = json.loads(urllib.request.urlopen(
            base + "/latency", timeout=10).read())
        assert body["ticks"] == N_TICKS
        assert set(body["stages"]) == {"ingest", "dispatch", "collect",
                                       "emit", "tick", "detect"}
        assert body["stages"]["tick"]["window"]["count"] > 0
        assert body["waterfall"]["ingest_lag_s"] is not None
        sbody = json.loads(urllib.request.urlopen(
            base + "/slo", timeout=10).read())
        assert sbody["met"] is True and len(sbody["slos"]) == 1
        # /healthz against the PROCESS registry the loop wrote into:
        # the last tick just completed -> 200 ok
        hz = urllib.request.urlopen(base + "/healthz", timeout=10)
        assert hz.status == 200
        hbody = json.loads(hz.read())
        assert hbody["ok"] is True
        assert hbody["last_tick_age_s"] < 30.0
    # a server over a fresh registry (no tick ever) answers 503, body
    # intact — the supervision-probe contract (docs/TELEMETRY.md)
    with ExpositionServer(registry=TelemetryRegistry()) as srv:
        host, port = srv.address
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://{host}:{port}/healthz",
                                   timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["ok"] is False
    # unarmed trackers 404 loudly, not 500
    with ExpositionServer(registry=TelemetryRegistry()) as srv:
        host, port = srv.address
        for route in ("/latency", "/slo"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"http://{host}:{port}{route}",
                                       timeout=10)
            assert ei.value.code == 404


@pytest.mark.quick
def test_seeded_burn_emits_event_and_postmortem_with_waterfall(tmp_path):
    """An unmeetable tick SLO burns deterministically: one slo_burn
    event line on the alert stream, a valid postmortem bundle with the
    latency waterfall embedded in its summary."""
    reg = _registry()
    lat, slo = _trackers(burn=True)
    pm_dir = tmp_path / "pm"
    os.makedirs(pm_dir)
    flight = FlightRecorder(n_ticks=32, out_dir=str(pm_dir),
                            registry=TelemetryRegistry())
    alerts = tmp_path / "alerts.jsonl"
    stats = live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.005,
                      alert_path=str(alerts), flight=flight,
                      latency=lat, slo=slo)
    v = stats["slo"]
    assert v["met"] is False
    assert v["slos"][0]["burn_events"] >= 1
    _, events = _split_lines(alerts)
    burns = [json.loads(e) for e in events
             if json.loads(e).get("event") == "slo_burn"]
    assert len(burns) == 1  # edge-triggered: one line per episode
    assert burns[0]["stage"] == "tick"
    bundles = [p for p in flight.bundles if "slo_burn" in p]
    assert len(bundles) == 1
    res = validate_bundle(bundles[0])
    assert res["ok"], res["problems"]
    assert res["reason"] == "slo_burn"
    with open(os.path.join(bundles[0], "summary.json")) as f:
        summary = json.load(f)
    assert summary["latency"]["waterfall"] is not None
    assert summary["latency"]["stages"]["tick"]["total"]["count"] > 0


@pytest.mark.quick
def test_serve_cli_flag_validation_sweep(capsys):
    """The --slo/--latency-* knob family fails fast on usage errors —
    before any backend init or listener (ISSUE 11 satellite). In-process
    main() calls: every case returns 2 from the cheap-check block (or
    the pre-listener spec parse), so no subprocess/backend cost."""
    from rtap_tpu.__main__ import main

    def run(*args):
        rc = main(["serve", "--streams", "a", "--backend", "cpu", *args])
        return rc, capsys.readouterr().err

    rc, err = run("--slo", "detect=2s@p99")
    assert rc == 2 and "add --latency" in err
    rc, err = run("--latency-window", "64")
    assert rc == 2 and "add --latency" in err
    rc, err = run("--slo-fast-window", "30")
    assert rc == 2 and "add --slo" in err
    rc, err = run("--latency", "--latency-window", "0")
    assert rc == 2 and "--latency-window" in err
    for bad in ("detect=2m@p99", "nonsense", "foo=2s@p99",
                "detect=2s@p100"):
        rc, err = run("--latency", "--slo", bad)
        assert rc == 2, (bad, err)
        assert "bad --slo" in err, (bad, err)
    # windows inverted: caught at tracker construction, still rc 2
    rc, err = run("--latency", "--slo", "detect=2s@p99",
                  "--slo-fast-window", "100", "--slo-slow-window", "10")
    assert rc == 2 and "--slo-*-window" in err


def test_serve_cli_latency_end_to_end(tmp_path):
    """Operator surface: serve --latency --slo through the real CLI —
    stats carry the latency block + SLO verdict, stderr announces the
    armed trackers."""
    alerts = tmp_path / "alerts.jsonl"
    ids = "a,b,c"
    p = subprocess.run(
        [sys.executable, "-m", "rtap_tpu", "serve", "--streams", ids,
         "--ticks", "6", "--cadence", "0.05", "--backend", "cpu",
         "--port", "0", "--threshold", "0.0", "--debounce", "1",
         "--alerts", str(alerts),
         "--latency", "--latency-window", "4",
         "--slo", "tick=30s@p99"],
        cwd=REPO, env=ENV, capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "detection-latency tracking armed" in p.stderr
    assert "SLOs armed: tick=30s@p99" in p.stderr
    stats = json.loads(p.stdout.strip().splitlines()[-1])
    assert stats["latency"]["ticks"] == 6
    assert stats["slo"]["met"] is True
