"""Operator CLI (`python -m rtap_tpu`) end-to-end: each subcommand drives
its real pipeline at a tiny size and emits parseable JSON."""

import json
import os

import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
ENV = {**os.environ, "RTAP_FORCE_CPU": "1"}


def run_cli(*args, timeout=600):
    return subprocess.run(
        [sys.executable, "-m", "rtap_tpu", *args],
        cwd=REPO, env=ENV, capture_output=True, text=True, timeout=timeout,
    )


def test_replay_emits_throughput_stats():
    p = run_cli("replay", "--nodes", "2", "--length", "900", "--backend", "cpu")
    assert p.returncode == 0, p.stderr[-2000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["streams"] == 6 and out["ticks"] == 900
    assert out["scored"] == 6 * 900


def test_replay_width_scaled_frozen():
    """--columns selects the width-scaled preset and --freeze runs
    inference-only, through the real CLI (the density + read-only levers
    SCALING.md recommends must be reachable by operators)."""
    p = run_cli("replay", "--nodes", "2", "--length", "100",
                "--columns", "32", "--freeze", "--backend", "cpu")
    assert p.returncode == 0, p.stderr[-2000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["scored"] == 6 * 100


def test_serve_rejects_columns_on_nab_preset():
    p = run_cli("serve", "--streams", "a", "--preset", "nab", "--columns", "32")
    assert p.returncode == 2
    assert "cluster preset only" in p.stderr


def test_serve_rejects_freeze_with_auto_register():
    """A frozen elastic serve is a footgun: lazily claimed models would
    never learn and score garbage forever — rejected instantly (before
    backend init), like the other flag-consistency gates."""
    p = run_cli("serve", "--streams", "a", "--freeze", "--auto-register")
    assert p.returncode == 2
    assert "can never learn" in p.stderr


def test_serve_streams_file_form(tmp_path):
    """--streams @file: fleets beyond a few thousand ids exceed the kernel
    argv limit (observed at the 16k-stream soak), so the file form is the
    at-scale registration path. Missing file = instant usage error."""
    p = run_cli("serve", "--streams", "@" + str(tmp_path / "absent.txt"))
    assert p.returncode == 2
    assert "cannot read stream-id file" in p.stderr


def test_serve_tcp_scores_pushed_records(tmp_path):
    alerts = tmp_path / "alerts.jsonl"
    # register via the @file form — the at-scale path (argv has a ~128 KB
    # single-argument limit): this pins the happy-path file parsing
    # (strip, skip blanks) through the real serve flow
    ids_file = tmp_path / "ids.txt"
    ids_file.write_text("a\n\nb\n")
    proc = subprocess.Popen(
        [sys.executable, "-m", "rtap_tpu", "serve",
         "--streams", "@" + str(ids_file),
         "--ticks", "5", "--cadence", "0.2", "--backend", "cpu", "--port", "0",
         "--alerts", str(alerts)],
        cwd=REPO, env=ENV, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )

    # the listener line tells us the bound port
    port = None
    deadline = time.time() + 120
    lines = []

    def feed():
        nonlocal port
        for line in proc.stderr:
            lines.append(line)
            if "listening for JSONL records on" in line:
                port = int(line.rsplit(":", 1)[1])
                break
        # keep draining so the child never blocks on a full pipe
        for line in proc.stderr:
            lines.append(line)

    t = threading.Thread(target=feed, daemon=True)
    t.start()
    while port is None and time.time() < deadline and proc.poll() is None:
        time.sleep(0.05)
    assert port, (proc.poll(), "".join(lines)[-2000:])

    stop = threading.Event()

    def produce():
        from rtap_tpu.service.sources import send_jsonl

        k = 0
        while not stop.is_set():
            try:
                send_jsonl(("127.0.0.1", port),
                           [{"id": "a", "value": 40 + k}, {"id": "b", "value": 60 - k}])
            except OSError:
                pass
            k += 1
            time.sleep(0.1)

    pt = threading.Thread(target=produce, daemon=True)
    pt.start()
    out, _ = proc.communicate(timeout=300)
    stop.set()
    assert proc.returncode == 0, "".join(lines)[-2000:]
    stats = json.loads(out.strip().splitlines()[-1])
    assert stats["ticks"] == 5 and stats["scored"] == 10
    assert "latency_p50_ms" in stats


def test_serve_rejects_bad_chaos_spec(tmp_path):
    """A malformed --chaos-spec is a usage error caught BEFORE any
    listener or registry exists — no half-started serve to clean up."""
    bad = tmp_path / "chaos.json"
    bad.write_text('{"faults": [{"kind": "meteor_strike", "tick": 0}]}')
    p = run_cli("serve", "--streams", "a", "--backend", "cpu",
                "--chaos-spec", str(bad))
    assert p.returncode == 2
    assert "bad --chaos-spec" in p.stderr


def test_serve_rejects_bad_degrade_params():
    """Invalid --degrade knobs are a usage error (exit 2 + message), not
    a traceback — same contract as every other serve flag."""
    p = run_cli("serve", "--streams", "a", "--backend", "cpu",
                "--degrade", "--degrade-after", "11")
    assert p.returncode == 2
    assert "bad --degrade parameters" in p.stderr


def test_serve_chaos_spec_quarantines_and_survives(tmp_path):
    """serve --chaos-spec end to end: a scripted dispatch exception
    quarantines its group mid-serve; the process exits 0 with the
    quarantine in its stats line and the event on the alert stream."""
    spec = tmp_path / "chaos.json"
    spec.write_text(json.dumps({"seed": 7, "faults": [
        {"kind": "dispatch_exception", "tick": 2, "group": 1},
        {"kind": "source_timeout", "tick": 1},
    ]}))
    alerts = tmp_path / "alerts.jsonl"
    # two single-stream groups; no feeder (the TCP source yields NaN
    # ticks, the documented missing-sample path)
    p = run_cli("serve", "--streams", "a,b", "--group-size", "1",
                "--ticks", "5", "--cadence", "0.05", "--backend", "cpu",
                "--alerts", str(alerts), "--chaos-spec", str(spec))
    assert p.returncode == 0, p.stderr[-2000:]
    assert "chaos spec loaded (2 faults" in p.stderr
    stats = json.loads(p.stdout.strip().splitlines()[-1])
    assert stats["ticks"] == 5
    # group 1 scored ticks 0-1 then quarantined; group 0 never skipped one
    assert stats["scored_by_group"] == [5, 2]
    assert stats["quarantine_log"][0]["group"] == 1
    assert stats["chaos_injected"] == 2
    events = [json.loads(line) for line in alerts.read_text().splitlines()
              if line.startswith('{"event"')]
    assert "group_quarantined" in {e["event"] for e in events}


def test_serve_trace_out_and_postmortem_dir_end_to_end(tmp_path):
    """ISSUE 4 CLI surface: serve --trace-out writes Perfetto-loadable
    Chrome trace JSON on exit, and --postmortem-dir auto-dumps a valid
    bundle when a scripted fault quarantines a group — all through the
    real operator command."""
    spec = tmp_path / "chaos.json"
    spec.write_text(json.dumps({"seed": 7, "faults": [
        {"kind": "dispatch_exception", "tick": 2, "group": 1}]}))
    trace_out = tmp_path / "trace.json"
    pm_dir = tmp_path / "pm"
    p = run_cli("serve", "--streams", "a,b", "--group-size", "1",
                "--ticks", "5", "--cadence", "0.05", "--backend", "cpu",
                "--alerts", str(tmp_path / "alerts.jsonl"),
                "--chaos-spec", str(spec),
                "--trace-out", str(trace_out),
                "--postmortem-dir", str(pm_dir),
                "--alert-attribution")
    assert p.returncode == 0, p.stderr[-2000:]
    stats = json.loads(p.stdout.strip().splitlines()[-1])
    assert stats["postmortem"]["bundles"] >= 1
    # the host timeline landed, schema-valid
    tj = json.loads(trace_out.read_text())
    spans = [e for e in tj["traceEvents"] if e.get("ph") == "X"]
    assert {"tick", "source", "dispatch"} <= {e["name"] for e in spans}
    assert any(e.get("ph") == "i" and e["name"] == "group_quarantined"
               and e["args"]["tick"] == 2 for e in tj["traceEvents"])
    # the bundle validates and names the quarantine
    from rtap_tpu.obs import validate_bundle

    bundles = [d for d in pm_dir.iterdir() if not d.name.startswith(".tmp")]
    assert len(bundles) == stats["postmortem"]["bundles"]
    verdicts = {v["reason"]: v for v in map(validate_bundle, map(str, bundles))}
    assert all(v["ok"] for v in verdicts.values()), verdicts
    q = verdicts["group_quarantined"]  # a miss-burst bundle may ride along
    assert q["tick"] == 2
    q_dir = next(d for d in bundles if "group_quarantined" in d.name)
    # and scripts/postmortem.py renders it with exit 0
    pp = subprocess.run(
        [sys.executable, "scripts/postmortem.py", str(q_dir)],
        cwd=REPO, env=ENV, capture_output=True, text=True, timeout=120)
    assert pp.returncode == 0, pp.stderr[-2000:]
    assert "group_quarantined" in pp.stdout


def test_nab_command_end_to_end(tmp_path):
    """`python -m rtap_tpu nab` — the SURVEY §6 drop-in drill: run the
    committed NAB-layout stand-in corpus (truncated + width-scaled for CPU
    cost) end to end, scores for all three profiles, report JSON written.
    Pointing --corpus at a real NAB checkout is the identical invocation."""
    out = tmp_path / "nab.json"
    p = run_cli("nab", "--rows", "600", "--columns", "64",
                "--subset", "realAWSCloudwatch",
                "--out", str(out), timeout=900)
    assert p.returncode == 0, p.stderr[-2000:]
    scores = json.loads(p.stdout.strip().splitlines()[-1])
    assert set(scores) == {"standard", "reward_low_FP", "reward_low_FN"}
    rep = json.loads(out.read_text())
    assert rep["records"] == 600 * 6  # six realAWSCloudwatch files
    assert rep["files"][0].startswith("realAWSCloudwatch/")
    for prof in scores.values():
        assert -200.0 <= prof["score"] <= 100.0


def test_nab_command_missing_corpus_fails_loudly(tmp_path):
    p = run_cli("nab", "--corpus", str(tmp_path / "nowhere"))
    assert p.returncode == 2
    assert "combined_windows.json" in p.stderr


def test_serve_fleet_flag_usage_errors():
    """The --fleet-* gates fire BEFORE backend init (exit 2 + message),
    the same contract as every other serve flag (ISSUE 19)."""
    p = run_cli("serve", "--streams", "a", "--fleet-join", "nocolon")
    assert p.returncode == 2
    assert "bad --fleet-join" in p.stderr
    p = run_cli("serve", "--streams", "a", "--fleet-join", "host:99999")
    assert p.returncode == 2
    assert "bad --fleet-join" in p.stderr
    # the aggregator's merged views ride the obs server: no --obs-port,
    # no /fleet/* routes to serve them on
    p = run_cli("serve", "--streams", "a", "--fleet-listen", "0")
    assert p.returncode == 2
    assert "--obs-port" in p.stderr
    p = run_cli("serve", "--streams", "a", "--fleet-push-interval", "0.5")
    assert p.returncode == 2
    assert "--fleet-join" in p.stderr
    p = run_cli("serve", "--streams", "a",
                "--fleet-join", ":9999", "--fleet-push-interval", "0")
    assert p.returncode == 2
    assert "must be > 0" in p.stderr
