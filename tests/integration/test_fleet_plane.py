"""Fleet observability plane end-to-end (ISSUE 19, rtap_tpu/fleet/).

In-process members against a real aggregator over real sockets:

- registration (HELLO) + periodic SNAP pushes land in the member table;
- a standby's ``set_role`` surfaces as a ``role_changed`` event — the
  exact sequence failover_soak judges against the lease truth;
- abrupt death (socket gone, no BYE) is marked DOWN by staleness, and
  a same-name re-HELLO is a ``rejoined``; an orderly close is ``left``;
- merged views: counters sum across members, fleet SLO pools window
  counts over merged sketches;
- the ``/fleet/*`` routes ride the obs HTTP server, 404ing with a hint
  when no aggregator is attached.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from rtap_tpu.fleet import (
    FLEET_BYE,
    FLEET_HELLO,
    FLEET_SNAP,
    FleetAggregator,
    FleetPublisher,
    pack_fleet,
)
from rtap_tpu.obs.expo import ExpositionServer
from rtap_tpu.obs.metrics import TelemetryRegistry
from rtap_tpu.obs.slo import tick_slo_pair

pytestmark = pytest.mark.quick


def _wait(cond, timeout_s=8.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def _pub(agg, name, registry, role="leader", **kw):
    return FleetPublisher(("127.0.0.1", agg.port), name, role=role,
                          push_interval_s=0.05, registry=registry, **kw)


def test_members_promotion_and_merged_views():
    agg = FleetAggregator(port=0, sweep_interval_s=0.02)
    agg.start()
    try:
        ra, rb = TelemetryRegistry(), TelemetryRegistry()
        ra.counter("rtap_obs_scored_total", "h").inc(40)
        rb.counter("rtap_obs_scored_total", "h").inc(2)
        lat_a, slo_a = tick_slo_pair(0.05, None)
        lat_b, slo_b = tick_slo_pair(0.05, None)
        rng = np.random.default_rng(0)
        for _ in range(60):  # A fast, B slow: merged p99 must see B
            lat_a.sketches["tick"].observe(0.001)
            lat_b.sketches["tick"].observe(float(rng.uniform(0.2, 0.4)))
        a = _pub(agg, "A", ra, latency=lat_a, slo=slo_a).start()
        b = _pub(agg, "B", rb, role="standby", latency=lat_b,
                 slo=slo_b).start()
        assert agg.wait_members(2)
        a.note_tick(7)
        roster = {m["member"]: m for m in agg.members_view()}
        assert roster["A"]["role"] == "leader"
        assert roster["B"]["role"] == "standby"
        assert roster["A"]["pid"] is not None
        assert _wait(lambda: {m["member"]: m for m in agg.members_view()}
                     ["A"]["tick"] == 7)

        # counters SUM across members; gauges label per member
        fm = agg.fleet_metrics()
        scored = next(c for c in fm["counters"]
                      if c["name"] == "rtap_obs_scored_total")
        assert scored["value"] == 42 and scored["members"] == 2

        # fleet latency/SLO from MERGED sketches: B's slow mode decides
        # the fleet p99 even though A pushed far more samples
        fl = agg.fleet_latency()
        assert fl["stages"]["tick"]["total"]["count"] == 120
        assert fl["stages"]["tick"]["total"]["p99"] >= 0.2

        # promotion: same member, new role -> role_changed with epochs
        b.set_role("leader", lease_epoch=2)
        assert _wait(lambda: any(
            e["event"] == "role_changed" and e["member"] == "B"
            for e in agg.events_view()))
        ev = next(e for e in agg.events_view()
                  if e["event"] == "role_changed")
        assert ev["role"] == "leader" and ev["old_role"] == "standby"
        assert ev["lease_epoch"] == 2

        # orderly close = LEFT (BYE), never DOWN
        b.close()
        assert _wait(lambda: {m["member"]: m["state"]
                              for m in agg.members_view()}["B"] == "left")
        a.close()
    finally:
        agg.close()


def test_staleness_down_then_rejoin():
    """A kill-9'd member sends no BYE: its silence crosses the declared
    staleness horizon -> DOWN; the supervisor's replacement re-HELLOs
    the same name -> rejoined. This is crash_soak's restart evidence."""
    agg = FleetAggregator(port=0, sweep_interval_s=0.02)
    agg.start()
    try:
        def raw_hello(sock):
            sock.sendall(pack_fleet(FLEET_HELLO, {
                "member": "M", "role": "leader", "down_after_s": 0.15,
                "clock": {"unix": time.time()}}))
            sock.sendall(pack_fleet(FLEET_SNAP,
                                    {"member": "M", "seq": 1, "tick": 3}))

        s = socket.create_connection(("127.0.0.1", agg.port), timeout=5)
        raw_hello(s)
        assert agg.wait_members(1)
        s.close()  # abrupt: no BYE — only staleness may declare DOWN
        assert _wait(lambda: {m["member"]: m["state"]
                              for m in agg.members_view()}["M"] == "down")
        assert any(e["event"] == "down" and e["member"] == "M"
                   for e in agg.events_view())
        s2 = socket.create_connection(("127.0.0.1", agg.port), timeout=5)
        raw_hello(s2)
        assert _wait(lambda: any(e["event"] == "rejoined"
                                 and e["member"] == "M"
                                 for e in agg.events_view()))
        s2.close()
    finally:
        agg.close()


def test_supervised_rejoin_and_drain_reason():
    """ISSUE 20 satellites: a rejoin whose restarts_total ADVANCED is
    the supervisor respawning the member (supervised=true, death rc
    attached); an unchanged counter is a cold return (supervised=false);
    and a BYE carrying reason=drain lands in the left event AND the
    roster row — the evidence fleet_report's exit contract reads."""
    agg = FleetAggregator(port=0, sweep_interval_s=0.02)
    agg.start()
    try:
        def hello(extra):
            s = socket.create_connection(("127.0.0.1", agg.port),
                                         timeout=5)
            s.sendall(pack_fleet(FLEET_HELLO, {
                "member": "M2", "role": "leader", "down_after_s": 0.15,
                "clock": {"unix": time.time()}, **extra}))
            return s

        def rejoins():
            return [e for e in agg.events_view()
                    if e["event"] == "rejoined" and e["member"] == "M2"]

        s = hello({"restarts_total": 0})
        assert agg.wait_members(1)
        s.close()  # kill-9: silence, then staleness declares DOWN
        assert _wait(lambda: {m["member"]: m["state"]
                              for m in agg.members_view()}["M2"] == "down")

        # supervisor respawn: counter advanced 0 -> 1, death rc rides
        s2 = hello({"restarts_total": 1, "last_death_rc": -9})
        assert _wait(lambda: len(rejoins()) == 1)
        ev = rejoins()[0]
        assert ev["supervised"] is True
        assert ev["restarts_total"] == 1 and ev["last_death_rc"] == -9
        # roster carries the lineage fields for fleet_report's table
        row = {m["member"]: m for m in agg.members_view()}["M2"]
        assert row["restarts_total"] == 1 and row["last_death_rc"] == -9
        s2.close()
        assert _wait(lambda: {m["member"]: m["state"]
                              for m in agg.members_view()}["M2"] == "down")

        # cold return: same counter -> NOT a supervised recovery
        s3 = hello({"restarts_total": 1})
        assert _wait(lambda: len(rejoins()) == 2)
        assert rejoins()[1]["supervised"] is False

        # orderly drain: reason rides the BYE into event + roster row
        s3.sendall(pack_fleet(FLEET_BYE, {"member": "M2",
                                          "reason": "drain"}))
        assert _wait(lambda: any(
            e["event"] == "left" and e["member"] == "M2"
            and e.get("reason") == "drain" for e in agg.events_view()))
        row = {m["member"]: m for m in agg.members_view()}["M2"]
        assert row["state"] == "left" and row["left_reason"] == "drain"
        s3.close()
    finally:
        agg.close()


def test_fleet_report_drain_and_expect_down_exits(tmp_path):
    """scripts/fleet_report.py exit contract (ISSUE 20 satellite): DOWN
    means an UNPLANNED outage — a drain departure never trips exit 4,
    and --expect-down N tolerates in-flight planned kills."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    def run(members, *extra):
        snap = tmp_path / "snap.json"
        snap.write_text(json.dumps({"members": members}))
        return subprocess.run(
            [sys.executable, os.path.join(repo, "scripts",
                                          "fleet_report.py"),
             "--snapshot", str(snap), *extra],
            cwd=repo, capture_output=True, text=True, timeout=120)

    drained = {"member": "A", "state": "down", "left_reason": "drain"}
    dead = {"member": "B", "state": "down", "left_reason": None}
    assert run([drained]).returncode == 0
    assert run([dead]).returncode == 4
    assert run([dead], "--expect-down", "1").returncode == 0
    assert run([drained, dead], "--expect-down", "1").returncode == 0
    p = run([dead], "--expect-down", "-1")
    assert p.returncode == 2 and "--expect-down" in p.stderr


def test_fleet_routes_on_obs_server():
    agg = FleetAggregator(port=0, sweep_interval_s=0.05)
    agg.start()
    reg = TelemetryRegistry()
    reg.counter("rtap_obs_ticks_total", "h").inc(5)
    pub = _pub(agg, "solo", reg).start()
    try:
        assert agg.wait_members(1)
        with ExpositionServer(registry=reg, fleet=agg) as srv:
            host, port = srv.address
            base = f"http://{host}:{port}"
            members = json.loads(urllib.request.urlopen(
                base + "/fleet/members", timeout=10).read())
            assert members[0]["member"] == "solo"
            snap = json.loads(urllib.request.urlopen(
                base + "/fleet/snapshot", timeout=10).read())
            assert "solo" in snap["snaps"]
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/fleet/nope", timeout=10)
            assert ei.value.code == 404
        # an aggregator-less obs server 404s with the enabling flag
        with ExpositionServer(registry=reg) as srv2:
            host, port = srv2.address
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://{host}:{port}/fleet/members", timeout=10)
            assert ei.value.code == 404
            assert "fleet-listen" in ei.value.reason
    finally:
        pub.close()
        agg.close()
