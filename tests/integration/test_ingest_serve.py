"""Wire-speed binary ingest end-to-end (ISSUE 7): the live_loop
equivalence proof (binary path bit-identical to the JSONL path on the
same row sequence — state AND alert stream), the auto-register NAMES
protocol, journal FRAME-record crash replay, and serve --ingest-port
CLI end-to-end. (File named to sort after test_cli.py — the tier-1
870 s window dies before it, by design; the quick tier runs it.)"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset
from rtap_tpu.ingest import BinaryBatchSource, send_binary
from rtap_tpu.ingest.emit import BinaryFeedConnection
from rtap_tpu.ingest.protocol import data_frame
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroupRegistry
from rtap_tpu.service.sources import TcpJsonlSource, send_jsonl

pytestmark = pytest.mark.quick

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

G = 6
IDS = [f"n{i // 3}.m{i % 3}" for i in range(G)]
TICKS = 8


def _tiny_cfg():
    # the durability-soak idiom: the real preset, tiny G, cpu oracle
    return cluster_preset()


def _registry():
    reg = StreamGroupRegistry(_tiny_cfg(), group_size=3, backend="cpu",
                              threshold=-1e9)  # floor: densest alert file
    for sid in IDS:
        reg.add_stream(sid)
    reg.finalize()
    return reg


def _records(k: int) -> list[dict]:
    rng = np.random.Generator(np.random.Philox(key=(41, k)))
    vals = (30 + 5 * rng.random(G)).astype(np.float32)
    return [{"id": sid, "value": float(v), "ts": 1_700_000_000 + k}
            for sid, v in zip(IDS, vals)]


def _lockstep(src, send):
    """Deterministic feed: push tick k's records, wait until the
    listener applied them, then snapshot — no cadence races, so two
    transports see byte-identical row sequences."""
    consumed = [0]

    def source(k: int):
        recs = _records(k)
        n = send(src.address, recs)
        assert n == G
        consumed[0] += G
        deadline = time.time() + 20
        while time.time() < deadline and src.records_parsed < consumed[0]:
            time.sleep(0.002)
        assert src.records_parsed == consumed[0]
        return src(k)

    source.take_tick_frames = getattr(src, "take_tick_frames", None)
    return source


def _run_loop(transport: str, alert_path: str, journal=None,
              n_ticks: int = TICKS):
    reg = _registry()
    if transport == "jsonl":
        src = TcpJsonlSource(IDS).start()
        send = send_jsonl
    else:
        src = BinaryBatchSource(reg.slot_map()).start()
        send = send_binary
    try:
        wrapper = _lockstep(src, send)
        if wrapper.take_tick_frames is None:
            del wrapper.take_tick_frames
        stats = live_loop(wrapper, reg, n_ticks=n_ticks, cadence_s=0.01,
                          alert_path=alert_path, journal=journal)
    finally:
        src.close()
    return reg, stats


def _alert_lines(path) -> list[bytes]:
    """The alert stream minus watchdog/resilience EVENT lines: events
    carry wall-clock payloads (elapsed_s of a missed tick) that cannot
    be identical across two real-time runs; every scored-alert line
    must be."""
    with open(path, "rb") as f:
        return [ln for ln in f if not ln.startswith(b'{"event"')]


def test_binary_live_loop_bit_identical_to_jsonl(tmp_path):
    """THE acceptance gate: the same row sequence through the binary
    batch path and the per-record JSONL path yields a byte-identical
    alert stream and bit-identical model state."""
    reg_j, stats_j = _run_loop("jsonl", str(tmp_path / "a_jsonl.jsonl"))
    reg_b, stats_b = _run_loop("binary", str(tmp_path / "a_bin.jsonl"))
    assert stats_j["scored"] == stats_b["scored"] == G * TICKS
    aj = _alert_lines(tmp_path / "a_jsonl.jsonl")
    ab = _alert_lines(tmp_path / "a_bin.jsonl")
    assert aj == ab and len(aj) == G * TICKS
    # model state, bit for bit (cpu backend: numpy oracle trees)
    for gj, gb in zip(reg_j.groups, reg_b.groups):
        assert gj._states[0].keys() == gb._states[0].keys()
        for sj, sb in zip(gj._states, gb._states):
            for key in sj:
                assert np.array_equal(np.asarray(sj[key]),
                                      np.asarray(sb[key]),
                                      equal_nan=True), key


def test_journal_frame_replay_matches_uninterrupted(tmp_path):
    """A binary-ingest serve killed mid-run resumes through the
    journal's raw-FRAME records bit-identically: alerts exactly-once,
    final state equal to the uninterrupted run's."""
    from rtap_tpu.resilience.journal import TickJournal

    # reference: 8 uninterrupted ticks
    reg_ref, _ = _run_loop("binary", str(tmp_path / "ref.jsonl"))
    # interrupted: 5 ticks journaled, then a fresh loop over the same
    # journal replays them and runs the remaining 3 (global feed clock)
    jdir = tmp_path / "journal"
    j1 = TickJournal(jdir)
    _run_loop("binary", str(tmp_path / "crash.jsonl"), journal=j1,
              n_ticks=5)
    j1.close()
    j2 = TickJournal(jdir)
    assert j2.recovered_count == 5
    reg2 = _registry()
    src2 = BinaryBatchSource(reg2.slot_map()).start()
    try:
        base = j2.next_tick
        consumed = [0]

        def source(k: int):
            recs = _records(base + k)
            assert send_binary(src2.address, recs) == G
            consumed[0] += G
            deadline = time.time() + 20
            while time.time() < deadline \
                    and src2.records_parsed < consumed[0]:
                time.sleep(0.002)
            return src2(k)

        source.take_tick_frames = src2.take_tick_frames
        stats = live_loop(source, reg2, n_ticks=TICKS - 5, cadence_s=0.01,
                          alert_path=str(tmp_path / "crash.jsonl"),
                          journal=j2)
    finally:
        src2.close()
        j2.close()
    assert stats["journal"]["replayed_ticks"] == 5
    assert stats["journal"]["skipped_rows"] == 0
    ref = _alert_lines(tmp_path / "ref.jsonl")
    crash = _alert_lines(tmp_path / "crash.jsonl")
    assert ref == crash  # exactly-once, content-identical
    for gr, g2 in zip(reg_ref.groups, reg2.groups):
        for sr, s2 in zip(gr._states, g2._states):
            for key in sr:
                assert np.array_equal(np.asarray(sr[key]),
                                      np.asarray(s2[key]),
                                      equal_nan=True), key


def test_auto_register_via_names_frames(tmp_path):
    """The shared membership protocol over binary: NAMES frames announce
    unknown ids, serve-side claims hand back fresh slot codes, and the
    producer's refreshed MAP routes rows to the claimed model."""
    reg = StreamGroupRegistry(_tiny_cfg(), group_size=3, backend="cpu",
                              threshold=-1e9)
    for sid in IDS:
        reg.add_stream(sid)
    reg.finalize(reserve=3)
    src = BinaryBatchSource(reg.slot_map(), track_unknown=True).start()
    try:
        newcomers = ["late.a", "late.b"]
        ticks = {"k": 0}

        def source(k):
            if k == 0:
                with BinaryFeedConnection(src.address) as conn:
                    assert all(s not in conn.code_of for s in newcomers)
                    conn.send_names(newcomers)
                deadline = time.time() + 20
                while time.time() < deadline and src.frames_applied < 2:
                    time.sleep(0.002)
            elif k == 2:
                # membership changed at tick 1's head; the refreshed
                # map must now carry the claimed codes
                recs = [{"id": s, "value": 42.0, "ts": 1_700_000_100}
                        for s in newcomers]
                assert send_binary(src.address, recs) == 2
                deadline = time.time() + 20
                while time.time() < deadline and src.records_parsed < 2:
                    time.sleep(0.002)
            ticks["k"] = k
            return src(k)

        # the loop talks membership to the SOURCE object's protocol
        # surface; a wrapper callable must carry it through
        source.drain_unknown = src.drain_unknown
        source.set_slot_map = src.set_slot_map
        stats = live_loop(source, reg, n_ticks=4, cadence_s=0.01,
                          alert_path=str(tmp_path / "a.jsonl"),
                          auto_register=True)
    finally:
        src.close()
    assert stats["auto_registered"] == 2
    assert all(s in reg for s in newcomers)
    assert src.records_parsed == 2 and src.rows_unknown == 0


def test_serve_cli_ingest_port(tmp_path):
    """serve --ingest-port end-to-end: binary listener line on stderr,
    send_binary feeds it, the stats line carries the ingest surface."""
    import re
    import threading

    ids = ",".join(IDS)
    env = {**os.environ, "RTAP_FORCE_CPU": "1",
           "RTAP_OBS_SNAPSHOT": str(tmp_path / "obs.jsonl")}
    proc = subprocess.Popen(
        [sys.executable, "-m", "rtap_tpu", "serve", "--streams", ids,
         "--ingest-port", "0", "--ingest-quota", "50",
         "--backend", "cpu", "--ticks", "4", "--cadence", "0.2",
         "--group-size", "3", "--threshold", "-1000000000.0",
         "--debounce", "1",
         "--alerts", str(tmp_path / "alerts.jsonl")],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=env)
    stderr_lines: list[str] = []
    drain = threading.Thread(
        target=lambda: stderr_lines.extend(iter(proc.stderr.readline, "")),
        daemon=True)
    drain.start()
    port = None
    deadline = time.time() + 120
    pat = re.compile(r"listening for binary batch frames on \S+?:(\d+)")
    while time.time() < deadline and port is None:
        for line in stderr_lines:
            m = pat.search(line)
            if m:
                port = int(m.group(1))
        if proc.poll() is not None:
            raise AssertionError(
                f"serve died rc={proc.returncode}: {''.join(stderr_lines)}")
        time.sleep(0.05)
    assert port is not None, "".join(stderr_lines)
    pushed = 0
    t_end = time.time() + 10
    while proc.poll() is None and time.time() < t_end:
        pushed += send_binary(("127.0.0.1", port), _records(pushed))
        time.sleep(0.1)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 0, "".join(stderr_lines)
    stats = json.loads(out.strip().splitlines()[-1])
    assert stats["ticks"] == 4
    assert stats["records_parsed"] > 0
    assert stats["frames_applied"] > 0
    assert stats["native_active"] in (True, False)
    assert stats["rows_quota_dropped"] == 0
    assert stats["alerts"] > 0
    assert (tmp_path / "alerts.jsonl").exists()
