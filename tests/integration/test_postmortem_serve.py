"""ISSUE 4 acceptance: a chaos-injected group quarantine during serve
auto-dumps a postmortem bundle whose trace file is valid Chrome
trace-event JSON containing phase spans, per-group child spans, and the
group_quarantined instant at the correct tick — and /trace?last=N over
the obs HTTP server returns the same schema live."""

import json
import urllib.request

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset
from rtap_tpu.obs import (
    ExpositionServer,
    FlightRecorder,
    TraceRecorder,
    get_registry,
    summarize_snapshot,
    validate_bundle,
)
from rtap_tpu.resilience import ChaosEngine, ChaosSpec, Fault
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroupRegistry

G_TOTAL = 6
GROUP_SIZE = 2  # 3 groups: quarantine the middle one
N_TICKS = 12
Q_TICK = 5


def _registry():
    reg = StreamGroupRegistry(cluster_preset(), group_size=GROUP_SIZE,
                              backend="tpu")
    for i in range(G_TOTAL):
        reg.add_stream(f"s{i}")
    reg.finalize()
    return reg


def _feed(k):
    rng = np.random.Generator(np.random.Philox(key=(91, k)))
    return (30 + 5 * rng.random(G_TOTAL)).astype(np.float32), \
        1_700_000_000 + k


def _spans(events):
    return [e for e in events if e.get("ph") == "X"]


def _check_timeline(events):
    """The schema contract shared by the bundle's trace.json and the live
    /trace route: phase spans on the loop track, per-group child spans on
    group tracks, the quarantine instant at its tick."""
    spans = _spans(events)
    names = {e["name"] for e in spans}
    # phase spans (checkpoint/membership only fire when they do work)
    assert {"tick", "source", "dispatch", "collect", "emit"} <= names
    # every span carries its tick correlation id
    assert all(isinstance(e["args"]["tick"], int) for e in spans)
    # per-group child spans land on per-group tracks (tid = group + 1)
    for gi in (0, 2):  # healthy groups dispatched every tick
        child = [e for e in spans
                 if e["args"].get("group") == gi and e["name"] == "dispatch"]
        assert child, f"no per-group dispatch child spans for group {gi}"
        assert all(e["tid"] == gi + 1 for e in child)
    # the quarantine instant, at the tick the fault was injected
    q = [e for e in events
         if e.get("ph") == "i" and e["name"] == "group_quarantined"]
    assert len(q) == 1
    assert q[0]["args"]["tick"] == Q_TICK and q[0]["args"]["group"] == 1


@pytest.mark.quick
def test_chaos_quarantine_autodumps_valid_bundle_and_trace_route(tmp_path):
    before = summarize_snapshot(get_registry().snapshot())
    trace = TraceRecorder(capacity=16384)
    # miss_burst above N_TICKS: the compiling CPU backend misses every
    # sub-ms deadline, and this test wants exactly the quarantine bundle
    flight = FlightRecorder(trace=trace, n_ticks=64,
                            out_dir=str(tmp_path / "pm"),
                            miss_burst=N_TICKS + 1,
                            info={"test": "postmortem_serve"})
    reg = _registry()
    stats = live_loop(
        _feed, reg, n_ticks=N_TICKS, cadence_s=0.01,
        alert_path=str(tmp_path / "alerts.jsonl"),
        chaos=ChaosEngine(ChaosSpec(faults=[
            Fault(kind="dispatch_exception", tick=Q_TICK, group=1)])),
        trace=trace, flight=flight)
    assert stats["ticks"] == N_TICKS
    assert stats["quarantine_log"][0]["tick"] == Q_TICK

    # ---- the bundle auto-dumped, atomically, and validates
    assert stats["postmortem"]["bundles"] == 1
    bundles = [d for d in (tmp_path / "pm").iterdir()
               if not d.name.startswith(".tmp")]
    assert len(bundles) == 1
    assert "group_quarantined" in bundles[0].name
    v = validate_bundle(str(bundles[0]))
    assert v["ok"], v
    assert v["reason"] == "group_quarantined" and v["tick"] == Q_TICK
    assert v["spans"] > 0 and v["events"] > 0

    # ---- the bundle's trace is a loadable timeline with the full schema
    tj = json.load(open(bundles[0] / "trace.json"))
    _check_timeline(tj["traceEvents"])
    # the quarantine event line is in the bundle's ledger too
    ledger = [json.loads(l) for l in
              (bundles[0] / "events.jsonl").read_text().splitlines()]
    assert any(e["event"] == "group_quarantined" and e["tick"] == Q_TICK
               for e in ledger)
    summary = json.load(open(bundles[0] / "summary.json"))
    assert summary["ticks"]["count"] > 0
    assert summary["info"]["test"] == "postmortem_serve"

    # ---- /trace?last=N over the obs HTTP server: same schema, live
    with ExpositionServer(trace=trace, flight=flight) as srv:
        host, port = srv.address
        body = urllib.request.urlopen(
            f"http://{host}:{port}/trace?last={N_TICKS}",
            timeout=10).read()
        http_tj = json.loads(body)
        _check_timeline(http_tj["traceEvents"])
        # windowing works: last=1 keeps only the final tick's records
        small = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/trace?last=1", timeout=10).read())
        ticks = {e["args"]["tick"] for e in _spans(small["traceEvents"])}
        assert ticks == {N_TICKS - 1}
        # on-demand postmortem over HTTP (fresh reason, not throttled)
        pm = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/postmortem", timeout=10).read())
        assert pm["bundle"] is not None
        assert validate_bundle(pm["bundle"])["ok"]

    # ---- the new metrics moved
    after = summarize_snapshot(get_registry().snapshot())
    assert after.get(
        "rtap_obs_postmortem_bundles_total{reason=group_quarantined}", 0) \
        - before.get(
            "rtap_obs_postmortem_bundles_total{reason=group_quarantined}",
            0) == 1
    assert after["rtap_obs_trace_records"] > 0


@pytest.mark.quick
@pytest.mark.quick
def test_live_multivariate_alert_carries_top_fields(tmp_path):
    """Satellite: --alert-attribution end to end on the real loop — a
    known per-field spike in a multivariate serve names that field on
    the alert line."""
    from rtap_tpu.config import node_preset
    from rtap_tpu.service.attribution import AlertAttributor

    cfg = node_preset(3)
    reg = StreamGroupRegistry(cfg, group_size=2, backend="tpu",
                              threshold=-1e9, debounce=1)
    for i in range(2):
        reg.add_stream(f"n{i}")
    reg.finalize()

    def feed(k):
        v = np.full((2, 3), 20.0, np.float32)
        if k >= 3:
            v[0, 2] += 300.0  # net on n0 spikes from tick 3 on
        return v, 1_700_000_000 + k

    stats = live_loop(feed, reg, n_ticks=5, cadence_s=0.01,
                      alert_path=str(tmp_path / "alerts.jsonl"),
                      attributor=AlertAttributor(cfg))
    assert stats["alerts"] > 0
    lines = [json.loads(l) for l in
             (tmp_path / "alerts.jsonl").read_text().splitlines()
             if not l.startswith('{"event"')]
    spiked = [l for l in lines if l["stream"] == "n0" and l["ts"] ==
              1_700_000_003]
    assert spiked and spiked[0]["top_fields"][0]["field"] == 2
