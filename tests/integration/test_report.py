"""C22 report script produces PNGs in CI (SURVEY.md C22 v1 plan)."""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]


def test_report_script_writes_pngs(tmp_path):
    eval_report = {
        "at_best": {"f1": 0.72, "recall": 0.88, "precision": 0.61,
                    "median_latency_s": 1.0},
        "per_kind": {
            "spike": {"recall": 0.82}, "level_shift": {"recall": 0.89},
            "dropout": {"recall": 0.9},
        },
    }
    rep_path = tmp_path / "fault_eval.json"
    rep_path.write_text(json.dumps(eval_report))
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "report.py"),
         "--out-dir", str(tmp_path), "--streams", "2", "--length", "850",
         "--eval-report", str(rep_path)],
        env={"RTAP_FORCE_CPU": "1", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": str(REPO), "HOME": "/root"},
        capture_output=True, text=True, timeout=520,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    overlay = tmp_path / "overlay.png"
    evalpng = tmp_path / "fault_eval.png"
    assert overlay.exists() and overlay.stat().st_size > 20_000, proc.stderr[-500:]
    assert evalpng.exists() and evalpng.stat().st_size > 5_000
    assert overlay.read_bytes()[:8] == b"\x89PNG\r\n\x1a\n"
