"""End-to-end NAB run as integration test (SURVEY.md §4 item 5): detector
over a mini-corpus through the full runner (encode -> SP -> TM -> likelihood
-> threshold sweep -> normalized score). Pass bar: comfortably above what a
naive z-score detector achieves on the same generator (~5/100)."""

import numpy as np

from rtap_tpu.data.nab_corpus import NabFile
from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_stream
from rtap_tpu.nab.runner import run_corpus
from tests.golden.generate_golden import golden_config


def _mini_corpus(n_files=2):
    files = []
    for i in range(n_files):
        s = generate_stream(
            f"int{i}.cpu",
            SyntheticStreamConfig(length=1200, cadence_s=300.0, n_anomalies=2,
                                  anomaly_magnitude=8.0, noise_scale=0.35,
                                  kinds=("spike", "dropout")),
            seed=21,
        )
        files.append(NabFile(f"it/int{i}.csv", s.timestamps, s.values, s.windows))
    return files


def test_nab_end_to_end_beats_naive_baseline():
    res = run_corpus(_mini_corpus(), cfg=golden_config(), backend="cpu")
    thr, score = res.scores["standard"]
    assert 0.0 < thr < 1.0
    # Bars at achieved-minus-margin (round-3 measurement: standard 59.0,
    # reward_low_FN 64.4, reward_low_FP 45.2 on this exact seed/corpus) so a
    # detector-chain regression trips them; a naive z-score detector scores
    # ~5 on this generator.
    assert score > 50.0, f"standard score {score:.1f} too low"
    assert res.scores["reward_low_FN"][1] > 55.0, res.scores
    assert res.scores["reward_low_FP"][1] > 35.0, res.scores
    # scores are finite and per-file outputs cover every row
    for s, ts, _ in res.per_file:
        assert np.isfinite(s).all() and len(s) == len(ts)


def test_batched_corpus_run_matches_per_file():
    """Benchmark config 2's vmapped batch (one device group, per-file
    encoder resolutions as runtime state) must score each file the same as
    the one-detector-per-file path. On the CPU test platform the device
    kernels are bit-exact vs the oracle; the batched likelihood is the
    vectorized twin of the scalar one, so scores agree to float tolerance."""
    from rtap_tpu.nab.runner import detect_file, detect_files_batched

    files = _mini_corpus(2)
    cfg = golden_config()
    per_file = [detect_file(nf, cfg, backend="cpu") for nf in files]
    batched = detect_files_batched(files, cfg)
    for nf, a, b in zip(files, per_file, batched):
        assert a.shape == b.shape
        np.testing.assert_allclose(a, b, atol=1e-9, err_msg=nf.name)


def test_batched_corpus_run_pads_unequal_lengths():
    """Shorter files pad with NaN (missing-sample path) and return scores
    only for their real rows."""
    files = _mini_corpus(2)
    short = files[1]
    files[1] = NabFile(short.name, short.timestamps[:900], short.values[:900], short.windows)
    from rtap_tpu.nab.runner import detect_files_batched

    out = detect_files_batched(files, golden_config())
    assert len(out[0]) == 1200 and len(out[1]) == 900
    assert all(np.isfinite(s).all() for s in out)


def test_detection_scores_spike_inside_windows():
    files = _mini_corpus(1)
    res = run_corpus(files, cfg=golden_config(), backend="cpu",
                     profiles=("standard",))
    scores, ts, windows = res.per_file[0]
    in_win = np.zeros(len(ts), bool)
    for a, b in windows:
        in_win |= (ts >= a) & (ts <= b)
    prob = int(0.15 * len(ts))
    # measured separation on this seed: 0.133 (anomaly-likelihood log scale)
    assert scores[prob:][in_win[prob:]].max() > np.median(scores[prob:]) + 0.10


def test_committed_corpus_artifact_floors():
    """The on-device corpus-scale artifact (reports/nab_standin.json,
    measured on the real chip 2026-08-01: standard 8.25 / reward_low_FN
    19.7 / reward_low_FP 3.41 over 32,256 records) must not silently
    regress when re-harvested. Floors at achieved-minus-margin; the
    stand-in's absolute level is corpus-dependent, not scoreboard-
    comparable (see the artifact's own note)."""
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "..",
                        "reports", "nab_standin.json")
    with open(path) as f:
        rep = json.load(f)
    assert rep["backend"] == "tpu"
    assert rep["records"] == 32256
    assert len(rep["files"]) == 8
    scores = {k: v["score"] for k, v in rep["scores"].items()}
    assert scores["standard"] >= 6.0, scores
    assert scores["reward_low_FN"] >= 15.0, scores
    assert scores["reward_low_FP"] >= 2.0, scores
    for prof, v in rep["scores"].items():
        assert 0.0 <= v["threshold"] <= 1.0, (prof, v)
