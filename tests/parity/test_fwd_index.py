"""RTAP_TM_DENDRITE=forward parity: the forward synapse index must produce
bit-identical dendrite counts (hence scores AND full state) to the full-pool
scan, with the index maintained incrementally through learning — evictions,
alloc-clears, growth, reinforce-death, punish-death (ops/fwd_index.py,
docs/FORWARD_INDEX_DESIGN.md).

The index itself is derived state with a free row layout; its contract is
(a) count parity per step, (b) set-consistency with `presyn` (every synapse
slot appears in exactly its presynaptic cell's row), (c) overflow counted,
never silent. (b) is asserted directly by rebuilding canonically and
comparing membership sets.
"""

import dataclasses

import numpy as np
import pytest

import rtap_tpu.ops.tm_tpu as tm_tpu
from rtap_tpu.models.htm_model import HTMModel
from rtap_tpu.ops.fwd_index import build_fwd_index

from tests.parity.test_e2e_parity import exact_only, make_values, small_cfg


@pytest.fixture
def forward_dendrite():
    tm_tpu.set_dendrite_mode("forward")
    yield
    tm_tpu.set_dendrite_mode(None)


def fwd_cfg(perm_bits: int = 0):
    """small_cfg with a fanout cap high enough that the 2048-cell pool can
    never overflow a row on the test trajectories (tests assert fwd_of == 0;
    measured: the seed-3 300-step run peaks at fanout 129 — a 128 cap
    correctly tripped fwd_of=1 and diverged, which is the overflow contract
    working)."""
    if perm_bits == 0:
        base = small_cfg()
    else:
        from tests.parity.test_quantized_parity import quant_cfg

        base = quant_cfg(perm_bits)
    return dataclasses.replace(base, tm=dataclasses.replace(base.tm, fanout_cap=320))


def test_build_fwd_index_matches_numpy():
    """Canonical build vs a direct numpy construction on random pools."""
    rng = np.random.Generator(np.random.Philox(key=(3, 14)))
    N, F = 64, 8
    pool = 512
    for density in (0.0, 0.1, 0.5):
        presyn = np.where(
            rng.random(pool) < density, rng.integers(0, N, pool), -1
        ).astype(np.int32)
        slots, pos, of = map(np.asarray, build_fwd_index(presyn, N, F))
        want_of = 0
        for n in range(N):
            where = np.flatnonzero(presyn == n)
            want_of += max(0, len(where) - F)
            got_row = slots[n][slots[n] >= 0]
            np.testing.assert_array_equal(np.sort(got_row), where[:F], err_msg=f"cell {n}")
        assert int(of) == want_of
        # back pointers: fwd_slots[presyn[s], fwd_pos[s]] == s for indexed slots
        for s in np.flatnonzero(pos >= 0):
            assert slots[presyn[s], pos[s]] == s


@exact_only
@pytest.mark.parametrize("perm_bits", [0, 16])
def test_e2e_parity_forward_dendrite(forward_dendrite, perm_bits):
    cfg = fwd_cfg(perm_bits)
    cpu = HTMModel(cfg, seed=3, backend="cpu")
    tpu = HTMModel(cfg, seed=3, backend="tpu")
    vals = make_values(300, 1)
    for i in range(300):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"


@pytest.mark.quick
@exact_only
@pytest.mark.parametrize("impl", ["scatter", "matmul"])
def test_forward_vs_scan_full_state(impl):
    """Forward dendrite (both histogram impls) vs the scan on identical
    inputs -> identical full state each run, and the incrementally-maintained
    index stays set-consistent with a canonical rebuild from presyn. (Each
    variant runs straight through under one mode — per-step flips would
    clear the jit caches 640x.)"""
    import jax

    cfg = fwd_cfg()
    vals = make_values(320, 1, seed=29)

    def run_mode(dendrite):
        tm_tpu.set_dendrite_mode(dendrite)
        tm_tpu.set_fwd_impl(impl if dendrite else None)
        try:
            m = HTMModel(cfg, seed=11, backend="tpu")
            raws = [
                m.run(1_700_000_000 + 300 * i, float(vals[i, 0]),
                      learn=(i % 13) != 5).raw_score  # inference interludes
                for i in range(320)
            ]
            return raws, jax.device_get(m._runner.state)
        finally:
            tm_tpu.set_dendrite_mode(None)
            tm_tpu.set_fwd_impl(None)

    raws_f, a = run_mode("forward")
    raws_s, b = run_mode(None)
    assert raws_f == raws_s
    for k in sorted(b):
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)
    assert int(a["tm_overflow"]) == 0
    assert int(a["fwd_of"]) == 0

    # index consistency: maintained rows hold exactly the slot sets of a
    # canonical rebuild (row order is free; membership is the contract)
    slots_c, pos_c, of_c = map(
        np.asarray, build_fwd_index(np.asarray(a["presyn"]), cfg.num_cells, cfg.tm.fanout_cap)
    )
    assert int(of_c) == 0
    maint = np.asarray(a["fwd_slots"])
    for n in range(cfg.num_cells):
        got = np.sort(maint[n][maint[n] >= 0])
        want = np.sort(slots_c[n][slots_c[n] >= 0])
        np.testing.assert_array_equal(got, want, err_msg=f"cell {n}")
    # back pointers agree with the rows
    pos_m = np.asarray(a["fwd_pos"])
    presyn_flat = np.asarray(a["presyn"]).reshape(-1)
    for s in np.flatnonzero(presyn_flat >= 0):
        assert pos_m[s] >= 0, f"slot {s} unindexed"
        assert maint[presyn_flat[s], pos_m[s]] == s, f"slot {s} back pointer"
    assert np.count_nonzero(pos_m >= 0) == np.count_nonzero(presyn_flat >= 0)


@exact_only
def test_forward_save_load_roundtrip(forward_dendrite, tmp_path):
    """model.save under forward mode stores no fwd arrays; load rebuilds the
    index and resumes bit-exactly."""
    cfg = fwd_cfg()
    m = HTMModel(cfg, seed=9, backend="tpu")
    vals = make_values(260, 1, seed=41)
    for i in range(200):
        m.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
    p = str(tmp_path / "fwd_model.npz")
    m.save(p)
    with np.load(p) as z:
        assert not any(k.startswith("s_fwd_") for k in z.files)
    m2 = HTMModel.load(p, backend="tpu")
    for i in range(200, 260):
        r1 = m.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r2 = m2.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r1.raw_score == pytest.approx(r2.raw_score, abs=0.0), f"step {i}"


@exact_only
def test_fanout_overflow_counts(forward_dendrite):
    """A fanout_cap of 1 must trip fwd_of (dropped appends are counted,
    never silent)."""
    import jax

    base = small_cfg()
    cfg = dataclasses.replace(base, tm=dataclasses.replace(base.tm, fanout_cap=1))
    m = HTMModel(cfg, seed=5, backend="tpu")
    vals = make_values(300, 1, seed=43)
    for i in range(300):
        m.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
    assert int(jax.device_get(m._runner.state)["fwd_of"]) > 0


@pytest.mark.quick
def test_incremental_maintenance_matches_rebuild_and_numpy():
    """apply_removals + apply_appends over a random mutation batch must
    leave an index that is membership-identical to a canonical rebuild,
    and dendrite_counts over either index (both impls) must match a
    direct numpy adjacency count — the ISSUE 14 twin-registry contract
    for the incremental-maintenance kernels."""
    import jax.numpy as jnp

    from rtap_tpu.ops.fwd_index import apply_appends, apply_removals, dendrite_counts

    rng = np.random.Generator(np.random.Philox(key=(9, 41)))
    N, F, pool, M = 64, 32, 512, 8
    presyn0 = np.where(rng.random(pool) < 0.5,
                       rng.integers(0, N, pool), -1).astype(np.int32)
    slots, pos, of = build_fwd_index(presyn0, N, F)
    assert int(of) == 0

    E = 48
    mut = rng.choice(pool, E, replace=False).astype(np.int32)
    new = rng.integers(-1, N, E).astype(np.int32)
    presyn1 = presyn0.copy()
    presyn1[mut] = new
    changed = presyn1[mut] != presyn0[mut]
    rem = changed & (presyn0[mut] >= 0)
    add = changed & (presyn1[mut] >= 0)

    s2, p2 = apply_removals(slots, pos, jnp.asarray(mut),
                            jnp.asarray(presyn0[mut]), jnp.asarray(rem))
    s2, p2, dropped = apply_appends(s2, p2, jnp.asarray(mut),
                                    jnp.asarray(presyn1[mut]),
                                    jnp.asarray(add))
    assert int(dropped) == 0

    rs, _rp, rof = build_fwd_index(presyn1, N, F)
    assert int(rof) == 0
    s2_np, rs_np = np.asarray(s2), np.asarray(rs)
    for n in range(N):
        got = set(s2_np[n][s2_np[n] >= 0].tolist())
        want = set(rs_np[n][rs_np[n] >= 0].tolist())
        assert got == want, f"cell {n} row membership diverged"

    perm = rng.random(pool).astype(np.float32)
    act = rng.choice(N, 10, replace=False).astype(np.int32)
    act_ids = jnp.asarray(np.concatenate([act, [N, N]]).astype(np.int32))
    n_seg = pool // M
    seg_of = np.arange(pool) // M
    active = np.isin(presyn1, act)
    want_pot = np.bincount(seg_of[active], minlength=n_seg).astype(np.int32)
    want_conn = np.bincount(seg_of[active & (perm >= 0.5)],
                            minlength=n_seg).astype(np.int32)
    for index in (s2, rs):
        for impl in ("scatter", "matmul"):
            conn, pot = dendrite_counts(index, jnp.asarray(perm), act_ids,
                                        0.5, n_seg, M, impl)
            np.testing.assert_array_equal(np.asarray(pot), want_pot,
                                          err_msg=f"pot {impl}")
            np.testing.assert_array_equal(np.asarray(conn), want_conn,
                                          err_msg=f"conn {impl}")
