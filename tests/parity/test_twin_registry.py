"""Twin-registry parity (ISSUE 14): every public device kernel resolves,
and the stage kernels the big parity suites reach only indirectly get
direct numeric parity against their host twins here.

Two jobs:

* pin the REGISTRY: `rtap_tpu/analysis/kernels.py` pairs every public
  ops/ kernel with an oracle twin (name pairing or a reviewed
  ``# rtap: twin[...]`` annotation), and the twin-parity gate fails on
  any kernel this resolution misses — this test runs the same
  resolution as a library over the real tree, so a new kernel without a
  twin fails HERE with a readable assertion before it fails the gate;
* direct stage parity for sp_overlap / sp_inhibit / sp_learn,
  classifier_bucket_device / classifier_step, health_reduce,
  replicate_state_device, and set_state_row — including the ISSUE 14
  regression for the i32 score-wrap class the dtype-domain pass found
  in SP inhibition (device computed q*C in i32 while the oracle widened
  to i64; both twins now clamp identically).
"""

import copy
import os

import jax.numpy as jnp
import numpy as np
import pytest

from rtap_tpu.config import (
    ClassifierConfig,
    ModelConfig,
    RDSEConfig,
    SPConfig,
    scaled_cluster_preset,
)
from rtap_tpu.models.oracle import spatial_pooler as sp_oracle
from rtap_tpu.models.oracle.classifier import (
    SDRClassifierOracle,
    classifier_bucket,
)
from rtap_tpu.models.state import init_state
from rtap_tpu.ops.classifier_tpu import classifier_bucket_device, classifier_step
from rtap_tpu.ops.sp_tpu import sp_inhibit, sp_learn, sp_overlap
from rtap_tpu.ops.step import replicate_state, replicate_state_device, set_state_row

pytestmark = pytest.mark.quick

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ------------------------------------------------------------ registry --
def test_every_public_ops_kernel_resolves_to_a_twin():
    """The twin-parity gate's registry, run as a library over the real
    tree: every public kernel must resolve (kernels carrying an inline
    `rtap: allow[twin-parity]` suppression are the reviewed exceptions,
    exactly as the gate treats them)."""
    from rtap_tpu.analysis.core import AnalysisContext, discover_files
    from rtap_tpu.analysis.kernels import build_kernel_model

    ctx = AnalysisContext(root=REPO, files=discover_files(REPO))
    model = build_kernel_model(ctx)
    public = [k for k in model.kernels if k.public]
    # the device surface is broad — a collapse here means kernel
    # discovery broke, not that ops/ shrank
    assert len(public) >= 15, [k.name for k in public]
    unresolved = []
    for k in public:
        sf = ctx.file(k.path)
        if sf is not None and sf.suppressed("twin-parity", k.line):
            continue
        if model.resolve_twin(k) is None:
            unresolved.append(f"{k.path}:{k.name}")
    assert unresolved == [], (
        "public kernels without an oracle twin (pair by name or add a "
        f"reviewed '# rtap: twin[...]' annotation): {unresolved}")


# ------------------------------------------------------ SP stage twins --
def _sp_cfg(**kw):
    return ModelConfig(
        rdse=RDSEConfig(size=64, active_bits=5, resolution=0.5),
        sp=SPConfig(columns=128, num_active_columns=8, **kw),
    )


def test_sp_stage_kernels_match_oracle_stages():
    """sp_overlap / sp_inhibit / sp_learn, stage by stage — the e2e SP
    parity suite only reaches them through sp_step, so a stage-local
    regression would be attributed to the wrong stage there."""
    cfg = _sp_cfg()
    rng = np.random.default_rng(11)
    host = init_state(cfg, seed=3)
    # np.array copies: the oracle mutates in place, and jnp.asarray on
    # the CPU backend may ALIAS numpy memory (test_sp_parity's deepcopy
    # exists for the same reason)
    dev = {k: jnp.asarray(np.array(host[k])) for k in
           ("perm", "boost", "overlap_duty", "active_duty", "sp_iter",
            "potential")}
    n_in = cfg.input_size
    for step in range(25):
        sdr = np.zeros(n_in, bool)
        sdr[rng.choice(n_in, size=6, replace=False)] = True
        h_olap = sp_oracle.sp_overlap(host, sdr, cfg.sp)
        d_olap = sp_overlap(dev["perm"], dev["potential"],
                            jnp.asarray(sdr), cfg.sp)
        np.testing.assert_array_equal(h_olap, np.asarray(d_olap),
                                      err_msg=f"overlap step {step}")
        h_act = sp_oracle.sp_inhibit(h_olap, np.asarray(host["boost"]),
                                     cfg.sp)
        d_act = sp_inhibit(d_olap, dev["boost"], cfg.sp)
        np.testing.assert_array_equal(h_act, np.asarray(d_act),
                                      err_msg=f"inhibit step {step}")
        sp_oracle.sp_learn(host, sdr, h_olap, h_act, cfg.sp)  # in place
        dev = sp_learn(dev, jnp.asarray(sdr), d_olap, d_act, cfg.sp)
        np.testing.assert_array_equal(host["perm"], np.asarray(dev["perm"]),
                                      err_msg=f"perm step {step}")


@pytest.mark.parametrize("columns", [64, 127, 128, 2048])
def test_sp_inhibit_extreme_boost_cannot_wrap_i32(columns):
    """ISSUE 14 regression (dtype-domain i32-wrap finding): with a
    pathological boost the device's i32 score q*C used to WRAP while
    the oracle's i64 did not, silently inverting winners on TPU only.
    Both twins now clamp q — in f32, BEFORE the int cast, capped at
    2^24 so the bound stays f32-exact for SMALL column counts too
    (C < 128 was the second wrap: float32((2^31-C)//C) rounds UP past
    2^24 and the 'clamped' product still overflowed). Winners stay
    identical across twins in every regime."""
    cfg = ModelConfig(
        rdse=RDSEConfig(size=64, active_bits=5, resolution=0.5),
        sp=SPConfig(columns=columns, num_active_columns=8,
                    boost_strength=2.0))
    C = cfg.sp.columns
    rng = np.random.default_rng(5)
    overlap = rng.integers(500, 2000, C).astype(np.int32)
    boost = np.full(C, 7.0e4, np.float32)  # q >> every clamp bound
    assert float(overlap.max()) * 7.0e4 * 256.0 > 2**31, "not extreme enough"
    h_act = sp_oracle.sp_inhibit(overlap, boost, cfg.sp)
    d_act = sp_inhibit(jnp.asarray(overlap), jnp.asarray(boost), cfg.sp)
    np.testing.assert_array_equal(h_act, np.asarray(d_act))
    assert int(np.asarray(d_act).sum()) == cfg.sp.num_active_columns


# ----------------------------------------------------- classifier twins --
def _cls_cfg():
    return ModelConfig(
        rdse=RDSEConfig(size=64, active_bits=5, resolution=0.5),
        sp=SPConfig(columns=64, num_active_columns=6),
        classifier=ClassifierConfig(enabled=True, buckets=17),
    )


def test_classifier_bucket_device_matches_oracle():
    cfg = _cls_cfg()
    B = cfg.classifier.buckets
    for v in (0.0, 3.2, -7.9, 1e9, -1e9, float("nan"), float("inf")):
        want = classifier_bucket(v, 0.5, 0.25, B)
        got = int(classifier_bucket_device(
            jnp.float32(v), jnp.float32(0.5), jnp.float32(0.25), B))
        assert got == want, f"value {v}: device {got} oracle {want}"


def test_classifier_step_matches_oracle_compute():
    cfg = _cls_cfg()
    rng = np.random.default_rng(23)
    host = init_state(cfg, seed=1)
    # np.array copies — the oracle updates host arrays in place and the
    # CPU backend may alias numpy memory into device buffers
    dev = {k: jnp.asarray(np.array(v)) for k, v in host.items()}
    oracle = SDRClassifierOracle(host, cfg.classifier)
    C, K = cfg.sp.columns, cfg.tm.cells_per_column
    for step in range(20):
        prev = rng.random((C, K)) < 0.05
        now = rng.random((C, K)) < 0.05
        value = float(rng.normal(5.0, 2.0))
        bucket = classifier_bucket(
            value, float(host["enc_offset"][0]),
            float(host["enc_resolution"][0]), cfg.classifier.buckets)
        want_pred, want_conf = oracle.compute(
            prev.reshape(-1), now.reshape(-1), bucket, value, learn=True)
        dev, pred, conf = classifier_step(
            dev, jnp.asarray(prev), jnp.asarray(now),
            jnp.float32(value), cfg, learn=True)
        np.testing.assert_allclose(float(pred), want_pred, rtol=1e-5,
                                   atol=1e-6, err_msg=f"pred step {step}")
        np.testing.assert_allclose(float(conf), want_conf, rtol=1e-5,
                                   atol=1e-6, err_msg=f"conf step {step}")
    np.testing.assert_allclose(host["cls_w"], np.asarray(dev["cls_w"]),
                               rtol=1e-5, atol=1e-6)


# -------------------------------------------------- health reducer twin --
def test_health_reduce_matches_host_twin():
    """health_reduce (device, inside the fused step) vs
    health_reduce_host (numpy twin) on a real served group — the parity
    home for the reducer pair (the unit suite covers the tracker)."""
    from rtap_tpu.ops.health_tpu import HEALTH_KEYS, health_reduce_host
    from rtap_tpu.service.registry import StreamGroup

    cfg = scaled_cluster_preset(32)
    G, T = 4, 5
    rng = np.random.Generator(np.random.Philox(key=(2, 9)))
    vals = (30 + 5 * rng.random((T, G))).astype(np.float32)
    ts = np.tile(1_700_000_000 + np.arange(T)[:, None], (1, G)).astype(np.int64)
    grp = StreamGroup(cfg, [f"s{i}" for i in range(G)], backend="tpu",
                      health=True)
    raw, _ll, _al = grp.run_chunk(vals, ts)
    host = health_reduce_host(
        {k: np.asarray(v) for k, v in grp.state.items()},
        raw[-1], vals[-1][:, None], cfg)
    for k in HEALTH_KEYS:
        np.testing.assert_allclose(
            np.asarray(grp.last_health[k][-1]), np.asarray(host[k]),
            rtol=1e-5, atol=1e-6, err_msg=k)


# ------------------------------------------------- state movement twins --
def test_replicate_state_device_matches_host_replicate():
    """replicate_state_device (transfer one stream, broadcast on chip)
    must build the same [G, ...] group state as the host-side tiling."""
    cfg = _sp_cfg()
    single = init_state(cfg, seed=4)
    G = 3
    host = replicate_state(single, G)
    dev = replicate_state_device(single, G)
    assert sorted(host) == sorted(dev)
    for k in host:
        np.testing.assert_array_equal(host[k], np.asarray(dev[k]),
                                      err_msg=k)


def test_set_state_row_matches_numpy_row_assignment():
    """set_state_row (donated device scatter) vs the obvious numpy row
    write — the dynamic slot-claim path's state movement twin."""
    cfg = _sp_cfg()
    G, slot = 4, 2
    group = replicate_state(init_state(cfg, seed=4), G)
    fresh = init_state(cfg, seed=9)
    want = copy.deepcopy(group)
    for k in want:
        want[k][slot] = np.asarray(fresh[k]).astype(want[k].dtype)
    got = set_state_row({k: jnp.asarray(v) for k, v in group.items()},
                        fresh, slot)
    for k in want:
        np.testing.assert_array_equal(want[k], np.asarray(got[k]),
                                      err_msg=k)
