"""The TM kernel has two strategies for lookup/compaction ops (gather/nonzero
vs the TPU reformulations — ops/tm_tpu.py FORCE_TPU_PATHS). The default test
platform is CPU, which exercises the gather path; this file forces the TPU
formulations and asserts bit-identical behavior against the oracle, so the
code that actually runs on hardware is pinned by the same parity suite
(SURVEY.md §4 item 2)."""

import numpy as np
import pytest

import rtap_tpu.ops.tm_tpu as tm_tpu
from rtap_tpu.models.htm_model import HTMModel

from tests.parity.test_e2e_parity import exact_only, make_values, small_cfg


@pytest.fixture
def force_tpu_paths():
    old = tm_tpu.FORCE_TPU_PATHS
    tm_tpu.FORCE_TPU_PATHS = True
    # the strategy is baked into traced programs at jit time
    tm_tpu.tm_step.clear_cache()
    yield
    tm_tpu.FORCE_TPU_PATHS = old
    tm_tpu.tm_step.clear_cache()


@exact_only
def test_e2e_parity_with_tpu_paths(force_tpu_paths):
    cfg = small_cfg()
    cpu = HTMModel(cfg, seed=3, backend="cpu")
    tpu = HTMModel(cfg, seed=3, backend="tpu")
    vals = make_values(300, 1)
    for i in range(300):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"


@pytest.fixture
def indexed_scatter():
    tm_tpu.set_scatter_mode("indexed")
    yield
    tm_tpu.set_scatter_mode(None)


@pytest.fixture
def flat_layout():
    tm_tpu.set_layout_mode("flat")
    yield
    tm_tpu.set_layout_mode(None)


@exact_only
@pytest.mark.parametrize("perm_bits", [0, 16])
def test_e2e_parity_with_flat_layout(flat_layout, perm_bits):
    """RTAP_TM_LAYOUT=flat (pools carried [C, K*S*M], segment tensors
    [C, K*S], per-segment counts via block-diagonal matmuls) is a pure
    layout change: bit-identical to the 4-D kernel in both permanence
    domains."""
    from tests.parity.test_quantized_parity import quant_cfg

    cfg = small_cfg() if perm_bits == 0 else quant_cfg(perm_bits)
    cpu = HTMModel(cfg, seed=5, backend="cpu")
    tpu = HTMModel(cfg, seed=5, backend="tpu")
    vals = make_values(300, 1)
    for i in range(300):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"


@pytest.mark.quick
@exact_only
@pytest.mark.parametrize("perm_bits", [0, 16])
def test_e2e_parity_flat_layout_all_tpu_paths(
    force_tpu_paths, flat_layout, indexed_scatter, perm_bits
):
    """The full hardware candidate: flat layout + indexed workspace movement
    + TPU compact-ids paths, all at once, in both permanence domains."""
    from tests.parity.test_quantized_parity import quant_cfg

    cfg = small_cfg() if perm_bits == 0 else quant_cfg(perm_bits)
    cpu = HTMModel(cfg, seed=13, backend="cpu")
    tpu = HTMModel(cfg, seed=13, backend="tpu")
    vals = make_values(300, 1, seed=21)
    for i in range(300):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"


@exact_only
@pytest.mark.parametrize("perm_bits", [0, 16])
def test_e2e_parity_with_indexed_scatter(indexed_scatter, perm_bits):
    """The indexed (take / .at[].set) workspace-movement strategy must be
    bit-identical to the one-hot-matmul strategy — the SCATTER_MODE switch
    is a pure layout/bandwidth experiment (ops/tm_tpu.py). Covered in both
    the f32 and the u16 fixed-point permanence domains (the quantized branch
    has its own round/astype epilogue)."""
    from tests.parity.test_quantized_parity import quant_cfg

    cfg = small_cfg() if perm_bits == 0 else quant_cfg(perm_bits)
    cpu = HTMModel(cfg, seed=3, backend="cpu")
    tpu = HTMModel(cfg, seed=3, backend="tpu")
    vals = make_values(300, 1)
    for i in range(300):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"


@exact_only
def test_e2e_parity_indexed_scatter_with_tpu_paths(force_tpu_paths, indexed_scatter):
    """Both strategy switches together = the exact program a hardware run
    with RTAP_TM_SCATTER=indexed would trace."""
    cfg = small_cfg()
    cpu = HTMModel(cfg, seed=9, backend="cpu")
    tpu = HTMModel(cfg, seed=9, backend="tpu")
    vals = make_values(300, 1, seed=11)
    for i in range(300):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"


@exact_only
def test_compact_ids_matches_nonzero(force_tpu_paths):
    import jax.numpy as jnp

    rng = np.random.Generator(np.random.Philox(key=(5, 5)))
    for n, size in ((64, 8), (2048, 80), (8192, 32)):
        for density in (0.0, 0.01, 0.2, 1.0):
            mask = rng.random(n) < density
            got = np.asarray(tm_tpu._compact_ids(jnp.asarray(mask), size))
            want = np.flatnonzero(mask)[:size]
            want = np.concatenate([want, np.full(size - len(want), n)]).astype(np.int32)
            np.testing.assert_array_equal(got, want, err_msg=f"n={n} size={size} d={density}")
