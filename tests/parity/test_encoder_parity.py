"""Oracle-vs-device parity for hashing + record encoding (SURVEY.md §4 item 2).

The RDSE/date encoder must be bit-identical across host numpy and jitted JAX:
every downstream parity test depends on both backends seeing the same SDR.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rtap_tpu.config import DateConfig, ModelConfig, RDSEConfig
from rtap_tpu.models.oracle.encoders import encode_record
from rtap_tpu.ops.encoders_tpu import bind_offsets, encode_device
from rtap_tpu.ops.hashing_tpu import hash_bits, hash_u32
from rtap_tpu.utils.hashing import hash_bits_np, hash_u32_np


def test_hash_u32_parity():
    keys = np.arange(-500, 500, dtype=np.int64)
    for seed in (0, 42, 0xDEADBEEF):
        np_h = hash_u32_np(keys, seed)
        dev_h = np.asarray(jax.jit(lambda k: hash_u32(k, seed))(jnp.asarray(keys, jnp.int32)))
        np.testing.assert_array_equal(np_h, dev_h)


def test_hash_bits_parity():
    keys = np.arange(-200, 200, dtype=np.int64)
    np_b = hash_bits_np(keys, 7, 400)
    dev_b = np.asarray(jax.jit(lambda k: hash_bits(k, 7, 400))(jnp.asarray(keys, jnp.int32)))
    np.testing.assert_array_equal(np_b, dev_b)


@pytest.mark.quick
@pytest.mark.parametrize("n_fields", [1, 3])
def test_encode_parity(n_fields):
    cfg = ModelConfig(
        rdse=RDSEConfig(size=100, active_bits=7, resolution=0.5),
        date=DateConfig(time_of_day_width=5, time_of_day_size=13, weekend_width=3),
        n_fields=n_fields,
    )
    rng = np.random.default_rng(0)
    offsets = rng.normal(size=n_fields).astype(np.float32)
    enc_dev = jax.jit(lambda v, t, o: encode_device(cfg, v, t, o))
    for i in range(50):
        values = (rng.normal(size=n_fields) * 10).astype(np.float32)
        if i % 7 == 0:
            values[rng.integers(n_fields)] = np.nan  # missing sample
        ts = int(rng.integers(0, 2_000_000_000))
        host = encode_record(cfg, values, ts, offsets)
        dev = np.asarray(enc_dev(jnp.asarray(values), jnp.int32(ts), jnp.asarray(offsets)))
        np.testing.assert_array_equal(host, dev, err_msg=f"record {i} ts={ts}")


def test_encode_parity_extreme_values():
    """Wild finite values (overflowed counters, sensor garbage) must encode
    identically on both backends: the shared RDSE_BUCKET_CLAMP keeps the
    device's int32 bucket from wrapping where the host's int64 would not."""
    cfg = ModelConfig(
        rdse=RDSEConfig(size=100, active_bits=7, resolution=0.5),
        date=DateConfig(time_of_day_width=0, time_of_day_size=0, weekend_width=0),
    )
    offsets = np.zeros(1, np.float32)
    enc_dev = jax.jit(lambda v, t, o: encode_device(cfg, v, t, o))
    for x in (3e9, -3e9, 1e12, 1e30, -1e30, 3.4e38):
        values = np.asarray([x], np.float32)
        host = encode_record(cfg, values, 0, offsets)
        dev = np.asarray(enc_dev(jnp.asarray(values), jnp.int32(0), jnp.asarray(offsets)))
        np.testing.assert_array_equal(host, dev, err_msg=f"value {x}")


def test_bind_offsets_matches_host_rule():
    values = jnp.asarray([np.nan, 2.5, 7.0], jnp.float32)
    off = jnp.zeros(3, jnp.float32)
    bound = jnp.asarray([False, False, True])
    new_off, new_bound = jax.jit(bind_offsets)(values, off, bound)
    # field0: NaN -> stays unbound; field1: binds to 2.5; field2: already bound
    np.testing.assert_array_equal(np.asarray(new_bound), [False, True, True])
    np.testing.assert_allclose(np.asarray(new_off), [0.0, 2.5, 0.0])


def test_scalar_encoder_parity_and_properties():
    """Classic ScalarEncoder (SURVEY.md C2): host/device bit-identical, and
    the classic properties hold — nearby values share bits proportionally to
    distance, out-of-range values clip to the edge runs."""
    import jax.numpy as jnp
    import numpy as np

    from rtap_tpu.config import ModelConfig, ScalarEncoderConfig
    from rtap_tpu.models.oracle.encoders import encode_record
    from rtap_tpu.ops.encoders_tpu import encode_device

    cfg = ModelConfig(scalar=ScalarEncoderConfig(size=100, width=9,
                                                 min_val=0.0, max_val=50.0))
    assert cfg.input_size == 100 + cfg.date.size
    off = np.zeros(1, np.float32)
    sdrs = {}
    for v in (-5.0, 0.0, 1.0, 25.0, 26.0, 49.9, 50.0, 75.0, float("nan")):
        host = encode_record(cfg, np.array([v]), 1_700_000_000, off)
        dev = np.asarray(
            encode_device(cfg, jnp.float32([v]), jnp.int32(1_700_000_000),
                          jnp.asarray(off))
        )
        np.testing.assert_array_equal(host, dev, err_msg=str(v))
        sdrs[v] = host[:100]
    w = 9
    assert sdrs[25.0].sum() == w
    # adjacent buckets overlap in w-1 bits; distance decays overlap
    assert (sdrs[25.0] & sdrs[26.0]).sum() in (w - 2, w - 1)
    assert (sdrs[1.0] & sdrs[49.9]).sum() == 0
    # clipping: out-of-range == edge encodings; NaN encodes nothing
    np.testing.assert_array_equal(sdrs[-5.0], sdrs[0.0])
    np.testing.assert_array_equal(sdrs[75.0], sdrs[50.0])
    nan_sdr = encode_record(cfg, np.array([np.nan]), 1_700_000_000, off)
    assert nan_sdr[:100].sum() == 0
    # full pipeline compiles with the scalar encoder selected
    from rtap_tpu.models.htm_model import HTMModel

    m_cpu = HTMModel(cfg, seed=2, backend="cpu")
    m_dev = HTMModel(cfg, seed=2, backend="tpu")
    for i in range(30):
        v = 25.0 + 10.0 * np.sin(i / 3)
        r1 = m_cpu.run(1_700_000_000 + i, v)
        r2 = m_dev.run(1_700_000_000 + i, v)
        assert r1.raw_score == r2.raw_score, i
