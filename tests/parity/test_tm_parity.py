"""Oracle-vs-device TM parity (SURVEY.md §4 item 2) — the crown-jewel test.

Runs the numpy TM oracle and the jitted device kernel from identical initial
state over identical active-column sequences and asserts bit-identical pools
(presyn, syn_perm, seg_last), cell states, and raw anomaly scores each step.
Sequences mix repetition (segment reinforcement), novelty (bursting, segment
allocation), ambiguity (shared prefixes -> multiple predicted cells), and
resets, to reach every learning branch including LRU eviction and
weakest-synapse eviction.
"""

import copy

import jax.numpy as jnp
import numpy as np
import pytest

from rtap_tpu.config import TMConfig
from rtap_tpu.models.oracle.temporal_memory import TMOracle
from rtap_tpu.ops.tm_tpu import from_kernel_layout, tm_step, to_kernel_layout

TM_KEYS = (
    "presyn", "syn_perm", "seg_last", "active_seg", "matching_seg",
    "seg_pot", "prev_active", "prev_winner", "tm_iter", "tm_overflow",
)


def _init_tm_state(C, cfg: TMConfig):
    K, S, M = cfg.cells_per_column, cfg.max_segments_per_cell, cfg.max_synapses_per_segment
    return {
        "presyn": np.full((C, K, S, M), -1, np.int32),
        "syn_perm": np.zeros((C, K, S, M), np.float32),
        "seg_last": np.full((C, K, S), -1, np.int32),
        "active_seg": np.zeros((C, K, S), bool),
        "matching_seg": np.zeros((C, K, S), bool),
        "seg_pot": np.zeros((C, K, S), np.int32),
        "prev_active": np.zeros((C, K), bool),
        "prev_winner": np.zeros((C, K), bool),
        "tm_iter": np.int32(0),
        "tm_overflow": np.int32(0),
    }


def _assert_state_equal(host, dev, step):
    for key in TM_KEYS:
        if key == "tm_overflow":
            assert int(dev[key]) == 0, f"device capacity overflow at step {step}"
            continue
        np.testing.assert_array_equal(
            np.asarray(host[key]), np.asarray(dev[key]), err_msg=f"{key} step {step}"
        )


def _run_parity(C, cfg, sequences, learn=True):
    host = _init_tm_state(C, cfg)
    # the kernel runs whatever layout is the process default (flat since the
    # r4 silicon A/B); the public [C, K, S, M] layout crosses the boundary
    # via the same reshape adapters ops/step.py uses
    dev = to_kernel_layout({k: jnp.asarray(v) for k, v in copy.deepcopy(host).items()})
    oracle = TMOracle(host, cfg)
    for step, cols in enumerate(sequences):
        active = np.zeros(C, bool)
        active[cols] = True
        raw_host = oracle.compute(active, learn=learn)
        dev, raw_dev = tm_step(dev, jnp.asarray(active), cfg, learn=learn)
        assert abs(raw_host - float(raw_dev)) < 1e-6, f"raw score step {step}"
        _assert_state_equal(host, from_kernel_layout(dev, cfg), step)


def _pattern(rng, C, n_active):
    return rng.choice(C, size=n_active, replace=False)


@pytest.mark.quick
@pytest.mark.parametrize("learn", [True, False])
def test_tm_parity_repeating_sequence(learn):
    """A-B-C-D repeated: drives prediction, reinforcement, growth."""
    C, cfg = 64, TMConfig(
        cells_per_column=8, activation_threshold=3, min_threshold=2,
        max_segments_per_cell=4, max_synapses_per_segment=12,
        new_synapse_count=6, learn_cap=32,
    )
    rng = np.random.default_rng(11)
    pats = [_pattern(rng, C, 5) for _ in range(4)]
    seq = pats * 10
    _run_parity(C, cfg, seq, learn=learn)


def test_tm_parity_ambiguous_sequences():
    """A-B-C-D vs A-B-C-E (shared prefix) -> multiple predicted cells per
    column, multi-segment learning in predicted columns."""
    C, cfg = 64, TMConfig(
        cells_per_column=8, activation_threshold=3, min_threshold=2,
        max_segments_per_cell=4, max_synapses_per_segment=12,
        new_synapse_count=6, learn_cap=32,
    )
    rng = np.random.default_rng(5)
    A, B, Cp, D, E = (_pattern(rng, C, 5) for _ in range(5))
    seq = ([A, B, Cp, D] * 5 + [A, B, Cp, E] * 5) * 3
    _run_parity(C, cfg, seq)


def test_tm_parity_random_stream_with_eviction():
    """Random novelty: constant bursting + allocation until pools fill and
    LRU segment eviction + weakest-synapse eviction kick in."""
    C, cfg = 32, TMConfig(
        cells_per_column=4, activation_threshold=2, min_threshold=1,
        max_segments_per_cell=2, max_synapses_per_segment=6,
        new_synapse_count=4, learn_cap=32,
    )
    rng = np.random.default_rng(23)
    seq = [_pattern(rng, C, 4) for _ in range(120)]
    _run_parity(C, cfg, seq)


@pytest.mark.quick
@pytest.mark.parametrize("layout", ["aos", "flat"])
def test_tm_parity_explicit_layouts(layout):
    """Full state parity under BOTH kernel layouts, explicitly pinned.

    The other tests run the process default (flat since the r4 silicon
    A/B); aos is still shipped and raced as the hardware reference rung
    (bench.py ladder), so a full-state regression in the aos path must
    not ride on the classifier test's raw-score check alone."""
    from rtap_tpu.ops import tm_tpu

    C, cfg = 32, TMConfig(
        cells_per_column=4, activation_threshold=2, min_threshold=1,
        max_segments_per_cell=2, max_synapses_per_segment=6,
        new_synapse_count=4, learn_cap=32,
    )
    rng = np.random.default_rng(29)
    seq = [_pattern(rng, C, 4) for _ in range(60)]
    tm_tpu.set_layout_mode(layout)
    try:
        _run_parity(C, cfg, seq)
    finally:
        tm_tpu.set_layout_mode(None)


def test_tm_parity_punishment_path():
    """Alternating similar patterns so matching segments form in columns that
    then fail to activate -> predicted_segment_decrement punishment."""
    C, cfg = 48, TMConfig(
        cells_per_column=6, activation_threshold=2, min_threshold=1,
        max_segments_per_cell=3, max_synapses_per_segment=8,
        new_synapse_count=5, predicted_segment_decrement=0.02,
        learn_cap=32,
    )
    rng = np.random.default_rng(31)
    X, Y = _pattern(rng, C, 6), _pattern(rng, C, 6)
    # overlapping variants of Y: some columns of Y activate, some don't
    Y2 = Y.copy(); Y2[:3] = _pattern(rng, C, 3)
    seq = ([X, Y] * 8 + [X, Y2] * 8) * 2
    _run_parity(C, cfg, seq)


def test_tm_parity_empty_and_full_columns():
    """Edge cases: empty active set (raw=0) and all-columns-active steps."""
    C, cfg = 16, TMConfig(
        cells_per_column=4, activation_threshold=2, min_threshold=1,
        max_segments_per_cell=2, max_synapses_per_segment=6,
        new_synapse_count=4, learn_cap=80,
    )
    rng = np.random.default_rng(3)
    seq = [_pattern(rng, C, 3), np.arange(C), np.array([], np.int64),
           _pattern(rng, C, 3), np.arange(C), _pattern(rng, C, 3)] * 4
    _run_parity(C, cfg, seq)
