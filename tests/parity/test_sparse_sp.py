"""Sparse member-index SP pool parity (ISSUE 18): oracle vs device twins
over the gather-addressed layout, bit-exact across every permanence domain
(f32 / u16 / u8), through the vmapped group-chunk path, and on the edge
rows the layout introduces (all-empty and completely-full member tables).
Also pins the migration invariant: a dense pool re-laid by
models/migrate.sparsify_sp_state scores bit-identically to the dense
original forever (same synapses, same permanences, order-independent
integer overlap).

Twin coverage: `sp_overlap` and `sp_compute` (oracle names) against
ops/sp_tpu.py's `sp_overlap` / `sp_step` — the same pairs the dense parity
file exercises, now on the sparse branch of each kernel.
"""

import copy
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rtap_tpu.config import ModelConfig, RDSEConfig, SPConfig, cluster_preset, dense_cluster_preset
from rtap_tpu.models.migrate import sparse_pool_width, sparsify_config, sparsify_sp_state
from rtap_tpu.models.oracle.spatial_pooler import sp_compute, sp_overlap
from rtap_tpu.models.state import init_state, members_dtype
from rtap_tpu.ops.sp_tpu import sp_step

SP_KEYS = ("perm", "boost", "overlap_duty", "active_duty", "sp_iter", "members")


def _sparse_cfg(perm_bits: int = 0, pool_members: int = 0) -> ModelConfig:
    return ModelConfig(
        rdse=RDSEConfig(size=64, active_bits=5, resolution=0.5),
        sp=SPConfig(columns=128, num_active_columns=8, potential_pct=0.5,
                    sparse_pool=True, pool_members=pool_members,
                    perm_bits=perm_bits),
    )


def _device_state(state):
    return {k: jnp.asarray(state[k]) for k in SP_KEYS}


def _sdr(rng, n_in, frac=0.05):
    sdr = np.zeros(n_in, bool)
    sdr[rng.choice(n_in, size=max(1, int(frac * n_in)), replace=False)] = True
    return sdr


def _run_parity(cfg: ModelConfig, n_steps: int, learn: bool, host=None):
    rng = np.random.default_rng(7)
    host = init_state(cfg, seed=3) if host is None else host
    dev = _device_state(copy.deepcopy(host))
    for step in range(n_steps):
        sdr = _sdr(rng, cfg.input_size)
        host_active = sp_compute(host, sdr, cfg.sp, learn=learn)
        dev, dev_active = sp_step(dev, jnp.asarray(sdr), cfg.sp, learn=learn)
        np.testing.assert_array_equal(
            host_active, np.asarray(dev_active), err_msg=f"step {step}")
        np.testing.assert_array_equal(
            host["perm"], np.asarray(dev["perm"]), err_msg=f"step {step}")
        np.testing.assert_array_equal(host["overlap_duty"], np.asarray(dev["overlap_duty"]))
        np.testing.assert_array_equal(host["active_duty"], np.asarray(dev["active_duty"]))
    assert int(host["sp_iter"]) == int(dev["sp_iter"]) == (n_steps if learn else 0)
    return host


@pytest.mark.parametrize("perm_bits", [0, 16, 8])
@pytest.mark.parametrize("learn", [True, False])
def test_sparse_sp_parity_all_domains(perm_bits, learn):
    """Gather-addressed overlap + learning bit-exact oracle-vs-device in
    every permanence domain (f32 arithmetic and int32 quanta arithmetic)."""
    _run_parity(_sparse_cfg(perm_bits), n_steps=100, learn=learn)


def test_sparse_sp_parity_cluster_preset():
    """The shipping geometry itself (C=256, P=64, u16)."""
    cfg = cluster_preset()
    assert cfg.sp.sparse_pool and cfg.sp_members == 64
    _run_parity(cfg, n_steps=40, learn=True)


@pytest.mark.parametrize("perm_bits", [0, 16])
def test_sparse_vmapped_chunk_parity(perm_bits):
    """The group path: sp_step vmapped over a stacked [G, ...] state (how
    the fused chunk kernel consumes the pool) matches G independent oracle
    streams bit-for-bit."""
    cfg = _sparse_cfg(perm_bits)
    G, n_steps = 4, 30
    hosts = [init_state(cfg, seed=10 + g) for g in range(G)]
    dev = {k: jnp.stack([jnp.asarray(h[k]) for h in hosts]) for k in SP_KEYS}
    step = jax.vmap(lambda st, sdr: sp_step(st, sdr, cfg.sp, learn=True))
    rng = np.random.default_rng(12)
    for t in range(n_steps):
        sdrs = np.stack([_sdr(rng, cfg.input_size) for _ in range(G)])
        host_active = np.stack(
            [sp_compute(hosts[g], sdrs[g], cfg.sp, learn=True) for g in range(G)])
        dev, dev_active = step(dev, jnp.asarray(sdrs))
        np.testing.assert_array_equal(host_active, np.asarray(dev_active), err_msg=f"t {t}")
    for g in range(G):
        np.testing.assert_array_equal(hosts[g]["perm"], np.asarray(dev["perm"][g]))
        np.testing.assert_array_equal(hosts[g]["members"], np.asarray(dev["members"][g]))


def test_empty_and_full_pool_edge_rows():
    """Padding semantics: an all-empty member row (every slot -1, the
    migration pad extreme) contributes overlap 0 and its permanences stay
    exactly 0 through learning and the weak-column bump on BOTH backends;
    a completely full row behaves like a dense column of the same members."""
    cfg = _sparse_cfg(perm_bits=16)
    host = init_state(cfg, seed=3)
    P = cfg.sp_members
    host["members"][0, :] = np.int16(-1)   # empty pool row
    host["perm"][0, :] = 0
    host["members"][1, :] = np.arange(P, dtype=members_dtype(cfg))  # full row
    dev = _device_state(copy.deepcopy(host))
    rng = np.random.default_rng(5)
    for t in range(60):
        sdr = _sdr(rng, cfg.input_size, frac=0.2)
        ho = sp_overlap(host, sdr, cfg.sp)
        assert ho[0] == 0, "empty pool row must never overlap"
        host_active = sp_compute(host, sdr, cfg.sp, learn=True)
        dev, dev_active = sp_step(dev, jnp.asarray(sdr), cfg.sp, learn=True)
        np.testing.assert_array_equal(host_active, np.asarray(dev_active), err_msg=f"t {t}")
        assert not host["perm"][0].any(), "empty slots must stay at permanence 0"
    np.testing.assert_array_equal(host["perm"], np.asarray(dev["perm"]))
    np.testing.assert_array_equal(host["members"], np.asarray(dev["members"]))


@pytest.mark.parametrize("perm_bits", [0, 16, 8])
def test_migrated_pool_scores_match_dense(perm_bits):
    """models/migrate.py invariant: the re-laid pool is the SAME pool —
    overlap, winners, and learned permanences track the dense original
    bit-for-bit through learning (the committed-checkpoint restore in
    tests/unit/test_checkpoint.py pins the end-to-end version)."""
    base = dense_cluster_preset(perm_bits=perm_bits)
    cfg = dataclasses.replace(
        base, sp=dataclasses.replace(base.sp, columns=128))
    dense = init_state(cfg, seed=5)
    P = sparse_pool_width(dense["potential"])
    scfg = sparsify_config(cfg, P)
    sparse = sparsify_sp_state({k: np.copy(v) for k, v in dense.items()}, P)
    rng = np.random.default_rng(11)
    for t in range(50):
        sdr = _sdr(rng, cfg.input_size, frac=0.08)
        np.testing.assert_array_equal(
            sp_overlap(dense, sdr, cfg.sp), sp_overlap(sparse, sdr, scfg.sp),
            err_msg=f"t {t}")
        a_d = sp_compute(dense, sdr, cfg.sp, learn=True)
        a_s = sp_compute(sparse, sdr, scfg.sp, learn=True)
        np.testing.assert_array_equal(a_d, a_s, err_msg=f"t {t}")
    # learned permanences agree slot-for-slot on the member table
    order = np.argsort(~dense["potential"], axis=-1, kind="stable")[:, :P]
    valid = np.take_along_axis(dense["potential"], order, axis=-1)
    np.testing.assert_array_equal(
        np.where(valid, np.take_along_axis(dense["perm"], order, axis=-1), 0),
        sparse["perm"])
