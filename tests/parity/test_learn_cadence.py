"""Learning-cadence (cfg.learn_every) parity and semantics.

The cadence schedule exists because the round-4 silicon A/B measured the
learning pass as ~85% of the fused step (SCALING.md): mature streams learn
every k-th tick instead of every tick. These tests pin:

1. the device schedule (a scalar `lax.cond` in ops/step.py:_tick, clocked
   by the checkpointed `tm_iter`) bit-identical to the oracle stepped with
   the SAME explicit learn/infer flag sequence;
2. the chunked path == the per-tick path (the cond composes with scan);
3. the host-side twin in HTMModel.run (both backends) == the device group
   schedule, so single-stream and grouped execution agree record-for-record;
4. learn_every=1 is exactly the old always-learn behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rtap_tpu.config import ModelConfig, RDSEConfig, DateConfig, SPConfig, TMConfig
from rtap_tpu.models.htm_model import HTMModel, oracle_record_step
from rtap_tpu.models.oracle.temporal_memory import TMOracle
from rtap_tpu.models.state import init_state
from rtap_tpu.ops.step import chunk_step, group_step, replicate_state

exact_only = pytest.mark.skipif(
    jax.devices()[0].platform != "cpu",
    reason="bit-exact parity is asserted on the CPU test backend only",
)


def cadence_cfg(learn_every=4, learn_full_until=20) -> ModelConfig:
    return ModelConfig(
        rdse=RDSEConfig(size=128, active_bits=11, resolution=0.7),
        date=DateConfig(time_of_day_width=7, time_of_day_size=18, weekend_width=3),
        sp=SPConfig(columns=256, num_active_columns=10),
        tm=TMConfig(cells_per_column=8, activation_threshold=6, min_threshold=4,
                    max_segments_per_cell=4, max_synapses_per_segment=16,
                    new_synapse_count=8, learn_cap=48),
        learn_every=learn_every, learn_full_until=learn_full_until,
    )


def expected_flags(n, cfg):
    """The schedule ops/step.py derives from tm_iter (= completed steps)."""
    return [
        i < cfg.learn_full_until or i % cfg.learn_every == 0 for i in range(n)
    ]


def make_vals(n, G, seed=3):
    rng = np.random.Generator(np.random.Philox(key=(seed, 2)))
    t = np.arange(n)[:, None]
    base = 40 + 15 * np.sin(2 * np.pi * (t + 7 * np.arange(G)[None, :]) / 60.0)
    v = (base + rng.normal(0, 2.0, (n, G))).astype(np.float32)
    v[n // 2] += 30.0
    return v


@pytest.mark.quick
@exact_only
def test_cadence_device_matches_oracle_with_explicit_flags():
    """group_step under cfg.learn_every == oracle fed the same flag sequence."""
    cfg = cadence_cfg()
    G, n = 3, 90
    gstate = jax.device_put(replicate_state(init_state(cfg, seed=5), G))
    oracles = []
    for _ in range(G):
        st = init_state(cfg, seed=5)
        oracles.append((st, TMOracle(st, cfg.tm)))
    vals = make_vals(n, G)
    flags = expected_flags(n, cfg)

    for i in range(n):
        ts = np.full(G, 1_700_000_000 + i, np.int32)
        gstate, graw = group_step(
            gstate, jnp.asarray(vals[i][:, None]), jnp.asarray(ts), cfg, learn=True
        )
        for g in range(G):
            st, tm = oracles[g]
            raw = oracle_record_step(
                cfg, st, tm, vals[i, g : g + 1], int(ts[g]), flags[i]
            )
            assert float(raw) == float(graw[g]), f"step {i} stream {g}"

    dev = jax.device_get(gstate)
    for k in ("perm", "presyn", "syn_perm", "seg_last", "prev_active",
              "prev_winner", "boost", "enc_offset"):
        for g in range(G):
            np.testing.assert_array_equal(
                np.asarray(dev[k][g]), np.asarray(oracles[g][0][k]),
                err_msg=f"{k} stream {g}",
            )


@exact_only
def test_cadence_chunked_matches_per_tick():
    """chunk_step's scanned cond == per-tick group_step, same schedule."""
    cfg = cadence_cfg(learn_every=3, learn_full_until=10)
    G, T, chunks = 2, 16, 3
    s_tick = jax.device_put(replicate_state(init_state(cfg, seed=8), G))
    s_chunk = jax.device_put(replicate_state(init_state(cfg, seed=8), G))
    vals = make_vals(T * chunks, G, seed=9)

    raws_tick = []
    for i in range(T * chunks):
        ts = np.full(G, 1_700_000_000 + i, np.int32)
        s_tick, raw = group_step(
            s_tick, jnp.asarray(vals[i][:, None]), jnp.asarray(ts), cfg
        )
        raws_tick.append(np.asarray(raw))
    raws_chunk = []
    for c in range(chunks):
        v = jnp.asarray(vals[c * T : (c + 1) * T][:, :, None])
        ts = jnp.asarray(
            1_700_000_000 + np.arange(c * T, (c + 1) * T)[:, None]
            + np.zeros((1, G)), jnp.int32
        )
        s_chunk, raw = chunk_step(s_chunk, v, ts, cfg)
        raws_chunk.append(np.asarray(raw))
    np.testing.assert_array_equal(
        np.stack(raws_tick), np.concatenate(raws_chunk).reshape(-1, G)
    )
    a, b = jax.device_get(s_tick), jax.device_get(s_chunk)
    for k in ("presyn", "syn_perm", "perm", "tm_iter"):
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)


@exact_only
def test_cadence_htm_model_both_backends():
    """HTMModel.run's host-side schedule == the device schedule, cpu == tpu."""
    cfg = cadence_cfg(learn_every=5, learn_full_until=8)
    cpu = HTMModel(cfg, seed=3, backend="cpu")
    tpu = HTMModel(cfg, seed=3, backend="tpu")
    vals = make_vals(60, 1)
    for i in range(60):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"


@exact_only
def test_cadence_registry_cpu_matches_tpu_backend():
    """StreamGroupRegistry honors the cadence on BOTH backends identically.

    Regression pin for the r4 bug where the registry's CPU oracle path
    passed the raw learn flag through (no schedule) while the device path
    applied it — the cadence quality sweep came back bit-identical across
    k because the cpu-backend eval never thinned learning at all."""
    from rtap_tpu.service.registry import StreamGroupRegistry

    cfg = cadence_cfg(learn_every=4, learn_full_until=12)
    G, n = 4, 60
    ids = [f"s{i}" for i in range(G)]
    reg_cpu = StreamGroupRegistry(cfg, group_size=G, backend="cpu")
    reg_tpu = StreamGroupRegistry(cfg, group_size=G, backend="tpu")
    for r in (reg_cpu, reg_tpu):
        for sid in ids:
            r.add_stream(sid)
        r.finalize()
    vals = make_vals(n, G, seed=13)
    for i in range(n):
        ts = 1_700_000_000 + i
        for gc, gt in zip(reg_cpu.groups, reg_tpu.groups):
            a = gc.tick(vals[i], ts)
            b = gt.tick(vals[i], ts)
            np.testing.assert_array_equal(
                np.asarray(a.raw), np.asarray(b.raw), err_msg=f"tick {i}"
            )


@exact_only
def test_cadence_survives_save_load(tmp_path):
    """Resume mid-schedule continues the cadence phase (tm_iter is the
    clock and is checkpointed): save at a tick that is NOT a multiple of
    k, reload, and the continued run must match an uninterrupted one
    record-for-record."""
    cfg = cadence_cfg(learn_every=4, learn_full_until=8)
    vals = make_vals(50, 1, seed=21)
    a = HTMModel(cfg, seed=9, backend="cpu")
    b = HTMModel(cfg, seed=9, backend="cpu")
    cut = 22  # 22 % 4 != 0: mid-phase
    for i in range(cut):
        a.run(1_700_000_000 + i, float(vals[i, 0]))
        b.run(1_700_000_000 + i, float(vals[i, 0]))
    p = str(tmp_path / "cadence_model")
    b.save(p)
    b2 = HTMModel.load(p, backend="cpu")
    for i in range(cut, 50):
        ra = a.run(1_700_000_000 + i, float(vals[i, 0]))
        rb = b2.run(1_700_000_000 + i, float(vals[i, 0]))
        assert ra.raw_score == pytest.approx(rb.raw_score, abs=0.0), f"step {i}"


@exact_only
def test_learn_every_one_is_always_learn():
    """Default cadence is bit-identical to the pre-cadence always-learn path."""
    base = cadence_cfg(learn_every=1, learn_full_until=0)
    G, n = 2, 40
    s_a = jax.device_put(replicate_state(init_state(base, seed=4), G))
    s_b = jax.device_put(replicate_state(init_state(base, seed=4), G))
    vals = make_vals(n, G, seed=5)
    for i in range(n):
        ts = np.full(G, 1_700_000_000 + i, np.int32)
        s_a, raw_a = group_step(s_a, jnp.asarray(vals[i][:, None]), jnp.asarray(ts), base)
        # learn=True static path (cadence disabled) is the exact old code path
        s_b, raw_b = group_step(
            s_b, jnp.asarray(vals[i][:, None]), jnp.asarray(ts), base, learn=True
        )
        np.testing.assert_array_equal(np.asarray(raw_a), np.asarray(raw_b))


@exact_only
def test_burst_cadence_semantics_and_parity():
    """learn_burst=B: B CONSECUTIVE learn ticks per k*B cycle — same
    average rate as the spread schedule, same shared predicate on host
    and device (HTMModel cpu == tpu backend, record for record)."""
    import dataclasses

    cfg = dataclasses.replace(cadence_cfg(learn_every=4, learn_full_until=8),
                              learn_burst=5)
    # predicate shape: full-rate window, then 5-on/15-off cycles phased
    # from the window's END (a burst starts the tick maturity ends —
    # absolute phasing would freeze learning for up to (k-1)*B ticks
    # right as scoring begins)
    flags = [bool(cfg.learns_on(i)) for i in range(48)]
    assert all(flags[:8])
    for i in range(8, 48):
        assert flags[i] == ((i - 8) % 20 < 5), i
    assert flags[8]  # the first post-window tick learns
    # average rate over whole cycles == 1/learn_every
    assert sum(flags[8:28]) == 5

    cpu = HTMModel(cfg, seed=3, backend="cpu")
    tpu = HTMModel(cfg, seed=3, backend="tpu")
    vals = make_vals(60, 1)
    for i in range(60):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"


def test_burst_one_is_spread_schedule():
    """burst=1 must be bit-identical to the original every-k-th predicate."""
    import dataclasses

    for k, fu in ((1, 0), (4, 20), (8, 0)):
        cfg = cadence_cfg(learn_every=k, learn_full_until=fu)
        cfgb = dataclasses.replace(cfg, learn_burst=1)
        for i in range(100):
            assert bool(cfg.learns_on(i)) == (i < fu or i % k == 0)
            assert bool(cfgb.learns_on(i)) == bool(cfg.learns_on(i))


def test_burst_without_cadence_fails_loudly():
    """learn_burst>1 at learn_every=1 can never thin learning — a saved
    config claiming it would misrepresent what ran; loud-failure policy."""
    import dataclasses

    import pytest as _pytest

    with _pytest.raises(ValueError, match="learn_burst"):
        dataclasses.replace(cadence_cfg(learn_every=1), learn_burst=8)


def test_learn_phase_predicate_and_parity():
    """learn_phase=p shifts the spread schedule by p ticks (the many-group
    load-stagger — SCALING.md 100k serving shape); host and device agree
    record for record, and the burst schedule shifts identically."""
    import dataclasses

    cfg = dataclasses.replace(cadence_cfg(learn_every=4, learn_full_until=8),
                              learn_phase=2)
    flags = [bool(cfg.learns_on(i)) for i in range(40)]
    assert all(flags[:8])  # maturity window unaffected by phase
    for i in range(8, 40):
        assert flags[i] == (i % 4 == 2), i

    bcfg = dataclasses.replace(cfg, learn_burst=3)
    bflags = [bool(bcfg.learns_on(i)) for i in range(60)]
    assert all(bflags[:8])
    for i in range(8, 60):
        assert bflags[i] == ((i - 8 - 2) % 12 < 3), i

    cpu = HTMModel(cfg, seed=3, backend="cpu")
    tpu = HTMModel(cfg, seed=3, backend="tpu")
    vals = make_vals(40, 1)
    for i in range(40):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"


def test_learn_phase_zero_is_unchanged_and_bounds_enforced():
    import dataclasses

    for k, fu in ((1, 0), (4, 20)):
        cfg = cadence_cfg(learn_every=k, learn_full_until=fu)
        cfgp = dataclasses.replace(cfg, learn_phase=0)
        for i in range(60):
            assert bool(cfgp.learns_on(i)) == bool(cfg.learns_on(i))
    with pytest.raises(ValueError, match="learn_phase"):
        dataclasses.replace(cadence_cfg(learn_every=4), learn_phase=4)
    with pytest.raises(ValueError, match="learn_phase"):
        dataclasses.replace(cadence_cfg(learn_every=1), learn_phase=1)
    with pytest.raises(ValueError, match="learn_phase"):
        dataclasses.replace(cadence_cfg(learn_every=4), learn_phase=-1)


def test_registry_stagger_assigns_phases_and_shifts_learning():
    """stagger_learn: group i gets learn_phase i%k; a staggered group's
    device state is bit-identical to an unstaggered group run with the
    same explicitly-phased config (the stagger is pure config plumbing)."""
    import dataclasses

    from rtap_tpu.service.registry import StreamGroupRegistry

    cfg = cadence_cfg(learn_every=2, learn_full_until=0)
    reg = StreamGroupRegistry(cfg, group_size=2, backend="tpu",
                              stagger_learn=True)
    for i in range(6):
        reg.add_stream(f"s{i}")
    reg.finalize(reserve=2)  # one extra all-pad group: staggered too
    phases = [g.cfg.learn_phase for g in reg.groups]
    assert phases == [0, 1, 0, 1]

    # behavioral check: the phase-1 group does NOT learn on tick 0
    vals = make_vals(4, 2)
    ref_cfg = dataclasses.replace(cfg, learn_phase=1)
    from rtap_tpu.service.registry import StreamGroup

    ref = StreamGroup(ref_cfg, ["s2", "s3"], seed=reg.groups[1].seed,
                      backend="tpu")
    got = reg.groups[1]
    for i in range(4):
        ref.tick(vals[i], 1_700_000_000 + i)
        got.tick(vals[i], 1_700_000_000 + i)
    import jax as _jax

    a = _jax.device_get(ref.state)
    b = _jax.device_get(got.state)
    for k in ("perm", "presyn", "syn_perm", "tm_iter"):
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)


def test_stagger_off_or_fullrate_is_inert():
    from rtap_tpu.service.registry import StreamGroupRegistry

    cfg = cadence_cfg(learn_every=1, learn_full_until=0)
    reg = StreamGroupRegistry(cfg, group_size=2, backend="tpu",
                              stagger_learn=True)  # k=1: nothing to stagger
    for i in range(4):
        reg.add_stream(f"s{i}")
    reg.finalize()
    assert [g.cfg.learn_phase for g in reg.groups] == [0, 0]
    assert not reg.stagger_learn


def test_stagger_with_burst_levels_learning_load():
    """stagger_learn x learn_burst: phases offset whole B-tick bursts
    ((gi mod k) * B), so every post-maturity tick carries exactly 1/k of
    the fleet's learning — the spike-leveling the flag exists for (a
    [0, k) phase would leave most of the k*B cycle unstaggered)."""
    import dataclasses

    from rtap_tpu.service.registry import StreamGroupRegistry

    cfg = dataclasses.replace(
        cadence_cfg(learn_every=4, learn_full_until=0), learn_burst=3)
    reg = StreamGroupRegistry(cfg, group_size=1, backend="tpu",
                              stagger_learn=True)
    for i in range(8):
        reg.add_stream(f"s{i}")
    reg.finalize()
    assert [g.cfg.learn_phase for g in reg.groups] == [0, 3, 6, 9, 0, 3, 6, 9]
    # per-tick learning-group count is flat at n_groups/k
    for it in range(48):
        learning = sum(bool(g.cfg.learns_on(it)) for g in reg.groups)
        assert learning == 2, (it, learning)
    # and burst structure survives per group: 3 consecutive on, 9 off
    flags = [bool(reg.groups[1].cfg.learns_on(i)) for i in range(24)]
    assert flags[3:6] == [True] * 3 and sum(flags[:12]) == 3
