"""Pallas dendrite-activity kernel parity (ops/pallas_tm.py).

Runs the kernel in interpreter mode on the CPU test backend and asserts
bit-identical counts against the XLA formulation, then end-to-end: tm_step
with the kernel enabled must reproduce the oracle state exactly, including
in the quantized permanence domain.
"""

import dataclasses

import numpy as np
import pytest

import rtap_tpu.ops.pallas_tm as pallas_tm
from rtap_tpu.config import ModelConfig, RDSEConfig, SPConfig, TMConfig
from rtap_tpu.models.htm_model import HTMModel


def small_cfg(perm_bits: int = 0, K: int = 8, S: int = 4, M: int = 16) -> ModelConfig:
    return ModelConfig(
        rdse=RDSEConfig(size=128, active_bits=11, resolution=0.7),
        sp=SPConfig(columns=256, num_active_columns=10, perm_bits=perm_bits),
        tm=TMConfig(cells_per_column=K, activation_threshold=6, min_threshold=4,
                    max_segments_per_cell=S, max_synapses_per_segment=M,
                    new_synapse_count=8, learn_cap=48, perm_bits=perm_bits),
    )


def test_kernel_matches_xla_formulation():
    import jax.numpy as jnp

    from rtap_tpu.models.perm import tm_domain
    from rtap_tpu.ops.pallas_tm import dendrite_activity_pallas
    from rtap_tpu.ops.tm_tpu import _presyn_active_packed

    rng = np.random.default_rng(5)
    for C, K, S, M, Ac in [(64, 8, 4, 12, 10), (32, 4, 2, 7, 6), (16, 32, 2, 5, 5)]:
        N = C * K
        presyn = rng.integers(-1, N, (C, K, S, M), dtype=np.int32)
        presyn[rng.random(presyn.shape) < 0.5] = -1
        perm = rng.random((C, K, S, M), dtype=np.float32)
        cols = np.sort(rng.choice(C, Ac, replace=False)).astype(np.int32)
        masks = rng.integers(1, 1 << K if K < 31 else (1 << 31) - 1,
                             Ac, dtype=np.int64).astype(np.int32)
        conn, pot = dendrite_activity_pallas(
            jnp.asarray(presyn), jnp.asarray(perm), jnp.asarray(cols),
            jnp.asarray(masks), 0.5, interpret=True,
        )
        syn_act = _presyn_active_packed(
            jnp.asarray(presyn), jnp.asarray(cols), jnp.asarray(masks), K
        )
        ref_pot = np.asarray(syn_act.sum(-1))
        ref_conn = np.asarray((syn_act & (jnp.asarray(perm) >= 0.5)).sum(-1))
        np.testing.assert_array_equal(np.asarray(pot), ref_pot, err_msg=f"{C},{K}")
        np.testing.assert_array_equal(np.asarray(conn), ref_conn, err_msg=f"{C},{K}")


@pytest.mark.parametrize("perm_bits", [0, 16])
def test_tm_step_with_pallas_matches_oracle(perm_bits, monkeypatch):
    """Full pipeline with the Pallas dendrite pass: bit-exact vs the oracle
    through 250 learned steps (burst, growth, eviction, death paths)."""
    import jax

    monkeypatch.setattr(pallas_tm, "USE_PALLAS", True)
    cfg = small_cfg(perm_bits)
    cpu = HTMModel(cfg, seed=7, backend="cpu")
    dev = HTMModel(cfg, seed=7, backend="tpu")
    t = np.arange(250)
    vals = (50 + 20 * np.sin(2 * np.pi * t / 50.0)
            + np.random.default_rng(3).normal(0, 2, 250)).astype(np.float32)
    vals[125] += 40
    for i in range(250):
        r1 = cpu.run(1_700_000_000 + 300 * i, float(vals[i]))
        r2 = dev.run(1_700_000_000 + 300 * i, float(vals[i]))
        assert r1.raw_score == r2.raw_score, f"step {i}"
    got = jax.device_get(dev._runner.state)
    for k in ("presyn", "syn_perm", "seg_last", "active_seg", "matching_seg",
              "seg_pot", "prev_active", "prev_winner"):
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(cpu.state[k]), err_msg=k)
    assert int(got["tm_overflow"]) == 0


def test_pallas_under_vmap(monkeypatch):
    """group_step (vmapped tm_step) with the kernel on == kernel off."""
    import jax
    import jax.numpy as jnp

    from rtap_tpu.models.state import init_state
    from rtap_tpu.ops.step import group_step, replicate_state

    cfg = small_cfg(16)
    G, n = 3, 60
    rng = np.random.default_rng(11)
    vals = (30 + 10 * rng.random((n, G))).astype(np.float32)

    def run():
        state = jax.device_put(replicate_state(init_state(cfg, seed=5), G))
        raws = []
        for i in range(n):
            ts = jnp.full(G, 1_700_000_000 + i, jnp.int32)
            state, raw = group_step(state, jnp.asarray(vals[i][:, None]), ts, cfg)
            raws.append(np.asarray(raw))
        return np.stack(raws), jax.device_get(state)

    monkeypatch.setattr(pallas_tm, "USE_PALLAS", False)
    raw_off, st_off = run()
    group_step.clear_cache()
    monkeypatch.setattr(pallas_tm, "USE_PALLAS", True)
    raw_on, st_on = run()
    group_step.clear_cache()
    np.testing.assert_array_equal(raw_on, raw_off)
    for k in ("presyn", "syn_perm", "seg_pot", "active_seg"):
        np.testing.assert_array_equal(st_on[k], st_off[k], err_msg=k)


def test_guards_reject_oversized_shapes():
    """VMEM budget (unblocked v1 kernel) and interpreter-size guards fail
    loudly instead of hanging/failing deep inside Mosaic."""
    import jax.numpy as jnp

    from rtap_tpu.config import nab_preset
    from rtap_tpu.models.state import init_state
    from rtap_tpu.ops.pallas_tm import dendrite_activity_pallas

    st = init_state(nab_preset(), seed=0)
    ids = jnp.arange(10, dtype=jnp.int32)
    masks = jnp.ones(10, jnp.int32)
    with pytest.raises(ValueError, match="VMEM|INTERPRETER"):
        dendrite_activity_pallas(
            jnp.asarray(st["presyn"]), jnp.asarray(st["syn_perm"]),
            ids, masks, 0.5,
        )
    # the VMEM guard specifically (interpret=False skips the interpreter one)
    with pytest.raises(ValueError, match="VMEM"):
        dendrite_activity_pallas(
            jnp.asarray(st["presyn"]), jnp.asarray(st["syn_perm"]),
            ids, masks, 0.5, interpret=False,
        )


def test_set_use_pallas_clears_caches():
    import rtap_tpu.ops.pallas_tm as pt

    pt.set_use_pallas(True)
    assert pt.use_pallas() is True
    pt.set_use_pallas(None)
    assert pt.use_pallas() in (False, True)  # env-dependent default
