"""Pallas TM-learning megakernel parity (ops/pallas_tm.py).

RTAP_TM_SCATTER=pallas fuses the whole TM learning pass (alloc, reinforce,
grow/evict, punish, death, dendrite counts) into one kernel. These tests run
it in interpreter mode on the CPU test backend and assert bit-identical
behavior against the numpy oracle — full state, every step — through the
same branch-coverage sequences the workspace-path parity uses, in both
permanence domains and under vmap (the group_step shape).
"""

import numpy as np
import pytest

import rtap_tpu.ops.tm_tpu as tm_tpu
from rtap_tpu.config import ModelConfig, RDSEConfig, SPConfig, TMConfig
from rtap_tpu.models.htm_model import HTMModel


def small_cfg(perm_bits: int = 0, K: int = 8, S: int = 4, M: int = 16) -> ModelConfig:
    # col_cap pinned to the winner count: the megakernel's winner loops
    # unroll W = col_cap * K times, and the interpreter pays every
    # unrolled iteration at CPU-compile time — the default 40 is a
    # hardware-preset bound, pathological for interpreter tests
    return ModelConfig(
        rdse=RDSEConfig(size=128, active_bits=11, resolution=0.7),
        sp=SPConfig(columns=256, num_active_columns=10, perm_bits=perm_bits),
        tm=TMConfig(cells_per_column=K, activation_threshold=6, min_threshold=4,
                    max_segments_per_cell=S, max_synapses_per_segment=M,
                    new_synapse_count=8, learn_cap=48, col_cap=10,
                    perm_bits=perm_bits),
    )


@pytest.fixture
def pallas_scatter():
    tm_tpu.set_scatter_mode("pallas")
    yield
    tm_tpu.set_scatter_mode(None)


def _run_tm_parity(C, cfg, sequences, learn=True):
    from tests.parity.test_tm_parity import (
        TM_KEYS, _assert_state_equal, _init_tm_state,
    )
    import copy

    import jax.numpy as jnp

    from rtap_tpu.models.oracle.temporal_memory import TMOracle
    from rtap_tpu.ops.tm_tpu import from_kernel_layout, tm_step, to_kernel_layout

    host = _init_tm_state(C, cfg)
    dev = to_kernel_layout({k: jnp.asarray(v) for k, v in copy.deepcopy(host).items()})
    oracle = TMOracle(host, cfg)
    for step, cols in enumerate(sequences):
        active = np.zeros(C, bool)
        active[cols] = True
        raw_host = oracle.compute(active, learn=learn)
        dev, raw_dev = tm_step(dev, jnp.asarray(active), cfg, learn=learn)
        assert abs(raw_host - float(raw_dev)) < 1e-6, f"raw score step {step}"
        _assert_state_equal(host, from_kernel_layout(dev, cfg), step)
    assert TM_KEYS  # imported for completeness


@pytest.mark.quick
def test_tm_parity_megakernel_repeating_and_novel(pallas_scatter):
    """Repetition (reinforce/grow) + novelty (burst alloc, eviction): the
    branch mix of the crown-jewel TM parity, through the megernel."""
    C = 64
    cfg = TMConfig(
        cells_per_column=8, activation_threshold=3, min_threshold=2,
        max_segments_per_cell=4, max_synapses_per_segment=12,
        new_synapse_count=6, learn_cap=32, col_cap=6,
    )
    rng = np.random.default_rng(11)
    pats = [rng.choice(C, size=5, replace=False) for _ in range(4)]
    seq = pats * 8 + [rng.choice(C, size=5, replace=False) for _ in range(24)]
    _run_tm_parity(C, cfg, seq)


def test_tm_parity_megakernel_eviction_and_punish(pallas_scatter):
    """Tiny pools force LRU segment eviction + weakest-synapse eviction;
    alternating near-miss patterns drive the punishment path."""
    C = 32
    cfg = TMConfig(
        cells_per_column=4, activation_threshold=2, min_threshold=1,
        max_segments_per_cell=2, max_synapses_per_segment=6,
        new_synapse_count=4, predicted_segment_decrement=0.02, learn_cap=32,
        col_cap=5,
    )
    rng = np.random.default_rng(23)
    X, Y = (rng.choice(C, size=4, replace=False) for _ in range(2))
    Y2 = Y.copy()
    Y2[:2] = rng.choice(C, size=2, replace=False)
    seq = [rng.choice(C, size=4, replace=False) for _ in range(60)]
    seq += ([X, Y] * 6 + [X, Y2] * 6) * 2
    _run_tm_parity(C, cfg, seq)


def test_tm_parity_megakernel_edge_columns(pallas_scatter):
    """Empty and all-columns-active steps through the megakernel."""
    C = 16
    cfg = TMConfig(
        cells_per_column=4, activation_threshold=2, min_threshold=1,
        max_segments_per_cell=2, max_synapses_per_segment=6,
        new_synapse_count=4, learn_cap=80, col_cap=16,
    )
    rng = np.random.default_rng(3)
    seq = [rng.choice(C, 3, replace=False), np.arange(C), np.array([], np.int64),
           rng.choice(C, 3, replace=False), np.arange(C)] * 4
    _run_tm_parity(C, cfg, seq)


@pytest.mark.parametrize("perm_bits", [
    # f32 rides the slow tier: the three TM-level parity tests above cover
    # the f32 arithmetic already, and the 250-step interpreter e2e costs
    # ~70 s of the tier-1 budget per domain — u16 (the production domain,
    # with the round/astype epilogue worth covering end-to-end) stays
    pytest.param(0, marks=pytest.mark.slow),
    16,
])
def test_e2e_with_megakernel_matches_oracle(perm_bits, pallas_scatter):
    """Full pipeline (encode -> SP -> TM) with the megakernel: bit-exact
    vs the oracle through 250 learned steps incl. an anomaly spike."""
    import jax

    cfg = small_cfg(perm_bits)
    cpu = HTMModel(cfg, seed=7, backend="cpu")
    dev = HTMModel(cfg, seed=7, backend="tpu")
    t = np.arange(250)
    vals = (50 + 20 * np.sin(2 * np.pi * t / 50.0)
            + np.random.default_rng(3).normal(0, 2, 250)).astype(np.float32)
    vals[125] += 40
    for i in range(250):
        r1 = cpu.run(1_700_000_000 + 300 * i, float(vals[i]))
        r2 = dev.run(1_700_000_000 + 300 * i, float(vals[i]))
        assert r1.raw_score == r2.raw_score, f"step {i}"
    got = jax.device_get(dev._runner.state)
    for k in ("presyn", "syn_perm", "seg_last", "active_seg", "matching_seg",
              "seg_pot", "prev_active", "prev_winner"):
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(cpu.state[k]), err_msg=k)
    assert int(got["tm_overflow"]) == 0


def test_megakernel_under_vmap(pallas_scatter):
    """group_step (vmapped tm_step) with the megakernel == without."""
    import jax
    import jax.numpy as jnp

    from rtap_tpu.models.state import init_state
    from rtap_tpu.ops.step import group_step, replicate_state

    cfg = small_cfg(16)
    G, n = 3, 50
    rng = np.random.default_rng(11)
    vals = (30 + 10 * rng.random((n, G))).astype(np.float32)

    def run():
        state = jax.device_put(replicate_state(init_state(cfg, seed=5), G))
        raws = []
        for i in range(n):
            ts = jnp.full(G, 1_700_000_000 + i, jnp.int32)
            state, raw = group_step(state, jnp.asarray(vals[i][:, None]), ts, cfg)
            raws.append(np.asarray(raw))
        return np.stack(raws), jax.device_get(state)

    raw_on, st_on = run()
    tm_tpu.set_scatter_mode(None)  # back to the process default (matmul)
    raw_off, st_off = run()
    np.testing.assert_array_equal(raw_on, raw_off)
    for k in ("presyn", "syn_perm", "seg_pot", "active_seg"):
        np.testing.assert_array_equal(st_on[k], st_off[k], err_msg=k)


def test_megakernel_rejects_incompatible_strategies(pallas_scatter):
    """forward dendrite and compact sweep cannot combine with the
    megakernel — tm_step must refuse loudly, not silently diverge."""
    import jax.numpy as jnp

    from tests.parity.test_tm_parity import _init_tm_state

    cfg = TMConfig(
        cells_per_column=4, activation_threshold=2, min_threshold=1,
        max_segments_per_cell=2, max_synapses_per_segment=6,
        new_synapse_count=4, learn_cap=16, col_cap=4,
    )
    C = 16
    state = {k: jnp.asarray(v) for k, v in _init_tm_state(C, cfg).items()}
    active = jnp.zeros(C, bool)
    tm_tpu.set_sweep_mode("compact")
    try:
        with pytest.raises(ValueError, match="SWEEP=compact"):
            tm_tpu.tm_step(
                tm_tpu.to_kernel_layout(state), active, cfg, learn=True)
    finally:
        tm_tpu.set_sweep_mode(None)
    tm_tpu.set_dendrite_mode("forward")
    try:
        with pytest.raises(ValueError, match="DENDRITE=forward"):
            tm_tpu.tm_step(
                tm_tpu.to_kernel_layout(state), active, cfg, learn=True)
    finally:
        tm_tpu.set_dendrite_mode(None)


def test_megakernel_guards_reject_oversized_shapes(pallas_scatter):
    """Interpreter-size / winner-unroll / VMEM guards fail loudly instead
    of hanging in the interpreter or deep inside Mosaic."""
    import jax.numpy as jnp

    from rtap_tpu.config import nab_preset
    from rtap_tpu.models.state import init_state
    from rtap_tpu.ops.tm_tpu import to_kernel_layout, tm_step

    cfg = nab_preset()
    st = to_kernel_layout(
        {k: jnp.asarray(v) for k, v in init_state(cfg, seed=0).items()
         if k not in ("potential", "perm", "boost", "overlap_duty",
                      "active_duty", "sp_iter", "enc_offset", "enc_bound",
                      "enc_resolution")})
    active = jnp.zeros(cfg.sp.columns, bool)
    with pytest.raises(ValueError, match="INTERPRETER|winner-list|VMEM"):
        tm_step(st, active, cfg.tm, learn=True)


@pytest.mark.quick
def test_pallas_mode_actually_dispatches_tm_learn_pallas(pallas_scatter, monkeypatch):
    """The twin-registry pin for tm_learn_pallas: RTAP_TM_SCATTER=pallas
    must route the learning pass through the megakernel entry point —
    if the mode switch silently fell back to the workspace path, every
    'pallas parity' test above would be vacuously green."""
    import rtap_tpu.ops.pallas_tm as pallas_tm

    calls = []
    real = pallas_tm.tm_learn_pallas

    def counting(*args, **kw):
        calls.append(1)
        return real(*args, **kw)

    monkeypatch.setattr(pallas_tm, "tm_learn_pallas", counting)
    C = 32
    cfg = TMConfig(
        cells_per_column=4, activation_threshold=2, min_threshold=1,
        max_segments_per_cell=2, max_synapses_per_segment=8,
        new_synapse_count=4, learn_cap=16, col_cap=4,
    )
    _run_tm_parity(C, cfg, [np.arange(4), np.arange(4)])
    assert calls, "pallas scatter mode never reached tm_learn_pallas"
