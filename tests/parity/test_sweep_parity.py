"""RTAP_TM_SWEEP=compact parity: the gather/punish/death-on-touched-rows
formulation must be bit-identical to the dense full-pool sweeps (which are
themselves pinned to the oracle by test_e2e_parity.py).

The compact sweep's correctness argument (ops/tm_tpu.py): synapse death can
only newly occur on rows whose permanences moved this step — the <= learn_cap
workspace rows and the <= punish_cap punished rows — because the previous
learn step's death pass already removed every perm<=0 synapse and inference
steps never move permanences. These tests check the equivalence end-to-end
(vs the oracle) and state-for-state (compact vs dense on the same inputs),
in all permanence domains and under the other kernel strategy switches.
"""

import dataclasses

import numpy as np
import pytest

import rtap_tpu.ops.tm_tpu as tm_tpu
from rtap_tpu.models.htm_model import HTMModel

from tests.parity.test_e2e_parity import exact_only, make_values, small_cfg


@pytest.fixture
def compact_sweep():
    tm_tpu.set_sweep_mode("compact")
    yield
    tm_tpu.set_sweep_mode(None)


def _cfg(perm_bits: int):
    if perm_bits == 0:
        return small_cfg()
    from tests.parity.test_quantized_parity import quant_cfg

    return quant_cfg(perm_bits)


@exact_only
@pytest.mark.parametrize("perm_bits", [0, 16, 8])
def test_e2e_parity_compact_sweep(compact_sweep, perm_bits):
    cfg = _cfg(perm_bits)
    cpu = HTMModel(cfg, seed=3, backend="cpu")
    tpu = HTMModel(cfg, seed=3, backend="tpu")
    vals = make_values(300, 1)
    for i in range(300):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"


@exact_only
@pytest.mark.parametrize("scatter", ["matmul", "indexed"])
def test_e2e_parity_compact_sweep_all_strategies(compact_sweep, scatter):
    """Compact sweep under both workspace-movement strategies + flat layout +
    TPU compact-ids paths — the full hardware-candidate matrix."""
    old = tm_tpu.FORCE_TPU_PATHS
    tm_tpu.FORCE_TPU_PATHS = True
    tm_tpu.set_scatter_mode(scatter)
    tm_tpu.set_layout_mode("flat")
    try:
        cfg = _cfg(16)
        cpu = HTMModel(cfg, seed=7, backend="cpu")
        tpu = HTMModel(cfg, seed=7, backend="tpu")
        vals = make_values(300, 1, seed=17)
        for i in range(300):
            r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
            r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
            assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"
    finally:
        tm_tpu.FORCE_TPU_PATHS = old
        tm_tpu.set_scatter_mode(None)
        tm_tpu.set_layout_mode(None)


@pytest.mark.quick
@exact_only
def test_compact_vs_dense_full_state():
    """Same inputs through compact-sweep and dense-sweep device models ->
    bit-identical FULL state (not just scores), including after punishment
    and death events. Inference interludes check the perms-don't-move
    invariant the equivalence rests on. (Each variant runs straight through
    under one mode — a per-step mode flip would clear the jit caches 700x.)"""
    import jax

    cfg = small_cfg()
    vals = make_values(350, 1, seed=23)

    def run_mode(mode):
        tm_tpu.set_sweep_mode(mode)
        try:
            m = HTMModel(cfg, seed=11, backend="tpu")
            raws = [
                m.run(1_700_000_000 + 300 * i, float(vals[i, 0]),
                      learn=(i % 10) < 8).raw_score  # inference interludes
                for i in range(350)
            ]
            return raws, jax.device_get(m._runner.state)
        finally:
            tm_tpu.set_sweep_mode(None)

    raws_c, a = run_mode("compact")
    raws_d, b = run_mode(None)
    assert raws_c == raws_d
    assert set(a) == set(b)
    for k in sorted(a):
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)
    assert int(a["tm_overflow"]) == 0


@exact_only
def test_punish_cap_overflow_counts(compact_sweep):
    """A punish_cap of 1 must trip the overflow counter (not crash, not
    silently drop): the counter is the contract that the capacity bound is
    observable."""
    import jax

    base = small_cfg()
    cfg = dataclasses.replace(base, tm=dataclasses.replace(base.tm, punish_cap=1))
    m = HTMModel(cfg, seed=5, backend="tpu")
    vals = make_values(400, 1, seed=31)
    for i in range(400):
        m.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
    overflow = int(jax.device_get(m._runner.state)["tm_overflow"])
    assert overflow > 0
