"""End-to-end parity: CPU oracle vs fused TPU step (SURVEY.md §4 item 2).

The upstream pattern this replicates is NuPIC's
spatial_pooler_compatibility_test.py — run the Python and C++ implementations
side by side with identical seeds and assert identical state. Here the pair is
(numpy oracle pipeline) vs (single fused jitted device program), and parity
must hold through the full encode -> SP -> TM -> raw-score composition, not
just per kernel.
"""

import jax as _jax
import numpy as np
import pytest

from rtap_tpu.config import ModelConfig, RDSEConfig, DateConfig, SPConfig, TMConfig, cluster_preset
from rtap_tpu.models.htm_model import HTMModel

N_RECORDS = 400

# Bit-exactness holds only when both backends run the same arithmetic: real
# TPU f32 division rounds 1 ulp differently from host numpy (verify SKILL.md
# gotcha). conftest.py forces the CPU platform under pytest; this guard keeps
# the exact assertions honest if the file is ever run outside that harness.
exact_only = pytest.mark.skipif(
    _jax.devices()[0].platform != "cpu",
    reason="bit-exact parity is asserted on the CPU test backend only",
)


def small_cfg(n_fields: int = 1) -> ModelConfig:
    # Small enough to run 400 steps fast on the CPU test backend, big enough
    # to exercise bursting, segment growth, LRU eviction, and date bits.
    return ModelConfig(
        rdse=RDSEConfig(size=128, active_bits=11, resolution=0.7),
        date=DateConfig(time_of_day_width=7, time_of_day_size=18, weekend_width=3),
        sp=SPConfig(columns=256, num_active_columns=10),
        tm=TMConfig(cells_per_column=8, activation_threshold=6, min_threshold=4,
                    max_segments_per_cell=4, max_synapses_per_segment=16,
                    new_synapse_count=8, learn_cap=48),
        n_fields=n_fields,
    )


def make_values(n, n_fields, seed=7):
    rng = np.random.Generator(np.random.Philox(key=(seed, 1)))
    t = np.arange(n)[:, None]
    base = 50 + 20 * np.sin(2 * np.pi * t / 60.0 + np.arange(n_fields)[None, :])
    vals = (base + rng.normal(0, 2.0, (n, n_fields))).astype(np.float32)
    vals[n // 2, :] += 40.0  # a spike so raw scores actually move
    vals[10, 0] = np.nan  # missing sample path
    return vals


@exact_only
@pytest.mark.parametrize("n_fields", [1, 3])
def test_e2e_raw_score_parity(n_fields):
    cfg = small_cfg(n_fields)
    cpu = HTMModel(cfg, seed=3, backend="cpu")
    tpu = HTMModel(cfg, seed=3, backend="tpu")
    vals = make_values(N_RECORDS, n_fields)
    ts0 = 1_700_000_000
    for i in range(N_RECORDS):
        v = vals[i] if n_fields > 1 else float(vals[i, 0])
        r_cpu = cpu.run(ts0 + 300 * i, v)
        r_tpu = tpu.run(ts0 + 300 * i, v)
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"
        assert r_cpu.log_likelihood == pytest.approx(r_tpu.log_likelihood, rel=1e-9), f"step {i}"


@pytest.mark.quick
@exact_only
def test_e2e_state_parity_exact():
    """After N steps, the full device state matches the oracle bit-for-bit."""
    import jax

    cfg = small_cfg()
    cpu = HTMModel(cfg, seed=11, backend="cpu")
    tpu = HTMModel(cfg, seed=11, backend="tpu")
    vals = make_values(200, 1)
    for i in range(200):
        cpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
        tpu.run(1_700_000_000 + 300 * i, float(vals[i, 0]))
    dev = jax.device_get(tpu._runner.state)
    for k in ("perm", "boost", "overlap_duty", "active_duty", "presyn", "syn_perm",
              "seg_last", "active_seg", "matching_seg", "seg_pot", "prev_active",
              "prev_winner", "enc_offset"):
        np.testing.assert_array_equal(np.asarray(dev[k]), np.asarray(cpu.state[k]), err_msg=k)
    assert int(dev["tm_overflow"]) == 0


@exact_only
def test_group_step_matches_single():
    """group_step over G streams == G independent single-stream runs."""
    import jax
    import jax.numpy as jnp

    from rtap_tpu.models.state import init_state
    from rtap_tpu.ops.step import fused_step, group_step, replicate_state

    cfg = cluster_preset()
    G, n = 4, 150
    base = init_state(cfg, seed=5)
    gstate = jax.device_put(replicate_state(base, G))
    singles = [jax.device_put(init_state(cfg, seed=5)) for _ in range(G)]

    rng = np.random.Generator(np.random.Philox(key=(9, 9)))
    vals = (30 + 10 * rng.random((n, G))).astype(np.float32)
    vals[60, 2] += 50.0

    for i in range(n):
        ts = np.full(G, 1_700_000_000 + i, np.int32)
        gstate, graw = group_step(gstate, jnp.asarray(vals[i][:, None]), jnp.asarray(ts), cfg)
        for g in range(G):
            singles[g], raw = fused_step(
                singles[g], jnp.asarray(vals[i, g : g + 1]), jnp.int32(ts[g]), cfg
            )
            assert float(raw) == float(graw[g]), f"step {i} stream {g}"
