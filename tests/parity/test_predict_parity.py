"""Predictive-horizon reducer parity: predict_update ≡ its numpy twin.

ISSUE 16 acceptance. ``predict_update`` (ops/predict_tpu.py) runs
INSIDE the fused device step when a group is built with ``predict=k``;
``predict_update_host`` (models/oracle/predict.py) is its numpy twin on
the public [G, ...] layout. The pair must be BIT-EXACT — the leaf's
EWMA uses a power-of-two alpha so float32 folding is associative-free —
across every served branch: the vmapped group path (tick and chunk),
both backends, and the quantized u8/u16 permanence domains. The twin
registry (rtap-lint v3) resolves the ``# rtap: twin[...]`` annotation
against this file.
"""

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset, scaled_cluster_preset
from rtap_tpu.models.oracle.predict import (
    PREDICT_KEYS,
    predict_from_states,
    predict_horizon_of,
    predict_nbytes,
    predict_update_host,
)
from rtap_tpu.service.registry import StreamGroup

CFG = scaled_cluster_preset(32)
G, K_HORIZON = 4, 3


def _feed(T, G, key=(7, 1)):
    rng = np.random.Generator(np.random.Philox(key=key))
    vals = (30 + 5 * rng.random((T, G))).astype(np.float32)
    ts = np.tile(1_700_000_000 + np.arange(T)[:, None],
                 (1, G)).astype(np.int64)
    return vals, ts


def _group(cfg=CFG, backend="tpu", predict=K_HORIZON):
    return StreamGroup(cfg, [f"s{i}" for i in range(G)], backend=backend,
                      predict=predict)


# ------------------------------------------------ device ≡ twin, vmapped --
def test_predict_update_matches_host_twin_vmapped_chunk():
    """predict_update inside the fused chunk (the vmapped group path)
    vs the numpy twin replayed over the SAME pre-step state: every leaf
    bit-exact, every tick of the chunk."""
    T = 10
    vals, ts = _feed(T, G)
    grp = _group()
    # replay the twin tick by tick against the public state snapshots
    twin_leaves = []
    host = {k: np.array(v) for k, v in grp.state.items()}
    for t in range(T):
        # the twin consumes the PRE-step TM state like the device kernel
        # (prev_active/active_seg are the step's own outputs, already in
        # the post-step state it reads) — run the real step, then fold
        r, _ll, _al = grp.run_chunk(vals[t:t + 1], ts[t:t + 1])
        host = {k: np.array(v) for k, v in grp.state.items()}
        # rewind the twin's OWN pred leaves: the device already folded
        # this tick, so hand the twin the previous ring/ewma
        host["pred_ring"] = twin_ring if t else np.zeros_like(
            np.asarray(grp.state["pred_ring"]))
        host["pred_miss_ewma"] = twin_ewma if t else np.full(
            (G,), np.nan, np.float32)
        out_state, leaf = predict_update_host(host, vals[t][:, None], CFG)
        twin_ring = out_state["pred_ring"]
        twin_ewma = out_state["pred_miss_ewma"]
        twin_leaves.append(leaf)
    assert grp.last_predict is not None
    for k in PREDICT_KEYS:
        np.testing.assert_array_equal(
            np.asarray(grp.last_predict[k][-1]),
            np.asarray(twin_leaves[-1][k]), err_msg=k)
    # the state rings themselves converged bit-exactly
    np.testing.assert_array_equal(
        np.asarray(grp.state["pred_ring"]), twin_ring)
    np.testing.assert_array_equal(
        np.asarray(grp.state["pred_miss_ewma"]).astype(np.float32),
        twin_ewma.astype(np.float32))


@pytest.mark.parametrize("micro", [1, 4])
def test_predict_tick_and_chunk_branches_agree(micro):
    """The per-tick dispatch branch and the scanned chunk branch fold
    the same leaves (per-branch parity): one group stepped tick by tick
    vs one fed the same T rows in chunks."""
    T = 8
    vals, ts = _feed(T, G, key=(7, 2))
    a, b = _group(), _group()
    last_a = None
    for t in range(T):
        a.tick(vals[t], int(ts[t, 0]))
        last_a = {k: np.asarray(v) for k, v in a.last_predict.items()}
    for t0 in range(0, T, micro):
        b.run_chunk(vals[t0:t0 + micro], ts[t0:t0 + micro])
    for k in PREDICT_KEYS:
        np.testing.assert_array_equal(
            last_a[k][-1], np.asarray(b.last_predict[k][-1]), err_msg=k)
    for k in ("pred_ring", "pred_miss_ewma", "pred_tick0"):
        np.testing.assert_array_equal(
            np.asarray(a.state[k]), np.asarray(b.state[k]), err_msg=k)


def test_predict_cpu_backend_matches_tpu():
    """The CPU backend's twin-driven fold (predict_from_states) and the
    device reducer produce identical leaves on identical input."""
    T = 8
    vals, ts = _feed(T, G, key=(7, 3))
    dev, host = _group(backend="tpu"), _group(backend="cpu")
    for t in range(T):
        dev.run_chunk(vals[t:t + 1], ts[t:t + 1])
        host.run_chunk(vals[t:t + 1], ts[t:t + 1])
    for k in PREDICT_KEYS:
        np.testing.assert_array_equal(
            np.asarray(dev.last_predict[k][-1]),
            np.asarray(host.last_predict[k][-1]), err_msg=k)


# ------------------------------------------------- quantized perm domains --
@pytest.mark.parametrize("perm_bits", [0, 8, 16])
def test_predict_parity_quantized_perm_domains(perm_bits):
    """f32/u8/u16 permanence domains change the TM's internal dtype but
    not the reducer contract: device leaves still match the twin
    bit-exactly (the reducer reads activity masks, never permanences —
    this pins that it STAYS that way)."""
    cfg = scaled_cluster_preset(32, perm_bits=perm_bits)
    T = 6
    vals, ts = _feed(T, G, key=(7, perm_bits))
    dev, host = _group(cfg=cfg), _group(cfg=cfg, backend="cpu")
    for t in range(T):
        dev.run_chunk(vals[t:t + 1], ts[t:t + 1])
        host.run_chunk(vals[t:t + 1], ts[t:t + 1])
    for k in PREDICT_KEYS:
        np.testing.assert_array_equal(
            np.asarray(dev.last_predict[k][-1]),
            np.asarray(host.last_predict[k][-1]), err_msg=k)


# --------------------------------------------------------- leaf contract --
def test_predict_leaf_schema_and_nbytes():
    grp = _group()
    vals, ts = _feed(2, G, key=(7, 9))
    grp.run_chunk(vals, ts)
    leaf = grp.last_predict
    assert sorted(leaf) == sorted(PREDICT_KEYS)
    assert np.asarray(leaf["overlap"]).dtype == np.float32
    assert np.asarray(leaf["miss_ewma"]).dtype == np.float32
    assert np.asarray(leaf["pred_col_frac"]).dtype == np.float32
    assert np.asarray(leaf["scored"]).dtype == np.bool_
    assert predict_nbytes(G) == G * 13
    assert predict_horizon_of(grp.state) == K_HORIZON


def test_predict_off_leaves_absent_and_state_identical():
    """predict=0 (the default): no pred_* leaves, no predict output, and
    the model state is bit-identical to a predict=k run's non-pred
    leaves — the reducer is a pure read."""
    T = 6
    vals, ts = _feed(T, G, key=(7, 4))
    off, on = _group(predict=0), _group(predict=K_HORIZON)
    for t in range(T):
        off.run_chunk(vals[t:t + 1], ts[t:t + 1])
        on.run_chunk(vals[t:t + 1], ts[t:t + 1])
    assert off.last_predict is None
    assert "pred_ring" not in off.state
    for k in off.state:
        np.testing.assert_array_equal(
            np.asarray(off.state[k]), np.asarray(on.state[k]), err_msg=k)


def test_predict_from_states_matches_group_fold():
    """The single-model stacking helper (the CPU service path) agrees
    with one big vmapped group on the same inputs."""
    from rtap_tpu.models.state import init_state

    cfg = cluster_preset()
    states = [init_state(cfg, seed=i, predict_horizon=K_HORIZON)
              for i in range(2)]
    vals = np.asarray([31.0, 44.0], np.float32)[:, None]
    leaf = predict_from_states(states, vals, cfg)
    assert sorted(leaf) == sorted(PREDICT_KEYS)
    assert leaf["scored"].shape == (2,)
    # tick 0: nothing can be scored yet (warm-up covers the zeroed ring)
    assert not leaf["scored"].any()
