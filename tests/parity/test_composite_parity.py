"""Oracle-vs-device parity for the ISSUE 9 composite encoder family.

Every new encoder kind (categorical, delta, composite multi-field) must
be bit-identical across host numpy and jitted JAX, exactly like the
uniform RDSE family test_encoder_parity.py pins: the cpu oracle IS the
reference for every committed eval artifact and the crash/replay
bit-exactness story, so a single diverging bit breaks the repo's
central contract.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rtap_tpu.config import (
    CompositeEncoderConfig,
    DateConfig,
    FieldSpec,
    ModelConfig,
)
from rtap_tpu.models.oracle.encoders import categorical_bits, encode_record
from rtap_tpu.ops.encoders_tpu import encode_device

#: one of each kind + per-field geometry that differs field to field, so
#: a layout-offset bug cannot hide behind uniform sizes
COMPOSITE = CompositeEncoderConfig(fields=(
    FieldSpec(name="value", kind="rdse", size=96, active_bits=9,
              resolution=0.5, seed=3),
    FieldSpec(name="delta", kind="delta", size=64, active_bits=7,
              resolution=0.25, seed=3),
    FieldSpec(name="event_class", kind="categorical", size=80,
              active_bits=5, seed=3),
))


def _cfg(date=DateConfig(time_of_day_width=5, time_of_day_size=13,
                         weekend_width=3)) -> ModelConfig:
    return ModelConfig(n_fields=3, composite=COMPOSITE, date=date)


def _dev(cfg):
    return jax.jit(lambda v, t, o, r, p: encode_device(cfg, v, t, o, r, p))


def _host(cfg, values, ts, off, res, prev):
    return encode_record(cfg, values.astype(np.float64), int(ts), off, res,
                         prev)


@pytest.mark.quick
def test_composite_encode_parity_with_gaps():
    """Random walk with NaN gaps: every record must encode bit-identically,
    with the delta predecessor advanced by the SAME finite-hold rule on
    both sides."""
    cfg = _cfg()
    enc = _dev(cfg)
    rng = np.random.default_rng(7)
    off = rng.normal(size=3).astype(np.float32)
    res = np.asarray(cfg.field_resolutions(), np.float32)
    prev = np.full(3, np.nan, np.float32)  # state.py init: no predecessor
    for i in range(60):
        values = (rng.normal(size=3) * 8).astype(np.float32)
        values[2] = float(rng.integers(0, 40))  # category ids are whole
        if i % 6 == 0:
            values[rng.integers(3)] = np.nan  # missing sample
        ts = int(rng.integers(0, 2_000_000_000))
        host = _host(cfg, values, ts, off, res, prev)
        dev = np.asarray(enc(jnp.asarray(values), jnp.int32(ts),
                             jnp.asarray(off), jnp.asarray(res),
                             jnp.asarray(prev)))
        np.testing.assert_array_equal(host, dev, err_msg=f"record {i}")
        # the device step's own predecessor-advance rule (ops/step.py)
        prev = np.where(np.isfinite(values), values, prev).astype(np.float32)


@pytest.mark.quick
def test_delta_first_sample_encodes_as_missing_on_both_backends():
    """NuPIC DeltaEncoder: the first sample has no predecessor — the delta
    field contributes ZERO bits (on both backends), while the sibling
    fields encode normally."""
    cfg = _cfg(date=DateConfig(0, 0, 0))
    enc = _dev(cfg)
    values = np.asarray([5.0, 5.0, 2.0], np.float32)
    off = np.zeros(3, np.float32)
    res = np.asarray(cfg.field_resolutions(), np.float32)
    prev = np.full(3, np.nan, np.float32)
    host = _host(cfg, values, 0, off, res, prev)
    dev = np.asarray(enc(jnp.asarray(values), jnp.int32(0), jnp.asarray(off),
                         jnp.asarray(res), jnp.asarray(prev)))
    np.testing.assert_array_equal(host, dev)
    layout = cfg.field_layout()
    _n, _k, d_off, d_size = layout[1]
    assert host[d_off:d_off + d_size].sum() == 0, \
        "delta field must be silent without a predecessor"
    assert host.sum() > 0, "value/categorical fields must still encode"
    # second sample: the delta field lights up
    prev2 = values
    host2 = _host(cfg, np.asarray([9.0, 9.0, 2.0], np.float32), 0, off, res,
                  prev2)
    assert host2[d_off:d_off + d_size].sum() > 0


def test_categorical_extreme_ids_clamp_identically():
    """Wild category ids (garbage joins, 1e30 sensor noise) must clamp
    through the same double bound on both backends: the f32 bucket clamp,
    then the per-field categorical clamp that keeps the device's int32
    c*w + k from wrapping."""
    cfg = ModelConfig(n_fields=1, composite=CompositeEncoderConfig(fields=(
        FieldSpec(name="ev", kind="categorical", size=80, active_bits=5),)),
        date=DateConfig(0, 0, 0))
    enc = _dev(cfg)
    off = np.zeros(1, np.float32)
    res = np.asarray(cfg.field_resolutions(), np.float32)
    prev = np.full(1, np.nan, np.float32)
    for x in (0.0, 1.0, -1.0, 1e9, -1e9, 1e30, -1e30, 3.4e38):
        values = np.asarray([x], np.float32)
        host = _host(cfg, values, 0, off, res, prev)
        dev = np.asarray(enc(jnp.asarray(values), jnp.int32(0),
                             jnp.asarray(off), jnp.asarray(res),
                             jnp.asarray(prev)))
        np.testing.assert_array_equal(host, dev, err_msg=f"id {x}")


def test_categorical_ids_are_pairwise_near_disjoint():
    """The defining categorical property (vs the RDSE's deliberate
    neighbor overlap): adjacent ids share no hash keys, so their SDRs
    overlap only by hash coincidence."""
    spec = FieldSpec(name="ev", kind="categorical", size=256, active_bits=11)
    sdrs = []
    for c in range(8):
        s = np.zeros(spec.size, bool)
        s[categorical_bits(spec, c)] = True
        sdrs.append(s)
    for i in range(8):
        for j in range(i + 1, 8):
            assert int((sdrs[i] & sdrs[j]).sum()) <= 2, (i, j)


@pytest.mark.quick
def test_composite_layout_bits_stay_inside_their_field():
    """Layout round-trip: each field's active bits land inside its own
    field_layout() range — the invariant attribution's per-field decode
    and the docs/WORKLOADS.md layout table both rest on."""
    cfg = _cfg(date=DateConfig(0, 0, 0))
    layout = cfg.field_layout()
    assert [r[3] for r in layout] == [96, 64, 80]
    assert [r[2] for r in layout] == [0, 96, 160]
    assert cfg.input_size == 240
    off = np.zeros(3, np.float32)
    res = np.asarray(cfg.field_resolutions(), np.float32)
    prev = np.asarray([1.0, 1.0, 1.0], np.float32)
    # one field at a time: the other two are NaN (no bits)
    for f, (_name, _kind, f_off, f_size) in enumerate(layout):
        values = np.full(3, np.nan, np.float32)
        values[f] = 7.0
        host = _host(cfg, values, 0, off, res, prev)
        on = np.flatnonzero(host)
        assert on.size > 0
        assert on.min() >= f_off and on.max() < f_off + f_size, \
            (f, on.min(), on.max())


def test_uniform_config_unchanged_by_composite_support():
    """The scalar path's guarantee: with composite=None the encode output
    (and the per-field resolution row init) is byte-identical to the
    pre-ISSUE-9 uniform family."""
    cfg = ModelConfig(n_fields=2)
    assert cfg.field_resolutions() == (cfg.rdse.resolution,) * 2
    rows = cfg.field_layout()
    assert [r[0] for r in rows] == ["f0", "f1"]
    assert all(r[1] == "rdse" for r in rows)
    values = np.asarray([3.0, 4.0], np.float32)
    off = np.zeros(2, np.float32)
    host_new = encode_record(cfg, values.astype(np.float64), 1234, off,
                             None, None)
    host_old = encode_record(cfg, values.astype(np.float64), 1234, off)
    np.testing.assert_array_equal(host_new, host_old)
