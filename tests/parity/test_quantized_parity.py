"""Oracle-vs-device parity in the quantized permanence domains.

Quantized domains (models/perm.py) change the *storage* of permanences to
uint16/uint8 fixed-point quanta; parity must remain BIT-exact because both
backends run the same integer arithmetic (oracle: int32; device kernel:
integer-valued f32, exact below 2^24). This is the compression analog of
SURVEY.md §4 item 2 (NuPIC's py/C++ compatibility tests).
"""

import jax as _jax
import numpy as np
import pytest

from rtap_tpu.config import DateConfig, ModelConfig, RDSEConfig, SPConfig, TMConfig, cluster_preset
from rtap_tpu.models.htm_model import HTMModel
from rtap_tpu.models.perm import PermDomain
from rtap_tpu.models.state import init_state, presyn_dtype, state_nbytes

exact_only = pytest.mark.skipif(
    _jax.devices()[0].platform != "cpu",
    reason="bit-exact parity is asserted on the CPU test backend only",
)


def quant_cfg(perm_bits: int) -> ModelConfig:
    return ModelConfig(
        rdse=RDSEConfig(size=128, active_bits=11, resolution=0.7),
        date=DateConfig(time_of_day_width=7, time_of_day_size=18, weekend_width=3),
        sp=SPConfig(columns=256, num_active_columns=10, perm_bits=perm_bits),
        tm=TMConfig(cells_per_column=8, activation_threshold=6, min_threshold=4,
                    max_segments_per_cell=4, max_synapses_per_segment=16,
                    new_synapse_count=8, learn_cap=48, perm_bits=perm_bits),
    )


def test_domain_constants():
    d16 = PermDomain(16)
    assert d16.dtype == np.uint16 and d16.one == 65535
    assert d16.threshold(0.5) == 32768
    assert d16.rate(0.1) == 6554 and d16.rate(0.0) == 0
    d8 = PermDomain(8)
    # a configured-nonzero rate is floored at one quantum, never a silent no-op
    assert d8.rate(0.001) == 1
    assert PermDomain(0).rate(0.1) == np.float32(0.1)


def test_state_dtypes_and_bytes():
    from rtap_tpu.config import dense_cluster_preset

    f32 = state_nbytes(cluster_preset(perm_bits=0))
    q16 = state_nbytes(cluster_preset(perm_bits=16))
    q8 = state_nbytes(cluster_preset(perm_bits=8))
    # the honest budgets the cluster_preset docstring quotes (round-2 fix of
    # the 9x understatement); the ISSUE 18 sparse member-index layout
    # (P=64 pools + S=2 TM lanes) cut the u16 figure 46% vs the dense
    # geometry, which survives as dense_cluster_preset below
    assert 0.41e6 < f32["total"] < 0.45e6, f32["total"]
    assert 0.29e6 < q16["total"] < 0.32e6, q16["total"]
    assert 0.22e6 < q8["total"] < 0.25e6, q8["total"]
    assert q16["total"] <= 340 * 1024  # the ISSUE 18 acceptance frontier
    dense16 = state_nbytes(dense_cluster_preset(perm_bits=16))
    assert q16["total"] < 0.60 * dense16["total"]  # >= 40% per-stream cut
    r2_layout = 1_015_000
    assert q16["total"] < 0.56 * r2_layout  # halved-or-better vs round 2
    assert q8["total"] < 0.43 * r2_layout
    st = init_state(cluster_preset(perm_bits=16))
    assert st["syn_perm"].dtype == np.uint16
    assert st["perm"].dtype == np.uint16
    assert st["members"].dtype == np.int16  # 128 inputs fit int16
    assert st["presyn"].dtype == np.int16  # 2048 cells fit int16
    assert st["seg_pot"].dtype == np.int16
    # nab preset has 65536 cells -> presyn must stay int32
    from rtap_tpu.config import nab_preset

    assert presyn_dtype(nab_preset()) == np.int32


@pytest.mark.quick
@exact_only
@pytest.mark.parametrize("perm_bits", [16, 8])
def test_e2e_state_parity_quantized(perm_bits):
    """After N steps with quantized perms, device state == oracle bit-for-bit."""
    import jax

    cfg = quant_cfg(perm_bits)
    cpu = HTMModel(cfg, seed=11, backend="cpu")
    tpu = HTMModel(cfg, seed=11, backend="tpu")
    rng = np.random.Generator(np.random.Philox(key=(21, 1)))
    t = np.arange(300)
    vals = (50 + 20 * np.sin(2 * np.pi * t / 60.0) + rng.normal(0, 2.0, 300)).astype(np.float32)
    vals[150] += 40.0
    for i in range(300):
        r_cpu = cpu.run(1_700_000_000 + 300 * i, float(vals[i]))
        r_tpu = tpu.run(1_700_000_000 + 300 * i, float(vals[i]))
        assert r_cpu.raw_score == pytest.approx(r_tpu.raw_score, abs=0.0), f"step {i}"
    dev = jax.device_get(tpu._runner.state)
    for k in ("perm", "boost", "overlap_duty", "active_duty", "presyn", "syn_perm",
              "seg_last", "active_seg", "matching_seg", "seg_pot", "prev_active",
              "prev_winner", "enc_offset"):
        np.testing.assert_array_equal(np.asarray(dev[k]), np.asarray(cpu.state[k]), err_msg=k)
    assert dev["syn_perm"].dtype == {16: np.uint16, 8: np.uint8}[perm_bits]
    assert int(dev["tm_overflow"]) == 0
    # learning actually happened in the quantized domain
    assert (np.asarray(dev["seg_last"]) >= 0).any()


@exact_only
def test_quantized_learning_tracks_f32():
    """Quantized-domain anomaly scores stay close to f32 semantics on a
    learnable periodic stream (the quantization deviation is bounded by the
    one-time rounding of the configured rates)."""
    cfg0, cfg16 = quant_cfg(0), quant_cfg(16)
    m0 = HTMModel(cfg0, seed=5, backend="cpu")
    m16 = HTMModel(cfg16, seed=5, backend="cpu")
    t = np.arange(400)
    vals = (50 + 20 * np.sin(2 * np.pi * t / 40.0)).astype(np.float32)
    r0 = [m0.run(1_700_000_000 + 300 * i, float(vals[i])).raw_score for i in range(400)]
    r16 = [m16.run(1_700_000_000 + 300 * i, float(vals[i])).raw_score for i in range(400)]
    # both learn the cycle: late-window mean raw score drops well below early
    assert np.mean(r16[-80:]) < 0.5 * np.mean(r16[40:120]) + 0.05
    assert abs(np.mean(r16[-80:]) - np.mean(r0[-80:])) < 0.1
