"""Oracle-vs-device SP parity (SURVEY.md §4 item 2, the NuPIC
spatial_pooler_compatibility_test pattern): run the numpy oracle and the
jitted kernel side by side from the same init_state and assert bit-identical
active columns, permanences, and duty cycles every step.
"""

import copy

import jax.numpy as jnp
import numpy as np
import pytest

from rtap_tpu.config import ModelConfig, RDSEConfig, SPConfig
from rtap_tpu.models.oracle.spatial_pooler import sp_compute
from rtap_tpu.models.state import init_state
from rtap_tpu.ops.sp_tpu import sp_step

SP_KEYS = ("perm", "boost", "overlap_duty", "active_duty", "sp_iter", "potential")


def _device_state(state):
    return {k: jnp.asarray(state[k]) for k in SP_KEYS}


def _run_parity(cfg: ModelConfig, n_steps: int, learn: bool, atol=0.0):
    rng = np.random.default_rng(7)
    host = init_state(cfg, seed=3)
    dev = _device_state(copy.deepcopy(host))
    n_in = cfg.input_size
    w = max(1, int(0.05 * n_in))
    for step in range(n_steps):
        sdr = np.zeros(n_in, bool)
        sdr[rng.choice(n_in, size=w, replace=False)] = True
        host_active = sp_compute(host, sdr, cfg.sp, learn=learn)
        dev, dev_active = sp_step(dev, jnp.asarray(sdr), cfg.sp, learn=learn)
        np.testing.assert_array_equal(host_active, np.asarray(dev_active), err_msg=f"step {step}")
        if atol == 0.0:
            np.testing.assert_array_equal(host["perm"], np.asarray(dev["perm"]), err_msg=f"step {step}")
            np.testing.assert_array_equal(host["overlap_duty"], np.asarray(dev["overlap_duty"]))
            np.testing.assert_array_equal(host["active_duty"], np.asarray(dev["active_duty"]))
        else:
            np.testing.assert_allclose(host["perm"], np.asarray(dev["perm"]), atol=atol)
    assert int(host["sp_iter"]) == int(dev["sp_iter"]) == (n_steps if learn else 0)


@pytest.mark.quick
@pytest.mark.parametrize("learn", [True, False])
def test_sp_parity_small(learn):
    cfg = ModelConfig(
        rdse=RDSEConfig(size=64, active_bits=5, resolution=0.5),
        sp=SPConfig(columns=128, num_active_columns=8),
    )
    _run_parity(cfg, n_steps=100, learn=learn)


def test_sp_parity_nab_scale():
    cfg = ModelConfig(sp=SPConfig(columns=2048, num_active_columns=40))
    _run_parity(cfg, n_steps=20, learn=True)


def test_sp_parity_with_boost():
    # boost>0 exercises the exp path; fp exp may differ in the last ulp across
    # backends, but the 1/256-quantized inhibition score must keep winner
    # selection identical, and permanences drift only via winner differences.
    cfg = ModelConfig(
        rdse=RDSEConfig(size=64, active_bits=5, resolution=0.5),
        sp=SPConfig(columns=128, num_active_columns=8, boost_strength=2.0),
    )
    _run_parity(cfg, n_steps=60, learn=True, atol=1e-6)
