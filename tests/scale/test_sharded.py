"""Sharded execution on the 8-virtual-device CPU mesh (SURVEY.md §4 item 6).

Validates the multi-chip design without hardware: stream-axis sharding
produces bit-identical scores to single-device execution, the compiled hot
loop contains no collectives (streams are independent by construction), and
the service layer runs transparently over a mesh.
"""

import numpy as np
import pytest

import jax

from rtap_tpu.config import cluster_preset
from rtap_tpu.parallel import make_stream_mesh, shard_state, stream_sharding
from rtap_tpu.service.registry import StreamGroup

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-virtual-device test mesh"
)


def _vals(n, g, seed=4):
    rng = np.random.Generator(np.random.Philox(key=(seed, 21)))
    v = (40 + 8 * rng.random((n, g))).astype(np.float32)
    v[n // 2, :: 3] += 45
    return v


def test_sharded_matches_single_device():
    cfg = cluster_preset()
    G, T = 16, 40
    ids = [f"s{i}" for i in range(G)]
    mesh = make_stream_mesh(8)
    plain = StreamGroup(cfg, ids, backend="tpu")
    sharded = StreamGroup(cfg, ids, backend="tpu", mesh=mesh)
    vals = _vals(T, G)
    ts = (1_700_000_000 + np.arange(T)[:, None] + np.zeros((1, G))).astype(np.int64)
    r_p, ll_p, _ = plain.run_chunk(vals, ts)
    r_s, ll_s, _ = sharded.run_chunk(vals, ts)
    np.testing.assert_array_equal(r_p, r_s)
    np.testing.assert_array_equal(ll_p, ll_s)
    # state stays sharded across steps (donation preserves sharding)
    leaf = sharded.state["perm"]
    assert len(leaf.sharding.device_set) == 8


def test_sharded_cadence_matches_single_device():
    """Learning cadence under shard_map: the schedule cond reads each
    shard's own tm_iter slice (lockstep across shards by construction), so
    sharded and single-device execution must stay bit-identical with
    learn_every set. Pins the r4 cadence feature on the production
    multi-chip path."""
    import dataclasses

    cfg = dataclasses.replace(cluster_preset(), learn_every=3, learn_full_until=10)
    G, T = 16, 30
    ids = [f"s{i}" for i in range(G)]
    mesh = make_stream_mesh(8)
    plain = StreamGroup(cfg, ids, backend="tpu")
    sharded = StreamGroup(cfg, ids, backend="tpu", mesh=mesh)
    vals = _vals(T, G, seed=9)
    ts = (1_700_000_000 + np.arange(T)[:, None] + np.zeros((1, G))).astype(np.int64)
    r_p, ll_p, _ = plain.run_chunk(vals, ts)
    r_s, ll_s, _ = sharded.run_chunk(vals, ts)
    np.testing.assert_array_equal(r_p, r_s)
    np.testing.assert_array_equal(ll_p, ll_s)


def test_hot_loop_is_collective_free():
    """No cross-chip communication in the compiled sharded step — the whole
    point of the stream-axis design (SURVEY.md §2.3). Plain jit over sharded
    inputs does NOT have this property (the partitioner all-gathers the TopK
    batch), which is why the service layer uses shard_map."""
    from rtap_tpu.models.state import init_state
    from rtap_tpu.ops.step import _sharded_chunk_fn, replicate_state

    cfg = cluster_preset()
    G, T = 16, 4
    mesh = make_stream_mesh(8)
    state = shard_state(replicate_state(init_state(cfg, 0), G), mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P

    vals = jax.device_put(np.zeros((T, G, 1), np.float32),
                          NamedSharding(mesh, P(None, "streams", None)))
    ts = jax.device_put(np.zeros((T, G), np.int32),
                        NamedSharding(mesh, P(None, "streams")))
    state_ranks = tuple(sorted((k, max(np.ndim(v), 1)) for k, v in state.items()))
    fn = _sharded_chunk_fn(cfg, mesh, True, state_ranks)
    txt = fn.lower(state, vals, ts).compile().as_text()
    for coll in ("all-reduce", "all-gather", "collective-permute", "all-to-all", "reduce-scatter"):
        assert coll not in txt, f"unexpected collective {coll} in sharded hot loop"


def test_sharded_matches_single_device_flat_layout():
    """The flat kernel layout's chunk-boundary adapters run INSIDE shard_map
    (per-shard reshapes) — sharded flat must equal single-device default."""
    import rtap_tpu.ops.tm_tpu as tm_tpu

    cfg = cluster_preset()
    G, T = 16, 24
    ids = [f"f{i}" for i in range(G)]
    vals = _vals(T, G)
    ts = (1_700_000_000 + np.arange(T)[:, None] + np.zeros((1, G))).astype(np.int64)
    plain = StreamGroup(cfg, ids, backend="tpu")
    r_p, ll_p, _ = plain.run_chunk(vals, ts)
    tm_tpu.set_layout_mode("flat")
    try:
        sharded = StreamGroup(cfg, ids, backend="tpu", mesh=make_stream_mesh(8))
        r_s, ll_s, _ = sharded.run_chunk(vals, ts)
    finally:
        tm_tpu.set_layout_mode(None)
    np.testing.assert_array_equal(r_p, r_s)
    np.testing.assert_array_equal(ll_p, ll_s)


def test_registry_over_mesh():
    cfg = cluster_preset()
    mesh = make_stream_mesh(8)
    from rtap_tpu.service.registry import StreamGroupRegistry

    reg = StreamGroupRegistry(cfg, group_size=8, backend="tpu", mesh=mesh)
    for i in range(11):  # second group padded 3 live + 5 pad
        reg.add_stream(f"n{i}")
    reg.finalize()
    assert len(reg.groups) == 2
    rng = np.random.Generator(np.random.Philox(key=(9, 2)))
    for grp in reg.groups:
        res = grp.tick((40 + rng.random(grp.G)).astype(np.float32), 1_700_000_000)
        assert np.isfinite(res.raw).all()


def test_shard_state_rejects_indivisible():
    cfg = cluster_preset()
    from rtap_tpu.models.state import init_state
    from rtap_tpu.ops.step import replicate_state

    mesh = make_stream_mesh(8)
    with pytest.raises(ValueError, match="not divisible"):
        shard_state(replicate_state(init_state(cfg, 0), 12), mesh)


def test_dynamic_claim_on_meshed_group():
    """Dynamic slot claims work on sharded groups (elastic fleets on the
    multi-chip path): the claimed slot's row reset is bit-identical to the
    single-device claim, sharding survives the donated update, and scoring
    continues bit-equal across the mesh boundary."""
    cfg = cluster_preset()
    G, T = 16, 12
    ids = [f"s{i}" for i in range(G - 2)] + ["__pad0", "__pad1"]
    mesh = make_stream_mesh(8)
    plain = StreamGroup(cfg, ids, backend="tpu")
    sharded = StreamGroup(cfg, ids, backend="tpu", mesh=mesh)
    vals = _vals(T, G, seed=13)
    ts = (1_700_000_000 + np.arange(T)[:, None] + np.zeros((1, G))).astype(np.int64)
    plain.run_chunk(vals, ts)
    sharded.run_chunk(vals, ts)

    sp = plain.claim_slot("late")
    ss = sharded.claim_slot("late")
    assert sp == ss == G - 2
    for key in plain.state:
        np.testing.assert_array_equal(
            np.asarray(plain.state[key]), np.asarray(sharded.state[key]),
            err_msg=key)
    # sharding preserved through the donated row update
    assert len(sharded.state["perm"].sharding.device_set) == 8

    vals2 = _vals(T, G, seed=14)
    ts2 = ts + T
    r_p, ll_p, _ = plain.run_chunk(vals2, ts2)
    r_s, ll_s, _ = sharded.run_chunk(vals2, ts2)
    np.testing.assert_array_equal(r_p, r_s)
    np.testing.assert_array_equal(ll_p, ll_s)


def test_live_serving_stack_over_mesh_bitexact():
    """The full round-5 serving stack (stagger_learn + micro_chunk +
    chunk_stagger + threaded dispatch, live_loop) over a MESHED registry
    must produce bit-identical output to the same stack unmeshed — the
    100k-per-chip serving shape composes with the v5e-8 scale-out axis
    unchanged (SURVEY.md §2.3: shard, then serve exactly the same way)."""
    import dataclasses
    import tempfile

    from rtap_tpu.config import LikelihoodConfig
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    # a 15-tick fresh model cannot alert discriminatively (the TM knows
    # nothing yet); a floor threshold makes every emitted log-likelihood
    # cross it, so the alert file carries REAL per-stream values through
    # the full emission path — the comparison is content-bearing, not two
    # empty files
    cfg = dataclasses.replace(
        cluster_preset(), learn_every=2,
        likelihood=LikelihoodConfig(mode="streaming", learning_period=4,
                                    estimation_samples=4,
                                    averaging_window=3))
    n, gsize, ticks = 12, 8, 15

    def _feed(k):
        rng = np.random.Generator(np.random.Philox(key=(31, k)))
        v = (40 + 6 * rng.random(n)).astype(np.float32)
        if k >= 9:
            v[::3] += 70.0
        return v, 1_700_000_000 + k

    out = {}
    for mode in ("plain", "mesh"):
        mesh = make_stream_mesh(8) if mode == "mesh" else None
        reg = StreamGroupRegistry(cfg, group_size=gsize, backend="tpu",
                                  mesh=mesh, stagger_learn=True,
                                  threshold=0.01)
        for i in range(n):
            reg.add_stream(f"s{i}")
        reg.finalize()
        with tempfile.NamedTemporaryFile("r", suffix=".jsonl") as f:
            stats = live_loop(_feed, reg, n_ticks=ticks, cadence_s=0.0,
                              alert_path=f.name, pipeline_depth=2,
                              dispatch_threads=2, micro_chunk=3,
                              chunk_stagger=True)
            lines = sorted(f.read().splitlines())
        assert stats["scored"] == n * ticks
        assert stats["alerts"] > 0, "emission comparison must be non-vacuous"
        final = [jax.device_get(g.state) for g in reg.groups]
        out[mode] = (lines, final)
    assert out["plain"][0] == out["mesh"][0]
    for s1, s2 in zip(out["plain"][1], out["mesh"][1]):
        for key in s1:
            np.testing.assert_array_equal(
                np.asarray(s1[key]), np.asarray(s2[key]), err_msg=key)
