"""Multi-host (DCN) smoke test — SURVEY.md §2.4's distributed backend.

The reference scales across hosts by share-nothing OS processes; our analog
is jax.distributed over DCN with the same stream-axis sharding code as the
single-host ICI path. This test launches TWO real processes (one per fake
"host", 2 virtual CPU devices each), initializes the jax.distributed
coordinator via rtap_tpu.parallel.init_distributed, and steps a sharded
stream group end to end on the 4-device global mesh — pinning that
init_distributed, put_sharded (make_array_from_callback across processes),
shard_state, and sharded_chunk_step all work multi-process, not just
single-process.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

WORKER = Path(__file__).parent / "dcn_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_dcn_smoke():
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    repo_root = str(Path(__file__).resolve().parents[2])
    # The workers must be hermetic virtual-CPU "hosts": inherited PYTHONPATH
    # entries can inject accelerator PJRT plugins via sitecustomize (this
    # environment does exactly that), and a plugin grabbing a device tunnel
    # inside a fake CPU host wedges jax.distributed. Keep only entries that
    # don't carry a sitecustomize module, with the repo root first.
    inherited = [
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and not (Path(p) / "sitecustomize.py").exists()
    ]
    env["PYTHONPATH"] = os.pathsep.join([repo_root, *inherited])
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), coordinator, "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"DCN_OK p{pid}" in out, out
