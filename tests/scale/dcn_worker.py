"""Worker for the 2-process DCN smoke test (launched by test_dcn.py).

Each process is one "host": jax.distributed.initialize over a localhost
coordinator, 2 virtual CPU devices per process -> a 4-device global mesh.
Runs the production sharded stream-group step end to end and prints the
process-local raw-score shard checksum for the parent to compare.

Usage: python dcn_worker.py <coordinator> <num_processes> <process_id>
"""

import os
import sys


def main() -> None:
    coordinator, num_processes, process_id = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
    ).strip()

    import numpy as np

    from rtap_tpu.parallel import init_distributed, make_stream_mesh, put_sharded, shard_state

    init_distributed(coordinator, num_processes, process_id)

    import jax

    assert jax.process_count() == num_processes, jax.process_count()
    n_dev = len(jax.devices())
    assert n_dev == 2 * num_processes, n_dev

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.models.state import init_state
    from rtap_tpu.ops.step import replicate_state, sharded_chunk_step

    cfg = cluster_preset()
    mesh = make_stream_mesh()
    G, T = 2 * n_dev, 3
    state = shard_state(replicate_state(init_state(cfg, seed=0), G), mesh)
    rng = np.random.Generator(np.random.Philox(key=(7, 3)))
    values = put_sharded(
        (30 + 5 * rng.random((T, G, cfg.n_fields))).astype(np.float32), mesh, axis=1
    )
    ts = put_sharded(
        (1_700_000_000 + np.arange(T)[:, None] + np.zeros((1, G))).astype(np.int32),
        mesh, axis=1,
    )
    state, raw = sharded_chunk_step(state, values, ts, cfg, mesh)
    # every process holds only its addressable shards of the global [T, G] raw
    local = np.concatenate(
        [np.asarray(s.data) for s in sorted(raw.addressable_shards, key=lambda s: s.index[1].start)],
        axis=1,
    )
    assert np.isfinite(local).all(), local
    print(f"DCN_OK p{process_id} shard_sum={float(local.sum()):.6f}", flush=True)


if __name__ == "__main__":
    main()
