"""Test harness config: run all tests on CPU with 8 virtual devices.

Real-TPU execution is exercised by bench.py and the driver's compile checks;
tests validate semantics + sharding on the virtual CPU mesh (SURVEY.md §4
item 6). Must run before anything imports jax.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xla = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla:
    os.environ["XLA_FLAGS"] = (_xla + " --xla_force_host_platform_device_count=8").strip()
