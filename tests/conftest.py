"""Test harness config: run all tests on CPU with 8 virtual devices.

Real-TPU execution is exercised by bench.py and the driver's compile checks;
tests validate semantics + sharding on the virtual CPU mesh (SURVEY.md §4
item 6).

This environment pre-imports jax and forces JAX_PLATFORMS=axon (the TPU
tunnel) via a sitecustomize .pth before any conftest runs, so mutating
os.environ here is too late for the platform choice — the env default is
latched into jax.config at interpreter start. `jax.config.update` still works
any time before first backend use, and XLA_FLAGS is read at backend init, so
the virtual device count can be set here.
"""

import os

_xla = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla:
    os.environ["XLA_FLAGS"] = (_xla + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
