"""Test harness config: run all tests on CPU with 8 virtual devices.

Real-TPU execution is exercised by bench.py and the driver's compile checks;
tests validate semantics + sharding on the virtual CPU mesh (SURVEY.md §4
item 6).

This environment pre-imports jax and forces JAX_PLATFORMS=axon (the TPU
tunnel) via a sitecustomize .pth before any conftest runs, so mutating
os.environ here is too late for the platform choice — the env default is
latched into jax.config at interpreter start. `jax.config.update` still works
any time before first backend use, and XLA_FLAGS is read at backend init, so
the virtual device count can be set here.
"""

import os
import threading
import time

import pytest

_xla = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla:
    os.environ["XLA_FLAGS"] = (_xla + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True)
def _no_leaked_nondaemon_threads():
    """Every test must join its non-daemon threads (ISSUE 2 CI satellite).

    Hung-thread regressions are exactly what chaos/serve runs produce —
    a dispatch pool whose shutdown path was skipped on a fault, a wedged
    producer — and a leaked non-daemon thread hangs the whole pytest
    process at exit, which CI reports as a timeout instead of the guilty
    test. A short grace period lets orderly shutdowns (pool.shutdown,
    server close) finish; daemon threads (listeners, watchers) are
    exempt by construction."""
    before = set(threading.enumerate())
    yield
    deadline = time.time() + 2.0
    while True:
        leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive() and not t.daemon]
        if not leaked:
            return
        if time.time() > deadline:
            raise AssertionError(
                f"test leaked non-daemon thread(s): "
                f"{[t.name for t in leaked]} — these hang pytest at exit "
                "(join them or mark them daemon)")
        time.sleep(0.05)
