"""Phase-0 unit tests: hashing, synthetic data, NAB corpus IO, NAB scorer."""

import numpy as np
import pytest

from rtap_tpu.data.nab_corpus import NabFile, ensure_standin_corpus, load_corpus, write_corpus
from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_cluster, generate_stream
from rtap_tpu.nab.scorer import (
    PROFILES,
    optimize_threshold,
    probation_rows,
    scaled_sigmoid,
    score_corpus,
    score_file,
)
from rtap_tpu.utils.hashing import hash_bits_np, hash_u32_np


class TestHashing:
    def test_deterministic(self):
        k = np.arange(1000)
        a, b = hash_u32_np(k, 42), hash_u32_np(k, 42)
        np.testing.assert_array_equal(a, b)

    def test_seed_changes_output(self):
        k = np.arange(1000)
        assert (hash_u32_np(k, 1) != hash_u32_np(k, 2)).mean() > 0.99

    def test_uniformity(self):
        bits = hash_bits_np(np.arange(100_000), 7, 400)
        counts = np.bincount(bits, minlength=400)
        assert counts.min() > 150 and counts.max() < 350  # ~250 expected

    def test_negative_keys_ok(self):
        assert hash_bits_np(np.array([-5]), 3, 400)[0] >= 0


class TestSynthetic:
    def test_deterministic(self):
        cfg = SyntheticStreamConfig(length=500)
        a = generate_stream("node0.cpu", cfg, seed=3)
        b = generate_stream("node0.cpu", cfg, seed=3)
        np.testing.assert_array_equal(a.values, b.values)
        assert a.windows == b.windows

    def test_labels_cover_injections(self):
        cfg = SyntheticStreamConfig(length=2000, n_anomalies=4)
        s = generate_stream("node1.cpu", cfg, seed=5)
        assert len(s.windows) == 4
        for a, b in s.windows:
            assert s.timestamps[0] <= a <= b <= s.timestamps[-1]

    def test_cluster_shape(self):
        streams = generate_cluster(3, ("cpu", "mem"), SyntheticStreamConfig(length=100))
        assert len(streams) == 6
        assert streams[0].stream_id == "node00000.cpu"

    def test_cpu_clipped(self):
        s = generate_stream("n.cpu", SyntheticStreamConfig(length=3000, metric="cpu"), 0)
        assert s.values.min() >= 0.0 and s.values.max() <= 100.0


class TestCorpusIO:
    def test_round_trip(self, tmp_path):
        s = generate_stream("x", SyntheticStreamConfig(length=300, cadence_s=300.0), 1)
        nf = NabFile("cat/x.csv", s.timestamps, s.values, s.windows)
        write_corpus(tmp_path, [nf])
        loaded = load_corpus(tmp_path)
        assert len(loaded) == 1
        assert loaded[0].name == "cat/x.csv"
        np.testing.assert_array_equal(loaded[0].timestamps, nf.timestamps)
        np.testing.assert_allclose(loaded[0].values, nf.values, atol=1e-4)
        assert loaded[0].windows == nf.windows

    def test_standin_corpus(self, tmp_path):
        root = ensure_standin_corpus(tmp_path / "nab")
        files = load_corpus(root)
        names = {f.name for f in files}
        assert "realAWSCloudwatch/ec2_cpu_utilization_5f5533.csv" in names
        assert all(len(f.windows) > 0 for f in files)
        # regeneration is a no-op (cached on disk)
        assert ensure_standin_corpus(tmp_path / "nab") == root

    def test_subset_filter(self, tmp_path):
        root = ensure_standin_corpus(tmp_path / "nab")
        files = load_corpus(root, subset="realAWSCloudwatch")
        assert all(f.name.startswith("realAWSCloudwatch") for f in files)
        assert len(files) == 6


def _mkfile(n=1000, windows=((400, 449), (700, 749))):
    ts = np.arange(n, dtype=np.int64)
    return ts, [(int(a), int(b)) for a, b in windows]


class TestScorer:
    def test_scaled_sigmoid_endpoints(self):
        assert scaled_sigmoid(-1.0) == pytest.approx(0.98661, abs=1e-4)
        assert scaled_sigmoid(0.0) == pytest.approx(0.0, abs=1e-9)
        assert scaled_sigmoid(4.0) == -1.0
        assert scaled_sigmoid(1.0) == pytest.approx(-0.98661, abs=1e-4)

    def test_perfect_is_100_null_is_0(self):
        ts, windows = _mkfile()
        prof = PROFILES["standard"]
        scores_perfect = np.zeros(1000)
        scores_perfect[400] = scores_perfect[700] = 1.0  # window starts
        scores_null = np.zeros(1000)
        per_perfect = [(scores_perfect, ts, windows)]
        per_null = [(scores_null, ts, windows)]
        assert score_corpus(per_perfect, 0.5, prof) == pytest.approx(100.0)
        assert score_corpus(per_null, 0.5, prof) == pytest.approx(0.0)

    def test_late_detection_scores_less(self):
        ts, windows = _mkfile()
        prof = PROFILES["standard"]
        early, late = np.zeros(1000), np.zeros(1000)
        early[405], late[445] = 1.0, 1.0
        s_early = score_file(early >= 0.5, ts, windows, prof)
        s_late = score_file(late >= 0.5, ts, windows, prof)
        assert s_early > s_late > -2.0  # both better than missing both windows

    def test_fp_penalty(self):
        ts, windows = _mkfile()
        prof = PROFILES["standard"]
        fp = np.zeros(1000)
        fp[300] = 1.0  # outside any window, after probation
        assert score_file(fp >= 0.5, ts, windows, prof) == pytest.approx(
            -prof.fp_weight - 2 * prof.fn_weight
        )

    def test_second_detection_in_window_ignored(self):
        ts, windows = _mkfile()
        prof = PROFILES["standard"]
        one, two = np.zeros(1000), np.zeros(1000)
        one[410] = 1.0
        two[410] = two[420] = 1.0
        assert score_file(one >= 0.5, ts, windows, prof) == pytest.approx(
            score_file(two >= 0.5, ts, windows, prof)
        )

    def test_probation_ignored(self):
        ts, windows = _mkfile()
        prof = PROFILES["standard"]
        det = np.zeros(1000)
        det[10] = 1.0  # inside probation (150 rows)
        assert probation_rows(1000) == 150
        assert score_file(det >= 0.5, ts, windows, prof) == pytest.approx(-2.0)

    def test_optimize_threshold_finds_separator(self):
        ts, windows = _mkfile()
        prof = PROFILES["standard"]
        rng = np.random.default_rng(0)
        scores = rng.uniform(0, 0.3, 1000)
        scores[405] = 0.95  # clear detection in window 1
        scores[705] = 0.95  # window 2
        t, s = optimize_threshold([(scores, ts, windows)], prof)
        assert 0.3 < t <= 0.95
        assert s > 90.0

    def test_profiles_order(self):
        # an FP hurts reward_low_FP more than standard
        ts, windows = _mkfile()
        det = np.zeros(1000)
        det[300] = 1.0
        s_std = score_file(det >= 0.5, ts, windows, PROFILES["standard"])
        s_fp = score_file(det >= 0.5, ts, windows, PROFILES["reward_low_FP"])
        assert s_fp < s_std


def test_scalar_encoder_config_validation():
    import pytest

    from rtap_tpu.config import ModelConfig, ScalarEncoderConfig

    with pytest.raises(ValueError, match="width"):
        ModelConfig(scalar=ScalarEncoderConfig(size=10, width=21))
    with pytest.raises(ValueError, match="min_val"):
        ModelConfig(scalar=ScalarEncoderConfig(min_val=5.0, max_val=5.0))
    # round-trips through JSON including the optional scalar section
    cfg = ModelConfig(scalar=ScalarEncoderConfig(size=80, width=9, max_val=50.0))
    back = ModelConfig.from_json(cfg.to_json())
    assert back.scalar == cfg.scalar and back.input_size == cfg.input_size
    assert ModelConfig.from_json(ModelConfig().to_json()).scalar is None
