"""service/shardpath.py (ISSUE 15): shard-qualified resource paths.

The load-bearing contract is shard 0 ≡ the pre-mesh paths, BYTE-
identical: every committed soak artifact, operator runbook, and resume
path keys on the exact strings the serve stack wrote before the helper
existed. These tests pin the equivalences against the literal old
spellings (the ones the shard-resource pass now bans at call sites) and
the nonzero-shard separation property the helper exists for.
"""

import os

import pytest

from rtap_tpu.service.shardpath import (
    alert_sidecar_path,
    group_checkpoint_path,
    shard_scoped_path,
)

pytestmark = pytest.mark.quick


def test_shard_zero_is_byte_identical_to_pre_mesh_paths():
    # the literal pre-ISSUE-15 spellings, pinned:
    assert shard_scoped_path("/data/journal", 0) == "/data/journal"
    assert shard_scoped_path("alerts.jsonl", 0) == "alerts.jsonl"
    for gi in (0, 7, 123, 9999):
        assert group_checkpoint_path("/ck", gi) \
            == os.path.join("/ck", f"group{gi:04d}")
    assert alert_sidecar_path("/tmp/a.jsonl", "corr") == "/tmp/a.jsonl.corr"
    assert alert_sidecar_path("/tmp/a.jsonl", "epoch") \
        == "/tmp/a.jsonl.epoch"


def test_nonzero_shards_never_collide():
    base = "/data/journal"
    paths = {shard_scoped_path(base, s) for s in range(256)}
    assert len(paths) == 256
    assert shard_scoped_path(base, 1) == "/data/journal.shard001"
    assert shard_scoped_path(base, 255) == "/data/journal.shard255"
    # a trailing separator on a dir flag must yield a SIBLING, never a
    # hidden entry nested inside shard 0's directory (review finding)
    assert shard_scoped_path("runs/journal/", 1) == "runs/journal.shard001"
    assert shard_scoped_path("runs/journal/", 0) == "runs/journal/"
    # sidecars derive from the scoped base, so they separate too
    a0 = alert_sidecar_path(shard_scoped_path("a.jsonl", 0), "corr")
    a1 = alert_sidecar_path(shard_scoped_path("a.jsonl", 1), "corr")
    assert a0 == "a.jsonl.corr" and a1 == "a.jsonl.shard001.corr"


def test_helper_rejects_garbage():
    with pytest.raises(ValueError):
        shard_scoped_path("x", -1)
    with pytest.raises(ValueError):
        shard_scoped_path("x", 1000)
    with pytest.raises(ValueError):
        alert_sidecar_path("x", "lock")   # unknown sidecar kind


def test_serve_stack_routes_through_helper():
    """The call sites this PR rewired produce exactly the helper's
    output (spot checks at the import level — the shard-resource pass
    plus the armed canaries own the no-regression story)."""
    from rtap_tpu.service import loop as loop_mod

    src = open(loop_mod.__file__, encoding="utf-8").read()
    assert 'f"group{gi:04d}"' not in src
    assert '+ ".corr"' not in src
    from rtap_tpu.obs import health as health_mod

    hsrc = open(health_mod.__file__, encoding="utf-8").read()
    assert '+ ".epoch"' not in hsrc
