"""Detection-latency primitives (ISSUE 11): quantile sketch + SLO burn.

Coverage pins the tentpole's two new primitives:

- QuantileSketch: fuzz vs ``numpy.percentile`` across distributions
  (relative error bounded by the log-bucket ratio), window-roll
  semantics, bounded memory regardless of observation count, clamping.
- SloTracker: spec grammar, multi-window burn gating, edge-triggered
  hysteresis (no flapping at the threshold, re-arm after recovery),
  and the budget-exhausted edge.
"""

import numpy as np
import pytest

from rtap_tpu.obs.latency import DEFAULT_QS, LatencyTracker, QuantileSketch
from rtap_tpu.obs.metrics import TelemetryRegistry
from rtap_tpu.obs.slo import SloTracker, parse_slo

pytestmark = pytest.mark.quick


# ------------------------------------------------------------- sketch --
@pytest.mark.parametrize("dist", ["uniform", "lognormal", "exponential",
                                  "bimodal"])
def test_sketch_quantiles_fuzz_vs_numpy(dist):
    """Interpolated quantiles track numpy.percentile within the bucket
    ratio (per_decade=20 -> 10^(1/20) ~ 12%) across distribution shapes
    spanning the sketch's range."""
    rng = np.random.default_rng(hash(dist) % 2**32)
    n = 30_000
    if dist == "uniform":
        vals = rng.uniform(1e-3, 5.0, n)
    elif dist == "lognormal":
        vals = rng.lognormal(-2.0, 1.2, n)
    elif dist == "exponential":
        vals = rng.exponential(0.25, n)
    else:  # bimodal: fast path + slow tail (the serve-shape failure).
        # 60/40 mix keeps every tested quantile INSIDE a mode — a
        # quantile landing in the inter-mode gap is ill-conditioned for
        # any sketch (and for numpy's own interpolation)
        vals = np.concatenate([rng.normal(0.01, 0.002, 3 * n // 5),
                               rng.normal(2.0, 0.3, 2 * n // 5)])
    vals = np.clip(vals, 1e-4, 99.0)
    sk = QuantileSketch()
    sk.observe_many(vals)
    for q in DEFAULT_QS:
        exact = float(np.percentile(vals, q * 100))
        est = sk.quantile(q)
        assert est is not None
        # one bucket ratio of slack either side (geometric buckets)
        ratio = 10 ** (1 / 20)
        assert exact / ratio <= est <= exact * ratio, (
            f"{dist} p{q * 100}: exact {exact}, sketch {est}")


def test_sketch_quantiles_monotone_and_scalar_observe():
    sk = QuantileSketch()
    for v in (0.01, 0.1, 0.5, 1.0, 3.0):
        sk.observe(v)
    qs = [sk.quantile(q) for q in (0.1, 0.5, 0.9, 0.99)]
    assert qs == sorted(qs)
    assert sk.count() == 5


def test_sketch_window_roll_retires_and_total_persists():
    sk = QuantileSketch()
    sk.observe_many(np.full(100, 0.01))
    assert sk.count("window") == 100
    sk.roll()
    # one roll: the window still covers the retired (prev) counts
    assert sk.count("window") == 100
    sk.observe_many(np.full(50, 1.0))
    assert sk.count("window") == 150
    sk.roll()
    # the 0.01 cohort aged out; the 1.0 cohort is now prev
    assert sk.count("window") == 50
    assert sk.quantile(0.5, "window") == pytest.approx(1.0, rel=0.15)
    sk.roll()
    assert sk.count("window") == 0
    assert sk.quantile(0.5, "window") is None
    # lifetime totals never age out
    assert sk.count("total") == 150
    assert sk.rolls == 3


def test_sketch_memory_bounded_and_clamps():
    sk = QuantileSketch()
    base = None
    rng = np.random.default_rng(0)
    for _ in range(20):
        sk.observe_many(rng.uniform(0, 10, 5000))
        sk.observe(-5.0)  # negative clamps to 0, never raises
        sk.observe(1e9)  # overflow clamps into the top bucket
        if base is None:
            base = sk.nbytes()
    assert sk.nbytes() == base  # constant after the first observe
    assert base < 16_384  # one thread: 3 int64 arrays of ~122 buckets
    assert sk.quantile(0.999) <= sk.edges[-1]  # overflow saturates at hi
    # the clamped negatives live in the first bucket
    assert sk.quantile(1e-9) <= sk.edges[0]


def test_sketch_rejects_bad_geometry():
    with pytest.raises(ValueError):
        QuantileSketch(lo=0.0)
    with pytest.raises(ValueError):
        QuantileSketch(lo=1.0, hi=0.5)
    with pytest.raises(ValueError):
        QuantileSketch(per_decade=0)
    with pytest.raises(ValueError):
        LatencyTracker(window_ticks=0, registry=TelemetryRegistry())


# ---------------------------------------------------------------- slo --
def test_parse_slo_grammar():
    s = parse_slo("detect=2s@p99")
    assert (s.name, s.target_s, s.quantile) == ("detect", 2.0, 0.99)
    assert s.budget_frac == pytest.approx(0.01)
    assert s.label() == "detect=2s@p99"
    s = parse_slo("tick=500ms@p95")
    assert (s.name, s.target_s, s.quantile) == ("tick", 0.5, 0.95)
    s = parse_slo("detect=1.5s@p99.9")
    assert s.target_s == 1.5 and s.quantile == pytest.approx(0.999)
    for bad in ("", "detect", "detect=2s", "detect=2m@p99", "foo=2s@p99",
                "detect=0s@p99", "detect=2s@p0", "detect=2s@p100",
                "detect=2s@p101", "DETECT=2s@p99"):
        with pytest.raises(ValueError):
            parse_slo(bad)


def _tracker(sink, fast=5, slow=20, flight=None):
    return SloTracker([parse_slo("tick=100ms@p90")], fast_window=fast,
                      slow_window=slow, fast_burn=2.0, slow_burn=1.5,
                      registry=TelemetryRegistry(), sink=sink,
                      flight=flight)


def test_slo_burn_fires_once_per_episode_and_rearms():
    """Edge-triggered with hysteresis: a sustained violation emits ONE
    slo_burn, recovery emits ONE slo_recovered, and a fresh violation
    opens a new episode."""
    events = []
    t = _tracker(events.append)
    k = 0
    # sustained violation: every tick bad (burn = 10 >> thresholds)
    for _ in range(30):
        t.observe("tick", 0.5)
        t.on_tick(k)
        k += 1
    burns = [e for e in events if e["event"] == "slo_burn"]
    assert len(burns) == 1  # no flapping while the burn persists
    assert burns[0]["stage"] == "tick"
    # recovery: good ticks age the violation out of both windows
    for _ in range(40):
        t.observe("tick", 0.01)
        t.on_tick(k)
        k += 1
    recs = [e for e in events if e["event"] == "slo_recovered"]
    assert len(recs) == 1
    # a NEW violation re-arms a NEW episode
    for _ in range(30):
        t.observe("tick", 0.5)
        t.on_tick(k)
        k += 1
    burns = [e for e in events if e["event"] == "slo_burn"]
    assert len(burns) == 2


def test_slo_no_flap_at_exact_budget_rate():
    """Burning exactly AT budget (burn rate ~1) never pages: the
    thresholds demand a multiple of budget, and hovering at the line
    must not flap the edge trigger."""
    events = []
    # windows aligned to the 1-in-10 pattern so every full window holds
    # exactly its budget's worth of bad ticks (burn rate exactly 1.0)
    t = _tracker(events.append, fast=10, slow=20)
    # 10% bad = exactly the p90 budget -> burn rate 1.0 < 2.0 threshold
    for k in range(200):
        t.observe("tick", 0.5 if k % 10 == 0 else 0.01)
        t.on_tick(k)
    assert [e for e in events if e["event"] == "slo_burn"] == []


def test_slo_multiwindow_and_gate():
    """A spike shorter than the slow window's appetite trips the fast
    burn alone — and must NOT page (the multi-window AND)."""
    events = []
    # slow window long enough that 3 bad ticks stay under slow_burn
    t = _tracker(events.append, fast=3, slow=60)
    k = 0
    for _ in range(50):  # healthy baseline fills the slow window
        t.observe("tick", 0.01)
        t.on_tick(k)
        k += 1
    for _ in range(3):  # brief spike: fast burn 10, slow burn ~0.5
        t.observe("tick", 0.5)
        t.on_tick(k)
        k += 1
    assert [e for e in events if e["event"] == "slo_burn"] == []


def test_slo_budget_exhausted_edge():
    events = []
    t = _tracker(events.append)
    # p90 budget = 10%: 30 straight bad ticks overdraw it immediately
    for k in range(30):
        t.observe("tick", 0.5)
        t.on_tick(k)
    ex = [e for e in events if e["event"] == "slo_budget_exhausted"]
    assert len(ex) == 1  # fires once, not per tick
    v = t.verdict()
    assert v["met"] is False
    one = v["slos"][0]
    assert one["bad"] == 30 and one["samples"] == 30
    assert one["budget_remaining"] < 0  # overdrawn reads negative


def test_slo_verdict_met_with_clean_run_and_quantile_source():
    events = []
    lat = LatencyTracker(window_ticks=10, registry=TelemetryRegistry())
    t = SloTracker([parse_slo("tick=100ms@p90")], fast_window=5,
                   slow_window=20, registry=TelemetryRegistry(),
                   sink=events.append, quantile_source=lat.quantile)
    lat.slo = t
    phases = {p: 0.001 for p in ("source", "membership", "dispatch",
                                 "collect", "emit", "checkpoint")}
    for k in range(25):
        lat.record_tick(k, 1_700_000_000 + k, phases, 0.02)
        t.on_tick(k)
    v = t.verdict()
    assert v["met"] is True and events == []
    one = v["slos"][0]
    assert one["samples"] == 25 and one["bad"] == 0
    assert one["observed_quantile_s"] == pytest.approx(0.02, rel=0.2)


def test_slo_burn_requests_postmortem_dump():
    class _Flight:
        def __init__(self):
            self.dumps = []
            self.events = []

        def request_dump(self, reason, tick):
            self.dumps.append((reason, tick))

        def record_event(self, ev):
            self.events.append(ev)

    fl = _Flight()
    t = _tracker(lambda e: None, flight=fl)
    for k in range(30):
        t.observe("tick", 0.5)
        t.on_tick(k)
    assert ("slo_burn" in [r for r, _ in fl.dumps])
    assert any(e["event"] == "slo_burn" for e in fl.events)


def test_slo_tracker_rejects_bad_config():
    spec = parse_slo("tick=1s@p99")
    reg = TelemetryRegistry()
    with pytest.raises(ValueError):
        SloTracker([], registry=reg)
    with pytest.raises(ValueError):
        SloTracker([spec], fast_window=10, slow_window=5, registry=reg)
    with pytest.raises(ValueError):
        SloTracker([spec], rearm_frac=1.5, registry=reg)
    with pytest.raises(ValueError):
        SloTracker([spec, parse_slo("tick=2s@p95")], registry=reg)


def test_stage_slo_is_fed_by_record_tick_and_can_burn():
    """Every advertised SLO stage (ingest/dispatch/collect/emit/tick)
    receives observations from the per-tick fold — a declared emit SLO
    must judge and burn, never sit inert (code-review regression)."""
    from rtap_tpu.obs.latency import LatencyTracker

    events = []
    reg = TelemetryRegistry()
    lat = LatencyTracker(window_ticks=10, registry=reg)
    slo = SloTracker([parse_slo("emit=1ms@p90"),
                      parse_slo("ingest=10s@p90")],
                     fast_window=5, slow_window=10, fast_burn=2.0,
                     slow_burn=1.5, registry=reg, sink=events.append,
                     quantile_source=lat.quantile)
    lat.slo = slo
    phases = {"dispatch": 0.001, "collect": 0.001, "emit": 0.05}
    now = 1_700_000_000
    for k in range(20):
        lat.record_tick(k, now + k, phases, 0.06, poll_wall=now + k + 0.4)
        slo.on_tick(k)
    v = {s["stage"]: s for s in slo.verdict()["slos"]}
    assert v["emit"]["samples"] == 20 and v["emit"]["bad"] == 20
    assert v["emit"]["met"] is False
    assert v["ingest"]["samples"] == 20 and v["ingest"]["met"] is True
    assert any(e["event"] == "slo_burn" and e["stage"] == "emit"
               for e in events)


def test_low_quantile_slo_can_still_page_with_default_thresholds():
    """Burn rate tops out at 1/budget: a p90 SLO's ceiling (10) sits
    BELOW the default fast threshold (14), so without the per-spec
    clamp a totally-violated p90 SLO could never page (found driving
    the real CLI). A total violation must always page."""
    events = []
    t = SloTracker([parse_slo("tick=1ms@p90")], fast_window=5,
                   slow_window=10, registry=TelemetryRegistry(),
                   sink=events.append)  # default 14/6 burn thresholds
    for k in range(20):
        t.observe("tick", 0.5)  # every tick bad: burn = ceiling = 10
        t.on_tick(k)
    assert any(e["event"] == "slo_burn" for e in events)


def test_tick_slo_pair_helper():
    """The shared seeded-soak arming helper: default spec formats tiny
    cadences safely and the pair comes pre-wired."""
    from rtap_tpu.obs.slo import tick_slo_pair

    lat, slo = tick_slo_pair(0.00001)  # str() would render 1e-05
    assert lat.slo is None and slo.quantile_source == lat.quantile
    assert slo.specs[0].name == "tick"
    assert slo.specs[0].target_s == pytest.approx(1e-5)
    lat2, slo2 = tick_slo_pair(1.0, "tick=2s@p95")
    assert slo2.specs[0].target_s == 2.0


# ------------------------------------------------- tracker integration --
def test_latency_tracker_waterfall_and_lag_providers():
    reg = TelemetryRegistry()
    t = LatencyTracker(window_ticks=4, registry=reg)
    t.lag_providers["repl_ack_ticks"] = lambda _k, _ts: 7.0
    t.lag_providers["broken"] = lambda _k, _ts: (_ for _ in ()).throw(
        RuntimeError("probe died"))  # must not kill the tick

    class _Src:
        last_arrival_lag_s = 0.25
        last_release_hold_s = 2.0

    phases = {"dispatch": 0.003, "collect": 0.004, "emit": 0.001}
    now = 1_700_000_000
    for k in range(9):
        t.observe_detect(np.array([0.2]))
        t.record_tick(k, now + k, phases, 0.01,
                      poll_wall=now + k + 0.5, source=_Src())
    wf = t.last_waterfall
    assert wf["ingest_lag_s"] == pytest.approx(0.5)
    assert wf["arrival_lag_s"] == pytest.approx(0.25)
    assert wf["backfill_hold_s"] == pytest.approx(2.0)
    assert wf["lags"] == {"repl_ack_ticks": 7.0}
    assert t.sketches["detect"].count("total") == 9
    # 9 ticks at window 4 -> 2 rolls
    assert t.sketches["tick"].rolls == 2
    snap = t.snapshot()
    assert snap["stages"]["dispatch"]["total"]["count"] == 9
    stats = t.stats()
    assert stats["detect"]["count"] == 9
    assert stats["waterfall"]["tick"] == 8
