"""Dynamic stream membership (SURVEY.md C19 lazy model creation): pad
slots are claimable capacity — a stream added after finalize gets a fresh
model, its own likelihood probation, and a cleared debounce counter, with
no recompile; a released stream stops being fed and emitted and its slot
becomes claimable again. The claimed-slot contract: indistinguishable from
a stream that was registered into a fresh group (streaming likelihood mode,
the at-scale serving default)."""

import json

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset
from rtap_tpu.service.likelihood_batch import BatchAnomalyLikelihood
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroup, StreamGroupRegistry

CFG = cluster_preset()


def _registry(n=6, group_size=4, reserve=0):
    reg = StreamGroupRegistry(CFG, group_size=group_size, backend="tpu")
    for i in range(n):
        reg.add_stream(f"s{i}")
    reg.finalize(reserve=reserve)
    return reg


def _feed_fn(ids_fn):
    def feed(k):
        rng = np.random.Generator(np.random.Philox(key=(3, k)))
        n = len(ids_fn())
        return (30 + 5 * rng.random(n)).astype(np.float32), 1_700_000_000 + k
    return feed


class TestLikelihoodBirth:
    def test_reset_slot_restarts_probation(self):
        import dataclasses

        lcfg = dataclasses.replace(CFG.likelihood, mode="streaming")
        lik = BatchAnomalyLikelihood(lcfg, 4)
        prob = lcfg.probationary_period
        rng = np.random.default_rng(0)
        for _ in range(prob + 5):
            out, _ = lik.update(rng.random(4) * 0.1)
        assert (out != 0.5).all()  # everyone mature
        lik.reset_slot(2)
        out, _ = lik.update(rng.random(4) * 0.1)
        assert out[2] == 0.5  # reborn slot back in probation
        assert (out[[0, 1, 3]] != 0.5).all()  # others unaffected
        # ...and it matures again after ITS OWN probation
        for _ in range(prob):
            out, _ = lik.update(rng.random(4) * 0.1)
        assert out[2] != 0.5

    def test_claimed_slot_matches_fresh_stream(self):
        """The composed contract: a slot reset at group-record N then fed
        values v_1..v_M produces the same likelihoods as a fresh
        single-stream instance fed v_1..v_M (streaming mode)."""
        import dataclasses

        lcfg = dataclasses.replace(CFG.likelihood, mode="streaming")
        grp = BatchAnomalyLikelihood(lcfg, 3)
        rng = np.random.default_rng(7)
        for _ in range(50):
            grp.update(rng.random(3) * 0.1)
        grp.reset_slot(1)

        solo = BatchAnomalyLikelihood(lcfg, 1)
        n = lcfg.probationary_period + 40
        vals = rng.random((n, 3)) * 0.1
        for t in range(n):
            g_out, g_log = grp.update(vals[t])
            s_out, s_log = solo.update(vals[t, 1:2])
            np.testing.assert_allclose(g_out[1], s_out[0], rtol=1e-12,
                                       err_msg=f"tick {t}")

    def test_window_mode_refit_excludes_pre_birth_zeros(self):
        """Window mode: a claimed slot's Gaussian must be fit from its OWN
        scores only — the reset zeros in the chronologically-pre-birth ring
        positions may not drag its mean toward 0 (they would make every
        normal score look anomalous... or nothing, depending on sign)."""
        import dataclasses

        # probationary_period derives: learning_period + estimation = 60
        lcfg = dataclasses.replace(
            CFG.likelihood, mode="window", learning_period=40,
            estimation_samples=20, reestimation_period=10,
            historic_window_size=200)
        rng = np.random.default_rng(11)

        grp = BatchAnomalyLikelihood(lcfg, 2)
        # slot 0 and 1 identical until the reset
        for _ in range(100):
            v = rng.random() * 0.1 + 0.45
            grp.update(np.array([v, v]))
        grp.reset_slot(1)
        # slot 1's fresh model emits a learning TRANSIENT (near-1.0 raws)
        # for its first learning_period ticks — the oracle excludes that
        # window for a fresh stream and the claimed slot must too
        for t in range(140):
            v = rng.random() * 0.1 + 0.45
            v1 = 0.95 + rng.random() * 0.05 if t < lcfg.learning_period else v
            grp.update(np.array([v, v1]))
        # slot 1's distribution must reflect only its ~0.5-level mature
        # scores, like slot 0's: pre-birth ZEROS would drag its mean far
        # down, the learning transient would drag it far up and inflate
        # sigma — either way muting real anomalies for the late joiner
        assert abs(grp.mean[1] - grp.mean[0]) < 0.05, (grp.mean, grp.std)
        assert grp.std[1] < 0.2, grp.std

    def test_checkpoint_roundtrip_preserves_birth(self):
        import dataclasses

        lcfg = dataclasses.replace(CFG.likelihood, mode="streaming")
        lik = BatchAnomalyLikelihood(lcfg, 2)
        for _ in range(10):
            lik.update(np.array([0.1, 0.2]))
        lik.reset_slot(0)
        d = lik.state_dict()
        fresh = BatchAnomalyLikelihood(lcfg, 2)
        fresh.load_state_dict(d)
        assert fresh.birth[0] == 10 and fresh.birth[1] == 0

    def test_legacy_checkpoint_defaults_birth_to_zero(self):
        lik = BatchAnomalyLikelihood(CFG.likelihood, 2)
        d = lik.state_dict()
        d.pop("birth")
        fresh = BatchAnomalyLikelihood(CFG.likelihood, 2)
        fresh.load_state_dict(d)
        assert (fresh.birth == 0).all()


class TestSlotClaims:
    def test_claim_resets_model_state_to_fresh(self):
        """Model state of a claimed slot must equal a brand-new group's
        (bit-exact on the CPU test platform: same config, same seed)."""
        reg = _registry()  # groups: [4 live, 2 live + 2 pad]
        feed = _feed_fn(lambda: range(6))
        live_loop(feed, reg, n_ticks=6, cadence_s=0.0)
        grp = reg.groups[1]
        slot = grp.claim_slot("late")
        assert slot == 2  # first pad slot
        fresh = StreamGroup(CFG, ["late"], seed=grp.seed, backend="tpu")
        for a, b in zip(
            (np.asarray(v) for _, v in sorted(grp.state.items())),
            (np.asarray(v) for _, v in sorted(fresh.state.items())),
        ):
            np.testing.assert_array_equal(a[slot], b[0])

    def test_release_then_claim_reuses_slot(self):
        reg = _registry()
        grp0, idx = reg.lookup("s1")
        reg.remove_stream("s1")
        assert "s1" not in [grp0.stream_ids[i] for i in grp0.live_slots()]
        reg.add_stream("replacement")
        grp, slot = reg.lookup("replacement")
        assert (grp, slot) == (grp0, idx)  # first free slot = the released one
        assert reg.free_slots == 2  # the two original pads remain

    def test_capacity_exhaustion_raises(self):
        reg = _registry(n=4, group_size=4)  # no pads at all
        with pytest.raises(RuntimeError, match="capacity"):
            reg.add_stream("overflow")

    def test_reserve_adds_claimable_groups(self):
        reg = _registry(n=4, group_size=4, reserve=4)
        assert len(reg.groups) == 2 and reg.free_slots == 4
        for i in range(4):
            reg.add_stream(f"extra{i}")
        assert reg.free_slots == 0
        assert reg.n_streams == 8

    def test_duplicate_and_pad_ids_rejected(self):
        reg = _registry()
        with pytest.raises(KeyError):
            reg.add_stream("s0")
        with pytest.raises(ValueError, match="__pad"):
            reg.groups[1].claim_slot("__pad_evil")


class TestLiveLoopDynamic:
    def test_removed_stream_stops_emitting_and_added_starts(self, tmp_path):
        reg = _registry()
        path = str(tmp_path / "alerts.jsonl")
        ids = ["s%d" % i for i in range(6)]

        def feed(k):
            rng = np.random.Generator(np.random.Philox(key=(5, k)))
            return (30 + 5 * rng.random(len(ids))).astype(np.float32), k

        stats = live_loop(feed, reg, n_ticks=4, cadence_s=0.0, alert_path=path)
        assert stats["scored"] == 6 * 4

        reg.remove_stream("s2")
        ids.remove("s2")
        stats = live_loop(feed, reg, n_ticks=4, cadence_s=0.0)
        assert stats["scored"] == 5 * 4

        reg.add_stream("late")
        # dispatch order: group 0 live slots (incl. reclaimed slot 2),
        # then group 1 — the registry defines it
        ids[:] = reg.dispatch_ids()
        assert "late" in ids
        stats = live_loop(feed, reg, n_ticks=4, cadence_s=0.0)
        assert stats["scored"] == 6 * 4

    @staticmethod
    def _run_with_feeder(reg, records_fn, n_ticks, known_ids,
                         checkpoint_dir=None, auto_release_after=0,
                         micro_chunk=1, chunk_stagger=False):
        """live_loop over a REAL TcpJsonlSource (the object is the source,
        as serve passes it — auto-register needs its drain_unknown/set_ids
        surface) with a producer thread pushing records_fn(k) each tick."""
        import threading
        import time

        from rtap_tpu.service.sources import TcpJsonlSource, send_jsonl

        src = TcpJsonlSource(known_ids, port=0, track_unknown=True).start()
        stop = threading.Event()

        def produce():
            k = 0
            while not stop.is_set():
                try:
                    send_jsonl(src.address, records_fn(k))
                except OSError:
                    pass
                k += 1
                time.sleep(0.02)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            stats = live_loop(src, reg, n_ticks=n_ticks, cadence_s=0.1,
                              auto_register=True,
                              checkpoint_dir=checkpoint_dir,
                              auto_release_after=auto_release_after,
                              micro_chunk=micro_chunk,
                              chunk_stagger=chunk_stagger)
        finally:
            stop.set()
            t.join(timeout=5)
            src.close()
        return stats

    def test_auto_register_over_real_socket(self):
        reg = _registry(n=2, group_size=2, reserve=2)
        stats = self._run_with_feeder(
            reg,
            lambda k: [{"id": "s0", "value": 30.0, "ts": k},
                       {"id": "s1", "value": 31.0, "ts": k},
                       {"id": "newcomer", "value": 32.0, "ts": k}],
            n_ticks=8, known_ids=["s0", "s1"])
        assert stats["auto_registered"] == 1
        assert stats["auto_rejected"] == 0
        assert reg.n_streams == 3
        reg.lookup("newcomer")  # registered and routable
        # it scored every tick after its registration tick
        assert stats["scored"] > 2 * 8

    def test_auto_register_composes_with_micro_chunk(self):
        """Plain micro_chunk + auto_register: claims land only at chunk
        boundaries (the drain-first rule generalized to the buffered
        path); a newcomer appearing mid-chunk registers at the next
        boundary and scores from there on."""
        reg = _registry(n=2, group_size=2, reserve=2)
        stats = self._run_with_feeder(
            reg,
            lambda k: [{"id": "s0", "value": 30.0, "ts": k},
                       {"id": "s1", "value": 31.0, "ts": k},
                       {"id": "newcomer", "value": 32.0, "ts": k}],
            n_ticks=12, known_ids=["s0", "s1"], micro_chunk=4)
        assert stats["micro_chunk"] == 4
        assert stats["auto_registered"] == 1
        reg.lookup("newcomer")
        # registered at a boundary tick; scored for >= one full chunk
        assert stats["scored"] >= 2 * 12 + 4

    def test_auto_register_composes_with_chunk_stagger(self):
        """Elastic membership under ROTATED chunk boundaries: a claim
        forces a one-tick boundary realignment (partial flush + drain +
        re-ramp) instead of being forbidden — the 100k serving shape
        stays elastic."""
        reg = _registry(n=2, group_size=2, reserve=2)
        stats = self._run_with_feeder(
            reg,
            lambda k: [{"id": "s0", "value": 30.0, "ts": k},
                       {"id": "s1", "value": 31.0, "ts": k},
                       {"id": "newcomer", "value": 32.0, "ts": k}],
            n_ticks=12, known_ids=["s0", "s1"], micro_chunk=3,
            chunk_stagger=True)
        assert stats["chunk_stagger"] is True
        assert stats["auto_registered"] == 1
        reg.lookup("newcomer")
        assert stats["scored"] >= 2 * 12 + 3

    def test_auto_release_composes_with_chunk_stagger(self):
        """The release path under rotated boundaries: a stream going
        silent mid-soak is released through the same forced boundary
        realignment as claims, with buffered old-length rows flushed
        first."""
        reg = _registry(n=3, group_size=3)
        stats = self._run_with_feeder(
            reg,
            lambda k: ([{"id": "s0", "value": 30.0, "ts": k},
                        {"id": "s1", "value": 31.0, "ts": k}]
                       + ([{"id": "s2", "value": 32.0, "ts": k}]
                          if k < 3 else [])),
            n_ticks=16, known_ids=["s0", "s1", "s2"],
            auto_release_after=4, micro_chunk=3, chunk_stagger=True)
        assert stats["chunk_stagger"] is True
        assert stats["auto_released"] == 1
        assert "s2" not in reg
        assert stats["scored"] >= 2 * 16  # survivors scored every tick

    def test_auto_register_capacity_rejection(self):
        reg = _registry(n=2, group_size=2)  # zero free slots
        stats = self._run_with_feeder(
            reg,
            lambda k: [{"id": "s0", "value": 30.0, "ts": k},
                       {"id": "nope", "value": 1.0, "ts": k}],
            n_ticks=6, known_ids=["s0", "s1"])
        assert stats["auto_registered"] == 0
        assert stats["auto_rejected"] == 1
        assert reg.n_streams == 2


class TestAutoRelease:
    def test_silent_stream_releases_slot(self):
        """A stream all-NaN for N consecutive ticks is released: its slot
        returns to claimable capacity and it stops being emitted."""
        reg = _registry(n=4, group_size=4)  # full group, no pads
        assert reg.free_slots == 0

        def feed(k):
            vals = np.full(len(reg.dispatch_ids()), 30.0, np.float32)
            if "s3" in reg.dispatch_ids() and k >= 2:
                vals[reg.dispatch_ids().index("s3")] = np.nan
            return vals, k

        stats = live_loop(feed, reg, n_ticks=10, cadence_s=0.0,
                          auto_release_after=3)
        assert stats["auto_released"] == 1
        assert "s3" not in reg
        assert reg.free_slots == 1
        # released at tick 5's membership block (silent ticks 2,3,4):
        # 4 streams x 5 ticks + 3 streams x 5 ticks
        assert stats["scored"] == 4 * 5 + 3 * 5

    def test_gap_shorter_than_threshold_survives(self):
        reg = _registry(n=2, group_size=2)

        def feed(k):
            vals = np.full(2, 30.0, np.float32)
            if 2 <= k < 4:  # a 2-tick outage, threshold 3
                vals[1] = np.nan
            return vals, k

        stats = live_loop(feed, reg, n_ticks=8, cadence_s=0.0,
                          auto_release_after=3)
        assert stats["auto_released"] == 0
        assert "s1" in reg

    def test_churn_cycle_release_then_reregister(self):
        """The full elastic loop over a real socket: a stream goes silent,
        its slot releases, it pushes again, auto-register claims it a
        FRESH model in the freed slot."""
        reg = _registry(n=2, group_size=2)  # zero spare capacity

        # event-driven phases (no wall-clock coupling): s1 pushes until
        # the feeder has warmed it up, goes silent, and resumes as soon as
        # the RELEASE is observed in registry state — so the return phase
        # always happens, however slow the host
        released_seen = {"v": False}

        def records(k):
            if "s1" not in reg:
                released_seen["v"] = True
            recs = [{"id": "s0", "value": 30.0, "ts": k}]
            if released_seen["v"]:
                recs.append({"id": "s1", "value": 32.0, "ts": k})
            elif k < 10:
                recs.append({"id": "s1", "value": 31.0, "ts": k})
            return recs

        stats = TestLiveLoopDynamic._run_with_feeder(
            reg, records, n_ticks=50, known_ids=["s0", "s1"],
            auto_release_after=4)
        assert stats["auto_released"] == 1
        assert stats["auto_registered"] == 1  # re-claimed after returning
        assert "s1" in reg  # back, as a fresh model in the freed slot
        grp, slot = reg.lookup("s1")
        assert grp.likelihood.birth[slot] > 0  # probation restarted


class TestLiveLoopDynamicResume:
    def test_auto_registered_stream_survives_restart(self, tmp_path):
        """serve --auto-register --checkpoint-dir crash/restart story: a
        stream lazily claimed in run 1 must resume LIVE in run 2 (which
        was built from the original --streams list only), keep its slot,
        and not be re-claimed into a duplicate when its records keep
        arriving."""
        ck = str(tmp_path / "ck")

        reg1 = _registry(n=2, group_size=2, reserve=2)
        stats1 = TestLiveLoopDynamic._run_with_feeder(
            reg1,
            lambda k: [{"id": "s0", "value": 30.0, "ts": k},
                       {"id": "s1", "value": 31.0, "ts": k},
                       {"id": "newcomer", "value": 32.0, "ts": k}],
            n_ticks=8, known_ids=["s0", "s1"], checkpoint_dir=ck)
        assert stats1["auto_registered"] == 1
        grp1, slot1 = reg1.lookup("newcomer")

        reg2 = _registry(n=2, group_size=2, reserve=2)  # original list only
        stats2 = TestLiveLoopDynamic._run_with_feeder(
            reg2,
            lambda k: [{"id": "s0", "value": 33.0, "ts": k},
                       {"id": "s1", "value": 34.0, "ts": k},
                       {"id": "newcomer", "value": 35.0, "ts": k}],
            n_ticks=6, known_ids=["s0", "s1"], checkpoint_dir=ck)
        # resumed live from the checkpoint, NOT re-registered
        assert stats2["auto_registered"] == 0
        assert "resumed_from" in stats2
        grp2, slot2 = reg2.lookup("newcomer")
        assert slot2 == slot1  # same slot, carried by the checkpoint
        assert stats2["scored"] == 3 * stats2["ticks"]  # all three emit


    def test_elastic_fleet_serves_frozen_from_its_checkpoint(self, tmp_path):
        """The register-then-freeze workflow: a fleet that auto-registered
        streams while learning must be servable READ-ONLY from its own
        checkpoint — frozen resume accepts the claimed extras (claiming
        NEW streams while frozen stays forbidden at the CLI)."""
        ck = str(tmp_path / "ck")
        reg1 = _registry(n=2, group_size=2, reserve=2)
        stats1 = TestLiveLoopDynamic._run_with_feeder(
            reg1,
            lambda k: [{"id": "s0", "value": 30.0, "ts": k},
                       {"id": "s1", "value": 31.0, "ts": k},
                       {"id": "newcomer", "value": 32.0, "ts": k}],
            n_ticks=8, known_ids=["s0", "s1"], checkpoint_dir=ck)
        assert stats1["auto_registered"] == 1

        reg2 = _registry(n=2, group_size=2, reserve=2)
        # frozen, no auto_register: resume must adopt the extras (the
        # source only feeds NaN here — missing samples still score)
        from rtap_tpu.service.sources import TcpJsonlSource

        src = TcpJsonlSource(["s0", "s1"], port=0, track_unknown=True).start()
        try:
            stats2 = live_loop(src, reg2, n_ticks=5, cadence_s=0.0,
                               learn=False, checkpoint_dir=ck)
        finally:
            src.close()
        assert stats2["learn"] is False
        assert "resumed_from" in stats2
        assert "newcomer" in reg2  # adopted from the checkpoint, read-only
        assert stats2["scored"] == 3 * 5
        assert stats2["checkpoints_saved"] == 0  # frozen = dir untouched


class TestCheckpointDynamic:
    def test_membership_survives_save_load(self, tmp_path):
        from rtap_tpu.service.checkpoint import load_group, save_group

        reg = _registry()
        feed = _feed_fn(lambda: range(6))
        live_loop(feed, reg, n_ticks=4, cadence_s=0.0)
        grp = reg.groups[1]
        grp.claim_slot("late")
        path = tmp_path / "ck"
        save_group(grp, path)
        resumed = load_group(path)
        assert resumed.stream_ids == grp.stream_ids
        assert resumed.n_live == 3
        np.testing.assert_array_equal(resumed.live_slots(), grp.live_slots())
        np.testing.assert_array_equal(resumed.likelihood.birth,
                                      grp.likelihood.birth)
