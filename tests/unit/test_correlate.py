"""Topology-aware incident correlation units (ISSUE 9 tentpole b):
TopologyMap spec parsing + inference + components, IncidentCorrelator
window edges / hysteresis / thresholds, and the crash-resume dedupe fold
over the shared alert-stream walker."""

import json

import pytest

from rtap_tpu.correlate import IncidentCorrelator, TopologyMap
from rtap_tpu.correlate.incidents import incident_id_of
from rtap_tpu.correlate.topology import (
    UNKNOWN_SERVICE,
    node_of_stream,
    service_of_node,
)
from rtap_tpu.obs.metrics import TelemetryRegistry

SPEC = {"services": {"web": ["web-00", "web-01"], "db": ["db-00"],
                     "batch": ["batch-00", "batch-01"]},
        "links": [["web", "db"]]}


def _correlator(**kw):
    kw.setdefault("topology", TopologyMap.from_spec(SPEC))
    kw.setdefault("window_s", 5)
    kw.setdefault("min_streams", 2)
    kw.setdefault("registry", TelemetryRegistry())
    return IncidentCorrelator(**kw)


class TestTopologyMap:
    def test_stream_and_node_parsing(self):
        assert node_of_stream("web-00.cpu") == "web-00"
        assert node_of_stream("a.b.cpu") == "a.b"
        assert node_of_stream("nodot") == "nodot"
        assert service_of_node("web-01") == "web"
        assert service_of_node("node00003") == "node"
        assert service_of_node("db2") == "db"
        assert service_of_node("12345") == "12345"  # all digits: own service

    @pytest.mark.quick
    def test_linked_services_share_a_cluster(self):
        topo = TopologyMap.from_spec(SPEC)
        assert topo.cluster_of("web-00.cpu") == topo.cluster_of("db-00.mem")
        assert topo.cluster_of("batch-00.cpu") != topo.cluster_of("web-00.cpu")
        assert topo.adjacent("web-01", "db-00")
        assert not topo.adjacent("batch-00", "db-00")

    def test_cluster_keys_are_deterministic(self):
        # canonical component name = lexicographically smallest member,
        # independent of declaration order
        spec2 = {"services": {"db": ["db-00"], "batch": ["batch-00"],
                              "web": ["web-00", "web-01"]},
                 "links": [["db", "web"]]}
        a = TopologyMap.from_spec(SPEC)
        b = TopologyMap.from_spec(spec2)
        assert a.cluster_of("web-00.cpu") == b.cluster_of("web-00.cpu") == "db"

    def test_cluster_keys_deterministic_across_hash_seeds(self):
        """ISSUE 13 replay-determinism pin: component keys must be
        byte-identical across PROCESSES, not just within one — CPython
        randomizes str hashes per process, so any surviving unsorted
        set iteration in _rebuild_components would diverge here."""
        import os
        import subprocess
        import sys

        prog = (
            "import json\n"
            "from rtap_tpu.correlate import TopologyMap\n"
            "spec = {'services': {chr(97 + i) * 3: ['n%d' % i]\n"
            "                     for i in range(12)},\n"
            "        'links': [[chr(97 + i) * 3, chr(98 + i) * 3]\n"
            "                  for i in range(0, 10, 2)]}\n"
            "t = TopologyMap.from_spec(spec)\n"
            "print(json.dumps(t._component, sort_keys=True))\n")
        outs = set()
        for seed in ("0", "1"):
            env = dict(os.environ, PYTHONHASHSEED=seed,
                       JAX_PLATFORMS="cpu")
            p = subprocess.run([sys.executable, "-c", prog], env=env,
                               capture_output=True, text=True,
                               timeout=120)
            assert p.returncode == 0, p.stderr
            outs.add(p.stdout.strip())
        assert len(outs) == 1, f"component map diverged: {outs}"

    def test_spec_accepts_json_string_and_rejects_bad_shapes(self):
        topo = TopologyMap.from_spec(json.dumps(SPEC))
        assert topo.cluster_of("db-00.x") == "db"
        with pytest.raises(ValueError, match="services"):
            TopologyMap.from_spec({"links": []})
        with pytest.raises(ValueError, match="node list"):
            TopologyMap.from_spec({"services": {"web": "web-00"}})
        with pytest.raises(ValueError, match="appears in services"):
            TopologyMap.from_spec(
                {"services": {"a": ["n0"], "b": ["n0"]}})
        with pytest.raises(ValueError, match="undeclared"):
            TopologyMap.from_spec(
                {"services": {"a": ["n0"]}, "links": [["a", "ghost"]]})

    def test_unknown_nodes_degrade_not_crash(self):
        topo = TopologyMap.from_spec(SPEC)
        # outside the spec: catch-all service, still correlates per node
        assert topo.service_of("mystery-07") == UNKNOWN_SERVICE
        assert topo.cluster_of("mystery-07.cpu") == UNKNOWN_SERVICE

    @pytest.mark.quick
    def test_inference_mode_groups_by_stripped_prefix(self):
        topo = TopologyMap.infer()
        assert topo.cluster_of("web-01.cpu") == topo.cluster_of("web-02.mem")
        assert topo.cluster_of("node00003.net") == \
            topo.cluster_of("node00009.cpu")
        assert topo.cluster_of("web-01.cpu") != topo.cluster_of("db-01.cpu")


class TestIncidentCorrelator:
    @pytest.mark.quick
    def test_one_incident_per_cluster_burst(self):
        out = []
        co = _correlator(sink=out.append)
        # linked web+db burst together; batch stays quiet
        co.observe_alert("a1", "web-00.cpu", 100)
        co.observe_alert("a2", "web-01.cpu", 101)
        co.observe_alert("a3", "db-00.mem", 103)
        for t in range(104, 110):
            co.on_tick(t)
        assert len(out) == 1
        inc = out[0]
        assert inc["event"] == "incident"
        assert inc["nodes"] == ["db-00", "web-00", "web-01"]
        assert inc["alert_ids"] == ["a1", "a2", "a3"]
        assert inc["onset_ts"] == 100 and inc["end_ts"] == 103
        assert inc["incident_id"] == incident_id_of(["a1", "a2", "a3"])

    def test_window_closes_on_quiescence_not_onset(self):
        """Hysteresis: a re-burst INSIDE the window extends the same
        incident instead of paging twice."""
        out = []
        co = _correlator(sink=out.append)
        co.observe_alert("a1", "web-00.cpu", 100)
        co.observe_alert("a2", "web-01.cpu", 101)
        co.on_tick(105)  # 4s after last member: window_s=5 not yet reached
        assert not out
        co.observe_alert("a3", "db-00.mem", 105)  # re-burst extends
        co.on_tick(110)
        assert not out
        co.on_tick(111)  # 6s after the re-burst: closes
        assert len(out) == 1 and out[0]["members"] == 3

    def test_window_edge_exact_boundary(self):
        """now - last == window_s holds the window; strictly greater
        closes it (the > in on_tick)."""
        out = []
        co = _correlator(sink=out.append)
        co.observe_alert("a1", "web-00.cpu", 100)
        co.observe_alert("a2", "web-01.cpu", 100)
        co.on_tick(105)
        assert not out
        co.on_tick(106)
        assert len(out) == 1

    def test_max_span_bounds_continuous_alerting(self):
        out = []
        co = _correlator(sink=out.append, max_span_s=10)
        for t in range(100, 140):  # a member EVERY tick: never quiesces
            co.observe_alert(f"a{t}", f"web-0{t % 2}.cpu", t)
            co.on_tick(t)
        assert out, "the hard span bound must force a close"
        assert out[0]["span_s"] <= 11

    def test_below_min_streams_expires_silently(self):
        out = []
        co = _correlator(sink=out.append, min_streams=3)
        co.observe_alert("a1", "web-00.cpu", 100)
        co.observe_alert("a2", "web-00.cpu", 101)  # same stream twice
        co.observe_alert("a3", "web-01.cpu", 102)  # 2 distinct < 3
        for t in range(103, 112):
            co.on_tick(t)
        assert not out
        assert co.stats()["windows_expired"] == 1

    def test_distinct_clusters_page_separately(self):
        out = []
        co = _correlator(sink=out.append)
        co.observe_alert("a1", "web-00.cpu", 100)
        co.observe_alert("a2", "db-00.cpu", 100)   # same cluster (linked)
        co.observe_alert("b1", "batch-00.cpu", 100)
        co.observe_alert("b2", "batch-01.cpu", 100)
        for t in range(101, 108):
            co.on_tick(t)
        assert len(out) == 2
        assert {o["cluster"] for o in out} == {"batch", "db"}

    def test_validation(self):
        with pytest.raises(ValueError, match="window_s"):
            _correlator(window_s=0)
        with pytest.raises(ValueError, match="min_streams"):
            _correlator(min_streams=1)
        with pytest.raises(ValueError, match="max_span_s"):
            _correlator(window_s=30, max_span_s=5)

    def test_incident_id_is_content_derived(self):
        assert incident_id_of(["b", "a"]) == incident_id_of(["a", "b"])
        assert incident_id_of(["a"]) != incident_id_of(["b"])

    def test_large_blast_requests_flight_dump(self):
        dumps = []

        class Flight:
            def request_dump(self, reason, tick):
                dumps.append((reason, tick))

        co = _correlator(sink=lambda _r: None, flight=Flight(),
                         blast_dump_nodes=3)
        for i, s in enumerate(("web-00.cpu", "web-01.mem", "db-00.cpu")):
            co.observe_alert(f"a{i}", s, 100 + i)
        for t in range(103, 110):
            co.on_tick(t, tick=t - 100)
        assert dumps and dumps[0][0] == "incident"


class TestResume:
    def _sink_file(self, tmp_path, lines):
        p = tmp_path / "alerts.jsonl"
        p.write_text("".join(json.dumps(d) + "\n" for d in lines))
        return str(p)

    def _alert(self, aid, stream, ts):
        return {"alert_id": aid, "stream": stream, "ts": ts}

    @pytest.mark.quick
    def test_already_emitted_incident_dedupes(self, tmp_path):
        """The event line landed pre-crash: the re-fold must NOT re-emit
        (exactly-once across kill-9)."""
        alerts = [self._alert("a1", "web-00.cpu", 100),
                  self._alert("a2", "web-01.cpu", 101)]
        inc = {"event": "incident",
               "incident_id": incident_id_of(["a1", "a2"]),
               "alert_ids": ["a1", "a2"]}
        path = self._sink_file(tmp_path, alerts + [inc])
        out = []
        co = _correlator(sink=out.append)
        summary = co.resume_from(path)
        assert summary["alerts_refolded"] == 2
        co.on_tick(200)  # well past the window: the re-folded window closes
        assert not out, "a pre-crash-emitted incident must not re-emit"
        assert co.stats()["resume_deduped"] == 1

    @pytest.mark.quick
    def test_unemitted_closed_incident_re_emits(self, tmp_path):
        """The window closed pre-crash but its event line never landed:
        the resume fold must emit it exactly once."""
        alerts = [self._alert("a1", "web-00.cpu", 100),
                  self._alert("a2", "web-01.cpu", 101),
                  # a much later alert: drives the scan clock past the
                  # window close while still replaying
                  self._alert("z9", "batch-00.cpu", 400)]
        path = self._sink_file(tmp_path, alerts)
        out = []
        co = _correlator(sink=out.append)
        summary = co.resume_from(path)
        assert summary["re_emitted"] == 1
        assert len(out) == 1
        assert out[0]["alert_ids"] == ["a1", "a2"]

    def test_open_window_survives_crash_and_extends_live(self, tmp_path):
        """The hard case the workload soak kills into: the correlator
        dies MID-FOLD (window open, no incident line on disk). The
        resume re-folds the delivered members from the sink tail —
        replayed duplicates are suppressed upstream by the AlertWriter,
        so they re-enter from disk exactly once — and a post-resume
        member extends the SAME window: one incident, identical to the
        uninterrupted run's."""
        alerts = [self._alert("a1", "web-00.cpu", 100),
                  self._alert("a2", "web-01.cpu", 101)]
        path = self._sink_file(tmp_path, alerts)
        out = []
        co = _correlator(sink=out.append, min_streams=3)
        co.resume_from(path)
        assert not out, "an open window must not close during resume"
        co.observe_alert("a3", "db-00.mem", 103)  # the fault continues
        for t in range(104, 110):
            co.on_tick(t)
        assert len(out) == 1
        assert out[0]["alert_ids"] == ["a1", "a2", "a3"]
        assert out[0]["incident_id"] == incident_id_of(["a1", "a2", "a3"])

    def test_missing_file_is_an_empty_stream(self, tmp_path):
        co = _correlator()
        summary = co.resume_from(str(tmp_path / "nope.jsonl"))
        assert summary["scanned"] == 0

    def test_torn_tail_is_skipped(self, tmp_path):
        path = tmp_path / "alerts.jsonl"
        path.write_text(
            json.dumps(self._alert("a1", "web-00.cpu", 100)) + "\n"
            + '{"alert_id": "torn-by-kil')
        co = _correlator()
        summary = co.resume_from(str(path))
        assert summary["alerts_refolded"] == 1


@pytest.mark.quick
def test_correlator_fold_overhead_within_one_percent_of_tick_budget():
    """The CI twin of the bench.py --obs-bench bar: even at the
    alert-storm ceiling (a full blast radius folding every tick with
    every cluster window open) the correlator stays host-noise."""
    from rtap_tpu.obs.selfbench import measure_correlate

    res = measure_correlate(n=300)
    assert res["per_tick_overhead_frac"] <= 0.01, res


@pytest.mark.quick
def test_fields_ranked_by_member_count():
    """Incident `fields` order = attribution count desc, then name (the
    most-implicated field leads the triage list)."""
    got = []
    co = _correlator(sink=got.append, min_streams=2)
    tf_v = [{"name": "value", "contribution": 1.0, "bucket_delta": 3}]
    tf_e = [{"name": "event_class", "contribution": 1.0, "bucket_delta": 1}]
    co.observe_alert("a1", "web-00.cpu", 100, top_fields=tf_v)
    co.observe_alert("a2", "web-01.cpu", 100, top_fields=tf_v)
    co.observe_alert("a3", "web-01.mem", 100, top_fields=tf_e)
    co.on_tick(200)
    assert got and got[0]["fields"] == ["value", "event_class"]


@pytest.mark.quick
def test_snapshot_is_safe_against_concurrent_folds():
    """GET /incidents reads from the obs HTTP thread while the loop
    thread folds/closes: hammer both for a moment — no 'dict changed
    size during iteration' (the correlator lock)."""
    import threading

    co = _correlator(sink=lambda _r: None, min_streams=2)
    stop = threading.Event()
    errors = []

    def folder():
        t = 0
        while not stop.is_set():
            t += 1
            co.observe_alert(f"a{t}", f"w{t % 17}-00.cpu", t)
            co.on_tick(t + (100 if t % 5 == 0 else 0))

    th = threading.Thread(target=folder, daemon=True)
    th.start()
    try:
        import time
        deadline = time.time() + 0.4
        while time.time() < deadline:
            try:
                snap = co.snapshot()
                assert "open_windows" in snap
            except RuntimeError as e:  # pragma: no cover - the regression
                errors.append(e)
                break
    finally:
        stop.set()
        th.join(timeout=5)
    assert not errors, errors


@pytest.mark.quick
def test_dropped_alert_batches_never_fold(tmp_path):
    """A batch the sink refused (breaker open / fence lost) must NOT
    seed correlation windows: the fold mirrors the DISK (the resume
    re-fold's source of truth), so incident member ids always reference
    lines that exist on the stream."""
    import numpy as np

    from rtap_tpu.service.alerts import AlertWriter

    co = _correlator(sink=lambda _r: None, min_streams=2)
    fenced = {"ok": True}
    w = AlertWriter(path=str(tmp_path / "a.jsonl"),
                    fence=lambda: fenced["ok"], correlator=co)
    ll = np.array([0.9, 0.9], np.float32)
    w.emit_batch(["web-00.cpu", "web-01.cpu"], np.array([100, 100]),
                 np.array([1.0, 1.0]), np.array([0.9, 0.9], np.float32),
                 ll, ll >= 0.5, group=0, tick=1)
    assert co.correlated == 2  # delivered batch folds
    fenced["ok"] = False  # lease lost: the sink refuses the batch
    w.emit_batch(["web-00.cpu", "web-01.cpu"], np.array([101, 101]),
                 np.array([1.0, 1.0]), np.array([0.9, 0.9], np.float32),
                 ll, ll >= 0.5, group=0, tick=2)
    assert w.fenced_drops == 2
    assert co.correlated == 2  # refused lines never entered a window


@pytest.mark.quick
def test_topology_workload_rejects_cascade_past_stream_end():
    from rtap_tpu.data.synthetic import (
        SyntheticStreamConfig,
        generate_topology_workload,
    )

    with pytest.raises(ValueError, match="cascade does not fit"):
        generate_topology_workload(
            nodes_per_service=40, cascade_lag=3, burst_at_frac=0.75,
            cfg=SyntheticStreamConfig(length=400, n_anomalies=0))


@pytest.mark.quick
def test_open_windows_gauge_refreshes_on_expired_close():
    """An expired-below-threshold close must refresh the gauge even
    while other windows stay open (operators read it against
    min-streams tuning — TELEMETRY.md)."""
    co = _correlator(sink=lambda _r: None, min_streams=3)
    co.observe_alert("a1", "web-00.cpu", 100)   # cluster web+db (linked)
    co.observe_alert("a2", "batch-00.cpu", 103) # cluster batch
    assert co._obs_open.value == 2
    # web quiesces (2 streams < 3: expires silently); batch stays open
    co.observe_alert("a3", "batch-00.mem", 106)
    co.on_tick(106)
    assert co.expired == 1
    assert co._obs_open.value == 1


@pytest.mark.quick
def test_storm_cap_still_tracks_blast_radius(monkeypatch):
    """Past MAX_MEMBERS_PER_WINDOW, member ids are counted-not-stored —
    but streams/nodes/fields keep accumulating (bounded by fleet size),
    so min_streams decisions and blast_dump_nodes triggers never
    under-count in a fleet-wide storm."""
    import rtap_tpu.correlate.incidents as mod

    monkeypatch.setattr(mod, "MAX_MEMBERS_PER_WINDOW", 2)
    got = []
    co = _correlator(sink=got.append, min_streams=3)
    co.observe_alert("a1", "web-00.cpu", 100)
    co.observe_alert("a2", "web-00.mem", 100)
    co.observe_alert("a3", "web-01.cpu", 100)  # past the cap
    co.on_tick(200)
    assert got and got[0]["members_dropped"] == 1
    assert got[0]["streams"] == ["web-00.cpu", "web-00.mem", "web-01.cpu"]
    assert got[0]["nodes"] == ["web-00", "web-01"]


class TestResumeSidecar:
    """The <alerts>.corr floor: a checkpoint cursor PAST an open
    window's earlier members must not shrink the re-folded member set
    (the content-hash incident_id would diverge)."""

    def _alert_line(self, aid, stream, ts):
        return json.dumps({"alert_id": aid, "stream": stream, "ts": ts,
                           "value": 1.0, "raw_score": 0.9,
                           "log_likelihood": 0.9}) + "\n"

    def test_refold_from_sidecar_reproduces_incident_id(self, tmp_path):
        sink = tmp_path / "alerts.jsonl"
        side = str(sink) + ".corr"
        # live run: two members fold while the window is open; a
        # checkpoint saves with its alert cursor at EOF (past both)
        got = []
        live = _correlator(sink=got.append, min_streams=2,
                           sidecar_path=side)
        off = 0
        with open(sink, "w") as f:
            for aid, stream, ts in (("0:web-00.cpu:5", "web-00.cpu", 100),
                                    ("0:web-01.cpu:6", "web-01.cpu", 101)):
                line = self._alert_line(aid, stream, ts)
                live.observe_alert(aid, stream, ts, sink_offset=off)
                f.write(line)
                off += len(line)
        cursor = off  # the checkpoint's alerts_offset: past both members
        # reference: the uninterrupted run closes the window later
        ref_id = None
        live2 = _correlator(sink=got.append, min_streams=2)
        live2.observe_alert("0:web-00.cpu:5", "web-00.cpu", 100)
        live2.observe_alert("0:web-01.cpu:6", "web-01.cpu", 101)
        live2.on_tick(200)
        ref_id = got[-1]["incident_id"]
        # crash here. Resume: the sidecar floor (0, before member 1)
        # must win over the cursor — the re-fold reconstructs the FULL
        # member set and hashes the reference id
        res = []
        resumed = _correlator(sink=res.append, min_streams=2,
                              sidecar_path=side)
        start = resumed.resume_scan_offset(cursor)
        assert start == 0  # sidecar floor beats the cursor
        resumed.resume_from(str(sink), start)
        resumed.on_tick(200)
        assert res and res[-1]["incident_id"] == ref_id
        # the buggy pre-sidecar behavior (scan from the cursor) would
        # have re-folded nothing and emitted no/other incident

    def test_sidecar_advances_when_all_windows_close(self, tmp_path):
        side = str(tmp_path / "a.jsonl.corr")
        co = _correlator(sink=lambda _r: None, min_streams=2,
                         sidecar_path=side)
        co.observe_alert("a1", "web-00.cpu", 100, sink_offset=40)
        assert json.load(open(side))["offset"] == 40
        co.on_tick(200, sink_offset=777)  # window expires; none open
        assert json.load(open(side))["offset"] == 777
        assert co.resume_scan_offset(1000) == 777  # clamped to sidecar
        assert co.resume_scan_offset(500) == 500   # never past the cursor

    def test_refold_boundary_gap_matches_live_merge(self, tmp_path):
        """A member landing at a gap of EXACTLY window_s+1 merged live
        (a tick's alerts fold BEFORE its on_tick, so the last close
        check live made saw the previous second); the re-fold must
        reproduce that merge — advancing the scan clock to the member's
        own ts first would close the window early, expire it below
        min_streams, and lose the incident."""
        sink = tmp_path / "alerts.jsonl"
        sink.write_text(
            self._alert_line("0:web-00.cpu:1", "web-00.cpu", 100)
            + self._alert_line("0:web-01.cpu:2", "web-01.cpu", 106))
        # live: the gap-6 member (window_s=5) folds at tick 106 before
        # that tick's close check runs — ONE window, one incident
        got = []
        ref = _correlator(sink=got.append, min_streams=2)
        ref.observe_alert("0:web-00.cpu:1", "web-00.cpu", 100)
        ref.on_tick(105)  # the last close check before the fold: open
        ref.observe_alert("0:web-01.cpu:2", "web-01.cpu", 106)
        ref.on_tick(200)
        ref_id = got[-1]["incident_id"]
        # crash after the close: the re-fold must hash the same id
        res = []
        co = _correlator(sink=res.append, min_streams=2)
        co.resume_from(str(sink), 0)
        co.on_tick(200)
        assert res and res[-1]["incident_id"] == ref_id

    def test_resumed_window_anchors_floor_at_scan_start(self, tmp_path):
        """A window re-opened by the re-fold must anchor the sidecar
        floor at the scan start: a cluster opening LIVE afterwards (at a
        far-later sink offset) must not advance the persisted floor past
        the resumed window's earlier members — a second crash would
        re-fold a smaller member set and hash a divergent incident_id."""
        sink = tmp_path / "alerts.jsonl"
        side = str(sink) + ".corr"
        sink.write_text(
            self._alert_line("0:web-00.cpu:1", "web-00.cpu", 100)
            + self._alert_line("0:web-01.cpu:2", "web-01.cpu", 101))
        # reference: the uninterrupted run's full-member incident id
        got = []
        ref = _correlator(sink=got.append, min_streams=2)
        ref.observe_alert("0:web-00.cpu:1", "web-00.cpu", 100)
        ref.observe_alert("0:web-01.cpu:2", "web-01.cpu", 101)
        ref.on_tick(200)
        ref_id = got[-1]["incident_id"]
        # crash 1 -> resume: web's window re-opens during the scan
        co = _correlator(sink=lambda _r: None, min_streams=2,
                         sidecar_path=side)
        co.resume_from(str(sink), 0)
        # batch opens LIVE at a sink offset far past web's members
        co.observe_alert("0:batch-00.cpu:9", "batch-00.cpu", 102,
                         sink_offset=4096)
        assert json.load(open(side))["offset"] == 0  # web pins the floor
        # crash 2 while web is still open: the re-fold from the floor
        # rebuilds the FULL member set and hashes the reference id
        res2 = []
        co2 = _correlator(sink=res2.append, min_streams=2,
                          sidecar_path=side)
        start = co2.resume_scan_offset(10_000)
        assert start == 0
        co2.resume_from(str(sink), start)
        co2.on_tick(200)
        assert res2 and res2[-1]["incident_id"] == ref_id

    def test_missing_sidecar_scans_from_cursor(self, tmp_path):
        """No sidecar = no window ever opened under correlation: the
        scan starts at the checkpoints' cursor, NOT byte 0 — arming
        --topology on a sink with history must not re-fold (and page)
        every long-past burst at startup."""
        co = _correlator(sink=lambda _r: None,
                         sidecar_path=str(tmp_path / "nope.corr"))
        assert co.resume_scan_offset(12345) == 12345
        assert co.resume_scan_offset(-3) == 0

    def test_event_line_settles_cluster_mid_scan(self, tmp_path):
        """A pipeline-lagged alert whose ts sits just inside the window
        band must NOT merge into an already-closed window on re-fold:
        the incident event line pins the live closure point."""
        sink = tmp_path / "alerts.jsonl"
        got = []
        co = _correlator(sink=got.append, min_streams=2, window_s=5)
        lines = [self._alert_line("0:web-00.cpu:1", "web-00.cpu", 100),
                 self._alert_line("0:web-01.cpu:2", "web-01.cpu", 101)]
        inc_id = incident_id_of(["0:web-00.cpu:1", "0:web-01.cpu:2"])
        lines.append(json.dumps(
            {"event": "incident", "incident_id": inc_id, "cluster": "db",
             "members": 2,
             "alert_ids": ["0:web-00.cpu:1", "0:web-01.cpu:2"]}) + "\n")
        # lagged alert: ts 104 is within window_s of last_ts 101, but
        # live had already closed (tick clock ran ahead) — the event
        # line above is the proof
        lines.append(self._alert_line("0:web-00.mem:9", "web-00.mem", 104))
        sink.write_text("".join(lines))
        res = co.resume_from(str(sink), 0)
        assert res["incidents_known"] == 1
        # the lagged alert sits in a FRESH window (1 member), not merged
        snap = co.snapshot()
        assert list(snap["open_windows"].values())[0]["members"] == 1
        # and closing it stays below min_streams: no duplicate page
        co.on_tick(300)
        assert co.incidents == 0 and co.deduped == 0
