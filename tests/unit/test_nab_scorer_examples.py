"""NAB scorer worked examples — exact hand-computed values (r4 verdict #5).

The scorer previously carried only endpoint/property tests (null=0,
perfect=100); silent drift in the sigmoid weighting, FP decay, probation
trim, or threshold sweep would have transferred into any corpus number.
These tests pin the published scoring definition with values derived
INDEPENDENTLY in the test body (explicit exp() formulas, not calls back
into the scorer), covering the NAB paper's canonical cases: TP at window
start (+0.9866 before weighting), late TP, second-detection-ignored,
FP-before-any-window (flat -1), FP decay after a window (-0.9866 at one
window-width), FN cost per profile, probation trim, multi-window files,
and the exhaustive threshold sweep's equivalence with direct re-scoring.

Scoring definition per SURVEY.md C23/§3.4 (the NAB paper "Evaluating
Real-Time Anomaly Detection Algorithms" + nab/sweeper.py semantics).
"""

import math

import numpy as np
import pytest

from rtap_tpu.nab.scorer import (
    PROFILES,
    optimize_threshold,
    probation_rows,
    scaled_sigmoid,
    score_corpus,
    score_file,
)

# independent derivation of NAB's scaled sigmoid: 2/(1+e^(5x)) - 1
def _sig(x: float) -> float:
    return 2.0 / (1.0 + math.exp(5.0 * x)) - 1.0


STD = PROFILES["standard"]
LOW_FP = PROFILES["reward_low_FP"]
LOW_FN = PROFILES["reward_low_FN"]

T = np.arange(100, dtype=np.int64)  # 100 rows at 1 s cadence
WIN = [(40, 49)]  # rows 40..49 inclusive, width (r - l) = 9


def det(*rows: int) -> np.ndarray:
    d = np.zeros(100, bool)
    for r in rows:
        d[r] = True
    return d


class TestWorkedExamples:
    def test_probation_is_15_percent_capped(self):
        assert probation_rows(100) == 15
        assert probation_rows(5000) == 750
        assert probation_rows(20_000) == 750  # cap at 5000 rows

    def test_tp_at_window_start(self):
        # rel = (40-49)/9 = -1 -> sigma(-1) = 2/(1+e^-5)-1 = 0.98661...
        expect = _sig(-5.0 / 5.0 * 5.0 / 5.0 * 5.0)  # keep explicit below
        expect = _sig(-1.0)
        assert expect == pytest.approx(0.9866142981514305, abs=1e-12)
        assert score_file(det(40), T, WIN, STD) == pytest.approx(expect, abs=1e-12)

    def test_tp_at_window_end_scores_zero(self):
        # rel = 0 -> sigma(0) = 0
        assert score_file(det(49), T, WIN, STD) == pytest.approx(0.0, abs=1e-12)

    def test_late_tp_partial_credit(self):
        # row 47: rel = (47-49)/9 = -2/9 -> sigma(-2/9)
        expect = _sig(-2.0 / 9.0)
        assert expect == pytest.approx(0.5046723977218568, abs=1e-12)
        assert score_file(det(47), T, WIN, STD) == pytest.approx(expect, abs=1e-12)

    def test_second_detection_in_window_ignored(self):
        # rows 41 and 45: only the FIRST (41) is credited
        expect = _sig((41 - 49) / 9.0)
        assert score_file(det(41, 45), T, WIN, STD) == pytest.approx(
            expect, abs=1e-12
        )

    def test_miss_costs_fn_weight_per_profile(self):
        assert score_file(det(), T, WIN, STD) == pytest.approx(-1.0)
        assert score_file(det(), T, WIN, LOW_FN) == pytest.approx(-2.0)

    def test_fp_before_any_window_is_flat_minus_one(self):
        # row 20 precedes the window: flat -1 * fp_weight, plus the FN
        assert score_file(det(20), T, WIN, STD) == pytest.approx(-0.11 - 1.0)
        assert score_file(det(20), T, WIN, LOW_FP) == pytest.approx(-0.22 - 1.0)

    def test_fp_after_window_sigmoid_decay(self):
        # row 58: rel = (58-49)/9 = +1 -> sigma(1) = -0.98661...
        expect = 0.11 * _sig(1.0) - 1.0  # decayed FP + missed window
        assert score_file(det(58), T, WIN, STD) == pytest.approx(expect, abs=1e-12)

    def test_fp_far_after_window_saturates_at_minus_one(self):
        # row 77: rel = (77-49)/9 = 3.11 > 3 -> flat -1
        assert score_file(det(77), T, WIN, STD) == pytest.approx(0.11 * -1.0 - 1.0)

    def test_probation_detection_ignored(self):
        # row 14 is inside the 15-row probation: contributes nothing
        assert score_file(det(14), T, WIN, STD) == pytest.approx(-1.0)  # FN only

    def test_multi_window_file(self):
        wins = [(20, 29), (60, 69)]
        # detect only the second window at its start; first window missed
        expect = _sig(-1.0) - 1.0
        assert score_file(det(60), T, wins, STD) == pytest.approx(expect, abs=1e-12)
        # detect both at start
        assert score_file(det(20, 60), T, wins, STD) == pytest.approx(
            2 * _sig(-1.0), abs=1e-12
        )

    def test_tp_and_fp_combined(self):
        # TP at 40 plus an FP at 18 (post-probation, before any window)
        expect = _sig(-1.0) - 0.11
        assert score_file(det(40, 18), T, WIN, STD) == pytest.approx(
            expect, abs=1e-12
        )

    def test_scaled_sigmoid_reference_points(self):
        assert scaled_sigmoid(-1.0) == pytest.approx(_sig(-1.0), abs=1e-15)
        assert scaled_sigmoid(0.0) == 0.0
        assert scaled_sigmoid(1.0) == pytest.approx(_sig(1.0), abs=1e-15)
        assert scaled_sigmoid(3.01) == -1.0  # hard floor beyond 3 widths


class TestNormalizedCorpus:
    def _scores(self, rows, n=100):
        s = np.zeros(n)
        for r in rows:
            s[r] = 1.0
        return s

    def test_perfect_and_null_endpoints(self):
        per_file = [
            (self._scores([40]), T, WIN),
            (self._scores([20, 60]), T, [(20, 29), (60, 69)]),
        ]
        assert score_corpus(per_file, 0.5, STD) == pytest.approx(100.0)
        assert score_corpus(per_file, 1.1, STD) == pytest.approx(0.0)

    def test_hand_computed_mid_corpus_score(self):
        # file 1: TP at window end (raw 0); file 2: miss (-1) + flat FP
        # (-0.11) at row 18 — post-probation, before the window
        per_file = [
            (self._scores([49]), T, WIN),
            (self._scores([18]), T, [(60, 69)]),
        ]
        raw = 0.0 + (-1.0 - 0.11)
        perfect = 2 * _sig(-1.0)
        null = -2.0
        expect = 100.0 * (raw - null) / (perfect - null)
        assert score_corpus(per_file, 0.5, STD) == pytest.approx(expect, abs=1e-9)


class TestExhaustiveSweep:
    def test_sweep_finds_isolated_optimum_quantiles_would_miss(self):
        # one window; the ONLY good threshold is a single high score value
        # carried by the in-window row, while 5000 low-score FP rows pull
        # every low threshold deep negative. A ~200-quantile sweep of this
        # distribution can skip the isolated optimum; exhaustive cannot.
        n = 5000
        ts = np.arange(n, dtype=np.int64)
        scores = np.random.default_rng(0).uniform(0.0, 0.90, n)
        wins = [(4000, 4099)]
        scores[4000] = 0.977731  # unique, not on any quantile grid
        hi = scores.max()
        per_file = [(scores, ts, wins)]
        t, s = optimize_threshold(per_file, STD)
        assert t == pytest.approx(0.977731)
        assert s == pytest.approx(100.0)
        # direct confirmation at the found threshold
        assert score_corpus(per_file, t, STD) == pytest.approx(s, abs=1e-9)
        assert score_corpus(per_file, hi + 1e-6, STD) == pytest.approx(0.0)

    @pytest.mark.parametrize("profile", ["standard", "reward_low_FP",
                                         "reward_low_FN"])
    def test_incremental_sweep_equals_direct_rescoring(self, profile):
        """Property: for randomized corpora, the O(n log n) incremental
        sweep returns exactly max over distinct thresholds of the direct
        scorer, for every profile."""
        rng = np.random.default_rng(7)
        prof = PROFILES[profile]
        for trial in range(8):
            files = []
            for _ in range(rng.integers(1, 4)):
                n = int(rng.integers(60, 220))
                ts = np.arange(n, dtype=np.int64)
                scores = np.round(rng.uniform(0, 1, n), 2)  # force ties
                wins = []
                lo = 20
                while lo + 12 < n and rng.random() < 0.7:
                    hi = lo + int(rng.integers(3, 10))
                    wins.append((lo, hi))
                    lo = hi + int(rng.integers(8, 25))
                files.append((scores, ts, wins))
            t_fast, s_fast = optimize_threshold(files, prof)
            cands = np.unique(np.concatenate([f[0] for f in files] + [[1.1]]))
            direct = [(score_corpus(files, float(c), prof), float(c))
                      for c in cands]
            s_best, _ = max(direct)
            assert s_fast == pytest.approx(s_best, abs=1e-9), (trial, profile)
            assert score_corpus(files, t_fast, prof) == pytest.approx(
                s_fast, abs=1e-9
            ), (trial, profile)
