"""Fleet merge core + push protocol (ISSUE 19).

Coverage pins the tentpole's merge semantics and wire discipline:

- QuantileSketch.merge: fuzz — the MERGED sketch's quantiles track
  ``numpy.percentile`` over the POOLED samples (the whole point: fleet
  p99 is the p99 of the pooled observations, never max-of-member-p99s),
  plus merge-of-empty, disjoint bucket geometry (ValueError), and
  window-roll state carried losslessly through state()/from_state().
- merge_metrics: counters sum per (name, labels); gauges gain a
  ``member`` label instead of a dishonest sum.
- merge_slo: pooled window counts + merged-sketch observed quantile;
  window-length conflicts surfaced, not pooled.
- FleetWalker: torn tails wait, CRC corruption resyncs past the bad
  record, well-framed unknown in-band types are skipped whole
  (version skew), out-of-band types are garbage.
- set_build_info: constant-1 identity gauge; the config hash is stable
  per config and moves when the config does.
"""

import json

import numpy as np
import pytest

from rtap_tpu.fleet import (
    FLEET_HELLO,
    FLEET_SNAP,
    FleetWalker,
    merge_metrics,
    merge_sketches,
    merge_slo,
    pack_fleet,
    unpack_payload,
)
from rtap_tpu.obs.health import config_digest, set_build_info
from rtap_tpu.obs.latency import QuantileSketch
from rtap_tpu.obs.metrics import TelemetryRegistry

pytestmark = pytest.mark.quick


# ------------------------------------------------------- sketch merge --
@pytest.mark.parametrize("members", [2, 5])
@pytest.mark.parametrize("dist", ["uniform", "lognormal", "skewed_split"])
def test_merged_sketch_quantiles_fuzz_vs_pooled_numpy(members, dist):
    """Split one pooled sample set across member sketches, merge, and
    pin the merged quantiles against numpy.percentile of the POOL —
    within one bucket ratio, exactly like a single sketch over the same
    data (losslessness means the split is invisible)."""
    rng = np.random.default_rng(members * 7 + hash(dist) % 2**16)
    n = 20_000
    if dist == "uniform":
        vals = rng.uniform(1e-3, 5.0, n)
    elif dist == "lognormal":
        vals = rng.lognormal(-2.0, 1.2, n)
    else:
        # the failover shape: one member fast, the others slow — a
        # max-of-p99s "merge" would be grossly wrong here
        vals = np.concatenate([rng.normal(0.005, 0.001, n // 4),
                               rng.normal(1.0, 0.2, 3 * n // 4)])
    vals = np.clip(vals, 1e-4, 99.0)
    parts = np.array_split(rng.permutation(vals), members)
    states = []
    for part in parts:
        sk = QuantileSketch()
        sk.observe_many(part)
        states.append(json.loads(json.dumps(sk.state())))  # wire form
    merged = merge_sketches(states)
    assert merged is not None
    single = QuantileSketch()
    single.observe_many(vals)
    ratio = 10 ** (1 / 20)
    for q in (0.5, 0.9, 0.99):
        exact = float(np.percentile(vals, q * 100))
        est = merged.quantile(q, "total")
        assert est is not None
        assert exact / ratio <= est <= exact * ratio, (
            f"{dist}/{members}m p{q * 100}: pooled {exact}, merged {est}")
        # merged == single-sketch-over-pool, bucket for bucket
        assert est == single.quantile(q, "total")
    st = merged.state()
    assert int(np.sum(st["total"])) == len(vals)
    assert st["max"] == pytest.approx(float(vals.max()))
    assert st["sum"] == pytest.approx(float(vals.sum()))


def test_merge_sketches_empty_and_zero_count():
    assert merge_sketches([]) is None
    empty = QuantileSketch().state()
    loaded = QuantileSketch()
    loaded.observe_many([0.01, 0.02, 0.03])
    merged = merge_sketches([empty, loaded.state()])
    assert merged.count("total") == 3
    assert merged.quantile(0.5, "total") == loaded.quantile(0.5, "total")


def test_merge_rejects_disjoint_bucket_geometry():
    a = QuantileSketch(per_decade=20)
    b = QuantileSketch(per_decade=10)
    with pytest.raises(ValueError, match="bucket edges"):
        a.merge(b)
    with pytest.raises(ValueError, match="bucket edges"):
        merge_sketches([a.state(), b.state()])


def test_from_state_rejects_wrong_count_length():
    st = QuantileSketch().state()
    st["cur"] = st["cur"][:-2]
    with pytest.raises(ValueError, match="wrong length"):
        QuantileSketch.from_state(st)


def test_window_roll_survives_state_roundtrip_and_merge():
    """cur/prev window split is carried losslessly: a member that rolled
    its window mid-push must merge with the same one-to-two-window
    coverage a local sketch would report."""
    sk = QuantileSketch()
    sk.observe_many([0.010] * 50)
    sk.roll()
    sk.observe_many([1.0] * 50)
    rt = QuantileSketch.from_state(json.loads(json.dumps(sk.state())))
    assert rt.rolls == sk.rolls == 1
    for scope in ("window", "total"):
        assert rt.count(scope) == sk.count(scope)
        assert rt.quantile(0.5, scope) == sk.quantile(0.5, scope)
    other = QuantileSketch()
    other.observe_many([0.10] * 100)
    merged = merge_sketches([sk.state(), other.state()])
    # window scope = cur+prev of BOTH members (100 + 100 observations)
    assert merged.count("window") == 200
    assert merged.count("total") == 200


# ------------------------------------------------------- metrics merge --
def _snap(rows):
    return {"metrics": {"metrics": rows}}


def test_merge_metrics_sums_counters_and_labels_gauges():
    snaps = {
        "A": _snap([
            {"name": "rtap_obs_ticks_total", "type": "counter",
             "value": 10},
            {"name": "rtap_obs_x_total", "type": "counter",
             "labels": {"k": "1"}, "value": 3},
            {"name": "rtap_obs_run_epoch", "type": "gauge", "value": 2},
        ]),
        "B": _snap([
            {"name": "rtap_obs_ticks_total", "type": "counter",
             "value": 32},
            {"name": "rtap_obs_x_total", "type": "counter",
             "labels": {"k": "2"}, "value": 5},
            {"name": "rtap_obs_run_epoch", "type": "gauge", "value": 4},
        ]),
    }
    out = merge_metrics(snaps)
    by_key = {(c["name"], tuple(sorted((c.get("labels") or {}).items()))):
              c for c in out["counters"]}
    assert by_key[("rtap_obs_ticks_total", ())]["value"] == 42
    assert by_key[("rtap_obs_ticks_total", ())]["members"] == 2
    # label sets are separate fleet totals, never pooled across labels
    assert by_key[("rtap_obs_x_total", (("k", "1"),))]["value"] == 3
    assert by_key[("rtap_obs_x_total", (("k", "2"),))]["value"] == 5
    gauges = {(g["name"], g["labels"]["member"]): g["value"]
              for g in out["gauges"]}
    assert gauges[("rtap_obs_run_epoch", "A")] == 2
    assert gauges[("rtap_obs_run_epoch", "B")] == 4


# ----------------------------------------------------------- slo merge --
def _slo_snap(bad, total, sketch_vals, fast_w=60, slow_w=600):
    sk = QuantileSketch()
    sk.observe_many(sketch_vals)
    return {
        "slo": [{"stage": "tick", "target_s": 0.05, "quantile": 0.99,
                 "fast_window_ticks": fast_w, "slow_window_ticks": slow_w,
                 "fast_bad": bad, "fast_total": total,
                 "slow_bad": bad, "slow_total": total,
                 "cum_bad": bad, "cum_total": total, "burn_events": 0}],
        "latency": {"sketches": {"tick": sk.state()}},
    }


def test_merge_slo_pools_counts_and_uses_merged_sketch():
    rng = np.random.default_rng(3)
    fast = rng.uniform(0.001, 0.01, 500)   # member A: comfortably in SLO
    slow = rng.uniform(0.2, 0.4, 500)      # member B: all bad
    snaps = {"A": _slo_snap(0, 500, fast), "B": _slo_snap(500, 500, slow)}
    out = merge_slo(snaps)
    (v,) = out["slos"]
    assert v["samples"] == 1000 and v["bad"] == 500
    assert v["met"] is False and out["met"] is False
    assert sorted(v["members"]) == ["A", "B"]
    # the merged-sketch p99 lands in B's slow mode — and equals the
    # pooled percentile within a bucket ratio (not max of member p99s,
    # which this case cannot distinguish; losslessness is pinned above)
    pooled = float(np.percentile(np.concatenate([fast, slow]), 99))
    ratio = 10 ** (1 / 20)
    assert pooled / ratio <= v["observed_quantile_s"] <= pooled * ratio


def test_merge_slo_surfaces_window_conflicts():
    snaps = {"A": _slo_snap(0, 100, [0.01] * 10),
             "B": _slo_snap(0, 100, [0.01] * 10, fast_w=120)}
    out = merge_slo(snaps)
    (v,) = out["slos"]
    assert v["samples"] == 100  # the conflicting member is NOT pooled
    assert out["window_conflicts"][0]["member"] == "B"


# ------------------------------------------------------------ protocol --
def test_walker_roundtrip_torn_tail_and_resync():
    frames = (pack_fleet(FLEET_HELLO, {"member": "A"})
              + pack_fleet(FLEET_SNAP, {"member": "A", "seq": 1}))
    w = FleetWalker()
    # torn tail: first half yields only complete records, rest completes
    cut = len(frames) - 7
    got = w.feed(frames[:cut])
    got += w.feed(frames[cut:])
    assert [t for t, _ in got] == [FLEET_HELLO, FLEET_SNAP]
    assert unpack_payload(got[1][1])["seq"] == 1
    assert w.garbage_bytes == 0 and w.bad_crc == 0

    # CRC corruption: the bad record is garbage, the next one recovers
    bad = bytearray(pack_fleet(FLEET_SNAP, {"member": "A", "seq": 2}))
    bad[12] ^= 0xFF
    w2 = FleetWalker()
    got = w2.feed(bytes(bad) + pack_fleet(FLEET_SNAP, {"seq": 3}))
    assert [unpack_payload(p)["seq"] for _, p in got] == [3]
    assert w2.bad_crc == 1 and w2.garbage_bytes > 0

    # leading garbage before the first magic
    w3 = FleetWalker()
    got = w3.feed(b"NOISE" + pack_fleet(FLEET_SNAP, {"seq": 4}))
    assert [unpack_payload(p)["seq"] for _, p in got] == [4]
    assert w3.garbage_bytes == 5


def test_walker_skips_version_skew_keeps_stream():
    """A well-framed record in the fleet band with an unknown type is
    dropped WHOLE and counted — never desyncs the records around it."""
    future = pack_fleet(40, {"new_field": True})  # in-band, unknown
    stream = (pack_fleet(FLEET_SNAP, {"seq": 1}) + future
              + pack_fleet(FLEET_SNAP, {"seq": 2}))
    w = FleetWalker()
    got = w.feed(stream)
    assert [unpack_payload(p)["seq"] for _, p in got] == [1, 2]
    assert w.skew_skipped == 1 and w.garbage_bytes == 0
    # a FUTURE PAYLOAD VERSION on a known type: framing passes, the
    # payload decode refuses to guess
    newer = json.dumps({"v": 99, "member": "A"}).encode()
    assert unpack_payload(newer) is None
    # out-of-band type (a journal record in the fleet stream) = garbage
    w2 = FleetWalker()
    from rtap_tpu.resilience.journal import _CRC, _HEADER, _MAGIC
    import zlib
    head = _HEADER.pack(_MAGIC, 1, 2)  # journal TICK type
    rogue = head + b"{}" + _CRC.pack(zlib.crc32(head[2:] + b"{}"))
    got = w2.feed(rogue + pack_fleet(FLEET_SNAP, {"seq": 5}))
    assert [unpack_payload(p)["seq"] for _, p in got] == [5]
    assert w2.garbage_bytes > 0 and w2.skew_skipped == 0


def test_pack_fleet_rejects_out_of_band_type():
    with pytest.raises(ValueError, match="fleet band"):
        pack_fleet(1, {})
    with pytest.raises(ValueError, match="fleet band"):
        pack_fleet(48, {})


# ---------------------------------------------------------- build info --
def test_build_info_gauge_and_config_hash():
    reg = TelemetryRegistry()
    h = set_build_info(role="leader", shard=0, run_epoch=3,
                       config={"cols": 2048, "cells": 32}, registry=reg)
    assert h == config_digest({"cols": 2048, "cells": 32})
    # key order must not move the hash; content must
    assert h == config_digest({"cells": 32, "cols": 2048})
    assert h != config_digest({"cols": 4096, "cells": 32})
    rows = [r for r in reg.snapshot()["metrics"]
            if r["name"] == "rtap_obs_build_info"]
    assert len(rows) == 1
    (row,) = rows
    assert row["value"] == 1
    assert row["labels"] == {"role": "leader", "shard": "0",
                             "run_epoch": "3", "config_hash": h}


# ------------------------------------------------------------- budget --
def test_fleet_publisher_overhead_within_one_percent_of_tick_budget():
    """The CI twin of the bench.py --obs-bench bar: even at the soak
    push density (two full snapshot builds per tick over a populated
    registry and full sketch windows) the fleet publisher stays host-
    noise, and note_tick — the only fleet op ON the tick path — is one
    guarded int store."""
    from rtap_tpu.obs.selfbench import measure_fleet

    res = measure_fleet(n=300)
    assert res["per_tick_overhead_frac"] <= 0.01, res
