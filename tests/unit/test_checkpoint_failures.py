"""Checkpoint FAILURE paths (ISSUE 2 satellite): crash-mid-save residue
recovery/cleanup, corrupt/truncated meta.json, config-mismatch resume —
each must fail loudly (or recover explicitly) without ever touching the
good checkpoint. The happy path lives in tests/unit/test_checkpoint.py."""

import json
import shutil

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset, scaled_cluster_preset
from rtap_tpu.obs import get_registry
from rtap_tpu.service.checkpoint import (
    _recover_residue,
    load_group,
    save_group,
    validate_resume,
)
from rtap_tpu.service.registry import StreamGroup


def _group(ticks=3, cfg=None):
    grp = StreamGroup(cfg or cluster_preset(), ["a", "b"], backend="cpu")
    for i in range(ticks):
        grp.tick(np.array([1.0 + i, 2.0 + i], np.float32),
                 1_700_000_000 + i)
    return grp


def _dir_signature(path):
    """Every file under `path` with its mtime — 'untouched' means equal."""
    return sorted((str(p.relative_to(path)), p.stat().st_mtime_ns)
                  for p in path.rglob("*"))


# ---- crash-mid-save residue ----------------------------------------


def test_recover_residue_renames_complete_old_sibling(tmp_path):
    """Crash window: old checkpoint renamed aside, tmp not yet renamed in
    (or lost). load_group must recover the complete .old-* sibling."""
    grp = _group(ticks=4)
    save_group(grp, tmp_path / "g")
    # simulate the crash: the swap moved the good dir aside and died
    (tmp_path / "g").rename(tmp_path / ".g.old-deadbeef")
    back = load_group(tmp_path / "g")
    assert back.ticks == 4
    assert (tmp_path / "g" / "meta.json").exists()
    assert not (tmp_path / ".g.old-deadbeef").exists()


def test_recover_residue_prefers_newest_and_ignores_incomplete(tmp_path):
    grp = _group(ticks=2)
    save_group(grp, tmp_path / "g")
    grp.tick(np.array([9.0, 9.0], np.float32), 1_700_000_099)
    save_group(grp, tmp_path / "g2")
    # two residue candidates: an INCOMPLETE tmp (no meta.json — the
    # completeness marker) and a complete old; only the complete one counts
    (tmp_path / ".g.tmp-junk").mkdir()
    (tmp_path / "g2").rename(tmp_path / ".g.old-newer")
    shutil.rmtree(tmp_path / "g")
    got = _recover_residue(tmp_path / "g")
    assert got == tmp_path / "g"
    assert load_group(tmp_path / "g").ticks == 3  # the newer candidate
    assert (tmp_path / ".g.tmp-junk").exists()  # incomplete: not touched


def test_recover_residue_noop_when_checkpoint_intact(tmp_path):
    grp = _group()
    save_group(grp, tmp_path / "g")
    (tmp_path / ".g.old-stale").mkdir()  # stale residue, no meta.json
    sig = _dir_signature(tmp_path / "g")
    assert _recover_residue(tmp_path / "g") == tmp_path / "g"
    assert _dir_signature(tmp_path / "g") == sig  # untouched


def test_next_save_sweeps_prior_residue_only_after_landing(tmp_path):
    grp = _group()
    save_group(grp, tmp_path / "g")
    (tmp_path / ".g.tmp-crashed").mkdir()
    (tmp_path / ".g.old-crashed").mkdir()
    grp.tick(np.array([3.0, 4.0], np.float32), 1_700_000_050)
    save_group(grp, tmp_path / "g")  # lands, then sweeps
    residue = [p.name for p in tmp_path.iterdir() if p.name != "g"]
    assert residue == [], residue
    assert load_group(tmp_path / "g").ticks == 4


# ---- corrupt / truncated meta.json ---------------------------------


def test_corrupt_meta_fails_loudly(tmp_path):
    grp = _group()
    save_group(grp, tmp_path / "g")
    (tmp_path / "g" / "meta.json").write_text("not json {{{")
    with pytest.raises(json.JSONDecodeError):
        load_group(tmp_path / "g")


def test_truncated_meta_fails_loudly_and_good_sibling_untouched(tmp_path):
    grp = _group(ticks=5)
    save_group(grp, tmp_path / "good")
    save_group(grp, tmp_path / "bad")
    meta = (tmp_path / "bad" / "meta.json").read_text()
    (tmp_path / "bad" / "meta.json").write_text(meta[: len(meta) // 2])
    sig = _dir_signature(tmp_path / "good")
    with pytest.raises(json.JSONDecodeError):
        load_group(tmp_path / "bad")
    # the failure touched nothing else: the good checkpoint still loads
    assert _dir_signature(tmp_path / "good") == sig
    assert load_group(tmp_path / "good").ticks == 5


def test_missing_meta_without_residue_fails_loudly(tmp_path):
    grp = _group()
    save_group(grp, tmp_path / "g")
    (tmp_path / "g" / "meta.json").unlink()
    with pytest.raises(FileNotFoundError):
        load_group(tmp_path / "g")


# ---- resume config mismatch ----------------------------------------


def test_resume_config_mismatch_fails_without_touching_checkpoint(tmp_path):
    grp = _group(ticks=4)
    save_group(grp, tmp_path / "g")
    sig = _dir_signature(tmp_path / "g")
    resumed = load_group(tmp_path / "g")
    other = StreamGroup(scaled_cluster_preset(32), ["a", "b"],
                        backend="cpu")
    with pytest.raises(ValueError, match="disagrees"):
        validate_resume(resumed, tmp_path / "g", other)
    # threshold mismatch is the same class of error
    other2 = StreamGroup(cluster_preset(), ["a", "b"], backend="cpu",
                         threshold=0.9)
    with pytest.raises(ValueError, match="threshold"):
        validate_resume(resumed, tmp_path / "g", other2)
    # stream-id mismatch too
    other3 = StreamGroup(cluster_preset(), ["a", "c"], backend="cpu")
    with pytest.raises(ValueError, match="refusing to resume"):
        validate_resume(resumed, tmp_path / "g", other3)
    assert _dir_signature(tmp_path / "g") == sig
    assert load_group(tmp_path / "g").ticks == 4


# ---- failed save leaves the previous checkpoint intact -------------


def test_failed_save_leaves_previous_checkpoint_intact(tmp_path,
                                                       monkeypatch):
    import orbax.checkpoint as ocp

    grp = _group(ticks=3)
    save_group(grp, tmp_path / "g")
    sig = _dir_signature(tmp_path / "g")
    failures = get_registry().counter(
        "rtap_obs_checkpoint_save_failures_total")
    before = failures.value
    grp.tick(np.array([7.0, 8.0], np.float32), 1_700_000_060)

    def boom(self, *a, **kw):
        raise OSError(28, "no space left on device")

    monkeypatch.setattr(ocp.PyTreeCheckpointer, "save", boom)
    with pytest.raises(OSError):
        save_group(grp, tmp_path / "g")
    monkeypatch.undo()
    # the failure was counted, the good checkpoint is bit-untouched, and
    # no temp residue remains to confuse a later recovery scan
    assert failures.value - before == 1
    assert _dir_signature(tmp_path / "g") == sig
    assert [p.name for p in tmp_path.iterdir()] == ["g"]
    assert load_group(tmp_path / "g").ticks == 3  # pre-failure state