"""rtap-lint v3 (ISSUE 14): device-kernel pass fixtures + --update-baseline.

Same discipline as test_analysis.py — every new pass gets a positive
(deliberately-bad snippet fails), a negative (idiomatic-good snippet
passes), and a suppressed fixture, all over in-memory SourceFiles with
synthetic paths. The armed-gate subprocess canaries live in
test_static_checks.py; this file proves the library semantics fast.
"""

import json
import os

import pytest

from rtap_tpu.analysis import run_analysis
from rtap_tpu.analysis.core import AnalysisContext, Baseline, SourceFile

pytestmark = pytest.mark.quick


def lint(path, code, rules=None, docs="", parity="", extra=(),
         baseline=None):
    files = [SourceFile(path, code)]
    files += [SourceFile(p, c) for p, c in extra]
    ctx = AnalysisContext(root="/__fixture__", files=files,
                          docs_text=docs, parity_text=parity)
    return run_analysis("/__fixture__", baseline=baseline or Baseline([]),
                        rules=set(rules) if rules is not None else None,
                        ctx=ctx)


def syms(report):
    return sorted(f.symbol for f in report.findings)


# ------------------------------------------------------- twin-parity --
_ORACLE = ("rtap_tpu/models/oracle/_fx.py",
           "def foo_step(state, sdr, cfg):\n    return state\n\n\n"
           "class BarOracle:\n    def compute(self):\n        pass\n")


def test_twin_parity_name_pair_and_parity_text():
    kernel = ("import jax.numpy as jnp\n\n\n"
              "def foo_step(state, sdr, cfg):\n    return jnp.sum(sdr)\n")
    r = lint("rtap_tpu/ops/_fx.py", kernel, ["twin-parity"],
             extra=(_ORACLE,), parity="exercises foo_step here")
    assert r.findings == [] and r.ok
    # deleting the parity test re-fails the gate (the parity tree is an
    # analyzer INPUT, which is the acceptance property)
    r2 = lint("rtap_tpu/ops/_fx.py", kernel, ["twin-parity"],
              extra=(_ORACLE,), parity="")
    assert syms(r2) == ["foo_step:untested"]


def test_twin_parity_untwinned_and_signature():
    orphan = ("import jax.numpy as jnp\n\n\n"
              "def lonely_kernel(x):\n    return jnp.sum(x)\n")
    r = lint("rtap_tpu/ops/_fx.py", orphan, ["twin-parity"],
             parity="lonely_kernel")
    assert syms(r) == ["lonely_kernel:untwinned"]
    # name-paired twin with a different positional arity
    skew = ("import jax.numpy as jnp\n\n\n"
            "def foo_step(state, sdr, extra, cfg):\n"
            "    return jnp.sum(sdr)\n")
    r2 = lint("rtap_tpu/ops/_fx.py", skew, ["twin-parity"],
              extra=(_ORACLE,), parity="foo_step")
    assert syms(r2) == ["foo_step:signature"]


def test_twin_parity_annotation_and_host_suffix():
    ann = ("import jax.numpy as jnp\n\n\n"
           "# rtap: twin[BarOracle] — stateful oracle\n"
           "def odd_kernel(state):\n    return jnp.sum(state)\n")
    r = lint("rtap_tpu/ops/_fx.py", ann, ["twin-parity"],
             extra=(_ORACLE,), parity="odd_kernel")
    assert r.findings == []
    # a dangling annotation target is an untwinned finding, not a pass
    dangling = ann.replace("BarOracle", "GhostOracle")
    r2 = lint("rtap_tpu/ops/_fx.py", dangling, ["twin-parity"],
              extra=(_ORACLE,), parity="odd_kernel")
    assert syms(r2) == ["odd_kernel:untwinned"]
    # same-file _host twin auto-pairs
    host = ("import jax.numpy as jnp\n\n\n"
            "def red_kernel(x):\n    return jnp.sum(x)\n\n\n"
            "def red_kernel_host(x):\n    return sum(x)\n")
    r3 = lint("rtap_tpu/ops/_fx.py", host, ["twin-parity"],
              parity="red_kernel")
    assert r3.findings == []


def test_twin_parity_scope_and_suppression():
    orphan = ("import jax.numpy as jnp\n\n\n"
              "def _private_kernel(x):\n    return jnp.sum(x)\n\n\n"
              "def dtype_helper(n):\n    return jnp.int16\n")
    # private kernels and dtype-table helpers are not the public surface
    r = lint("rtap_tpu/ops/_fx.py", orphan, ["twin-parity"])
    assert r.findings == []
    supp = ("import jax.numpy as jnp\n\n\n"
            "def infra_kernel(x):  # rtap: allow[twin-parity] — fixture\n"
            "    return jnp.sum(x)\n")
    r2 = lint("rtap_tpu/ops/_fx.py", supp, ["twin-parity"])
    assert r2.findings == [] and len(r2.suppressed) == 2  # both halves


# ------------------------------------------------------ trace-safety --
def test_trace_safety_if_on_traced_value():
    bad = ("import jax.numpy as jnp\n\n\n"
           "def k(x: jnp.ndarray):\n"
           "    y = jnp.sum(x)\n"
           "    if y > 0:\n        return y\n"
           "    return -y\n")
    r = lint("rtap_tpu/ops/_fx.py", bad, ["trace-safety"])
    assert syms(r) == ["k:if-on-traced:y"]
    # static structure checks stay legal: shapes and is-None identity
    ok = ("import jax.numpy as jnp\n\n\n"
          "def k(x: jnp.ndarray, prev: jnp.ndarray | None):\n"
          "    if x.shape[0] > 2 and prev is not None:\n"
          "        return jnp.sum(x)\n"
          "    if prev is None:\n        return jnp.sum(x)\n"
          "    return x\n")
    assert lint("rtap_tpu/ops/_fx.py", ok, ["trace-safety"]).findings == []


def test_trace_safety_py_cast_and_host_call():
    bad = ("import jax.numpy as jnp\nimport numpy as np\n\n\n"
           "def k(x: jnp.ndarray):\n"
           "    total = float(jnp.sum(x))\n"
           "    return np.prod(x)\n")
    r = lint("rtap_tpu/ops/_fx.py", bad, ["trace-safety"])
    assert "k:py-cast:float" in syms(r)
    assert "k:host-call:np.prod" in syms(r)
    # np over STATIC shape attributes is host-boundary-legal
    ok = ("import jax.numpy as jnp\nimport numpy as np\n\n\n"
          "def k(x: jnp.ndarray):\n"
          "    n = int(np.prod(x.shape))\n"
          "    return jnp.sum(x) / n\n")
    assert lint("rtap_tpu/ops/_fx.py", ok, ["trace-safety"]).findings == []


def test_trace_safety_shape_traps_and_suppression():
    bad = ("import jax.numpy as jnp\n\n\n"
           "def k(m: jnp.ndarray):\n"
           "    idx = jnp.where(m)\n"
           "    return jnp.nonzero(m)\n")
    r = lint("rtap_tpu/ops/_fx.py", bad, ["trace-safety"])
    assert syms(r) == ["k:shape-trap:nonzero", "k:shape-trap:where"]
    ok = bad.replace("jnp.where(m)", "jnp.where(m, 1, 0)") \
            .replace("jnp.nonzero(m)", "jnp.nonzero(m, size=4)")
    assert lint("rtap_tpu/ops/_fx.py", ok, ["trace-safety"]).findings == []
    # a trailing allow covers its line (and the one below — core
    # grammar), so keep a spacer before the still-armed nonzero
    supp = ("import jax.numpy as jnp\n\n\n"
            "def k(m: jnp.ndarray):\n"
            "    idx = jnp.where(m)  # rtap: allow[trace-safety] — fixture\n"
            "    keep = m\n"
            "    return jnp.nonzero(m)\n")
    r2 = lint("rtap_tpu/ops/_fx.py", supp, ["trace-safety"])
    assert syms(r2) == ["k:shape-trap:nonzero"] and len(r2.suppressed) == 1


def test_trace_safety_out_of_scope():
    # methods are host-boundary wrappers; non-ops dirs are not kernels
    meth = ("import jax.numpy as jnp\n\n\n"
            "class Runner:\n"
            "    def step(self, x: jnp.ndarray):\n"
            "        y = jnp.sum(x)\n"
            "        if y > 0:\n            return float(y)\n"
            "        return 0.0\n")
    assert lint("rtap_tpu/ops/_fx.py", meth, ["trace-safety"]).findings == []
    bad = ("import jax.numpy as jnp\n\n\n"
           "def k(x: jnp.ndarray):\n"
           "    y = jnp.sum(x)\n"
           "    if y > 0:\n        return y\n"
           "    return -y\n")
    assert lint("rtap_tpu/service/_fx.py", bad,
                ["trace-safety"]).findings == []


# ------------------------------------------------------- donate-read --
_DONOR = ("from functools import partial\n\nimport jax\n\n\n"
          "@partial(jax.jit, donate_argnums=(0,))\n"
          "def burn(state, x):\n    return state, x\n\n\n")


def test_donate_read_positive_negative_suppressed():
    bad = _DONOR + ("def leak(state, x):\n"
                    "    s2, out = burn(state, x)\n"
                    "    return state, out\n")
    r = lint("rtap_tpu/service/_fx.py", bad, ["donate-read"])
    assert syms(r) == ["leak:state@burn"]
    # the idiomatic same-statement rebind never fires
    ok = _DONOR + ("def fine(state, x):\n"
                   "    state, out = burn(state, x)\n"
                   "    return state, out\n")
    assert lint("rtap_tpu/service/_fx.py", ok,
                ["donate-read"]).findings == []
    supp = bad.replace(
        "    return state, out\n",
        "    return state, out  # rtap: allow[donate-read] — fixture\n")
    r2 = lint("rtap_tpu/service/_fx.py", supp, ["donate-read"])
    assert r2.findings == [] and len(r2.suppressed) == 1


def test_donate_read_keyword_dotted_and_rebind():
    bad = _DONOR + ("class Loop:\n"
                    "    def tick(self, x):\n"
                    "        out = burn(state=self.state, x=x)\n"
                    "        return self.state\n")
    r = lint("rtap_tpu/service/_fx.py", bad, ["donate-read"])
    assert syms(r) == ["Loop.tick:self.state@burn"]
    ok = _DONOR + ("class Loop:\n"
                   "    def tick(self, x):\n"
                   "        self.state, out = burn(self.state, x)\n"
                   "        return self.state\n")
    assert lint("rtap_tpu/service/_fx.py", ok,
                ["donate-read"]).findings == []


def test_donate_read_lambda_params_are_fresh_scope():
    ok = _DONOR + ("def bench(state, time_fn):\n"
                   "    time_fn(lambda s: burn(s, 1))\n"
                   "    time_fn(lambda s: burn(s, 2))\n"
                   "    return state\n")
    assert lint("rtap_tpu/service/_fx.py", ok,
                ["donate-read"]).findings == []


def test_donate_read_nested_wrapper_is_file_local():
    factory = ("from functools import partial\n\nimport jax\n\n\n"
               "def make():\n"
               "    @partial(jax.jit, donate_argnums=(0,))\n"
               "    def run(state):\n        return state\n"
               "    return run\n")
    # another file calling something NAMED `run` must not match the
    # factory-local wrapper
    other = ("def drive(ctx):\n"
             "    out = run(ctx)\n"
             "    return ctx, out\n")
    r = lint("rtap_tpu/service/_fx.py", other, ["donate-read"],
             extra=(("rtap_tpu/ops/_factory.py", factory),))
    assert r.findings == []


# ------------------------------------------------------- static-hash --
def test_static_hash_unhashable_and_dangling():
    bad = ("from functools import partial\n\nimport jax\n\n\n"
           "@partial(jax.jit, static_argnames=(\"cfg\", \"gone\"))\n"
           "def f(state, cfg: dict):\n    return state\n")
    r = lint("rtap_tpu/ops/_fx.py", bad, ["static-hash"])
    assert syms(r) == ["f:static:cfg", "f:static:gone"]
    ok = ("from functools import partial\n\nimport jax\n\n\n"
          "@partial(jax.jit, static_argnames=(\"cfg\",))\n"
          "def f(state, cfg: ModelConfig):\n    return state\n")
    assert lint("rtap_tpu/ops/_fx.py", ok, ["static-hash"]).findings == []
    oob = ("from functools import partial\n\nimport jax\n\n\n"
           "@partial(jax.jit, donate_argnums=(3,))\n"
           "def f(state, x):\n    return state\n")
    r2 = lint("rtap_tpu/ops/_fx.py", oob, ["static-hash"])
    assert syms(r2) == ["f:argnum:3"]


def test_jit_churn_loop_lambda_and_suppression():
    loop = ("import jax\n\n\n"
            "def churn(fns):\n"
            "    for fn in fns:\n"
            "        g = jax.jit(fn)\n"
            "    return g\n")
    r = lint("rtap_tpu/service/_fx.py", loop, ["jit-churn"])
    assert syms(r) == ["churn:jit-loop"]
    lam = ("import jax\n\n\n"
           "def build(cfg):\n"
           "    return jax.jit(lambda s: s)\n")
    r2 = lint("rtap_tpu/service/_fx.py", lam, ["jit-churn"])
    assert syms(r2) == ["build:jit-lambda"]
    hoisted = ("import jax\n\n\n"
               "def build(cfg):\n"
               "    def stepper(s):\n        return s\n"
               "    return jax.jit(stepper)\n")
    assert lint("rtap_tpu/service/_fx.py", hoisted,
                ["jit-churn"]).findings == []
    supp = loop.replace(
        "        g = jax.jit(fn)\n",
        "        g = jax.jit(fn)  # rtap: allow[jit-churn] — fixture\n")
    r3 = lint("rtap_tpu/service/_fx.py", supp, ["jit-churn"])
    assert r3.findings == [] and len(r3.suppressed) == 1


# ------------------------------------------------------ dtype-domain --
def test_dtype_domain_mix_and_widening_cast():
    bad = ("# rtap: domain[pa=u8, pb=u16]\n"
           "import jax.numpy as jnp\n\n\n"
           "def f(pa, pb):\n    return pa + pb\n")
    r = lint("rtap_tpu/ops/_fx.py", bad, ["dtype-domain"])
    assert syms(r) == ["f:mix:u16~u8"]
    ok = bad.replace("pa + pb", "pa.astype(jnp.uint16) + pb")
    assert lint("rtap_tpu/ops/_fx.py", ok, ["dtype-domain"]).findings == []
    # state["<key>"] subscripts adopt declared domains too
    sub = ("# rtap: domain[perm=u16, qperm=u8]\n"
           "def f(state):\n"
           "    return state[\"perm\"] + state[\"qperm\"]\n")
    r2 = lint("rtap_tpu/ops/_fx.py", sub, ["dtype-domain"])
    assert syms(r2) == ["f:mix:u16~u8"]


def test_dtype_domain_i32_wrap_needs_clamp():
    bad = ("import jax.numpy as jnp\n\n\n"
           "def f(v, w):\n"
           "    cat = jnp.round(v).astype(jnp.int32)\n"
           "    return cat * w\n")
    r = lint("rtap_tpu/ops/_fx.py", bad, ["dtype-domain"])
    assert syms(r) == ["f:i32-wrap:cat"]
    ok = bad.replace("jnp.round(v).astype(jnp.int32)",
                     "jnp.clip(jnp.round(v), -9, 9).astype(jnp.int32)")
    assert lint("rtap_tpu/ops/_fx.py", ok, ["dtype-domain"]).findings == []
    # the host's i64 widening is the wrap-safe idiom, not a key domain
    host = ("import numpy as np\n\n\n"
            "def f(v, w):\n"
            "    cat = np.round(v).astype(np.int64)\n"
            "    return cat * w\n")
    assert lint("rtap_tpu/models/oracle/_fx.py", host,
                ["dtype-domain"]).findings == []


def test_dtype_domain_undeclared_cast_and_suppression():
    bad = ("import jax.numpy as jnp\n\n\n"
           "def f(x):\n    return (x * 255.0).astype(jnp.uint8)\n")
    r = lint("rtap_tpu/ops/_fx.py", bad, ["dtype-domain"])
    assert syms(r) == ["f:undeclared:u8"]
    declared = bad.replace(
        ".astype(jnp.uint8)",
        ".astype(jnp.uint8)  # rtap: domain[u8]")
    assert lint("rtap_tpu/ops/_fx.py", declared,
                ["dtype-domain"]).findings == []
    supp = bad.replace(
        ".astype(jnp.uint8)",
        ".astype(jnp.uint8)  # rtap: allow[dtype-domain] — fixture")
    r2 = lint("rtap_tpu/ops/_fx.py", supp, ["dtype-domain"])
    assert r2.findings == [] and len(r2.suppressed) == 1
    # unknown domain tokens are themselves findings
    junk = "# rtap: domain[pa=u12]\nx = 1\n"
    r3 = lint("rtap_tpu/ops/_fx.py", junk, ["dtype-domain"])
    assert syms(r3) == ["domain-syntax:pa"]


def test_dtype_domain_out_of_scope_dir():
    bad = ("# rtap: domain[pa=u8, pb=u16]\n"
           "def f(pa, pb):\n    return pa + pb\n")
    assert lint("rtap_tpu/obs/_fx.py", bad,
                ["dtype-domain"]).findings == []


# ----------------------------------------------------- wire-contract --
_WIRE_FIXTURE = (
    "import struct\n\n"
    "MAGIC = b\"XY1\"\n"
    "KIND_A = 1\n"
    "KIND_B = 2\n"
    "_KINDS = (KIND_A, KIND_B)\n"
    "HEADER = struct.Struct(\"<3sBH\")  # magic, kind, count\n")

_WIRE_DOCS = (
    "The XY1 frame:\n\n"
    "| offset | size | field | notes |\n"
    "|--------|------|-------|-------|\n"
    "| 0 | 3 | magic | `XY1` |\n"
    "| 3 | 1 | kind | 1=A, 2=B |\n"
    "| 4 | 2 | count | rows |\n")


def test_wire_contract_green_fixture():
    r = lint("rtap_tpu/ingest/_fx.py", _WIRE_FIXTURE, ["wire-contract"],
             docs=_WIRE_DOCS)
    assert r.findings == [] and r.ok


def test_wire_contract_struct_drift_fails():
    # widening count to u32 without touching the doc row = gate failure
    drifted = _WIRE_FIXTURE.replace('"<3sBH"', '"<3sBI"')
    r = lint("rtap_tpu/ingest/_fx.py", drifted, ["wire-contract"],
             docs=_WIRE_DOCS)
    assert syms(r) == ["HEADER.count"]


def test_wire_contract_doc_row_drift_fails():
    # mutating the documented layout row (the other direction) fails too
    r = lint("rtap_tpu/ingest/_fx.py", _WIRE_FIXTURE, ["wire-contract"],
             docs=_WIRE_DOCS.replace("| 4 | 2 | count |",
                                     "| 4 | 4 | count |"))
    assert syms(r) == ["HEADER.count"]
    # deleting the row entirely = undocumented field
    gone = _WIRE_DOCS.replace("| 4 | 2 | count | rows |\n", "")
    r2 = lint("rtap_tpu/ingest/_fx.py", _WIRE_FIXTURE, ["wire-contract"],
              docs=gone)
    assert syms(r2) == ["HEADER.count:undocumented"]


def test_wire_contract_type_codes():
    dup = _WIRE_FIXTURE.replace("KIND_B = 2", "KIND_B = 1")
    r = lint("rtap_tpu/ingest/_fx.py", dup, ["wire-contract"],
             docs=_WIRE_DOCS)
    assert "code:KIND_B" in syms(r)
    undoc = _WIRE_DOCS.replace("1=A, 2=B", "1=A")
    r2 = lint("rtap_tpu/ingest/_fx.py", _WIRE_FIXTURE, ["wire-contract"],
              docs=undoc)
    assert syms(r2) == ["code:KIND_B"]


def test_wire_contract_magic_collision_and_endian():
    twin = ("import struct\n\nMAGIC = b\"XY\"\n")
    r = lint("rtap_tpu/ingest/_fx.py", _WIRE_FIXTURE, ["wire-contract"],
             docs=_WIRE_DOCS,
             extra=(("rtap_tpu/resilience/_fx2.py", twin),))
    assert "magic:XY" in syms(r) or "magic:XY1" in syms(r)
    native = _WIRE_FIXTURE.replace('"<3sBH"', '"3sBH"')
    r2 = lint("rtap_tpu/ingest/_fx.py", native, ["wire-contract"],
              docs=_WIRE_DOCS)
    assert "fmt:HEADER:endian" in syms(r2)


def test_wire_contract_inline_width_line():
    code = ("import struct\n\n"
            "_MAGIC = b\"ZJ\"\n"
            "_HEADER = struct.Struct(\"<2sBI\")  # magic, typ, length\n")
    docs = 'framing: `b"ZJ" | typ u8 | length u32 | payload | crc32`\n'
    r = lint("rtap_tpu/resilience/_fx.py", code, ["wire-contract"],
             docs=docs)
    assert r.findings == []
    # doc narrows length to u16: drift
    r2 = lint("rtap_tpu/resilience/_fx.py", code, ["wire-contract"],
              docs=docs.replace("length u32", "length u16"))
    assert syms(r2) == ["_HEADER.length"]
    # no doc coverage at all: undocumented framing
    r3 = lint("rtap_tpu/resilience/_fx.py", code, ["wire-contract"],
              docs="")
    assert syms(r3) == ["_HEADER:undocumented"]


def test_wire_contract_comment_name_count_and_suppression():
    short = _WIRE_FIXTURE.replace("# magic, kind, count", "# magic, kind")
    r = lint("rtap_tpu/ingest/_fx.py", short, ["wire-contract"],
             docs=_WIRE_DOCS)
    assert syms(r) == ["fmt:HEADER:names"]
    supp = _WIRE_FIXTURE.replace(
        'HEADER = struct.Struct("<3sBH")',
        '# rtap: allow[wire-contract] — fixture\n'
        'HEADER = struct.Struct("<3sBH")')
    r2 = lint("rtap_tpu/ingest/_fx.py", supp, ["wire-contract"],
              docs=_WIRE_DOCS.replace("| 4 | 2 |", "| 4 | 4 |"))
    assert r2.findings == [] and len(r2.suppressed) == 1


# --------------------------------------------------- --update-baseline --
BAD_CODE = ("def f(p):\n    try:\n        load(p)\n"
            "    except Exception:\n        pass\n")


def _mini_repo(tmp_path, module="mod.py", code=BAD_CODE):
    """A throwaway tree run_analysis can discover: one violating serve
    module plus the strict-coverage pin stubs."""
    root = tmp_path / "repo"
    for stub in ("rtap_tpu/obs/latency.py", "rtap_tpu/obs/slo.py",
                 "rtap_tpu/obs/metrics.py", "rtap_tpu/service/loop.py"):
        p = root / stub
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("x = 1\n")
    (root / "rtap_tpu" / "service" / module).write_text(code)
    return str(root)


def _write_baseline(root, entries):
    path = os.path.join(root, "analysis_baseline.json")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"entries": entries}, fh)
    return path


def _read_entries(path):
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)["entries"]


def test_update_baseline_rekeys_moved_path(tmp_path):
    from rtap_tpu.analysis.baseline_update import update_baseline

    root = _mini_repo(tmp_path, module="renamed.py")
    path = _write_baseline(root, [
        {"rule": "except-silent", "path": "rtap_tpu/service/old.py",
         "symbol": "f:except Exception", "why": "legacy swallow"}])
    summary = update_baseline(root, baseline_path=path)
    assert summary["unmatched"] == [] and summary["wrote"]
    assert summary["rekeyed"] == [(
        ("except-silent", "rtap_tpu/service/old.py",
         "f:except Exception"),
        ("except-silent", "rtap_tpu/service/renamed.py",
         "f:except Exception"))]
    ent = _read_entries(path)
    assert ent[0]["path"] == "rtap_tpu/service/renamed.py"
    assert ent[0]["why"] == "legacy swallow"  # preserved verbatim


def test_update_baseline_rekeys_moved_symbol(tmp_path):
    from rtap_tpu.analysis.baseline_update import update_baseline

    root = _mini_repo(tmp_path, code=BAD_CODE.replace("def f(", "def g("))
    path = _write_baseline(root, [
        {"rule": "except-silent", "path": "rtap_tpu/service/mod.py",
         "symbol": "f:except Exception", "why": "legacy swallow"}])
    summary = update_baseline(root, baseline_path=path)
    assert summary["unmatched"] == []
    ent = _read_entries(path)
    assert ent[0]["symbol"] == "g:except Exception"
    assert ent[0]["why"] == "legacy swallow"


def test_update_baseline_drops_stale_refuses_new(tmp_path):
    from rtap_tpu.analysis.baseline_update import update_baseline

    root = _mini_repo(tmp_path)
    path = _write_baseline(root, [
        # matches the real finding (kept)
        {"rule": "except-silent", "path": "rtap_tpu/service/mod.py",
         "symbol": "f:except Exception", "why": "legacy swallow"},
        # matches nothing on any axis (dropped)
        {"rule": "race", "path": "rtap_tpu/service/gone.py",
         "symbol": "C.n", "why": "obsolete"}])
    summary = update_baseline(root, baseline_path=path)
    assert summary["dropped"] == [
        ("race", "rtap_tpu/service/gone.py", "C.n")]
    assert [e["symbol"] for e in _read_entries(path)] == \
        ["f:except Exception"]
    # a NEW finding with no stale candidate is refused, never minted
    root2 = _mini_repo(tmp_path / "b")
    path2 = _write_baseline(root2, [])
    summary2 = update_baseline(root2, baseline_path=path2)
    assert summary2["unmatched"] == [
        ("except-silent", "rtap_tpu/service/mod.py",
         "f:except Exception")]
    assert not summary2["wrote"] and _read_entries(path2) == []


def test_update_baseline_leaves_whyless_for_a_human(tmp_path):
    from rtap_tpu.analysis.baseline_update import update_baseline

    root = _mini_repo(tmp_path)
    path = _write_baseline(root, [
        {"rule": "except-silent", "path": "rtap_tpu/service/mod.py",
         "symbol": "f:except Exception"}])  # no why
    summary = update_baseline(root, baseline_path=path)
    assert summary["format_errors"]
    # the malformed entry is neither fixed nor deleted — a human owns it
    ent = _read_entries(path)
    assert len(ent) == 1 and "why" not in ent[0]


# ------------------- review-hardening regressions (ISSUE 14 follow-ups) --
def test_donate_read_branches_are_mutually_exclusive():
    """A donation inside the if-body must not poison the else branch
    (they never both run), and code AFTER the If only sees bindings
    donated on EVERY branch (must-analysis)."""
    one_sided = _DONOR + (
        "def route(state, x, fast):\n"
        "    if fast:\n"
        "        state, out = burn(state, x)\n"
        "    else:\n"
        "        out = fallback(state)\n"
        "    return state, out\n")
    assert lint("rtap_tpu/service/_fx.py", one_sided,
                ["donate-read"]).findings == []
    both = _DONOR + (
        "def route(state, x, fast):\n"
        "    if fast:\n"
        "        s2, out = burn(state, x)\n"
        "    else:\n"
        "        s2, out = burn(state, x)\n"
        "    return state, out\n")
    r = lint("rtap_tpu/service/_fx.py", both, ["donate-read"])
    assert syms(r) == ["route:state@burn"]


def test_static_hash_checks_same_named_wrapper_in_second_file():
    """Two files defining a jit wrapper with the SAME bare name: the
    registry must check both (a by-name first-wins dict silently
    skipped the second one's broken spec)."""
    good = ("from functools import partial\n\nimport jax\n\n\n"
            "@partial(jax.jit, static_argnames=(\"cfg\",))\n"
            "def runner(state, cfg: ModelConfig):\n    return state\n")
    bad = ("from functools import partial\n\nimport jax\n\n\n"
           "@partial(jax.jit, static_argnames=(\"gone\",))\n"
           "def runner(state, cfg: ModelConfig):\n    return state\n")
    r = lint("rtap_tpu/ops/_fx_b.py", bad, ["static-hash"],
             extra=(("rtap_tpu/ops/_fx_a.py", good),))
    assert syms(r) == ["runner:static:gone"]


def test_donate_read_same_named_local_donor_wins():
    """When two files define donors with one name, a call site binds to
    the wrapper in ITS OWN file."""
    remote = ("from functools import partial\n\nimport jax\n\n\n"
              "@partial(jax.jit, donate_argnums=(1,))\n"
              "def burn2(aux, state):\n    return state\n")
    local = ("from functools import partial\n\nimport jax\n\n\n"
             "@partial(jax.jit, donate_argnums=(0,))\n"
             "def burn2(state, aux):\n    return state\n\n\n"
             "def use(state, aux):\n"
             "    out = burn2(state, aux)\n"
             "    return aux, out\n")
    # local donor donates position 0 (state); aux read stays legal
    r = lint("rtap_tpu/service/_fx.py", local, ["donate-read"],
             extra=(("rtap_tpu/ops/_fx_r.py", remote),))
    assert r.findings == []
    leak = local.replace("    return aux, out\n", "    return state\n")
    r2 = lint("rtap_tpu/service/_fx.py", leak, ["donate-read"],
              extra=(("rtap_tpu/ops/_fx_r.py", remote),))
    assert syms(r2) == ["use:state@burn2"]


def test_wire_contract_non_header_2s_struct_not_misclassified():
    """A struct that merely OPENS with a 2-byte string field is not the
    framing header — only a comment whose first field is `magic` (and
    the matching Ns) is checked against the framing docs."""
    code = ("import struct\n\n"
            "_MAGIC = b\"ZJ\"\n"
            "_HEADER = struct.Struct(\"<2sBI\")  # magic, typ, length\n"
            "_TRAILER = struct.Struct(\"<2sI\")  # pad, crc\n")
    docs = 'framing: `b"ZJ" | typ u8 | length u32 | payload | crc32`\n'
    r = lint("rtap_tpu/resilience/_fx.py", code, ["wire-contract"],
             docs=docs)
    assert r.findings == []


def test_update_baseline_never_transfers_why_to_unrelated_finding(tmp_path):
    """A stale entry whose (rule, path) matches a NEW, unrelated
    finding must not be re-keyed onto it (the why would grandfather a
    finding nobody reviewed): the tails differ, so the entry drops and
    the finding is refused."""
    from rtap_tpu.analysis.baseline_update import update_baseline

    root = _mini_repo(tmp_path)  # finding: f:except Exception
    path = _write_baseline(root, [
        {"rule": "except-silent", "path": "rtap_tpu/service/mod.py",
         "symbol": "g:except ValueError", "why": "old tolerance"}])
    summary = update_baseline(root, baseline_path=path)
    assert summary["rekeyed"] == []
    assert summary["dropped"] == [
        ("except-silent", "rtap_tpu/service/mod.py",
         "g:except ValueError")]
    assert summary["unmatched"] == [
        ("except-silent", "rtap_tpu/service/mod.py",
         "f:except Exception")]
    assert _read_entries(path) == []


def test_update_baseline_no_rekey_when_old_path_still_exists(tmp_path):
    """Round-1 (file-move) re-keys only when the entry's old file is
    GONE: if it still exists, a same-named finding in another file is
    more likely a new, unrelated site than a move — refuse, drop the
    stale entry, and leave the why out of the new finding."""
    from rtap_tpu.analysis.baseline_update import update_baseline

    root = _mini_repo(tmp_path, module="b.py")
    # the entry's path exists in the tree but carries no finding
    (  # noqa: the stub keeps a.py alive without violations
        __import__("pathlib").Path(root) / "rtap_tpu" / "service" / "a.py"
    ).write_text("x = 1\n")
    path = _write_baseline(root, [
        {"rule": "except-silent", "path": "rtap_tpu/service/a.py",
         "symbol": "f:except Exception", "why": "reviewed for a.py only"}])
    summary = update_baseline(root, baseline_path=path)
    assert summary["rekeyed"] == []
    assert summary["dropped"] == [
        ("except-silent", "rtap_tpu/service/a.py", "f:except Exception")]
    assert summary["unmatched"] == [
        ("except-silent", "rtap_tpu/service/b.py", "f:except Exception")]


def test_twin_parity_dangling_method_target_is_untwinned():
    """`# rtap: twin[Class.method]` must validate the FULL dotted
    target — a typoed method on a real class is a dangling pairing,
    not a pass."""
    ann = ("import jax.numpy as jnp\n\n\n"
           "# rtap: twin[BarOracle.no_such_method] — typo\n"
           "def odd_kernel(state):\n    return jnp.sum(state)\n")
    r = lint("rtap_tpu/ops/_fx.py", ann, ["twin-parity"],
             extra=(_ORACLE,), parity="odd_kernel")
    assert syms(r) == ["odd_kernel:untwinned"]
    good = ann.replace("BarOracle.no_such_method", "BarOracle.compute")
    r2 = lint("rtap_tpu/ops/_fx.py", good, ["twin-parity"],
              extra=(_ORACLE,), parity="odd_kernel")
    assert r2.findings == []


def test_dtype_domain_augassign_is_arithmetic_too():
    """`pa += pb` is the permanence-update idiom — the mix and wrap
    checks must see in-place updates, not just BinOp expressions."""
    bad = ("# rtap: domain[pa=u8, pb=u16]\n"
           "def f(pa, pb):\n"
           "    pa += pb\n"
           "    return pa\n")
    r = lint("rtap_tpu/ops/_fx.py", bad, ["dtype-domain"])
    assert syms(r) == ["f:mix:u16~u8"]
    wrap = ("import jax.numpy as jnp\n\n\n"
            "def f(v, w):\n"
            "    cat = jnp.round(v).astype(jnp.int32)\n"
            "    cat *= w\n"
            "    return cat\n")
    r2 = lint("rtap_tpu/ops/_fx.py", wrap, ["dtype-domain"])
    assert syms(r2) == ["f:i32-wrap:cat"]


def test_wire_contract_unrelated_comment_below_struct_is_not_a_field():
    """A plain comment on the next line must not be swallowed into the
    field list (continuations are only consumed while the list ends
    with a comma) — a prose edit near a framing must not go red."""
    prose = _WIRE_FIXTURE.replace(
        'HEADER = struct.Struct("<3sBH")  # magic, kind, count\n',
        'HEADER = struct.Struct("<3sBH")  # magic, kind, count\n'
        '# the walker helpers live below this line\n')
    r = lint("rtap_tpu/ingest/_fx.py", prose, ["wire-contract"],
             docs=_WIRE_DOCS)
    assert r.findings == []
    # the protocol.py idiom — trailing comma opens a continuation
    cont = _WIRE_FIXTURE.replace(
        'HEADER = struct.Struct("<3sBH")  # magic, kind, count\n',
        'HEADER = struct.Struct("<3sBH")  # magic, kind,\n'
        '# count\n')
    r2 = lint("rtap_tpu/ingest/_fx.py", cont, ["wire-contract"],
              docs=_WIRE_DOCS)
    assert r2.findings == []
