"""SP semantics on tiny handcrafted configurations (SURVEY.md §4 item 1)."""

import numpy as np
import pytest

from rtap_tpu.config import ModelConfig, RDSEConfig, DateConfig, SPConfig
from rtap_tpu.models.oracle.spatial_pooler import sp_compute, sp_inhibit, sp_learn, sp_overlap
from rtap_tpu.models.state import init_state


def tiny_state(C=10, n=20, **sp_kw):
    cfg = ModelConfig(
        rdse=RDSEConfig(size=n, active_bits=5, resolution=1.0),
        date=DateConfig(time_of_day_width=0, time_of_day_size=0),
        sp=SPConfig(columns=C, num_active_columns=3, **sp_kw),
    )
    return init_state(cfg, seed=7), cfg.sp


class TestOverlap:
    def test_exact_counts_handcrafted(self):
        state, cfg = tiny_state()
        # handcraft: column 0 connected to inputs {0,1,2}, column 1 to {2,3}
        state["potential"][:] = False
        state["perm"][:] = 0.0
        state["potential"][0, [0, 1, 2]] = True
        state["perm"][0, [0, 1, 2]] = cfg.syn_perm_connected
        state["potential"][1, [2, 3]] = True
        state["perm"][1, [2, 3]] = cfg.syn_perm_connected
        inp = np.zeros(20, bool)
        inp[[0, 2, 3]] = True
        ov = sp_overlap(state, inp, cfg)
        assert ov[0] == 2 and ov[1] == 2 and ov[2:].sum() == 0

    def test_disconnected_synapse_ignored(self):
        state, cfg = tiny_state()
        state["potential"][:] = False
        state["perm"][:] = 0.0
        state["potential"][0, [0, 1]] = True
        state["perm"][0, 0] = cfg.syn_perm_connected - 0.01  # below threshold
        state["perm"][0, 1] = cfg.syn_perm_connected
        inp = np.ones(20, bool)
        assert sp_overlap(state, inp, cfg)[0] == 1


class TestInhibition:
    def test_topk_and_low_index_tiebreak(self):
        cfg = SPConfig(columns=6, num_active_columns=2)
        overlap = np.array([3, 5, 5, 5, 1, 0])
        active = sp_inhibit(overlap, np.ones(6, np.float32), cfg)
        # three tie at 5 -> lowest indices 1,2 win
        np.testing.assert_array_equal(np.nonzero(active)[0], [1, 2])

    def test_stimulus_threshold(self):
        cfg = SPConfig(columns=4, num_active_columns=3, stimulus_threshold=2)
        overlap = np.array([5, 1, 0, 3])
        active = sp_inhibit(overlap, np.ones(4, np.float32), cfg)
        np.testing.assert_array_equal(np.nonzero(active)[0], [0, 3])  # 1 below threshold

    def test_boost_changes_winners(self):
        cfg = SPConfig(columns=4, num_active_columns=1, boost_strength=2.0)
        overlap = np.array([4, 5, 0, 0])
        boost = np.array([2.0, 1.0, 1.0, 1.0], np.float32)
        active = sp_inhibit(overlap, boost, cfg)
        np.testing.assert_array_equal(np.nonzero(active)[0], [0])  # 8 > 5 boosted

    def test_boost_small_margin_beats_index_tiebreak(self):
        # regression: a real boosted-overlap gap (>= 1/256) must beat the
        # low-index tie-break no matter how the indices fall
        cfg = SPConfig(columns=2048, num_active_columns=1, boost_strength=1.0)
        overlap = np.zeros(2048, np.int64)
        overlap[100], overlap[1800] = 5, 5
        boost = np.ones(2048, np.float32)
        boost[100], boost[1800] = 1.04, 1.06  # 5.2 vs 5.3 boosted
        active = sp_inhibit(overlap, boost, cfg)
        np.testing.assert_array_equal(np.nonzero(active)[0], [1800])


class TestLearning:
    def test_hebbian_deltas_exact(self):
        state, cfg = tiny_state()
        state["potential"][:] = True
        state["perm"][:] = 0.3
        inp = np.zeros(20, bool)
        inp[:10] = True
        active = np.zeros(10, bool)
        active[0] = True
        overlap = sp_overlap(state, inp, cfg)
        sp_learn(state, inp, overlap, active, cfg)
        np.testing.assert_allclose(state["perm"][0, :10], 0.3 + cfg.syn_perm_active_inc, atol=1e-6)
        np.testing.assert_allclose(state["perm"][0, 10:], 0.3 - cfg.syn_perm_inactive_dec, atol=1e-6)
        # non-winner column untouched
        np.testing.assert_allclose(state["perm"][1], 0.3, atol=1e-6)

    def test_clip_bounds(self):
        state, cfg = tiny_state()
        state["potential"][:] = True
        state["perm"][:] = 0.9999
        inp = np.ones(20, bool)
        active = np.ones(10, bool)
        sp_learn(state, inp, sp_overlap(state, inp, cfg), active, cfg)
        assert state["perm"].max() <= 1.0

    def test_duty_cycles_update(self):
        state, cfg = tiny_state()
        inp = np.ones(20, bool)
        active = sp_compute(state, inp, cfg, learn=True)
        assert state["sp_iter"] == 1
        np.testing.assert_allclose(state["active_duty"], active.astype(float))

    def test_weak_column_bump(self):
        state, cfg = tiny_state()
        # column 0 has no connected synapses and never overlaps -> bumped
        state["perm"][0][state["potential"][0]] = 0.0
        before = state["perm"][0].copy()
        inp = np.ones(20, bool)
        for _ in range(3):
            sp_compute(state, inp, cfg, learn=True)
        grown = state["perm"][0][state["potential"][0]] > before[state["potential"][0]]
        assert grown.all()


class TestStability:
    def test_repeated_input_stable_winners(self):
        state, cfg = tiny_state(C=64, n=40)
        rng = np.random.default_rng(0)
        inp = rng.random(40) < 0.3
        first = sp_compute(state, inp, cfg, learn=True)
        for _ in range(20):
            last = sp_compute(state, inp, cfg, learn=True)
        np.testing.assert_array_equal(first, last)

    def test_learn_false_does_not_mutate(self):
        state, cfg = tiny_state()
        snap = {k: np.copy(v) for k, v in state.items()}
        sp_compute(state, np.ones(20, bool), cfg, learn=False)
        for k in snap:
            np.testing.assert_array_equal(state[k], snap[k], err_msg=k)

    def test_determinism_across_runs(self):
        outs = []
        for _ in range(2):
            state, cfg = tiny_state(C=32, n=30)
            rng = np.random.default_rng(5)
            seq = [rng.random(30) < 0.25 for _ in range(10)]
            outs.append([sp_compute(state, s, cfg, learn=True) for s in seq])
        for a, b in zip(*outs):
            np.testing.assert_array_equal(a, b)
