"""Checkpoint round-trip: save mid-stream, resume, outputs stay bit-identical
to the uninterrupted run (SURVEY.md §4 item 3 — the serialization test
pattern NuPIC uses for its Cap'n Proto save/resume)."""

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset
from rtap_tpu.service.checkpoint import load_group, save_group
from rtap_tpu.service.registry import StreamGroup


def _vals(n, g, seed):
    rng = np.random.Generator(np.random.Philox(key=(seed, 11)))
    v = (40 + 8 * rng.random((n, g))).astype(np.float32)
    v[int(n * 0.7), :] += 50
    return v


@pytest.mark.parametrize("backend", ["tpu", "cpu"])
def test_group_checkpoint_roundtrip(backend, tmp_path):
    cfg = cluster_preset()
    ids = [f"s{i}" for i in range(3)]
    n, cut = 160, 80
    vals = _vals(n, 3, seed=1)

    ref = StreamGroup(cfg, ids, backend=backend)
    for i in range(cut):
        ref.tick(vals[i], 1_700_000_000 + i)
    save_group(ref, tmp_path / "grp0")

    resumed = load_group(tmp_path / "grp0")
    assert resumed.stream_ids == ids and resumed.ticks == cut
    for i in range(cut, n):
        r_ref = ref.tick(vals[i], 1_700_000_000 + i)
        r_res = resumed.tick(vals[i], 1_700_000_000 + i)
        np.testing.assert_array_equal(r_ref.raw, r_res.raw, err_msg=f"tick {i}")
        np.testing.assert_array_equal(
            r_ref.log_likelihood, r_res.log_likelihood, err_msg=f"tick {i}"
        )
        np.testing.assert_array_equal(r_ref.alerts, r_res.alerts)


def test_checkpoint_preserves_config_and_threshold(tmp_path):
    cfg = cluster_preset()
    grp = StreamGroup(cfg, ["a", "b"], backend="cpu", threshold=0.37)
    grp.tick(np.array([1.0, 2.0], np.float32), 1_700_000_000)
    save_group(grp, tmp_path / "g")
    back = load_group(tmp_path / "g")
    assert back.threshold == 0.37
    assert back.cfg == cfg
    assert back.backend == "cpu"


def test_checkpoint_overwrite_atomic(tmp_path):
    """Re-saving to an existing path swaps directories whole: the new state is
    readable and no temp/old residue remains."""
    cfg = cluster_preset()
    grp = StreamGroup(cfg, ["a", "b"], backend="cpu")
    grp.tick(np.array([1.0, 2.0], np.float32), 1_700_000_000)
    save_group(grp, tmp_path / "g")
    grp.tick(np.array([3.0, 4.0], np.float32), 1_700_000_001)
    save_group(grp, tmp_path / "g")  # overwrite
    back = load_group(tmp_path / "g")
    assert back.ticks == 2
    residue = [p.name for p in tmp_path.iterdir() if p.name != "g"]
    assert residue == [], residue


def test_config_validation_rejects_small_col_cap():
    from rtap_tpu.config import ModelConfig, SPConfig, TMConfig

    with pytest.raises(ValueError, match="col_cap"):
        ModelConfig(sp=SPConfig(num_active_columns=50),
                    tm=TMConfig(cells_per_column=32, col_cap=10))
    ModelConfig()  # defaults must validate


def test_from_dict_drops_retired_fields_and_clamps_col_cap():
    from rtap_tpu.config import ModelConfig, SPConfig

    old = ModelConfig(sp=SPConfig(num_active_columns=40)).to_dict()
    old["tm"]["active_cap"] = 512  # retired field from an old serialization
    old["tm"]["winner_cap"] = 192
    old["tm"]["col_cap"] = 8  # pre-col_cap checkpoint migrated too low
    cfg = ModelConfig.from_dict(old)
    assert cfg.tm.col_cap == 40


@pytest.mark.parametrize("backend", ["tpu", "cpu"])
def test_checkpoint_roundtrip_with_classifier(backend, tmp_path):
    """Classifier weights/actual-values resume with the group: predictions
    after resume match the uninterrupted run exactly."""
    from tests.unit.test_classifier import _cfg, _periodic_values

    cfg = _cfg()
    ids = ["a", "b"]
    vals = _periodic_values(120)
    ref = StreamGroup(cfg, ids, backend=backend)
    for i in range(60):
        ref.tick(np.array([vals[i], vals[i] + 1], np.float32), 1_700_000_000 + i)
    save_group(ref, tmp_path / "g")
    resumed = load_group(tmp_path / "g")
    for i in range(60, 120):
        v = np.array([vals[i], vals[i] + 1], np.float32)
        r_ref = ref.tick(v, 1_700_000_000 + i)
        r_res = resumed.tick(v, 1_700_000_000 + i)
        np.testing.assert_array_equal(r_ref.raw, r_res.raw, err_msg=f"tick {i}")
        np.testing.assert_array_equal(r_ref.prediction, r_res.prediction, err_msg=f"tick {i}")


class TestDenseToSparseMigration:
    """ISSUE 18: a COMMITTED dense-layout checkpoint restores into the
    sparse build (``load_group(..., sparsify=True)``) and continues
    bit-identically to the dense run recorded at fixture-creation time
    (scripts/make_migration_fixture.py). The re-layout is lossless: every
    synapse keeps its exact permanence, so scores can never drift."""

    FIXTURE = "tests/fixtures/migration"

    def _fixture(self):
        import pathlib

        root = pathlib.Path(__file__).resolve().parents[2] / self.FIXTURE
        exp = np.load(root / "expected.npz")
        return root / "dense_ckpt", exp

    def test_committed_dense_checkpoint_restores_sparse_bit_identical(self):
        ckpt, exp = self._fixture()
        grp = load_group(ckpt, sparsify=True)
        # the resumed group IS the sparse build: layout flipped, the
        # migration's exact pool width pinned, dense mask gone
        assert grp.cfg.sp.sparse_pool
        assert grp.cfg.sp.pool_members == grp.cfg.sp_members > 0
        assert "members" in grp.state and "potential" not in grp.state
        warm = int(exp["warm_ticks"])
        vals = exp["vals"]
        for j in range(exp["raw"].shape[0]):
            r = grp.tick(vals[warm + j], 1_700_000_000 + warm + j)
            np.testing.assert_array_equal(r.raw, exp["raw"][j], err_msg=f"tick {j}")
            np.testing.assert_array_equal(
                r.log_likelihood, exp["log_likelihood"][j], err_msg=f"tick {j}")

    def test_sparsify_rebuilds_fwd_index_from_migrated_state(self):
        from functools import partial

        import jax

        from rtap_tpu.ops.fwd_index import build_fwd_index
        from rtap_tpu.ops.tm_tpu import set_dendrite_mode

        ckpt, _ = self._fixture()
        set_dendrite_mode("forward")
        try:
            grp = load_group(ckpt, sparsify=True)
            assert {"fwd_slots", "fwd_pos", "fwd_of"} <= set(grp.state)
            slots, pos, of = jax.vmap(partial(
                build_fwd_index, n_cells=grp.cfg.num_cells,
                fanout_cap=grp.cfg.tm.fanout_cap,
            ))(np.asarray(grp.state["presyn"]))
            np.testing.assert_array_equal(np.asarray(grp.state["fwd_slots"]), slots)
            np.testing.assert_array_equal(np.asarray(grp.state["fwd_pos"]), pos)
        finally:
            set_dendrite_mode(None)

    def test_sparsify_noop_on_already_sparse_checkpoint(self, tmp_path):
        cfg = cluster_preset()  # sparse layout since ISSUE 18
        grp = StreamGroup(cfg, ["a", "b"], backend="tpu")
        grp.tick(np.array([1.0, 2.0], np.float32), 1_700_000_000)
        save_group(grp, tmp_path / "g")
        back = load_group(tmp_path / "g", sparsify=True)
        assert back.cfg == cfg  # untouched: no pool_members pin, same layout


class TestSingleModelSaveLoad:
    """HTMModel.save/load (SURVEY.md C16 model.save surface): resume is
    bit-exact vs an uninterrupted run, across backends and domains."""

    def _vals(self, n=220):
        import numpy as np

        t = np.arange(n)
        v = (50 + 20 * np.sin(2 * np.pi * t / 40.0)
             + np.random.default_rng(8).normal(0, 2, n)).astype(np.float32)
        v[int(0.77 * n)] += 35
        return v

    @pytest.mark.parametrize("perm_bits", [0, 16])
    def test_roundtrip_bit_exact(self, tmp_path, perm_bits):
        import dataclasses

        import numpy as np

        from rtap_tpu.models.htm_model import HTMModel

        base = cluster_preset(perm_bits=perm_bits)
        cfg = dataclasses.replace(
            base, likelihood=dataclasses.replace(
                base.likelihood, learning_period=60, estimation_samples=30)
        )
        vals = self._vals()
        full = HTMModel(cfg, seed=4, backend="cpu")
        ref = [full.run(1_700_000_000 + i, float(vals[i])) for i in range(220)]

        m = HTMModel(cfg, seed=4, backend="cpu")
        for i in range(150):
            m.run(1_700_000_000 + i, float(vals[i]))
        p = tmp_path / "model.npz"
        m.save(str(p))
        resumed = HTMModel.load(str(p))
        assert resumed.cfg == cfg
        out = [resumed.run(1_700_000_000 + i, float(vals[i])) for i in range(150, 220)]
        for a, b in zip(out, ref[150:]):
            assert a.raw_score == b.raw_score
            assert a.log_likelihood == b.log_likelihood
        # saved state untouched by the resumed run's mutation
        with np.load(p) as z:
            assert int(z["lik_records"]) == 150

    def test_cpu_save_tpu_resume(self, tmp_path):
        from rtap_tpu.models.htm_model import HTMModel

        cfg = cluster_preset()
        vals = self._vals(120)
        m = HTMModel(cfg, seed=4, backend="cpu")
        for i in range(80):
            m.run(1_700_000_000 + i, float(vals[i]))
        p = tmp_path / "model.npz"
        m.save(str(p))
        cpu = HTMModel.load(str(p), backend="cpu")
        tpu = HTMModel.load(str(p), backend="tpu")
        for i in range(80, 120):
            a = cpu.run(1_700_000_000 + i, float(vals[i]))
            b = tpu.run(1_700_000_000 + i, float(vals[i]))
            assert a.raw_score == b.raw_score, i
