"""Native JSONL ingest parser (rtap_tpu/native/jsonl_parser.c) vs the pure
Python handler: counter-for-counter, value-for-value parity on the realistic
record space, plus the C-only mechanics (chunk splits, remainder flush,
oversized-line resync).

The native path exists because the host core feeding the chip at the 100k
streams/s north star cannot spend microseconds per record in json.loads
(SURVEY.md C18, §7 host-feed hard part); parity here is what lets the
service swap it in by default with the Python path as fallback.
"""

import json
import socket
import time

import numpy as np
import pytest

from rtap_tpu.service.sources import TcpJsonlSource, send_jsonl

try:
    from rtap_tpu.native import NativeJsonlState

    _err = None
except Exception as e:  # no toolchain: the fallback story, not a failure
    NativeJsonlState = None
    _err = e

needs_native = pytest.mark.skipif(
    NativeJsonlState is None, reason=f"native build unavailable: {_err}")

IDS = ["node0000.m0", "node0000.m1", "a", "long." * 10 + "id"]


def _state(ids=IDS):
    latest = np.full(len(ids), np.nan, np.float32)
    st = NativeJsonlState(ids, latest)
    return st, latest


# ------------------------------------------------------------ direct C API


@needs_native
def test_split_chunks_and_flush():
    st, latest = _state()
    c = st.new_conn()
    c.feed(b'{"id": "node0000.m0", "va')
    c.feed(b'lue": 2.5, "ts": 7}\n{"id": "a", "value"')
    c.feed(b': -1}\n{"id": "node0000.m1", "value": 9}')  # no trailing \n
    assert np.isnan(latest[1])  # unterminated: not yet processed
    c.flush()                   # EOF processes it, like rfile iteration
    assert latest[0] == np.float32(2.5)
    assert latest[1] == np.float32(9)
    assert latest[2] == np.float32(-1)
    assert st.ts_buf[0] == 7
    assert list(st.counters) == [3, 0, 0]
    c.close()


@needs_native
def test_value_and_ts_coercions_match_python():
    st, latest = _state()
    c = st.new_conn()
    # every coercion np.float32/int accept: quoted numbers, bools,
    # scientific notation, float ts (truncates), quoted ts digits
    c.feed(b'{"id": "a", "value": "7.25", "ts": 101.9}\n')
    assert latest[2] == np.float32(7.25) and st.ts_buf[0] == 101
    c.feed(b'{"id": "a", "value": true, "ts": "144"}\n')
    assert latest[2] == np.float32(1.0) and st.ts_buf[0] == 144
    c.feed(b'{"id": "a", "value": -3e2}\n')
    assert latest[2] == np.float32(-300.0)
    # np.float32(None) is nan, NOT an error: null values are missing samples
    c.feed(b'{"id": "a", "value": null}\n')
    assert np.isnan(latest[2])
    assert list(st.counters) == [4, 0, 0]
    # ...but np.float32("null") (quoted) raises
    c.feed(b'{"id": "a", "value": "null"}\n')
    assert list(st.counters) == [4, 1, 0]
    # bad ts on a known id still applies the value first (Python assigns
    # latest[i] before int(ts) can raise)
    c.feed(b'{"id": "a", "value": 5, "ts": "xx"}\n')
    assert latest[2] == np.float32(5.0)
    assert list(st.counters) == [4, 2, 0]
    # quoted ts goes through int(str): "101.9" and "1e3" raise in Python
    # (value still applied); hex never parses as a value
    c.feed(b'{"id": "a", "value": 6, "ts": "101.9"}\n')
    assert latest[2] == np.float32(6.0)
    c.feed(b'{"id": "a", "value": 8, "ts": "1e3"}\n')
    c.feed(b'{"id": "a", "value": "0x10"}\n')  # np.float32("0x10") raises
    assert list(st.counters) == [4, 5, 0]
    assert st.ts_buf[0] == 144  # unchanged by the failed conversions
    c.feed(b'{"id": "a", "value": 7, "ts": " -12 "}\n')  # int(" -12 ") works
    assert list(st.counters) == [5, 5, 0]
    c.close()


@needs_native
def test_counter_semantics_match_python_ordering():
    st, latest = _state()
    c = st.new_conn()
    c.feed(b'{"value": 5}\n')            # no id -> rec["id"] KeyError
    c.feed(b'{"id": "a"}\n')             # known id, no value -> KeyError
    c.feed(b'{"id": "zzz"}\n')           # unknown id checked BEFORE value
    c.feed(b'{"id": 5, "value": 1}\n')   # non-string id -> dict.get miss
    c.feed(b'garbage\n\n')               # malformed + empty line
    assert list(st.counters) == [0, 4, 2]
    # unhashable id: Python's dict.get({...}) raises TypeError -> error,
    # NOT unknown (scalar non-string ids are hashable and count unknown)
    c.feed(b'{"id": {"x": 1}, "value": 2}\n{"id": [1], "value": 2}\n')
    assert list(st.counters) == [0, 6, 2]
    c.close()


@needs_native
def test_oversized_line_resync():
    st, latest = _state()
    c = st.new_conn()
    big = b'{"id": "a", "value": ' + b"9" * 70000  # > MAX_LINE, no newline yet
    c.feed(big)
    c.feed(b'999}\n{"id": "a", "value": 3}\n')
    assert list(st.counters) == [1, 1, 0]  # oversized -> 1 error, then resync
    assert latest[2] == np.float32(3.0)
    c.close()


@needs_native
def test_escaped_strings_and_nested_values():
    st, latest = _state()
    c = st.new_conn()
    # escaped quote inside an irrelevant field; nested object skipped
    c.feed(b'{"note": "q\\"uoted", "id": "a", "meta": {"x": [1, 2]}, "value": 4}\n')
    assert latest[2] == np.float32(4.0)
    assert list(st.counters) == [1, 0, 0]
    c.close()


# ----------------------------------------------------- socket-level parity


def _drive(native: bool) -> tuple[np.ndarray, int, int, int]:
    ids = [f"s{i}" for i in range(8)]
    recs = []
    rng = np.random.default_rng(7)
    for k in range(500):
        recs.append({"id": ids[int(rng.integers(0, 8))],
                     "value": float(rng.normal()), "ts": 1700000000 + k})
    recs.insert(50, {"id": "nope", "value": 1.0})            # unknown
    recs.insert(90, {"id": ids[0], "value": "not-a-number"})  # parse error
    # in-order sentinel LAST: seeing its value means every record on this
    # connection was processed — counters alone are satisfied at record ~91
    # and would let the drain race the rest of the stream
    recs.append({"id": ids[7], "value": 424242.0, "ts": 1700009999})
    src = TcpJsonlSource(ids, native=native)
    with src:
        assert src.native_active == native
        send_jsonl(src.address, recs)
        deadline = time.time() + 5
        while time.time() < deadline:
            with src._lock:
                if src._latest[7] == np.float32(424242.0):
                    break
            time.sleep(0.02)
        values, ts = src(0)
    return values, ts, src.parse_errors, src.unknown_ids, src.records_parsed


@needs_native
def test_socket_parity_native_vs_python():
    v_n, ts_n, pe_n, unk_n, rec_n = _drive(native=True)
    v_p, ts_p, pe_p, unk_p, rec_p = _drive(native=False)
    assert np.array_equal(v_n, v_p, equal_nan=True)
    assert (ts_n, pe_n, unk_n) == (ts_p, pe_p, unk_p) == (ts_p, 1, 1)
    # ISSUE 7 satellite: success counting must agree across parser
    # backends (the Python fallback used to return None and starve
    # rtap_obs_ingest_records_total)
    assert rec_n == rec_p == 501


@needs_native
def test_multi_connection_and_drain():
    ids = ["x", "y"]
    src = TcpJsonlSource(ids, native=True)
    with src:
        send_jsonl(src.address, [{"id": "x", "value": 1.0, "ts": 10}])
        send_jsonl(src.address, [{"id": "y", "value": 2.0, "ts": 12}])
        deadline = time.time() + 5
        while time.time() < deadline and src.records_parsed != 2:
            time.sleep(0.02)
        assert src.records_parsed == 2
        values, ts = src(0)
        assert values[0] == 1.0 and values[1] == 2.0 and ts == 12
        # drain: next tick with no pushes is all-NaN, ts sticks
        values2, ts2 = src(1)
        assert np.isnan(values2).all() and ts2 == 12


def _fuzz_records(seed: int, ids: list[str], n: int) -> list[bytes]:
    """Randomized realistic-space records: shuffled field order, mixed
    value/ts types (including the coercible and the erroneous), unknown
    ids, extra fields, whitespace variation, malformed tails."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        r = rng.random()
        sid = ids[int(rng.integers(0, len(ids)))] if r < 0.85 else "ghost"
        value = rng.choice([
            str(float(rng.normal())), '"7.5"', "true", "false", "null",
            '"nope"', str(int(rng.integers(-100, 100))), "1e3",
        ])
        ts = rng.choice([str(int(rng.integers(1, 10**9))), '"123"',
                         '"9.5"', "55.7", "null"])
        fields = [f'"id": "{sid}"', f'"value": {value}', f'"ts": {ts}',
                  '"extra": {"nested": [1, "x"]}']
        rng.shuffle(fields)
        sep = ", " if rng.random() < 0.8 else ","
        line = "{" + sep.join(fields) + "}"
        if rng.random() < 0.06:
            line = line[: int(rng.integers(1, len(line)))]  # malformed tail
        out.append(line.encode())
    return out


@needs_native
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_socket_parity_fuzz(seed):
    """Native and Python paths must agree value-for-value and counter-for-
    counter across the randomized realistic record space — the evidence
    behind swapping the native parser in by default."""
    ids = [f"n{i}" for i in range(6)]
    lines = _fuzz_records(seed, ids, 400)
    payload = b"\n".join(lines) + b"\n"
    sentinel = json.dumps({"id": ids[0], "value": 31337.0}).encode() + b"\n"
    results = []
    for native in (True, False):
        src = TcpJsonlSource(ids, native=native)
        with src:
            with socket.create_connection(src.address, timeout=5.0) as s:
                s.sendall(payload + sentinel)
            deadline = time.time() + 10
            while time.time() < deadline:
                with src._lock:
                    if src._latest[0] == np.float32(31337.0):
                        break
                time.sleep(0.01)
            values, ts = src(0)
        results.append((values, ts, src.parse_errors, src.unknown_ids,
                        src.records_parsed))
    (v_n, ts_n, pe_n, unk_n, rec_n), (v_p, ts_p, pe_p, unk_p, rec_p) \
        = results
    assert np.array_equal(v_n, v_p, equal_nan=True)
    assert (ts_n, pe_n, unk_n, rec_n) == (ts_p, pe_p, unk_p, rec_p)
    assert pe_n > 0 and unk_n > 0  # the fuzz actually exercised both paths
    assert rec_n > 0  # and the success counter, on BOTH backends


@needs_native
def test_concurrent_producers_stress():
    """Two live connections pushing interleaved records in tiny odd-sized
    socket writes: per-connection remainder isolation plus the shared
    output array under the chunk lock. Every stream must end at its
    producer's final value and no record may be miscounted."""
    import threading

    G = 32
    ids = [f"c{i}" for i in range(G)]
    src = TcpJsonlSource(ids, native=True)
    n_each = 400

    def produce(half: int):
        own = ids[half * (G // 2):(half + 1) * (G // 2)]
        with socket.create_connection(src.address, timeout=5.0) as s:
            payload = b"".join(
                json.dumps({"id": own[k % len(own)],
                            "value": half * 1000.0 + k,
                            "ts": 1700000000 + k}).encode() + b"\n"
                for k in range(n_each)
            )
            # deliberately awkward write sizes to force mid-record splits
            for off in range(0, len(payload), 17):
                s.sendall(payload[off:off + 17])

    with src:
        threads = [threading.Thread(target=produce, args=(h,)) for h in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        deadline = time.time() + 10
        while time.time() < deadline and src.records_parsed < 2 * n_each:
            time.sleep(0.02)
        assert src.records_parsed == 2 * n_each
        assert src.parse_errors == 0 and src.unknown_ids == 0
        values, ts = src(0)
    # last value per stream: producer h wrote k = i, i+16, ... for its
    # stream i; the final write for stream i is the largest such k
    for half in (0, 1):
        own = list(range(half * (G // 2), (half + 1) * (G // 2)))
        for j, g in enumerate(own):
            last_k = max(k for k in range(n_each) if k % len(own) == j)
            assert values[g] == np.float32(half * 1000.0 + last_k)
    assert ts == 1700000000 + n_each - 1


def test_python_fallback_forced():
    src = TcpJsonlSource(["x"], native=False)
    with src:
        assert not src.native_active
        send_jsonl(src.address, [{"id": "x", "value": 3.5, "ts": 9}])
        deadline = time.time() + 5
        while time.time() < deadline:
            with src._lock:
                if not np.isnan(src._latest[0]):
                    break
            time.sleep(0.02)
        values, ts = src(0)
    assert values[0] == np.float32(3.5) and ts == 9
    assert src.records_parsed == 1  # counted on the fallback path too


def test_python_fallback_bad_ts_keeps_value_not_counted():
    """The C parser's ordering rule on the Python path: a bad ts keeps
    the value (written first) but the record counts as a parse error,
    never a parsed success — backends must agree on BOTH tallies."""
    src = TcpJsonlSource(["x"], native=False)
    with src:
        send_jsonl(src.address, [{"id": "x", "value": 5, "ts": "xx"}])
        deadline = time.time() + 5
        while time.time() < deadline and src.parse_errors < 1:
            time.sleep(0.02)
        values, _ = src(0)
    assert values[0] == np.float32(5.0)
    assert src.records_parsed == 0 and src.parse_errors == 1


@needs_native
def test_native_unknown_name_capture():
    """track_unknown on the NATIVE path: the C parser captures unknown-id
    names into the bounded buffer and drain_unknown returns them — serve
    --auto-register no longer needs the Python parse path."""
    src = TcpJsonlSource(["a", "b"], port=0, native=True,
                         track_unknown=True).start()
    try:
        assert src.native_active
        # the escaped id rides raw: wire bytes 'café' — capture must
        # SKIP it (a name registered under its wire spelling would
        # dead-letter on the Python fallback path, which json-decodes)
        with socket.create_connection(src.address, timeout=5.0) as s:
            s.sendall(b'{"id": "caf\\u00e9", "value": 0.5}\n')
        send_jsonl(src.address, [
            {"id": "a", "value": 1.0},
            {"id": "newcomer.x", "value": 2.0},
            {"id": "newcomer.y", "value": 3.0},
            {"id": "newcomer.x", "value": 4.0},  # dup: set dedups
            {"id": 123, "value": 5.0},           # numeric id: counted, not captured
        ])
        # both connections' handlers are async: wait for ALL 5 unknown
        # RECORDS (escaped café, x twice, y, numeric 123 — hashable miss
        # like dict.get(5)) before draining the captured names
        deadline = time.time() + 5
        while time.time() < deadline and src.unknown_ids < 5:
            time.sleep(0.02)
        assert src.unknown_ids == 5
        # only the 2 distinct plain string NAMES are capturable
        assert src.drain_unknown() == ["newcomer.x", "newcomer.y"]
        assert src.drain_unknown() == []  # drained
    finally:
        src.close()


@needs_native
def test_native_set_ids_swaps_table_mid_connection():
    """set_ids on the native path: the owner's table swap propagates to a
    per-connection parser mid-stream (shared indirection), partial-line
    state survives, and retained ids keep their latest value by id."""
    src = TcpJsonlSource(["a", "b"], port=0, native=True,
                         track_unknown=True).start()
    try:
        with socket.create_connection(src.address, timeout=5.0) as s:
            s.sendall(b'{"id": "a", "value": 7.0}\n{"id": "c", "value"')
            deadline = time.time() + 5
            while time.time() < deadline:
                with src._lock:
                    if src._latest[0] == np.float32(7.0):
                        break
                time.sleep(0.02)
            # membership change while the connection holds a partial line
            src.set_ids(["c", "a"])  # new id first: order is the caller's
            s.sendall(b": 9.0}\n")
        deadline = time.time() + 5
        while time.time() < deadline:
            with src._lock:
                if src._latest[0] == np.float32(9.0):
                    break
            time.sleep(0.02)
        values, _ = src(0)
        assert values[0] == np.float32(9.0)   # c: completed after the swap
        assert values[1] == np.float32(7.0)   # a: carried over BY ID
    finally:
        src.close()


def test_python_fallback_parse_error_count_is_exact_under_concurrency():
    """rtap-lint race-pass fix (ISSUE 12): the Python fallback handler
    bumped ``_py_parse_errors`` OUTSIDE the chunk lock — one
    read-modify-write per malformed line across N concurrent producer
    threads loses increments (the classic += lost update; every other
    tally already sat under the lock). The fix moves the bump under
    the lock; this pins the count exact across concurrent garbage
    producers on the fallback path."""
    import sys
    import threading

    src = TcpJsonlSource(["a", "b"], native=False)
    n_threads, n_bad = 6, 250

    def produce():
        with socket.create_connection(src.address, timeout=5.0) as s:
            payload = b"".join(b"not json at all\n" for _ in range(n_bad))
            s.sendall(payload)

    old_interval = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)  # widen the lost-update window
    try:
        with src:
            threads = [threading.Thread(target=produce)
                       for _ in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            deadline = time.time() + 10
            want = n_threads * n_bad
            while time.time() < deadline and src.parse_errors < want:
                time.sleep(0.02)
            assert src.parse_errors == want
            assert src.records_parsed == 0
    finally:
        sys.setswitchinterval(old_interval)


def test_tcp_source_close_joins_accept_thread():
    """ISSUE 13 resource-lifecycle regression: close() must join the
    accept thread (bounded) — before the fix the Thread object outlived
    close(), which the conftest leak fixture only caught when a test
    happened to observe the window."""
    src = TcpJsonlSource(["s0"], port=0).start()
    src.close()
    assert not src._thread.is_alive()
