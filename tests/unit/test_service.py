"""Service-layer tests: batched likelihood parity, groups, replay loop."""

import json

import numpy as np
import pytest

from rtap_tpu.config import LikelihoodConfig, cluster_preset
from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_cluster
from rtap_tpu.models.oracle.likelihood import AnomalyLikelihood
from rtap_tpu.service.likelihood_batch import BatchAnomalyLikelihood
from rtap_tpu.service.loop import live_loop, replay_streams
from rtap_tpu.service.registry import StreamGroup, StreamGroupRegistry


def _scores(n, g, seed=0):
    rng = np.random.Generator(np.random.Philox(key=(seed, 2)))
    s = rng.random((n, g)) * 0.3
    s[n // 2 :, :] *= 0.5
    s[int(n * 0.8), :] = 1.0  # a spike
    return s


@pytest.mark.parametrize("mode", ["window", "streaming"])
def test_batch_likelihood_matches_oracle(mode):
    cfg = LikelihoodConfig(mode=mode, learning_period=40, estimation_samples=20,
                           historic_window_size=120, reestimation_period=10)
    G, N = 5, 300
    batch = BatchAnomalyLikelihood(cfg, G)
    oracles = [AnomalyLikelihood(cfg) for _ in range(G)]
    scores = _scores(N, G)
    for i in range(N):
        lik_b, log_b = batch.update(scores[i])
        for g in range(G):
            lik_o, log_o = oracles[g].update(float(scores[i, g]))
            # batch reductions may differ from sequential sums by ~ulps
            assert lik_b[g] == pytest.approx(lik_o, rel=1e-9, abs=1e-12), f"step {i} g {g}"
            assert log_b[g] == pytest.approx(log_o, rel=1e-9, abs=1e-12), f"step {i} g {g}"


@pytest.mark.parametrize("mode", ["window", "streaming"])
def test_batch_likelihood_checkpoint_roundtrip(mode):
    cfg = LikelihoodConfig(mode=mode, learning_period=30, estimation_samples=10,
                           historic_window_size=80, reestimation_period=10)
    G, N = 3, 150
    a = BatchAnomalyLikelihood(cfg, G)
    scores = _scores(N, G, seed=3)
    for i in range(N // 2):
        a.update(scores[i])
    b = BatchAnomalyLikelihood(cfg, G)
    b.load_state_dict({k: np.copy(v) for k, v in a.state_dict().items()})
    for i in range(N // 2, N):
        la, ga = a.update(scores[i])
        lb, gb = b.update(scores[i])
        np.testing.assert_array_equal(la, lb)
        np.testing.assert_array_equal(ga, gb)


def test_group_backends_agree():
    """TPU group tick == CPU oracle group tick, end to end with likelihood."""
    cfg = cluster_preset()
    ids = [f"s{i}" for i in range(3)]
    tpu = StreamGroup(cfg, ids, backend="tpu")
    cpu = StreamGroup(cfg, ids, backend="cpu")
    rng = np.random.Generator(np.random.Philox(key=(1, 4)))
    for i in range(120):
        v = (40 + 10 * rng.random(3)).astype(np.float32)
        if i == 90:
            v[1] += 60
        rt = tpu.tick(v, 1_700_000_000 + i)
        rc = cpu.tick(v, 1_700_000_000 + i)
        np.testing.assert_allclose(rt.raw, rc.raw, atol=0)  # bit-exact on CPU platform
        np.testing.assert_allclose(rt.log_likelihood, rc.log_likelihood, rtol=1e-9)


def test_chunk_matches_ticks():
    """run_chunk(T ticks) == T sequential tick() calls."""
    cfg = cluster_preset()
    ids = [f"s{i}" for i in range(4)]
    a = StreamGroup(cfg, ids, backend="tpu")
    b = StreamGroup(cfg, ids, backend="tpu")
    rng = np.random.Generator(np.random.Philox(key=(2, 4)))
    T = 60
    vals = (30 + 5 * rng.random((T, 4))).astype(np.float32)
    ts = (1_700_000_000 + np.arange(T)[:, None] + np.zeros((1, 4))).astype(np.int64)
    raw_chunk, ll_chunk, _ = a.run_chunk(vals, ts)
    for i in range(T):
        res = b.tick(vals[i], ts[i])
        np.testing.assert_array_equal(raw_chunk[i], res.raw, err_msg=f"tick {i}")
        np.testing.assert_array_equal(ll_chunk[i], res.log_likelihood, err_msg=f"tick {i}")


def test_registry_grouping_and_padding():
    cfg = cluster_preset()
    reg = StreamGroupRegistry(cfg, group_size=4, backend="cpu")
    for i in range(6):
        reg.add_stream(f"node{i}.cpu")
    reg.finalize()
    assert len(reg.groups) == 2
    assert reg.groups[0].n_live == 4 and reg.groups[1].n_live == 2
    assert reg.groups[1].G == 4  # padded to fixed size
    grp, slot = reg.lookup("node4.cpu")
    assert grp is reg.groups[1] and slot == 0
    with pytest.raises(KeyError):
        reg.add_stream("node0.cpu")


def test_replay_streams_end_to_end(tmp_path):
    """Replay a small synthetic cluster; anomalies raise scores; alerts JSONL."""
    scfg = SyntheticStreamConfig(length=500, cadence_s=1.0, n_anomalies=1,
                                 kinds=("spike",), anomaly_magnitude=8.0)
    streams = generate_cluster(3, metrics=("cpu",), cfg=scfg, seed=5)
    cfg = cluster_preset()
    path = str(tmp_path / "alerts.jsonl")
    res = replay_streams(streams, cfg, backend="tpu", group_size=2,
                         chunk_ticks=50, alert_path=path)
    assert res.raw.shape == (500, 3)
    assert res.throughput["scored"] == 1500
    # every line in the alert file is valid JSON with the expected keys
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == res.throughput["alerts"] == int(res.alerts.sum())
    for l in lines[:3]:
        assert set(l) == {"stream", "ts", "value", "raw_score", "log_likelihood"}


def test_live_loop_paced():
    cfg = cluster_preset()
    grp = StreamGroup(cfg, [f"s{i}" for i in range(4)], backend="tpu")
    rng = np.random.Generator(np.random.Philox(key=(3, 4)))

    def source(k):
        return (30 + 5 * rng.random(4)).astype(np.float32), 1_700_000_000 + k

    import time as _time

    t0 = _time.perf_counter()
    stats = live_loop(source, grp, n_ticks=6, cadence_s=0.25)
    elapsed = _time.perf_counter() - t0
    assert stats["scored"] == 24 and stats["ticks"] == 6
    # This pins PACING SEMANTICS, not performance: the loop must sleep off
    # unused budget (so 6 ticks take >= 5 cadences) and count only genuine
    # overruns. The cadence is deliberately generous — at 0.02 s this test
    # flaked whenever a background process stole the 1-core host for a few
    # ticks (observed: 4/10 missed under a concurrent jax-init probe).
    assert elapsed >= 5 * 0.25
    assert stats["missed_deadlines"] <= 2  # first tick compiles; allow jitter


def test_learn_false_freezes_state():
    """Inference-only stepping must not mutate learned state on either backend."""
    import jax

    cfg = cluster_preset()
    ids = [f"s{i}" for i in range(3)]
    rng = np.random.Generator(np.random.Philox(key=(9, 4)))
    warm = (30 + 5 * rng.random((40, 3))).astype(np.float32)
    probe = (30 + 5 * rng.random((10, 3))).astype(np.float32)
    ts0 = 1_700_000_000

    for backend in ("tpu", "cpu"):
        grp = StreamGroup(cfg, ids, backend=backend)
        for i in range(40):
            grp.tick(warm[i], ts0 + i)
        if backend == "tpu":
            before = {k: np.asarray(v) for k, v in jax.device_get(grp.state).items()}
        else:
            before = [{k: np.copy(v) for k, v in s.items()} for s in grp._states]
        for i in range(10):
            grp.tick(probe[i], ts0 + 40 + i, learn=False)
        # learned state identical; only the recurrent activity /iter slots move
        frozen = ("perm", "syn_perm", "presyn", "boost", "overlap_duty",
                  "active_duty", "seg_last", "sp_iter")
        if backend == "tpu":
            after = {k: np.asarray(v) for k, v in jax.device_get(grp.state).items()}
            for k in frozen:
                np.testing.assert_array_equal(before[k], after[k], err_msg=f"{backend}:{k}")
        else:
            for g in range(3):
                for k in frozen:
                    np.testing.assert_array_equal(
                        before[g][k], grp._states[g][k], err_msg=f"{backend}:{k}"
                    )


def test_replay_learn_false_runs():
    scfg = SyntheticStreamConfig(length=120, cadence_s=1.0, n_anomalies=0)
    streams = generate_cluster(2, metrics=("cpu",), cfg=scfg, seed=6)
    cfg = cluster_preset()
    res = replay_streams(streams, cfg, backend="tpu", chunk_ticks=40, learn=False)
    assert res.raw.shape == (120, 2) and np.isfinite(res.raw).all()


# ---- advisor-finding guards (round 5) ----


def test_bulk_add_rejects_pad_prefix():
    """A pad-prefixed id on the PRE-finalize bulk path must fail like
    claim_slot's guard: buffered, it would silently read as pad capacity
    (never emitted) and its slot could later be double-claimed."""
    reg = StreamGroupRegistry(cluster_preset(), group_size=4, backend="tpu")
    with pytest.raises(ValueError, match="__pad"):
        reg.add_stream("__pad_evil")


def test_live_loop_rejects_unfinalized_registry():
    """Exact-multiple stream counts leave _pending empty WITHOUT finalize();
    live_loop must still refuse — post-finalize membership (claims/releases)
    on an unfinalized registry buffers into _pending, invisible to the
    loop's groups snapshot."""
    reg = StreamGroupRegistry(cluster_preset(), group_size=2, backend="tpu")
    reg.add_stream("a")
    reg.add_stream("b")  # seals the group: _pending is empty, not finalized
    assert not reg._pending and not reg._finalized

    def source(k):
        return np.zeros(2, np.float32), 1_700_000_000 + k

    with pytest.raises(ValueError, match="finalize"):
        live_loop(source, reg, n_ticks=1, cadence_s=0.01)


def test_stray_checkpoint_guard_matches_long_group_names(tmp_path):
    """group indices >= 10000 are saved as 'group10000' (5 digits); the
    stray-topology scan must catch them too, not just \\d{4}."""
    import os

    reg = StreamGroupRegistry(cluster_preset(), group_size=2, backend="tpu")
    reg.add_stream("a")
    reg.finalize()
    os.makedirs(tmp_path / "group10000")

    def source(k):
        return np.zeros(1, np.float32), 1_700_000_000 + k

    with pytest.raises(ValueError, match="beyond this"):
        live_loop(source, reg, n_ticks=1, cadence_s=0.01,
                  checkpoint_dir=str(tmp_path), checkpoint_every=1)


def test_frozen_replay_from_completed_checkpoint_errors(tmp_path):
    """Resuming a COMPLETED run's final checkpoint (frozen or learning)
    would silently score zero ticks; it must error and point at
    serve --freeze."""
    scfg = SyntheticStreamConfig(length=64, cadence_s=1.0, n_anomalies=0)
    streams = generate_cluster(1, metrics=("cpu",), cfg=scfg, seed=6)
    cfg = cluster_preset()
    ck = str(tmp_path / "ck")
    replay_streams(streams, cfg, backend="tpu", chunk_ticks=32,
                   checkpoint_dir=ck, checkpoint_every=1)
    with pytest.raises(ValueError, match="nothing left to replay"):
        replay_streams(streams, cfg, backend="tpu", chunk_ticks=32,
                       checkpoint_dir=ck, learn=False)
    # same silent no-op exists for a LEARNING replay resumed at the end
    with pytest.raises(ValueError, match="nothing left to replay"):
        replay_streams(streams, cfg, backend="tpu", chunk_ticks=32,
                       checkpoint_dir=ck)


def test_partial_multigroup_resume_still_works(tmp_path):
    """The all-complete guard must NOT break crash recovery when only SOME
    groups finished: a completed group skips (all-NaN rows, prior-run
    semantics) while the interrupted group replays to the end."""
    import shutil

    scfg = SyntheticStreamConfig(length=64, cadence_s=1.0, n_anomalies=0)
    streams = generate_cluster(2, metrics=("cpu",), cfg=scfg, seed=6)
    cfg = cluster_preset()
    ck = str(tmp_path / "ck")
    replay_streams(streams, cfg, backend="tpu", group_size=1, chunk_ticks=32,
                   checkpoint_dir=ck, checkpoint_every=1)
    # simulate a crash that lost group1's checkpoint: group0 is complete,
    # group1 must restart from scratch — the replay must run, not raise
    shutil.rmtree(tmp_path / "ck" / "group0001")
    res = replay_streams(streams, cfg, backend="tpu", group_size=1,
                         chunk_ticks=32, checkpoint_dir=ck)
    assert np.isnan(res.raw[:, 0]).all()      # completed group: prior run's
    assert np.isfinite(res.raw[:, 1]).all()   # interrupted group: rescored
    assert res.throughput["resumed_from"] == {"group0": 64}


@pytest.mark.quick
def test_occupancy_sums_over_every_local_device(monkeypatch):
    """Regression for the ISSUE 15 device-scope finding: _occupancy read
    local_devices()[0] only, under-reporting HBM by the shard count on a
    multi-device host. It must SUM bytes over the local device list (and
    stay numerically identical on single-device hosts)."""
    import jax

    from rtap_tpu.service.loop import _occupancy

    class _Dev:
        def __init__(self, stats):
            self._stats = stats

        def memory_stats(self):
            return self._stats

    devs = [_Dev({"bytes_in_use": 100, "peak_bytes_in_use": 150}),
            _Dev({"bytes_in_use": 40, "peak_bytes_in_use": 60}),
            _Dev(None)]  # a backend exposing no stats must not poison
    monkeypatch.setattr(jax, "local_devices", lambda: devs)
    out = _occupancy()
    assert out == {"hbm_bytes_in_use": 140, "hbm_peak_bytes_in_use": 210}
    # single-device: identical to the old [0] read
    monkeypatch.setattr(jax, "local_devices", lambda: devs[:1])
    assert _occupancy() == {"hbm_bytes_in_use": 100,
                            "hbm_peak_bytes_in_use": 150}
