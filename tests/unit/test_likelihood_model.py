"""AnomalyLikelihood semantics + HTMModel/AnomalyDetector API surface."""

import numpy as np
import pytest

from rtap_tpu.config import (
    DateConfig,
    LikelihoodConfig,
    ModelConfig,
    RDSEConfig,
    SPConfig,
    TMConfig,
    cluster_preset,
    nab_preset,
)
from rtap_tpu.models import AnomalyDetector, HTMModel, create_model
from rtap_tpu.models.oracle.likelihood import AnomalyLikelihood, log_likelihood, tail_probability


class TestLikelihood:
    CFG = LikelihoodConfig(learning_period=20, estimation_samples=10,
                           historic_window_size=200, reestimation_period=10,
                           averaging_window=5)

    def test_probation_returns_half(self):
        al = AnomalyLikelihood(self.CFG)
        for _ in range(self.CFG.probationary_period - 1):
            lik, _ = al.update(0.3)
            assert lik == 0.5

    def test_spike_after_stable_history_is_anomalous(self):
        al = AnomalyLikelihood(self.CFG)
        rng = np.random.default_rng(0)
        for _ in range(100):
            al.update(float(rng.uniform(0.0, 0.2)))
        liks = [al.update(1.0)[0] for _ in range(5)]
        assert max(liks) > 0.999

    def test_stable_scores_not_anomalous(self):
        al = AnomalyLikelihood(self.CFG)
        rng = np.random.default_rng(1)
        liks = [al.update(float(rng.uniform(0.0, 0.2)))[0] for _ in range(200)]
        assert max(liks[50:]) < 0.999

    def test_streaming_mode_tracks_window_mode(self):
        import dataclasses

        rng = np.random.default_rng(2)
        scores = rng.uniform(0.0, 0.3, 300).tolist() + [1.0] * 3
        a = AnomalyLikelihood(self.CFG)
        b = AnomalyLikelihood(dataclasses.replace(self.CFG, mode="streaming", streaming_decay=0.99))
        la = [a.update(s)[0] for s in scores]
        lb = [b.update(s)[0] for s in scores]
        # both flag the spike hard
        assert la[-1] > 0.99 and lb[-1] > 0.99

    def test_log_likelihood_scale(self):
        assert log_likelihood(0.5) == pytest.approx(0.0301, abs=1e-3)
        assert log_likelihood(1.0) == pytest.approx(1.0, abs=1e-4)
        assert log_likelihood(0.0) == pytest.approx(0.0, abs=1e-9)

    def test_tail_probability(self):
        assert tail_probability(0.0) == pytest.approx(0.5)
        assert tail_probability(3.0) == pytest.approx(0.00135, abs=1e-4)


def small_cfg():
    return ModelConfig(
        rdse=RDSEConfig(size=64, active_bits=7, resolution=1.0),
        date=DateConfig(time_of_day_width=0, time_of_day_size=0),
        sp=SPConfig(columns=64, num_active_columns=4),
        tm=TMConfig(cells_per_column=4, activation_threshold=3, min_threshold=2,
                    max_segments_per_cell=4, max_synapses_per_segment=8,
                    new_synapse_count=4),
        likelihood=LikelihoodConfig(learning_period=20, estimation_samples=10,
                                    reestimation_period=10, averaging_window=5),
    )


class TestHTMModel:
    def test_run_returns_result(self):
        m = HTMModel(small_cfg(), seed=1)
        r = m.run(1000, 5.0)
        assert r.raw_score == 1.0  # first record always fully novel
        assert 0.0 <= r.likelihood <= 1.0

    def test_offset_binds_to_first_value(self):
        m = HTMModel(small_cfg())
        m.run(0, 42.5)
        assert m.state["enc_offset"][0] == pytest.approx(42.5)
        assert m.state["enc_bound"].all()

    def test_leading_nan_does_not_poison_offset(self):
        m = HTMModel(small_cfg())
        m.run(0, float("nan"))
        assert not m.state["enc_bound"].any()
        m.run(1, 42.5)
        assert m.state["enc_offset"][0] == pytest.approx(42.5)
        r = m.run(2, 42.5)
        assert np.isfinite(r.raw_score)

    def test_periodic_signal_becomes_predictable(self):
        m = HTMModel(small_cfg(), seed=2)
        raws = [m.run(t, float(10 + 5 * (t % 4))).raw_score for t in range(200)]
        assert np.mean(raws[:8]) > 0.8
        assert np.mean(raws[-40:]) < 0.1

    def test_invalid_backend_raises(self):
        with pytest.raises(ValueError, match="backend"):
            HTMModel(small_cfg(), backend="gpu")

    def test_create_model_default_is_nab_preset(self):
        m = create_model(min_val=0, max_val=130)
        assert m.cfg.sp.columns == 2048
        assert m.cfg.rdse.resolution == pytest.approx(1.0)

    def test_presets_valid(self):
        for cfg in (nab_preset(), cluster_preset()):
            assert cfg.input_size > 0
            assert cfg.sp.num_active_columns < cfg.sp.columns


class TestAnomalyDetector:
    def test_alert_on_pattern_break(self):
        det = AnomalyDetector(small_cfg(), seed=3, threshold=0.35)
        alerts = []
        for t in range(300):
            v = 10.0 + 5 * (t % 4)
            if 250 <= t < 260:
                # erratic injected anomaly; a *constant* anomalous level would
                # be learned as the new normal within a few steps (HTM design)
                v = 60.0 + 17.0 * (t % 3)
            score, alert = det.handle_record(t, v)
            alerts.append(alert)
        assert not any(alerts[100:250])
        assert any(alerts[250:270])


def test_window_ring_memory_guard(monkeypatch, caplog):
    """Window mode's [G, W] host ring warns above 1 GB and refuses above the
    (env-overridable) hard cap — the 100k-stream regime must use streaming
    mode, not silently swallow host RAM (SURVEY.md §7 hard part 5)."""
    import logging

    import pytest

    from rtap_tpu.config import LikelihoodConfig
    from rtap_tpu.service.likelihood_batch import BatchAnomalyLikelihood

    cfg = LikelihoodConfig(mode="window", historic_window_size=8640)
    monkeypatch.setenv("RTAP_MAX_LIKELIHOOD_RING_GB", "0.05")
    with pytest.raises(ValueError, match="streaming"):
        BatchAnomalyLikelihood(cfg, 100_000)
    # warn path: shrink the soft limit so the test ring stays tiny
    monkeypatch.setenv("RTAP_MAX_LIKELIHOOD_RING_GB", "1000")
    small = LikelihoodConfig(mode="window", historic_window_size=10)

    class _Probe(BatchAnomalyLikelihood):
        RING_WARN_BYTES = 1024

    with caplog.at_level(logging.WARNING):
        _Probe(small, 100)  # 8 * 100 * 10 = 8000 B > 1024 B probe limit
    assert any("streaming" in r.message for r in caplog.records)
    caplog.clear()
    with caplog.at_level(logging.WARNING):
        BatchAnomalyLikelihood(small, 4)  # tiny ring: silent
    assert not caplog.records


def test_vector_erfc_matches_libm():
    """The vectorized Cody erfc (the G=100k production path —
    reports/likelihood_100k.json) must track math.erfc to ~1e-15 relative
    everywhere erfc is representable, including the branch joins at
    0.46875 and 4.0 and the negative reflection."""
    import math

    from rtap_tpu.service.likelihood_batch import erfc_np

    xs = np.concatenate([
        np.linspace(-26.0, 26.0, 200_001),
        np.linspace(0.46874, 0.46876, 2001),   # branch-1/2 join
        np.linspace(3.9999, 4.0001, 2001),     # branch-2/3 join
        np.array([0.0, -0.0, 1e-300, -1e-300, 0.46875, 4.0, 26.0, -26.0]),
    ])
    ref = np.array([math.erfc(float(v)) for v in xs])
    got = erfc_np(xs)
    ok = ref != 0.0
    rel = np.abs(got[ok] - ref[ok]) / np.abs(ref[ok])
    assert rel.max() < 5e-15, rel.max()
    # extreme tails: exact saturation must match (Q=0 / Q=2 semantics)
    assert erfc_np(np.array([40.0]))[0] == 0.0
    assert erfc_np(np.array([-40.0]))[0] == 2.0
    # empty input must not crash the subset-evaluation paths
    assert erfc_np(np.empty(0)).shape == (0,)
