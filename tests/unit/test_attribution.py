"""Per-alert provenance (ISSUE 4 satellite): the encoder key-space
decode in service/attribution.py must name the field that actually
spiked on a known multivariate fault, ride alert JSONL lines through
AlertWriter, and survive NaN gaps / routing changes without growing
state."""

import json

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset, node_preset
from rtap_tpu.service.alerts import AlertWriter
from rtap_tpu.service.attribution import AlertAttributor

NO_ALERTS = np.array([], np.int64)


@pytest.mark.quick
def test_known_multivariate_spike_attributes_to_the_spiked_field():
    cfg = node_preset(3)  # cpu/mem/net fused into one SDR
    at = AlertAttributor(cfg, top_k=3)
    ids = ["node0", "node1"]
    base = np.array([[30.0, 50.0, 10.0], [1.0, 2.0, 3.0]], np.float32)
    at.update_and_attribute(ids, base, NO_ALERTS)
    spike = base.copy()
    spike[0, 1] += 500.0  # mem on node0 jumps; cpu/net unchanged
    out = at.update_and_attribute(ids, spike, np.array([0]))
    top = out[0]
    assert top and top[0]["field"] == 1
    # the other fields didn't move a bucket: the spiked field owns the
    # whole contribution mass
    assert top[0]["contribution"] == pytest.approx(1.0)
    assert abs(top[0]["bucket_delta"]) >= cfg.rdse.active_bits
    assert [f["field"] for f in top] == [1]


@pytest.mark.quick
def test_partial_moves_rank_fields_by_bucket_distance():
    cfg = node_preset(3)
    at = AlertAttributor(cfg, top_k=2)
    ids = ["n0"]
    res = float(np.float32(cfg.rdse.resolution))
    at.update_and_attribute(ids, np.array([[10.0, 10.0, 10.0]], np.float32),
                            NO_ALERTS)
    # field 2 moves 4 buckets, field 0 moves 2, field 1 holds still
    nxt = np.array([[10.0 + 2 * res, 10.0, 10.0 + 4 * res]], np.float32)
    out = at.update_and_attribute(ids, nxt, np.array([0]))
    fields = [f["field"] for f in out[0]]
    assert fields == [2, 0]  # top_k=2, ranked by lost overlap
    assert out[0][0]["contribution"] > out[0][1]["contribution"]


@pytest.mark.quick
def test_large_magnitude_baseline_keeps_precision():
    """Review fix: the bucket delta must be round((cur-base)/res), not
    round(cur/res) - round(base/res) — on a large-magnitude baseline
    (cumulative counters ~1e10) the separate roundings saturate the
    ±2^30 bucket clamp / lose the move to f32 mantissa, zeroing the
    attribution of the very field that spiked."""
    cfg = node_preset(3)
    at = AlertAttributor(cfg)
    ids = ["n0"]
    base = np.array([[1.0e10, 2.0e10, 3.0e10]], np.float32)
    at.update_and_attribute(ids, base, NO_ALERTS)
    spike = base.copy()
    spike[0, 1] += 1.0e9  # a real move, tiny relative to the baseline
    out = at.update_and_attribute(ids, spike, np.array([0]))
    assert out[0] and out[0][0]["field"] == 1
    assert out[0][0]["contribution"] == pytest.approx(1.0)


@pytest.mark.quick
def test_nan_gap_keeps_the_pre_gap_baseline():
    cfg = node_preset(2)
    at = AlertAttributor(cfg)
    ids = ["n0"]
    at.update_and_attribute(ids, np.array([[5.0, 5.0]], np.float32),
                            NO_ALERTS)
    # a missing sample (both fields NaN) must not become the baseline
    at.update_and_attribute(
        ids, np.array([[np.nan, np.nan]], np.float32), NO_ALERTS)
    out = at.update_and_attribute(
        ids, np.array([[5.0, 500.0]], np.float32), np.array([0]))
    assert out[0] and out[0][0]["field"] == 1


@pytest.mark.quick
def test_first_tick_and_no_movement_yield_empty_attribution():
    cfg = node_preset(2)
    at = AlertAttributor(cfg)
    ids = ["n0"]
    v = np.array([[1.0, 2.0]], np.float32)
    assert at.update_and_attribute(ids, v, np.array([0]))[0] == []
    # unchanged values: nothing to attribute (temporal/date-driven alert)
    assert at.update_and_attribute(ids, v, np.array([0]))[0] == []


@pytest.mark.quick
def test_univariate_streams_attribute_to_field_zero():
    cfg = cluster_preset()
    at = AlertAttributor(cfg)
    ids = ["s0", "s1"]
    at.update_and_attribute(ids, np.array([10.0, 10.0], np.float32),
                            NO_ALERTS)
    out = at.update_and_attribute(
        ids, np.array([10.0, 900.0], np.float32), np.array([1]))
    assert out[1][0]["field"] == 0
    assert out[1][0]["contribution"] == pytest.approx(1.0)


@pytest.mark.quick
def test_alert_writer_rides_top_fields_onto_alert_lines(tmp_path):
    cfg = node_preset(3)
    path = tmp_path / "alerts.jsonl"
    w = AlertWriter(str(path), attributor=AlertAttributor(cfg))
    ids = ["n0", "n1"]
    base = np.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]], np.float32)
    raw = np.zeros(2, np.float32)
    ll = np.zeros(2)
    # tick 0: history primes, no alert
    w.emit_batch(ids, np.array([100, 100]), base, raw, ll,
                 np.zeros(2, bool))
    spike = base.copy()
    spike[1, 0] += 400.0
    w.emit_batch(ids, np.array([101, 101]), spike, raw, ll,
                 np.array([False, True]))
    w.close()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == 1
    assert lines[0]["stream"] == "n1"
    assert lines[0]["top_fields"][0]["field"] == 0
    # without an attributor the schema is unchanged
    w2 = AlertWriter(str(tmp_path / "plain.jsonl"))
    w2.emit_batch(ids, np.array([1, 1]), base, raw, ll,
                  np.array([True, False]))
    w2.close()
    line = json.loads((tmp_path / "plain.jsonl").read_text())
    assert "top_fields" not in line


@pytest.mark.quick
def test_routing_history_keeps_many_live_groups_and_bounds_churn(
        monkeypatch):
    """Review fix: a fleet with hundreds of groups (100k streams at
    G=256 is ~390 routing tuples) must keep EVERY live group's history —
    the cap only retires churned-away tuples, and an eviction of a
    recently-updated route is counted, never silent."""
    import rtap_tpu.service.attribution as mod

    cfg = cluster_preset()
    at = AlertAttributor(cfg)
    # 390 live "groups", touched every round: far below the cap, so no
    # eviction ever — attribution still works after several rounds
    live = [[f"g{i}"] for i in range(390)]
    for _round in range(3):
        for ids in live:
            at.update_and_attribute(ids, np.array([10.0], np.float32),
                                    NO_ALERTS)
    assert len(at._prev) == 390 and at.live_evictions == 0
    out = at.update_and_attribute(live[0], np.array([900.0], np.float32),
                                  np.array([0]))
    assert out[0] and out[0][0]["field"] == 0  # history intact -> attributed
    # unbounded churn of single-use routes stays bounded at the cap (LRU
    # drops the oldest), and the cap-overflow accounting fires
    monkeypatch.setattr(mod, "_MAX_TRACKED_ROUTES", 64)
    for i in range(200):
        at.update_and_attribute([f"churn{i}"], np.array([1.0], np.float32),
                                NO_ALERTS)
    assert len(at._prev) <= 64
    assert at.live_evictions > 0  # fresh evictions are visible, not silent


# ---- composite per-field decode (ISSUE 9): alerts name the FIELD ----

def _composite_cfg():
    import dataclasses

    from rtap_tpu.config import CompositeEncoderConfig, FieldSpec

    return dataclasses.replace(
        cluster_preset(), n_fields=3,
        composite=CompositeEncoderConfig(fields=(
            FieldSpec(name="value", kind="rdse", size=128, active_bits=11,
                      resolution=0.5),
            FieldSpec(name="delta", kind="delta", size=128, active_bits=11,
                      resolution=0.5),
            FieldSpec(name="event_class", kind="categorical", size=128,
                      active_bits=11),
        )))


@pytest.mark.quick
def test_composite_alert_names_the_spiked_field():
    cfg = _composite_cfg()
    at = AlertAttributor(cfg, top_k=3)
    ids = ["svc-00"]
    # two quiet ticks first: the delta field needs 2-deep history
    at.update_and_attribute(ids, np.array([[10.0, 10.0, 2.0]], np.float32),
                            NO_ALERTS)
    at.update_and_attribute(ids, np.array([[10.0, 10.0, 2.0]], np.float32),
                            NO_ALERTS)
    # the value spikes; it carries the SAME wire value into the delta
    # field (the composite wire convention), so both fire — the value
    # by bucket distance, the delta by its encoded first difference
    out = at.update_and_attribute(
        ids, np.array([[60.0, 60.0, 2.0]], np.float32), np.array([0]))
    top = out[0]
    assert top, "a 100-bucket move must attribute"
    names = [f["name"] for f in top]
    assert "value" in names and "delta" in names
    assert "event_class" not in names  # the category never changed
    for f in top:
        assert f["name"] == ("value", "delta", "event_class")[f["field"]]


@pytest.mark.quick
def test_categorical_field_is_all_or_nothing():
    """Distinct category ids share no hash keys: ANY id change is full
    novelty (1.0), and an unchanged id contributes zero — unlike the
    rdse's graded bucket distance."""
    cfg = _composite_cfg()
    at = AlertAttributor(cfg, top_k=3)
    ids = ["svc-00"]
    at.update_and_attribute(ids, np.array([[10.0, 10.0, 2.0]], np.float32),
                            NO_ALERTS)
    at.update_and_attribute(ids, np.array([[10.0, 10.0, 2.0]], np.float32),
                            NO_ALERTS)
    # only the event class moves — by ONE id, the adjacency the rdse
    # would score as a near-zero 1-bucket nudge
    out = at.update_and_attribute(
        ids, np.array([[10.0, 10.0, 3.0]], np.float32), np.array([0]))
    top = out[0]
    assert [f["name"] for f in top] == ["event_class"]
    assert top[0]["contribution"] == pytest.approx(1.0)
    assert top[0]["bucket_delta"] == 1


@pytest.mark.quick
def test_categorical_ids_beyond_the_encoder_clamp_do_not_attribute():
    """Two raw wire ids past ``FieldSpec.categorical_clamp()`` clip to
    the SAME category in the encoder (bit-identical SDR on both
    backends), so the decode must not name the field as spiked — the
    attribution mirrors the encoder's id clamp."""
    cfg = _composite_cfg()
    at = AlertAttributor(cfg, top_k=3)
    ids = ["svc-00"]
    # clamp = (1<<30)//11 ~= 97.6M: both ids below sit beyond it
    at.update_and_attribute(ids, np.array([[10.0, 10.0, 2e8]], np.float32),
                            NO_ALERTS)
    at.update_and_attribute(ids, np.array([[10.0, 10.0, 2e8]], np.float32),
                            NO_ALERTS)
    out = at.update_and_attribute(
        ids, np.array([[10.0, 10.0, 3e8]], np.float32), np.array([0]))
    assert "event_class" not in [f["name"] for f in out[0]]


@pytest.mark.quick
def test_delta_field_fires_on_slope_flip_inside_the_band():
    """The delta encoder's reason to exist: a rate-of-change anomaly at
    an ordinary absolute level. The value field sees a small bucket
    move; the delta field sees its encoded first difference jump."""
    cfg = _composite_cfg()
    at = AlertAttributor(cfg, top_k=3)
    ids = ["svc-00"]
    # steady +0.5/tick ramp: encoded delta constant at bucket +1
    at.update_and_attribute(ids, np.array([[10.0, 10.0, 2.0]], np.float32),
                            NO_ALERTS)
    at.update_and_attribute(ids, np.array([[10.5, 10.5, 2.0]], np.float32),
                            NO_ALERTS)
    # slope flips to -0.5/tick: |value| moves 2 buckets, the DELTA moves
    # from +0.5 to -0.5 (2 buckets at res 0.5) — both report, and the
    # delta's verdict needed the tick-before-base history row
    out = at.update_and_attribute(
        ids, np.array([[10.0, 10.0, 2.0]], np.float32), np.array([0]))
    by_name = {f["name"]: f for f in out[0]}
    assert "delta" in by_name
    assert by_name["delta"]["bucket_delta"] == -2


@pytest.mark.quick
def test_delta_field_has_no_verdict_without_two_ticks_of_history():
    cfg = _composite_cfg()
    at = AlertAttributor(cfg, top_k=3)
    ids = ["svc-00"]
    at.update_and_attribute(ids, np.array([[10.0, 10.0, 2.0]], np.float32),
                            NO_ALERTS)
    # first attributable tick: base exists, base2 does not — the delta
    # field must stay silent instead of fabricating a verdict
    out = at.update_and_attribute(
        ids, np.array([[60.0, 60.0, 2.0]], np.float32), np.array([0]))
    assert [f["name"] for f in out[0]] == ["value"]
