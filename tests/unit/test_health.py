"""Model-health observability units (ISSUE 6).

Covers the fused on-device reducers (ops/health_tpu.py: device vs host
twin vs CPU-oracle backend parity, schema + size bound), the
HealthTracker (drift / saturation / collapse incidents with hysteresis,
quantiles, flight-dump requests), the run-epoch continuity counter, and
the <= 1% host-fold overhead gate.
"""

import json

import numpy as np
import pytest

from rtap_tpu.config import scaled_cluster_preset
from rtap_tpu.obs.health import HealthTracker, bump_run_epoch
from rtap_tpu.obs.metrics import TelemetryRegistry
from rtap_tpu.ops.health_tpu import (
    HEALTH_KEYS,
    OCC_BINS,
    PERM_BINS,
    SCORE_BINS,
    health_nbytes,
    health_reduce_host,
)
from rtap_tpu.service.registry import StreamGroup

CFG = scaled_cluster_preset(32)
G = 4
T = 6


def _data(seed=0, n=G, t=T):
    rng = np.random.Generator(np.random.Philox(key=(seed, 7)))
    vals = (30 + 5 * rng.random((t, n))).astype(np.float32)
    ts = np.tile(1_700_000_000 + np.arange(t)[:, None], (1, n)).astype(np.int64)
    return vals, ts


def _device_group(**kw):
    return StreamGroup(CFG, [f"s{i}" for i in range(G)], backend="tpu",
                       health=True, **kw)


# ---------------------------------------------------------- reducers --
@pytest.mark.quick
def test_health_leaf_schema_and_size_bound():
    grp = _device_group()
    vals, ts = _data()
    grp.run_chunk(vals, ts)
    assert sorted(grp.last_health) == sorted(HEALTH_KEYS)
    per_tick = sum(np.asarray(v[0]).nbytes for v in grp.last_health.values())
    # "a few hundred bytes per group per tick" is a schema contract, not
    # an aspiration — and the helper must agree with the real leaf
    assert per_tick == health_nbytes()
    assert per_tick < 512
    for k, v in grp.last_health.items():
        assert v.shape[0] == T, k


@pytest.mark.quick
def test_health_device_vs_host_twin_parity():
    grp = _device_group()
    vals, ts = _data()
    raw, _ll, _al = grp.run_chunk(vals, ts)
    host = health_reduce_host(
        {k: np.asarray(v) for k, v in grp.state.items()},
        raw[-1], vals[-1][:, None], CFG)
    for k in HEALTH_KEYS:
        np.testing.assert_allclose(
            np.asarray(grp.last_health[k][-1]), np.asarray(host[k]),
            rtol=1e-5, atol=1e-6, err_msg=k)


@pytest.mark.quick
def test_health_cpu_backend_matches_device():
    vals, ts = _data(seed=3)
    gd = _device_group()
    gc = StreamGroup(CFG, [f"s{i}" for i in range(G)], backend="cpu",
                     health=True)
    rd, *_ = gd.run_chunk(vals, ts)
    rc, *_ = gc.run_chunk(vals, ts)
    np.testing.assert_array_equal(rd, rc)
    for k in HEALTH_KEYS:
        np.testing.assert_allclose(
            np.asarray(gd.last_health[k]), np.asarray(gc.last_health[k]),
            rtol=1e-5, atol=1e-6, err_msg=k)


@pytest.mark.quick
def test_health_live_mask_excludes_silent_streams():
    """All-NaN (pad/silent) streams must not dilute the scorecard: a
    half-silent group reports the same occupancy/sparsity as a fully
    live one fed the same data."""
    vals, ts = _data(seed=5)
    vals = np.repeat(vals[:, :1], G, axis=1)  # identical data per stream:
    # the live-masked means must then be invariant to how many streams
    # are live
    full = _device_group()
    full.run_chunk(vals, ts)
    half = StreamGroup(CFG, [f"s{i}" for i in range(G // 2)]
                       + [f"__pad{i}" for i in range(G // 2)],
                       backend="tpu", health=True)
    hv = vals.copy()
    hv[:, G // 2:] = np.nan  # pads are fed NaN by the loop's routing
    half.run_chunk(hv, ts)
    assert int(half.last_health["scored"][-1]) == G // 2
    assert int(half.last_health["occ_hist"][-1].sum()) == G // 2
    # live streams saw identical data -> identical per-stream stats, and
    # the live-masked means must agree between the two fleets
    np.testing.assert_allclose(half.last_health["act_col_frac"],
                               full.last_health["act_col_frac"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(half.last_health["seg_occ_frac"],
                               full.last_health["seg_occ_frac"],
                               rtol=1e-5, atol=1e-6)


@pytest.mark.quick
def test_health_requires_no_mesh():
    with pytest.raises(ValueError, match="mesh"):
        StreamGroup(CFG, ["a"], backend="tpu", health=True, mesh=object())


# ----------------------------------------------------------- tracker --
def _leaf(score_bin=0, scored=8, occ=0.2, act=None, t=1):
    """Synthetic per-tick health leaves ([T, ...])."""
    act = CFG.sp.num_active_columns / CFG.sp.columns if act is None else act
    hist = np.zeros((t, SCORE_BINS), np.int32)
    hist[:, score_bin] = scored
    return {
        "occ_hist": np.tile(
            np.eye(OCC_BINS, dtype=np.int32)[
                min(OCC_BINS - 1, int(occ * OCC_BINS))], (t, 1)),
        "seg_occ_frac": np.full(t, occ, np.float32),
        "syn_frac": np.full(t, 0.1, np.float32),
        "perm_hist": np.full((t, PERM_BINS), 1.0 / PERM_BINS, np.float32),
        "perm_conn_frac": np.full(t, 0.5, np.float32),
        "act_col_frac": np.full(t, act, np.float32),
        "pred_cell_frac": np.full(t, 0.01, np.float32),
        "hit_num": np.full(t, 0.75 * scored, np.float32),
        "hit_den": np.full(t, float(scored), np.float32),
        "score_hist": hist,
        "scored": np.full(t, scored, np.int32),
    }


class _FlightStub:
    def __init__(self):
        self.events = []
        self.dumps = []
        self.health_provider = None

    def record_event(self, ev):
        self.events.append(ev)

    def request_dump(self, reason, tick):
        self.dumps.append((reason, tick))


@pytest.mark.quick
def test_tracker_score_drift_fires_once_and_requests_dump():
    events = []
    fl = _FlightStub()
    ht = HealthTracker(CFG, registry=TelemetryRegistry(),
                       sink=events.append, flight=fl,
                       drift_min_ticks=4, drift_threshold=0.3,
                       alpha_fast=0.5, alpha_slow=0.01)
    for k in range(6):
        ht.fold(0, _leaf(score_bin=1), tick=k)
    assert not any(e["event"] == "score_drift" for e in events)
    # the distribution jumps to the top bin: fast EWMA chases it, the
    # slow baseline stays put -> tvd crosses the threshold, ONCE
    for k in range(6, 12):
        ht.fold(0, _leaf(score_bin=SCORE_BINS - 1), tick=k)
    drift = [e for e in events if e["event"] == "score_drift"]
    assert len(drift) == 1 and drift[0]["group"] == 0
    assert drift[0]["tvd"] >= 0.3
    assert ("score_drift", drift[0]["tick"]) in fl.dumps
    assert ht.scorecard(0)["score"]["drifting"]
    # back to the baseline long enough -> clears, re-arms, fires again
    for k in range(12, 400):
        ht.fold(0, _leaf(score_bin=1), tick=k)
    assert not ht.scorecard(0)["score"]["drifting"]
    for k in range(400, 410):
        ht.fold(0, _leaf(score_bin=SCORE_BINS - 1), tick=k)
    assert sum(1 for e in events if e["event"] == "score_drift") == 2


@pytest.mark.quick
def test_tracker_pool_saturated_hysteresis():
    events = []
    ht = HealthTracker(CFG, registry=TelemetryRegistry(),
                       sink=events.append, occupancy_threshold=0.9)
    ht.fold(1, _leaf(occ=0.95), tick=0)
    ht.fold(1, _leaf(occ=0.96), tick=1)  # still saturated: no re-fire
    sat = [e for e in events if e["event"] == "pool_saturated"]
    assert len(sat) == 1 and sat[0]["occupancy"] == 0.95
    ht.fold(1, _leaf(occ=0.85), tick=2)  # above 0.9*thr: stays armed off
    ht.fold(1, _leaf(occ=0.95), tick=3)  # did not clear below margin
    assert sum(1 for e in events if e["event"] == "pool_saturated") == 1
    ht.fold(1, _leaf(occ=0.5), tick=4)  # clears (below 0.81)
    ht.fold(1, _leaf(occ=0.95), tick=5)
    assert sum(1 for e in events if e["event"] == "pool_saturated") == 2


@pytest.mark.quick
def test_tracker_outage_ticks_do_not_flap_saturation():
    """An all-NaN source outage zeroes every live-masked mean; adopting
    those zeros would clear the saturation edge-trigger and re-fire the
    incident (plus a postmortem dump) on every source recovery. Outage
    ticks must leave the scorecard and the condition state alone."""
    events = []
    ht = HealthTracker(CFG, registry=TelemetryRegistry(),
                       sink=events.append, occupancy_threshold=0.9)
    ht.fold(0, _leaf(occ=0.95), tick=0)
    ht.fold(0, _leaf(occ=0.0, scored=0), tick=1)  # breaker/NaN outage
    ht.fold(0, _leaf(occ=0.95), tick=2)  # recovery: no re-fire
    assert sum(1 for e in events if e["event"] == "pool_saturated") == 1
    # the scorecard kept the last real observation through the outage
    assert ht.scorecard(0)["occupancy"]["frac"] == pytest.approx(0.95)


@pytest.mark.quick
def test_tracker_sparsity_collapse_respects_warmup_and_floor():
    events = []
    ht = HealthTracker(CFG, registry=TelemetryRegistry(),
                       sink=events.append, sparsity_min_frac=0.5,
                       warmup_ticks=3)
    collapsed = 0.1 * CFG.sp.num_active_columns / CFG.sp.columns
    ht.fold(0, _leaf(act=collapsed), tick=0)  # warm-up: not judged yet
    assert not any(e["event"] == "sparsity_collapsed" for e in events)
    ht.fold(0, _leaf(act=collapsed, t=3), tick=1)
    assert sum(1 for e in events
               if e["event"] == "sparsity_collapsed") == 1
    # healthy sparsity clears the flag; a fresh collapse re-fires
    ht.fold(0, _leaf(), tick=2)
    ht.fold(0, _leaf(act=collapsed), tick=3)
    assert sum(1 for e in events
               if e["event"] == "sparsity_collapsed") == 2


@pytest.mark.quick
def test_tracker_quantiles_and_snapshot_schema():
    ht = HealthTracker(CFG, registry=TelemetryRegistry())
    ht.fold(0, _leaf(score_bin=0, t=4), tick=3)
    ht.fold(2, _leaf(score_bin=SCORE_BINS - 1, t=4), tick=3)
    snap = ht.snapshot()
    assert snap["fleet"]["groups"] == 2
    assert snap["fleet"]["verdict"] == "ok"
    assert [g["group"] for g in snap["groups"]] == [0, 2]
    g0 = snap["groups"][0]
    for section in ("occupancy", "synapses", "sparsity", "score"):
        assert section in g0
    q0 = g0["score"]["quantiles"]
    q2 = snap["groups"][1]["score"]["quantiles"]
    # all mass in the bottom vs top bin -> quantiles pinned to the bin
    assert q0["p99"] <= 1.0 / SCORE_BINS
    assert q2["p50"] >= 1.0 - 1.0 / SCORE_BINS
    assert json.dumps(snap)  # JSON-able end to end (the /health body)
    assert ht.scorecard(0)["hit_rate"] == pytest.approx(0.75)


@pytest.mark.quick
def test_tracker_rejects_bad_params():
    for kw in ({"occupancy_threshold": 0.0}, {"drift_threshold": 2.0},
               {"sparsity_min_frac": 1.0}, {"drift_min_ticks": 0},
               {"alpha_fast": 0.01, "alpha_slow": 0.5}):
        with pytest.raises(ValueError):
            HealthTracker(CFG, registry=TelemetryRegistry(), **kw)


# -------------------------------------------------- run epoch + bench --
@pytest.mark.quick
def test_bump_run_epoch_monotonic_and_corruption_tolerant(tmp_path):
    reg = TelemetryRegistry()
    beside = str(tmp_path / "alerts.jsonl")
    assert bump_run_epoch(beside, registry=reg) == 1
    assert bump_run_epoch(beside, registry=reg) == 2
    assert bump_run_epoch(beside, registry=reg) == 3
    gauges = {(i.name): i.value for i in reg.collect()}
    assert gauges["rtap_obs_run_epoch"] == 3
    # corrupt sidecar: restart the count, never raise
    (tmp_path / "alerts.jsonl.epoch").write_text("not json{")
    assert bump_run_epoch(beside, registry=reg) == 1
    # no incident stream -> nothing to be continuous with
    assert bump_run_epoch(None, registry=reg) == 0


@pytest.mark.quick
def test_health_fold_overhead_within_one_percent_of_tick_budget():
    from rtap_tpu.obs.selfbench import measure_health

    res = measure_health(n=300)
    assert res["per_tick_overhead_frac"] <= 0.01, res
    assert res["leaf_bytes_per_group_tick"] == health_nbytes()
