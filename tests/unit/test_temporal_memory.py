"""TM semantics: fixed column sequences, exact predictive sets, bursting,
segment growth/punishment/eviction (SURVEY.md §4 item 1 — the A-B-C-D vs
A-B-C-E pattern tests)."""

import numpy as np
import pytest

from rtap_tpu.config import ModelConfig, RDSEConfig, DateConfig, SPConfig, TMConfig
from rtap_tpu.models.oracle.temporal_memory import TMOracle
from rtap_tpu.models.state import init_state


def make_tm(C=16, K=4, S=4, M=8, **kw):
    tm_kw = dict(
        cells_per_column=K,
        activation_threshold=2,
        min_threshold=1,
        initial_permanence=0.55,  # connected at birth -> predicts after 1 rep
        connected_permanence=0.5,
        permanence_increment=0.1,
        permanence_decrement=0.05,
        predicted_segment_decrement=0.01,
        max_segments_per_cell=S,
        max_synapses_per_segment=M,
        new_synapse_count=4,
    )
    tm_kw.update(kw)
    cfg = ModelConfig(
        rdse=RDSEConfig(size=16, active_bits=2),
        date=DateConfig(time_of_day_width=0, time_of_day_size=0),
        sp=SPConfig(columns=C, num_active_columns=2),
        tm=TMConfig(**tm_kw),
    )
    state = init_state(cfg, seed=0)
    return TMOracle(state, cfg.tm), state


def cols(C, *idx):
    a = np.zeros(C, bool)
    a[list(idx)] = True
    return a


class TestBasics:
    def test_first_input_bursts_full_anomaly(self):
        tm, state = make_tm()
        raw = tm.compute(cols(16, 0, 1))
        assert raw == 1.0
        assert state["prev_active"][0].all() and state["prev_active"][1].all()  # burst
        assert state["prev_active"][2:].sum() == 0

    def test_burst_winner_is_fewest_segments_lowest_index(self):
        tm, state = make_tm()
        tm.compute(cols(16, 0))
        # no prior winners -> no segment grown, winner = cell 0 (all tie at 0 segs)
        assert state["prev_winner"][0, 0] and state["prev_winner"][0, 1:].sum() == 0

    def test_empty_input_zero_anomaly(self):
        tm, state = make_tm()
        assert tm.compute(np.zeros(16, bool)) == 0.0


class TestSequenceLearning:
    def test_abcd_predicts_after_one_rep(self):
        # initial_permanence 0.55 > connected 0.5: one presentation suffices
        tm, state = make_tm()
        seq = [cols(16, 0, 1), cols(16, 2, 3), cols(16, 4, 5), cols(16, 6, 7)]
        first = [tm.compute(a) for a in seq]
        assert first == [1.0, 1.0, 1.0, 1.0]
        second = [tm.compute(a) for a in seq]
        # B, C, D now predicted (A after D also learned once wrapped)
        assert second[1] == 0.0 and second[2] == 0.0 and second[3] == 0.0

    def test_abce_novel_element_full_anomaly(self):
        tm, state = make_tm()
        seq = [cols(16, 0, 1), cols(16, 2, 3), cols(16, 4, 5), cols(16, 6, 7)]
        for _ in range(3):
            for a in seq:
                tm.compute(a)
        out = [
            tm.compute(cols(16, 0, 1), learn=False),
            tm.compute(cols(16, 2, 3), learn=False),
            tm.compute(cols(16, 4, 5), learn=False),
            tm.compute(cols(16, 10, 11), learn=False),  # E
        ]
        assert out[1] == 0.0 and out[2] == 0.0
        assert out[3] == 1.0

    def test_predicted_cells_exact(self):
        # single-column steps -> only one prev-winner to connect to, so the
        # activation threshold must be 1 for the segment to ever fire
        tm, state = make_tm(activation_threshold=1)
        tm.compute(cols(16, 0))
        tm.compute(cols(16, 1))  # grows segment on (1, winner) to col-0 cells
        tm.compute(cols(16, 0))  # A again
        pred = state["active_seg"].any(-1)
        assert pred[1].sum() == 1  # exactly the winner cell of column 1 predicted
        assert pred[[0] + list(range(2, 16))].sum() == 0

    def test_half_predicted_half_anomaly(self):
        tm, state = make_tm(activation_threshold=1)
        tm.compute(cols(16, 0))
        tm.compute(cols(16, 1))
        tm.compute(cols(16, 0))
        # column 1 predicted; present columns {1, 9} -> half predicted
        raw = tm.compute(cols(16, 1, 9))
        assert raw == pytest.approx(0.5)


class TestGrowthBounds:
    def test_synapse_slots_bounded(self):
        tm, state = make_tm(M=4, new_synapse_count=16)
        for i in range(6):
            tm.compute(cols(16, i % 8, (i + 1) % 8))
        assert (state["presyn"] >= 0).sum(-1).max() <= 4

    def test_segment_slots_bounded_with_lru_eviction(self):
        tm, state = make_tm(S=2, K=1)  # 1 cell/col, 2 segments max
        # many distinct transitions into column 0 force segment churn
        for i in range(1, 12):
            tm.compute(cols(16, i % 15 + 1))
            tm.compute(cols(16, 0))
        assert (state["seg_last"][0, 0] >= 0).sum() <= 2

    def test_no_growth_without_prev_winners(self):
        tm, state = make_tm()
        tm.compute(cols(16, 3))
        assert (state["presyn"] >= 0).sum() == 0  # nothing to connect to


class TestPunishment:
    def test_predicted_inactive_column_decremented(self):
        tm, state = make_tm()
        tm.compute(cols(16, 0))
        tm.compute(cols(16, 1))
        tm.compute(cols(16, 0))  # column 1 now predicted
        seg_idx = np.nonzero(state["matching_seg"])
        perm_before = state["syn_perm"][seg_idx].copy()
        tm.compute(cols(16, 9))  # prediction fails
        perm_after = state["syn_perm"][seg_idx]
        assert (perm_after <= perm_before).all() and (perm_after < perm_before).any()

    def test_no_punishment_when_disabled(self):
        tm, state = make_tm(predicted_segment_decrement=0.0)
        tm.compute(cols(16, 0))
        tm.compute(cols(16, 1))
        tm.compute(cols(16, 0))
        before = state["syn_perm"].copy()
        tm.compute(cols(16, 9), learn=True)
        # segment perms may only have changed via death, not punishment
        assert (state["syn_perm"] >= before - 1e-9).all()


class TestDeathAndDeterminism:
    def test_synapse_death_at_zero_perm(self):
        tm, state = make_tm(initial_permanence=0.04, permanence_decrement=0.05,
                            predicted_segment_decrement=0.0, min_threshold=1,
                            activation_threshold=1, connected_permanence=0.03)
        tm.compute(cols(16, 0))
        tm.compute(cols(16, 1))  # segment born at 0.04, connected
        tm.compute(cols(16, 2))
        tm.compute(cols(16, 1))  # matching seg reinforced? presyn (col2 cells) inactive... decrement to 0 -> death
        # eventually no synapse may carry negative permanence
        assert (state["syn_perm"] >= 0).all()
        dead_slots = state["presyn"] < 0
        assert (state["syn_perm"][dead_slots] == 0).all()

    def test_learn_false_pure(self):
        tm, state = make_tm()
        tm.compute(cols(16, 0))
        tm.compute(cols(16, 1))
        snap = {k: np.copy(v) for k, v in state.items()}
        tm2 = TMOracle(state, tm.cfg)
        tm2.compute(cols(16, 5), learn=False)
        for k in ("presyn", "syn_perm", "seg_last"):
            np.testing.assert_array_equal(state[k], snap[k], err_msg=k)

    def test_learn_false_does_not_stamp_lru(self):
        # regression: inference steps that *activate* segments must not
        # refresh their LRU stamps (would perturb eviction once learning resumes)
        tm, state = make_tm(activation_threshold=1)
        tm.compute(cols(16, 0))
        tm.compute(cols(16, 1))  # segment grown on col 1
        snap_last = state["seg_last"].copy()
        tm.compute(cols(16, 0), learn=False)  # col-1 segment becomes active
        assert state["active_seg"].any()  # precondition: a segment did activate
        np.testing.assert_array_equal(state["seg_last"], snap_last)

    def test_determinism(self):
        outs = []
        for _ in range(2):
            tm, state = make_tm()
            rng = np.random.default_rng(4)
            raws = []
            for _ in range(30):
                active = np.zeros(16, bool)
                active[rng.choice(16, 2, replace=False)] = True
                raws.append(tm.compute(active))
            outs.append((raws, state["presyn"].copy(), state["syn_perm"].copy()))
        assert outs[0][0] == outs[1][0]
        np.testing.assert_array_equal(outs[0][1], outs[1][1])
        np.testing.assert_array_equal(outs[0][2], outs[1][2])
