"""rtap-lint (rtap_tpu/analysis, ISSUE 12): per-pass fixture coverage.

Every pass gets a positive (deliberately-bad snippet fails), a negative
(idiomatic-good snippet passes), and a suppressed fixture (the inline
``# rtap: allow[rule]`` comment silences exactly that rule) — mirroring
the print-gate canary discipline of test_static_checks.py, but at the
library layer (in-memory SourceFiles, no subprocess) so the whole file
stays fast. Baseline mechanics (match / why-less entry / stale entry)
are covered here too; the end-to-end gate (real repo, real baseline,
wall budget, --json artifact) lives in test_static_checks.py.
"""

import pytest

from rtap_tpu.analysis import run_analysis
from rtap_tpu.analysis.core import (
    AnalysisContext,
    Baseline,
    Finding,
    SourceFile,
)

pytestmark = pytest.mark.quick


def lint(path, code, rules=None, docs="", extra=(), baseline=None):
    """Run the analyzer over in-memory fixtures, filtered to `rules`
    (None = a full run, as the gate does it)."""
    files = [SourceFile(path, code)]
    files += [SourceFile(p, c) for p, c in extra]
    ctx = AnalysisContext(root="/__fixture__", files=files, docs_text=docs)
    return run_analysis("/__fixture__", baseline=baseline or Baseline([]),
                        rules=set(rules) if rules is not None else None,
                        ctx=ctx)


#: stubs for the MUST_BE_STRICT pin so full (rules=None) fixture runs
#: don't trip strict-coverage on the synthetic context
PIN_STUBS = tuple((p, "x = 1\n") for p in (
    "rtap_tpu/obs/latency.py", "rtap_tpu/obs/slo.py",
    "rtap_tpu/obs/metrics.py", "rtap_tpu/service/loop.py"))


def rules_of(report):
    return sorted({f.rule for f in report.findings})


# ---------------------------------------------------------------- races --
RACY = """
import threading

class Racy:
    def __init__(self):
        self.n = 0
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._run, name="rtap-t", daemon=True).start()

    def _run(self):
        self.n += 1

    def bump(self):
        with self._lock:
            self.n += 1
"""

GUARDED = RACY.replace(
    "    def _run(self):\n        self.n += 1\n",
    "    def _run(self):\n        with self._lock:\n            self.n += 1\n")


def test_race_positive_and_symbol():
    r = lint("rtap_tpu/obs/_fx.py", RACY, ["race"])
    assert [f.symbol for f in r.findings] == ["Racy.n"]
    assert not r.ok


def test_race_negative_when_both_sides_guarded():
    r = lint("rtap_tpu/obs/_fx.py", GUARDED, ["race"])
    assert r.findings == [] and r.ok


def test_race_out_of_scope_dir_ignored():
    # models/ is not serve stack — the pass only covers the strict dirs
    r = lint("rtap_tpu/models/_fx.py", RACY, ["race"])
    assert r.findings == []


def test_race_suppression_comment():
    code = RACY.replace(
        "        self.n += 1\n\n    def bump",
        "        self.n += 1  # rtap: allow[race] — test tolerance\n\n"
        "    def bump")
    r = lint("rtap_tpu/obs/_fx.py", code, ["race"])
    assert r.findings == [] and len(r.suppressed) == 1


def test_race_interprocedural_guard_inheritance():
    """A private method whose EVERY call site (both sides) holds the
    lock inherits the guard — the BinaryBatchSource._apply idiom."""
    code = """
import threading

class C:
    def __init__(self):
        self.n = 0
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._run, name="rtap-t").start()

    def _run(self):
        with self._lock:
            self._bump()

    def public(self):
        with self._lock:
            self._bump()

    def _bump(self):
        self.n += 1
"""
    r = lint("rtap_tpu/ingest/_fx.py", code, ["race"])
    assert r.findings == []
    # ... but one unlocked call path from either side breaks the
    # inheritance (intersection over paths, not union)
    leaky = code.replace(
        "    def public(self):\n        with self._lock:\n"
        "            self._bump()\n",
        "    def public(self):\n        self._bump()\n")
    r2 = lint("rtap_tpu/ingest/_fx.py", leaky, ["race"])
    assert [f.symbol for f in r2.findings] == ["C.n"]


def test_race_nested_thread_target_function():
    """The Lease.start_heartbeat idiom: a nested function handed to
    Thread(target=...) is thread-side code."""
    code = """
import threading

class C:
    def __init__(self):
        self.state = 0
        self._lock = threading.Lock()

    def go(self):
        def _beat():
            self.state = 1
        threading.Thread(target=_beat, name="rtap-t").start()

    def poke(self):
        self.state = 2
"""
    r = lint("rtap_tpu/resilience/_fx.py", code, ["race"])
    assert [f.symbol for f in r.findings] == ["C.state"]


def test_race_request_handler_self_concurrency():
    """A nested RequestHandler class runs one thread PER CONNECTION:
    an unguarded write to an outer attr races with ITSELF — the
    TcpJsonlSource._py_parse_errors lost-update class."""
    code = """
import socketserver
import threading

class Src:
    def __init__(self):
        self.errors = 0
        self._lock = threading.Lock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                outer.errors += 1
"""
    r = lint("rtap_tpu/service/_fx.py", code, ["race"])
    assert [f.symbol for f in r.findings] == ["Src.errors"]
    guarded = code.replace(
        "                outer.errors += 1",
        "                with outer._lock:\n"
        "                    outer.errors += 1")
    assert lint("rtap_tpu/service/_fx.py", guarded, ["race"]).findings == []


def test_race_init_writes_are_construction_time():
    """__init__ runs before any thread exists: a thread-side writer plus
    only-__init__ main writes is single-writer, not a race."""
    code = """
import threading

class C:
    def __init__(self):
        self.n = 0

    def start(self):
        threading.Thread(target=self._run, name="rtap-t").start()

    def _run(self):
        self.n += 1
"""
    r = lint("rtap_tpu/obs/_fx.py", code, ["race"])
    assert r.findings == []


def test_thread_name_rule():
    anon = ("import threading\n"
            "t = threading.Thread(target=print, daemon=True)\n")
    r = lint("rtap_tpu/obs/_fx.py", anon, ["thread-name"])
    assert rules_of(r) == ["thread-name"]
    named = anon.replace("daemon=True", 'daemon=True, name="rtap-x-y"')
    assert lint("rtap_tpu/obs/_fx.py", named, ["thread-name"]).findings == []
    offform = anon.replace("daemon=True", 'daemon=True, name="worker"')
    assert len(lint("rtap_tpu/obs/_fx.py", offform,
                    ["thread-name"]).findings) == 1
    # out of the serve stack: utils/ threads are not gated
    assert lint("rtap_tpu/utils/_fx.py", anon, ["thread-name"]).findings == []


# --------------------------------------------------------------- purity --
def test_purity_nondet_in_ops():
    code = "import time\n\ndef kernel(x):\n    return x + time.time()\n"
    r = lint("rtap_tpu/ops/_fx.py", code, ["purity-nondet"])
    assert rules_of(r) == ["purity-nondet"]
    # the loop module may read the wall clock (it IS the pacer)...
    assert lint("rtap_tpu/service/loop.py", code,
                ["purity-nondet"]).findings == []
    # ...but never mint randomness mid-path
    rnd = "import random\n\ndef f():\n    return random.random()\n"
    assert len(lint("rtap_tpu/service/loop.py", rnd,
                    ["purity-nondet"]).findings) == 1
    # keyed jax.random is deterministic and exempt everywhere
    jr = "import jax\n\ndef f(k):\n    return jax.random.uniform(k)\n"
    assert lint("rtap_tpu/ops/_fx.py", jr, ["purity-nondet"]).findings == []


def test_purity_fetch_only_in_tracing_functions():
    fetch = ("import numpy as np\nimport jax.numpy as jnp\n\n"
             "def kernel(x):\n    y = jnp.sum(x)\n"
             "    return np.asarray(y)\n")
    r = lint("rtap_tpu/ops/_fx.py", fetch, ["purity-fetch"])
    assert rules_of(r) == ["purity-fetch"]
    item = ("import jax.numpy as jnp\n\n"
            "def kernel(x):\n    return jnp.sum(x).item()\n")
    assert len(lint("rtap_tpu/ops/_fx.py", item,
                    ["purity-fetch"]).findings) == 1
    # a pure-numpy host twin is out of the rule by construction
    twin = ("import numpy as np\n\n"
            "def host_twin(x):\n    return np.asarray(x).sum()\n")
    assert lint("rtap_tpu/ops/_fx.py", twin, ["purity-fetch"]).findings == []


def test_purity_isfinite_presence_contract():
    code = ("import numpy as np\n\n"
            "def merge(vec):\n    return vec[np.isfinite(vec)]\n")
    r = lint("rtap_tpu/ingest/_fx.py", code, ["purity-isfinite"])
    assert rules_of(r) == ["purity-isfinite"]
    # model-layer encoders keep their deliberate isfinite semantics
    assert lint("rtap_tpu/ops/_fx.py", code,
                ["purity-isfinite"]).findings == []
    ok = code.replace("np.isfinite(vec)", "~np.isnan(vec)")
    assert lint("rtap_tpu/ingest/_fx.py", ok,
                ["purity-isfinite"]).findings == []
    supp = code.replace(
        "np.isfinite(vec)]",
        "np.isfinite(vec)]  # rtap: allow[purity-isfinite] — fixture")
    r3 = lint("rtap_tpu/ingest/_fx.py", supp, ["purity-isfinite"])
    assert r3.findings == [] and len(r3.suppressed) == 1


# -------------------------------------------------------------- excepts --
def test_except_silent_positive_negative_suppressed():
    bad = ("def f(path):\n    try:\n        load(path)\n"
           "    except Exception:\n        pass\n")
    r = lint("rtap_tpu/service/_fx.py", bad, ["except-silent"])
    assert rules_of(r) == ["except-silent"]
    assert "f:except Exception" in r.findings[0].symbol
    # binding an outcome is handling
    ok = bad.replace("        pass\n", "        result = None\n")
    assert lint("rtap_tpu/service/_fx.py", ok,
                ["except-silent"]).findings == []
    # the cleanup carve-out: single teardown call + OSError family
    cleanup = ("def f(sock):\n    try:\n        sock.close()\n"
               "    except OSError:\n        pass\n")
    assert lint("rtap_tpu/service/_fx.py", cleanup,
                ["except-silent"]).findings == []
    # ... but a broad catch does NOT get the carve-out
    broad = cleanup.replace("except OSError", "except Exception")
    assert len(lint("rtap_tpu/service/_fx.py", broad,
                    ["except-silent"]).findings) == 1
    supp = bad.replace("    except Exception:",
                       "    except Exception:  # rtap: allow[except-silent]")
    r2 = lint("rtap_tpu/service/_fx.py", supp, ["except-silent"])
    assert r2.findings == [] and len(r2.suppressed) == 1
    # out of the serve stack: no rule
    assert lint("rtap_tpu/models/_fx.py", bad,
                ["except-silent"]).findings == []


# ---------------------------------------------------------------- flags --
_MAIN_FIXTURE = """
import argparse

def build():
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers()
    p = sub.add_parser("serve")
    p.add_argument("--documented-flag")
    p.add_argument("--ghost-flag")
    p = sub.add_parser("replay")
    p.add_argument("--replay-only-flag")
"""


def test_flag_docs_drift():
    r = lint("rtap_tpu/__main__.py", _MAIN_FIXTURE, ["flag-docs"],
             docs="serve takes `--documented-flag` (see runbook)")
    assert [f.symbol for f in r.findings] == ["--ghost-flag"]
    # flags of OTHER subcommands are out of this gate's scope
    assert all("--replay-only-flag" != f.symbol for f in r.findings)
    r2 = lint("rtap_tpu/__main__.py", _MAIN_FIXTURE, ["flag-docs"],
              docs="`--documented-flag` and `--ghost-flag`")
    assert r2.findings == []


def test_flag_docs_prefix_is_not_documentation():
    """Word-boundary matching: a documented `--ghost-flag-extra` must
    NOT satisfy the gate for an undocumented `--ghost-flag` (the serve
    surface has ~11 such prefix pairs — the masking this gate exists
    to catch)."""
    r = lint("rtap_tpu/__main__.py", _MAIN_FIXTURE, ["flag-docs"],
             docs="`--documented-flag`; also `--ghost-flag-extra` exists")
    assert [f.symbol for f in r.findings] == ["--ghost-flag"]


# --------------------------------------------------------------- prints --
def test_print_rules_and_non_suppressibility():
    strict = 'import sys\nprint("x", file=sys.stderr)\n'
    r = lint("rtap_tpu/service/_fx.py", strict, ["print-strict"])
    assert rules_of(r) == ["print-strict"]
    # an allow comment must NOT silence the print gate (guard the guard)
    supp = strict.replace(")\n", ")  # rtap: allow[print-strict]\n")
    r2 = lint("rtap_tpu/service/_fx.py", supp, ["print-strict"])
    assert rules_of(r2) == ["print-strict"]
    # outside the serve stack: file= and single-json.dumps are legal,
    # bare stdout is not
    outside = ('import json, sys\nprint("d", file=sys.stderr)\n'
               'print(json.dumps({"a": 1}))\nprint("bare")\n')
    r3 = lint("rtap_tpu/eval/_fx.py", outside, ["print-bare"])
    assert len(r3.findings) == 1 and r3.findings[0].line == 4


def test_strict_coverage_pin():
    # a context missing the pinned modules reports each as out of
    # coverage — the rename/move tripwire
    r = lint("rtap_tpu/eval/_fx.py", "x = 1\n", ["strict-coverage"])
    assert len(r.findings) == 4
    assert all(f.rule == "strict-coverage" for f in r.findings)


# ------------------------------------------------------------- baseline --
def test_baseline_match_whyless_and_stale():
    bad = ("def f(p):\n    try:\n        load(p)\n"
           "    except Exception:\n        pass\n")
    ent = {"rule": "except-silent", "path": "rtap_tpu/service/_fx.py",
           "symbol": "f:except Exception", "why": "fixture legacy"}
    r = lint("rtap_tpu/service/_fx.py", bad, ["except-silent"],
             baseline=Baseline([ent]))
    assert r.ok and len(r.baselined) == 1 and r.stale_baseline == []
    # a why-less entry is itself a gate failure
    whyless = {k: v for k, v in ent.items() if k != "why"}
    r2 = lint("rtap_tpu/service/_fx.py", bad, ["except-silent"],
              baseline=Baseline([whyless]))
    assert not r2.ok and r2.baseline_errors
    # the finding the why-less entry failed to cover is a real finding
    assert len(r2.findings) == 1


def test_baseline_stale_entry_is_nonfatal():
    # staleness is only judged on a FULL run (rules=None), so the
    # fixture context carries the strict-pin stubs
    bad = ("def f(p):\n    try:\n        load(p)\n"
           "    except Exception:\n        pass\n")
    ent = {"rule": "except-silent", "path": "rtap_tpu/service/_fx.py",
           "symbol": "f:except Exception", "why": "fixture legacy"}
    stale = dict(ent, symbol="gone:except OSError")
    r = lint("rtap_tpu/service/_fx.py", bad, extra=PIN_STUBS,
             baseline=Baseline([ent, stale]))
    assert r.ok and len(r.stale_baseline) == 1
    assert r.stale_baseline[0]["symbol"] == "gone:except OSError"


def test_rules_subset_never_reports_stale_baseline():
    """A --rules subset run skips the baseline for unselected rules, so
    their (valid) entries must NOT be advised stale — only a full run
    can judge staleness."""
    bad = ("def f(p):\n    try:\n        load(p)\n"
           "    except Exception:\n        pass\n")
    ent = {"rule": "except-silent", "path": "rtap_tpu/service/_fx.py",
           "symbol": "f:except Exception", "why": "fixture legacy"}
    r = lint("rtap_tpu/service/_fx.py", bad, ["race"],
             baseline=Baseline([ent]))
    assert r.ok and r.stale_baseline == []


def test_finding_json_shape():
    f = Finding(rule="race", path="a.py", line=3, symbol="C.x",
                message="m")
    d = f.to_dict()
    assert d == {"rule": "race", "path": "a.py", "line": 3,
                 "symbol": "C.x", "message": "m"}


def test_parse_error_is_a_finding():
    r = lint("rtap_tpu/service/_fx.py", "def broken(:\n", ["parse-error"])
    assert rules_of(r) == ["parse-error"]
