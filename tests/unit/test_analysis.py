"""rtap-lint (rtap_tpu/analysis, ISSUE 12): per-pass fixture coverage.

Every pass gets a positive (deliberately-bad snippet fails), a negative
(idiomatic-good snippet passes), and a suppressed fixture (the inline
``# rtap: allow[rule]`` comment silences exactly that rule) — mirroring
the print-gate canary discipline of test_static_checks.py, but at the
library layer (in-memory SourceFiles, no subprocess) so the whole file
stays fast. Baseline mechanics (match / why-less entry / stale entry)
are covered here too; the end-to-end gate (real repo, real baseline,
wall budget, --json artifact) lives in test_static_checks.py.
"""

import pytest

from rtap_tpu.analysis import run_analysis
from rtap_tpu.analysis.core import (
    AnalysisContext,
    Baseline,
    Finding,
    SourceFile,
)

pytestmark = pytest.mark.quick


def lint(path, code, rules=None, docs="", extra=(), baseline=None):
    """Run the analyzer over in-memory fixtures, filtered to `rules`
    (None = a full run, as the gate does it)."""
    files = [SourceFile(path, code)]
    files += [SourceFile(p, c) for p, c in extra]
    ctx = AnalysisContext(root="/__fixture__", files=files, docs_text=docs)
    return run_analysis("/__fixture__", baseline=baseline or Baseline([]),
                        rules=set(rules) if rules is not None else None,
                        ctx=ctx)


#: stubs for the MUST_BE_STRICT pin so full (rules=None) fixture runs
#: don't trip strict-coverage on the synthetic context
PIN_STUBS = tuple((p, "x = 1\n") for p in (
    "rtap_tpu/obs/latency.py", "rtap_tpu/obs/slo.py",
    "rtap_tpu/obs/metrics.py", "rtap_tpu/service/loop.py"))


def rules_of(report):
    return sorted({f.rule for f in report.findings})


# ---------------------------------------------------------------- races --
RACY = """
import threading

class Racy:
    def __init__(self):
        self.n = 0
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._run, name="rtap-t", daemon=True).start()

    def _run(self):
        self.n += 1

    def bump(self):
        with self._lock:
            self.n += 1
"""

GUARDED = RACY.replace(
    "    def _run(self):\n        self.n += 1\n",
    "    def _run(self):\n        with self._lock:\n            self.n += 1\n")


def test_race_positive_and_symbol():
    r = lint("rtap_tpu/obs/_fx.py", RACY, ["race"])
    assert [f.symbol for f in r.findings] == ["Racy.n"]
    assert not r.ok


def test_race_negative_when_both_sides_guarded():
    r = lint("rtap_tpu/obs/_fx.py", GUARDED, ["race"])
    assert r.findings == [] and r.ok


def test_race_out_of_scope_dir_ignored():
    # models/ is not serve stack — the pass only covers the strict dirs
    r = lint("rtap_tpu/models/_fx.py", RACY, ["race"])
    assert r.findings == []


def test_race_suppression_comment():
    code = RACY.replace(
        "        self.n += 1\n\n    def bump",
        "        self.n += 1  # rtap: allow[race] — test tolerance\n\n"
        "    def bump")
    r = lint("rtap_tpu/obs/_fx.py", code, ["race"])
    assert r.findings == [] and len(r.suppressed) == 1


def test_race_interprocedural_guard_inheritance():
    """A private method whose EVERY call site (both sides) holds the
    lock inherits the guard — the BinaryBatchSource._apply idiom."""
    code = """
import threading

class C:
    def __init__(self):
        self.n = 0
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._run, name="rtap-t").start()

    def _run(self):
        with self._lock:
            self._bump()

    def public(self):
        with self._lock:
            self._bump()

    def _bump(self):
        self.n += 1
"""
    r = lint("rtap_tpu/ingest/_fx.py", code, ["race"])
    assert r.findings == []
    # ... but one unlocked call path from either side breaks the
    # inheritance (intersection over paths, not union)
    leaky = code.replace(
        "    def public(self):\n        with self._lock:\n"
        "            self._bump()\n",
        "    def public(self):\n        self._bump()\n")
    r2 = lint("rtap_tpu/ingest/_fx.py", leaky, ["race"])
    assert [f.symbol for f in r2.findings] == ["C.n"]


def test_race_nested_thread_target_function():
    """The Lease.start_heartbeat idiom: a nested function handed to
    Thread(target=...) is thread-side code."""
    code = """
import threading

class C:
    def __init__(self):
        self.state = 0
        self._lock = threading.Lock()

    def go(self):
        def _beat():
            self.state = 1
        threading.Thread(target=_beat, name="rtap-t").start()

    def poke(self):
        self.state = 2
"""
    r = lint("rtap_tpu/resilience/_fx.py", code, ["race"])
    assert [f.symbol for f in r.findings] == ["C.state"]


def test_race_request_handler_self_concurrency():
    """A nested RequestHandler class runs one thread PER CONNECTION:
    an unguarded write to an outer attr races with ITSELF — the
    TcpJsonlSource._py_parse_errors lost-update class."""
    code = """
import socketserver
import threading

class Src:
    def __init__(self):
        self.errors = 0
        self._lock = threading.Lock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                outer.errors += 1
"""
    r = lint("rtap_tpu/service/_fx.py", code, ["race"])
    assert [f.symbol for f in r.findings] == ["Src.errors"]
    guarded = code.replace(
        "                outer.errors += 1",
        "                with outer._lock:\n"
        "                    outer.errors += 1")
    assert lint("rtap_tpu/service/_fx.py", guarded, ["race"]).findings == []


def test_race_init_writes_are_construction_time():
    """__init__ runs before any thread exists: a thread-side writer plus
    only-__init__ main writes is single-writer, not a race."""
    code = """
import threading

class C:
    def __init__(self):
        self.n = 0

    def start(self):
        threading.Thread(target=self._run, name="rtap-t").start()

    def _run(self):
        self.n += 1
"""
    r = lint("rtap_tpu/obs/_fx.py", code, ["race"])
    assert r.findings == []


def test_thread_name_rule():
    anon = ("import threading\n"
            "t = threading.Thread(target=print, daemon=True)\n")
    r = lint("rtap_tpu/obs/_fx.py", anon, ["thread-name"])
    assert rules_of(r) == ["thread-name"]
    named = anon.replace("daemon=True", 'daemon=True, name="rtap-x-y"')
    assert lint("rtap_tpu/obs/_fx.py", named, ["thread-name"]).findings == []
    offform = anon.replace("daemon=True", 'daemon=True, name="worker"')
    assert len(lint("rtap_tpu/obs/_fx.py", offform,
                    ["thread-name"]).findings) == 1
    # out of the serve stack: utils/ threads are not gated
    assert lint("rtap_tpu/utils/_fx.py", anon, ["thread-name"]).findings == []


# --------------------------------------------------------------- purity --
def test_purity_nondet_in_ops():
    code = "import time\n\ndef kernel(x):\n    return x + time.time()\n"
    r = lint("rtap_tpu/ops/_fx.py", code, ["purity-nondet"])
    assert rules_of(r) == ["purity-nondet"]
    # the loop module may read the wall clock (it IS the pacer)...
    assert lint("rtap_tpu/service/loop.py", code,
                ["purity-nondet"]).findings == []
    # ...but never mint randomness mid-path
    rnd = "import random\n\ndef f():\n    return random.random()\n"
    assert len(lint("rtap_tpu/service/loop.py", rnd,
                    ["purity-nondet"]).findings) == 1
    # keyed jax.random is deterministic and exempt everywhere
    jr = "import jax\n\ndef f(k):\n    return jax.random.uniform(k)\n"
    assert lint("rtap_tpu/ops/_fx.py", jr, ["purity-nondet"]).findings == []


def test_purity_fetch_only_in_tracing_functions():
    fetch = ("import numpy as np\nimport jax.numpy as jnp\n\n"
             "def kernel(x):\n    y = jnp.sum(x)\n"
             "    return np.asarray(y)\n")
    r = lint("rtap_tpu/ops/_fx.py", fetch, ["purity-fetch"])
    assert rules_of(r) == ["purity-fetch"]
    item = ("import jax.numpy as jnp\n\n"
            "def kernel(x):\n    return jnp.sum(x).item()\n")
    assert len(lint("rtap_tpu/ops/_fx.py", item,
                    ["purity-fetch"]).findings) == 1
    # a pure-numpy host twin is out of the rule by construction
    twin = ("import numpy as np\n\n"
            "def host_twin(x):\n    return np.asarray(x).sum()\n")
    assert lint("rtap_tpu/ops/_fx.py", twin, ["purity-fetch"]).findings == []


def test_purity_isfinite_presence_contract():
    code = ("import numpy as np\n\n"
            "def merge(vec):\n    return vec[np.isfinite(vec)]\n")
    r = lint("rtap_tpu/ingest/_fx.py", code, ["purity-isfinite"])
    assert rules_of(r) == ["purity-isfinite"]
    # model-layer encoders keep their deliberate isfinite semantics
    assert lint("rtap_tpu/ops/_fx.py", code,
                ["purity-isfinite"]).findings == []
    ok = code.replace("np.isfinite(vec)", "~np.isnan(vec)")
    assert lint("rtap_tpu/ingest/_fx.py", ok,
                ["purity-isfinite"]).findings == []
    supp = code.replace(
        "np.isfinite(vec)]",
        "np.isfinite(vec)]  # rtap: allow[purity-isfinite] — fixture")
    r3 = lint("rtap_tpu/ingest/_fx.py", supp, ["purity-isfinite"])
    assert r3.findings == [] and len(r3.suppressed) == 1


# -------------------------------------------------------------- excepts --
def test_except_silent_positive_negative_suppressed():
    bad = ("def f(path):\n    try:\n        load(path)\n"
           "    except Exception:\n        pass\n")
    r = lint("rtap_tpu/service/_fx.py", bad, ["except-silent"])
    assert rules_of(r) == ["except-silent"]
    assert "f:except Exception" in r.findings[0].symbol
    # binding an outcome is handling
    ok = bad.replace("        pass\n", "        result = None\n")
    assert lint("rtap_tpu/service/_fx.py", ok,
                ["except-silent"]).findings == []
    # the cleanup carve-out: single teardown call + OSError family
    cleanup = ("def f(sock):\n    try:\n        sock.close()\n"
               "    except OSError:\n        pass\n")
    assert lint("rtap_tpu/service/_fx.py", cleanup,
                ["except-silent"]).findings == []
    # ... but a broad catch does NOT get the carve-out
    broad = cleanup.replace("except OSError", "except Exception")
    assert len(lint("rtap_tpu/service/_fx.py", broad,
                    ["except-silent"]).findings) == 1
    supp = bad.replace("    except Exception:",
                       "    except Exception:  # rtap: allow[except-silent]")
    r2 = lint("rtap_tpu/service/_fx.py", supp, ["except-silent"])
    assert r2.findings == [] and len(r2.suppressed) == 1
    # out of the serve stack: no rule
    assert lint("rtap_tpu/models/_fx.py", bad,
                ["except-silent"]).findings == []


# ---------------------------------------------------------------- flags --
_MAIN_FIXTURE = """
import argparse

def build():
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers()
    p = sub.add_parser("serve")
    p.add_argument("--documented-flag")
    p.add_argument("--ghost-flag")
    p = sub.add_parser("replay")
    p.add_argument("--replay-only-flag")
"""


def test_flag_docs_drift():
    r = lint("rtap_tpu/__main__.py", _MAIN_FIXTURE, ["flag-docs"],
             docs="serve takes `--documented-flag` (see runbook)")
    assert [f.symbol for f in r.findings] == ["--ghost-flag"]
    # flags of OTHER subcommands are out of this gate's scope
    assert all("--replay-only-flag" != f.symbol for f in r.findings)
    r2 = lint("rtap_tpu/__main__.py", _MAIN_FIXTURE, ["flag-docs"],
              docs="`--documented-flag` and `--ghost-flag`")
    assert r2.findings == []


def test_flag_docs_prefix_is_not_documentation():
    """Word-boundary matching: a documented `--ghost-flag-extra` must
    NOT satisfy the gate for an undocumented `--ghost-flag` (the serve
    surface has ~11 such prefix pairs — the masking this gate exists
    to catch)."""
    r = lint("rtap_tpu/__main__.py", _MAIN_FIXTURE, ["flag-docs"],
             docs="`--documented-flag`; also `--ghost-flag-extra` exists")
    assert [f.symbol for f in r.findings] == ["--ghost-flag"]


# --------------------------------------------------------------- prints --
def test_print_rules_and_non_suppressibility():
    strict = 'import sys\nprint("x", file=sys.stderr)\n'
    r = lint("rtap_tpu/service/_fx.py", strict, ["print-strict"])
    assert rules_of(r) == ["print-strict"]
    # an allow comment must NOT silence the print gate (guard the guard)
    supp = strict.replace(")\n", ")  # rtap: allow[print-strict]\n")
    r2 = lint("rtap_tpu/service/_fx.py", supp, ["print-strict"])
    assert rules_of(r2) == ["print-strict"]
    # outside the serve stack: file= and single-json.dumps are legal,
    # bare stdout is not
    outside = ('import json, sys\nprint("d", file=sys.stderr)\n'
               'print(json.dumps({"a": 1}))\nprint("bare")\n')
    r3 = lint("rtap_tpu/eval/_fx.py", outside, ["print-bare"])
    assert len(r3.findings) == 1 and r3.findings[0].line == 4


def test_strict_coverage_pin():
    # a context missing the pinned modules reports each as out of
    # coverage — the rename/move tripwire
    r = lint("rtap_tpu/eval/_fx.py", "x = 1\n", ["strict-coverage"])
    assert len(r.findings) == 4
    assert all(f.rule == "strict-coverage" for f in r.findings)


# ------------------------------------------------------------- baseline --
def test_baseline_match_whyless_and_stale():
    bad = ("def f(p):\n    try:\n        load(p)\n"
           "    except Exception:\n        pass\n")
    ent = {"rule": "except-silent", "path": "rtap_tpu/service/_fx.py",
           "symbol": "f:except Exception", "why": "fixture legacy"}
    r = lint("rtap_tpu/service/_fx.py", bad, ["except-silent"],
             baseline=Baseline([ent]))
    assert r.ok and len(r.baselined) == 1 and r.stale_baseline == []
    # a why-less entry is itself a gate failure
    whyless = {k: v for k, v in ent.items() if k != "why"}
    r2 = lint("rtap_tpu/service/_fx.py", bad, ["except-silent"],
              baseline=Baseline([whyless]))
    assert not r2.ok and r2.baseline_errors
    # the finding the why-less entry failed to cover is a real finding
    assert len(r2.findings) == 1


def test_baseline_stale_entry_is_nonfatal():
    # staleness is only judged on a FULL run (rules=None), so the
    # fixture context carries the strict-pin stubs
    bad = ("def f(p):\n    try:\n        load(p)\n"
           "    except Exception:\n        pass\n")
    ent = {"rule": "except-silent", "path": "rtap_tpu/service/_fx.py",
           "symbol": "f:except Exception", "why": "fixture legacy"}
    stale = dict(ent, symbol="gone:except OSError")
    r = lint("rtap_tpu/service/_fx.py", bad, extra=PIN_STUBS,
             baseline=Baseline([ent, stale]))
    assert r.ok and len(r.stale_baseline) == 1
    assert r.stale_baseline[0]["symbol"] == "gone:except OSError"


def test_rules_subset_never_reports_stale_baseline():
    """A --rules subset run skips the baseline for unselected rules, so
    their (valid) entries must NOT be advised stale — only a full run
    can judge staleness."""
    bad = ("def f(p):\n    try:\n        load(p)\n"
           "    except Exception:\n        pass\n")
    ent = {"rule": "except-silent", "path": "rtap_tpu/service/_fx.py",
           "symbol": "f:except Exception", "why": "fixture legacy"}
    r = lint("rtap_tpu/service/_fx.py", bad, ["race"],
             baseline=Baseline([ent]))
    assert r.ok and r.stale_baseline == []


def test_finding_json_shape():
    f = Finding(rule="race", path="a.py", line=3, symbol="C.x",
                message="m")
    d = f.to_dict()
    assert d == {"rule": "race", "path": "a.py", "line": 3,
                 "symbol": "C.x", "message": "m"}


def test_parse_error_is_a_finding():
    r = lint("rtap_tpu/service/_fx.py", "def broken(:\n", ["parse-error"])
    assert rules_of(r) == ["parse-error"]


# ----------------------------------------------------------- lock-order --
LOCK_CYCLE = """
import threading

class C:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def one(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def two(self):
        with self._b_lock:
            with self._a_lock:
                pass
"""


def test_lock_order_cycle_positive_and_canonical_symbol():
    r = lint("rtap_tpu/resilience/_fx.py", LOCK_CYCLE, ["lock-order"])
    assert [f.symbol for f in r.findings] == \
        ["C._a_lock->C._b_lock->C._a_lock"]
    assert not r.ok


def test_lock_order_consistent_nesting_is_clean():
    ordered = LOCK_CYCLE.replace(
        "    def two(self):\n        with self._b_lock:\n"
        "            with self._a_lock:\n",
        "    def two(self):\n        with self._a_lock:\n"
        "            with self._b_lock:\n")
    r = lint("rtap_tpu/resilience/_fx.py", ordered, ["lock-order"])
    assert r.findings == [] and r.ok


def test_lock_order_interprocedural_cycle_through_call():
    """One side nests lexically, the other reaches the reverse order
    through a method call — the acquisition-closure worklist must see
    through the call."""
    code = """
import threading

class C:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def one(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def two(self):
        with self._b_lock:
            self._grab_a()

    def _grab_a(self):
        with self._a_lock:
            pass
"""
    r = lint("rtap_tpu/ingest/_fx.py", code, ["lock-order"])
    assert [f.symbol for f in r.findings] == \
        ["C._a_lock->C._b_lock->C._a_lock"]


def test_lock_order_cross_class_cycle_via_collaborators():
    """The whole-program shape: A holds its lock and calls into B,
    B holds its lock and calls back into A — no single class shows a
    cycle, only the global graph does (constructor-injection typing)."""
    code = """
import threading

class A:
    def __init__(self, b: "B"):
        self._a_lock = threading.Lock()
        self.b = b

    def m(self):
        with self._a_lock:
            self.b.push()

    def poke(self):
        with self._a_lock:
            pass

class B:
    def __init__(self, a: "A"):
        self._b_lock = threading.Lock()
        self.a = a

    def push(self):
        with self._b_lock:
            self.a.poke()
"""
    r = lint("rtap_tpu/obs/_fx.py", code, ["lock-order"])
    # TWO distinct deadlocks live here: the A->B->A ordering cycle
    # (two threads entering from different edges), and the
    # single-thread self-deadlock (A.m's call reaches A.poke, which
    # re-acquires the non-reentrant lock A.m already holds)
    assert sorted(f.symbol for f in r.findings) == \
        ["A._a_lock->A._a_lock", "A._a_lock->B._b_lock->A._a_lock"]
    # ... and breaking one direction (B no longer calls back) is clean
    oneway = code.replace("            self.a.poke()\n",
                          "            pass\n")
    assert lint("rtap_tpu/obs/_fx.py", oneway, ["lock-order"]).findings == []


def test_lock_order_nonreentrant_self_deadlock():
    """Re-acquiring a plain threading.Lock on a path that already holds
    it — the Lease.read-inside-refresh near-miss (PR 8)."""
    code = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            self._inner()

    def _inner(self):
        with self._lock:
            pass
"""
    r = lint("rtap_tpu/resilience/_fx.py", code, ["lock-order"])
    assert [f.symbol for f in r.findings] == ["C._lock->C._lock"]
    # an RLock makes the same nesting legal
    rl = code.replace("threading.Lock()", "threading.RLock()")
    assert lint("rtap_tpu/resilience/_fx.py", rl,
                ["lock-order"]).findings == []


def test_lock_order_self_deadlock_via_collaborator_roundtrip():
    """A holds its plain Lock and calls into B, which calls straight
    back into A re-acquiring the same lock: the re-acquisition is
    reached through a collaborator, so reentrancy must be judged by
    the lock's OWNING class, not the callee."""
    code = """
import threading

class A:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b

    def m(self):
        with self._lock:
            self.b.push()

    def poke(self):
        with self._lock:
            pass

class B:
    def __init__(self, a: "A"):
        self.a = a

    def push(self):
        self.a.poke()
"""
    r = lint("rtap_tpu/obs/_fx.py", code, ["lock-order"])
    assert [f.symbol for f in r.findings] == ["A._lock->A._lock"]
    # with an RLock the round-trip is legal
    rl = code.replace("threading.Lock()", "threading.RLock()")
    assert lint("rtap_tpu/obs/_fx.py", rl, ["lock-order"]).findings == []


def test_lock_order_explicit_acquire_extends_held_set():
    """self.<lock>.acquire() must contribute ordering edges exactly
    like the with-form: explicit acquire/release code (conditional
    locking) must not bypass the deadlock gate."""
    code = """
import threading

class C:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def one(self):
        self._a_lock.acquire()
        with self._b_lock:
            pass
        self._a_lock.release()

    def two(self):
        with self._b_lock:
            with self._a_lock:
                pass
"""
    r = lint("rtap_tpu/resilience/_fx.py", code, ["lock-order"])
    assert [f.symbol for f in r.findings] == \
        ["C._a_lock->C._b_lock->C._a_lock"]
    # release before the nested acquisition breaks the edge (and the
    # cycle): the held-set tracking honors release, not just acquire
    released = code.replace(
        "        self._a_lock.acquire()\n        with self._b_lock:\n"
        "            pass\n        self._a_lock.release()\n",
        "        self._a_lock.acquire()\n        self._a_lock.release()\n"
        "        with self._b_lock:\n            pass\n")
    assert lint("rtap_tpu/resilience/_fx.py", released,
                ["lock-order"]).findings == []


def test_lock_order_suppression_comment():
    # the cycle finding anchors on the FIRST in-cycle acquisition site
    # (smallest path/line) — that is where the suppression must sit
    supp = LOCK_CYCLE.replace(
        "        with self._a_lock:\n            with self._b_lock:",
        "        with self._a_lock:\n"
        "            # rtap: allow[lock-order] — fixture\n"
        "            with self._b_lock:")
    r = lint("rtap_tpu/resilience/_fx.py", supp, ["lock-order"])
    assert r.findings == [] and len(r.suppressed) == 1


# ---------------------------------------------------------- cross-share --
_TRACKER = """
import threading

class Tracker:
    def __init__(self):
        self.n = 0
        self.samples = {}
        self._lock = threading.Lock()

    def fold(self, k):
        self.samples[k] = self.samples.get(k, 0) + 1
        self.n += 1

    def snapshot(self):
        return dict(self.samples), self.n

class Runner:
    def __init__(self, tracker):
        self.tracker = tracker

    def start(self):
        threading.Thread(target=self._run, name="rtap-t",
                         daemon=True).start()

    def _run(self):
        pass
"""

_WIRE = """
def wire():
    t = Tracker()
    r = Runner(t)
    consume(t)
    return r
"""


def cross_lint(tracker_code, wire_code=_WIRE):
    """Two-module fixture: the tracker lives in obs/, the wiring (and
    the thread-running consumer handoff) in service/ — the pass must
    cross the module boundary to connect them."""
    return lint("rtap_tpu/obs/_fx.py", tracker_code, ["cross-share"],
                extra=(("rtap_tpu/service/_wire.py", wire_code),))


def test_cross_share_positive_across_modules():
    r = cross_lint(_TRACKER)
    assert sorted(f.symbol for f in r.findings) == ["Tracker.n",
                                                    "Tracker.samples"]
    assert "thread-running" in r.findings[0].message


def test_cross_share_guarded_writes_are_clean():
    guarded = _TRACKER.replace(
        "    def fold(self, k):\n"
        "        self.samples[k] = self.samples.get(k, 0) + 1\n"
        "        self.n += 1\n",
        "    def fold(self, k):\n"
        "        with self._lock:\n"
        "            self.samples[k] = self.samples.get(k, 0) + 1\n"
        "            self.n += 1\n")
    assert cross_lint(guarded).findings == []


def test_cross_share_interprocedural_guard_inheritance():
    """A private helper whose every call site holds the lock inherits
    it — the IncidentCorrelator shape that a naive every-method-is-an-
    entry analysis would falsely flag."""
    code = _TRACKER.replace(
        "    def fold(self, k):\n"
        "        self.samples[k] = self.samples.get(k, 0) + 1\n"
        "        self.n += 1\n",
        "    def fold(self, k):\n"
        "        with self._lock:\n"
        "            self._bump(k)\n\n"
        "    def _bump(self, k):\n"
        "        self.samples[k] = self.samples.get(k, 0) + 1\n"
        "        self.n += 1\n")
    assert cross_lint(code).findings == []


def test_cross_share_atomic_rebind_is_the_snapshot_idiom():
    rebind = _TRACKER.replace(
        "        self.samples[k] = self.samples.get(k, 0) + 1\n"
        "        self.n += 1\n",
        "        self.samples = {**self.samples, k: 1}\n")
    assert cross_lint(rebind).findings == []


def test_cross_share_needs_a_threaded_consumer():
    """Handing the tracker to two PLAIN consumers is single-threaded
    wiring — not this pass's business."""
    wire = _WIRE.replace("    r = Runner(t)\n", "    r = consume2(t)\n")
    assert cross_lint(_TRACKER, wire).findings == []


def test_cross_share_suppression_comment():
    supp = _TRACKER.replace(
        "        self.n += 1\n",
        "        self.n += 1  # rtap: allow[cross-share] — fixture\n")
    r = cross_lint(supp)
    assert [f.symbol for f in r.findings] == ["Tracker.samples"]
    assert len(r.suppressed) == 1


# ---------------------------------------------- replay-determinism --
def test_replay_det_set_iteration():
    code = ("def emit(fh):\n"
            "    acc = set()\n"
            "    acc.add(1)\n"
            "    for x in acc:\n"
            "        fh.write(str(x))\n")
    r = lint("rtap_tpu/correlate/_fx.py", code, ["replay-determinism"])
    assert len(r.findings) == 1 and "set-iter" in r.findings[0].symbol
    ok = code.replace("for x in acc:", "for x in sorted(acc):")
    assert lint("rtap_tpu/correlate/_fx.py", ok,
                ["replay-determinism"]).findings == []
    # model/ops code may iterate sets freely — scope is the
    # serialization surface only
    assert lint("rtap_tpu/ops/_fx.py", code,
                ["replay-determinism"]).findings == []


def test_replay_det_self_attr_set_and_comprehension():
    code = ("class J:\n"
            "    def __init__(self):\n"
            "        self._seen = set()\n\n"
            "    def digest(self):\n"
            "        return ''.join(str(x) for x in self._seen)\n")
    r = lint("rtap_tpu/resilience/journal.py", code,
             ["replay-determinism"])
    assert len(r.findings) == 1
    assert "J.digest" in r.findings[0].symbol


def test_replay_det_unsorted_listing():
    code = ("import os\n\n"
            "def walk(d, fh):\n"
            "    for n in os.listdir(d):\n"
            "        fh.write(n)\n")
    r = lint("rtap_tpu/service/checkpoint.py", code,
             ["replay-determinism"])
    assert len(r.findings) == 1 and "fs-iter" in r.findings[0].symbol
    ok = code.replace("os.listdir(d):", "sorted(os.listdir(d)):")
    assert lint("rtap_tpu/service/checkpoint.py", ok,
                ["replay-determinism"]).findings == []
    # Path.iterdir()/glob() method forms count too
    meth = ("def walk(p, fh):\n"
            "    for n in p.iterdir():\n"
            "        fh.write(str(n))\n")
    assert len(lint("rtap_tpu/service/checkpoint.py", meth,
                    ["replay-determinism"]).findings) == 1


def test_replay_det_dict_view_set_ops():
    """a.keys() - b.keys() returns a REAL set (hash-ordered) even
    though iterating a bare .keys() view is insertion-ordered —
    the BinOp branch must treat dict views as set-like."""
    code = ("def diff(a, b, fh):\n"
            "    for k in a.keys() - b.keys():\n"
            "        fh.write(k)\n")
    r = lint("rtap_tpu/correlate/_fx.py", code, ["replay-determinism"])
    assert len(r.findings) == 1 and "set-iter" in r.findings[0].symbol
    # a bare .keys() iteration stays legal (insertion-ordered)
    plain = ("def emit(a, fh):\n"
             "    for k in a.keys():\n"
             "        fh.write(k)\n")
    assert lint("rtap_tpu/correlate/_fx.py", plain,
                ["replay-determinism"]).findings == []


def test_replay_det_float_sum_over_set():
    code = ("def tot(vals):\n"
            "    s = set(vals)\n"
            "    return sum(s)\n")
    r = lint("rtap_tpu/correlate/_fx.py", code, ["replay-determinism"])
    assert len(r.findings) == 1 and "float-sum" in r.findings[0].symbol
    ok = code.replace("sum(s)", "sum(sorted(s))")
    assert lint("rtap_tpu/correlate/_fx.py", ok,
                ["replay-determinism"]).findings == []


def test_replay_det_direct_set_consumption():
    """','.join(set) serializes in hash order with no for-loop for the
    iteration check to see — direct consumption is flagged too."""
    code = ("def emit(fh):\n"
            "    acc = set()\n"
            "    acc.add('x')\n"
            "    fh.write(','.join(acc))\n")
    r = lint("rtap_tpu/correlate/_fx.py", code, ["replay-determinism"])
    assert len(r.findings) == 1
    assert "set-consume" in r.findings[0].symbol
    ok = code.replace("','.join(acc)", "','.join(sorted(acc))")
    assert lint("rtap_tpu/correlate/_fx.py", ok,
                ["replay-determinism"]).findings == []


def test_replay_det_suppression_comment():
    code = ("import os\n\n"
            "def sweep(d):\n"
            "    # rtap: allow[replay-determinism] — all deleted\n"
            "    for n in os.listdir(d):\n"
            "        os.remove(n)\n")
    r = lint("rtap_tpu/service/checkpoint.py", code,
             ["replay-determinism"])
    assert r.findings == [] and len(r.suppressed) == 1


# ---------------------------------------------- resource-lifecycle --
_LEAKY_THREAD = """
import threading

class R:
    def start(self):
        self._t = threading.Thread(target=self._run, name="rtap-x",
                                   daemon=True)
        self._t.start()

    def _run(self):
        pass
"""


def test_lifecycle_thread_without_teardown():
    r = lint("rtap_tpu/obs/_fx.py", _LEAKY_THREAD, ["resource-lifecycle"])
    assert [f.symbol for f in r.findings] == ["R._t"]
    assert "no teardown surface" in r.findings[0].message


def test_lifecycle_bounded_join_is_clean_and_unbounded_flagged():
    closed = _LEAKY_THREAD + (
        "\n    def close(self):\n"
        "        self._t.join(timeout=2.0)\n")
    assert lint("rtap_tpu/obs/_fx.py", closed,
                ["resource-lifecycle"]).findings == []
    unbounded = _LEAKY_THREAD + (
        "\n    def close(self):\n"
        "        self._t.join()\n")
    r = lint("rtap_tpu/obs/_fx.py", unbounded, ["resource-lifecycle"])
    assert [f.symbol for f in r.findings] == ["R._t:unbounded-join"]


def test_lifecycle_release_reached_through_helper():
    """close() -> _stop() -> join: reachability is the in-class call
    closure, not a literal scan of close()'s own body."""
    code = _LEAKY_THREAD + (
        "\n    def close(self):\n"
        "        self._stop()\n"
        "\n    def _stop(self):\n"
        "        self._t.join(timeout=1.0)\n")
    assert lint("rtap_tpu/obs/_fx.py", code,
                ["resource-lifecycle"]).findings == []


def test_lifecycle_socket_and_scope():
    sock = ("import socket\n\n"
            "class S:\n"
            "    def connect(self, addr):\n"
            "        self._sock = socket.create_connection(addr)\n")
    r = lint("rtap_tpu/ingest/_fx.py", sock, ["resource-lifecycle"])
    assert [f.symbol for f in r.findings] == ["S._sock"]
    closed = sock + ("\n    def close(self):\n"
                     "        self._sock.close()\n")
    assert lint("rtap_tpu/ingest/_fx.py", closed,
                ["resource-lifecycle"]).findings == []
    # outside the serve stack: not gated
    assert lint("rtap_tpu/models/_fx.py", sock,
                ["resource-lifecycle"]).findings == []


def test_lifecycle_join_timeout_none_is_unbounded():
    """join(timeout=None) / join(None) are the UNbounded spellings —
    the keyword's mere presence must not count as bounded."""
    kw_none = _LEAKY_THREAD + (
        "\n    def close(self):\n"
        "        self._t.join(timeout=None)\n")
    r = lint("rtap_tpu/obs/_fx.py", kw_none, ["resource-lifecycle"])
    assert [f.symbol for f in r.findings] == ["R._t:unbounded-join"]
    pos_none = _LEAKY_THREAD + (
        "\n    def close(self):\n"
        "        self._t.join(None)\n")
    r2 = lint("rtap_tpu/obs/_fx.py", pos_none, ["resource-lifecycle"])
    assert [f.symbol for f in r2.findings] == ["R._t:unbounded-join"]


def test_lifecycle_covers_nested_handler_classes():
    """A class nested inside a method (the request-handler idiom) owns
    per-connection resources too — top-level-only scanning would
    exempt exactly the BinaryBatchSource leak class."""
    code = """
import socket

class Outer:
    def build(self):
        class Handler:
            def setup(self):
                self._peer = socket.create_connection(("h", 1))
        return Handler
"""
    r = lint("rtap_tpu/ingest/_fx.py", code, ["resource-lifecycle"])
    assert [f.symbol for f in r.findings] == ["Handler._peer"]


def test_lifecycle_suppression_comment():
    supp = _LEAKY_THREAD.replace(
        "        self._t = threading.Thread(target=self._run, "
        'name="rtap-x",\n',
        "        # rtap: allow[resource-lifecycle] — fixture daemon\n"
        "        self._t = threading.Thread(target=self._run, "
        'name="rtap-x",\n')
    r = lint("rtap_tpu/obs/_fx.py", supp, ["resource-lifecycle"])
    assert r.findings == [] and len(r.suppressed) == 1
