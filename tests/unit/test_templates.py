"""Drain-style log-template miner units (ISSUE 9 encoder family): stable
first-seen ids, variable masking, merge-vs-mint behavior, determinism
across replay, and the bounded-overflow contract."""

import pytest

from rtap_tpu.ingest.templates import WILDCARD, TemplateMiner


@pytest.mark.quick
def test_same_structure_same_id_across_variables():
    m = TemplateMiner()
    a = m.observe("connected to host 10.0.3.7 port 443")
    b = m.observe("connected to host 10.0.9.1 port 8080")
    assert a == b
    assert m.n_templates() == 1
    assert WILDCARD in m.template(a)


@pytest.mark.quick
def test_different_structures_mint_different_ids():
    m = TemplateMiner()
    a = m.observe("heartbeat ok seq 1")
    b = m.observe("ERROR disk failure on volume 3 remounting read-only")
    assert a != b
    assert m.n_templates() == 2


def test_ids_are_dense_in_first_seen_order():
    m = TemplateMiner()
    lines = ["alpha event", "beta event happened", "alpha event",
             "gamma thing done now", "beta event happened"]
    ids = [m.observe(ln) for ln in lines]
    assert ids == [0, 1, 0, 2, 1]


def test_replay_determinism():
    """The same line sequence mines the same ids — the property the
    journal/crash replay story rests on."""
    lines = [f"request /api/v1/items served in {i * 13 % 400} ms status 200"
             if i % 3 else f"gc pause {i} ms heap {i * 7} mb"
             for i in range(200)]
    a = TemplateMiner().encode_values(lines)
    b = TemplateMiner().encode_values(lines)
    assert a == b


def test_template_generalizes_variable_positions():
    m = TemplateMiner(sim_threshold=0.5)
    m.observe("job sync finished with status ok")
    tid = m.observe("job sync finished with status failed")
    assert m.template(tid) == f"job sync finished with status {WILDCARD}"


def test_token_count_partitions():
    """Drain's first split is token count: same words, different arity
    never merge."""
    m = TemplateMiner()
    a = m.observe("cache miss")
    b = m.observe("cache miss on shard primary")
    assert a != b


def test_overflow_folds_not_drops(caplog):
    m = TemplateMiner(max_templates=4)
    ids = [m.observe(f"structure{'x' * (i + 1)} one two") for i in range(8)]
    assert max(ids) == m.overflow_id
    assert m.overflow == 8 - 3  # 3 real templates + the overflow bucket
    assert m.template(m.overflow_id) == "<overflow>"
    assert m.stats()["overflow"] == m.overflow


def test_empty_and_whitespace_lines():
    m = TemplateMiner()
    a = m.observe("")
    b = m.observe("   ")
    assert a == b  # both mask to the single-wildcard template


def test_encode_values_returns_floats():
    m = TemplateMiner()
    out = m.encode_values(["heartbeat ok seq 5", "heartbeat ok seq 6"])
    assert out == [0.0, 0.0]
    assert all(isinstance(v, float) for v in out)


def test_validation():
    with pytest.raises(ValueError, match="depth"):
        TemplateMiner(depth=0)
    with pytest.raises(ValueError, match="sim_threshold"):
        TemplateMiner(sim_threshold=0.0)
    with pytest.raises(ValueError, match="max_templates"):
        TemplateMiner(max_templates=1)
