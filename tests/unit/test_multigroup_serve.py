"""Multi-group live serving (service/loop.py live_loop over a registry).

Measured chip throughput peaks at small G (SCALING.md bench G-sweep), so
at-scale serving is many interleaved groups per chip. These tests pin the
registry path of live_loop: per-group slicing of the source vector, NaN
padding of the sealed partial group, dispatch-all-then-collect-all
ordering, alert emission only for live slots — and bit-exact equivalence
of a registry group against the same streams served as one standalone
group (same seed, same feed => same final model state).
"""

import json

import numpy as np

from rtap_tpu.config import cluster_preset
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroup, StreamGroupRegistry

G_TOTAL = 6
GROUP_SIZE = 4  # -> groups of [4 live, 2 live + 2 pad]
IDS = [f"s{i}" for i in range(G_TOTAL)]
N_TICKS = 12


def _feed(k: int):
    rng = np.random.Generator(np.random.Philox(key=(11, k)))
    return (30 + 5 * rng.random(G_TOTAL)).astype(np.float32), 1_700_000_000 + k


def _registry():
    reg = StreamGroupRegistry(cluster_preset(), group_size=GROUP_SIZE,
                              backend="tpu")
    for sid in IDS:
        reg.add_stream(sid)
    reg.finalize()
    return reg


def _alert_records(path):
    """The ALERT records of a shared alert/event stream file. Watchdog
    events (rtap_tpu.obs; json.dumps puts their discriminating "event" key
    first) carry wall-clock measurements, so they are legitimately
    nondeterministic across otherwise bit-identical runs — bitexactness is
    a contract on the alert stream, not on latency telemetry."""
    with open(path) as f:
        return "".join(l for l in f if not l.startswith('{"event"'))


def test_registry_live_loop_stats_and_alert_hygiene(tmp_path):
    reg = _registry()
    assert [g.n_live for g in reg.groups] == [4, 2]
    path = str(tmp_path / "alerts.jsonl")
    stats = live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.01,
                      alert_path=path)
    assert stats["scored"] == G_TOTAL * N_TICKS  # live slots only, no pads
    assert stats["n_groups"] == 2
    assert stats["ticks"] == N_TICKS
    for line in open(path):
        rec = json.loads(line)
        if "event" in rec:
            # watchdog events (rtap_tpu.obs) share the alert stream,
            # discriminated by their "event" key — never alert-shaped
            assert "stream" not in rec
            continue
        assert not rec["stream"].startswith("__pad")


def test_registry_group_bitexact_vs_standalone():
    """Group 0 of the registry must evolve bit-identically to a standalone
    StreamGroup over the same 4 streams and feed (same seed, same kernel
    path): the multi-group schedule may not perturb the model math."""
    reg = _registry()
    live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.01)

    solo = StreamGroup(cluster_preset(), IDS[:GROUP_SIZE], backend="tpu")
    for k in range(N_TICKS):
        values, ts = _feed(k)
        solo.run_chunk(values[None, :GROUP_SIZE],
                       np.full((1, GROUP_SIZE), ts, np.int64))

    a, b = reg.groups[0].state, solo.state
    assert set(a) == set(b)
    for key in a:
        np.testing.assert_array_equal(
            np.asarray(a[key]), np.asarray(b[key]), err_msg=key)


def test_unfinalized_registry_rejected_loudly():
    import pytest

    reg = StreamGroupRegistry(cluster_preset(), group_size=GROUP_SIZE,
                              backend="tpu")
    for sid in IDS:
        reg.add_stream(sid)  # 6 streams, group_size 4: 2 left pending
    with pytest.raises(ValueError, match="finalize"):
        live_loop(_feed, reg, n_ticks=1, cadence_s=0.01)


def test_source_length_mismatch_rejected_loudly():
    import pytest

    reg = _registry()
    bad = lambda k: (np.zeros(G_TOTAL - 1, np.float32), 1_700_000_000)  # noqa: E731
    with pytest.raises(ValueError, match="live streams"):
        live_loop(bad, reg, n_ticks=1, cadence_s=0.01)


def test_multifield_source_through_registry():
    """[G, n_fields] sources (node_preset multivariate) must survive the
    padding path — StreamGroup.tick always supported them."""
    from rtap_tpu.config import node_preset

    reg = StreamGroupRegistry(node_preset(n_metrics=2), group_size=2,
                              backend="tpu")
    for sid in ("n0", "n1", "n2"):
        reg.add_stream(sid)
    reg.finalize()

    def feed(k):
        rng = np.random.Generator(np.random.Philox(key=(13, k)))
        return (30 + rng.random((3, 2))).astype(np.float32), 1_700_000_000 + k

    stats = live_loop(feed, reg, n_ticks=4, cadence_s=0.01)
    assert stats["scored"] == 3 * 4 and stats["n_groups"] == 2


def test_live_checkpoint_resume_bitexact(tmp_path):
    """A serve killed and restarted from its checkpoint dir must continue
    bit-identically to an uninterrupted serve: 6 ticks + resume + 6 ticks
    == 12 ticks, state-for-state, across both groups (incl. the padded
    one). SURVEY.md §5 checkpoint/resume at the live-service level."""
    ck = str(tmp_path / "ck")

    # uninterrupted reference
    ref = _registry()
    live_loop(_feed, ref, n_ticks=12, cadence_s=0.01)

    # first serve: 6 ticks, checkpoint every 2 (last save lands on tick 6)
    first = _registry()
    stats1 = live_loop(_feed, first, n_ticks=6, cadence_s=0.01,
                       checkpoint_dir=ck, checkpoint_every=2)
    assert stats1["checkpoints_saved"] == 3

    # "restart": fresh registry, same ids/config, resumes from the dir and
    # continues with the rest of the feed
    second = _registry()
    stats2 = live_loop(lambda k: _feed(k + 6), second, n_ticks=6,
                       cadence_s=0.01, checkpoint_dir=ck)
    assert stats2["resumed_from"] == {"group0": 6, "group1": 6}

    for gi in range(2):
        a, b = second.groups[gi].state, ref.groups[gi].state
        assert set(a) == set(b)
        for key in a:
            np.testing.assert_array_equal(
                np.asarray(a[key]), np.asarray(b[key]), err_msg=f"g{gi}/{key}")


def test_torn_checkpoint_set_resumes_with_skew(tmp_path):
    """A crash between per-group saves leaves groups at different ticks.
    Live data is not tick-indexed and groups are independent, so the serve
    must come back up (a behind group merely lost some learning) — with
    the skew surfaced in stats, not hidden."""
    import shutil

    ck = str(tmp_path / "ck")
    first = _registry()
    live_loop(_feed, first, n_ticks=4, cadence_s=0.01,
              checkpoint_dir=ck, checkpoint_every=2)
    shutil.rmtree(ck + "/group0001")  # group1's save "lost in the crash"
    stats = live_loop(_feed, _registry(), n_ticks=2, cadence_s=0.01,
                      checkpoint_dir=ck)
    assert stats["resumed_from"] == {"group0": 4}  # group1 started fresh
    assert stats["resume_tick_skew"] == 4
    assert stats["scored"] == G_TOTAL * 2


def test_checkpoint_requires_registry(tmp_path):
    import pytest

    grp = StreamGroup(cluster_preset(), IDS, backend="tpu")
    with pytest.raises(ValueError, match="Registry"):
        live_loop(_feed, grp, n_ticks=1, cadence_s=0.01,
                  checkpoint_dir=str(tmp_path))


def test_graceful_stop_saves_final_state(tmp_path):
    """An orderly shutdown (stop_event, serve's SIGTERM path) finishes the
    current tick, saves final state, and reports truncated-but-honest
    stats instead of dying silently."""
    import threading

    ck = str(tmp_path / "ck")
    reg = _registry()
    stop = threading.Event()

    def feed_then_stop(k):
        if k == 3:
            stop.set()  # raised mid-run, e.g. by a signal handler
        return _feed(k)

    stats = live_loop(feed_then_stop, reg, n_ticks=50, cadence_s=0.01,
                      checkpoint_dir=ck, checkpoint_every=10,
                      stop_event=stop)
    assert stats["stopped_early"] is True
    assert stats["ticks"] == 4 and stats["ticks_requested"] == 50
    assert stats["scored"] == G_TOTAL * 4
    assert stats["checkpoints_saved"] == 1  # the final on-stop save

    # the saved state resumes exactly where the stop landed
    cont = _registry()
    stats2 = live_loop(lambda k: _feed(k + 4), cont, n_ticks=1,
                       cadence_s=0.01, checkpoint_dir=ck)
    assert stats2["resumed_from"] == {"group0": 4, "group1": 4}


def test_single_group_path_unchanged(tmp_path):
    """A bare StreamGroup still works through live_loop (the pre-registry
    API), and emits for every slot."""
    grp = StreamGroup(cluster_preset(), IDS, backend="tpu")
    stats = live_loop(_feed, grp, n_ticks=5, cadence_s=0.01,
                      alert_path=str(tmp_path / "a.jsonl"))
    assert stats["scored"] == G_TOTAL * 5
    assert stats["n_groups"] == 1


def test_pipeline_depth2_bitexact_vs_depth1(tmp_path):
    """pipeline_depth=2 changes WHEN results are collected (one tick
    later), never WHAT is computed: alert lines, throughput, and final
    model state must be bit-identical to depth 1 — including across a
    mid-run checkpoint save, which drains the pipeline first."""
    out = {}
    for depth in (1, 2):
        reg = _registry()
        path = str(tmp_path / f"alerts_d{depth}.jsonl")
        ck = str(tmp_path / f"ck_d{depth}")
        stats = live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.0,
                          alert_path=path, checkpoint_dir=ck,
                          checkpoint_every=5, pipeline_depth=depth)
        assert stats["pipeline_depth"] == depth
        assert stats["scored"] == G_TOTAL * N_TICKS
        import jax

        out[depth] = (_alert_records(path),
                      [jax.tree_util.tree_map(lambda x: np.asarray(x).copy(),
                                              g.state) for g in reg.groups],
                      stats["checkpoints_saved"])
    assert out[1][0] == out[2][0]  # identical alert stream, same order
    for s1, s2 in zip(out[1][1], out[2][1]):
        l1 = jax.tree_util.tree_leaves(s1)
        l2 = jax.tree_util.tree_leaves(s2)
        assert len(l1) == len(l2)
        for a, b in zip(l1, l2):
            np.testing.assert_array_equal(a, b)
    assert out[1][2] == out[2][2]


def test_pipeline_depth_validation():
    import pytest

    reg = _registry()
    with pytest.raises(ValueError, match="pipeline_depth"):
        live_loop(_feed, reg, n_ticks=2, cadence_s=0.0, pipeline_depth=0)


def test_dispatch_threads_bitexact_vs_serial(tmp_path):
    """dispatch_threads=N overlaps the per-group dispatch/collect RPCs
    (the tunnel's serial ~65 ms/group floor that depth-2 pipelining alone
    cannot touch — reports/live_soak_pipelined.json); it must never change
    WHAT is computed: alert stream, order, and final model state are
    bit-identical to serial dispatch, including across a mid-run
    checkpoint drain and with depth 2 stacked on top."""
    import jax

    out = {}
    for threads in (1, 4):
        reg = _registry()
        path = str(tmp_path / f"alerts_t{threads}.jsonl")
        ck = str(tmp_path / f"ck_t{threads}")
        stats = live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.0,
                          alert_path=path, checkpoint_dir=ck,
                          checkpoint_every=5, pipeline_depth=2,
                          dispatch_threads=threads)
        # stats carry the EFFECTIVE worker count (capped at n_groups; 1
        # when the pool was never created), not the requested flag value
        assert stats["dispatch_threads"] == min(threads, len(reg.groups))
        assert stats["scored"] == G_TOTAL * N_TICKS
        out[threads] = (_alert_records(path),
                        [jax.tree_util.tree_map(
                            lambda x: np.asarray(x).copy(), g.state)
                         for g in reg.groups])
    assert out[1][0] == out[4][0]  # identical alert stream, same order
    for s1, s2 in zip(out[1][1], out[4][1]):
        l1, l2 = jax.tree_util.tree_leaves(s1), jax.tree_util.tree_leaves(s2)
        assert len(l1) == len(l2)
        for a, b in zip(l1, l2):
            np.testing.assert_array_equal(a, b)


def test_dispatch_threads_validation():
    import pytest

    reg = _registry()
    with pytest.raises(ValueError, match="dispatch_threads"):
        live_loop(_feed, reg, n_ticks=2, cadence_s=0.0, dispatch_threads=0)


# What --freeze freezes: the learned tensors. Everything else is temporal
# context that inference itself evolves (NuPIC TM with learn=False still
# computes activations and predictions; it just never touches permanences,
# synapse growth, or duty cycles). seg_pot is dynamic: it is the count of
# potential synapses whose presynaptic cell fired at the PREVIOUS step —
# frozen weights x evolving activity (models/state.py).
FROZEN_KEYS = {"perm", "syn_perm", "presyn", "members", "boost",
               "active_duty", "overlap_duty", "seg_last", "tm_overflow",
               "sp_iter", "enc_bound", "enc_offset", "enc_resolution"}
DYNAMIC_KEYS = {"active_seg", "matching_seg", "prev_active", "prev_winner",
                "seg_pot", "tm_iter"}


def test_freeze_serves_without_mutating_learned_state(tmp_path):
    """learn=False (serve --freeze, NuPIC disableLearning parity): every
    learned tensor (SP permanences/boost/duty cycles, TM synapses/pools)
    is bit-identical after any number of frozen ticks, while scoring
    still flows, temporal context still evolves, and the host-side
    likelihood normalizer keeps adapting."""
    reg = _registry()
    # mature the models first: a frozen fresh model only proves zeros
    live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.0)
    before = [{k: np.asarray(v).copy() for k, v in g.state.items()}
              for g in reg.groups]
    assert FROZEN_KEYS | DYNAMIC_KEYS == set(before[0])  # no key unaccounted
    lik_records_before = [g.likelihood.records for g in reg.groups]

    path = str(tmp_path / "alerts_frozen.jsonl")
    ck = tmp_path / "ck_frozen"
    ck.mkdir()
    stats = live_loop(lambda k: _feed(k + N_TICKS), reg, n_ticks=N_TICKS,
                      cadence_s=0.0, alert_path=path, learn=False,
                      checkpoint_dir=str(ck), checkpoint_every=3)
    assert stats["learn"] is False
    assert stats["scored"] == G_TOTAL * N_TICKS  # scoring still flows
    # frozen serving treats --checkpoint-dir as strictly read-only: no
    # periodic saves, no exit save (replicas may share a golden dir)
    assert stats["checkpoints_saved"] == 0
    assert list(ck.iterdir()) == []
    # the likelihood normalizer is downstream of the model and must keep
    # adapting while frozen (documented --freeze semantics)
    for n0, g in zip(lik_records_before, reg.groups):
        assert g.likelihood.records == n0 + N_TICKS

    for b, g in zip(before, reg.groups):
        for key in FROZEN_KEYS:
            np.testing.assert_array_equal(
                b[key], np.asarray(g.state[key]), err_msg=key)
        # the recurrent context must still advance — a frozen model that
        # stops predicting would score every tick anomalous
        assert any(not np.array_equal(b[k], np.asarray(g.state[k]))
                   for k in DYNAMIC_KEYS)


def test_micro_chunk_bitexact_vs_per_tick(tmp_path):
    """micro_chunk=M batches M ticks into one dispatch (the per-program-
    floor amortizer, SCALING.md round 5): alert lines, throughput, and
    final model state must be bit-identical to per-tick dispatch — the
    chunked scan IS the same program the per-tick path runs, including a
    non-divisible tail (N_TICKS=12, M=5 -> chunks 5+5+2) and composed
    with depth 2 + threads."""
    import jax

    out = {}
    for m in (1, 5):
        reg = _registry()
        path = str(tmp_path / f"alerts_m{m}.jsonl")
        stats = live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.0,
                          alert_path=path, pipeline_depth=2,
                          dispatch_threads=2, micro_chunk=m)
        assert stats["micro_chunk"] == m
        assert stats["scored"] == G_TOTAL * N_TICKS
        out[m] = (_alert_records(path),
                  [jax.tree_util.tree_map(lambda x: np.asarray(x).copy(),
                                          g.state) for g in reg.groups])
    assert out[1][0] == out[5][0]  # identical alert stream, same order
    for s1, s2 in zip(out[1][1], out[5][1]):
        for a, b in zip(jax.tree_util.tree_leaves(s1),
                        jax.tree_util.tree_leaves(s2)):
            np.testing.assert_array_equal(a, b)


def test_micro_chunk_validation_and_stagger_stats():
    import pytest

    reg = _registry()
    with pytest.raises(ValueError, match="micro_chunk"):
        live_loop(_feed, reg, n_ticks=2, cadence_s=0.0, micro_chunk=0)


def test_micro_chunk_early_stop_flushes_buffer(tmp_path):
    """A stop_event landing mid-chunk must still score the buffered ticks
    (nothing ingested is silently dropped)."""
    import threading

    reg = _registry()
    stop = threading.Event()
    calls = [0]

    def feed(k):
        calls[0] += 1
        if calls[0] == 8:  # mid-chunk for M=5 (ticks 6..8 buffered)
            stop.set()
        return _feed(k)

    path = str(tmp_path / "alerts_stop.jsonl")
    stats = live_loop(feed, reg, n_ticks=N_TICKS, cadence_s=0.0,
                      alert_path=path, micro_chunk=5, stop_event=stop)
    # stop is checked at the TOP of the next tick: 8 ticks were polled,
    # all 8 must be scored (5 in the first chunk, 3 flushed)
    assert stats["ticks"] == 8
    assert stats["scored"] == G_TOTAL * 8


def test_chunk_stagger_content_equal_and_state_bitexact(tmp_path):
    """chunk_stagger rotates WHEN each group's chunk dispatches, never WHAT
    any group computes: final model state must be bit-identical to plain
    per-tick serving, and the alert stream must contain exactly the same
    lines (order differs across groups by design — per stream it is still
    chronological)."""
    import jax

    out = {}
    for mode in ("plain", "stagger"):
        reg = _registry()
        path = str(tmp_path / f"alerts_{mode}.jsonl")
        kw = dict(micro_chunk=3, chunk_stagger=True) if mode == "stagger" \
            else {}
        stats = live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.0,
                          alert_path=path, pipeline_depth=2,
                          dispatch_threads=2, **kw)
        assert stats["scored"] == G_TOTAL * N_TICKS
        out[mode] = (sorted(_alert_records(path).splitlines()),
                     [jax.tree_util.tree_map(lambda x: np.asarray(x).copy(),
                                             g.state) for g in reg.groups])
    assert out["plain"][0] == out["stagger"][0]
    for s1, s2 in zip(out["plain"][1], out["stagger"][1]):
        for a, b in zip(jax.tree_util.tree_leaves(s1),
                        jax.tree_util.tree_leaves(s2)):
            np.testing.assert_array_equal(a, b)


def test_chunk_stagger_validation():
    import pytest

    reg = _registry()
    with pytest.raises(ValueError, match="micro_chunk >= 2"):
        live_loop(_feed, reg, n_ticks=2, cadence_s=0.0, chunk_stagger=True)


def test_chunk_stagger_checkpoint_resume_bitexact(tmp_path):
    """Periodic checkpoints under chunk_stagger force a boundary
    realignment; the saved state matches the last emitted tick exactly,
    so resume continues bit-identically to an uninterrupted plain run
    (chunking never changes WHAT is computed)."""
    ck = str(tmp_path / "ck")

    ref = _registry()
    live_loop(_feed, ref, n_ticks=12, cadence_s=0.01)

    first = _registry()
    stats1 = live_loop(_feed, first, n_ticks=6, cadence_s=0.01,
                       checkpoint_dir=ck, checkpoint_every=4,
                       micro_chunk=3, chunk_stagger=True)
    assert stats1["checkpoints_saved"] >= 1

    second = _registry()
    stats2 = live_loop(lambda k: _feed(k + 6), second, n_ticks=6,
                       cadence_s=0.01, checkpoint_dir=ck,
                       micro_chunk=3, chunk_stagger=True)
    assert stats2["resumed_from"] == {"group0": 6, "group1": 6}
    for gi in range(2):
        a, b = second.groups[gi].state, ref.groups[gi].state
        for key in a:
            np.testing.assert_array_equal(
                np.asarray(a[key]), np.asarray(b[key]), err_msg=f"g{gi}/{key}")


def test_micro_chunk_checkpoint_cadence_not_degraded(tmp_path):
    """checkpoint_every that is no multiple of micro_chunk must still save
    at every first boundary PAST due (due-since-last-save trigger), not at
    lcm(M, checkpoint_every): M=4, every=3 over 12 ticks -> saves at
    boundaries 4, 8, 12 (three), where the old modulus rule saved only at
    tick 12."""
    reg = _registry()
    ck = str(tmp_path / "ck")
    stats = live_loop(_feed, reg, n_ticks=N_TICKS, cadence_s=0.0,
                      checkpoint_dir=ck, checkpoint_every=3, micro_chunk=4)
    assert stats["checkpoints_saved"] == 3


def test_live_checkpoint_resume_with_micro_chunk(tmp_path):
    """Resume composes with micro_chunk: a serve chunking M=3 ticks per
    dispatch, killed after its tick-6 checkpoint, restarted with the same
    M, must continue bit-identically to an uninterrupted M=3 serve (saves
    land only at chunk boundaries; the due-since trigger keeps the
    cadence)."""
    ck = str(tmp_path / "ck")

    ref = _registry()
    live_loop(_feed, ref, n_ticks=12, cadence_s=0.01, micro_chunk=3)

    first = _registry()
    stats1 = live_loop(_feed, first, n_ticks=6, cadence_s=0.01,
                       checkpoint_dir=ck, checkpoint_every=2, micro_chunk=3)
    # boundaries at 3, 6: due-since-last >= 2 fires at both
    assert stats1["checkpoints_saved"] == 2

    second = _registry()
    stats2 = live_loop(lambda k: _feed(k + 6), second, n_ticks=6,
                       cadence_s=0.01, checkpoint_dir=ck, micro_chunk=3)
    assert stats2["resumed_from"] == {"group0": 6, "group1": 6}

    for gi in range(2):
        a, b = second.groups[gi].state, ref.groups[gi].state
        for key in a:
            np.testing.assert_array_equal(
                np.asarray(a[key]), np.asarray(b[key]), err_msg=f"g{gi}/{key}")
