"""SDR classifier (SURVEY.md C10): oracle-vs-device parity + prediction
quality. The classifier decodes TM active cells to a predicted next value —
the "prediction" half of the reference's name; quality bar: on a periodic
stream it must beat the last-value baseline once trained."""

import numpy as np
import pytest

from rtap_tpu.config import (
    ClassifierConfig,
    DateConfig,
    LikelihoodConfig,
    ModelConfig,
    RDSEConfig,
    SPConfig,
    TMConfig,
)
from rtap_tpu.models.htm_model import HTMModel


def _cfg(buckets=33, alpha=0.1):
    return ModelConfig(
        rdse=RDSEConfig(size=128, active_bits=9, resolution=1.0),
        date=DateConfig(time_of_day_width=0, time_of_day_size=0, weekend_width=0),
        sp=SPConfig(columns=128, num_active_columns=8),
        tm=TMConfig(cells_per_column=8, activation_threshold=4, min_threshold=2,
                    max_segments_per_cell=4, max_synapses_per_segment=12,
                    new_synapse_count=6, learn_cap=48, col_cap=8),
        likelihood=LikelihoodConfig(mode="streaming", learning_period=20,
                                    estimation_samples=10),
        classifier=ClassifierConfig(enabled=True, buckets=buckets, alpha=alpha),
    )


def _periodic_values(n, period=6, unique=False):
    if unique:
        cycle = np.array([10.0, 13.0, 17.0, 22.0, 19.0, 15.0], np.float32)[:period]
    else:
        # 14 and 18 each appear twice with different successors — requires
        # TM context disambiguation (the hard case)
        cycle = np.array([10.0, 14.0, 18.0, 22.0, 18.0, 14.0], np.float32)[:period]
    return np.tile(cycle, n // period + 1)[:n]


@pytest.mark.parametrize("layout", ["aos", "flat"])
def test_classifier_parity_cpu_vs_device(layout):
    """Same records through the numpy oracle and the jitted device kernel:
    predictions agree to float tolerance (softmax exp may differ by ulps).
    Covered under both kernel layouts — the classifier consumes TM cell
    state (prev_active), which the flat adapters must hand over unchanged."""
    import rtap_tpu.ops.tm_tpu as tm_tpu

    cfg = _cfg()
    cpu = HTMModel(cfg, seed=1, backend="cpu")
    tm_tpu.set_layout_mode(layout)
    try:
        dev = HTMModel(cfg, seed=1, backend="tpu")
        vals = _periodic_values(200)
        for i, v in enumerate(vals):
            rc = cpu.run(1_700_000_000 + i, float(v))
            rd = dev.run(1_700_000_000 + i, float(v))
            assert rc.raw_score == pytest.approx(rd.raw_score, abs=0.0), f"step {i}"
            assert rc.prediction == pytest.approx(rd.prediction, rel=1e-4, abs=1e-4), f"step {i}"
            assert rc.prediction_prob == pytest.approx(rd.prediction_prob, rel=1e-3, abs=1e-5), f"step {i}"
    finally:
        tm_tpu.set_layout_mode(None)


def _prediction_maes(vals, train=400):
    cfg = _cfg()
    model = HTMModel(cfg, seed=0, backend="cpu")
    preds, actual_next, last_vals = [], [], []
    for i, v in enumerate(vals[:-1]):
        res = model.run(1_700_000_000 + i, float(v))
        if i >= train:
            preds.append(res.prediction)
            actual_next.append(float(vals[i + 1]))
            last_vals.append(float(v))
    mae_model = np.mean(np.abs(np.array(preds) - np.array(actual_next)))
    mae_last = np.mean(np.abs(np.array(last_vals) - np.array(actual_next)))
    return mae_model, mae_last


def test_classifier_near_exact_on_unique_cycle():
    """Unique-successor cycle: TM predicts every transition, so the decoded
    next value must be near-exact — and far better than last-value."""
    mae_model, mae_last = _prediction_maes(_periodic_values(600, unique=True))
    assert mae_model < 0.25, mae_model
    assert mae_model < 0.1 * mae_last, (mae_model, mae_last)


def test_classifier_beats_last_value_on_ambiguous_cycle():
    """Shared-element cycle (14/18 appear twice with different successors):
    the vanilla TM does not fully disambiguate every context (the behavior
    NuPIC's backtracking TM targets — SURVEY.md C6), but the decoded
    prediction must still beat the last-value baseline."""
    mae_model, mae_last = _prediction_maes(_periodic_values(600))
    assert mae_model < 0.8 * mae_last, (mae_model, mae_last)


def test_classifier_bucket_clamps_and_handles_nan():
    from rtap_tpu.models.oracle.classifier import classifier_bucket

    assert classifier_bucket(0.0, 0.0, 1.0, 33) == 16
    assert classifier_bucket(5.0, 0.0, 1.0, 33) == 21
    assert classifier_bucket(1e9, 0.0, 1.0, 33) == 32  # clamp high
    assert classifier_bucket(-1e9, 0.0, 1.0, 33) == 0  # clamp low
    assert classifier_bucket(float("nan"), 0.0, 1.0, 33) == 16  # NaN -> center


def test_classifier_group_and_replay_predictions():
    """Stream groups surface predictions on both backends; replay collects
    them into ReplayResult.predictions."""
    from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_cluster
    from rtap_tpu.service.loop import replay_streams
    from rtap_tpu.service.registry import StreamGroup

    cfg = _cfg()
    ids = ["a", "b"]
    tpu = StreamGroup(cfg, ids, backend="tpu")
    cpu = StreamGroup(cfg, ids, backend="cpu")
    vals = _periodic_values(80)
    for i in range(80):
        v = np.array([vals[i], vals[i] + 1], np.float32)
        rt = tpu.tick(v, 1_700_000_000 + i)
        rc = cpu.tick(v, 1_700_000_000 + i)
        assert rt.prediction is not None and rc.prediction is not None
        np.testing.assert_allclose(rt.prediction, rc.prediction, rtol=1e-4, atol=1e-4)

    scfg = SyntheticStreamConfig(length=60, cadence_s=1.0, n_anomalies=0)
    streams = generate_cluster(2, metrics=("cpu",), cfg=scfg, seed=3)
    res = replay_streams(streams, cfg, backend="tpu", chunk_ticks=30)
    assert res.predictions is not None and res.predictions.shape == (60, 2)
    assert np.isfinite(res.predictions).all()
