"""scripts/check_static.sh rides tier-1: compileall over rtap_tpu AND
scripts/ + bench.py, plus `python -m rtap_tpu.analysis` (rtap-lint,
ISSUE 12) — the AST invariant analyzer that now owns the print gate
(NO print() in the serve stack; elsewhere print() must target an
explicit stream or be the one-JSON-line artifact emission), the
MUST_BE_STRICT coverage pin, and the race/purity/exception/flag-docs
passes. The gate is zero unsuppressed findings against the committed
analysis_baseline.json.

Also gated here (ISSUE 12 CI satellite): the analyzer's wall-time
budget — it must never become the slow part of the static gate on the
1-core tier-1 host — and the --json artifact contract soaks/hw_session
archive."""

import glob
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the analyzer's wall budget on the 1-core tier-1 host (ISSUE 12: the
#: static gate must stay fast; measured ~1.6 s — the 10 s ceiling is
#: headroom, not a target)
ANALYZER_BUDGET_S = 10.0


def _run():
    return subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "check_static.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )


def _cleanup(victim, subdir):
    os.remove(victim)
    # the script's compileall step byte-compiles the canary before the
    # analyzer fails — drop the orphaned pyc too, not just the source
    base = os.path.splitext(os.path.basename(victim))[0]
    for pyc in glob.glob(os.path.join(subdir, "__pycache__", base + "*")):
        os.remove(pyc)


def test_check_static_passes():
    proc = _run()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check_static: OK" in proc.stdout


def test_print_gate_bites_in_serve_stack():
    """The strict gate must fail on ANY print( in service/ — even one
    aimed at stderr (guard the guard: a checker regression could silently
    let prints back into the serve stack)."""
    subdir = os.path.join(REPO, "rtap_tpu", "service")
    victim = os.path.join(subdir, "_gate_canary.py")
    with open(victim, "w") as f:
        f.write('import sys\nprint("scraped", file=sys.stderr)\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary" in proc.stdout + proc.stderr


def test_print_gate_not_suppressible():
    """print-strict is gate-critical plumbing: an inline allow comment
    must NOT silence it (a suppressible guard is no guard)."""
    subdir = os.path.join(REPO, "rtap_tpu", "obs")
    victim = os.path.join(subdir, "_gate_canary_ns.py")
    with open(victim, "w") as f:
        f.write('import sys\n'
                'print("x", file=sys.stderr)  # rtap: allow[print-strict]\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary_ns" in proc.stdout + proc.stderr


def test_print_gate_bites_in_obs():
    """The strict gate covers rtap_tpu/obs/ too — the tracing/flight
    modules (ISSUE 4) live there, and a postmortem path that printed to
    stdout would corrupt the one-JSON-line serve artifact contract."""
    subdir = os.path.join(REPO, "rtap_tpu", "obs")
    victim = os.path.join(subdir, "_gate_canary_o.py")
    with open(victim, "w") as f:
        f.write('import sys\nprint("trace", file=sys.stderr)\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary_o" in proc.stdout + proc.stderr


def test_print_gate_bites_in_scripts():
    """The widened gate (ISSUE 3 satellite) must catch a bare print in
    scripts/ — including the multi-line call form a line-grep cannot see —
    while leaving file=stderr diagnostics and JSON emission legal."""
    subdir = os.path.join(REPO, "scripts")
    victim = os.path.join(subdir, "_gate_canary_s.py")
    with open(victim, "w") as f:
        f.write('print(\n    "bare stdout"\n)\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary_s" in proc.stdout + proc.stderr


def test_analyzer_budget_and_json_artifact():
    """One invocation, two gates: `python -m rtap_tpu.analysis --json`
    must finish inside ANALYZER_BUDGET_S on this host AND emit exactly
    one parseable JSON artifact line on stdout (the soak/hw_session
    archival surface), reporting ok=true with zero findings against the
    committed baseline."""
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "rtap_tpu.analysis", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    elapsed = time.perf_counter() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < ANALYZER_BUDGET_S, (
        f"analyzer took {elapsed:.1f}s (> {ANALYZER_BUDGET_S}s budget) — "
        "it must never become the slow part of the static gate")
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"--json must emit ONE stdout line, got: {lines}"
    art = json.loads(lines[0])["analysis"]
    assert art["ok"] is True
    assert art["findings"] == []
    assert art["files_scanned"] > 50
    assert art["baseline_errors"] == []
    # every committed baseline entry must still match a real finding —
    # stale entries mean the code moved on and the baseline should shrink
    assert art["stale_baseline"] == [], (
        "stale baseline entries — delete them from analysis_baseline.json: "
        f"{art['stale_baseline']}")


def test_race_canary_bites_end_to_end():
    """A deliberately racy class dropped into the serve stack must fail
    the whole gate (the ISSUE 12 acceptance shape: the analyzer, not a
    reviewer, catches the next Lease.set_meta-class bug)."""
    subdir = os.path.join(REPO, "rtap_tpu", "resilience")
    victim = os.path.join(subdir, "_gate_canary_r.py")
    with open(victim, "w") as f:
        f.write(
            "import threading\n\n\n"
            "class Racy:\n"
            "    def __init__(self):\n"
            "        self.n = 0\n"
            "        self._lock = threading.Lock()\n\n"
            "    def start(self):\n"
            "        t = threading.Thread(target=self._run,\n"
            "                             name='rtap-canary-r', daemon=True)\n"
            "        t.start()\n\n"
            "    def _run(self):\n"
            "        self.n += 1\n\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self.n += 1\n"
        )
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "Racy.n" in proc.stdout + proc.stderr
