"""scripts/check_static.sh rides tier-1: compileall over rtap_tpu AND
scripts/ + bench.py, plus `python -m rtap_tpu.analysis` (rtap-lint,
ISSUE 12) — the AST invariant analyzer that now owns the print gate
(NO print() in the serve stack; elsewhere print() must target an
explicit stream or be the one-JSON-line artifact emission), the
MUST_BE_STRICT coverage pin, and the race/purity/exception/flag-docs
passes. The gate is zero unsuppressed findings against the committed
analysis_baseline.json.

Also gated here (ISSUE 12 CI satellite): the analyzer's CPU budget —
it must never become the slow part of the static gate on the 1-core
tier-1 host — and the --json artifact contract soaks/hw_session
archive."""

import glob
import json
import os
import resource
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the analyzer's CPU budget on the 1-core tier-1 host (ISSUE 12: the
#: static gate must stay fast; measured ~1.6 s — the 10 s ceiling is
#: headroom, not a target). Budgets here are CHILD CPU SECONDS, not
#: wall time: wall budgets flaked whenever a concurrent process stole
#: the host mid-run (a 5 s analysis read as 13+ s under suite load) —
#: CPU time pins the analyzer's WORK, which is what the budget is
#: about, and is immune to preemption (the paced-loop deflake pattern:
#: pin semantics, not speed).
ANALYZER_BUDGET_S = 10.0


def _child_cpu_s():
    r = resource.getrusage(resource.RUSAGE_CHILDREN)
    return r.ru_utime + r.ru_stime


def _run():
    return subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "check_static.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )


def _cleanup(victim, subdir):
    os.remove(victim)
    # the script's compileall step byte-compiles the canary before the
    # analyzer fails — drop the orphaned pyc too, not just the source
    base = os.path.splitext(os.path.basename(victim))[0]
    for pyc in glob.glob(os.path.join(subdir, "__pycache__", base + "*")):
        os.remove(pyc)


def test_check_static_passes():
    proc = _run()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check_static: OK" in proc.stdout


def test_print_gate_bites_in_serve_stack():
    """The strict gate must fail on ANY print( in service/ — even one
    aimed at stderr (guard the guard: a checker regression could silently
    let prints back into the serve stack)."""
    subdir = os.path.join(REPO, "rtap_tpu", "service")
    victim = os.path.join(subdir, "_gate_canary.py")
    with open(victim, "w") as f:
        f.write('import sys\nprint("scraped", file=sys.stderr)\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary" in proc.stdout + proc.stderr


def test_print_gate_not_suppressible():
    """print-strict is gate-critical plumbing: an inline allow comment
    must NOT silence it (a suppressible guard is no guard)."""
    subdir = os.path.join(REPO, "rtap_tpu", "obs")
    victim = os.path.join(subdir, "_gate_canary_ns.py")
    with open(victim, "w") as f:
        f.write('import sys\n'
                'print("x", file=sys.stderr)  # rtap: allow[print-strict]\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary_ns" in proc.stdout + proc.stderr


def test_print_gate_bites_in_obs():
    """The strict gate covers rtap_tpu/obs/ too — the tracing/flight
    modules (ISSUE 4) live there, and a postmortem path that printed to
    stdout would corrupt the one-JSON-line serve artifact contract."""
    subdir = os.path.join(REPO, "rtap_tpu", "obs")
    victim = os.path.join(subdir, "_gate_canary_o.py")
    with open(victim, "w") as f:
        f.write('import sys\nprint("trace", file=sys.stderr)\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary_o" in proc.stdout + proc.stderr


def test_print_gate_bites_in_scripts():
    """The widened gate (ISSUE 3 satellite) must catch a bare print in
    scripts/ — including the multi-line call form a line-grep cannot see —
    while leaving file=stderr diagnostics and JSON emission legal."""
    subdir = os.path.join(REPO, "scripts")
    victim = os.path.join(subdir, "_gate_canary_s.py")
    with open(victim, "w") as f:
        f.write('print(\n    "bare stdout"\n)\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary_s" in proc.stdout + proc.stderr


def test_analyzer_budget_and_json_artifact():
    """One invocation, two gates: a COLD `python -m rtap_tpu.analysis
    --json --no-cache` (all twenty passes live, no cache shortcut) must
    finish inside ANALYZER_BUDGET_S on this 1-core host AND emit exactly
    one parseable JSON artifact line on stdout (the soak/hw_session
    archival surface), reporting ok=true with zero findings against the
    committed baseline."""
    cpu0 = _child_cpu_s()
    proc = subprocess.run(
        [sys.executable, "-m", "rtap_tpu.analysis", "--json",
         "--no-cache"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    cpu = _child_cpu_s() - cpu0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert cpu < ANALYZER_BUDGET_S, (
        f"analyzer burned {cpu:.1f} CPU s (> {ANALYZER_BUDGET_S}s "
        "budget) — it must never become the slow part of the static "
        "gate")
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"--json must emit ONE stdout line, got: {lines}"
    art = json.loads(lines[0])["analysis"]
    assert art["schema_version"] == 4
    assert art["ok"] is True
    assert art["cache"] == "off"
    assert art["findings"] == []
    assert art["files_scanned"] > 50
    assert art["baseline_errors"] == []
    # all twenty passes ran (the per-pass tally is the liveness proof)
    assert set(art["per_pass"]) == {
        "prints", "excepts", "flags", "purity", "races",
        "replay-determinism", "resource-lifecycle", "lock-order",
        "cross-share",
        "trace-safety", "static-hash", "dtype-domain",
        "twin-parity", "donation", "wire-contract",
        "device-scope", "collective-discipline", "shard-resource",
        "partition-contract", "scaling-math"}
    # every committed baseline entry must still match a real finding —
    # stale entries mean the code moved on and the baseline should shrink
    assert art["stale_baseline"] == [], (
        "stale baseline entries — delete them from analysis_baseline.json: "
        f"{art['stale_baseline']}")


def _analysis_json(*extra_args):
    """Run the analyzer; returns (proc, artifact, child CPU seconds).
    CPU seconds — not the artifact's wall-clock elapsed_s — feed the
    budget assertions (see ANALYZER_BUDGET_S: pin work, not speed)."""
    cpu0 = _child_cpu_s()
    proc = subprocess.run(
        [sys.executable, "-m", "rtap_tpu.analysis", "--json", *extra_args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    cpu = _child_cpu_s() - cpu0
    art = json.loads(proc.stdout.splitlines()[-1])["analysis"]
    return proc, art, cpu


def test_findings_cache_cold_vs_hit_identical_and_subsecond(tmp_path):
    """The ISSUE 13 cache contract, end to end: a cold run and the
    cache-hit run that follows must be FINDING-IDENTICAL (same artifact
    minus timing/cache-mode), and the hit must be sub-second — the
    whole point of hashing instead of re-parsing ~100 files."""
    cache = str(tmp_path / "lint_cache.json")
    _p1, art1, _cpu1 = _analysis_json("--cache-path", cache)
    _p2, art2, cpu2 = _analysis_json("--cache-path", cache)
    assert art1["cache"] == "cold"
    assert art2["cache"] == "hit"
    assert cpu2 < 1.0, (
        f"cache hit burned {cpu2:.2f} CPU s — the incremental path "
        "must stay sub-second")
    for volatile in ("elapsed_s", "cache"):
        art1.pop(volatile), art2.pop(volatile)
    assert art1 == art2, "cached run diverged from the cold run"


def test_findings_cache_invalidated_by_file_edit(tmp_path):
    """Stale-cache invalidation under the PASS-PARTITIONED cache
    (ISSUE 14): after a warm cache, ADDING a file with a violation must
    produce a re-run ("warm" — unchanged files replay their per-file
    pass findings, the new file and every whole-program pass run live)
    that REPORTS the violation — a cache that kept serving the old
    report would be a hole in the gate."""
    cache = str(tmp_path / "lint_cache.json")
    _analysis_json("--cache-path", cache)          # warm it
    subdir = os.path.join(REPO, "rtap_tpu", "obs")
    victim = os.path.join(subdir, "_gate_canary_cache.py")
    with open(victim, "w") as f:
        f.write('import sys\nprint("x", file=sys.stderr)\n')
    try:
        proc, art, _cpu = _analysis_json("--cache-path", cache)
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert art["cache"] == "warm"
    assert any(f["path"].endswith("_gate_canary_cache.py")
               for f in art["findings"])
    # ... and reverting the edit re-runs again (file-set hash): the
    # next run is live and green, not a stale red replay
    proc3, art3, _cpu3 = _analysis_json("--cache-path", cache)
    assert proc3.returncode == 0 and art3["cache"] == "warm"
    # EDITING an existing file (content change, same file set) must
    # also re-run — the per-file content hash, not the path list, is
    # the freshness judge
    target = os.path.join(REPO, "rtap_tpu", "utils", "measure.py")
    with open(target, encoding="utf-8") as f:
        original = f.read()
    with open(target, "a", encoding="utf-8") as f:
        f.write("\n# cache-invalidation canary (comment only)\n")
    try:
        _proc4, art4, _cpu4 = _analysis_json("--cache-path", cache)
    finally:
        with open(target, "w", encoding="utf-8") as f:
            f.write(original)
    assert art4["cache"] == "warm"


def test_findings_cache_warm_equals_cold_and_meets_budget(tmp_path):
    """The ISSUE 14 pass-partition contract, end to end: a one-file
    edit after a warm cache must (a) produce the same findings picture
    as a from-scratch cold run of the same tree, and (b) come back
    under the ~2 s warm budget — the point of partitioning with
    twenty passes live."""
    cache = str(tmp_path / "lint_cache.json")
    _analysis_json("--cache-path", cache)          # prime
    target = os.path.join(REPO, "rtap_tpu", "utils", "measure.py")
    with open(target, encoding="utf-8") as f:
        original = f.read()
    with open(target, "a", encoding="utf-8") as f:
        f.write("\n# warm-budget canary (comment only)\n")
    try:
        _p, warm, warm_cpu = _analysis_json("--cache-path", cache)
        _p2, cold, _cold_cpu = _analysis_json("--no-cache")
    finally:
        with open(target, "w", encoding="utf-8") as f:
            f.write(original)
    assert warm["cache"] == "warm"
    # 3.0 s: the v3 budget was 2.0 with fifteen passes; the ISSUE 15
    # mesh model + two new program passes (partition-contract,
    # scaling-math) add ~0.4 s of per-warm-run work that per-file
    # partitioning cannot elide (their inputs are cross-file by nature)
    assert warm_cpu < 3.0, (
        f"warm run burned {warm_cpu:.2f} CPU s — per-file pass reuse "
        "must keep incremental runs fast")
    for volatile in ("elapsed_s", "cache"):
        warm.pop(volatile), cold.pop(volatile)
    assert warm == cold, "warm partial-reuse run diverged from cold"


def test_sarif_artifact_shape(tmp_path):
    """--sarif writes a SARIF 2.1.0 log beside the one-line --json
    contract: version/schema pinned, every rule listed, results carry
    a physical location and the stable (rule,path,symbol) fingerprint,
    suppressed/baselined findings ride along as suppressions."""
    out = tmp_path / "lint.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "rtap_tpu.analysis", "--json",
         "--no-cache", "--sarif", str(out)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # stdout still exactly one line — SARIF must not leak onto it
    assert len([ln for ln in proc.stdout.splitlines() if ln.strip()]) == 1
    sarif = json.loads(out.read_text())
    assert sarif["version"] == "2.1.0"
    assert sarif["$schema"].endswith("sarif-2.1.0.json")
    run = sarif["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "rtap-lint"
    rule_ids = {r["id"] for r in driver["rules"]}
    # the rules section is generated from ALL_RULES, so new passes are
    # covered automatically — the v3 ids prove it
    for rid in ("race", "lock-order", "cross-share",
                "replay-determinism", "resource-lifecycle",
                "print-strict", "parse-error",
                "twin-parity", "trace-safety", "donate-read",
                "static-hash", "jit-churn", "dtype-domain",
                "wire-contract"):
        assert rid in rule_ids
    assert run["results"], "green tree still carries suppressed/baselined"
    for res in run["results"]:
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
        assert "rtapLintKey/v1" in res["partialFingerprints"]
    # the gate is green, so every result must be a suppression carrier
    assert all("suppressions" in r for r in run["results"])


def _canary_bites(subdir_parts, name, code, expect):
    """Drop a violating file into the tree, assert the gate goes red
    naming it — per-pass end-to-end canaries (the fixture tests prove
    the library; these prove the gate stays ARMED). Invokes the
    analyzer directly (its exit code IS the gate check_static.sh
    wraps) to keep the canary fleet inside the tier-1 time budget."""
    subdir = os.path.join(REPO, *subdir_parts)
    victim = os.path.join(subdir, name)
    with open(victim, "w") as f:
        f.write(code)
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "rtap_tpu.analysis", "--no-cache"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert expect in proc.stdout + proc.stderr


def test_lock_order_canary_bites_end_to_end():
    _canary_bites(
        ("rtap_tpu", "resilience"), "_gate_canary_lo.py",
        "import threading\n\n\n"
        "class Knot:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n\n"
        "    def one(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:\n"
        "                pass\n\n"
        "    def two(self):\n"
        "        with self._b_lock:\n"
        "            with self._a_lock:\n"
        "                pass\n",
        "Knot._a_lock->Knot._b_lock->Knot._a_lock")


def test_cross_share_canary_bites_end_to_end():
    _canary_bites(
        ("rtap_tpu", "service"), "_gate_canary_cs.py",
        "import threading\n\n\n"
        "class CanaryTracker:\n"
        "    def __init__(self):\n"
        "        self.hits = 0\n\n"
        "    def fold(self):\n"
        "        self.hits += 1\n\n"
        "    def snapshot(self):\n"
        "        return self.hits\n\n\n"
        "class CanaryRunner:\n"
        "    def __init__(self, tracker):\n"
        "        self.tracker = tracker\n\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._run, name='rtap-cs',\n"
        "                         daemon=True).start()\n\n"
        "    def _run(self):\n"
        "        pass\n\n\n"
        "def wire(consume):\n"
        "    t = CanaryTracker()\n"
        "    r = CanaryRunner(t)\n"
        "    consume(t)\n"
        "    return r\n",
        "CanaryTracker.hits")


def test_replay_determinism_canary_bites_end_to_end():
    _canary_bites(
        ("rtap_tpu", "correlate"), "_gate_canary_rd.py",
        "def emit(fh):\n"
        "    acc = set()\n"
        "    acc.add('x')\n"
        "    for item in acc:\n"
        "        fh.write(item)\n",
        "emit:set-iter")


def test_resource_lifecycle_canary_bites_end_to_end():
    _canary_bites(
        ("rtap_tpu", "obs"), "_gate_canary_rl.py",
        "import threading\n\n\n"
        "class Leaky:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._run,\n"
        "                                   name='rtap-rl', daemon=True)\n"
        "        self._t.start()\n\n"
        "    def _run(self):\n"
        "        pass\n",
        "Leaky._t")


def test_race_canary_bites_end_to_end():
    """A deliberately racy class dropped into the serve stack must fail
    the whole gate (the ISSUE 12 acceptance shape: the analyzer, not a
    reviewer, catches the next Lease.set_meta-class bug)."""
    subdir = os.path.join(REPO, "rtap_tpu", "resilience")
    victim = os.path.join(subdir, "_gate_canary_r.py")
    with open(victim, "w") as f:
        f.write(
            "import threading\n\n\n"
            "class Racy:\n"
            "    def __init__(self):\n"
            "        self.n = 0\n"
            "        self._lock = threading.Lock()\n\n"
            "    def start(self):\n"
            "        t = threading.Thread(target=self._run,\n"
            "                             name='rtap-canary-r', daemon=True)\n"
            "        t.start()\n\n"
            "    def _run(self):\n"
            "        self.n += 1\n\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self.n += 1\n"
        )
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "Racy.n" in proc.stdout + proc.stderr


# ---- ISSUE 14: the device-kernel pass family stays ARMED end to end ----

def test_twin_parity_canary_bites_end_to_end():
    """An untwinned public kernel dropped into ops/ fails the gate —
    the acceptance shape: removing a kernel's oracle (or its parity
    test) is an analyzer failure, not a review catch."""
    _canary_bites(
        ("rtap_tpu", "ops"), "_gate_canary_tp.py",
        "import jax.numpy as jnp\n\n\n"
        "def phantom_kernel(x):\n"
        "    return jnp.sum(x)\n",
        "phantom_kernel:untwinned")


def test_traced_if_canary_bites_end_to_end():
    """The traced-`if` canary (ISSUE 14 satellite): data-dependent
    Python control flow in a kernel fails the gate."""
    _canary_bites(
        ("rtap_tpu", "ops"), "_gate_canary_ts.py",
        "import jax.numpy as jnp\n\n\n"
        "def leaky_kernel(x: jnp.ndarray):\n"
        "    y = jnp.sum(x)\n"
        "    if y > 0:\n"
        "        return y\n"
        "    return -y\n",
        "leaky_kernel:if-on-traced:y")


def test_donated_read_canary_bites_end_to_end():
    """The donated-read canary (ISSUE 14 satellite): reading a buffer
    after donating it to a jit wrapper — garbage on TPU, invisible to
    CPU tier-1 — fails the gate."""
    _canary_bites(
        ("rtap_tpu", "service"), "_gate_canary_dr.py",
        "from functools import partial\n\nimport jax\n\n\n"
        "@partial(jax.jit, donate_argnums=(0,))\n"
        "def _canary_burn(state):\n"
        "    return state\n\n\n"
        "def leak(state):\n"
        "    out = _canary_burn(state)\n"
        "    return state, out\n",
        "leak:state@_canary_burn")


def test_jit_churn_canary_bites_end_to_end():
    _canary_bites(
        ("scripts",), "_gate_canary_sh.py",
        "import jax\n\n\n"
        "def churn(fns):\n"
        "    for fn in fns:\n"
        "        g = jax.jit(fn)\n"
        "    return g\n",
        "churn:jit-loop")


def test_dtype_domain_canary_bites_end_to_end():
    _canary_bites(
        ("rtap_tpu", "ops"), "_gate_canary_dd.py",
        "# rtap: domain[pa=u8, pb=u16]\n"
        "import jax.numpy as jnp\n\n\n"
        "def mixer(pa, pb):\n"
        "    return jnp.sum(pa + pb)\n",
        "mixer:mix:u16~u8")


def test_wire_contract_canary_bites_end_to_end():
    """A second framing reusing the journal's RJ magic (and narrowing
    its documented len field) must fail against the REAL docs — the
    seeded-drift acceptance criterion."""
    _canary_bites(
        ("rtap_tpu", "resilience"), "_gate_canary_wc.py",
        "import struct\n\n"
        "_MAGIC = b\"RJ\"\n"
        "_HEADER = struct.Struct(\"<2sBH\")  # magic, type, len\n",
        "magic:RJ")


# ---- ISSUE 15: the mesh-readiness pass family stays ARMED end to end ----

def test_collective_in_scan_canary_bites_end_to_end():
    """The seeded collective-in-scan canary (ISSUE 15 acceptance): a
    psum inside a chunk-scan body dropped into ops/ fails the gate —
    sharded_chunk_step's collective-free property is a permanent gate,
    not an inspection result."""
    _canary_bites(
        ("rtap_tpu", "ops"), "_gate_canary_cd.py",
        "import jax\nimport jax.numpy as jnp\n\n\n"
        "def sneaky_chunk(state, values):\n"
        "    def body(s, v):\n"
        "        coupled = jax.lax.psum(v, axis_name='streams')\n"
        "        return s, coupled\n"
        "    return jax.lax.scan(body, state, values)\n",
        "collective:psum")


def test_unannotated_leaf_canary_bites_end_to_end():
    """The unannotated-leaf canary (ISSUE 15 acceptance): a new state
    tree in models/ whose leaves carry no partition rules fails the
    gate — a brand-new subsystem cannot dodge the contract by not
    opting in (constructor discovery is structural)."""
    _canary_bites(
        ("rtap_tpu", "models"), "_gate_canary_pc.py",
        "import numpy as np\n\n\n"
        "def init_canary_tree(n):\n"
        "    return {\n"
        "        'canary_a': np.zeros(n, np.float32),\n"
        "        'canary_b': np.zeros(n, np.int32),\n"
        "        'canary_c': np.zeros(n, bool),\n"
        "    }\n",
        "init_canary_tree:unruled:canary_a")


def test_shard_resource_mint_canary_bites_end_to_end():
    """A serve-stack file minting a sidecar path by bare concat fails
    the gate — only service/shardpath.py may spell the suffixes, so a
    new call site cannot forget the shard."""
    _canary_bites(
        ("rtap_tpu", "service"), "_gate_canary_sr.py",
        "def sidecar_for(alert_path):\n"
        "    return alert_path + '.corr'\n",
        "sidecar_for:mint")


def test_device_scope_canary_bites_end_to_end():
    """A devices()[0] read dropped into the serve stack fails the gate
    (the loop.py:_occupancy class this PR fixed, pinned armed)."""
    _canary_bites(
        ("rtap_tpu", "obs"), "_gate_canary_ds.py",
        "def probe():\n"
        "    import jax\n\n"
        "    return jax.local_devices()[0].memory_stats()\n",
        "probe:device0")


def test_scaling_math_canary_bites_end_to_end():
    """Staling SCALING.md's analytic table (a config edit without a
    scaling_law.py re-run) fails the gate: the doc's memory twin. The
    canary perturbs ONE digit of the committed bytes/stream table in
    place and restores it byte-exactly."""
    import re

    scaling = os.path.join(REPO, "SCALING.md")
    with open(scaling, encoding="utf-8") as f:
        original = f.read()
    doctored, n = re.subn(r"\| u16 quanta \| 302,101 \|",
                          "| u16 quanta | 302,102 |", original, count=1)
    assert n == 1, "SCALING.md analytic table row moved — update canary"
    with open(scaling, "w", encoding="utf-8") as f:
        f.write(doctored)
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "rtap_tpu.analysis", "--no-cache"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
    finally:
        with open(scaling, "w", encoding="utf-8") as f:
            f.write(original)
    assert proc.returncode != 0
    assert "bytes:u16" in proc.stdout + proc.stderr
