"""scripts/check_static.sh rides tier-1: compileall over rtap_tpu AND
scripts/ + bench.py, plus the AST print-gate — NO print() in the serve
stack (service/obs/resilience: telemetry goes through rtap_tpu.obs, never
ad-hoc stdout lines the harness would have to scrape), and everywhere else
in the package/scripts a print() must either target an explicit stream
(file=) or be the sanctioned one-JSON-line artifact emission
(json.dumps/.to_json single argument)."""

import glob
import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run():
    return subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "check_static.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )


def _cleanup(victim, subdir):
    os.remove(victim)
    # the script's compileall step byte-compiles the canary before the
    # print gate fails — drop the orphaned pyc too, not just the source
    base = os.path.splitext(os.path.basename(victim))[0]
    for pyc in glob.glob(os.path.join(subdir, "__pycache__", base + "*")):
        os.remove(pyc)


def test_check_static_passes():
    proc = _run()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check_static: OK" in proc.stdout


def test_print_gate_bites_in_serve_stack():
    """The strict gate must fail on ANY print( in service/ — even one
    aimed at stderr (guard the guard: a checker regression could silently
    let prints back into the serve stack)."""
    subdir = os.path.join(REPO, "rtap_tpu", "service")
    victim = os.path.join(subdir, "_gate_canary.py")
    with open(victim, "w") as f:
        f.write('import sys\nprint("scraped", file=sys.stderr)\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary" in proc.stdout + proc.stderr


def test_print_gate_bites_in_obs():
    """The strict gate covers rtap_tpu/obs/ too — the tracing/flight
    modules (ISSUE 4) live there, and a postmortem path that printed to
    stdout would corrupt the one-JSON-line serve artifact contract."""
    subdir = os.path.join(REPO, "rtap_tpu", "obs")
    victim = os.path.join(subdir, "_gate_canary_o.py")
    with open(victim, "w") as f:
        f.write('import sys\nprint("trace", file=sys.stderr)\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary_o" in proc.stdout + proc.stderr


def test_print_gate_bites_in_scripts():
    """The widened gate (ISSUE 3 satellite) must catch a bare print in
    scripts/ — including the multi-line call form a line-grep cannot see —
    while leaving file=stderr diagnostics and JSON emission legal."""
    subdir = os.path.join(REPO, "scripts")
    victim = os.path.join(subdir, "_gate_canary_s.py")
    with open(victim, "w") as f:
        f.write('print(\n    "bare stdout"\n)\n')
    try:
        proc = _run()
    finally:
        _cleanup(victim, subdir)
    assert proc.returncode != 0
    assert "_gate_canary_s" in proc.stdout + proc.stderr
