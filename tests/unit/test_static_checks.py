"""scripts/check_static.sh rides tier-1: compileall over rtap_tpu plus the
no-bare-print gate for rtap_tpu/service/ (telemetry goes through
rtap_tpu.obs, never ad-hoc stdout lines the harness would have to scrape)."""

import glob
import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_check_static_passes():
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "check_static.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check_static: OK" in proc.stdout


def test_print_gate_actually_bites():
    """The grep gate must fail on a real bare print( — guard the guard
    (a pattern typo could silently let prints back into the service layer)."""
    victim = os.path.join(REPO, "rtap_tpu", "service", "_gate_canary.py")
    with open(victim, "w") as f:
        f.write('print("scraped-stdout telemetry")\n')
    try:
        proc = subprocess.run(
            ["bash", os.path.join(REPO, "scripts", "check_static.sh")],
            cwd=REPO, capture_output=True, text=True, timeout=300,
        )
    finally:
        os.remove(victim)
        # the script's compileall step byte-compiles the canary before the
        # grep gate fails — drop the orphaned pyc too, not just the source
        for pyc in glob.glob(os.path.join(
                REPO, "rtap_tpu", "service", "__pycache__", "_gate_canary*")):
            os.remove(pyc)
    assert proc.returncode != 0
    assert "_gate_canary" in proc.stdout + proc.stderr
