"""Unit tests for the tunnel-harvester ledger decisions (scripts/hw_watch.py).

The done.json ledger gates which hardware measurements the round presents
as evidence, across oscillating-tunnel retries AND agenda edits between
runs — the same test-the-measurement-machinery practice as
tests/unit/test_bench_logic.py. Pure logic; no subprocess, no backend.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", "scripts"))
from hw_watch import ledger_entry_for, pending_steps  # noqa: E402
from hw_session import pick_steps, step_budget, STEPS  # noqa: E402

S_A = ("probe", ["python", "scripts/probe.py"])
S_B = ("bench", ["python", "bench.py", "--x"], 1700.0)


def test_fresh_ledger_everything_pending():
    assert pending_steps([S_A, S_B], {}) == [S_A, S_B]


def test_completed_step_with_matching_cmd_not_pending():
    ledger = {"probe": {"rc": 0, "cmd": ["scripts/probe.py"]}}
    assert pending_steps([S_A, S_B], ledger) == [S_B]


def test_completed_step_with_changed_cmd_reruns():
    """A step redefined between runs (same name, new flags) must re-run;
    the old success is no evidence for the new config."""
    ledger = {"probe": {"rc": 0, "cmd": ["scripts/probe.py", "--old-flag"]}}
    assert pending_steps([S_A], ledger) == [S_A]
    assert ledger_entry_for(S_A, ledger) == {}


def test_legacy_entry_without_cmd_reruns():
    """Pre-cmd-ledger entries (no "cmd" key) are likewise no evidence."""
    ledger = {"probe": {"rc": 0}}
    assert pending_steps([S_A], ledger) == [S_A]


def test_gave_up_parks_only_the_same_cmd():
    """A step that exhausted attempts under OLD flags must not park its
    redefined replacement."""
    parked_same = {"probe": {"rc": -1, "gave_up": True, "cmd": ["scripts/probe.py"]}}
    assert pending_steps([S_A], parked_same) == []
    parked_old = {"probe": {"rc": -1, "gave_up": True,
                            "cmd": ["scripts/probe.py", "--old"]}}
    assert pending_steps([S_A], parked_old) == [S_A]


def test_failed_but_not_gave_up_stays_pending():
    ledger = {"probe": {"rc": 113, "cmd": ["scripts/probe.py"]}}
    assert pending_steps([S_A], ledger) == [S_A]


def test_step_budget_default_and_override():
    assert step_budget(S_A, 700.0) == 700.0
    assert step_budget(S_B, 700.0) == 1700.0


def test_pick_steps_validates_range():
    assert pick_steps(None) == STEPS
    assert pick_steps("1") == [STEPS[0]]
    with pytest.raises(SystemExit):
        pick_steps("0")
    with pytest.raises(SystemExit):
        pick_steps(str(len(STEPS) + 1))
