"""Metric-catalog drift gate (ISSUE 6 satellite).

docs/TELEMETRY.md is the operator-facing catalog of every `rtap_obs_*`
instrument; it went stale twice in past PRs before anyone noticed.
This gate makes drift a test failure in BOTH directions:

- every metric name registered in code (rtap_tpu/, scripts/, bench.py)
  must appear in docs/TELEMETRY.md, and
- every metric name the catalog's tables document must exist in code
  (a doc row for a deleted metric is a lie operators will alert on).

Names are extracted as string literals — the codebase registers every
instrument with a literal name (a dynamically-built name would also be
un-greppable for operators, so the convention is load-bearing).
"""

import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_NAME = re.compile(r'"(rtap_obs_[a-z0-9_]+)"')
_DOC_NAME = re.compile(r"rtap_obs_[a-z0-9_]+")
# catalog table rows: | `rtap_obs_...` | type | ...
_DOC_ROW = re.compile(r"^\|\s*`(rtap_obs_[a-z0-9_]+)`", re.MULTILINE)


def _code_names() -> set[str]:
    names: set[str] = set()
    roots = [os.path.join(REPO, "rtap_tpu"), os.path.join(REPO, "scripts")]
    files = [os.path.join(REPO, "bench.py")]
    for root in roots:
        for dirpath, _dirs, fns in os.walk(root):
            files.extend(os.path.join(dirpath, fn)
                         for fn in fns if fn.endswith(".py"))
    for path in files:
        with open(path, encoding="utf-8") as f:
            names.update(_NAME.findall(f.read()))
    return names


def _doc_text() -> str:
    with open(os.path.join(REPO, "docs", "TELEMETRY.md"),
              encoding="utf-8") as f:
        return f.read()


@pytest.mark.quick
def test_every_registered_metric_is_documented():
    code = _code_names()
    assert code, "metric literal scan found nothing — the gate is broken"
    documented = set(_DOC_NAME.findall(_doc_text()))
    missing = sorted(code - documented)
    assert not missing, (
        f"metrics registered in code but absent from docs/TELEMETRY.md: "
        f"{missing} — add a catalog row (docs/TELEMETRY.md 'Adding a "
        "metric')")


@pytest.mark.quick
def test_every_documented_metric_exists_in_code():
    code = _code_names()
    rows = set(_DOC_ROW.findall(_doc_text()))
    assert rows, "catalog table scan found nothing — the gate is broken"
    stale = sorted(rows - code)
    assert not stale, (
        f"docs/TELEMETRY.md documents metrics no code registers: {stale} "
        "— drop the stale rows (or restore the instrument)")
