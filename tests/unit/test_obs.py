"""rtap_tpu.obs primitives: instrument semantics, exposition formats,
watchdog event detection, and the <= 1%-of-tick overhead bar.

The telemetry registry is the seam every serve-path hot loop emits through
(ISSUE 1 tentpole); these tests pin the parts the loop depends on blind:
Prometheus `le` bucket-edge semantics, snapshot idempotence (a scrape must
not perturb state), lock-free correctness under concurrent writer threads
(the dispatch pool emits), and the self-measured overhead budget.
"""

import json
import threading

import numpy as np
import pytest

from rtap_tpu.obs import (
    TelemetryRegistry,
    TickWatchdog,
    log_buckets,
    render_prometheus,
    summarize_snapshot,
)
from rtap_tpu.obs.selfbench import measure


# ---------------------------------------------------------- instruments ----


def test_counter_inc_and_monotonicity():
    reg = TelemetryRegistry()
    c = reg.counter("t_total")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_inc_dec():
    reg = TelemetryRegistry()
    g = reg.gauge("t_gauge")
    g.set(5)
    g.inc(2)
    g.dec(3)
    assert g.value == 4.0


def test_histogram_bucket_edges_le_semantics():
    """Prometheus `le` semantics: v lands in the FIRST bucket with v <= edge;
    values above the top edge land in +Inf."""
    reg = TelemetryRegistry()
    h = reg.histogram("t_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.100001, 1.0, 10.0, 10.1):
        h.observe(v)
    snap = h.snapshot_value()
    # cumulative counts at each edge
    assert snap["buckets"] == {
        "0.1": 2,        # 0.05, 0.1 (edge value is INCLUDED)
        "1.0": 4,        # + 0.100001, 1.0
        "10.0": 5,       # + 10.0
        "+Inf": 6,       # + 10.1
    }
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(21.350001)
    assert snap["min"] == pytest.approx(0.05)
    assert snap["max"] == pytest.approx(10.1)


def test_histogram_rejects_bad_buckets():
    reg = TelemetryRegistry()
    with pytest.raises(ValueError):
        reg.histogram("bad_seconds", buckets=(1.0, 1.0, 2.0))
    with pytest.raises(ValueError):
        reg.histogram("bad2_seconds", buckets=(2.0, 1.0))


def test_log_buckets_cover_tick_range():
    edges = log_buckets()
    assert edges[0] == pytest.approx(1e-3)
    assert edges[-1] == pytest.approx(10.0)
    assert all(b > a for a, b in zip(edges, edges[1:]))


def test_registry_get_or_create_and_type_conflict():
    reg = TelemetryRegistry()
    a = reg.counter("x_total", phase="source")
    b = reg.counter("x_total", phase="source")
    assert a is b  # cached per (name, labels): call sites may re-fetch
    c = reg.counter("x_total", phase="emit")
    assert c is not a  # distinct label set = distinct child
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # one name, one type


def test_snapshot_idempotent_and_json_serializable():
    """Two snapshots with no writes in between are identical (scraping must
    not perturb state), and the snapshot round-trips through json."""
    reg = TelemetryRegistry()
    reg.counter("a_total").inc(3)
    reg.gauge("b").set(1.5)
    h = reg.histogram("c_seconds", buckets=(0.5, 5.0))
    h.observe(0.2)
    s1, s2 = reg.snapshot(), reg.snapshot()
    assert s1["metrics"] == s2["metrics"]
    assert json.loads(json.dumps(s1))["metrics"] == s1["metrics"]


def test_concurrent_writers_lose_nothing():
    """8 threads hammering one counter and one histogram: the per-thread
    cell sharding must make every increment and observation land (the
    dispatch pool emits concurrently with the loop thread)."""
    reg = TelemetryRegistry()
    c = reg.counter("cc_total")
    h = reg.histogram("ch_seconds", buckets=(0.5, 5.0))
    n_threads, n_ops = 8, 5000

    def work():
        for _ in range(n_ops):
            c.inc()
            h.observe(0.1)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_ops
    assert h.count == n_threads * n_ops
    assert h.snapshot_value()["buckets"]["0.5"] == n_threads * n_ops


def test_registry_reset_zeroes_but_keeps_instruments():
    reg = TelemetryRegistry()
    c = reg.counter("r_total")
    c.inc(5)
    reg.reset()
    assert c.value == 0
    assert reg.counter("r_total") is c  # cached references stay valid
    c.inc()
    assert c.value == 1


# ----------------------------------------------------------- exposition ----


def test_prometheus_exposition_golden():
    """The exact text a scraper sees: HELP/TYPE headers, label rendering,
    cumulative histogram buckets, _sum/_count. Format drift breaks real
    Prometheus ingestion, so this is a golden comparison, not a grep."""
    reg = TelemetryRegistry()
    reg.counter("g_ticks_total", "ticks completed").inc(7)
    reg.gauge("g_streams", "live streams").set(3)
    h = reg.histogram("g_phase_seconds", "per-phase seconds",
                      buckets=(0.1, 1.0), phase="emit")
    h.observe(0.05)
    h.observe(0.05)
    h.observe(2.0)
    assert render_prometheus(reg) == (
        '# HELP g_phase_seconds per-phase seconds\n'
        '# TYPE g_phase_seconds histogram\n'
        'g_phase_seconds_bucket{phase="emit",le="0.1"} 2\n'
        'g_phase_seconds_bucket{phase="emit",le="1"} 2\n'
        'g_phase_seconds_bucket{phase="emit",le="+Inf"} 3\n'
        'g_phase_seconds_sum{phase="emit"} 2.1\n'
        'g_phase_seconds_count{phase="emit"} 3\n'
        '# HELP g_streams live streams\n'
        '# TYPE g_streams gauge\n'
        'g_streams 3\n'
        '# HELP g_ticks_total ticks completed\n'
        '# TYPE g_ticks_total counter\n'
        'g_ticks_total 7\n'
    )


def test_summarize_snapshot_flattens_for_artifacts():
    reg = TelemetryRegistry()
    reg.counter("s_total", phase="a").inc(2)
    h = reg.histogram("s_seconds", buckets=(1.0,))
    h.observe(0.5)
    h.observe(1.5)
    s = summarize_snapshot(reg.snapshot())
    assert s["s_total{phase=a}"] == 2
    assert s["s_seconds"]["count"] == 2
    assert s["s_seconds"]["mean"] == pytest.approx(1.0)
    assert s["s_seconds"]["max"] == pytest.approx(1.5)


# -------------------------------------------------------------- watchdog ----


def test_watchdog_missed_tick_detection():
    reg = TelemetryRegistry()
    events = []
    wd = TickWatchdog(1.0, registry=reg, event_sink=events.append)
    assert wd.observe_tick(0, 0.5) is False
    assert wd.observe_tick(1, 1.0) is False  # exactly on budget = made it
    assert wd.observe_tick(2, 1.25) is True
    assert reg.counter("rtap_obs_missed_ticks_total").value == 1
    assert events == [{"event": "missed_tick", "tick": 2,
                       "elapsed_s": 1.25, "cadence_s": 1.0}]


def test_watchdog_source_starvation_runs():
    reg = TelemetryRegistry()
    events = []
    wd = TickWatchdog(1.0, registry=reg, event_sink=events.append,
                      starved_after=3)
    nan3 = np.full(3, np.nan, np.float32)
    some = np.array([np.nan, 2.0, np.nan], np.float32)
    for k in range(2):
        wd.observe_source(k, nan3)
    assert events == []  # below the threshold: ordinary missing samples
    wd.observe_source(2, nan3)
    assert events == [{"event": "source_starved", "tick": 2,
                       "consecutive_ticks": 3}]
    wd.observe_source(3, some)  # ANY real value resets the run
    for k in range(4, 7):
        wd.observe_source(k, nan3)
    assert len(events) == 2 and events[1]["consecutive_ticks"] == 3


def test_watchdog_checkpoint_stall():
    reg = TelemetryRegistry()
    events = []
    wd = TickWatchdog(1.0, registry=reg, event_sink=events.append)
    wd.observe_checkpoint(5, 0.3)  # under budget: expected, no event
    wd.observe_checkpoint(9, 2.5)
    assert [e["event"] for e in events] == ["checkpoint_stall"]
    assert reg.counter("rtap_obs_watchdog_events_total",
                       event="checkpoint_stall").value == 1


# --------------------------------------------------------------- budget ----


def test_obs_overhead_within_one_percent_of_tick_budget():
    """Acceptance bar (ISSUE 1): a full tick's instrument traffic costs
    <= 1% of the 1 s cadence budget. Measured, not assumed — the same
    measurement bench.py --obs-bench ships. Typical hosts land 3-4 orders
    of magnitude under the bar, so this does not flake on slow CI."""
    res = measure(n=5000)
    assert res["per_tick_overhead_frac"] <= 0.01, res
