"""Held-out signal family (data/synthetic.py family="heldout") — the
external-validation world for the model-width quality claims (r4 verdict
"what's weak" #1). Pins: determinism, fault labeling parity with the tuned
family, genuinely different statistics (heavy tails), and that the tuned-on
"diurnal" family is bit-identical to before the family switch existed."""

import numpy as np
import pytest

from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_stream

HELD = SyntheticStreamConfig(length=1500, cadence_s=1.0, n_anomalies=2,
                             anomaly_magnitude=6.0, noise_phi=0.97,
                             noise_scale=0.5, inject_after_frac=0.4,
                             family="heldout")


def _kurt(x: np.ndarray) -> float:
    x = np.asarray(x, np.float64)
    x = x - x.mean()
    return float((x**4).mean() / (x**2).mean() ** 2 - 3)


def test_heldout_deterministic_and_labeled():
    a = generate_stream("node00001.cpu", HELD, seed=11)
    b = generate_stream("node00001.cpu", HELD, seed=11)
    np.testing.assert_array_equal(a.values, b.values)
    assert np.isfinite(a.values).all()
    assert len(a.windows) == 2 and len(a.events) == 2
    assert all(e.kind in ("spike", "level_shift", "drift", "stuck", "dropout")
               for e in a.events)
    c = generate_stream("node00001.cpu", HELD, seed=23)
    assert not np.array_equal(a.values, c.values)


def test_heldout_heavier_tails_than_diurnal():
    """The family must be a genuinely different world: per-tick deltas carry
    Student-t/burst tails (excess kurtosis far above the tuned family's
    near-Gaussian AR(1))."""
    import dataclasses

    held = generate_stream(
        "node00001.cpu", dataclasses.replace(HELD, n_anomalies=0), seed=11)
    diurnal = generate_stream(
        "node00001.cpu",
        dataclasses.replace(HELD, n_anomalies=0, family="diurnal"), seed=11)
    assert _kurt(np.diff(held.values)) > 5 * max(
        _kurt(np.diff(diurnal.values)), 1.0)


def test_diurnal_family_bit_identical_golden():
    """The default family's draw order is the regeneration contract for
    every committed artifact: pin a golden slice."""
    cfg = SyntheticStreamConfig(length=64, cadence_s=1.0, n_anomalies=0,
                                noise_phi=0.9)
    s = generate_stream("golden.cpu", cfg, seed=3)
    # golden values recorded at the family-switch commit (identical draw
    # order to the pre-switch generator)
    assert s.values[:4].tolist() == pytest.approx(
        [47.27411651611328, 48.14616012573242,
         47.246849060058594, 45.97713851928711], abs=0.0)
    assert float(s.values.astype(np.float64).sum()) == pytest.approx(
        2959.4722633361816, abs=1e-9)


def test_unknown_family_rejected():
    import dataclasses

    with pytest.raises(ValueError, match="family"):
        generate_stream(
            "x.cpu", dataclasses.replace(HELD, family="nope"), seed=1)
