"""obs/flight.py: the black-box flight recorder (ISSUE 4 tentpole).
Pins strictly bounded ring memory (ring size x record size — the ISSUE's
acceptance bullet), the atomic bundle layout + validate_bundle verdicts,
dump throttling (per-reason gap + per-run cap), and the miss-burst
trigger."""

import json
import os
import time

import pytest

from rtap_tpu.obs.flight import FlightRecorder, validate_bundle
from rtap_tpu.obs.metrics import TelemetryRegistry
from rtap_tpu.obs.trace import TraceRecorder

PHASES = ("source", "membership", "dispatch", "collect", "emit",
          "checkpoint")


def _phases(v=0.001):
    return {p: v for p in PHASES}


def _fill(fl, n, n_groups=3, missed=False, start=0):
    for k in range(start, start + n):
        fl.record_tick(k, 0.01, _phases(), [2] * n_groups, missed)


@pytest.mark.quick
def test_tick_ring_memory_is_strictly_bounded():
    fl = FlightRecorder(n_ticks=16, registry=TelemetryRegistry())
    _fill(fl, 100, n_groups=3)
    # ring size x record size, exactly: tick i64 + elapsed f64 + missed
    # bool + 6 phase f64 + 3 scored i64 per slot, REGARDLESS of how many
    # ticks were recorded (the black box can fly forever)
    per_record = 8 + 8 + 1 + len(PHASES) * 8 + 3 * 8
    assert fl.nbytes() == 16 * per_record
    s = fl.summary()
    assert s["ticks"]["count"] == 16
    assert s["ticks"]["first"] == 84 and s["ticks"]["last"] == 99
    assert s["scored_by_group_window"] == [32, 32, 32]


@pytest.mark.quick
def test_event_ring_is_bounded_and_truncated():
    fl = FlightRecorder(n_ticks=8, n_events=5, max_event_bytes=64,
                        registry=TelemetryRegistry())
    for i in range(20):
        fl.record_event({"event": "missed_tick", "tick": i,
                         "blob": "x" * 1000})
    assert len(fl._events) == 5
    assert all(len(line) <= 64 for line in fl._events)
    s = fl.summary()
    assert s["events"]["total_seen"] == 20
    assert s["events"]["by_kind"] == {"missed_tick": 20}


@pytest.mark.quick
def test_dump_writes_atomic_valid_bundle(tmp_path):
    tr = TraceRecorder(capacity=256)
    t0 = time.perf_counter()
    reg = TelemetryRegistry()
    fl = FlightRecorder(trace=tr, n_ticks=8, out_dir=str(tmp_path),
                        registry=reg, info={"command": "test"})
    for k in range(6):
        tr.add_span("tick", k, t0 + k * 0.01, 0.009)
        fl.record_tick(k, 0.009, _phases(), [4, 4], k == 5)
    tr.add_instant("group_quarantined", 5, {"group": 1})
    fl.record_event({"event": "group_quarantined", "tick": 5, "group": 1})
    path = fl.dump("group_quarantined", 5)
    assert path is not None and os.path.isdir(path)
    # atomic: no torn temp dirs left behind
    assert not [d for d in os.listdir(tmp_path) if d.startswith(".tmp")]
    v = validate_bundle(path)
    assert v["ok"], v
    assert v["spans"] == 6 and v["instants"] == 1 and v["events"] == 1
    assert v["reason"] == "group_quarantined" and v["tick"] == 5
    summary = json.load(open(os.path.join(path, "summary.json")))
    assert summary["info"]["command"] == "test"
    assert summary["ticks"]["missed"] == 1
    assert summary["phase_ms"]["dispatch"]["mean"] == pytest.approx(1.0)
    # the registry counters moved
    assert reg.counter("rtap_obs_postmortem_bundles_total",
                       reason="group_quarantined").value == 1


@pytest.mark.quick
def test_dump_throttling_per_reason_gap_and_run_cap(tmp_path):
    fl = FlightRecorder(trace=TraceRecorder(capacity=32), n_ticks=8,
                        out_dir=str(tmp_path), registry=TelemetryRegistry(),
                        min_dump_gap_ticks=10, max_bundles=2)
    _fill(fl, 3)
    assert fl.dump("group_quarantined", 2) is not None
    # same reason within the gap: suppressed
    assert fl.dump("group_quarantined", 5) is None
    # different reason: its own gap clock
    assert fl.dump("missed_tick_burst", 5) is not None
    # run cap reached: everything suppressed from here
    assert fl.dump("group_quarantined", 50) is None
    assert fl.dumps_skipped == 2
    assert len(fl.bundles) == 2


@pytest.mark.quick
def test_miss_burst_queues_one_dump_per_episode(tmp_path):
    tr = TraceRecorder(capacity=32)
    fl = FlightRecorder(trace=tr, n_ticks=32,
                        out_dir=str(tmp_path), registry=TelemetryRegistry(),
                        miss_burst=3)
    tr.add_span("tick", 0, time.perf_counter(), 0.01)
    _fill(fl, 2, missed=False)
    _fill(fl, 5, missed=True, start=2)  # one burst, however long
    assert [r for r, _ in fl._pending] == ["missed_tick_burst"]
    paths = fl.flush_pending()
    assert len(paths) == 1 and fl._pending == []
    v = validate_bundle(paths[0])
    assert v["ok"] and v["reason"] == "missed_tick_burst"


@pytest.mark.quick
def test_crash_dump_is_exempt_from_cap_and_gap(tmp_path):
    """Review fix: a soak that spent its bundle budget on quarantine
    churn must STILL leave its crash black box — unhandled_exception
    bypasses both the per-run cap and the per-reason gap."""
    tr = TraceRecorder(capacity=32)
    tr.add_span("tick", 0, time.perf_counter(), 0.01)
    fl = FlightRecorder(trace=tr, n_ticks=8, out_dir=str(tmp_path),
                        registry=TelemetryRegistry(), max_bundles=1,
                        min_dump_gap_ticks=100)
    _fill(fl, 3)
    assert fl.dump("group_quarantined", 1) is not None  # cap reached
    assert fl.dump("group_quarantined", 2) is None
    p = fl.dump("unhandled_exception", 2)
    assert p is not None and validate_bundle(p)["ok"]


@pytest.mark.quick
def test_rerun_into_same_dir_never_collides(tmp_path):
    """Review fix: bundle names carry a per-run tag — a re-run pointed
    at the same --postmortem-dir (hw_session hardcodes its dir) must
    dump its own bundle even at the same deterministic tick/reason,
    never os.rename onto the prior run's directory."""
    for pass_n in (1, 2):
        tr = TraceRecorder(capacity=32)
        tr.add_span("tick", 0, time.perf_counter(), 0.01)
        fl = FlightRecorder(trace=tr, n_ticks=8, out_dir=str(tmp_path),
                            registry=TelemetryRegistry())
        fl._run_tag = f"run{pass_n}"  # distinct runs (time+pid in prod)
        _fill(fl, 3)
        assert fl.dump("missed_tick_burst", 2) is not None
    bundles = [d for d in os.listdir(tmp_path) if not d.startswith(".tmp")]
    assert len(bundles) == 2  # both runs' evidence retained


@pytest.mark.quick
def test_dump_without_out_dir_is_a_counted_noop():
    fl = FlightRecorder(n_ticks=4, registry=TelemetryRegistry())
    _fill(fl, 2)
    assert fl.dump("on_demand") is None
    assert fl.dumps_skipped == 1


@pytest.mark.quick
def test_validate_bundle_rejects_garbage(tmp_path):
    v = validate_bundle(str(tmp_path / "missing"))
    assert not v["ok"]
    bad = tmp_path / "bundle"
    bad.mkdir()
    (bad / "summary.json").write_text("{not json")
    (bad / "events.jsonl").write_text('{"event": "x"}\n')
    (bad / "trace.json").write_text('{"traceEvents": []}')
    v = validate_bundle(str(bad))
    assert not v["ok"]
    assert any("summary.json" in p for p in v["problems"])
    assert any("no spans" in p for p in v["problems"])


@pytest.mark.quick
def test_dump_concurrent_with_event_appends(tmp_path):
    """ISSUE 13 cross-share regression: the dump path must materialize
    the event deque (one C-level list() copy) before iterating —
    iterating the LIVE deque while the loop thread appends raises
    RuntimeError mid-dump and kills the postmortem it was writing. A
    tiny GIL switch interval makes the pre-fix race land reliably."""
    import sys
    import threading

    fl = FlightRecorder(n_ticks=16, out_dir=str(tmp_path),
                        registry=TelemetryRegistry(), n_events=4096,
                        min_dump_gap_ticks=0, max_bundles=10_000)
    _fill(fl, 4)
    for k in range(2000):  # pre-load so every dump iterates a long ring
        fl.record_event({"event": f"k{k % 17}", "n": k})
    stop = threading.Event()

    def _writer():
        k = 0
        while not stop.is_set():
            fl.record_event({"event": f"w{k % 13}", "n": k})
            k += 1

    t = threading.Thread(target=_writer, name="rtap-test-eventwriter",
                         daemon=True)
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)
    try:
        t.start()
        for i in range(30):
            assert fl.dump("concurrency", i) is not None
    finally:
        stop.set()
        t.join(timeout=5.0)
        sys.setswitchinterval(old)
    assert len(fl.bundles) == 30
