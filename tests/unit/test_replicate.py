"""Unit coverage for hot-standby replication (ISSUE 8).

The failover soak (tests/integration/test_failover.py) owns the
end-to-end kill-9 verdict; these tests pin the components: the lease's
acquire/fence/heartbeat semantics, the RJ wire walker's torn/corrupt
tolerance, the sender's bounded drop-oldest buffer and compaction
clamp (the PR 5 pause rule applied to replication), the chaos wire
fault kinds (digest-stable for existing seeds, fire-once on retry),
the in-process leader->standby apply path (bit-identical state, alert
buffering pruned by cursors), and the compaction-gap ->
full-checkpoint-fetch fallback.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset
from rtap_tpu.resilience.chaos import (
    FAULT_KINDS,
    GENERATED_KINDS,
    ChaosEngine,
    ChaosSpec,
    Fault,
)
from rtap_tpu.resilience.journal import (
    TickJournal,
    first_journal_tick,
    iter_raw_records,
)
from rtap_tpu.resilience.replicate import (
    WIRE_ACK,
    WIRE_HELLO,
    WIRE_SNAP,
    Lease,
    ReplicationSender,
    StandbyFollower,
    WireWalker,
    pack_wire,
)
from rtap_tpu.service.loop import live_loop
from rtap_tpu.service.registry import StreamGroupRegistry

pytestmark = pytest.mark.quick


def _reg(n=4, gs=2, threshold=-1e9):
    reg = StreamGroupRegistry(cluster_preset(), group_size=gs,
                              backend="cpu", threshold=threshold,
                              debounce=1)
    for i in range(n):
        reg.add_stream(f"s{i}")
    reg.finalize()
    return reg


def _row(seed, k, n):
    rng = np.random.Generator(np.random.Philox(key=(seed, k)))
    return (30 + 5 * rng.random(n)).astype(np.float32), 1_700_000_000 + k


def _state_fingerprint(grp):
    out = {"ticks": grp.ticks}
    for g, st in enumerate(grp._states):
        for k, v in st.items():
            out[f"s{g}/{k}"] = np.asarray(v)
    for k, v in grp.likelihood.state_dict().items():
        out[f"lik/{k}"] = np.asarray(v)
    return out


def _assert_groups_equal(a, b):
    for ga, gb in zip(a.groups, b.groups):
        fa, fb = _state_fingerprint(ga), _state_fingerprint(gb)
        assert sorted(fa) == sorted(fb)
        for k in fa:
            np.testing.assert_array_equal(np.asarray(fa[k]),
                                          np.asarray(fb[k]), err_msg=k)


# ---------------------------------------------------------------- lease
def test_lease_acquire_refresh_and_fence(tmp_path):
    path = tmp_path / "lease"
    a = Lease(path, "A", timeout_s=0.4)
    assert a.try_acquire()
    assert a.epoch == 1
    assert a.refresh()
    # a fresh foreign lease refuses a second owner
    b = Lease(path, "B", timeout_s=0.4)
    assert not b.try_acquire()
    assert not b.is_stale()
    # staleness admits the takeover and BUMPS the epoch (the fence)
    time.sleep(0.5)
    assert b.is_stale()
    assert b.try_acquire()
    assert b.epoch == 2
    # the old holder is fenced — sticky, on both probes
    assert not a.refresh()
    assert a.fenced
    assert not a.still_mine()
    # and a fenced lease can never re-acquire
    assert not a.try_acquire()
    # the file records the winner
    assert b.holder() == "B"
    assert json.loads(path.read_text())["epoch"] == 2


def test_lease_heartbeat_keeps_it_fresh_through_a_stall(tmp_path):
    path = tmp_path / "lease"
    a = Lease(path, "A", timeout_s=0.4)
    assert a.try_acquire()
    a.start_heartbeat()
    try:
        b = Lease(path, "B", timeout_s=0.4)
        # the OWNER thread does nothing for 3x the timeout — liveness
        # must come from the heartbeat thread, not the tick loop
        deadline = time.monotonic() + 1.2
        while time.monotonic() < deadline:
            assert not b.is_stale()
            time.sleep(0.1)
        assert not b.try_acquire()
    finally:
        a.stop_heartbeat()


def test_woken_zombie_heartbeat_never_clobbers_the_new_leader(tmp_path):
    path = tmp_path / "lease"
    a = Lease(path, "A", timeout_s=0.3)
    assert a.try_acquire()
    b = Lease(path, "B", timeout_s=0.3)
    time.sleep(0.4)
    assert b.try_acquire()  # epoch 2
    # A "wakes up": its next refresh must fence, not overwrite
    assert not a.refresh()
    cur = json.loads(path.read_text())
    assert cur["owner"] == "B" and cur["epoch"] == 2


def test_lease_acquire_over_unreadable_file_still_bumps_past_leader(
        tmp_path):
    """An acquire whose read finds the file missing/unreadable must
    bump past the highest epoch EVER OBSERVED, never restart at 1 —
    restarting would invert the fence (the old leader at epoch N>1
    keeps serving, the promoted standby fences itself)."""
    path = tmp_path / "lease"
    # a leader several failovers in: epoch 7, stalled past the timeout
    path.write_text(json.dumps(
        {"epoch": 7, "owner": "A", "ts": time.time() - 9.0}))
    a = Lease(path, "A", timeout_s=0.3)
    a.epoch = 7
    b = Lease(path, "B", timeout_s=0.3)
    assert b.is_stale()  # B OBSERVES epoch 7 via this read
    path.unlink()  # transient shared-fs fault at the worst moment
    assert b.try_acquire()
    assert b.epoch == 8  # bumped past the observed epoch, not reset to 1
    assert not a.refresh()
    assert a.fenced


def test_lease_set_meta_is_safe_under_a_live_heartbeat(tmp_path):
    """set_meta rebinds (never mutates) the meta dict: an in-place
    insert racing the heartbeat thread's ``{**self.meta}`` unpack would
    raise and silently kill the thread."""
    path = tmp_path / "lease"
    a = Lease(path, "A", timeout_s=0.4)
    assert a.try_acquire()
    a.start_heartbeat()
    try:
        for i in range(200):
            a.set_meta(**{f"k{i % 7}": i, "ingest": f"h:{i}"})
        time.sleep(0.3)  # a few heartbeat periods with churned meta
        assert a._hb_thread.is_alive()
        assert json.loads(path.read_text())["ingest"] == "h:199"
    finally:
        a.stop_heartbeat()


# ----------------------------------------------------------- wire layer
def test_wire_walker_roundtrip_torn_and_corrupt():
    w = WireWalker()
    recs = [pack_wire(WIRE_HELLO, np.int64(7).tobytes()),
            pack_wire(WIRE_ACK, np.int64(9).tobytes()),
            pack_wire(WIRE_SNAP, np.int64(3).tobytes())]
    blob = b"".join(recs)
    # torn delivery: byte-at-a-time still yields every record in order
    out = []
    for i in range(len(blob)):
        out += w.feed(blob[i:i + 1])
    assert [t for t, _p in out] == [WIRE_HELLO, WIRE_ACK, WIRE_SNAP]
    assert w.garbage_bytes == 0 and w.bad_crc == 0
    # a corrupt record is skipped by CRC; the NEXT record still parses
    bad = bytearray(recs[0])
    bad[len(bad) // 2] ^= 0xFF
    out = w.feed(bytes(bad) + recs[1])
    assert [t for t, _p in out] == [WIRE_ACK]
    assert w.bad_crc + (1 if w.garbage_bytes else 0) >= 1
    # pure garbage resyncs without emitting records
    out = w.feed(b"x" * 64 + recs[2])
    assert [t for t, _p in out] == [WIRE_SNAP]
    assert w.garbage_bytes >= 64


def test_journal_tee_ships_exact_record_bytes(tmp_path):
    shipped = []
    j = TickJournal(tmp_path / "j")
    j.tee = lambda typ, tick, rec: shipped.append((typ, tick, rec))
    j.append_tick(0, 100, np.arange(3, dtype=np.float32))
    j.append_cursor(0, 55)
    j.append_tick_frames(1, 101, 3, [b"rawframe"])
    j.close()
    assert [(t, k) for t, k, _r in shipped] == [(1, 0), (2, 0), (3, 1)]
    # the teed bytes ARE the on-disk bytes (the mirror is byte-exact)
    disk = [rec for _t, _k, rec in iter_raw_records(tmp_path / "j", 0)]
    assert disk == [r for _t, _k, r in shipped]
    # and the wire walker accepts them as-is
    w = WireWalker()
    out = w.feed(b"".join(r for _t, _k, r in shipped))
    assert [t for t, _p in out] == [1, 2, 3]


# ------------------------------------------------- sender buffer + clamp
def test_sender_buffer_is_bounded_drop_oldest(tmp_path):
    j = TickJournal(tmp_path / "j")
    # nothing listening on a closed port: the sender can never drain
    s = ReplicationSender(("127.0.0.1", 1), j, max_buffer=16)
    for k in range(100):
        s.tee(1, k, b"x" * 20)
    assert len(s._q) == 16
    assert s.dropped_records == 84
    # drop-oldest: the newest records survive
    assert [t for _typ, t, _r in s._q] == list(range(84, 100))
    j.close()


def test_compaction_clamped_to_standby_ack(tmp_path):
    # tiny segments force rotation so compact() has segments to drop
    j = TickJournal(tmp_path / "j", segment_bytes=1024)
    row = np.arange(64, dtype=np.float32)
    for k in range(40):
        j.append_tick(k, 100 + k, row)
    s = ReplicationSender(("127.0.0.1", 1), j, max_buffer=64)
    j.compact_floor = s.compact_floor
    # CONNECTED and lagging: the pause rule — nothing the standby has
    # not acked past may be dropped, whatever the checkpoints say
    s.connected = True
    s.acked_tick = 5
    j.compact(40)
    assert first_journal_tick(tmp_path / "j") <= 6
    # the standby catches up: compaction may proceed
    s.acked_tick = 39
    j.compact(30)
    assert first_journal_tick(tmp_path / "j") >= 7
    # DISCONNECTED: the clamp lifts entirely (bounded disk growth; a
    # reconnect past the gap takes the checkpoint-fetch fallback)
    s.connected = False
    j.compact(40)
    assert j.stats()["segments"] <= 2
    j.close()


# -------------------------------------------------- chaos wire faults
def test_generated_kinds_exclude_wire_and_proc_exit_kinds():
    for kind in ("proc_exit", "conn_drop", "stall_socket",
                 "corrupt_bytes"):
        assert kind in FAULT_KINDS
        assert kind not in GENERATED_KINDS
    # the pre-ISSUE-8 digest pin: adding kinds must not shift existing
    # seeds' generated schedules
    assert ChaosSpec.generate(seed=3, n_ticks=40,
                              n_groups=2).digest() == "b804a3aefde807d4"


def test_on_wire_faults_fire_once_per_scheduled_fault():
    spec = ChaosSpec(faults=[Fault(kind="conn_drop", tick=3),
                             Fault(kind="corrupt_bytes", tick=5),
                             Fault(kind="stall_socket", tick=7,
                                   seconds=0.01)])
    eng = ChaosEngine(spec)
    data = b"A" * 32
    assert eng.on_wire(2, data) == data
    with pytest.raises(ConnectionResetError):
        eng.on_wire(3, data)
    # the retry of the SAME record passes: a fault, not an outage
    assert eng.on_wire(3, data) == data
    out = eng.on_wire(5, data)
    assert out != data and len(out) == len(data)
    assert eng.on_wire(5, data) == data  # fire-once for corruption too
    t0 = time.perf_counter()
    assert eng.on_wire(7, data) == data
    assert time.perf_counter() - t0 >= 0.01
    assert sorted(e["kind"] for e in eng.injected) == [
        "conn_drop", "corrupt_bytes", "stall_socket"]


# ------------------------------------- follower: apply + splice + snap
def _run_pair(tmp_path, n_ticks, leader_kw=None, standby_journal=None,
              ck=None):
    """Drive a leader live_loop shipping to an in-process follower;
    returns (leader_reg, standby_reg, follower, leader stats)."""
    leader, standby = _reg(), _reg()
    ck = ck or str(tmp_path / "ck")
    lease_path = tmp_path / "lease"
    llease = Lease(lease_path, "L", timeout_s=30.0)
    assert llease.try_acquire()
    slease = Lease(lease_path, "S", timeout_s=1e9)
    stop = threading.Event()
    sj = standby_journal or TickJournal(tmp_path / "sj")
    follower = StandbyFollower(standby, sj, lease=slease, port=0,
                               alert_path=str(tmp_path / "alerts.jsonl"),
                               checkpoint_dir=ck, stop_event=stop)
    t = threading.Thread(target=follower.run, daemon=True)
    t.start()
    deadline = time.monotonic() + 20
    while follower.address is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert follower.address is not None
    lj = TickJournal(tmp_path / "lj")
    sender = ReplicationSender(follower.address, lj,
                               checkpoint_dir=ck).start()
    lj.tee, lj.compact_floor = sender.tee, sender.compact_floor
    stats = live_loop(
        lambda k: _row(7, k, 4), leader, n_ticks=n_ticks, cadence_s=0.0,
        alert_path=str(tmp_path / "alerts.jsonl"), checkpoint_dir=ck,
        checkpoint_every=5, journal=lj, lease=llease,
        **(leader_kw or {}))
    deadline = time.monotonic() + 30
    while follower.expected < n_ticks and time.monotonic() < deadline:
        time.sleep(0.01)
    lj.close()
    sender.close()
    stop.set()
    t.join(timeout=20)
    sj.close()
    return leader, standby, follower, stats


def test_follower_applies_stream_bit_identically(tmp_path):
    leader, standby, follower, _stats = _run_pair(tmp_path, 12)
    assert follower.applied == 12
    _assert_groups_equal(leader, standby)
    # cursors pruned the buffer: everything shipped was delivered
    assert follower.stats()["buffered_alerts"] == 0
    assert follower.last_cursor is not None
    # the mirror is byte-identical to the leader's journal records
    lrecs = [r for _t, _k, r in
             iter_raw_records(tmp_path / "lj", 0)]
    srecs = [r for _t, _k, r in
             iter_raw_records(tmp_path / "sj", 0)]
    assert lrecs == srecs


def test_snapshot_fallback_after_compaction_gap(tmp_path):
    # the reconnect-after-gap drill: the standby adopts the shared
    # checkpoints at tick 8, then the leader serves ON ALONE —
    # checkpointing + compacting until the journal no longer holds
    # tick 8 (no standby connected = no clamp). When the sender finally
    # connects, the standby's HELLO(8) cannot be served from disk: the
    # leader sends SNAP, the standby re-adopts the (newer) shared
    # checkpoints, re-HELLOs from there, and catches up — final state
    # bit-identical.
    leader = _reg()
    ck = str(tmp_path / "ck")
    lj = TickJournal(tmp_path / "lj", segment_bytes=1024)
    live_loop(lambda k: _row(7, k, 4), leader, n_ticks=8, cadence_s=0.0,
              alert_path=str(tmp_path / "alerts.jsonl"),
              checkpoint_dir=ck, checkpoint_every=4, journal=lj)
    standby = _reg()
    lease_path = tmp_path / "lease"
    llease = Lease(lease_path, "L", timeout_s=30.0)
    assert llease.try_acquire()
    slease = Lease(lease_path, "S", timeout_s=1e9)
    stop = threading.Event()
    sj = TickJournal(tmp_path / "sj")
    follower = StandbyFollower(standby, sj, lease=slease, port=0,
                               alert_path=str(tmp_path / "alerts.jsonl"),
                               checkpoint_dir=ck, stop_event=stop)
    t = threading.Thread(target=follower.run, daemon=True)
    t.start()
    deadline = time.monotonic() + 20
    while follower.address is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert follower.address is not None
    # the leader races ahead DISCONNECTED; compaction drops tick 8
    live_loop(lambda k: _row(7, 8 + k, 4), leader, n_ticks=12,
              cadence_s=0.0, alert_path=str(tmp_path / "alerts.jsonl"),
              checkpoint_dir=ck, checkpoint_every=4, journal=lj)
    assert first_journal_tick(tmp_path / "lj") > 8, \
        "compaction never dropped the standby's position — shrink " \
        "segment_bytes or grow the run"
    sender = ReplicationSender(follower.address, lj,
                               checkpoint_dir=ck).start()
    lj.tee, lj.compact_floor = sender.tee, sender.compact_floor
    live_loop(lambda k: _row(7, 20 + k, 4), leader, n_ticks=4,
              cadence_s=0.0, alert_path=str(tmp_path / "alerts.jsonl"),
              checkpoint_dir=ck, checkpoint_every=4, journal=lj)
    deadline = time.monotonic() + 30
    while follower.expected < 24 and time.monotonic() < deadline:
        time.sleep(0.01)
    lj.close()
    sender.close()
    stop.set()
    t.join(timeout=20)
    sj.close()
    assert sender.snapshot_fallbacks >= 1
    assert follower.expected == 24
    _assert_groups_equal(leader, standby)


def test_follower_discards_divergent_local_tail(tmp_path):
    # a returning standby whose own journal extends past the adopted
    # checkpoints (the pre-failover timeline) must WIPE it and re-sync
    # from the stream, never replay it
    reg = _reg()
    ck = str(tmp_path / "ck")
    lj = TickJournal(tmp_path / "lj")
    live_loop(lambda k: _row(7, k, 4), reg, n_ticks=6, cadence_s=0.0,
              checkpoint_dir=ck, checkpoint_every=3, journal=lj)
    lj.close()
    # the standby's local mirror claims MORE ticks than the shared
    # checkpoints record (orphaned pre-failover rows)
    sj = TickJournal(tmp_path / "sj")
    for k in range(10):
        sj.append_tick(k, 100 + k, np.arange(4, dtype=np.float32))
    standby = _reg()
    slease = Lease(tmp_path / "lease2", "S", timeout_s=1e9)
    follower = StandbyFollower(standby, sj, lease=slease, port=0,
                               checkpoint_dir=ck)
    follower._catch_up()
    assert follower.expected == 6  # the checkpoints' position, not 10
    assert sj.next_tick == 0  # the divergent mirror was wiped
    sj.close()


# ----------------------------------------------------- writer fencing
def test_alert_writer_fence_refuses_writes(tmp_path):
    from rtap_tpu.service.alerts import AlertWriter

    path = str(tmp_path / "a.jsonl")
    fenced = {"v": False}
    w = AlertWriter(path, fence=lambda: not fenced["v"])
    w.emit_batch(["s0"], np.array([1]), np.array([1.0]),
                 np.array([0.9]), np.array([-5.0]), np.array([True]),
                 group=0, tick=0)
    fenced["v"] = True
    w.emit_batch(["s0"], np.array([2]), np.array([1.0]),
                 np.array([0.9]), np.array([-5.0]), np.array([True]),
                 group=0, tick=1)
    w.emit_event({"event": "should_not_land"})
    w.close()
    lines = [ln for ln in open(path) if ln.strip()]
    assert len(lines) == 1
    assert json.loads(lines[0])["alert_id"] == "0:s0:0"
    assert w.fenced_drops == 2


def test_live_loop_breaks_and_skips_final_save_when_fenced(tmp_path):
    reg = _reg()
    lease_path = tmp_path / "lease"
    mine = Lease(lease_path, "L", timeout_s=30.0)
    assert mine.try_acquire()
    ck = str(tmp_path / "ck")

    def source(k):
        if k == 5:
            # a standby promotes mid-run: epoch bumps behind our back
            cur = json.loads(lease_path.read_text())
            cur["epoch"] += 1
            cur["owner"] = "usurper"
            cur["ts"] = time.time()
            lease_path.write_text(json.dumps(cur))
            # expire the still_mine() probe cache: at cadence 0 on a
            # fast host the remaining ticks can all land inside the
            # min(0.2, timeout/4) s cache window and the run finishes
            # un-fenced (observed-flake class, reproduced at HEAD) —
            # the test pins the FENCE logic, not the cache cadence
            mine._last_probe = -1e9
        return _row(7, k, 4)

    stats = live_loop(source, reg, n_ticks=20, cadence_s=0.0,
                      alert_path=str(tmp_path / "a.jsonl"),
                      checkpoint_dir=ck, checkpoint_every=50,
                      lease=mine)
    assert stats["fenced"] is True
    assert stats["ticks"] < 20
    # the fenced leader never wrote the shared checkpoint dir (no
    # periodic round was due, and the final save is fence-gated)
    assert not os.path.isdir(os.path.join(ck, "group0000"))


def test_serve_cli_has_replication_flags():
    # the flag surface is load-bearing for the runbook; pin the names
    import rtap_tpu.__main__ as cli

    src = open(cli.__file__).read()
    for flag in ("--replicate-to", "--standby", "--replicate-listen",
                 "--lease-file", "--lease-timeout"):
        assert flag in src


def test_lease_seen_epoch_floor_is_race_safe(tmp_path):
    """rtap-lint race-pass fix (ISSUE 12): read() updates the seen-epoch
    floor from BOTH the heartbeat thread (under self._lock) and unlocked
    main-side probes (is_stale/holder). Unguarded, the read-modify-write
    max() could REGRESS the floor (T2 loads the old floor, T1 stores a
    higher one, T2 stores its stale max) — and a regressed floor at a
    promotion whose lease read fails restarts epochs low and re-inverts
    the fence. The fix serializes the update under a dedicated lock;
    this hammer pins the floor's monotonicity under contention."""
    import sys

    path = tmp_path / "lease"
    lease = Lease(path, "B", timeout_s=5.0)
    stop = threading.Event()
    regressions = []

    def probe():
        last = 0
        while not stop.is_set():
            lease.read()
            cur = lease._seen_epoch
            if cur < last:
                regressions.append((last, cur))
                return
            last = cur

    old_interval = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)  # widen the interleaving window
    try:
        threads = [threading.Thread(target=probe, name=f"rtap-test-{i}")
                   for i in range(4)]
        for t in threads:
            t.start()
        for epoch in range(1, 300):
            path.write_text(json.dumps(
                {"epoch": epoch, "owner": "A", "ts": time.time()}))
            lease.read()
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
    finally:
        sys.setswitchinterval(old_interval)
    assert not regressions, (
        f"seen-epoch floor regressed under concurrent reads: {regressions}")
    assert lease._seen_epoch == 299
