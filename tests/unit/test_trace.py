"""obs/trace.py: the span-ring recorder the serve loop flies with blind
(ISSUE 4 tentpole). Pins the parts everything downstream depends on:
strictly bounded memory (ring size x record size — the flight recorder's
"black box can run forever" contract), overwrite-oldest semantics,
Chrome trace-event JSON schema (Perfetto loads exactly this), the tick
window filter the /trace route and bundle dumps use, lock-free
multi-thread capture, and the <= 1% tick-budget overhead bar."""

import json
import threading
import time

import pytest

from rtap_tpu.obs.trace import REC_DTYPE, TraceRecorder


@pytest.mark.quick
def test_ring_is_strictly_bounded_and_overwrites_oldest():
    tr = TraceRecorder(capacity=8)
    t0 = time.perf_counter()
    for i in range(20):
        tr.add_span("tick", i, t0 + i * 1e-3, 1e-4)
    assert tr.total == 20
    assert tr.dropped == 12
    recs = tr.records()
    assert len(recs) == 8
    # oldest overwritten: only the last capacity ticks remain
    assert sorted(r["tick"] for r in recs) == list(range(12, 20))
    # the memory bound the flight-recorder contract rests on: ONE
    # preallocated structured array per writer thread, never grown
    assert tr.nbytes() == 8 * REC_DTYPE.itemsize


@pytest.mark.quick
def test_instant_payloads_are_truncated_and_memory_stays_flat():
    tr = TraceRecorder(capacity=4, max_arg_bytes=32)
    for i in range(10):
        tr.add_instant("group_quarantined", i, {"blob": "x" * 10_000})
    shard = next(iter(tr._shards.values()))
    assert len(shard.aux) == 4
    assert all(a is None or len(a) <= 32 for a in shard.aux)


@pytest.mark.quick
def test_name_interning_is_bounded():
    tr = TraceRecorder(capacity=64, max_names=4)
    t0 = time.perf_counter()
    for i in range(10):
        tr.add_span(f"name{i}", 0, t0, 1e-6)
    # vocabulary overflow maps to "<other>" instead of growing the table
    assert len(tr._names_rev) == 4
    names = {r["name"] for r in tr.records()}
    assert "<other>" in names


@pytest.mark.quick
def test_chrome_trace_schema_spans_instants_and_group_tracks():
    tr = TraceRecorder(capacity=64)
    t0 = time.perf_counter()
    tr.add_span("source", 3, t0, 0.002)
    tr.add_span("dispatch", 3, t0 + 0.002, 0.004, group=1)
    tr.add_instant("group_quarantined", 3, {"phase": "dispatch"}, group=1)
    ct = json.loads(json.dumps(tr.chrome_trace()))  # must round-trip
    evs = ct["traceEvents"]
    spans = [e for e in evs if e.get("ph") == "X"]
    instants = [e for e in evs if e.get("ph") == "i"]
    assert len(spans) == 2 and len(instants) == 1
    src = next(e for e in spans if e["name"] == "source")
    assert src["tid"] == 0 and src["args"]["tick"] == 3
    assert src["dur"] == pytest.approx(2000, rel=0.01)  # microseconds
    disp = next(e for e in spans if e["name"] == "dispatch")
    assert disp["tid"] == 2 and disp["args"]["group"] == 1  # group g -> tid g+1
    q = instants[0]
    assert q["name"] == "group_quarantined" and q["s"] == "g"
    assert q["args"]["tick"] == 3 and q["args"]["phase"] == "dispatch"
    # track naming metadata present for the loop and the group
    meta = {(e["tid"], e["args"]["name"]) for e in evs if e.get("ph") == "M"}
    assert (0, "serve loop") in meta and (2, "group1") in meta


@pytest.mark.quick
def test_last_ticks_window_filters_by_tick_not_position():
    tr = TraceRecorder(capacity=64)
    t0 = time.perf_counter()
    for i in range(10):
        tr.add_span("tick", i, t0 + i, 0.5)
    recs = tr.records(last_ticks=3)
    assert sorted(r["tick"] for r in recs) == [7, 8, 9]
    ct = tr.chrome_trace(last_ticks=3)
    assert all(e["args"]["tick"] >= 7 for e in ct["traceEvents"]
               if e.get("ph") == "X")


@pytest.mark.quick
def test_concurrent_writers_have_private_shards():
    tr = TraceRecorder(capacity=1000)
    t0 = time.perf_counter()
    # all 4 workers alive simultaneously: thread idents are only unique
    # among LIVE threads (CPython reuses them), and the shard-per-thread
    # claim is about concurrent writers
    barrier = threading.Barrier(4)

    def work():
        barrier.wait()
        for i in range(500):
            tr.add_span("collect", i, t0, 1e-6, group=0)
        barrier.wait()

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tr.add_span("tick", 0, t0, 1e-6)
    # every append landed (each thread owns its ring; nothing raced away)
    assert tr.total == 4 * 500 + 1
    assert tr.dropped == 0
    assert len(tr._shards) >= 4


@pytest.mark.quick
def test_replay_streams_records_chunk_spans():
    """replay_streams is instrumented too (ISSUE 4 tentpole): every chunk
    dispatch/collect lands as a per-group span keyed by the chunk's first
    tick."""
    from rtap_tpu.config import cluster_preset
    from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_cluster
    from rtap_tpu.service.loop import replay_streams

    streams = generate_cluster(
        1, cfg=SyntheticStreamConfig(length=16, cadence_s=1.0,
                                     n_anomalies=0), seed=0)
    tr = TraceRecorder(capacity=256)
    res = replay_streams(streams, cluster_preset(), backend="tpu",
                         chunk_ticks=8, trace=tr)
    assert res.raw.shape[0] == 16
    recs = tr.records()
    disp = [r for r in recs if r["name"] == "replay_dispatch"]
    coll = [r for r in recs if r["name"] == "replay_collect"]
    assert len(disp) == 2 and len(coll) == 2  # 16 ticks / 8 per chunk
    assert sorted(r["tick"] for r in disp) == [0, 8]
    assert all(r["group"] == 0 for r in disp + coll)


@pytest.mark.quick
def test_trace_and_flight_overhead_within_one_percent_of_tick_budget():
    """ISSUE 4 acceptance: span-ring + flight-recorder traffic for a full
    16-group tick costs <= 1% of the 1 s cadence (the same bar, and the
    same measurement, as bench.py --obs-bench's second line)."""
    from rtap_tpu.obs.selfbench import measure_trace

    res = measure_trace(n=5000)
    assert res["per_tick_overhead_frac"] <= 0.01, res
