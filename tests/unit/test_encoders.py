"""Encoder semantics (SURVEY.md C1/C2): RDSE overlap properties, date fields."""

import numpy as np

from rtap_tpu.config import DateConfig, ModelConfig, RDSEConfig
from rtap_tpu.models.oracle.encoders import (
    encode_record,
    is_weekend,
    rdse_bits,
    rdse_bucket,
    time_of_day_bits,
)

CFG = RDSEConfig(size=400, active_bits=21, resolution=1.0, seed=3)


def _sdr(bucket):
    s = np.zeros(CFG.size, bool)
    s[rdse_bits(CFG, bucket)] = True
    return s


class TestRDSE:
    def test_deterministic(self):
        np.testing.assert_array_equal(rdse_bits(CFG, 7), rdse_bits(CFG, 7))

    def test_active_count_near_w(self):
        # hash collisions may merge a couple of bits, never more than a few
        for b in range(-50, 50, 7):
            n = _sdr(b).sum()
            assert CFG.active_bits - 3 <= n <= CFG.active_bits

    def test_neighbor_overlap_decays_linearly(self):
        base = _sdr(100)
        overlaps = [(k, int((base & _sdr(100 + k)).sum())) for k in range(0, 25, 4)]
        vals = [o for _, o in overlaps]
        assert vals[0] >= CFG.active_bits - 3  # self
        assert all(a >= b - 2 for a, b in zip(vals, vals[1:]))  # decreasing-ish
        assert vals[-1] <= 4  # distance 24 > w: near-zero overlap

    def test_far_buckets_nearly_disjoint(self):
        assert int((_sdr(0) & _sdr(1000)).sum()) <= 4

    def test_bucket_arithmetic(self):
        assert rdse_bucket(10.0, 10.0, 0.5) == 0
        assert rdse_bucket(11.0, 10.0, 0.5) == 2
        assert rdse_bucket(9.74, 10.0, 0.5) == -1

    def test_field_seeds_differ(self):
        a = rdse_bits(CFG, 5, field_index=0)
        b = rdse_bits(CFG, 5, field_index=1)
        assert not np.array_equal(np.sort(a), np.sort(b))


class TestDate:
    DCFG = DateConfig(time_of_day_width=5, time_of_day_size=48, weekend_width=3)

    def test_time_of_day_wraps(self):
        bits = time_of_day_bits(self.DCFG, 0)  # midnight -> centered at 0, wraps
        assert set(bits) == {46, 47, 0, 1, 2}

    def test_noon_center(self):
        bits = time_of_day_bits(self.DCFG, 12 * 3600)
        assert set(bits) == {22, 23, 24, 25, 26}

    def test_weekend(self):
        assert not is_weekend(0)  # 1970-01-01 Thursday
        assert is_weekend(2 * 86400)  # Saturday
        assert is_weekend(3 * 86400)  # Sunday
        assert not is_weekend(4 * 86400)  # Monday


class TestMultiField:
    def test_layout(self):
        cfg = ModelConfig(
            rdse=RDSEConfig(size=100, active_bits=5, resolution=1.0),
            date=DateConfig(time_of_day_width=3, time_of_day_size=24, weekend_width=2),
            n_fields=2,
        )
        sdr = encode_record(cfg, np.array([5.0, 7.0]), 2 * 86400, np.zeros(2, np.float32))
        assert sdr.shape == (cfg.input_size,)
        assert sdr[:100].sum() >= 4  # field 0 block
        assert sdr[100:200].sum() >= 4  # field 1 block
        assert sdr[200:224].sum() == 3  # time-of-day ring
        assert sdr[224:226].all()  # weekend (Saturday)

    def test_fields_independent(self):
        cfg = ModelConfig(
            rdse=RDSEConfig(size=100, active_bits=5, resolution=1.0),
            date=DateConfig(time_of_day_width=0, time_of_day_size=0),
            n_fields=2,
        )
        a = encode_record(cfg, np.array([5.0, 7.0]), 0, np.zeros(2, np.float32))
        b = encode_record(cfg, np.array([5.0, 50.0]), 0, np.zeros(2, np.float32))
        np.testing.assert_array_equal(a[:100], b[:100])  # field 0 unchanged
        assert (a[100:200] != b[100:200]).any()
