"""Scaled-preset geometry: the width-scaling rules that gate the density
and CPU-feasibility studies (SCALING.md model-width section).

The first quarter-model measurement was confounded by a degenerate
geometry (banker's rounding gave a 2-of-2 segment-activation
requirement) — these tests pin the non-degeneracy rules so a future edit
can't silently reintroduce it.
"""

import dataclasses

import pytest

from rtap_tpu.config import (
    cluster_preset,
    nab_preset,
    scaled_cluster_preset,
    scaled_nab_preset,
)


class TestScaledClusterPreset:
    def test_identity_width_keeps_preset_geometry(self):
        base, scaled = cluster_preset(), scaled_cluster_preset(256)
        assert scaled.sp.num_active_columns == base.sp.num_active_columns
        assert scaled.tm.new_synapse_count == base.tm.new_synapse_count

    @pytest.mark.parametrize("columns", [16, 32, 64, 128])
    def test_non_degenerate_geometry(self, columns):
        cfg = scaled_cluster_preset(columns)
        tm, sp = cfg.tm, cfg.sp
        # activation must require strictly fewer matches than the segment
        # samples, else only perfect recurrence ever predicts (the measured
        # confound); min_threshold must stay a reachable match bar
        assert 2 <= tm.activation_threshold < tm.new_synapse_count
        assert 1 <= tm.min_threshold <= tm.activation_threshold
        assert tm.new_synapse_count <= tm.max_synapses_per_segment
        assert sp.num_active_columns == tm.col_cap
        # sparsity stays in the sparse-coding regime (preset is ~3.9%)
        assert sp.num_active_columns / sp.columns <= 0.20

    def test_upscale_past_segment_capacity_raises(self):
        with pytest.raises(ValueError, match="segment capacity"):
            scaled_cluster_preset(1024)


class TestScaledNabPreset:
    def test_identity_width_keeps_preset_geometry(self):
        base, scaled = nab_preset(), scaled_nab_preset(2048)
        assert scaled.sp.num_active_columns == base.sp.num_active_columns
        assert scaled.tm.new_synapse_count == base.tm.new_synapse_count
        assert scaled.tm.activation_threshold == base.tm.activation_threshold
        assert scaled.tm.min_threshold == base.tm.min_threshold

    @pytest.mark.parametrize("columns", [128, 256, 512, 1024])
    def test_non_degenerate_geometry(self, columns):
        cfg = scaled_nab_preset(columns)
        tm, sp = cfg.tm, cfg.sp
        assert 2 <= tm.activation_threshold < tm.new_synapse_count
        assert 1 <= tm.min_threshold <= tm.activation_threshold
        assert tm.new_synapse_count <= tm.max_synapses_per_segment
        assert sp.num_active_columns == tm.col_cap
        assert sp.num_active_columns / sp.columns <= 0.20
        # cells axis deliberately unscaled (see docstring)
        assert tm.cells_per_column == nab_preset().tm.cells_per_column

    def test_winner_ratio_tracks_nupic_family(self):
        # 512 cols at the preset's ~2% sparsity: 10 winners, 5 sampled,
        # activate on 3, match on 3 — the 40/20/13/10 family scaled by 1/4
        cfg = scaled_nab_preset(512)
        assert cfg.sp.num_active_columns == 10
        assert cfg.tm.new_synapse_count == 5
        assert cfg.tm.activation_threshold == 3
        assert cfg.tm.min_threshold == 3

    def test_upscale_past_segment_capacity_raises(self):
        # the guard input is the DERIVED new_synapse_count (k/2), not k:
        # 4096 cols -> k=80 -> ns=40 > the 32-synapse pool capacity
        with pytest.raises(ValueError, match="segment capacity"):
            scaled_nab_preset(4096)

    def test_validates_as_model_config(self):
        # dataclasses.replace must not sidestep ModelConfig invariants
        cfg = scaled_nab_preset(256)
        assert dataclasses.replace(cfg) == cfg


class TestWithLearningPeriod:
    """ModelConfig.with_learning_period — the probation lever (lp600 is
    the measured +3-point precision option) with cadence-alignment safety:
    the two composition orders must agree, so callers cannot produce a
    full-rate window that contradicts the probation."""

    def test_sets_probation(self):
        from rtap_tpu.config import cluster_preset

        cfg = cluster_preset().with_learning_period(600)
        assert cfg.likelihood.learning_period == 600

    def test_order_independent_with_cadence(self):
        from rtap_tpu.config import cluster_preset

        base = cluster_preset()
        a = base.with_learning_period(600).with_learn_every(2)
        b = base.with_learn_every(2).with_learning_period(600)
        assert a == b
        assert a.learn_full_until == 600  # maturity boundary follows lp

    def test_explicit_full_until_is_preserved(self):
        from rtap_tpu.config import cluster_preset

        cfg = cluster_preset().with_learn_every(2, full_until=1000)
        cfg = cfg.with_learning_period(600)
        # an explicit, non-default boundary is the caller's choice: the
        # probation change must not silently overwrite it
        assert cfg.learn_full_until == 1000
        assert cfg.likelihood.learning_period == 600

    def test_invalid_period_raises(self):
        import pytest

        from rtap_tpu.config import cluster_preset

        with pytest.raises(ValueError, match="learning_period"):
            cluster_preset().with_learning_period(0)
