"""RB1 binary batch ingest (ISSUE 7): frame codec + walker edges (torn/
short frames, bad magic/CRC, version skew), native-vs-Python walker
parity fuzz, the registry slot map / dispatch table, admission control
(quota, drop-oldest backpressure), backfill horizon boundaries, the shm
ring, and the journal's raw-FRAME write-ahead records."""

import struct
import zlib

import numpy as np
import pytest

from rtap_tpu.config import cluster_preset
from rtap_tpu.ingest import (
    BinaryBatchSource,
    DispatchTable,
    FrameWalker,
    ShmRing,
    build_frame,
    decode_slot,
    encode_slot,
)
from rtap_tpu.ingest.dispatch import decode_frames_to_row
from rtap_tpu.ingest.protocol import (
    KIND_DATA,
    KIND_MAP,
    KIND_NAMES,
    MAX_GROUPS,
    MAX_SHARDS,
    MAX_SLOTS,
    data_frame,
    scan_frames_py,
)
from rtap_tpu.service.registry import StreamGroupRegistry

try:
    from rtap_tpu.native import frame_walker_scan

    _ = frame_walker_scan(b"")
    _nat_err = None
except Exception as e:  # no toolchain: the fallback story, not a failure
    frame_walker_scan = None
    _nat_err = e

needs_native = pytest.mark.skipif(
    frame_walker_scan is None, reason=f"native walker unavailable: {_nat_err}")

pytestmark = pytest.mark.quick


def _reg(n=6, group_size=4, reserve=0):
    reg = StreamGroupRegistry(cluster_preset(), group_size=group_size,
                              backend="cpu")
    for i in range(n):
        reg.add_stream(f"s{i}")
    reg.finalize(reserve=reserve)
    return reg


def _codes(reg, *ids):
    sm = reg.slot_map()
    return np.array([encode_slot(sm[i].shard, sm[i].group, sm[i].slot)
                     for i in ids], np.uint32)


# ------------------------------------------------------------- codec ----


def test_slot_codec_roundtrip_and_bounds():
    for shard, group, slot in [(0, 0, 0), (3, 77, 1023),
                               (MAX_SHARDS - 1, MAX_GROUPS - 1,
                                MAX_SLOTS - 1)]:
        sh, g, s = decode_slot(encode_slot(shard, group, slot))
        assert (int(sh), int(g), int(s)) == (shard, group, slot)
    for bad in [(-1, 0, 0), (MAX_SHARDS, 0, 0), (0, MAX_GROUPS, 0),
                (0, 0, MAX_SLOTS)]:
        with pytest.raises(ValueError):
            encode_slot(*bad)


def test_frame_roundtrip_all_kinds():
    codes = np.array([encode_slot(0, 0, i) for i in range(3)], np.uint32)
    vals = np.array([1.5, np.nan, -7.0], np.float32)
    frames = [
        data_frame(codes, vals, 1_700_000_000, deltas=[0, 1, 2],
                   tenant="acme"),
        build_frame(KIND_NAMES, b"new.a\nnew.b"),
        build_frame(KIND_MAP, b'{"s0": 0}'),
    ]
    w = FrameWalker(native=False)
    out = w.feed(b"".join(frames))
    assert [f.kind for f in out] == [KIND_DATA, KIND_NAMES, KIND_MAP]
    assert out[0].tenant == "acme" and out[0].base_ts == 1_700_000_000
    rows = out[0].rows()
    assert np.array_equal(rows["slot"], codes)
    assert np.array_equal(rows["value"], vals, equal_nan=True)
    assert list(rows["dt"]) == [0, 1, 2]
    assert bytes(out[1].payload) == b"new.a\nnew.b"
    assert out[0].raw == frames[0]  # verbatim — the journal's payload


def test_walker_torn_frames_wait_for_bytes():
    frame = data_frame(np.array([encode_slot(0, 0, 0)], np.uint32),
                       [3.0], 1000)
    w = FrameWalker(native=False)
    # drip-feed in 3-byte chunks: nothing emits until the frame completes
    got = []
    for off in range(0, len(frame), 3):
        got += w.feed(frame[off:off + 3])
    assert len(got) == 1 and got[0].rows()["value"][0] == 3.0
    assert w.bad_crc == 0 and w.garbage_bytes == 0


def test_walker_bad_magic_resyncs_and_counts():
    frame = build_frame(KIND_NAMES, b"x")
    w = FrameWalker(native=False)
    out = w.feed(b"NOISE" + frame + b"RB" + frame)  # stray partial magic
    assert len(out) == 2
    assert w.garbage_bytes >= 5


def test_walker_bad_crc_skips_frame():
    frame = bytearray(data_frame(
        np.array([encode_slot(0, 0, 0)], np.uint32), [3.0], 1000))
    frame[-1] ^= 0xFF  # flip a CRC byte
    good = build_frame(KIND_NAMES, b"ok")
    w = FrameWalker(native=False)
    out = w.feed(bytes(frame) + good)
    assert [f.kind for f in out] == [KIND_NAMES]
    assert w.bad_crc == 1


def test_walker_version_skew_skips_whole_frame():
    """Framing fields are frozen across versions: a well-framed future-
    version (or unknown-kind) frame is skipped WHOLE and counted, never
    treated as garbage (docs/INGEST.md versioning rules)."""
    def reskew(frame: bytes, byte_off: int, value: int) -> bytes:
        b = bytearray(frame[:-4])
        b[byte_off] = value
        return bytes(b) + struct.pack("<I", zlib.crc32(bytes(b[3:])))

    good = build_frame(KIND_NAMES, b"ok")
    futures = [reskew(good, 3, 9),   # version 9
               reskew(good, 4, 200)]  # unknown kind
    w = FrameWalker(native=False)
    out = w.feed(futures[0] + futures[1] + good)
    assert [f.kind for f in out] == [KIND_NAMES]
    assert w.version_skew == 2 and w.garbage_bytes == 0


def _fuzz_stream(seed: int) -> bytes:
    rng = np.random.default_rng(seed)
    parts = []
    for _ in range(60):
        r = rng.random()
        n = int(rng.integers(1, 20))
        codes = np.array([encode_slot(0, int(rng.integers(0, 4)),
                                      int(rng.integers(0, 64)))
                          for _ in range(n)], np.uint32)
        frame = data_frame(codes, rng.normal(size=n).astype(np.float32),
                           int(rng.integers(1, 2**40)),
                           deltas=rng.integers(0, 65536, n).astype(np.uint16),
                           tenant="t" * int(rng.integers(0, 6)))
        if r < 0.55:
            parts.append(frame)
        elif r < 0.7:  # flipped byte somewhere (CRC or header damage)
            b = bytearray(frame)
            b[int(rng.integers(0, len(b)))] ^= 0xFF
            parts.append(bytes(b))
        elif r < 0.8:  # version/kind skew with a VALID crc
            b = bytearray(frame[:-4])
            b[3 if r < 0.75 else 4] = int(rng.integers(5, 250))
            parts.append(bytes(b) + struct.pack(
                "<I", zlib.crc32(bytes(b[3:]))))
        elif r < 0.9:  # raw garbage (may contain magic-like bytes)
            parts.append(bytes(rng.integers(0, 256, int(rng.integers(1, 80)),
                                            dtype=np.uint8)))
        else:  # truncated frame mid-payload
            parts.append(frame[:int(rng.integers(1, len(frame)))])
    return b"".join(parts)


@needs_native
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_walker_parity_native_vs_python_fuzz(seed):
    """The C scanner and the Python fallback must agree meta-for-meta,
    byte-for-byte, counter-for-counter on adversarial streams — the
    evidence behind auto-selecting the native walker."""
    blob = _fuzz_stream(seed)
    assert scan_frames_py(blob) == frame_walker_scan(blob)
    # and incrementally, at awkward chunk sizes
    wn, wp = FrameWalker(native=True), FrameWalker(native=False)
    fn, fp = [], []
    for off in range(0, len(blob), 1237):
        chunk = blob[off:off + 1237]
        fn += wn.feed(chunk)
        fp += wp.feed(chunk)
    assert [f.raw for f in fn] == [f.raw for f in fp]
    assert (wn.bad_crc, wn.version_skew, wn.garbage_bytes) \
        == (wp.bad_crc, wp.version_skew, wp.garbage_bytes)
    assert wn.frames == len(fn) > 0


# ------------------------------------------- slot map / dispatch table ----


def test_slot_map_matches_dispatch_order():
    reg = _reg(n=6, group_size=4, reserve=4)
    sm = reg.slot_map()
    assert list(sm) == reg.dispatch_ids()
    assert all(a.shard == 0 for a in sm.values())  # single-device
    # claims land in the map at their claimed (group, slot) address
    reg.add_stream("late")
    sm2 = reg.slot_map()
    assert list(sm2) == reg.dispatch_ids() and "late" in sm2
    table = DispatchTable(sm2)
    assert table.ids == reg.dispatch_ids()
    pos = table.lookup(table.codes)
    assert np.array_equal(pos, np.arange(table.n))


def test_dispatch_lookup_rejects_bad_codes():
    reg = _reg(n=6, group_size=4)
    table = DispatchTable.from_registry(reg)
    good = table.codes[2]
    bad = np.array([
        encode_slot(0, 2, 0),    # group beyond the fleet
        encode_slot(0, 0, 100),  # slot beyond the group... (dense bound)
        encode_slot(1, 0, 2),    # wrong shard for an existing (g, s)
        good,
    ], np.uint32)
    pos = table.lookup(bad)
    assert list(pos) == [-1, -1, -1, 2]
    # pads are NOT addressable: group 1 holds 2 live + 2 pad slots
    pad_code = np.array([encode_slot(0, 1, 3)], np.uint32)
    assert table.lookup(pad_code)[0] == -1


# ------------------------------------------------- admission control ----


def test_quota_exhaustion_and_counters():
    reg = _reg(n=4, group_size=4)
    src = BinaryBatchSource(reg.slot_map(), port=None, quota_rows=3)
    codes = src._table.codes
    src.feed_frames([data_frame(codes, [1, 2, 3, 4], 2000, tenant="a"),
                     data_frame(codes[:2], [9, 9], 2000, tenant="b")])
    v, _ = src(0)
    # tenant a: first 3 of 4 rows admitted; tenant b under quota
    assert src.rows_quota_dropped == 1
    assert v[0] == 9 and v[1] == 9 and v[2] == 3 and np.isnan(v[3])
    # quota window resets per tick
    src.feed_frames([data_frame(codes[:1], [5.0], 2001, tenant="a")])
    v2, _ = src(1)
    assert v2[0] == 5.0 and src.rows_quota_dropped == 1
    # a quota-truncated tick synthesizes journal frames that replay
    # to the EMITTED vector, not the wire rows
    src.feed_frames([data_frame(codes, [1, 2, 3, 4], 2002, tenant="a")])
    v3, _ = src(2)
    row = decode_frames_to_row(src.take_tick_frames(), 4,
                               DispatchTable.from_registry(reg))
    assert np.array_equal(row, v3, equal_nan=True)


def test_stale_epoch_frames_refused_whole():
    """A membership change bumps the map epoch; frames stamped with the
    old epoch are refused whole (a re-claimed slot code must never
    route a stale producer's rows into the NEW stream's model).
    Epoch-0 (epoch-unaware) frames stay admitted."""
    reg = _reg(n=4, group_size=4, reserve=4)
    src = BinaryBatchSource(reg.slot_map(), port=None)
    codes = src._table.codes
    old_epoch = src._map_epoch
    src.feed_frames([data_frame(codes[:1], [1.0], 100, epoch=old_epoch)])
    assert src.records_parsed == 1
    reg.add_stream("newcomer")  # claims a pad slot -> membership change
    src.set_slot_map(reg.slot_map())
    assert src._map_epoch == old_epoch + 1
    src.feed_frames([data_frame(codes[:1], [2.0], 101, epoch=old_epoch)])
    assert src.records_parsed == 1 and src.rows_stale_epoch == 1
    src.feed_frames([data_frame(codes[:1], [3.0], 102)])  # epoch 0: ok
    src.feed_frames([data_frame(codes[:1], [4.0], 103,
                                epoch=src._map_epoch)])
    assert src.records_parsed == 3


def test_inf_values_survive_backfill_and_synth_replay():
    """inf is a legal f32 wire value: it must survive the backfill
    merge AND the synthesized-frame journal replay (presence is
    not-NaN, never isfinite)."""
    reg = _reg(n=4, group_size=4)
    src = BinaryBatchSource(reg.slot_map(), port=None, quota_rows=3)
    codes = src._table.codes
    src.feed_frames([data_frame(codes, [np.inf, -np.inf, 3.0, 4.0],
                                2000, tenant="a")])
    v, _ = src(0)  # quota-truncated -> impure -> synthesized journal
    assert v[0] == np.inf and v[1] == -np.inf
    row = decode_frames_to_row(src.take_tick_frames(), 4,
                               DispatchTable.from_registry(reg))
    assert np.array_equal(row, v, equal_nan=True)
    srcb = BinaryBatchSource(reg.slot_map(), port=None,
                             backfill_horizon=1)
    srcb.feed_frames([data_frame(codes[:1], [np.inf], 3000),
                      data_frame(codes[1:2], [1.0], 3002)])
    v, _ = srcb(0)
    assert v[0] == np.inf  # merged through the bucket path


def test_map_push_on_membership_change_and_poll():
    """A membership change PUSHES the fresh map (with its new epoch) to
    every connected producer; poll_map() drains it without blocking —
    no producer is left stamping a stale epoch after someone else's
    claim/release."""
    import time

    from rtap_tpu.ingest.emit import BinaryFeedConnection

    reg = _reg(n=4, group_size=4, reserve=4)
    src = BinaryBatchSource(reg.slot_map()).start()
    try:
        with BinaryFeedConnection(src.address) as conn:
            e0 = conn.epoch
            assert conn.poll_map() is False  # nothing pushed yet
            reg.add_stream("pushed.late")
            src.set_slot_map(reg.slot_map())
            deadline = time.time() + 10
            while time.time() < deadline and not conn.poll_map():
                time.sleep(0.01)
            assert conn.epoch == e0 + 1
            assert "pushed.late" in conn.code_of
    finally:
        src.close()


def test_send_binary_splits_wide_ts_spans():
    """A batch spanning more than the u16 delta range must deliver
    EXACT timestamps across several frames, never clamp hours wrong."""
    from rtap_tpu.ingest.emit import _split_by_ts_span

    batch = [{"id": "a", "value": 1.0, "ts": 1_000},
             {"id": "b", "value": 2.0},              # ts-less: rides along
             {"id": "c", "value": 3.0, "ts": 1_000 + 65535},
             {"id": "d", "value": 4.0, "ts": 1_000 + 65536},  # overflows
             {"id": "e", "value": 5.0, "ts": 500}]   # new run's own base
    runs = _split_by_ts_span(batch)
    assert [[r["id"] for r in sub] for sub, _ in runs] \
        == [["a", "b", "c"], ["d"], ["e"]]
    for sub, base in runs:
        for r in sub:
            if "ts" in r:
                assert 0 <= r["ts"] - base <= 65535


def test_backfill_horizon_boundaries():
    reg = _reg(n=4, group_size=4)
    src = BinaryBatchSource(reg.slot_map(), port=None, backfill_horizon=2)
    c = src._table.codes
    T = 5000
    src.feed_frames([data_frame(c[:1], [1.0], T)])
    v, _ = src(0)
    assert np.isnan(v).all()  # watermark T-2: bucket T not yet due
    src.feed_frames([data_frame(c[1:2], [2.0], T + 2)])  # watermark -> T
    v, ts = src(1)
    assert v[0] == 1.0 and np.isnan(v[1:]).all() and ts == T
    # a late row INSIDE the horizon lands in its own (earlier) slot
    src.feed_frames([data_frame(c[2:3], [3.0], T + 1)])
    assert src.rows_backfilled == 1 and src.rows_late_dropped == 0
    src.feed_frames([data_frame(c[3:4], [4.0], T + 3)])  # watermark -> T+1
    v, ts = src(2)
    assert v[2] == 3.0 and ts == T + 1
    # at/below the emitted floor = beyond the horizon: dropped, counted
    src.feed_frames([data_frame(c[:1], [9.0], T + 1)])
    assert src.rows_late_dropped == 1
    v, _ = src(3)
    assert np.isnan(v[0])


def test_backpressure_drop_oldest():
    reg = _reg(n=4, group_size=4)
    src = BinaryBatchSource(reg.slot_map(), port=None, backfill_horizon=1,
                            max_pending_buckets=3)
    c = src._table.codes
    for i in range(6):  # 6 distinct future buckets > the 3-bucket bound
        src.feed_frames([data_frame(c[:1], [float(i)], 7000 + 10 * i)])
    assert src.rows_backpressure_dropped >= 2
    # the freshest data survived: drain everything due
    last = None
    for tick in range(10):
        v, _ = src(tick)
        if np.isfinite(v[0]):
            last = v[0]
    assert last == 4.0  # newest emittable bucket (7050 is above watermark)


# --------------------------------------------------------------- shm ----


def test_shm_ring_roundtrip_and_wraparound():
    import os

    name = f"rtap_t_ring_{os.getpid()}"
    ring = ShmRing.create(name, 4096)
    try:
        w = ShmRing.attach(name)
        frame = build_frame(KIND_NAMES, b"n" * 100)
        walker = FrameWalker(native=False)
        got = 0
        for k in range(200):  # ~25 KiB through a 4 KiB ring: many wraps
            assert w.push(frame)
            if k % 3 == 0:
                got += len(walker.feed(ring.drain()))
        got += len(walker.feed(ring.drain()))
        assert got == 200 and walker.bad_crc == 0
        assert walker.garbage_bytes == 0
        # a frame that cannot fit is refused, counted, never torn
        assert not w.push(build_frame(KIND_NAMES, b"x" * 5000))
        assert w.push_rejected == 1
        w.close()
    finally:
        ring.close()


def test_shm_attach_rejects_non_ring():
    from multiprocessing import shared_memory

    import os

    name = f"rtap_t_bad_{os.getpid()}"
    raw = shared_memory.SharedMemory(name=name, create=True, size=1024)
    try:
        with pytest.raises(ValueError):
            ShmRing.attach(name)
    finally:
        raw.close()
        raw.unlink()


# ---------------------------------------------- journal FRAME records ----


def test_journal_frame_records_roundtrip_and_torn_tail(tmp_path):
    from rtap_tpu.resilience.journal import (
        JournaledFrames,
        TickJournal,
        count_journal_ticks,
        last_journal_tick,
    )

    reg = _reg(n=4, group_size=4)
    src = BinaryBatchSource(reg.slot_map(), port=None)
    c = src._table.codes
    j = TickJournal(tmp_path / "j")
    frames0 = [data_frame(c, [1, 2, 3, 4], 9000)]
    j.append_tick_frames(0, 9000, 4, frames0)
    j.append_tick_frames(1, 9001, 4, [])  # no-data tick: legal, all-NaN
    j.append_tick(2, 9002, np.array([5, 6, 7, 8], np.float32))  # mixed log
    j.close()
    assert count_journal_ticks(tmp_path / "j") == 3
    assert last_journal_tick(tmp_path / "j") == 2

    j2 = TickJournal(tmp_path / "j")
    assert [r[0] for r in j2.recovered_ticks] == [0, 1, 2]
    t0 = j2.recovered_ticks[0][2]
    assert isinstance(t0, JournaledFrames) and t0.width == 4
    table = DispatchTable.from_registry(reg)
    assert np.array_equal(decode_frames_to_row([t0.blob], 4, table),
                          np.array([1, 2, 3, 4], np.float32))
    t1 = j2.recovered_ticks[1][2]
    assert np.isnan(decode_frames_to_row([t1.blob], 4, table)).all()
    with pytest.raises(ValueError):
        decode_frames_to_row([t0.blob], 5, table)  # width mismatch
    j2.close()

    # torn tail on a FRAME record truncates back to the last valid one
    seg = sorted((tmp_path / "j").glob("seg-*.rjl"))[-1]
    data = seg.read_bytes()
    seg.write_bytes(data[:-7])
    j3 = TickJournal(tmp_path / "j")
    assert j3.truncations == 1
    assert [r[0] for r in j3.recovered_ticks] == [0, 1]
    j3.close()


def test_listener_close_joins_threads_deterministically():
    """ISSUE 8 satellite: repeated open/close — with live producer
    connections blocked in recv — must leave no listener or handler
    thread behind (the conftest no-leaked-thread fixture's flake mode).
    close() wakes every handler via socket shutdown and joins bounded."""
    import socket
    import threading
    import time

    for _ in range(3):
        before = {t for t in threading.enumerate() if t.is_alive()}
        reg = _reg(n=4, group_size=4)
        src = BinaryBatchSource(reg.slot_map()).start()
        conns = [socket.create_connection(src.address) for _ in range(3)]
        # wait until every handler thread is up (it sends the MAP hello)
        for c in conns:
            c.settimeout(5.0)
            assert c.recv(1 << 16)
        src.close()
        deadline = time.time() + 5.0
        while time.time() < deadline:
            leaked = [t for t in threading.enumerate()
                      if t.is_alive() and t not in before]
            if not leaked:
                break
            time.sleep(0.02)
        assert not leaked, f"listener close leaked threads: " \
                           f"{[t.name for t in leaked]}"
        for c in conns:
            c.close()


def test_announce_leader_repoints_producers():
    """ISSUE 8: a fenced old leader pushes a MAP naming its successor;
    connected producers pick up the __leader__ hint (and the epoch bump
    makes their next stale-coded frame go loudly deaf here)."""
    import time

    from rtap_tpu.ingest.emit import BinaryFeedConnection

    reg = _reg(n=4, group_size=4)
    src = BinaryBatchSource(reg.slot_map()).start()
    try:
        with BinaryFeedConnection(src.address) as conn:
            assert conn.leader_hint is None
            e0 = conn.epoch
            src.announce_leader("127.0.0.1:12345")
            deadline = time.time() + 10
            while time.time() < deadline and not conn.poll_map():
                time.sleep(0.01)
            assert conn.leader_hint == "127.0.0.1:12345"
            assert conn.epoch == e0 + 1
    finally:
        src.close()


def test_arrival_lag_pair_is_one_atomic_tuple():
    """rtap-lint race-audit fix (ISSUE 12, docs/ANALYSIS.md): the
    latency tracker probes ``last_arrival_lag_s`` from the loop thread
    WITHOUT the source lock while handler threads record arrivals. As
    two separate attributes the (wall, ts) pair could tear — a fresh
    wall clock against a stale row ts reports a lag the wire never had —
    so the pair lives in ONE tuple rebound atomically; the property
    computes from a single snapshot."""
    import threading
    import time as _time

    reg = _reg(n=4, group_size=4)
    src = BinaryBatchSource(reg.slot_map(), port=None)
    codes = src._table.codes
    assert src.last_arrival_lag_s is None  # no data yet
    now = int(_time.time())
    src.feed_frames([data_frame(codes[:1], [1.0], now - 3)])
    lag = src.last_arrival_lag_s
    assert lag is not None and 2.0 <= lag < 60.0
    # a future-stamped producer clamps at 0, never goes negative
    src.feed_frames([data_frame(codes[:1], [2.0], now + 3600)])
    assert src.last_arrival_lag_s == 0.0
    # the surface stays a coherent snapshot under concurrent feeders:
    # every observed lag must be explainable by ONE frame's pair
    # (~0 for the future-stamped feeder, ~600 for the lagged one) —
    # a torn wall/ts mix would land far outside both bands
    stop = threading.Event()
    errs = []

    def feed(offset):
        while not stop.is_set():
            src.feed_frames([data_frame(
                codes[:1], [1.0], int(_time.time()) + offset)])

    threads = [threading.Thread(target=feed, args=(off,),
                                name=f"rtap-test-feed{off}")
               for off in (-600, 3600)]
    for t in threads:
        t.start()
    try:
        for _ in range(2000):
            lag = src.last_arrival_lag_s
            ok = lag == 0.0 or 590.0 <= lag <= 610.0
            if not ok:
                errs.append(lag)
                break
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
    assert not errs, f"torn arrival pair produced impossible lag: {errs}"
