"""The bench's failure machinery (bench.py): last-known-good fallback,
CPU-drive guards, emit idempotence. Round 2 ended with no number because
this machinery didn't exist; pin it."""

import importlib.util
import json
import sys


def load_bench(tmp_path, monkeypatch, lkg: dict | None):
    """Import bench.py as an isolated module with LKG_PATH redirected."""
    spec = importlib.util.spec_from_file_location("bench_under_test", "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.LKG_PATH = str(tmp_path / "BENCH_LKG.json")
    # the trend series is a committed artifact too: every test writes to
    # its own sandbox (a _finish() with a fresh best appends a round)
    mod.TREND_PATH = str(tmp_path / "trend_rung.json")
    if lkg is not None:
        (tmp_path / "BENCH_LKG.json").write_text(json.dumps(lkg))
    return mod


def test_emit_prefers_fresh_result(tmp_path, monkeypatch, capsys):
    b = load_bench(tmp_path, monkeypatch, {"value": 111.0, "measured_at": "x"})
    assert b.emit({"value": 42.0}) == 0  # fresh result -> exit code 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 42.0 and "cached" not in out


def test_emit_falls_back_to_lkg_flagged(tmp_path, monkeypatch, capsys):
    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    b = load_bench(tmp_path, monkeypatch, {"value": 38956.1, "measured_at": "2026-07-30"})
    # cached fallback is emitted but exits CACHED_EXIT so exit-code-only
    # consumers can tell a dead-tunnel LKG from a fresh number (ADVICE.md r3)
    assert b.emit(None) == b.CACHED_EXIT
    out = json.loads(capsys.readouterr().out.strip())
    assert out["cached"] is True and out["value"] == 38956.1
    assert out["measured_at"] == "2026-07-30" and "cached_reason" in out


def test_emit_cpu_drives_never_read_lkg(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("BENCH_ALLOW_CPU", "1")
    b = load_bench(tmp_path, monkeypatch, {"value": 38956.1, "measured_at": "x"})
    assert b.emit(None) is None
    assert capsys.readouterr().out == ""


def test_emit_without_lkg_returns_none(tmp_path, monkeypatch, capsys):
    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    b = load_bench(tmp_path, monkeypatch, None)
    assert b.emit(None) is None
    assert capsys.readouterr().out == ""


def test_emit_is_idempotent(tmp_path, monkeypatch, capsys):
    b = load_bench(tmp_path, monkeypatch, None)
    assert b.emit({"value": 1.0}) == 0
    assert b.emit({"value": 2.0}) == 0  # reports success, prints nothing new
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1 and json.loads(lines[0])["value"] == 1.0


def test_malformed_lkg_degrades_to_none(tmp_path, monkeypatch, capsys):
    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    for bad in ('{"value": null}', "[1,2]", "not json"):
        (tmp_path / "BENCH_LKG.json").write_text(bad)
        b = load_bench(tmp_path, monkeypatch, None)
        b.LKG_PATH = str(tmp_path / "BENCH_LKG.json")
        assert b.emit(None) is None, bad
    assert capsys.readouterr().out == ""


def test_store_lkg_guard_and_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_ALLOW_CPU", "1")
    b = load_bench(tmp_path, monkeypatch, None)
    b._store_lkg({"value": 9.9, "G": 1, "T": 1})
    assert not (tmp_path / "BENCH_LKG.json").exists()  # CPU drives never write

    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    b._store_lkg({"value": 9.9, "G": 1, "T": 1})
    stored = json.loads((tmp_path / "BENCH_LKG.json").read_text())
    assert stored["value"] == 9.9 and stored["G"] == 1 and "measured_at" in stored
    fallback, extra = b._load_lkg()
    assert fallback == {"value": 9.9, "G": 1, "T": 1, "modes": None, "full_rate_value": None} and extra["cached"] is True


def test_state_bytes_gate_matches_derivation(tmp_path, monkeypatch, capsys):
    """The honest per-stream figure (real arrays) and the scaling-math static
    derivation must agree on the cluster preset — the gate that keeps
    SCALING.md's capacity table and the actual layout from drifting apart
    (ISSUE 18)."""
    from rtap_tpu.analysis.scalingmath import derived_stream_bytes

    b = load_bench(tmp_path, monkeypatch, None)
    measured = b.state_bytes_gate()
    assert measured == b._STATE_BYTES == derived_stream_bytes(".", 16)
    line = json.loads(capsys.readouterr().err.strip().splitlines()[-1])
    assert line["state_bytes_gate"] == "pass"
    assert line["state_bytes_per_stream"] == measured
    # the figure rides the emitted result line
    assert b.emit({"value": 42.0}) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["state_bytes_per_stream"] == measured


def test_state_bytes_gate_fails_on_drift(tmp_path, monkeypatch, capsys):
    import pytest

    import rtap_tpu.analysis.scalingmath as sm

    b = load_bench(tmp_path, monkeypatch, None)
    monkeypatch.setattr(sm, "derived_stream_bytes", lambda root, bits: 1)
    with pytest.raises(SystemExit) as exc:
        b.state_bytes_gate()
    assert exc.value.code == 1
    line = json.loads(capsys.readouterr().err.strip().splitlines()[-2])
    assert line["state_bytes_gate"] == "FAIL"


def test_oom_dominance_skip_logic():
    """The ladder-skip predicate: only configs dominating the observed OOM
    point in BOTH dims are skipped."""
    oom_at = (2048, 64)
    skipped = [
        (g, t) for g, t in [(4096, 64), (2048, 128), (1024, 64), (4096, 32), (2048, 64)]
        if g >= oom_at[0] and t >= oom_at[1]
    ]
    assert skipped == [(4096, 64), (2048, 128), (2048, 64)]


def test_finish_tunnel_down_exits_init_watchdog(tmp_path, monkeypatch, capsys):
    """A wedged-tunnel abort with nothing fresh measured must exit
    INIT_WATCHDOG_EXIT (not CACHED_EXIT): harness loops key their retry
    budgets on that code, and a dead tunnel must never consume bench's
    attempts and park the round's headline step (hw_watch.py ledger)."""
    import pytest

    from rtap_tpu.utils.platform import INIT_WATCHDOG_EXIT

    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    b = load_bench(tmp_path, monkeypatch, {"value": 38956.1, "measured_at": "x"})
    with pytest.raises(SystemExit) as e:
        b._finish(None, tunnel_down=True)
    assert e.value.code == INIT_WATCHDOG_EXIT
    out = json.loads(capsys.readouterr().out.strip())
    assert out["cached"] is True  # the emission line survives


def test_finish_tunnel_down_with_fresh_best_is_still_fresh(tmp_path, monkeypatch, capsys):
    """If the tunnel died mid-ladder AFTER a fresh measurement landed, the
    run IS a fresh result: exit 0, store LKG, no cached flag."""
    import pytest

    b = load_bench(tmp_path, monkeypatch, None)
    with pytest.raises(SystemExit) as e:
        b._finish({"value": 42.0}, tunnel_down=True)
    assert e.value.code == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert "cached" not in out and out["value"] == 42.0


def test_emit_carries_full_rate_alongside_cadence_headline(tmp_path, monkeypatch, capsys):
    """A cadence rung wins the ladder max, so the full-rate default rung's
    number must ride the line as full_rate_value — otherwise a default-
    config regression hides behind an unchanged cadence headline."""
    b = load_bench(tmp_path, monkeypatch, None)
    b._BEST_FULL = {"value": 32893.3, "G": 256, "T": 256}
    assert b.emit({"value": 120345.6, "modes": "flat/matmul/dense/learn_every=8"}) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 120345.6
    assert out["full_rate_value"] == 32893.3
    assert out["modes"].endswith("learn_every=8")


def test_lkg_roundtrips_full_rate_value(tmp_path, monkeypatch, capsys):
    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    b = load_bench(tmp_path, monkeypatch, None)
    b._BEST_FULL = {"value": 31905.0}
    b._store_lkg({"value": 115429.0, "G": 1024, "T": 64,
                  "modes": "flat/matmul/dense/learn_every=8"})
    stored = json.loads((tmp_path / "BENCH_LKG.json").read_text())
    assert stored["full_rate_value"] == 31905.0
    b._BEST_FULL = None  # a later dead-tunnel run has no fresh full-rate
    fallback, extra = b._load_lkg()
    assert extra["cached"] is True
    assert b.emit(None) == b.CACHED_EXIT
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 115429.0 and out["full_rate_value"] == 31905.0


def test_append_trend_appends_and_preserves_protocol_study(tmp_path, monkeypatch):
    """The full-rate trend rides reports/trend_rung.json as a first-class
    series: every fresh bench appends {round, full_rate, headline} under
    "rounds" WITHOUT clobbering the protocol-study keys trend_rung.py
    owns (ISSUE 3 satellite)."""
    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    monkeypatch.setenv("BENCH_ROUND", "6")
    b = load_bench(tmp_path, monkeypatch, None)
    b.TREND_PATH = str(tmp_path / "trend_rung.json")
    (tmp_path / "trend_rung.json").write_text(json.dumps(
        {"novel_feed_metrics_per_s": 32904.0, "config": "x"}))
    b._BEST_FULL = {"value": 33100.4}
    b._append_trend({"value": 86000.2, "modes": "flat/matmul/dense/learn_every=4"})
    data = json.loads((tmp_path / "trend_rung.json").read_text())
    assert data["novel_feed_metrics_per_s"] == 32904.0  # study keys intact
    assert len(data["rounds"]) == 1
    entry = data["rounds"][0]
    assert entry["round"] == "6"
    assert entry["headline"] == 86000.2
    assert entry["full_rate"] == 33100.4
    # second fresh run appends, never rewrites history
    b._append_trend({"value": 90000.0, "modes": "m"})
    data = json.loads((tmp_path / "trend_rung.json").read_text())
    assert len(data["rounds"]) == 2


def test_append_trend_records_full_rate_hole(tmp_path, monkeypatch):
    """Every default-config rung failing must show as full_rate: null in
    the series — a hole in the trend, not a silently skipped round."""
    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    monkeypatch.delenv("BENCH_ROUND", raising=False)
    b = load_bench(tmp_path, monkeypatch, None)
    b.TREND_PATH = str(tmp_path / "trend_rung.json")
    assert b._BEST_FULL is None
    b._append_trend({"value": 50.0, "modes": "m"})
    data = json.loads((tmp_path / "trend_rung.json").read_text())
    assert data["rounds"][0]["full_rate"] is None


def test_append_trend_cpu_drive_guard(tmp_path, monkeypatch):
    """BENCH_ALLOW_CPU=1 without an explicit BENCH_TREND_PATH must never
    touch the committed series (same guard family as the LKG store)."""
    monkeypatch.setenv("BENCH_ALLOW_CPU", "1")
    monkeypatch.delenv("BENCH_TREND_PATH", raising=False)
    b = load_bench(tmp_path, monkeypatch, None)
    b.TREND_PATH = str(tmp_path / "trend_rung.json")
    b._append_trend({"value": 1.0})
    assert not (tmp_path / "trend_rung.json").exists()


def test_append_trend_survives_corrupt_artifact(tmp_path, monkeypatch):
    """_append_trend runs inside _finish (including the signal handler):
    a mangled trend artifact must degrade to a fresh series, and a
    non-JSON one must not raise through the emission path."""
    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    b = load_bench(tmp_path, monkeypatch, None)
    b.TREND_PATH = str(tmp_path / "trend_rung.json")
    (tmp_path / "trend_rung.json").write_text("{not json")
    b._append_trend({"value": 1.0})  # must not raise
    (tmp_path / "trend_rung.json").write_text("[1, 2]")  # wrong shape
    b._append_trend({"value": 2.0})
    data = json.loads((tmp_path / "trend_rung.json").read_text())
    assert [e["headline"] for e in data["rounds"]] == [2.0]


def test_infer_round_from_committed_artifacts(tmp_path, monkeypatch):
    """Unattended hw_session bench runs label trend entries one past the
    newest committed BENCH_rNN.json instead of appending null rounds."""
    monkeypatch.delenv("BENCH_ROUND", raising=False)
    b = load_bench(tmp_path, monkeypatch, None)
    # bench.py sits in the repo root next to BENCH_r01..r05
    assert b._infer_round() == "r06"
    monkeypatch.setenv("BENCH_ROUND", "override")
    assert b._infer_round() == "override"
