"""rtap-lint v4 (ISSUE 15): mesh-readiness pass fixtures.

Same discipline as test_analysis.py / test_analysis_device.py — every
new pass gets a positive (deliberately-bad snippet fails), a negative
(idiomatic-good snippet passes), and a suppressed fixture, all over
in-memory SourceFiles with synthetic paths. The armed-gate subprocess
canaries live in test_static_checks.py; this file proves the library
semantics fast. The tests/scale sweep at the bottom runs the mesh
passes over the REAL mesh test files — the code that exercises the
sharded path must itself analyze clean.
"""

import os

import pytest

from rtap_tpu.analysis import run_analysis
from rtap_tpu.analysis.core import AnalysisContext, Baseline, SourceFile

pytestmark = pytest.mark.quick

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def lint(path, code, rules=None, docs="", parity="", scaling="",
         extra=(), baseline=None):
    files = [SourceFile(path, code)]
    files += [SourceFile(p, c) for p, c in extra]
    ctx = AnalysisContext(root="/__fixture__", files=files,
                          docs_text=docs, parity_text=parity,
                          scaling_text=scaling)
    return run_analysis("/__fixture__", baseline=baseline or Baseline([]),
                        rules=set(rules) if rules is not None else None,
                        ctx=ctx)


def syms(report):
    return sorted(f.symbol for f in report.findings)


# ------------------------------------------------- partition-contract --
_TREE = ("rtap_tpu/models/_fx_state.py",
         "import numpy as np\n\n\n"
         "def init_fx(n):\n"
         "    return {\n"
         "        'alpha': np.zeros(n),  # rtap: partition[shard-streams]\n"
         "        'beta': np.zeros(n),  # rtap: partition[shard-streams]\n"
         "        'gamma': np.zeros(n),  # rtap: partition[host-only]\n"
         "    }\n")


def test_partition_unruled_and_trailing_form():
    bad = ("import numpy as np\n\n\n"
           "def init_fx(n):\n"
           "    return {\n"
           "        'alpha': np.zeros(n),  # rtap: partition[shard-streams]\n"
           "        'beta': np.zeros(n),\n"
           "        'gamma': np.zeros(n),\n"
           "    }\n")
    r = lint("rtap_tpu/models/_fx_state.py", bad, ["partition-contract"])
    assert syms(r) == ["init_fx:unruled:beta", "init_fx:unruled:gamma"]
    r2 = lint(*_TREE, rules=["partition-contract"])
    assert r2.findings == [] and r2.ok


def test_partition_module_table_and_stale_entry():
    tabled = ("# rtap: partition[alpha=shard-streams, beta=replicated,"
              " ghost=host-only]\n"
              "import numpy as np\n\n\n"
              "def init_fx(n):\n"
              "    return {\n"
              "        'alpha': np.zeros(n),\n"
              "        'beta': np.zeros(n),\n"
              "        'gamma': np.zeros(n),  # rtap: partition[host-only]\n"
              "    }\n")
    r = lint("rtap_tpu/models/_fx_state.py", tabled,
             ["partition-contract"])
    # coverage is exact BOTH directions: gamma rides its trailing rule,
    # ghost's table entry names no constructed leaf
    assert syms(r) == ["partition-table:stale:ghost"]


def test_partition_bad_rule_token_and_suppression():
    bad = ("import numpy as np\n\n\n"
           "def init_fx(n):\n"
           "    return {\n"
           "        'alpha': np.zeros(n),  # rtap: partition[sharded]\n"
           "        'beta': np.zeros(n),\n"
           "        'gamma': np.zeros(n),\n"
           "    }\n")
    r = lint("rtap_tpu/models/_fx_state.py", bad, ["partition-contract"])
    assert "partition-syntax:trailing" in syms(r)
    supp = bad.replace(
        "'beta': np.zeros(n),",
        "'beta': np.zeros(n),  # rtap: allow[partition-contract] — fx")
    r2 = lint("rtap_tpu/models/_fx_state.py", supp,
              ["partition-contract"])
    assert not any("beta" in s for s in syms(r2))
    assert any("beta" in f.symbol for f in r2.suppressed)


def test_partition_small_dicts_are_not_constructors():
    """A two-key helper dict in models/ is not a state tree — the
    structural discovery needs >= 3 array leaves, so dtype maps and
    option dicts don't drag the contract onto non-state code."""
    ok = ("import numpy as np\n\n\n"
          "def helper(n):\n"
          "    return {'a': np.zeros(n), 'b': np.ones(n)}\n")
    r = lint("rtap_tpu/models/_fx_state.py", ok, ["partition-contract"])
    assert r.findings == []


def test_partition_consumer_unknown_leaf():
    consumer = ("def fold(grp):\n"
                "    x = grp.state['alpha']\n"
                "    y = grp.state['ghost_leaf']\n"
                "    return x, y\n")
    r = lint("rtap_tpu/service/_fx_consumer.py", consumer,
             ["partition-contract"], extra=(_TREE,))
    assert syms(r) == ["fold:unknown-leaf:ghost_leaf"]
    # non-state receivers are not judged (meta dicts, option tables)
    meta = ("def read(meta):\n"
            "    return meta['ghost_leaf']\n")
    r2 = lint("rtap_tpu/service/_fx_consumer.py", meta,
              ["partition-contract"], extra=(_TREE,))
    assert r2.findings == []


def test_partition_wiring_gates():
    """shard-streams leaves demand a shard-aware checkpoint restore and
    DispatchTable-routed journal materialization — deleting either
    reference re-fails the gate."""
    naked_ck = ("rtap_tpu/service/checkpoint.py",
                "def load_group(path):\n    return path\n")
    r = lint(*_TREE, rules=["partition-contract"], extra=(naked_ck,))
    assert "restore:not-shard-aware" in syms(r)
    aware_ck = ("rtap_tpu/service/checkpoint.py",
                "def load_group(path, mesh=None):\n"
                "    from rtap_tpu.parallel.sharding import shard_state\n"
                "    return shard_state\n")
    r2 = lint(*_TREE, rules=["partition-contract"], extra=(aware_ck,))
    assert r2.findings == []
    naked_loop = ("rtap_tpu/service/loop.py",
                  "def live_loop():\n    pass\n")
    r3 = lint(*_TREE, rules=["partition-contract"], extra=(naked_loop,))
    assert "journal-frame:not-dispatch-routed" in syms(r3)


# ------------------------------------------------------ device-scope --
def test_device_scope_device0_and_suppression():
    bad = ("def probe():\n"
           "    import jax\n\n"
           "    return jax.local_devices()[0].memory_stats()\n")
    r = lint("rtap_tpu/obs/_fx_ds.py", bad, ["device-scope"])
    assert syms(r) == ["probe:device0"]
    supp = bad.replace(
        "return jax.local_devices()[0].memory_stats()",
        "return jax.local_devices()[0].memory_stats()"
        "  # rtap: allow[device-scope] — fx")
    r2 = lint("rtap_tpu/obs/_fx_ds.py", supp, ["device-scope"])
    assert r2.findings == [] and len(r2.suppressed) == 1
    # iterating the device list is the idiomatic-good form
    ok = ("def probe():\n"
          "    import jax\n\n"
          "    return [d.memory_stats() for d in jax.local_devices()]\n")
    r3 = lint("rtap_tpu/obs/_fx_ds.py", ok, ["device-scope"])
    assert r3.findings == []


def test_device_scope_fetch_and_host_boundary():
    bad = ("import jax\nimport numpy as np\n\n\n"
           "def snapshot(grp):\n"
           "    return jax.device_get(grp.state)\n\n\n"
           "def peek(st):\n"
           "    return np.asarray(st['tm_overflow'])\n")
    r = lint("rtap_tpu/service/_fx_ds.py", bad, ["device-scope"])
    assert syms(r) == ["peek:fetch:st", "snapshot:fetch:device_get"]
    # the host-boundary declaration legalizes the materialization
    ann = bad.replace("def snapshot(grp):",
                      "# rtap: host-boundary — fx owns the fetch\n"
                      "def snapshot(grp):")
    ann = ann.replace("def peek(st):",
                      "# rtap: host-boundary — fx stats read\n"
                      "def peek(st):")
    r2 = lint("rtap_tpu/service/_fx_ds.py", ann, ["device-scope"])
    assert r2.findings == []
    # host-data asarray (no state root) was never a finding
    ok = ("import numpy as np\n\n\n"
          "def parse(rows):\n"
          "    return np.asarray(rows, np.float32)\n")
    r3 = lint("rtap_tpu/service/_fx_ds.py", ok, ["device-scope"])
    assert r3.findings == []


def test_device_scope_mesh_entry_is_boundary():
    """A function that calls the parallel placement API owns placement
    in both directions — its fetches are legal without annotation."""
    ok = ("import jax\n\n"
          "from rtap_tpu.parallel.sharding import put_sharded\n\n\n"
          "def reshard(grp, mesh):\n"
          "    host = jax.device_get(grp.state)\n"
          "    return {k: put_sharded(v, mesh) for k, v in host.items()}\n")
    r = lint("rtap_tpu/service/_fx_ds.py", ok, ["device-scope"])
    assert r.findings == []


def test_device_scope_flat_id_arithmetic():
    bad = ("def route(sid, group_size):\n"
           "    return sid // group_size\n")
    r = lint("rtap_tpu/service/_fx_ds.py", bad, ["device-scope"])
    assert syms(r) == ["route:flat-id:sid"]
    # the addressing owners are exempt — the conversion LIVES there
    r2 = lint("rtap_tpu/service/registry.py", bad, ["device-scope"])
    assert r2.findings == []
    shift = ("SLOT_BITS = 12\n\n\n"
             "def unpack(code):\n"
             "    return code >> SLOT_BITS\n")
    r3 = lint("rtap_tpu/ingest/_fx_ds.py", shift, ["device-scope"])
    assert syms(r3) == ["unpack:flat-id:SLOT_BITS"]
    r4 = lint("rtap_tpu/ingest/protocol.py", shift, ["device-scope"])
    assert r4.findings == []


# --------------------------------------------- collective-discipline --
def test_collective_in_scan_body():
    bad = ("import jax\nimport jax.numpy as jnp\n\n\n"
           "def chunk(state, values):\n"
           "    def body(s, v):\n"
           "        return s, jax.lax.psum(v, axis_name='streams')\n"
           "    return jax.lax.scan(body, state, values)\n")
    r = lint("rtap_tpu/ops/_fx_cd.py", bad, ["collective-discipline"])
    assert syms(r) == ["chunk.body:collective:psum"]
    assert "collective-free" in r.findings[0].message


def test_collective_entry_points_are_legal():
    # explicit declaration
    ann = ("import jax\n\n\n"
           "# rtap: mesh-entry — fx reduction owner\n"
           "def fleet_total(x):\n"
           "    return jax.lax.psum(x, axis_name='streams')\n")
    r = lint("rtap_tpu/service/_fx_cd.py", ann, ["collective-discipline"])
    assert r.findings == []
    # discovered: the function makes placement decisions itself
    disc = ("import jax\n\n"
            "from rtap_tpu.parallel.sharding import make_stream_mesh\n\n\n"
            "def fleet_total(x):\n"
            "    mesh = make_stream_mesh(8)\n"
            "    return jax.lax.psum(x, axis_name='streams')\n")
    r2 = lint("rtap_tpu/service/_fx_cd.py", disc,
              ["collective-discipline"])
    assert r2.findings == []
    # rtap_tpu/parallel/ is the blessed home wholesale
    bare = ("import jax\n\n\n"
            "def helper(x):\n"
            "    return jax.lax.psum(x, axis_name='streams')\n")
    r3 = lint("rtap_tpu/parallel/_fx_cd.py", bare,
              ["collective-discipline"])
    assert r3.findings == []


def test_collective_foreign_method_and_suppression():
    # someone else's method named psum is not a jax collective
    ok = ("def fold(accumulator, x):\n"
          "    return accumulator.psum(x)\n")
    r = lint("rtap_tpu/obs/_fx_cd.py", ok, ["collective-discipline"])
    assert r.findings == []
    supp = ("import jax\n\n\n"
            "def fleet_total(x):\n"
            "    # rtap: allow[collective-discipline] — fx\n"
            "    return jax.lax.psum(x, axis_name='streams')\n")
    r2 = lint("rtap_tpu/service/_fx_cd.py", supp,
              ["collective-discipline"])
    assert r2.findings == [] and len(r2.suppressed) == 1


# --------------------------------------------------- shard-resource --
def test_shard_resource_sidecar_mint():
    bad = ("def sidecar_for(alert_path):\n"
           "    return alert_path + '.corr'\n")
    r = lint("rtap_tpu/service/_fx_sr.py", bad, ["shard-resource"])
    assert syms(r) == ["sidecar_for:mint"]
    # the helper module itself owns the suffixes
    r2 = lint("rtap_tpu/service/shardpath.py", bad, ["shard-resource"])
    assert r2.findings == []
    supp = bad.replace("return alert_path + '.corr'",
                       "return alert_path + '.corr'"
                       "  # rtap: allow[shard-resource] — fx")
    r3 = lint("rtap_tpu/service/_fx_sr.py", supp, ["shard-resource"])
    assert r3.findings == [] and len(r3.suppressed) == 1


def test_shard_resource_group_claim_mint():
    bad = ("import os\n\n\n"
           "def claim(ck_dir, gi):\n"
           "    return os.path.join(ck_dir, f'group{gi:04d}')\n")
    r = lint("rtap_tpu/resilience/_fx_sr.py", bad, ["shard-resource"])
    assert syms(r) == ["claim:mint"]
    # a diagnostic f-string that merely SAYS group is not a claim
    ok = ("def label(gi):\n"
          "    return f'group{gi} quarantined'\n")
    r2 = lint("rtap_tpu/resilience/_fx_sr.py", ok, ["shard-resource"])
    assert r2.findings == []


def test_shard_resource_inline_constructor_path():
    bad = ("from rtap_tpu.resilience.journal import TickJournal\n\n\n"
           "def boot(base):\n"
           "    return TickJournal(base + '/journal')\n")
    r = lint("rtap_tpu/resilience/_fx_sr.py", bad, ["shard-resource"])
    assert syms(r) == ["boot:inline-path:TickJournal"]
    ok = ("from rtap_tpu.resilience.journal import TickJournal\n\n\n"
          "def boot(journal_dir):\n"
          "    return TickJournal(journal_dir)\n")
    r2 = lint("rtap_tpu/resilience/_fx_sr.py", ok, ["shard-resource"])
    assert r2.findings == []


def test_shard_resource_serve_wiring():
    unwired = ("rtap_tpu/__main__.py",
               "def _cmd_serve(args):\n"
               "    journal = open(args.journal_dir)\n"
               "    return journal\n")
    r = lint(*unwired, rules=["shard-resource"])
    assert syms(r) == ["serve-wiring:journal_dir"]
    wired = ("rtap_tpu/__main__.py",
             "from rtap_tpu.service.shardpath import shard_scoped_path\n\n\n"
             "def _cmd_serve(args):\n"
             "    for attr in ('journal_dir',):\n"
             "        setattr(args, attr,\n"
             "                shard_scoped_path(getattr(args, attr), 0))\n"
             "    journal = open(args.journal_dir)\n"
             "    return journal\n")
    r2 = lint(*wired, rules=["shard-resource"])
    assert r2.findings == []


# ----------------------------------------------------- scaling-math --
_FX_CONFIG = ("rtap_tpu/config.py", """
def cluster_preset(perm_bits=16):
    return ModelConfig(
        rdse=RDSEConfig(size=8, active_bits=3, resolution=0.5),
        date=DateConfig(time_of_day_width=0, time_of_day_size=0,
                        weekend_width=0),
        sp=SPConfig(columns=4, perm_bits=perm_bits),
        tm=TMConfig(cells_per_column=2, max_segments_per_cell=2,
                    max_synapses_per_segment=3, perm_bits=perm_bits),
    )
""")
_FX_PERM = ("rtap_tpu/models/perm.py",
            "import numpy as np\n\n"
            "_DTYPES = {0: np.float32, 8: np.uint8, 16: np.uint16}\n")
_FX_LAW = ("scripts/scaling_law.py",
           "HBM_BYTES = 1000000\nWORKSPACE_RESERVE = 0\n")

# derived for the fixture geometry (C=4, K=2, S=2, M=3, n_in=8):
# u16 501 B, f32 661 B, u8 421 B; fits at 1 MB HBM: 1996/1512/2375
_FX_SCALING_OK = """
| perm domain | bytes/stream | max streams/chip (fx) |
|---|---|---|
| f32 | 661 | 1,512 |
| u16 quanta | 501 | 1,996 |
| u8 quanta | 421 | 2,375 |

Largest tensors (u16 domain): `presyn` 96 B, `syn_perm` 96 B, `perm` 64 B, `potential` 32 B.
"""


def _scaling_lint(scaling, extra=None):
    files = [_FX_CONFIG, _FX_PERM, _FX_LAW] if extra is None else extra
    return lint(files[0][0], files[0][1], ["scaling-math"],
                scaling=scaling, extra=tuple(files[1:]))


def test_scaling_math_green_and_stale_bytes():
    r = _scaling_lint(_FX_SCALING_OK)
    assert r.findings == [] and r.ok
    stale = _FX_SCALING_OK.replace("| u16 quanta | 501 |",
                                   "| u16 quanta | 502 |")
    r2 = _scaling_lint(stale)
    assert syms(r2) == ["bytes:u16"]
    assert "502" in r2.findings[0].message
    assert "501" in r2.findings[0].message


def test_scaling_math_stale_fit_and_tensor():
    stale_fit = _FX_SCALING_OK.replace("| 501 | 1,996 |",
                                       "| 501 | 2,000 |")
    r = _scaling_lint(stale_fit)
    assert syms(r) == ["fit:u16"]
    stale_tensor = _FX_SCALING_OK.replace("`presyn` 96 B",
                                          "`presyn` 97 B")
    r2 = _scaling_lint(stale_tensor)
    assert syms(r2) == ["tensor:presyn"]
    renamed = _FX_SCALING_OK.replace("`potential` 32 B",
                                     "`ghost_pool` 32 B")
    r3 = _scaling_lint(renamed)
    assert syms(r3) == ["tensor:ghost_pool"]


def test_scaling_math_underivable_and_absent():
    # a quoted table with no derivable config is itself a finding —
    # the memory twin must never go silently blind
    r = lint("rtap_tpu/obs/_fx_other.py", "x = 1\n", ["scaling-math"],
             scaling=_FX_SCALING_OK)
    assert syms(r) == ["derive:inputs"]
    # no analytic table in the doc -> nothing to check
    r2 = _scaling_lint("# SCALING\n\nprose only\n")
    assert r2.findings == []


def test_scaling_math_real_tree_agrees():
    """The committed SCALING.md figures agree with the real config —
    run the pass over the actual repo files (the live twin check, in
    process). Guards against the fixture diverging from reality."""
    names = ("rtap_tpu/config.py", "rtap_tpu/models/perm.py",
             "scripts/scaling_law.py")
    files = []
    for rel in names:
        with open(os.path.join(REPO, rel), encoding="utf-8") as fh:
            files.append(SourceFile(rel, fh.read()))
    with open(os.path.join(REPO, "SCALING.md"), encoding="utf-8") as fh:
        scaling = fh.read()
    ctx = AnalysisContext(root=REPO, files=files, docs_text="",
                          parity_text="", scaling_text=scaling)
    r = run_analysis(REPO, baseline=Baseline([]),
                     rules={"scaling-math"}, ctx=ctx)
    assert r.findings == [], syms(r)


# ------------------------------------ baseline matrix for the new keys --
def test_new_rules_baseline_match_whyless_stale():
    bad = ("def probe():\n"
           "    import jax\n\n"
           "    return jax.local_devices()[0].memory_stats()\n")
    entry = {"rule": "device-scope", "path": "rtap_tpu/obs/_fx_b.py",
             "symbol": "probe:device0", "why": "fixture inventory entry"}
    r = lint("rtap_tpu/obs/_fx_b.py", bad, ["device-scope"],
             baseline=Baseline([entry]))
    assert r.findings == [] and len(r.baselined) == 1
    # why-less entries are a gate failure by design
    r2 = lint("rtap_tpu/obs/_fx_b.py", bad, ["device-scope"],
              baseline=Baseline([{**entry, "why": ""}]))
    assert r2.baseline_errors and not r2.ok
    # stale entries report on a full run (rules=None)
    clean = "def probe():\n    return 0\n"
    r3 = lint("rtap_tpu/obs/_fx_b.py", clean,
              baseline=Baseline([entry]))
    assert r3.stale_baseline == [entry]


def test_update_baseline_rekeys_new_finding_kinds(tmp_path):
    """--update-baseline's mechanical re-key covers the v4 rules: a
    moved symbol (function rename) keeps its why, stale entries drop,
    new findings are refused (never minted why-less)."""
    import json

    from rtap_tpu.analysis.baseline_update import update_baseline

    root = tmp_path / "repo"
    (root / "rtap_tpu" / "obs").mkdir(parents=True)
    (root / "rtap_tpu" / "obs" / "_fx_u.py").write_text(
        "def probe_renamed():\n"
        "    import jax\n\n"
        "    return jax.local_devices()[0].memory_stats()\n")
    baseline = root / "analysis_baseline.json"
    baseline.write_text(json.dumps({"entries": [
        {"rule": "device-scope", "path": "rtap_tpu/obs/_fx_u.py",
         "symbol": "probe:device0", "why": "kept why"},
        {"rule": "shard-resource", "path": "rtap_tpu/obs/_gone.py",
         "symbol": "gone:mint", "why": "stale"},
    ]}))
    summary = update_baseline(str(root), baseline_path=str(baseline))
    data = json.loads(baseline.read_text())
    assert [tuple(k) for k in (e[1] for e in summary["rekeyed"])] == [
        ("device-scope", "rtap_tpu/obs/_fx_u.py",
         "probe_renamed:device0")]
    assert summary["dropped"] == [
        ("shard-resource", "rtap_tpu/obs/_gone.py", "gone:mint")]
    whys = {e["symbol"]: e["why"] for e in data["entries"]}
    assert whys == {"probe_renamed:device0": "kept why"}


# ------------------------------- review-pass fixes, regression-pinned --
def test_module_level_violations_are_visible():
    """Review finding: the mesh passes scanned only function bodies, so
    import-time violations passed the gate. Module scope (and class
    bodies) must be first-class — a module-level devices()[0] pick or
    sidecar mint runs at import and is worse, not exempt."""
    dev = ("import jax\n\n"
           "DEV = jax.local_devices()[0]\n")
    r = lint("rtap_tpu/service/_fx_ml.py", dev, ["device-scope"])
    assert syms(r) == ["(module):device0"]
    mint = ("ALERTS = '/tmp/a.jsonl'\n"
            "SIDECAR = ALERTS + '.corr'\n")
    r2 = lint("rtap_tpu/service/_fx_ml.py", mint, ["shard-resource"])
    assert syms(r2) == ["(module):mint"]
    coll = ("import jax\n\n"
            "_Z = jax.lax.psum(0, axis_name='streams')\n")
    r3 = lint("rtap_tpu/obs/_fx_ml.py", coll, ["collective-discipline"])
    assert syms(r3) == ["(module):collective:psum"]
    # class bodies execute at import too
    cls = ("import jax\n\n\n"
           "class Pinned:\n"
           "    DEV = jax.devices()[0]\n")
    r4 = lint("rtap_tpu/service/_fx_ml.py", cls, ["device-scope"])
    assert syms(r4) == ["(module):device0"]


def test_device0_legal_inside_mesh_entry():
    """Review finding: docs say mesh entry points own 'device picks',
    so a declared entry indexing the device list (by shard index) must
    not go red — the annotation legalizes exactly that."""
    ok = ("import jax\n\n\n"
          "# rtap: mesh-entry — fx launcher picks its shard's device\n"
          "def launch(shard):\n"
          "    return jax.devices()[shard]\n")
    r = lint("rtap_tpu/service/_fx_me.py", ok, ["device-scope"])
    assert r.findings == []


def test_partition_conflicting_rules_across_files():
    """Review finding: two models/ files declaring DIFFERENT rules for
    one leaf name silently resolved first-wins. It must be a finding."""
    other = ("rtap_tpu/models/_fx_other.py",
             "import numpy as np\n\n\n"
             "def init_other(n):\n"
             "    return {\n"
             "        'gamma': np.zeros(n),  # rtap: partition[shard-streams]\n"
             "        'delta': np.zeros(n),  # rtap: partition[shard-streams]\n"
             "        'eps': np.zeros(n),  # rtap: partition[shard-streams]\n"
             "    }\n")
    # _TREE declares gamma=host-only; the second file says shard-streams
    r = lint(*_TREE, rules=["partition-contract"], extra=(other,))
    assert "partition-conflict:gamma" in syms(r)
    # same rule in both files is NOT a conflict
    agree = (other[0], other[1].replace(
        "'gamma': np.zeros(n),  # rtap: partition[shard-streams]",
        "'gamma': np.zeros(n),  # rtap: partition[host-only]"))
    r2 = lint(*_TREE, rules=["partition-contract"], extra=(agree,))
    assert r2.findings == []


# ---------------------------------------------- tests/scale mesh sweep --
def test_scale_tree_analyzes_clean_under_mesh_rules():
    """The mesh test files themselves (tests/scale/) must satisfy the
    mesh-readiness rules when held to serve-stack scope: their fetches
    happen inside functions that own placement (they call the parallel
    API), and no collective leaks outside those functions. The code
    that PROVES the sharded path cannot itself model the anti-pattern."""
    import glob

    scale_files = sorted(glob.glob(os.path.join(REPO, "tests", "scale",
                                                "*.py")))
    assert scale_files, "tests/scale moved — update the sweep"
    files = []
    for full in scale_files:
        name = os.path.basename(full)
        with open(full, encoding="utf-8") as fh:
            files.append(SourceFile(f"rtap_tpu/service/_scale_{name}",
                                    fh.read()))
    ctx = AnalysisContext(root="/__fixture__", files=files,
                          docs_text="", parity_text="", scaling_text="")
    r = run_analysis("/__fixture__", baseline=Baseline([]),
                     rules={"device-scope", "collective-discipline"},
                     ctx=ctx)
    assert r.findings == [], syms(r)
