"""ISSUE 5 unit surface: the write-ahead tick journal + alert-id plumbing.

Torn-write fuzz is the heart: corrupt/truncate journal segments at
arbitrary byte offsets and recovery must always land on the last valid
record — a clean, bit-exact PREFIX of what was written, never a refusal
to start, always appendable afterwards. Plus: rotation/compaction/bound
mechanics, the fsync-policy parser, the <=1% self-benchmark gate, the
AlertWriter's stable alert_id / resume suppression / sink-offset
tracking / torn-line healing, ChaosSpec restart shifting, and the
supervisor's argv surgery.
"""

import json
import os
import shutil

import numpy as np
import pytest

from rtap_tpu.resilience import ChaosSpec, Fault, TickJournal
from rtap_tpu.resilience.journal import (
    count_journal_ticks,
    last_journal_tick,
    parse_fsync,
)
from rtap_tpu.resilience.supervisor import strip_supervise_flags
from rtap_tpu.service.alerts import AlertWriter, scan_alert_ids

pytestmark = pytest.mark.quick


def _fill(path, n=40, width=6, segment_bytes=1024):
    j = TickJournal(path, segment_bytes=segment_bytes)
    rows = []
    for k in range(n):
        vals = (np.arange(width, dtype=np.float32) + 10 * k)
        j.append_tick(k, 1_700_000_000 + k, vals)
        j.append_cursor(k, 100 * k)
        rows.append((k, 1_700_000_000 + k, vals))
    j.close()
    return rows


def _segments(path):
    return sorted(p for p in os.listdir(path)
                  if p.startswith("seg-") and p.endswith(".rjl"))


class TestJournalRoundtrip:
    def test_recover_bit_exact(self, tmp_path):
        rows = _fill(tmp_path / "j")
        j = TickJournal(tmp_path / "j")
        assert len(j.recovered_ticks) == len(rows)
        assert j.next_tick == len(rows)
        for (k, ts, vals), (rk, rts, rvals) in zip(rows, j.recovered_ticks):
            assert (k, ts) == (rk, rts)
            np.testing.assert_array_equal(vals, rvals)
        assert j.cursors == [(k, 100 * k) for k in range(len(rows))]
        assert j.truncations == 0
        j.close()

    def test_multivariate_rows_roundtrip(self, tmp_path):
        j = TickJournal(tmp_path / "j")
        row = np.arange(12, dtype=np.float32).reshape(4, 3)
        j.append_tick(0, 7, row)
        j.close()
        j2 = TickJournal(tmp_path / "j")
        np.testing.assert_array_equal(j2.recovered_ticks[0][2], row)
        assert j2.recovered_ticks[0][2].shape == (4, 3)
        j2.close()

    def test_rotation_and_count(self, tmp_path):
        _fill(tmp_path / "j", n=40, segment_bytes=1024)
        assert len(_segments(tmp_path / "j")) > 1
        assert count_journal_ticks(tmp_path / "j") == 40
        assert last_journal_tick(tmp_path / "j") == 39

    def test_last_tick_monotonic_across_compaction(self, tmp_path):
        """The crash soak's progress probe must keep advancing after
        checkpoint compaction drops old segments (a record COUNT
        shrinks; the tick index never does)."""
        _fill(tmp_path / "j", n=40, segment_bytes=1024)
        j = TickJournal(tmp_path / "j", segment_bytes=1024)
        j.compact(35)
        j.close()
        assert count_journal_ticks(tmp_path / "j") < 40
        assert last_journal_tick(tmp_path / "j") == 39
        assert last_journal_tick(tmp_path / "missing") == -1

    def test_appends_continue_across_reopen(self, tmp_path):
        _fill(tmp_path / "j", n=10)
        j = TickJournal(tmp_path / "j")
        assert j.next_tick == 10
        j.append_tick(10, 1_700_000_010, np.zeros(6, np.float32))
        j.close()
        j2 = TickJournal(tmp_path / "j")
        assert [r[0] for r in j2.recovered_ticks] == list(range(11))
        j2.close()

    def test_compact_drops_only_pre_checkpoint_segments(self, tmp_path):
        _fill(tmp_path / "j", n=40, segment_bytes=1024)
        j = TickJournal(tmp_path / "j", segment_bytes=1024)
        dropped = j.compact(30)
        assert dropped >= 1
        j.close()
        j2 = TickJournal(tmp_path / "j")
        ticks = [r[0] for r in j2.recovered_ticks]
        # every tick >= the checkpoint cursor survives; earlier ticks may
        # only vanish in whole-segment units
        assert ticks == list(range(ticks[0], 40))
        assert ticks[0] <= 30
        j2.close()

    def test_max_segments_bound_evicts_oldest(self, tmp_path):
        j = TickJournal(tmp_path / "j", segment_bytes=1024, max_segments=2)
        for k in range(60):
            j.append_tick(k, k, np.arange(8, dtype=np.float32))
        assert j.evicted_segments > 0
        assert len(_segments(tmp_path / "j")) <= 3  # 2 sealed + the open one
        j.close()


class TestTornWriteFuzz:
    def test_recovery_always_lands_on_last_valid_record(self, tmp_path):
        """Corrupt every journal copy at a different seeded byte offset
        (flip in any segment, truncate the tail): recovery must yield a
        bit-exact PREFIX of the written rows, count the damage, and
        leave the journal appendable."""
        src = tmp_path / "src"
        rows = _fill(src, n=40, segment_bytes=1024)
        segs = _segments(src)
        rng = np.random.default_rng(1234)
        cases = []
        for i in range(10):  # byte flips at arbitrary offsets
            seg = segs[int(rng.integers(len(segs)))]
            size = os.path.getsize(src / seg)
            cases.append(("flip", seg, int(rng.integers(size))))
        for i in range(6):  # tail truncations at arbitrary offsets
            size = os.path.getsize(src / segs[-1])
            cases.append(("trunc", segs[-1], int(rng.integers(1, size))))
        for mode, seg, off in cases:
            work = tmp_path / "work"
            if work.exists():
                shutil.rmtree(work)
            shutil.copytree(src, work)
            p = work / seg
            if mode == "flip":
                data = bytearray(p.read_bytes())
                data[off] ^= 0xFF
                p.write_bytes(bytes(data))
            else:
                with open(p, "r+b") as f:
                    f.truncate(off)
            j = TickJournal(work)  # never raises: truncate + count
            got = j.recovered_ticks
            assert len(got) <= len(rows), (mode, seg, off)
            for (k, ts, vals), (rk, rts, rvals) in zip(rows, got):
                assert (k, ts) == (rk, rts), (mode, seg, off)
                np.testing.assert_array_equal(vals, rvals)
            if len(got) < len(rows):
                assert j.truncations + j.dropped_segments > 0, \
                    (mode, seg, off)
            # the journal keeps working from the surviving prefix
            j.append_tick(j.next_tick, 1, np.zeros(6, np.float32))
            nxt = j.next_tick
            j.close()
            j2 = TickJournal(work)
            assert j2.next_tick == nxt
            assert [r[0] for r in j2.recovered_ticks] == \
                [r[0] for r in got] + [nxt - 1]
            j2.close()

    def test_recovery_truncates_file_idempotently(self, tmp_path):
        _fill(tmp_path / "j", n=8, segment_bytes=1 << 20)
        seg = _segments(tmp_path / "j")[0]
        p = tmp_path / "j" / seg
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) - 5)
        j = TickJournal(tmp_path / "j")
        assert j.truncations == 1
        j.close()
        j2 = TickJournal(tmp_path / "j")  # second pass: nothing left to cut
        assert j2.truncations == 0
        j2.close()


class TestFsyncPolicy:
    def test_parse(self):
        assert parse_fsync("os") == ("os", 0)
        assert parse_fsync("every-tick") == ("every-tick", 0)
        assert parse_fsync("every-64") == ("every-n", 64)

    @pytest.mark.parametrize("bad", ["", "always", "every-0", "every-x",
                                     "every--3"])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            parse_fsync(bad)

    def test_policies_fsync_counts(self, tmp_path):
        j = TickJournal(tmp_path / "a", fsync="every-tick")
        for k in range(5):
            j.append_tick(k, k, np.zeros(4, np.float32))
        assert j.fsyncs == 5
        j.close()
        j = TickJournal(tmp_path / "b", fsync="every-n", fsync_every=3)
        for k in range(7):
            j.append_tick(k, k, np.zeros(4, np.float32))
        assert j.fsyncs == 2
        j.close()
        j = TickJournal(tmp_path / "c", fsync="os")
        j.append_tick(0, 0, np.zeros(4, np.float32))
        assert j.fsyncs == 0
        j.close()


def test_journal_overhead_within_one_percent_of_tick_budget():
    """ISSUE 5 satellite: journaling (tick append + cursor append at the
    1024-stream row width) stays <= 1% of the 1 s cadence, same bar as
    the metrics registry and the trace/flight recorders."""
    from rtap_tpu.obs.selfbench import measure_journal

    res = measure_journal(n=300)
    assert res["per_tick_overhead_frac"] <= 0.01, res


class TestAlertIdsAndSuppression:
    def _emit(self, w, ids, tick, group=0, alerting=None):
        n = len(ids)
        al = np.ones(n, bool) if alerting is None else np.asarray(alerting)
        w.emit_batch(ids, np.full(n, 1_700_000_000 + tick),
                     np.full(n, 30.0, np.float32), np.full(n, 0.5, np.float32),
                     np.full(n, 0.9), al, group=group, tick=tick)

    def test_lines_carry_stable_alert_id(self, tmp_path):
        path = str(tmp_path / "a.jsonl")
        w = AlertWriter(path)
        self._emit(w, ["s0", "s1"], tick=3, group=1)
        w.close()
        lines = [json.loads(x) for x in open(path)]
        assert [d["alert_id"] for d in lines] == ["1:s0:3", "1:s1:3"]

    def test_epoch_suffixed_group_passes_through(self, tmp_path):
        # a quarantine-restored group's rewound timeline emits under
        # an epoch-suffixed group field (loop._alert_gid)
        path = str(tmp_path / "a.jsonl")
        w = AlertWriter(path)
        self._emit(w, ["s0"], tick=5, group="3.e2")
        w.close()
        assert json.loads(open(path).readline())["alert_id"] == "3.e2:s0:5"

    def test_no_id_without_tick_context(self, tmp_path):
        path = str(tmp_path / "a.jsonl")
        w = AlertWriter(path)
        n = 1
        w.emit_batch(["s0"], np.full(n, 1), np.full(n, 30.0, np.float32),
                     np.full(n, 0.5, np.float32), np.full(n, 0.9),
                     np.ones(n, bool))
        w.close()
        assert "alert_id" not in json.loads(open(path).readline())

    def test_suppression_is_exactly_once(self, tmp_path):
        path = str(tmp_path / "a.jsonl")
        w = AlertWriter(path)
        w.arm_suppression({"0:s0:1", "0:s1:1"})
        self._emit(w, ["s0", "s1"], tick=0)  # not suppressed
        self._emit(w, ["s0", "s1"], tick=1)  # both suppressed
        self._emit(w, ["s0", "s1"], tick=1)  # set drained: written again
        w.close()
        ids = [json.loads(x)["alert_id"] for x in open(path)]
        assert ids == ["0:s0:0", "0:s1:0", "0:s0:1", "0:s1:1"]
        assert w.suppressed == 2
        assert w.count == 6  # threshold crossings counted regardless

    def test_sink_offset_tracks_disk_size(self, tmp_path):
        path = str(tmp_path / "a.jsonl")
        w = AlertWriter(path)
        assert w.sink_offset() == 0
        self._emit(w, ["s0"], tick=0)
        w.flush_sink()
        assert w.sink_offset() == os.path.getsize(path)
        w.emit_event({"event": "x", "tick": 1})
        assert w.sink_offset() == os.path.getsize(path)  # events flush
        w.close()
        w2 = AlertWriter(path)  # reopen: cursor continues from disk size
        assert w2.sink_offset() == os.path.getsize(path)
        w2.close()

    def test_torn_line_healed_on_reopen(self, tmp_path):
        path = str(tmp_path / "a.jsonl")
        with open(path, "w") as f:
            f.write('{"alert_id": "0:s0:0", "stream": "s0"}\n{"alert_id')
        w = AlertWriter(path)
        assert w.torn_heals == 1
        self._emit(w, ["s1"], tick=1)
        w.close()
        lines = open(path).read().splitlines()
        assert lines[1] == '{"alert_id'  # fragment isolated on its own line
        assert json.loads(lines[2])["alert_id"] == "0:s1:1"

    def test_scan_alert_ids_from_offset(self, tmp_path):
        path = str(tmp_path / "a.jsonl")
        w = AlertWriter(path)
        self._emit(w, ["s0"], tick=0)
        w.flush_sink()
        cursor = w.sink_offset()
        self._emit(w, ["s0"], tick=1)
        w.emit_event({"event": "noise", "tick": 1})
        w.close()
        assert scan_alert_ids(path, cursor) == {"0:s0:1"}
        assert scan_alert_ids(path, 0) == {"0:s0:0", "0:s0:1"}
        assert scan_alert_ids(str(tmp_path / "missing.jsonl")) == set()


class TestRestartPlumbing:
    def test_chaos_spec_shifted(self):
        spec = ChaosSpec(faults=[
            Fault(kind="proc_exit", tick=5),
            Fault(kind="source_timeout", tick=8, duration=4),
            Fault(kind="alert_sink_oserror", tick=2),
        ], seed=0)
        s = spec.shifted(6)
        kinds = {(f.kind, f.tick, f.duration) for f in s.faults}
        # fired faults drop; the straddling window clips to the remainder
        assert kinds == {("source_timeout", 2, 4)}
        assert spec.shifted(0) is spec

    def test_generated_schedules_never_include_proc_exit(self):
        spec = ChaosSpec.generate(seed=3, n_ticks=400, rate=0.5)
        assert spec.faults and all(
            f.kind != "proc_exit" for f in spec.faults)

    def test_strip_supervise_flags(self):
        argv = ["serve", "--streams", "a,b", "--supervise",
                "--supervise-restarts", "4", "--supervise-backoff=0.1",
                "--ticks", "9"]
        assert strip_supervise_flags(argv) == \
            ["serve", "--streams", "a,b", "--ticks", "9"]

    def test_supervise_cli_requires_checkpoint_dir(self, capsys):
        from rtap_tpu.__main__ import main

        rc = main(["serve", "--streams", "s0", "--supervise",
                   "--backend", "cpu"])
        assert rc == 2
        assert "--checkpoint-dir" in capsys.readouterr().err
