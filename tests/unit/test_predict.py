"""PredictTracker / BlastFuser / lead-time scorer unit matrix (ISSUE 16).

The device↔twin reducer parity lives in
tests/parity/test_predict_parity.py; this suite covers the HOST side:
warm-up gating, edge-triggered hysteresis and re-arm, pad-slot
discipline, suppression replay, blast-radius fusion over a declared
topology, the cascade workload's precursor ramp, and
eval/fault_eval.score_lead_time's win condition.
"""

import numpy as np
import pytest

from rtap_tpu.correlate import TopologyMap
from rtap_tpu.data.synthetic import (
    SyntheticStreamConfig,
    generate_topology_workload,
)
from rtap_tpu.eval.fault_eval import score_lead_time
from rtap_tpu.predict import BlastFuser, PredictTracker

SPEC = {"services": {"web": ["web-00", "web-01"], "db": ["db-00"]}}


def _leaves(ewma, scored=None, overlap=None, col_frac=None):
    """One fold's [T, G] leaf dict from a [T, G] (or [G]) ewma array."""
    e = np.atleast_2d(np.asarray(ewma, np.float32))
    s = np.isfinite(e) if scored is None \
        else np.atleast_2d(np.asarray(scored, bool))
    ov = np.where(s, np.float32(1.0) - e, np.nan).astype(np.float32) \
        if overlap is None else np.atleast_2d(np.asarray(overlap, np.float32))
    cf = np.full_like(e, 0.05) if col_frac is None \
        else np.atleast_2d(np.asarray(col_frac, np.float32))
    return {"miss_ewma": e, "scored": s, "overlap": ov,
            "pred_col_frac": cf}


def _tracker(**kw):
    kw.setdefault("horizon", 4)
    kw.setdefault("threshold", 0.5)
    kw.setdefault("min_ticks", 3)
    kw.setdefault("warmup_ticks", 5)
    events = []
    t = PredictTracker(sink=events.append, **kw)
    return t, events


# ------------------------------------------------------------- tracker --
def test_precursor_fires_once_after_warmup_and_min_ticks():
    t, events = _tracker()
    tick = 0
    # 4 cool scored ticks (warm-up samples), then hot forever
    for _ in range(4):
        t.fold(0, _leaves([0.1, 0.1]), tick=tick, ids=["a", "b"])
        tick += 1
    for _ in range(10):
        t.fold(0, _leaves([0.9, 0.1]), tick=tick, ids=["a", "b"])
        tick += 1
    pre = [e for e in events if e["event"] == "precursor"]
    assert len(pre) == 1
    ev = pre[0]
    # warm-up needs 5 samples; run needs 3 consecutive hot: whichever
    # binds later — hot ticks start at tick 4, warmup satisfied at 4,
    # run of 3 completes on tick 6
    assert ev["stream"] == "a" and ev["tick"] == 6
    assert ev["alert_id"] == "precursor:a:6"
    assert ev["predicted_lead_ticks"] == 4
    assert ev["miss_ewma"] == pytest.approx(0.9)
    # latched: no refire while hot
    assert t.stats()["streams_alarmed"] == 1


def test_warmup_blocks_early_hot_streams():
    t, events = _tracker(warmup_ticks=8)
    for k in range(6):
        t.fold(0, _leaves([0.9]), tick=k, ids=["a"])
    assert not events  # only 6 samples < 8, despite run >= min_ticks


def test_rearm_below_half_threshold_then_refire():
    t, events = _tracker(warmup_ticks=0)
    tick = 0
    for _ in range(3):
        t.fold(0, _leaves([0.9]), tick=tick, ids=["a"]); tick += 1
    assert len(events) == 1
    # cooling to 0.3 (>= rearm 0.25) keeps the latch
    t.fold(0, _leaves([0.3]), tick=tick, ids=["a"]); tick += 1
    # below rearm_frac * threshold re-arms
    t.fold(0, _leaves([0.2]), tick=tick, ids=["a"]); tick += 1
    for _ in range(3):
        t.fold(0, _leaves([0.9]), tick=tick, ids=["a"]); tick += 1
    assert len(events) == 2
    assert events[1]["tick"] > events[0]["tick"]


def test_unscored_ticks_hold_run_scored_cool_resets():
    t, events = _tracker(warmup_ticks=0)
    t.fold(0, _leaves([0.9]), tick=0, ids=["a"])
    t.fold(0, _leaves([0.9]), tick=1, ids=["a"])
    # outage tick: unscored (NaN) must HOLD the run, not reset it
    t.fold(0, _leaves([np.nan], scored=[False]), tick=2, ids=["a"])
    t.fold(0, _leaves([0.9]), tick=3, ids=["a"])
    assert len(events) == 1 and events[0]["tick"] == 3
    # a SCORED cool tick resets the run
    t2, ev2 = _tracker(warmup_ticks=0)
    t2.fold(0, _leaves([0.9]), tick=0, ids=["a"])
    t2.fold(0, _leaves([0.9]), tick=1, ids=["a"])
    t2.fold(0, _leaves([0.1]), tick=2, ids=["a"])
    t2.fold(0, _leaves([0.9]), tick=3, ids=["a"])
    assert not ev2


def test_pad_slots_never_page():
    t, events = _tracker(warmup_ticks=0)
    for k in range(5):
        t.fold(0, _leaves([0.9, 0.9]), tick=k, ids=["a", "__pad1"])
    assert [e["stream"] for e in events] == ["a"]


def test_multi_row_chunk_fold_ticks_back_from_last():
    """A [T, G] chunk folds row i at tick - (T - 1 - i): the precursor's
    tick (and alert_id) is exact even inside a chunk."""
    t, events = _tracker(warmup_ticks=0)
    e = np.stack([np.full(1, 0.9, np.float32)] * 3)  # [3, 1] all hot
    t.fold(0, _leaves(e), tick=12, ids=["a"])
    assert events and events[0]["tick"] == 12  # rows 10, 11, 12
    assert events[0]["alert_id"] == "precursor:a:12"


def test_suppression_swallows_replayed_ids_but_latches_state():
    t, events = _tracker(warmup_ticks=0)
    t.arm_suppression({"precursor:a:2"})
    for k in range(3):
        t.fold(0, _leaves([0.9]), tick=k, ids=["a"])
    assert not events
    assert t.events_suppressed == 1
    assert t.stats()["streams_alarmed"] == 1  # latched — no double fire
    for k in range(3, 6):
        t.fold(0, _leaves([0.9]), tick=k, ids=["a"])
    assert not events


def test_snapshot_and_scorecard_schema():
    t, _ = _tracker()
    t.fold(0, _leaves([0.2, np.nan], scored=[True, False]),
           tick=0, ids=["a", "b"])
    snap = t.snapshot()
    assert snap["fleet"]["groups"] == 1
    assert snap["fleet"]["horizon_ticks"] == 4
    g = snap["groups"][0]
    assert g["streams_scored"] == 1
    assert g["miss_ewma"]["max"] == pytest.approx(0.2)
    assert g["verdict"] == "ok"
    assert "blast" not in snap  # no fuser attached
    stats = t.stats()
    assert stats["ticks_folded"] == 1 and stats["verdict"] == "ok"


def test_tracker_parameter_validation():
    for kw in ({"horizon": 0}, {"threshold": 0.0}, {"threshold": 1.5},
               {"min_ticks": 0}, {"warmup_ticks": -1},
               {"rearm_frac": 1.5}):
        with pytest.raises(ValueError):
            PredictTracker(**{"horizon": 4, **kw})


# ---------------------------------------------------------------- blast --
def test_blast_first_precursor_opens_window_and_predicts_radius():
    b = BlastFuser(TopologyMap.from_spec(SPEC))
    inc = b.precursor("web-00.cpu", 100, {"alert_id": "precursor:web-00.cpu:100"})
    assert inc is not None
    assert inc["event"] == "predicted_incident"
    assert inc["first_node"] == "web-00"
    # the whole declared service is the predicted radius
    assert set(inc["blast_radius"]) >= {"web-00", "web-01"}
    assert inc["alert_id"].startswith("predicted_incident:")
    snap = b.snapshot()
    assert snap["open"] and snap["open"][0]["incident_id"] == inc["alert_id"]
    # later precursors in the open window attach silently
    assert b.precursor("web-01.cpu", 110,
                       {"alert_id": "precursor:web-01.cpu:110"}) is None


def test_blast_window_expires_then_new_incident():
    b = BlastFuser(TopologyMap.from_spec(SPEC), window_ticks=50)
    a = b.precursor("web-00.cpu", 0, {"alert_id": "p:0"})
    assert a is not None
    assert b.precursor("web-00.cpu", 40, {"alert_id": "p:40"}) is None
    c = b.precursor("web-00.cpu", 200, {"alert_id": "p:200"})
    assert c is not None and c["alert_id"] != a["alert_id"]


def test_blast_observe_streams_extends_radius():
    b = BlastFuser(TopologyMap.from_spec(SPEC))
    b.observe_streams(["web-00.cpu", "web-01.mem", "__pad3"])
    inc = b.precursor("web-00.cpu", 5, {"alert_id": "p:5"})
    assert {"web-00", "web-01"} <= set(inc["blast_radius"])
    assert not any(n.startswith("__pad") for n in inc["blast_radius"])


# --------------------------------------------------------- lead scoring --
def _cascade_events():
    return [
        {"event": "precursor", "stream": "svca-00.cpu", "tick": 250},
        {"event": "predicted_incident", "tick": 250,
         "alert_id": "predicted_incident:svca:250", "first_node": "svca-00",
         "blast_radius": ["svca-00", "svca-01", "svca-02"]},
        {"event": "precursor", "stream": "svcb-01.cpu", "tick": 260},
        {"event": "precursor", "stream": "svca-01.mem", "tick": 315},
    ]


def test_score_lead_time_win_and_false_precursors():
    sc = score_lead_time(
        _cascade_events(),
        {"svca-00": 300, "svca-01": 308, "svca-02": 316},
        ["svca-00", "svca-01", "svca-02"])
    assert sc["win"] and sc["paged"] and sc["blast_covered"]
    assert sc["page_tick"] == 250
    assert sc["lead_ticks_vs_origin"] == 50
    assert sc["lead_ticks_vs_second"] == 58
    assert sc["false_precursors"] == 1  # the svcb one
    assert sc["first_precursor_by_node"] == {"svca-00": 250, "svca-01": 315}
    assert sc["predicted_incident"]["incident_id"] == \
        "predicted_incident:svca:250"


def test_score_lead_time_late_page_is_not_a_win():
    events = [{"event": "precursor", "stream": "svca-00.cpu", "tick": 310}]
    sc = score_lead_time(events, {"svca-00": 300, "svca-01": 308},
                         ["svca-00", "svca-01"])
    assert sc["paged"] and not sc["win"]
    assert sc["lead_ticks_vs_second"] == -2
    assert not sc["blast_covered"]  # no incident at all


def test_score_lead_time_no_events():
    sc = score_lead_time([], {"n0": 10, "n1": 20}, ["n0", "n1"])
    assert not sc["paged"] and not sc["win"]
    assert sc["page_tick"] is None


# ------------------------------------------------------ cascade workload --
def test_precursor_ramp_digest_stable_and_shape():
    scfg = SyntheticStreamConfig(length=200, n_anomalies=0,
                                 noise_phi=0.9, noise_scale=0.3)
    base = generate_topology_workload(n_services=2, nodes_per_service=2,
                                      cfg=scfg, seed=5)
    ramp = generate_topology_workload(n_services=2, nodes_per_service=2,
                                      cfg=scfg, seed=5,
                                      precursor_ramp=6.0,
                                      precursor_ticks=40)
    assert ramp.precursor_node == ramp.burst_nodes[0]
    onset = ramp.burst_onsets[ramp.precursor_node]
    assert ramp.precursor_start == onset - 40
    by_id = {s.stream_id: s for s in base.streams}
    for s in ramp.streams:
        b = by_id[s.stream_id]
        if s.stream_id.startswith(ramp.precursor_node):
            d = np.asarray(s.values, np.float64) - \
                np.asarray(b.values, np.float64)
            # zero outside the ramp span, monotone non-trivial inside
            assert d[:ramp.precursor_start].max() == 0.0
            assert (d[onset:] == 0.0).all()
            inner = d[ramp.precursor_start:onset]
            assert inner[0] == 0.0 and inner[-1] > 0.0
        else:
            # every other stream (incl. the ramp-free call) byte-stable
            np.testing.assert_array_equal(s.values, b.values,
                                          err_msg=s.stream_id)


def test_precursor_ramp_validation():
    scfg = SyntheticStreamConfig(length=200, n_anomalies=0)
    with pytest.raises(ValueError, match="together"):
        generate_topology_workload(cfg=scfg, precursor_ramp=1.0)
    with pytest.raises(ValueError, match="does not fit"):
        generate_topology_workload(cfg=scfg, precursor_ramp=1.0,
                                   precursor_ticks=10_000)
    with pytest.raises(ValueError, match=">= 0"):
        generate_topology_workload(cfg=scfg, precursor_ramp=-1.0,
                                   precursor_ticks=4)
