"""rtap_tpu.resilience unit surface: retry/backoff determinism, breaker
state machine, degradation ladder hysteresis, chaos-spec determinism, and
the non-fatal IO edges (send_jsonl, AlertWriter) — no serve loop here
(tests/integration/test_chaos_serve.py drives the loop end to end)."""

import json

import numpy as np
import pytest

from rtap_tpu.resilience import (
    ChaosEngine,
    ChaosSpec,
    CircuitBreaker,
    CircuitOpenError,
    DegradationController,
    Fault,
    Retry,
)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---- Retry ----------------------------------------------------------


def test_retry_is_deterministic_per_seed():
    a = Retry(attempts=5, base_delay_s=0.1, jitter=0.5, seed=7,
              sleep=lambda s: None)
    b = Retry(attempts=5, base_delay_s=0.1, jitter=0.5, seed=7,
              sleep=lambda s: None)
    assert [a.delay_for(i) for i in range(1, 5)] == \
        [b.delay_for(i) for i in range(1, 5)]
    # and the backoff actually grows exponentially under the cap
    c = Retry(attempts=5, base_delay_s=0.1, max_delay_s=10.0, jitter=0.0)
    assert [c.delay_for(i) for i in (1, 2, 3)] == [0.1, 0.2, 0.4]


def test_retry_call_retries_then_raises():
    slept = []
    r = Retry(attempts=3, base_delay_s=0.01, jitter=0.0, sleep=slept.append)
    calls = []

    def fail():
        calls.append(1)
        raise OSError("nope")

    with pytest.raises(OSError):
        r.call(fail)
    assert len(calls) == 3 and len(slept) == 2  # no sleep after the last


def test_retry_succeeds_midway_and_filters_exceptions():
    r = Retry(attempts=3, base_delay_s=0.0, sleep=lambda s: None)
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert r.call(flaky) == "ok"
    # non-retry_on exceptions propagate immediately (one call, no retry)
    state["n"] = 0

    def bug():
        state["n"] += 1
        raise ValueError("programming error")

    with pytest.raises(ValueError):
        r.call(bug)
    assert state["n"] == 1


# ---- CircuitBreaker -------------------------------------------------


def test_breaker_opens_after_threshold_and_half_open_probes():
    clk = _Clock()
    br = CircuitBreaker(fail_threshold=3, cooldown_s=10.0, clock=clk,
                        name="t1")
    for _ in range(2):
        br.record_failure()
    assert br.state == br.CLOSED and br.allow()
    br.record_failure()  # third consecutive: open
    assert br.state == br.OPEN
    assert not br.allow()  # short-circuited inside the cooldown
    clk.t = 11.0
    assert br.allow()  # half-open: one probe admitted
    assert br.state == br.HALF_OPEN
    assert not br.allow()  # no second probe before the verdict
    br.record_failure()  # probe failed: re-open, cooldown restarts
    assert br.state == br.OPEN and not br.allow()
    clk.t = 22.0
    assert br.allow()
    br.record_success()  # probe landed: closed, counters reset
    assert br.state == br.CLOSED and br.consecutive_failures == 0


def test_breaker_call_raises_circuit_open():
    clk = _Clock()
    br = CircuitBreaker(fail_threshold=1, cooldown_s=5.0, clock=clk,
                        name="t2")
    with pytest.raises(OSError):
        br.call(lambda: (_ for _ in ()).throw(OSError("x")))
    assert br.state == br.OPEN
    with pytest.raises(CircuitOpenError):
        br.call(lambda: "never runs")
    clk.t = 6.0
    assert br.call(lambda: "ok") == "ok"
    assert br.state == br.CLOSED


# ---- DegradationController -----------------------------------------


def test_degradation_ladder_escalates_and_recovers_with_hysteresis():
    events = []
    ctl = DegradationController(window=5, degrade_after=2, recover_after=3,
                                thin_factor=4, widen_factor=2.0,
                                event_sink=events.append)
    assert ctl.level == 0 and ctl.learn_allowed(1) and ctl.cadence_scale == 1
    ctl.observe(0, True)
    assert ctl.level == 0  # one miss is not a trend
    ctl.observe(1, True)
    assert ctl.level == 1  # learn_thin
    assert ctl.learn_allowed(4) and not ctl.learn_allowed(5)
    # the escalation cleared the window: the NEXT level needs fresh misses
    ctl.observe(2, True)
    assert ctl.level == 1
    ctl.observe(3, True)
    assert ctl.level == 2  # score_only
    assert not ctl.learn_allowed(4)
    ctl.observe(4, True)
    ctl.observe(5, True)
    assert ctl.level == 3 and ctl.cadence_scale == 2.0  # tick_widen
    # recovery: one level per recover_after consecutive clean ticks
    for t in range(6, 9):
        ctl.observe(t, False)
    assert ctl.level == 2
    ctl.observe(9, True)  # a miss resets the clean run
    for t in range(10, 13):
        ctl.observe(t, False)
    assert ctl.level == 1
    kinds = [e["event"] for e in events]
    assert kinds == ["degraded", "degraded", "degraded", "recovered",
                     "recovered"]
    assert events[2] == {"event": "degraded", "tick": 5, "level": 3,
                         "step": "tick_widen"}
    assert ctl.stats()["max_level"] == 3


def test_degradation_never_escalates_past_the_ladder():
    ctl = DegradationController(window=3, degrade_after=1, recover_after=99)
    for t in range(10):
        ctl.observe(t, True)
    assert ctl.level == 3


# ---- ChaosSpec / ChaosEngine ---------------------------------------


def test_chaos_spec_generate_is_seed_deterministic():
    a = ChaosSpec.generate(seed=42, n_ticks=200, n_groups=4, rate=0.1)
    b = ChaosSpec.generate(seed=42, n_ticks=200, n_groups=4, rate=0.1)
    c = ChaosSpec.generate(seed=43, n_ticks=200, n_groups=4, rate=0.1)
    assert a.to_dict() == b.to_dict() and a.digest() == b.digest()
    assert a.digest() != c.digest()
    assert a.faults  # rate 0.1 over 200 ticks: statistically certain
    # round-trips through the --chaos-spec JSON shape
    back = ChaosSpec.from_dict(json.loads(json.dumps(a.to_dict())))
    assert back.digest() == a.digest()


def test_chaos_engine_injects_at_scheduled_ticks_only():
    spec = ChaosSpec(faults=[
        Fault(kind="dispatch_exception", tick=3, group=1),
        Fault(kind="source_timeout", tick=2, duration=2, streams=(0,)),
        Fault(kind="checkpoint_oserror", tick=5),
    ])
    eng = ChaosEngine(spec)
    eng.on_dispatch(0, 3)  # wrong group: no fault
    eng.on_dispatch(1, 2)  # wrong tick: no fault
    with pytest.raises(RuntimeError, match="chaos"):
        eng.on_dispatch(1, 3)
    with pytest.raises(OSError):
        eng.on_checkpoint_save(0, 5)  # group None = every group

    def src(tick):
        return np.array([1.0, 2.0], np.float32), 100 + tick

    wrapped = eng.wrap_source(src)
    v, _ = wrapped(1)
    assert not np.isnan(v).any()
    v, _ = wrapped(2)
    assert np.isnan(v[0]) and not np.isnan(v[1])  # targeted stream only
    v, _ = wrapped(3)  # duration 2: still active
    assert np.isnan(v[0])
    v, _ = wrapped(4)
    assert not np.isnan(v).any()
    assert [e["kind"] for e in eng.injected] == [
        "dispatch_exception", "checkpoint_oserror", "source_timeout",
        "source_timeout"]


def test_chaos_engine_group_targeted_source_timeout_uses_routing():
    """A generated source_timeout carries a GROUP, not stream indices;
    the engine must resolve it through the loop-provided routing so only
    that group's slice goes NaN (serve --chaos-spec with a generate
    spec — healthy groups keep bit-identical inputs)."""
    eng = ChaosEngine(ChaosSpec(faults=[
        Fault(kind="source_timeout", tick=0, group=1)]))
    eng.set_group_streams({0: (0, 1), 1: (2, 3)})
    wrapped = eng.wrap_source(lambda t: (np.ones(4, np.float32), 5))
    v, _ = wrapped(0)
    assert np.isnan(v[[2, 3]]).all()
    assert not np.isnan(v[[0, 1]]).any()
    # without a mapping (bare StreamGroup callers), whole-vector NaN is
    # the declared fallback
    eng2 = ChaosEngine(ChaosSpec(faults=[
        Fault(kind="source_timeout", tick=0, group=1)]))
    v2, _ = eng2.wrap_source(lambda t: (np.ones(4, np.float32), 5))(0)
    assert np.isnan(v2).all()


def test_chaos_topology_burst_floods_targeted_streams_only():
    """The ISSUE 9 blast-radius fault: targeted indices gain `magnitude`
    for the window, bystanders stay bit-identical, and a co-firing
    source_timeout NaN stays NaN (a dead exporter reports nothing,
    burst or not)."""
    eng = ChaosEngine(ChaosSpec(faults=[
        Fault(kind="topology_burst", tick=1, duration=2, streams=(1, 2),
              magnitude=7.5),
        Fault(kind="source_timeout", tick=2, streams=(2,))]))
    wrapped = eng.wrap_source(lambda t: (np.ones(4, np.float32), 5))
    v0, _ = wrapped(0)          # before the window: untouched
    assert (v0 == 1.0).all()
    v1, _ = wrapped(1)
    assert v1.tolist() == [1.0, 8.5, 8.5, 1.0]
    v2, _ = wrapped(2)          # timeout wins on the overlapping index
    assert v2[1] == 8.5 and np.isnan(v2[2])
    v3, _ = wrapped(3)          # window over
    assert (v3 == 1.0).all()
    assert [e["kind"] for e in eng.injected].count("topology_burst") == 2


def test_chaos_topology_burst_spec_round_trips_and_shifts():
    """`magnitude` serializes for topology_burst only (pre-ISSUE-9 specs
    keep their exact dict shape — the digest pin in test_replicate.py)
    and survives both the JSON round-trip and a restart shift."""
    spec = ChaosSpec(faults=[
        Fault(kind="topology_burst", tick=4, duration=3, streams=(0, 1),
              magnitude=3.25),
        Fault(kind="source_malformed", tick=1)])
    d = spec.to_dict()
    assert d["faults"][0]["magnitude"] == 3.25
    assert "magnitude" not in d["faults"][1]
    back = ChaosSpec.from_dict(json.loads(json.dumps(d)))
    assert back.digest() == spec.digest()
    assert back.faults[0].magnitude == 3.25
    shifted = spec.shifted(5)
    assert shifted.faults == [Fault(kind="topology_burst", tick=0,
                                    duration=2, streams=(0, 1),
                                    magnitude=3.25)]


def test_chaos_spec_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown fault kind"):
        Fault(kind="meteor_strike", tick=0)
    with pytest.raises(ValueError, match="'faults' OR 'generate'"):
        ChaosSpec.from_dict({"faults": [], "generate": {"n_ticks": 1}})


# ---- send_jsonl bounded retry --------------------------------------


def test_send_jsonl_returns_zero_on_dead_listener_without_raising():
    from rtap_tpu.service.sources import send_jsonl

    fast = Retry(attempts=2, base_delay_s=0.01, jitter=0.0,
                 op="send_jsonl_test")
    # port 9 (discard) refuses on loopback in this environment; a raise
    # here was exactly the mid-soak producer death ISSUE 2 names
    delivered = send_jsonl(("127.0.0.1", 9),
                           [{"id": "a", "value": 1.0}], retry=fast)
    assert delivered == 0


def test_send_jsonl_delivers_and_counts():
    from rtap_tpu.service.sources import TcpJsonlSource, send_jsonl

    ids = ["a", "b"]
    with TcpJsonlSource(ids) as src:
        n = send_jsonl(src.address, [
            {"id": "a", "value": 1.0, "ts": 10},
            {"id": "b", "value": 2.0, "ts": 11},
        ])
        assert n == 2
        import time

        deadline = time.time() + 2.0
        got = np.full(2, np.nan, np.float32)
        while time.time() < deadline and np.isnan(got).any():
            v, _ = src(0)
            got = np.where(np.isnan(got), v, got)
            time.sleep(0.02)
        np.testing.assert_allclose(got, [1.0, 2.0])


# ---- AlertWriter non-fatal sink ------------------------------------


class _FlakyFile:
    """In-memory file that raises OSError while `broken` is True."""

    def __init__(self):
        self.lines: list[str] = []
        self.flushes = 0
        self.broken = False

    def _check(self):
        if self.broken:
            raise OSError(28, "no space left on device")

    def write(self, s):
        self._check()
        self.lines.append(s)

    def writelines(self, lines):
        self._check()
        self.lines.extend(lines)

    def flush(self):
        self._check()
        self.flushes += 1

    def close(self):
        pass


def _writer_with(fh, flush_every=1, breaker=None, tmp_path=None):
    from rtap_tpu.service.alerts import AlertWriter

    w = AlertWriter(str(tmp_path / "a.jsonl"), flush_every=flush_every,
                    breaker=breaker)
    w._fh.close()
    w._fh = fh
    return w


def _emit_one(w, alert=True):
    return w.emit_batch(["s0"], np.array([100]), np.array([1.0]),
                        np.array([0.5]), np.array([9.9]),
                        np.array([alert]))


def test_alert_writer_batches_writes_and_honors_flush_cadence(tmp_path):
    fh = _FlakyFile()
    w = _writer_with(fh, flush_every=3, tmp_path=tmp_path)
    for _ in range(6):
        _emit_one(w)
    assert len(fh.lines) == 6
    assert fh.flushes == 2  # once per 3 batches, not per batch
    # events always flush (rare, load-bearing)
    w.emit_event({"event": "x"})
    assert fh.flushes == 3


def test_alert_writer_survives_full_disk_and_recovers(tmp_path):
    clk = _Clock()
    br = CircuitBreaker(fail_threshold=2, cooldown_s=5.0, clock=clk,
                        name="alert_sink_test")
    fh = _FlakyFile()
    w = _writer_with(fh, breaker=br, tmp_path=tmp_path)
    _emit_one(w)
    assert len(fh.lines) == 1 and w.dropped == 0
    fh.broken = True  # the disk fills
    _emit_one(w)  # failure 1 (after its immediate retry)
    _emit_one(w)  # failure 2: breaker opens -> sink quarantined
    assert w.dropped == 2 and w.sink_quarantines == 1
    assert br.state == br.OPEN
    _emit_one(w)  # quarantined: dropped with zero write attempts
    assert w.dropped == 3
    # alert COUNTING is sink-independent: scoring never noticed
    assert w.count == 4
    fh.broken = False  # space freed
    clk.t = 6.0  # cooldown passed: next batch is the half-open probe
    _emit_one(w)
    assert br.state == br.CLOSED
    # the probe line landed, plus the restored event announcing the gap
    assert any('"event": "alert_sink_restored"' in ln for ln in fh.lines)
    assert sum('"stream"' in ln for ln in fh.lines) == 2
    w.close()


def test_alert_writer_none_path_still_counts(tmp_path):
    from rtap_tpu.service.alerts import AlertWriter

    w = AlertWriter(None)
    assert _emit_one(w) == 1
    assert w.count == 1 and w.dropped == 0
    w.close()


def test_alert_writer_rejects_bad_flush_every():
    from rtap_tpu.service.alerts import AlertWriter

    with pytest.raises(ValueError, match="flush_every"):
        AlertWriter(None, flush_every=0)


# ---- HttpPollSource breaker ----------------------------------------


def test_http_poll_breaker_short_circuits_dead_endpoint():
    from rtap_tpu.service.sources import HttpPollSource

    clk = _Clock()
    br = CircuitBreaker(fail_threshold=2, cooldown_s=30.0, clock=clk,
                        name="http_poll_test")
    fast = Retry(attempts=1, base_delay_s=0.0, op="http_poll_test")
    src = HttpPollSource("http://127.0.0.1:9/nothing", ["a"], timeout_s=0.2,
                         retry=fast, breaker=br)
    src(0)
    src(1)  # second consecutive failure: breaker opens
    assert src.poll_failures == 2 and br.state == br.OPEN
    import time

    t0 = time.perf_counter()
    v, ts = src(2)  # short-circuited: NaN immediately, no connect wait
    # well under the 0.2 s connect timeout proves no dial was attempted;
    # the old 0.05 s bound flaked when suite load preempted the host
    # mid-call (pin semantics, not speed) — the counters below are the
    # real short-circuit proof
    assert time.perf_counter() - t0 < 0.15
    assert np.isnan(v).all() and ts > 0
    assert src.polls_short_circuited == 1
    assert src.poll_failures == 2  # no attempt, no new failure


def test_multivariate_source_raise_on_first_tick_does_not_quarantine():
    """A source that RAISES on tick 0 of a multivariate serve must score a
    NaN missing-sample tick shaped [G, n_fields] — not a [G] substitute
    whose dispatch shape error would quarantine every group permanently."""
    import numpy as np

    from rtap_tpu.config import node_preset
    from rtap_tpu.service.loop import live_loop
    from rtap_tpu.service.registry import StreamGroupRegistry

    cfg = node_preset(n_metrics=3, perm_bits=16)
    reg = StreamGroupRegistry(cfg, group_size=2, backend="cpu")
    for i in range(2):
        reg.add_stream(f"n{i}")
    reg.finalize()

    def source(k):
        if k == 0:
            raise OSError("collector not up yet")
        rng = np.random.default_rng(k)
        return (30 + rng.random((2, 3))).astype(np.float32), 1_700_000_000 + k

    stats = live_loop(source, reg, n_ticks=4, cadence_s=0.0)
    assert stats["ticks"] == 4
    assert not stats.get("quarantined")
    assert stats["scored_by_group"] == [8]  # 4 ticks x 2 streams, no gap
