"""Regenerate the frozen golden outputs (SURVEY.md §4 item 4).

Run manually after a *deliberate* semantic change:
    python tests/golden/generate_golden.py
The paired test regenerates the same deterministic inputs and asserts
bit-identical raw scores and log-likelihoods against the frozen file.
"""

from pathlib import Path

import numpy as np

GOLDEN_PATH = Path(__file__).parent / "golden_config1.npz"
ROWS = 400


def golden_config():
    from rtap_tpu.config import (
        DateConfig,
        LikelihoodConfig,
        ModelConfig,
        RDSEConfig,
        SPConfig,
        TMConfig,
    )

    # mid-size model: small enough to run in seconds, big enough to exercise
    # every code path (date bits, boosting off, pools, punishment)
    return ModelConfig(
        rdse=RDSEConfig(size=200, active_bits=11, resolution=0.9),
        date=DateConfig(time_of_day_width=11, time_of_day_size=32),
        sp=SPConfig(columns=512, num_active_columns=20),
        tm=TMConfig(cells_per_column=8, activation_threshold=9, min_threshold=6,
                    max_segments_per_cell=8, max_synapses_per_segment=16,
                    new_synapse_count=12),
        likelihood=LikelihoodConfig(learning_period=60, estimation_samples=30,
                                    reestimation_period=20, averaging_window=5),
    )


def run(tmp_root):
    from rtap_tpu.data.nab_corpus import ensure_standin_corpus, load_corpus
    from rtap_tpu.models import AnomalyDetector

    root = ensure_standin_corpus(tmp_root)
    files = load_corpus(root)
    nf = next(f for f in files if "5f5533" in f.name)
    det = AnomalyDetector(golden_config(), seed=0)
    raw = np.zeros(ROWS)
    loglik = np.zeros(ROWS)
    for i in range(ROWS):
        res = det.model.run(int(nf.timestamps[i]), float(nf.values[i]))
        raw[i], loglik[i] = res.raw_score, res.log_likelihood
    return raw, loglik


GOLDEN_Q16_PATH = Path(__file__).parent / "golden_cluster_q16.npz"
Q16_ROWS = 900


def run_quant():
    """Golden for the quantized (u16) dense-pool cluster geometry over a
    deterministic synthetic stream — pins the fixed-point arithmetic itself
    (a change to quantum conversion or integer update order shows up here
    even if oracle/device parity still holds, since both would drift
    together). dense_cluster_preset IS the pre-ISSUE-18 cluster_preset
    geometry, so the committed golden survives the sparse-pool flip
    unchanged — the strongest no-regression proof for the dense path."""
    import dataclasses

    from rtap_tpu.config import dense_cluster_preset
    from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_stream
    from rtap_tpu.models import AnomalyDetector

    base = dense_cluster_preset(perm_bits=16)
    cfg = dataclasses.replace(
        base, likelihood=dataclasses.replace(base.likelihood, mode="window")
    )
    s = generate_stream(
        "golden.cpu",
        SyntheticStreamConfig(length=Q16_ROWS, n_anomalies=1,
                              kinds=("level_shift",), anomaly_magnitude=6.0,
                              noise_phi=0.97, noise_scale=0.5,
                              inject_after_frac=cfg.likelihood.safe_inject_frac(Q16_ROWS)),
        seed=33,
    )
    det = AnomalyDetector(cfg, seed=0)
    raw = np.zeros(Q16_ROWS)
    loglik = np.zeros(Q16_ROWS)
    for i in range(Q16_ROWS):
        res = det.model.run(int(s.timestamps[i]), float(s.values[i]))
        raw[i], loglik[i] = res.raw_score, res.log_likelihood
    return raw, loglik


GOLDEN_SPARSE_PATH = Path(__file__).parent / "golden_cluster_sparse.npz"


def run_sparse():
    """Golden for the SHIPPING cluster preset (sparse member-index pools,
    u16 quanta, S=2 TM lanes — ISSUE 18) over the same deterministic stream
    as run_quant: pins the gather-addressed overlap/learning arithmetic
    against history the way the dense golden pins the matmul path."""
    import dataclasses

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_stream
    from rtap_tpu.models import AnomalyDetector

    base = cluster_preset(perm_bits=16)
    cfg = dataclasses.replace(
        base, likelihood=dataclasses.replace(base.likelihood, mode="window")
    )
    s = generate_stream(
        "golden.cpu",
        SyntheticStreamConfig(length=Q16_ROWS, n_anomalies=1,
                              kinds=("level_shift",), anomaly_magnitude=6.0,
                              noise_phi=0.97, noise_scale=0.5,
                              inject_after_frac=cfg.likelihood.safe_inject_frac(Q16_ROWS)),
        seed=33,
    )
    det = AnomalyDetector(cfg, seed=0)
    raw = np.zeros(Q16_ROWS)
    loglik = np.zeros(Q16_ROWS)
    for i in range(Q16_ROWS):
        res = det.model.run(int(s.timestamps[i]), float(s.values[i]))
        raw[i], loglik[i] = res.raw_score, res.log_likelihood
    return raw, loglik


if __name__ == "__main__":
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        raw, loglik = run(Path(td) / "nab")
    np.savez(GOLDEN_PATH, raw=raw, loglik=loglik)
    print(f"wrote {GOLDEN_PATH}: raw mean={raw.mean():.4f} loglik mean={loglik.mean():.4f}")
    raw, loglik = run_quant()
    np.savez(GOLDEN_Q16_PATH, raw=raw, loglik=loglik)
    print(f"wrote {GOLDEN_Q16_PATH}: raw mean={raw.mean():.4f} loglik mean={loglik.mean():.4f}")
    raw, loglik = run_sparse()
    np.savez(GOLDEN_SPARSE_PATH, raw=raw, loglik=loglik)
    print(f"wrote {GOLDEN_SPARSE_PATH}: raw mean={raw.mean():.4f} loglik mean={loglik.mean():.4f}")
