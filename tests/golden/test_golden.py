"""Golden regression: frozen per-row raw/log-likelihood sequences for the
config-1 stream (SURVEY.md §4 item 4). Any semantic drift in encoder, SP,
TM, or likelihood shows up here as a bit-level diff."""

import numpy as np

from tests.golden.generate_golden import GOLDEN_PATH, run


def test_golden_config1(tmp_path):
    assert GOLDEN_PATH.exists(), "run python tests/golden/generate_golden.py"
    golden = np.load(GOLDEN_PATH)
    raw, loglik = run(tmp_path / "nab")
    np.testing.assert_array_equal(raw, golden["raw"])
    np.testing.assert_allclose(loglik, golden["loglik"], atol=1e-12)
