"""Golden regression: frozen per-row raw/log-likelihood sequences for the
config-1 stream (SURVEY.md §4 item 4). Any semantic drift in encoder, SP,
TM, or likelihood shows up here as a bit-level diff."""

import numpy as np

from tests.golden.generate_golden import GOLDEN_PATH, run


def test_golden_config1(tmp_path):
    assert GOLDEN_PATH.exists(), "run python tests/golden/generate_golden.py"
    golden = np.load(GOLDEN_PATH)
    raw, loglik = run(tmp_path / "nab")
    np.testing.assert_array_equal(raw, golden["raw"])
    np.testing.assert_allclose(loglik, golden["loglik"], atol=1e-12)


def test_golden_cluster_quantized():
    """Frozen sequence for the u16 dense cluster geometry
    (dense_cluster_preset = the pre-ISSUE-18 cluster_preset, so this golden
    predates the sparse flip and proves the dense path untouched): pins the
    fixed-point permanence arithmetic against history (parity tests can't
    catch a drift that moves oracle and device together)."""
    from tests.golden.generate_golden import GOLDEN_Q16_PATH, run_quant

    assert GOLDEN_Q16_PATH.exists(), "run python tests/golden/generate_golden.py"
    golden = np.load(GOLDEN_Q16_PATH)
    raw, loglik = run_quant()
    np.testing.assert_array_equal(raw, golden["raw"])
    np.testing.assert_allclose(loglik, golden["loglik"], atol=1e-12)


def test_golden_cluster_sparse():
    """Frozen sequence for the shipping sparse cluster preset (member-index
    pools, ISSUE 18): pins the gather-addressed arithmetic against history."""
    from tests.golden.generate_golden import GOLDEN_SPARSE_PATH, run_sparse

    assert GOLDEN_SPARSE_PATH.exists(), "run python tests/golden/generate_golden.py"
    golden = np.load(GOLDEN_SPARSE_PATH)
    raw, loglik = run_sparse()
    np.testing.assert_array_equal(raw, golden["raw"])
    np.testing.assert_allclose(loglik, golden["loglik"], atol=1e-12)
