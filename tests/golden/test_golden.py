"""Golden regression: frozen per-row raw/log-likelihood sequences for the
config-1 stream (SURVEY.md §4 item 4). Any semantic drift in encoder, SP,
TM, or likelihood shows up here as a bit-level diff."""

import numpy as np

from tests.golden.generate_golden import GOLDEN_PATH, run


def test_golden_config1(tmp_path):
    assert GOLDEN_PATH.exists(), "run python tests/golden/generate_golden.py"
    golden = np.load(GOLDEN_PATH)
    raw, loglik = run(tmp_path / "nab")
    np.testing.assert_array_equal(raw, golden["raw"])
    np.testing.assert_allclose(loglik, golden["loglik"], atol=1e-12)


def test_golden_cluster_quantized():
    """Frozen sequence for the u16 cluster preset: pins the fixed-point
    permanence arithmetic against history (parity tests can't catch a drift
    that moves oracle and device together)."""
    from tests.golden.generate_golden import GOLDEN_Q16_PATH, run_quant

    assert GOLDEN_Q16_PATH.exists(), "run python tests/golden/generate_golden.py"
    golden = np.load(GOLDEN_Q16_PATH)
    raw, loglik = run_quant()
    np.testing.assert_array_equal(raw, golden["raw"])
    np.testing.assert_allclose(loglik, golden["loglik"], atol=1e-12)
