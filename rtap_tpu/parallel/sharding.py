"""Stream-axis sharding utilities (SURVEY.md §2.4).

A stream group's state pytree carries the group axis G as the leading
dimension of every leaf; sharding that axis over a 1-D `("streams",)` mesh
splits the group across chips with zero collectives in the hot loop (each
chip steps its own stream shard; XLA inserts no cross-chip communication
because no op mixes streams). Host code gathers only the [G] raw-score
vector per tick.

Multi-host (DCN) replay uses `init_distributed()` (a thin
`jax.distributed.initialize` wrapper) before mesh construction, after which
`jax.devices()` spans all hosts and the same sharding code applies.
"""

from __future__ import annotations

import numpy as np


def init_distributed(coordinator: str | None = None, num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Initialize multi-host JAX (DCN) when launched as one process per host.

    No-op when running single-process (the common case and every test); args
    default to the JAX_* / cloud-TPU environment autodetection.
    """
    import jax

    if num_processes in (None, 1) and coordinator is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_stream_mesh(n_devices: int | None = None):
    """1-D device mesh over the stream axis: Mesh([d0..dn], ("streams",))."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("streams",))


def stream_sharding(mesh, ndim: int, axis: int = 0):
    """NamedSharding that splits the stream axis (default: leading) over the
    mesh and replicates every other axis."""
    from jax.sharding import NamedSharding, PartitionSpec

    spec = [None] * ndim
    spec[axis] = "streams"
    return NamedSharding(mesh, PartitionSpec(*spec))


def put_sharded(value: np.ndarray, mesh, axis: int = 0):
    """Host array -> device array sharded on `axis` over the stream mesh.

    Single-process: a plain device_put. Multi-process (DCN: one process per
    host after init_distributed): jax.make_array_from_callback, where each
    process materializes only the shards its local devices own — the
    supported way to build a global array across hosts (device_put of a
    global numpy array raises on non-addressable devices).
    """
    import jax

    value = np.asarray(value)
    sharding = stream_sharding(mesh, max(np.ndim(value), 1), axis)
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    return jax.make_array_from_callback(value.shape, sharding, lambda idx: value[idx])


def broadcast_group_state(single: dict, group_size: int, mesh) -> dict:
    """Build the sharded [G, ...] group state directly from ONE stream's
    state dict, without ever materializing the full group on host.

    `replicate_state` + `shard_state` peaks at several copies of the full
    group (measured 4.7x of state size at G=4k — fatal at the 100k-stream
    x ~54 GiB scale). Here each shard's host-side source is a numpy
    broadcast VIEW of the single-stream leaf (zero bytes), copied exactly
    once into its device buffer by make_array_from_callback. Works
    single-process and multi-host (callback materializes only local shards).
    """
    import jax

    n = mesh.devices.size
    if group_size % n:
        raise ValueError(
            f"group size {group_size} not divisible by mesh size {n} (the "
            "registry pads groups to a fixed size — pick a multiple of the "
            "chip count)"
        )
    out = {}
    for k, v in single.items():
        v = np.asarray(v)
        shape = (group_size, *v.shape)
        sharding = stream_sharding(mesh, len(shape), 0)

        def cb(idx, v=v):
            n = len(range(*idx[0].indices(group_size)))
            return np.broadcast_to(v[None], (n, *v.shape))

        out[k] = jax.make_array_from_callback(shape, sharding, cb)
    return out


def shard_state(state: dict, mesh) -> dict:
    """Shard every leaf of a group state pytree on its leading (stream) axis
    over the mesh. Group size must be divisible by the mesh size (the
    registry pads groups to a fixed size, so pick group_size as a multiple
    of the chip count). Works single-process and multi-host (see
    :func:`put_sharded`)."""
    n = mesh.devices.size
    for k, v in state.items():
        if np.shape(v) and np.shape(v)[0] % n:
            raise ValueError(
                f"state leaf {k!r} group axis {np.shape(v)[0]} not divisible by mesh size {n}"
            )
    return {k: put_sharded(v, mesh) for k, v in state.items()}
