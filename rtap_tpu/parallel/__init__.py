"""Cross-chip parallelism: stream sharding over the device mesh.

The reference's only parallelism is share-nothing per-stream processes
(SURVEY.md §2.3); the TPU-native analog is data parallelism over a 1-D
`("streams",)` mesh — streams never communicate, so the hot loop is
collective-free by design and scales linearly over ICI. TP/PP/EP/CP and
sequence parallelism are deliberately absent: HTM is a recurrent
O(1)-state-per-step algorithm with no attention and no sequence-length
scaling problem (SURVEY.md §5 "Long-context").
"""

from rtap_tpu.parallel.sharding import (
    broadcast_group_state,
    init_distributed,
    make_stream_mesh,
    put_sharded,
    shard_state,
    stream_sharding,
)

__all__ = [
    "broadcast_group_state",
    "init_distributed",
    "make_stream_mesh",
    "put_sharded",
    "shard_state",
    "stream_sharding",
]
