/* Native frame walker for the RB1 binary batch ingest protocol
 * (rtap_tpu/ingest/protocol.py owns the format; docs/INGEST.md is the
 * operator reference).
 *
 * The socket drain path hands each recv() chunk to one scan call: it
 * delimits complete frames, validates magic/reserved/count sanity and
 * the trailing crc32, resyncs over garbage to the next magic, and
 * reports per-frame header fields back as int64 tuples — so the Python
 * side touches one object per FRAME (thousands of rows), never per
 * byte. Semantics are pinned 1:1 against the pure-Python fallback
 * (protocol.scan_frames_py) by tests/unit/test_ingest_protocol.py; any
 * divergence is a bug here.
 *
 * Same build/fallback discipline as jsonl_parser.c: compiled on demand
 * by rtap_tpu/native/__init__.py, and callers treat a load failure as
 * "native path unavailable" (pure-Python walker takes over).
 */

#include <stdint.h>
#include <string.h>

#define HEADER_SIZE 20
#define CRC_SIZE 4
#define ROW_SIZE 10
#define KIND_DATA 1
#define KIND_NAMES 2
#define KIND_MAP 3
#define PROTOCOL_VERSION 1
#define MAX_DATA_ROWS (1LL << 22)
#define MAX_BLOB_BYTES (16LL << 20)

/* zlib-compatible CRC-32 (IEEE reflected, init/final xor 0xffffffff) */
static uint32_t crc_table[256];
static int crc_ready = 0;

static void crc_init(void) {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xedb88320u ^ (c >> 1) : c >> 1;
        crc_table[i] = c;
    }
    crc_ready = 1;
}

static uint32_t crc32_calc(const unsigned char *p, long long n) {
    if (!crc_ready) crc_init();
    uint32_t c = 0xffffffffu;
    for (long long i = 0; i < n; i++)
        c = crc_table[(c ^ p[i]) & 0xffu] ^ (c >> 8);
    return c ^ 0xffffffffu;
}

static uint32_t load_u32(const unsigned char *p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
           ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

static int64_t load_i64(const unsigned char *p) {
    uint64_t v = 0;
    for (int i = 7; i >= 0; i--) v = (v << 8) | p[i];
    return (int64_t)v;
}

/* next occurrence of "RB1" at/after pos, or -1 */
static long long find_magic(const unsigned char *buf, long long n,
                            long long pos) {
    for (long long i = pos; i + 3 <= n; i++) {
        if (buf[i] == 'R' && buf[i + 1] == 'B' && buf[i + 2] == '1')
            return i;
    }
    return -1;
}

/* Scan buf for complete frames.
 *
 * out: up to out_cap frames x 8 int64s each:
 *      [kind, version, epoch, tenant_off, tenant_len, count, base_ts,
 *       payload_off]
 * stats: int64[4] — [garbage_bytes, bad_crc, version_skew, consumed]
 *        (accumulated into, caller zeroes; consumed is SET).
 * Returns the number of frames written (scan stops early at out_cap —
 * the Python wrapper loops on the unconsumed remainder).
 */
long long rtap_fw_scan(const unsigned char *buf, long long n,
                       int64_t *out, long long out_cap, int64_t *stats) {
    long long off = 0, emitted = 0;
    while (off + HEADER_SIZE <= n && emitted < out_cap) {
        if (!(buf[off] == 'R' && buf[off + 1] == 'B' &&
              buf[off + 2] == '1')) {
            long long nxt = find_magic(buf, n, off + 1);
            long long skip_to = nxt >= 0 ? nxt
                                         : (n - 2 > off + 1 ? n - 2 : off + 1);
            stats[0] += skip_to - off;
            off = skip_to;
            continue;
        }
        int version = buf[off + 3];
        int kind = buf[off + 4];
        int tlen = buf[off + 5];
        uint32_t epoch = (uint32_t)buf[off + 6] |
                         ((uint32_t)buf[off + 7] << 8);
        int64_t count = (int64_t)load_u32(buf + off + 8);
        int64_t base_ts = load_i64(buf + off + 12);
        int sane = (kind == KIND_DATA ? count <= MAX_DATA_ROWS
                                      : count <= MAX_BLOB_BYTES);
        if (!sane) {
            long long nxt = find_magic(buf, n, off + 1);
            long long skip_to = nxt >= 0 ? nxt
                                         : (n - 2 > off + 1 ? n - 2 : off + 1);
            stats[0] += skip_to - off;
            off = skip_to;
            continue;
        }
        int64_t payload = kind == KIND_DATA ? count * ROW_SIZE : count;
        long long end = off + HEADER_SIZE + tlen + payload + CRC_SIZE;
        if (end > n) break; /* torn tail: wait for more bytes */
        uint32_t crc = load_u32(buf + end - CRC_SIZE);
        if (crc != crc32_calc(buf + off + 3,
                              end - CRC_SIZE - (off + 3))) {
            stats[1] += 1;
            long long nxt = find_magic(buf, n, off + 1);
            long long skip_to = nxt >= 0 ? nxt
                                         : (n - 2 > off + 1 ? n - 2 : off + 1);
            stats[0] += skip_to - off;
            off = skip_to;
            continue;
        }
        if (version != PROTOCOL_VERSION ||
            (kind != KIND_DATA && kind != KIND_NAMES && kind != KIND_MAP)) {
            /* framing fields are frozen across versions: skip whole,
             * counted — forward compatibility, not corruption */
            stats[2] += 1;
            off = end;
            continue;
        }
        int64_t *m = out + emitted * 8;
        m[0] = kind;
        m[1] = version;
        m[2] = (int64_t)epoch;
        m[3] = off + HEADER_SIZE;
        m[4] = tlen;
        m[5] = count;
        m[6] = base_ts;
        m[7] = off + HEADER_SIZE + tlen;
        emitted++;
        off = end;
    }
    stats[3] = off;
    return emitted;
}
