"""Native host-runtime pieces (C, loaded via ctypes — no pybind11 in this
environment). Currently: the JSONL metrics-ingest parser (SURVEY.md C18)
and the RB1 binary-ingest frame walker (ISSUE 7, rtap_tpu/ingest/).

Each shared library is compiled on demand from its adjacent .c source
with the system compiler into ``_build/`` (atomic rename, so concurrent
processes can race the build safely) and cached until the source
changes. Callers must treat ImportError/OSError from the loaders as
"native path unavailable" and fall back to pure Python — the service
must run (slower) on hosts without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "jsonl_parser.c")
_BUILD_DIR = os.path.join(_DIR, "_build")
_SO = os.path.join(_BUILD_DIR, "jsonl_parser.so")
_FW_SRC = os.path.join(_DIR, "frame_walker.c")
_FW_SO = os.path.join(_BUILD_DIR, "frame_walker.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_fw_lib: ctypes.CDLL | None = None


def _compile(src: str = _SRC, so: str = _SO) -> None:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["cc", "-O2", "-shared", "-fPIC", "-std=c99", "-o", tmp, src],
            check=True, capture_output=True, text=True,
        )
        os.replace(tmp, so)  # atomic: concurrent builders both win
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load() -> ctypes.CDLL:
    """The parser library, compiling it first if missing or stale.
    Raises on any failure (no toolchain, compile error) — callers fall
    back to the pure-Python parser."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            _compile()
        lib = ctypes.CDLL(_SO)
        lib.rtap_parser_new.restype = ctypes.c_void_p
        lib.rtap_parser_new.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.rtap_parser_clone.restype = ctypes.c_void_p
        lib.rtap_parser_clone.argtypes = [ctypes.c_void_p]
        lib.rtap_parser_free_clone.restype = None
        lib.rtap_parser_free_clone.argtypes = [ctypes.c_void_p]
        lib.rtap_parser_free_owner.restype = None
        lib.rtap_parser_free_owner.argtypes = [ctypes.c_void_p]
        f64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.rtap_parser_set_table.restype = ctypes.c_int
        lib.rtap_parser_set_table.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32]
        lib.rtap_parser_feed.restype = ctypes.c_int
        lib.rtap_parser_feed.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long, f32p, f64p, f64p,
            u8p, f64p, ctypes.c_long]
        lib.rtap_parser_flush.restype = None
        lib.rtap_parser_flush.argtypes = [
            ctypes.c_void_p, f32p, f64p, f64p, u8p, f64p, ctypes.c_long]
        _lib = lib
        return _lib


class NativeJsonlState:
    """Listener-wide native parse state: the id hash table plus the shared
    output buffers the C code writes into.

    ``latest`` is the caller's float32 [G] array — feed() updates it in
    place (the caller must never reallocate it). ``counters`` is
    [parsed, parse_errors, unknown_ids]; ``ts_buf[0]`` is the running ts
    maximum. One :class:`ConnParser` per connection carries that
    connection's partial-line remainder; the caller serializes feed()
    calls across connections with its own lock.
    """

    #: unknown-name capture buffer ("id\n" entries; full = drop, Python
    #: dedups and the id re-surfaces next tick)
    UNKNOWN_BUF_BYTES = 1 << 16

    def __init__(self, stream_ids: list[str], latest: np.ndarray,
                 track_unknown: bool = False):
        if latest.dtype != np.float32 or not latest.flags.c_contiguous:
            raise ValueError("latest must be a C-contiguous float32 array")
        self._lib = load()
        ids = [sid.encode() for sid in stream_ids]
        blob = b"".join(ids)
        lens = (ctypes.c_int32 * len(ids))(*[len(b) for b in ids])
        self._owner = self._lib.rtap_parser_new(blob, lens, len(ids))
        if not self._owner:
            raise MemoryError("rtap_parser_new failed")
        self.latest = latest
        self.ts_buf = np.zeros(1, np.int64)
        self.counters = np.zeros(3, np.int64)
        self.unk_buf = np.zeros(self.UNKNOWN_BUF_BYTES, np.uint8)
        # cap 0 disables capture in C (no memcpy on the hot locked path
        # when nothing will ever drain the buffer)
        self.unk_cap = self.UNKNOWN_BUF_BYTES if track_unknown else 0
        self.unk_cur = np.zeros(1, np.int64)

    def new_conn(self) -> "ConnParser":
        return ConnParser(self)

    def set_table(self, stream_ids: list[str], latest: np.ndarray) -> None:
        """Swap the id table + output array (registry membership changed).
        The caller must hold the listener lock that serializes feed() —
        every per-connection parser observes the new table on its next
        line via the shared indirection; partial-line state survives."""
        if latest.dtype != np.float32 or not latest.flags.c_contiguous:
            raise ValueError("latest must be a C-contiguous float32 array")
        ids = [sid.encode() for sid in stream_ids]
        blob = b"".join(ids)
        lens = (ctypes.c_int32 * len(ids))(*[len(b) for b in ids])
        if self._lib.rtap_parser_set_table(self._owner, blob, lens, len(ids)):
            raise MemoryError("rtap_parser_set_table failed")
        self.latest = latest

    def drain_unknown_names(self) -> list[str]:
        """Pop captured unknown-id names (caller holds the listener lock).

        Strict UTF-8: invalid-byte ids are dropped — a name that cannot
        round-trip to its wire bytes would register a permanently
        valueless model (the C side already skips escaped ids for the
        same must-match-json.loads reason)."""
        n = int(self.unk_cur[0])
        if n == 0:
            return []
        raw = bytes(self.unk_buf[:n])
        self.unk_cur[0] = 0
        out = []
        for s in raw.split(b"\n"):
            if not s:
                continue
            try:
                out.append(s.decode("utf-8"))
            except UnicodeDecodeError:
                pass
        return out

    def __del__(self):
        owner = getattr(self, "_owner", None)
        if owner:
            self._lib.rtap_parser_free_owner(owner)
            self._owner = None


class ConnParser:
    """Per-connection parser (owns the partial-line remainder)."""

    def __init__(self, state: NativeJsonlState):
        self._state = state
        self._h = state._lib.rtap_parser_clone(state._owner)
        if not self._h:
            raise MemoryError("rtap_parser_clone failed")

    def feed(self, data: bytes) -> None:
        st = self._state
        st._lib.rtap_parser_feed(self._h, data, len(data),
                                 st.latest, st.ts_buf, st.counters,
                                 st.unk_buf, st.unk_cur, st.unk_cap)

    def flush(self) -> None:
        st = self._state
        st._lib.rtap_parser_flush(self._h, st.latest, st.ts_buf, st.counters,
                                  st.unk_buf, st.unk_cur, st.unk_cap)

    def close(self) -> None:
        if self._h:
            self._state._lib.rtap_parser_free_clone(self._h)
            self._h = None

    def __del__(self):
        self.close()


# ---------------------------------------------------------------------
# RB1 frame walker (frame_walker.c) — the binary-ingest scan fast path
# ---------------------------------------------------------------------

#: frames per C scan call; the wrapper loops, so this only bounds the
#: meta array allocation, not throughput
_FW_CAP = 4096


def load_frame_walker() -> ctypes.CDLL:
    """The frame-walker library, compiling it first if missing or
    stale. Raises on any failure — callers fall back to the pure-Python
    walker (rtap_tpu/ingest/protocol.py)."""
    global _fw_lib
    with _lock:
        if _fw_lib is not None:
            return _fw_lib
        if (not os.path.exists(_FW_SO)
                or os.path.getmtime(_FW_SO) < os.path.getmtime(_FW_SRC)):
            _compile(_FW_SRC, _FW_SO)
        lib = ctypes.CDLL(_FW_SO)
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.rtap_fw_scan.restype = ctypes.c_longlong
        lib.rtap_fw_scan.argtypes = [
            u8p, ctypes.c_longlong, i64p, ctypes.c_longlong, i64p]
        _fw_lib = lib
        return _fw_lib


_fw_tls = threading.local()  # reused per-thread scan buffers (the scan
# runs per recv chunk on the ingest hot path; a fresh 224 KiB meta
# allocation per chunk was measurable)


def frame_walker_scan(buf) -> tuple[list[tuple], int, dict]:
    """Native twin of protocol.scan_frames_py: scan ``buf`` (bytes-like)
    for complete RB1 frames -> (metas, consumed, stats), zero-copy over
    the caller's buffer. Loops the C scanner past its per-call frame
    cap so semantics match the uncapped Python walker exactly
    (parity-pinned)."""
    lib = load_frame_walker()
    out = getattr(_fw_tls, "out", None)
    if out is None:
        out = _fw_tls.out = np.empty(_FW_CAP * 8, np.int64)
        _fw_tls.stats = np.empty(4, np.int64)
    raw_stats = _fw_tls.stats
    data = np.frombuffer(buf, np.uint8)
    metas: list[tuple] = []
    stats = {"garbage_bytes": 0, "bad_crc": 0, "version_skew": 0}
    base = 0
    while True:
        raw_stats[:3] = 0
        n = int(lib.rtap_fw_scan(data[base:], len(data) - base, out,
                                 _FW_CAP, raw_stats))
        for i in range(n):
            kind, ver, epoch, toff, tlen, count, base_ts, poff = \
                out[i * 8:i * 8 + 8]
            metas.append((int(kind), int(ver), int(epoch), base + int(toff),
                          int(tlen), int(count), int(base_ts),
                          base + int(poff)))
        stats["garbage_bytes"] += int(raw_stats[0])
        stats["bad_crc"] += int(raw_stats[1])
        stats["version_skew"] += int(raw_stats[2])
        base += int(raw_stats[3])
        if n < _FW_CAP:
            return metas, base, stats
