/* Native JSONL metrics parser for the TCP push collector (SURVEY.md C18).
 *
 * The reference's collector normalizes per-node stats into (node, metric,
 * t, value) tuples on the host; at the 100k-streams-per-chip north star the
 * push listener must parse ~100k records/s on a host core that is also
 * driving the device and computing likelihoods. The pure-Python hot path
 * (json.loads + dict lookup + per-record lock) costs microseconds per
 * record; this module does the whole drain in C: scan a raw recv() chunk,
 * extract the {"id", "value", "ts"} fields of each line, resolve the id
 * against a precomputed open-addressing hash table, and write the latest
 * value per stream straight into the caller-owned float32 array.
 *
 * Scope (documented, tested): this is a schema parser for flat JSONL
 * metric records, not a general JSON validator. Fields may appear in any
 * order; unknown extra fields are skipped token-wise; strings honor
 * backslash escapes for delimiter purposes but ids are matched on their
 * raw (unescaped) bytes; values accept numbers, quoted numbers, true/
 * false, and NaN/Infinity (the Python json module accepts those too).
 * Records that fail schema extraction count as parse errors; structurally
 * deeper divergences from strict JSON (e.g. trailing garbage after the
 * fields we need) are accepted here but rejected by the Python fallback —
 * the parity tests pin both parsers on the realistic record space.
 *
 * Concurrency: one Parser per connection (it owns that connection's
 * partial-line remainder); the output arrays are shared and the caller
 * serializes feed() calls with its own lock (one lock per chunk, not per
 * record — part of the win).
 */

#include <ctype.h>
#include <math.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define MAX_LINE 65536          /* longer lines: parse_error + resync    */
#define COUNTER_PARSED 0
#define COUNTER_PARSE_ERRORS 1
#define COUNTER_UNKNOWN_IDS 2

/* ------------------------------------------------------------------ hash */

/* FNV-1a over raw id bytes: ids are short metric names; the table is
 * built once per listener and only probed afterwards. */
static uint64_t fnv1a(const char *s, long n) {
    uint64_t h = 1469598103934665603ULL;
    for (long i = 0; i < n; i++) {
        h ^= (unsigned char)s[i];
        h *= 1099511628211ULL;
    }
    return h;
}

typedef struct {
    char **keys;     /* owned copies of id bytes        */
    int *key_lens;
    int32_t *vals;   /* stream index                    */
    long cap;        /* power of two                    */
    long n;
} Table;

static Table *table_new(long n_ids) {
    Table *t = (Table *)calloc(1, sizeof(Table));
    if (!t) return NULL;
    long cap = 16;
    while (cap < n_ids * 2) cap <<= 1;   /* load factor <= 0.5 */
    t->cap = cap;
    t->keys = (char **)calloc((size_t)cap, sizeof(char *));
    t->key_lens = (int *)calloc((size_t)cap, sizeof(int));
    t->vals = (int32_t *)calloc((size_t)cap, sizeof(int32_t));
    if (!t->keys || !t->key_lens || !t->vals) return NULL;
    return t;
}

static void table_free(Table *t) {
    if (!t) return;
    for (long i = 0; i < t->cap; i++) free(t->keys[i]);
    free(t->keys);
    free(t->key_lens);
    free(t->vals);
    free(t);
}

static int table_put(Table *t, const char *key, int len, int32_t val) {
    uint64_t h = fnv1a(key, len);
    for (long i = 0; i < t->cap; i++) {
        long slot = (long)((h + (uint64_t)i) & (uint64_t)(t->cap - 1));
        if (t->keys[slot] == NULL) {
            t->keys[slot] = (char *)malloc((size_t)len);
            if (!t->keys[slot]) return -1;
            memcpy(t->keys[slot], key, (size_t)len);
            t->key_lens[slot] = len;
            t->vals[slot] = val;
            t->n++;
            return 0;
        }
        if (t->key_lens[slot] == len && memcmp(t->keys[slot], key, (size_t)len) == 0) {
            t->vals[slot] = val;  /* duplicate id: last wins, like dict */
            return 0;
        }
    }
    return -1;
}

static int32_t table_get(const Table *t, const char *key, long len) {
    if (len > INT32_MAX) return -1;
    uint64_t h = fnv1a(key, len);
    for (long i = 0; i < t->cap; i++) {
        long slot = (long)((h + (uint64_t)i) & (uint64_t)(t->cap - 1));
        if (t->keys[slot] == NULL) return -1;
        if (t->key_lens[slot] == (int)len &&
            memcmp(t->keys[slot], key, (size_t)len) == 0)
            return t->vals[slot];
    }
    return -1;
}

/* ---------------------------------------------------------------- parser */

typedef struct {
    Table **table_ref;   /* shared indirection: the owner can swap the
                            table (dynamic membership, set_ids) and every
                            per-connection clone observes the new one on
                            its next line — the caller's listener lock
                            serializes feeds against the swap */
    int owns_ref;
    char rem[MAX_LINE];  /* partial trailing line from the previous chunk */
    long rem_len;
    int rem_overflow;    /* current line exceeded MAX_LINE: swallow to \n */
} Parser;

static Table *build_table(const char *ids_blob, const int32_t *id_lens,
                          int32_t n_ids) {
    Table *t = table_new(n_ids > 0 ? n_ids : 1);
    if (!t) return NULL;
    const char *cur = ids_blob;
    for (int32_t i = 0; i < n_ids; i++) {
        if (table_put(t, cur, id_lens[i], i) != 0) {
            table_free(t);
            return NULL;
        }
        cur += id_lens[i];
    }
    return t;
}

Parser *rtap_parser_new(const char *ids_blob, const int32_t *id_lens, int32_t n_ids) {
    Parser *p = (Parser *)calloc(1, sizeof(Parser));
    if (!p) return NULL;
    p->table_ref = (Table **)calloc(1, sizeof(Table *));
    if (!p->table_ref) { free(p); return NULL; }
    *p->table_ref = build_table(ids_blob, id_lens, n_ids);
    if (!*p->table_ref) { free(p->table_ref); free(p); return NULL; }
    p->owns_ref = 1;
    return p;
}

/* Swap the owner's id table (registry membership changed). The caller must
 * hold the same lock that serializes feed()/flush() — no parser may be
 * mid-line-batch during the swap. Returns 0, -1 on allocation failure
 * (the old table stays in place). */
int rtap_parser_set_table(Parser *owner, const char *ids_blob,
                          const int32_t *id_lens, int32_t n_ids) {
    Table *fresh = build_table(ids_blob, id_lens, n_ids);
    if (!fresh) return -1;
    table_free(*owner->table_ref);
    *owner->table_ref = fresh;
    return 0;
}

/* Share one listener-wide table across per-connection parsers. */
Parser *rtap_parser_clone(const Parser *src) {
    Parser *p = (Parser *)calloc(1, sizeof(Parser));
    if (!p) return NULL;
    p->table_ref = src->table_ref;   /* borrowed: freed only by the owner */
    return p;
}

void rtap_parser_free_clone(Parser *p) { free(p); }

void rtap_parser_free_owner(Parser *p) {
    if (!p) return;
    if (p->owns_ref) {
        table_free(*p->table_ref);
        free(p->table_ref);
    }
    free(p);
}

/* -- line-level field scanner -------------------------------------------- */

/* Skip a JSON string starting at s (s[0]=='"'); returns pointer past the
 * closing quote, or NULL if unterminated before end. */
static const char *skip_string(const char *s, const char *end) {
    s++;
    while (s < end) {
        if (*s == '\\') { s += 2; continue; }
        if (*s == '"') return s + 1;
        s++;
    }
    return NULL;
}

static const char *skip_ws(const char *s, const char *end) {
    while (s < end && (*s == ' ' || *s == '\t' || *s == '\r')) s++;
    return s;
}

/* Field slots extracted from one record. */
typedef struct {
    const char *id;   long id_len;   int has_id;
    const char *val;  long val_len;  int has_val;  int val_quoted;
    const char *ts;   long ts_len;   int has_ts;   int ts_quoted;
} Fields;

/* Scan one line's top-level "key": value pairs. Returns 0 on schema
 * success (structure walkable), -1 on malformed structure. */
static int scan_line(const char *s, const char *end, Fields *f) {
    memset(f, 0, sizeof(*f));
    s = skip_ws(s, end);
    if (s >= end || *s != '{') return -1;
    s++;
    for (;;) {
        s = skip_ws(s, end);
        if (s < end && *s == '}') return 0;
        if (s >= end || *s != '"') return -1;
        const char *kstart = s + 1;
        const char *kend_q = skip_string(s, end);
        if (!kend_q) return -1;
        const char *kend = kend_q - 1;  /* closing quote */
        s = skip_ws(kend_q, end);
        if (s >= end || *s != ':') return -1;
        s = skip_ws(s + 1, end);
        if (s >= end) return -1;

        const char *vstart = s;
        const char *vend;
        int quoted = 0;
        if (*s == '"') {
            quoted = 1;
            vend = skip_string(s, end);
            if (!vend) return -1;
        } else if (*s == '{' || *s == '[') {
            /* nested value: skip balanced, honoring strings */
            int depth = 0;
            const char *q = s;
            while (q < end) {
                if (*q == '"') {
                    q = skip_string(q, end);
                    if (!q) return -1;
                    continue;
                }
                if (*q == '{' || *q == '[') depth++;
                else if (*q == '}' || *q == ']') {
                    depth--;
                    if (depth == 0) { q++; break; }
                }
                q++;
            }
            if (depth != 0) return -1;
            vend = q;
        } else {
            vend = s;
            while (vend < end && *vend != ',' && *vend != '}' &&
                   *vend != ' ' && *vend != '\t' && *vend != '\r')
                vend++;
            if (vend == s) return -1;
        }

        long klen = kend - kstart;
        const char *vs = quoted ? vstart + 1 : vstart;
        long vlen = quoted ? (vend - 1) - (vstart + 1) : vend - vstart;
        if (klen == 2 && memcmp(kstart, "id", 2) == 0) {
            f->id = vs; f->id_len = vlen;
            /* 1 = string (lookup on raw bytes); 2 = non-string scalar
             * (hashable: dict.get(5) misses -> unknown); 3 = object/array
             * (unhashable: dict.get raises TypeError -> parse_error) */
            f->has_id = quoted ? 1 : (*vstart == '{' || *vstart == '[') ? 3 : 2;
        } else if (klen == 5 && memcmp(kstart, "value", 5) == 0) {
            f->val = vs; f->val_len = vlen; f->has_val = 1; f->val_quoted = quoted;
        } else if (klen == 2 && memcmp(kstart, "ts", 2) == 0) {
            f->ts = vs; f->ts_len = vlen; f->has_ts = 1; f->ts_quoted = quoted;
        }

        s = skip_ws(vend, end);
        if (s < end && *s == ',') { s++; continue; }
        if (s < end && *s == '}') return 0;
        return -1;
    }
}

/* Parse a number token (optionally the inside of a quoted string) the way
 * the Python path does (np.float32(x)): strtod handles inf/nan spellings;
 * true/false/null follow np.float32(True/False) and reject None; hex is
 * rejected (strtod accepts C99 hex floats, np.float32(str)/json.loads do
 * not). Returns 0 ok. */
static int token_to_double(const char *s, long n, double *out) {
    if (n <= 0 || n >= 64) return -1;
    char buf[64];
    memcpy(buf, s, (size_t)n);
    buf[n] = 0;
    if (strcmp(buf, "true") == 0) { *out = 1.0; return 0; }
    if (strcmp(buf, "false") == 0) { *out = 0.0; return 0; }
    if (strcmp(buf, "null") == 0) return -1;
    for (long i = 0; i < n; i++)
        if (buf[i] == 'x' || buf[i] == 'X') return -1;  /* no hex floats */
    char *endp = NULL;
    double v = strtod(buf, &endp);
    if (endp == buf) return -1;
    while (*endp == ' ') endp++;
    if (*endp != 0) return -1;
    *out = v;
    return 0;
}

/* Quoted ts goes through Python's int(str), which accepts ONLY an
 * optionally-signed decimal integer with surrounding whitespace —
 * int("101.9") and int("1e3") raise. Mirror that exactly. */
static int quoted_ts_to_int(const char *s, long n, int64_t *out) {
    long i = 0;
    while (i < n && (s[i] == ' ' || s[i] == '\t')) i++;
    long start = i;
    if (i < n && (s[i] == '+' || s[i] == '-')) i++;
    long digits0 = i;
    int64_t v = 0;
    int neg = (start < n && s[start] == '-');
    while (i < n && s[i] >= '0' && s[i] <= '9') {
        v = v * 10 + (s[i] - '0');
        i++;
    }
    if (i == digits0) return -1;       /* no digits */
    while (i < n && (s[i] == ' ' || s[i] == '\t')) i++;
    if (i != n) return -1;             /* trailing junk e.g. ".9" */
    *out = neg ? -v : v;
    return 0;
}

/* Process one complete line. Counter semantics mirror the Python handler
 * exactly: structural/schema failure -> parse_errors; well-formed record
 * whose id is absent from the table -> unknown_ids (checked BEFORE value
 * conversion, like `_index.get(rec["id"])` runs before np.float32);
 * known id with unconvertible value -> parse_errors. */
static void process_line(Parser *p, const char *s, const char *end,
                         float *latest, int64_t *ts_max, int64_t *counters,
                         char *unk_buf, int64_t *unk_cur, long unk_cap) {
    /* blank lines: Python json.loads("") raises -> parse_error; but a
     * bare "\n" between records is produced by no real producer — treat
     * whitespace-only lines as Python does (error) for parity. */
    const char *c = skip_ws(s, end);
    if (c == end) {
        if (s != end) counters[COUNTER_PARSE_ERRORS]++;  /* "  \n" */
        return;                                          /* "" between \n\n: python
                                                            iterates rfile lines, a
                                                            lone \n IS a line -> error
                                                            handled above via s!=end */
    }
    Fields f;
    if (scan_line(s, end, &f) != 0 || !f.has_id || f.has_id == 3) {
        /* json.loads / rec["id"] / dict.get(unhashable) raised */
        counters[COUNTER_PARSE_ERRORS]++;
        return;
    }
    int32_t idx = -1;
    if (f.has_id == 1)
        idx = table_get(*p->table_ref, f.id, f.id_len);
    if (idx < 0) {
        /* _index.get(...) is None -> unknown BEFORE value conversion: a
         * valueless record with an unknown id counts unknown, not error */
        counters[COUNTER_UNKNOWN_IDS]++;
        /* track_unknown (serve --auto-register): capture the NAME as
         * "id\n" into the caller's bounded buffer; full buffer (or
         * unk_cap 0 = tracking off) = drop (the Python side dedups and
         * re-sees the id next tick). Only string ids (a numeric id can
         * never be registered) and only ids WITHOUT escapes: a captured
         * name must equal what json.loads would produce, and this
         * scanner matches raw bytes — an escaped id ('café') would
         * register under its wire spelling and then dead-letter on the
         * Python fallback path. Python-side strict-UTF-8 decode rejects
         * the invalid-bytes case for the same reason. */
        if (unk_buf != NULL && f.has_id == 1 &&
                memchr(f.id, '\\', (size_t)f.id_len) == NULL &&
                *unk_cur + f.id_len + 1 <= unk_cap) {
            memcpy(unk_buf + *unk_cur, f.id, (size_t)f.id_len);
            unk_buf[*unk_cur + f.id_len] = '\n';
            *unk_cur += f.id_len + 1;
        }
        return;
    }
    double v;
    if (f.has_val && !f.val_quoted && f.val_len == 4
            && memcmp(f.val, "null", 4) == 0) {
        v = NAN;  /* np.float32(None) is nan, not an error */
    } else if (!f.has_val || token_to_double(f.val, f.val_len, &v) != 0) {
        counters[COUNTER_PARSE_ERRORS]++;   /* rec["value"]/np.float32 raised */
        return;
    }
    /* Python assigns latest[i] and THEN converts ts; a bad ts therefore
     * still applies the value (and counts as a parse error). Mirror it. */
    latest[idx] = (float)v;
    if (f.has_ts) {
        int64_t tsv;
        if (f.ts_quoted) {
            if (quoted_ts_to_int(f.ts, f.ts_len, &tsv) != 0) {
                counters[COUNTER_PARSE_ERRORS]++;  /* int("101.9") raised */
                return;
            }
        } else {
            double tv;
            if (token_to_double(f.ts, f.ts_len, &tv) != 0) {
                counters[COUNTER_PARSE_ERRORS]++;  /* int(None) raised */
                return;
            }
            tsv = (int64_t)tv;  /* truncation toward zero, like int(float) */
        }
        if (tsv > *ts_max) *ts_max = tsv;
    }
    counters[COUNTER_PARSED]++;
}

/* Connection EOF: Python's rfile iteration yields a final line even
 * without a trailing newline — process the remainder the same way. */
void rtap_parser_flush(Parser *p, float *latest, int64_t *ts_max,
                       int64_t *counters, char *unk_buf, int64_t *unk_cur,
                       long unk_cap) {
    if (p->rem_overflow) {
        counters[COUNTER_PARSE_ERRORS]++;
        p->rem_overflow = 0;
        p->rem_len = 0;
        return;
    }
    if (p->rem_len > 0) {
        process_line(p, p->rem, p->rem + p->rem_len, latest, ts_max,
                     counters, unk_buf, unk_cur, unk_cap);
        p->rem_len = 0;
    }
}

/* Feed one recv() chunk. Complete lines are processed; a trailing partial
 * line is kept in the parser for the next chunk. Returns 0, or -1 on
 * internal error (never raises mid-stream; malformed data only bumps
 * counters). */
int rtap_parser_feed(Parser *p, const char *buf, long n,
                     float *latest, int64_t *ts_max, int64_t *counters,
                     char *unk_buf, int64_t *unk_cur, long unk_cap) {
    long i = 0;
    while (i < n) {
        const char *nl = (const char *)memchr(buf + i, '\n', (size_t)(n - i));
        if (nl == NULL) {
            long tail = n - i;
            if (p->rem_overflow || p->rem_len + tail > MAX_LINE) {
                p->rem_overflow = 1;   /* swallow until newline */
                p->rem_len = 0;
            } else {
                memcpy(p->rem + p->rem_len, buf + i, (size_t)tail);
                p->rem_len += tail;
            }
            return 0;
        }
        long line_end = nl - buf;
        if (p->rem_overflow) {
            counters[COUNTER_PARSE_ERRORS]++;   /* the oversized line ends here */
            p->rem_overflow = 0;
            p->rem_len = 0;
        } else if (p->rem_len > 0) {
            long tail = line_end - i;
            if (p->rem_len + tail > MAX_LINE) {
                counters[COUNTER_PARSE_ERRORS]++;
                p->rem_len = 0;
            } else {
                memcpy(p->rem + p->rem_len, buf + i, (size_t)tail);
                p->rem_len += tail;
                process_line(p, p->rem, p->rem + p->rem_len, latest, ts_max,
                             counters, unk_buf, unk_cur, unk_cap);
                p->rem_len = 0;
            }
        } else if (line_end > i) {   /* skip empty lines like rfile iteration? no:
                                        a lone "\n" yields the line "\n" in Python,
                                        whose json.loads fails -> parse_error */
            process_line(p, buf + i, buf + line_end, latest, ts_max,
                         counters, unk_buf, unk_cur, unk_cap);
        } else {
            counters[COUNTER_PARSE_ERRORS]++;   /* empty line between \n\n */
        }
        i = line_end + 1;
    }
    return 0;
}
