"""Alert emission + throughput accounting (host side).

The reference thresholds anomaly log-likelihood and pushes alerts to a
dashboard (SURVEY.md C20/C22, §3.3). v1 keeps the design but emits JSONL —
one object per alert — plus periodic throughput stats implementing the
north-star counter "anomaly-scored metrics/sec/chip" (SURVEY.md §5
"Metrics / logging").
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import IO

import numpy as np


class AlertWriter:
    """JSONL alert sink. One line per (stream, tick) whose score crosses the
    threshold; `None` path writes nowhere but still counts."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._fh: IO[str] | None = open(path, "a") if path else None
        self.count = 0

    def emit_batch(
        self,
        stream_ids: list[str],
        ts: np.ndarray,
        values: np.ndarray,
        raw: np.ndarray,
        log_likelihood: np.ndarray,
        alerts: np.ndarray,
    ) -> int:
        """Write one JSONL line per alerting stream; returns alert count."""
        idx = np.nonzero(alerts)[0]
        self.count += idx.size
        if self._fh is not None and idx.size:
            ts = np.broadcast_to(np.asarray(ts), alerts.shape)
            for g in idx:
                self._fh.write(
                    json.dumps(
                        {
                            "stream": stream_ids[g],
                            "ts": int(ts[g]),
                            "value": float(np.asarray(values)[g]) if np.ndim(values) == 1 else [float(x) for x in np.asarray(values)[g]],
                            "raw_score": float(raw[g]),
                            "log_likelihood": float(log_likelihood[g]),
                        }
                    )
                    + "\n"
                )
            self._fh.flush()
        return int(idx.size)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


@dataclass
class ThroughputCounter:
    """Counts scored metrics against wall clock -> metrics/sec/chip."""

    start: float = field(default_factory=time.perf_counter)
    scored: int = 0

    def add(self, n: int) -> None:
        self.scored += int(n)

    @property
    def elapsed(self) -> float:
        return max(time.perf_counter() - self.start, 1e-9)

    @property
    def metrics_per_sec(self) -> float:
        return self.scored / self.elapsed

    def stats(self) -> dict:
        return {
            "scored": self.scored,
            "elapsed_s": round(self.elapsed, 3),
            "metrics_per_sec": round(self.metrics_per_sec, 1),
        }
