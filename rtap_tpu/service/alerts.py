"""Alert emission + throughput accounting (host side).

The reference thresholds anomaly log-likelihood and pushes alerts to a
dashboard (SURVEY.md C20/C22, §3.3). v1 keeps the design but emits JSONL —
one object per alert — plus periodic throughput stats implementing the
north-star counter "anomaly-scored metrics/sec/chip" (SURVEY.md §5
"Metrics / logging").
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import IO

import numpy as np

from rtap_tpu.obs import get_registry


class AlertWriter:
    """JSONL alert sink. One line per (stream, tick) whose score crosses the
    threshold; `None` path writes nowhere but still counts. Structured
    watchdog events (`emit_event`) share the stream, discriminated by their
    "event" key — one file tells the whole incident story in order."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._fh: IO[str] | None = open(path, "a") if path else None
        self.count = 0
        obs = get_registry()
        self._obs_alerts = obs.counter(
            "rtap_obs_alerts_total", "alert lines emitted (threshold "
            "crossings that survived debounce)")
        self._obs_events = obs.counter(
            "rtap_obs_alert_stream_events_total",
            "structured watchdog/ops events written to the alert stream")
        self._obs_emit = obs.histogram(
            "rtap_obs_alert_emit_seconds",
            "wall seconds per emit_batch call (JSONL format + write + flush)")

    def emit_batch(
        self,
        stream_ids: list[str],
        ts: np.ndarray,
        values: np.ndarray,
        raw: np.ndarray,
        log_likelihood: np.ndarray,
        alerts: np.ndarray,
    ) -> int:
        """Write one JSONL line per alerting stream; returns alert count."""
        t0 = time.perf_counter()
        idx = np.nonzero(alerts)[0]
        self.count += idx.size
        if idx.size:
            self._obs_alerts.inc(int(idx.size))
        if self._fh is not None and idx.size:
            ts = np.broadcast_to(np.asarray(ts), alerts.shape)
            for g in idx:
                self._fh.write(
                    json.dumps(
                        {
                            "stream": stream_ids[g],
                            "ts": int(ts[g]),
                            "value": float(np.asarray(values)[g]) if np.ndim(values) == 1 else [float(x) for x in np.asarray(values)[g]],
                            "raw_score": float(raw[g]),
                            "log_likelihood": float(log_likelihood[g]),
                        }
                    )
                    + "\n"
                )
            self._fh.flush()
        self._obs_emit.observe(time.perf_counter() - t0)
        return int(idx.size)

    def emit_event(self, event: dict) -> None:
        """Write one structured event line (watchdog missed_tick /
        source_starved / checkpoint_stall, membership changes, ...). Events
        must carry an "event" key so downstream consumers can split them
        from alert records on the shared stream. Serialization hoists that
        key first regardless of the caller's dict order: line consumers
        (live_soak's counter, the bitexactness tests' filter) split on the
        literal prefix '{"event"' without parsing every line."""
        if "event" not in event:
            raise ValueError(f"structured events need an 'event' key: {event}")
        self._obs_events.inc()
        if self._fh is not None:
            self._fh.write(json.dumps({"event": event["event"], **event}) + "\n")
            self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


@dataclass
class ThroughputCounter:
    """Counts scored metrics against wall clock -> metrics/sec/chip."""

    start: float = field(default_factory=time.perf_counter)
    scored: int = 0

    def add(self, n: int) -> None:
        self.scored += int(n)

    @property
    def elapsed(self) -> float:
        return max(time.perf_counter() - self.start, 1e-9)

    @property
    def metrics_per_sec(self) -> float:
        return self.scored / self.elapsed

    def stats(self) -> dict:
        return {
            "scored": self.scored,
            "elapsed_s": round(self.elapsed, 3),
            "metrics_per_sec": round(self.metrics_per_sec, 1),
        }
