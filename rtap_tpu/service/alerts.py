"""Alert emission + throughput accounting (host side).

The reference thresholds anomaly log-likelihood and pushes alerts to a
dashboard (SURVEY.md C20/C22, §3.3). v1 keeps the design but emits JSONL —
one object per alert — plus periodic throughput stats implementing the
north-star counter "anomaly-scored metrics/sec/chip" (SURVEY.md §5
"Metrics / logging").
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import IO

import numpy as np

from rtap_tpu.obs import get_registry


def format_alert_line(alert_id, stream: str, ts: int, value,
                      raw_score: float, log_likelihood: float,
                      top_fields=None) -> str:
    """THE alert-line serialization — one function so every producer of
    alert JSONL bytes (AlertWriter.emit_batch and the hot-standby
    follower's buffered splice, resilience/replicate.py) emits
    byte-identical lines for identical inputs: the failover soak's
    per-id record-equality check depends on it. ``value`` may be a
    scalar or a 1-D multivariate row."""
    val = np.asarray(value)
    return json.dumps(
        {
            **({"alert_id": alert_id} if alert_id is not None else {}),
            "stream": stream,
            "ts": int(ts),
            "value": float(val) if val.ndim == 0
            else [float(x) for x in val],
            "raw_score": float(raw_score),
            "log_likelihood": float(log_likelihood),
            **({"top_fields": top_fields} if top_fields is not None else {}),
        }
    ) + "\n"


def heal_torn_tail(path: str) -> int:
    """Append a newline if `path` ends mid-line (a writer killed
    mid-``write``): the fragment becomes its own unparseable — and
    therefore skipped — line instead of merging with the next append
    and corrupting BOTH records. Shared by the alert sink on reopen and
    the supervisor's incident-stream appends. Returns bytes added
    (0 or 1); a missing/empty/unwritable path heals nothing."""
    try:
        with open(path, "rb") as f:
            f.seek(-1, 2)
            if f.read(1) == b"\n":
                return 0
    except (OSError, ValueError):
        return 0
    try:
        with open(path, "a") as f:
            f.write("\n")
    except OSError:
        return 0
    return 1


class AlertWriter:
    """JSONL alert sink. One line per (stream, tick) whose score crosses the
    threshold; `None` path writes nowhere but still counts. Structured
    watchdog events (`emit_event`) share the stream, discriminated by their
    "event" key — one file tells the whole incident story in order.

    The sink is NON-FATAL: a full disk must never kill scoring. Every
    write goes through retry-then-quarantine — one immediate retry on
    ``OSError``, then a circuit breaker (`breaker`; 3 consecutive failed
    batches open it) quarantines the sink: lines are counted and DROPPED
    (``dropped``, ``rtap_obs_alert_lines_dropped_total``) with zero write
    attempts until the cooldown admits a probe batch. A probe that lands
    re-closes the breaker and the stream resumes — with a gap, which the
    drop counters size. ``count`` tracks threshold crossings regardless
    of sink health (it feeds the loop stats, not the file).

    `flush_every=N` flushes once per N batches instead of per batch —
    the fsync-adjacent cost dominated emit at high alert rates. The
    default 1 keeps flush-per-batch crash-safety: a killed serve loses at
    most the current batch. Events always flush (rare, load-bearing).

    Durability (ISSUE 5, docs/RESILIENCE.md): every alert line carries a
    stable ``alert_id`` (``group:stream:tick`` — the group index, the
    stream id, and the GROUP's own tick counter, identical across
    restarts) whenever the caller supplies ``group``/``tick``. The
    writer tracks its byte offset into the sink (``sink_offset``; the
    checkpoint meta records it at drained save instants as the alert
    cursor) and can be armed with a resume suppression set
    (``arm_suppression``): alert ids already on disk from a crashed
    run's post-checkpoint window are counted and NOT re-written during
    journal replay — exactly-once across the crash. Opening an existing
    sink whose last line was torn mid-write (killed mid-``writelines``)
    first heals it with a newline so subsequent lines stay parseable.
    """

    def __init__(self, path: str | None = None, flush_every: int = 1,
                 breaker=None, attributor=None, fence=None,
                 correlator=None, latency=None):
        import os

        from rtap_tpu.resilience.policies import CircuitBreaker

        if flush_every < 1:
            raise ValueError(f"flush_every must be >= 1; got {flush_every}")
        self.path = path
        # leader fencing (ISSUE 8, resilience/replicate.py): a callable
        # consulted before every sink write; False means this process no
        # longer holds the leadership lease — a paused old leader that
        # wakes up after a standby promoted must NOT append to the alert
        # sink (the new leader owns the stream now). Fenced lines are
        # dropped + counted, never written; the loop itself also exits
        # on fence loss, this is the last-line guard under it.
        self._fence = fence
        self.fenced_drops = 0
        # per-alert provenance (service/attribution.py, serve
        # --alert-attribution): alert lines gain a top_fields block.
        # History advances on EVERY batch (attribution compares against
        # the previous tick), alert or not.
        self._attributor = attributor
        # topology-aware incident correlation (ISSUE 9,
        # rtap_tpu/correlate/): every NON-SUPPRESSED alert batch this
        # writer lands on the sink also folds into the correlator's
        # windows (suppressed ids were delivered by the crashed run —
        # the correlator's resume scan of the sink tail already saw
        # them, and dropped batches never fold, so the fold mirrors the
        # DISK exactly once by construction).
        self._correlator = correlator
        # detection-latency observability (ISSUE 11, obs/latency.py):
        # every batch that reached the sink observes wall-clock-minus-
        # source-ts per alert into the e2e detect sketch — the sink
        # write IS the delivery moment the paper's real-time claim is
        # judged by. Pure observation: bytes on the stream are identical
        # with the tracker armed or absent.
        self._latency = latency
        self._offset = 0  # bytes handed to the sink (the alert cursor)
        self.torn_heals = 0
        if path:
            try:
                self._offset = os.path.getsize(path)
            except OSError:
                self._offset = 0
            # heal a torn tail from a killed writer: without the newline
            # the next append would merge into the partial line and
            # corrupt BOTH records for line consumers
            self.torn_heals = heal_torn_tail(path)
            self._offset += self.torn_heals
        self._fh: IO[str] | None = open(path, "a") if path else None
        self.count = 0
        self.suppressed = 0  # resume-suppressed (already-delivered) lines
        self._suppress: set[str] = set()
        self.dropped = 0
        self.sink_quarantines = 0  # times the breaker opened on the sink
        self.flush_every = int(flush_every)
        self._batches_since_flush = 0
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            fail_threshold=3, cooldown_s=5.0, name="alert_sink")
        obs = get_registry()
        self._obs_alerts = obs.counter(
            "rtap_obs_alerts_total", "alert lines emitted (threshold "
            "crossings that survived debounce)")
        self._obs_events = obs.counter(
            "rtap_obs_alert_stream_events_total",
            "structured watchdog/ops events written to the alert stream")
        self._obs_emit = obs.histogram(
            "rtap_obs_alert_emit_seconds",
            "wall seconds per emit_batch call (JSONL format + write + flush)")
        self._obs_sink_errors = obs.counter(
            "rtap_obs_alert_sink_errors_total",
            "OSError write/flush failures against the alert sink (each "
            "failed batch counts once, after its immediate retry)")
        self._obs_dropped = obs.counter(
            "rtap_obs_alert_lines_dropped_total",
            "alert/event lines dropped while the sink was failing or "
            "quarantined (full disk etc. — scoring continued)")
        self._obs_suppressed = obs.counter(
            "rtap_obs_alerts_suppressed_total",
            "already-delivered alert ids suppressed during journal/"
            "checkpoint resume (exactly-once across a crash)")
        self._obs_quarantined = {
            kind: obs.counter(
                "rtap_obs_resilience_events_total",
                "structured resilience events by kind", event=kind)
            for kind in ("alert_sink_quarantined", "alert_sink_restored")
        }
        self._obs_fenced = obs.counter(
            "rtap_obs_alert_lines_fenced_total",
            "alert/event lines refused because this process lost the "
            "leadership lease (a fenced old leader must never append to "
            "the sink a promoted standby now owns)")

    def wrap_sink(self, wrap) -> None:
        """Wrap the underlying file object (the chaos engine's injection
        seam: faults land UNDER the retry/quarantine path, proving it)."""
        if self._fh is not None:
            self._fh = wrap(self._fh)

    def _safe_write(self, lines: list[str], force_flush: bool = False) -> bool:
        """Write + maybe flush, retry once, quarantine via the breaker.
        Never raises; failed/skipped lines are counted in ``dropped``.
        Returns True iff the lines were handed to the sink (the batch is
        all-or-nothing: one writelines call) — consumers that must stay
        consistent with the on-disk stream (the incident correlator's
        fold) key on it."""
        if self._fh is None or not lines:
            return False
        if self._fence is not None and not self._fence():
            self.fenced_drops += len(lines)
            self._obs_fenced.inc(len(lines))
            return False
        if not self._breaker.allow():
            self.dropped += len(lines)
            self._obs_dropped.inc(len(lines))
            return False
        was_closed = self._breaker.state == self._breaker.CLOSED
        wrote = False  # a flush-only failure must not re-write the lines
        # on retry (duplicated alert lines would corrupt bit-exactness
        # consumers of the stream)
        for attempt in (1, 2):  # retry once, immediately: transient EINTR/
            # EAGAIN-class blips recover; a full disk fails twice and
            # feeds the breaker
            try:
                if not wrote:
                    self._fh.writelines(lines)
                    wrote = True
                    # the alert cursor: bytes handed to the sink (exact
                    # disk offset whenever the buffer is flushed — the
                    # checkpoint path flushes before reading it)
                    self._offset += sum(len(ln.encode("utf-8", "replace"))
                                        for ln in lines)
                    self._batches_since_flush += 1
                if force_flush or self._batches_since_flush >= self.flush_every:
                    self._fh.flush()
                    self._batches_since_flush = 0
                self._breaker.record_success()
                if not was_closed:
                    # the probe landed: the sink is back. Say so ON the
                    # now-working stream, with the gap size.
                    self._obs_quarantined["alert_sink_restored"].inc()
                    self.emit_event({"event": "alert_sink_restored",
                                     "lines_dropped": self.dropped})
                return True
            except OSError:
                if attempt == 2:
                    self._obs_sink_errors.inc()
                    if not wrote:
                        # flush-only failures leave the lines in the
                        # stdio buffer — they land on a later successful
                        # flush, so counting them dropped would overstate
                        # the gap the restored event reports
                        self.dropped += len(lines)
                        self._obs_dropped.inc(len(lines))
                    self._breaker.record_failure()
                    if self._breaker.state == self._breaker.OPEN:
                        # quarantined: counted, not written (the sink is
                        # the thing that just died)
                        self.sink_quarantines += 1
                        self._obs_quarantined["alert_sink_quarantined"].inc()
        # both attempts raised: the lines reached the sink only if the
        # write itself landed and the failure was flush-only
        return wrote

    def arm_suppression(self, alert_ids: set[str]) -> None:
        """Arm the resume suppression set: lines whose ``alert_id`` is in
        the set are counted as already delivered and NOT re-written (the
        set shrinks as ids match, so steady-state cost is an empty-set
        check). service/loop.py fills it by scanning the alert sink past
        the checkpoint's alert cursor before a journal replay."""
        self._suppress |= set(alert_ids)

    def sink_offset(self) -> int:
        """Bytes handed to the sink so far — the alert-delivery cursor
        recorded in checkpoint meta (flush first via :meth:`flush_sink`
        so the cursor equals the on-disk size at a drained instant)."""
        return self._offset

    def flush_sink(self) -> None:
        """Force the sink's stdio buffer to the kernel (best effort —
        failures feed the breaker on the next write, never raise)."""
        if self._fh is None:
            return
        try:
            self._fh.flush()
            self._batches_since_flush = 0
        except OSError:
            pass

    def emit_batch(
        self,
        stream_ids: list[str],
        ts: np.ndarray,
        values: np.ndarray,
        raw: np.ndarray,
        log_likelihood: np.ndarray,
        alerts: np.ndarray,
        group: int | str | None = None,
        tick: int | None = None,
    ) -> int:
        """Write one JSONL line per alerting stream; returns alert count.

        ``group`` + ``tick`` (the group index — possibly epoch-suffixed
        after a quarantine restore, see loop._alert_gid — and the
        group's own tick counter for this row) give every line its
        stable ``alert_id`` (``group:stream:tick``) — the dedupe/replay
        key downstream consumers and crash-resume suppression rely
        on."""
        t0 = time.perf_counter()
        idx = np.nonzero(alerts)[0]
        self.count += idx.size  # crossings scored, sink/suppression aside
        suppressed_this = 0
        attr = None
        if self._attributor is not None:
            # history must advance on every batch, not just alerting ones
            # — but the per-alert decode is only worth computing when a
            # sink will carry it (path=None serves count-only callers)
            attr = self._attributor.update_and_attribute(
                stream_ids, values, idx if self._fh is not None else idx[:0])
        if self._fh is not None and idx.size:
            ts = np.broadcast_to(np.asarray(ts), alerts.shape)
            values = np.asarray(values)
            with_id = group is not None and tick is not None
            # one writelines per batch, not one write per line: the
            # serialization stays per-line (each line is one JSON object)
            # but the file sees a single buffered call
            lines = []
            folds = []
            lat_ts = [] if self._latency is not None else None
            for g in idx:
                aid = f"{group}:{stream_ids[g]}:{int(tick)}" \
                    if with_id else None
                if aid is not None and self._suppress and \
                        aid in self._suppress:
                    # already delivered by the run that crashed: counted,
                    # never duplicated (exactly-once across the crash)
                    self._suppress.discard(aid)
                    self.suppressed += 1
                    suppressed_this += 1
                    self._obs_suppressed.inc()
                    continue
                tf = attr.get(int(g), []) if attr is not None else None
                if self._correlator is not None:
                    folds.append((aid, stream_ids[g], int(ts[g]), tf))
                if lat_ts is not None:
                    lat_ts.append(int(ts[g]))
                lines.append(format_alert_line(
                    aid, stream_ids[g], int(ts[g]), values[g],
                    float(raw[g]), float(log_likelihood[g]),
                    top_fields=tf))
            # fold into the correlator only AFTER the batch reached the
            # sink: a dropped batch (fence lost, breaker open, double
            # write failure) must not seed windows with alert_ids that
            # exist nowhere on the stream — the resume re-fold reads the
            # DISK, and the content-hash incident_id must agree with it.
            # The pre-write offset anchors the correlator's crash-resume
            # sidecar floor (every member of a window lives at/after its
            # window's anchor).
            off0 = self._offset
            if self._safe_write(lines):
                if self._correlator is not None:
                    for aid, sid, tsi, tf in folds:
                        self._correlator.observe_alert(aid, sid, tsi,
                                                       top_fields=tf,
                                                       sink_offset=off0)
                if lat_ts:
                    # e2e detect latency at the delivery moment: wall
                    # clock minus each alert's SOURCE timestamp (clamped
                    # >= 0 in the sketch) — pipeline depth, micro-chunk
                    # staleness and backfill hold all show up honestly
                    self._latency.observe_detect(
                        time.time() - np.asarray(lat_ts, np.float64))
        emitted = int(idx.size) - suppressed_this
        if emitted:
            # lines handed toward the sink this call: suppressed ids ride
            # rtap_obs_alerts_suppressed_total instead, never both
            self._obs_alerts.inc(emitted)
        self._obs_emit.observe(time.perf_counter() - t0)
        return int(idx.size)

    def emit_event(self, event: dict) -> None:
        """Write one structured event line (watchdog missed_tick /
        source_starved / checkpoint_stall, quarantine/degradation events,
        membership changes, ...). Events must carry an "event" key so
        downstream consumers can split them from alert records on the
        shared stream. Serialization hoists that key first regardless of
        the caller's dict order: line consumers (live_soak's counter, the
        bitexactness tests' filter) split on the literal prefix
        '{"event"' without parsing every line. Events flush immediately —
        they are rare and tell the incident story."""
        if "event" not in event:
            raise ValueError(f"structured events need an 'event' key: {event}")
        self._obs_events.inc()
        self._safe_write(
            [json.dumps({"event": event["event"], **event}) + "\n"],
            force_flush=True)

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.flush()
                self._fh.close()
            except OSError:
                pass  # the quarantine counters already told the story
            self._fh = None


def iter_alert_records(path: str, offset: int = 0):
    """THE tolerant alert-stream line iterator — one walker for every
    consumer of the shared alert/incident JSONL (the resume suppression
    scan below, scripts/crash_soak.parse_alert_stream and everything
    layered on it, and the incident correlator's resume scan —
    rtap_tpu/correlate/incidents.py), so torn-fragment and event-vs-alert
    semantics can never drift between them.

    Yields ``(kind, record)`` pairs in file order starting at byte
    ``offset``: kind ``"event"`` (a structured line carrying an "event"
    key — dict), ``"alert"`` (a dict, possibly without an alert_id on
    pre-ISSUE-5 streams), or ``"garbage"`` (record is the raw line: a
    torn fragment from a kill mid-write, or a non-object). A missing/
    unreadable file yields nothing — absence is an empty stream, the
    callers' shared convention."""
    try:
        with open(path) as f:
            f.seek(max(0, int(offset)))
            for line in f:
                stripped = line.strip()
                if not stripped:
                    continue
                try:
                    d = json.loads(stripped)
                except ValueError:
                    yield "garbage", line
                    continue
                if not isinstance(d, dict):
                    yield "garbage", line
                    continue
                yield ("event" if "event" in d else "alert"), d
    except OSError:
        return


def scan_alert_ids(path: str, offset: int = 0) -> set[str]:
    """Alert ids already on disk at/after byte `offset` — the resume
    suppression set. The checkpoint meta's alert cursor (recorded at a
    fully-drained save instant) bounds the scan to the post-checkpoint
    window, so resume cost is O(ticks since the last save), not O(file).
    Event lines and torn/unparseable fragments are skipped (a torn line
    never fully delivered its alert — replay re-emits it properly)."""
    ids: set[str] = set()
    for kind, d in iter_alert_records(path, offset):
        if kind != "alert":
            continue
        aid = d.get("alert_id")
        if aid:
            ids.add(aid)
    return ids


def scan_event_ids(path: str, offset: int = 0,
                   events: tuple = ("precursor", "predicted_incident"),
                   ) -> set[str]:
    """Stable EVENT-line alert_ids already on disk at/after `offset` —
    the resume suppression set for id-carrying structured events (the
    predictive ``precursor`` / ``predicted_incident`` lines, whose ids
    are pure functions of (stream, tick) so a journal replay reproduces
    them bit-for-bit). Same walker, same cursor discipline as
    :func:`scan_alert_ids`; alert records and other event kinds are
    skipped."""
    ids: set[str] = set()
    for kind, d in iter_alert_records(path, offset):
        if kind != "event" or d.get("event") not in events:
            continue
        aid = d.get("alert_id")
        if aid:
            ids.add(aid)
    return ids


@dataclass
class ThroughputCounter:
    """Counts scored metrics against wall clock -> metrics/sec/chip."""

    start: float = field(default_factory=time.perf_counter)
    scored: int = 0

    def add(self, n: int) -> None:
        self.scored += int(n)

    @property
    def elapsed(self) -> float:
        return max(time.perf_counter() - self.start, 1e-9)

    @property
    def metrics_per_sec(self) -> float:
        return self.scored / self.elapsed

    def stats(self) -> dict:
        return {
            "scored": self.scored,
            "elapsed_s": round(self.elapsed, 3),
            "metrics_per_sec": round(self.metrics_per_sec, 1),
        }
