"""Shard-qualified resource paths — the ONE place that spells them.

ROADMAP-1's pod-scale serving runs one serve process per mesh shard,
and every filesystem resource the serve stack owns — the journal dir,
the per-group checkpoint claims, the lease file, the alert sink's
``.corr``/``.epoch`` sidecars — must be distinct per shard or two
shards silently clobber one file (interleaved journal segments, a lease
two leaders both think they hold, a correlator floor ping-ponging
between two folds). The rtap-lint ``shard-resource`` pass (ISSUE 15)
enforces that these names are minted HERE and nowhere else: a call site
cannot forget the shard because it never spells the suffix.

Shard 0 is byte-identical to the pre-mesh paths (pinned by
tests/unit/test_shardpath.py), so every existing artifact, soak ledger,
and operator runbook keeps working unchanged; nonzero shards qualify
the base name itself (``journal.shard001/``, ``lease.json.shard001``),
which works uniformly for files and directories.
"""

from __future__ import annotations

import os

__all__ = ["shard_scoped_path", "group_checkpoint_path",
           "alert_sidecar_path"]

#: sidecar kinds the alert sink owns (correlator resume floor, run
#: epoch); the helper rejects unknown kinds so a typo cannot mint an
#: orphan file the resume paths never read
SIDECAR_KINDS = ("corr", "epoch")


def shard_scoped_path(base: str, shard: int) -> str:
    """Qualify an operator-provided resource path with the mesh shard.

    Shard 0 returns `base` unchanged — today's single-shard serve keeps
    byte-identical artifacts. Nonzero shards suffix the base itself
    (``<base>.shard<NNN>``), uniform for files and directories; 3
    digits covers the 256-shard ingest-protocol ceiling (MAX_SHARDS).
    A trailing separator on a dir flag (``runs/journal/``) is stripped
    before suffixing — otherwise shard 1's dir would nest INSIDE shard
    0's as a hidden ``.shard001`` entry instead of being a sibling.
    """
    if not 0 <= int(shard) <= 999:
        raise ValueError(f"shard must be in [0, 999]; got {shard!r}")
    if shard == 0:
        return base
    return f"{base.rstrip('/' + os.sep)}.shard{int(shard):03d}"


def group_checkpoint_path(checkpoint_dir: str, gi: int) -> str:
    """The per-group checkpoint claim directory inside an (already
    shard-scoped) checkpoint dir — ``<dir>/group<NNNN>``, the name
    save_group/load_group and every resume scan agree on."""
    return os.path.join(checkpoint_dir, f"group{int(gi):04d}")


def alert_sidecar_path(alert_path: str, kind: str) -> str:
    """A sidecar beside an (already shard-scoped) alert sink:
    ``<alerts>.corr`` (correlator resume floor) or ``<alerts>.epoch``
    (run-epoch continuity). The shard rides the base path."""
    if kind not in SIDECAR_KINDS:
        raise ValueError(
            f"unknown sidecar kind {kind!r}; valid: {SIDECAR_KINDS}")
    return f"{alert_path}.{kind}"
