"""Vectorized anomaly likelihood over a stream group (host side).

Semantically identical to the per-stream oracle
(models/oracle/likelihood.py, itself faithful to NuPIC's
anomaly_likelihood.py — SURVEY.md C8) but runs all G streams of a group in
lockstep with numpy array ops, so the host post-process stays negligible next
to the device step even at 100k streams (SURVEY.md §7 hard part 5).

Lockstep is the group invariant: every stream in a group receives a score
every tick, so the record count, ring-buffer cursor, and refit schedule are
scalars shared by the whole batch. Floating-point note: batch reductions
(np.sum/np.mean along an axis) may round differently from the oracle's
sequential Python sums by ~1 ulp; parity tests use rel tolerances, not
bit-equality.
"""

from __future__ import annotations

import math

import numpy as np

from rtap_tpu.config import LikelihoodConfig

# NuPIC's log-scale constant: log(1.0000000001 - x) / log(1e-10)
_LOG_DENOM = np.log(1e-10)


# numpy ships no erfc ufunc and scipy is unavailable here. A frompyfunc over
# math.erfc measured 14 ms/tick at G=100k on the 1-core host (reports/
# likelihood_100k.json) — 14% of the tick's 100 ms share of the 1 s budget —
# so the production path is a vectorized W. J. Cody rational approximation
# (the CALERF algorithm behind most libm erfc implementations), accurate to
# ~1e-16 relative against math.erfc (pinned by
# tests/unit/test_likelihood_model.py::test_vector_erfc_matches_libm).
_SQRT2 = math.sqrt(2.0)

# Cody branch 1 (|x| <= 0.46875): erf(x) = x * P1(x^2)/Q1(x^2)
_ERF_A = (3.16112374387056560e0, 1.13864154151050156e2,
          3.77485237685302021e2, 3.20937758913846947e3,
          1.85777706184603153e-1)
_ERF_B = (2.36012909523441209e1, 2.44024637934444173e2,
          1.28261652607737228e3, 2.84423683343917062e3)
# branch 2 (0.46875 < x <= 4): erfc(x) = exp(-x^2) * P2(x)/Q2(x)
_ERF_C = (5.64188496988670089e-1, 8.88314979438837594e0,
          6.61191906371416295e1, 2.98635138197400131e2,
          8.81952221241769090e2, 1.71204761263407058e3,
          2.05107837782607147e3, 1.23033935479799725e3,
          2.15311535474403846e-8)
_ERF_D = (1.57449261107098347e1, 1.17693950891312499e2,
          5.37181101862009858e2, 1.62138957456669019e3,
          3.29079923573345963e3, 4.36261909014324716e3,
          3.43936767414372164e3, 1.23033935480374942e3)
# branch 3 (x > 4): erfc(x) = exp(-x^2)/x * (1/sqrt(pi) - P3(z)/Q3(z)/x^2),
# z = 1/x^2
_ERF_P = (3.05326634961232344e-1, 3.60344899949804439e-1,
          1.25781726111229246e-1, 1.60837851487422766e-2,
          6.58749161529837803e-4, 1.63153871373020978e-2)
_ERF_Q = (2.56852019228982242e0, 1.87295284992346047e0,
          5.27905102951428412e-1, 6.05183413124413191e-2,
          2.33520497626869185e-3)
_SQRPI = 5.6418958354775628695e-1  # 1/sqrt(pi)


def _erfc_tail(y: np.ndarray) -> np.ndarray:
    """erfc on |x| > 0.46875 (Cody branches 2/3), y = |x| within range."""
    # both branches share the exp(-y^2) split: ysq = trunc(16y)/16 keeps the
    # squared term exactly representable, dely catches the residual
    yc = np.minimum(y, 30.0)  # erfc underflows to 0 well before 30
    ysq = np.trunc(yc * 16.0) / 16.0
    expterm = np.exp(-ysq * ysq) * np.exp(-(yc - ysq) * (yc + ysq))

    mid = yc <= 4.0
    out = np.empty_like(yc)
    y2 = yc[mid]
    num = _ERF_C[8] * y2
    den = y2.copy()
    for i in range(7):
        num = (num + _ERF_C[i]) * y2
        den = (den + _ERF_D[i]) * y2
    out[mid] = expterm[mid] * (num + _ERF_C[7]) / (den + _ERF_D[7])

    big = ~mid
    if big.any():
        y3 = yc[big]
        z3 = 1.0 / (y3 * y3)
        num = _ERF_P[5] * z3
        den = z3.copy()
        for i in range(4):
            num = (num + _ERF_P[i]) * z3
            den = (den + _ERF_Q[i]) * z3
        r3 = z3 * (num + _ERF_P[4]) / (den + _ERF_Q[4])
        out[big] = expterm[big] * (_SQRPI - r3) / y3
    return out


def erfc_np(x: np.ndarray) -> np.ndarray:
    """Vectorized double-precision erfc (Cody's CALERF rational
    approximations), elementwise over any-shape float64 input. Branches
    evaluate on compressed subsets — for the Gaussian-z inputs of the
    likelihood path nearly everything lands in branches 1/2."""
    x = np.asarray(x, np.float64)
    y = np.abs(x)
    out = np.empty_like(y)

    small = y <= 0.46875
    z1 = y[small] ** 2
    num = _ERF_A[4] * z1
    den = z1.copy()
    for i in range(3):
        num = (num + _ERF_A[i]) * z1
        den = (den + _ERF_B[i]) * z1
    out[small] = 1.0 - y[small] * (num + _ERF_A[3]) / (den + _ERF_B[3])

    tail = ~small
    if tail.any():
        out[tail] = _erfc_tail(y[tail])
    return np.where(x < 0.0, 2.0 - out, out)


def tail_probability_np(z: np.ndarray) -> np.ndarray:
    """Gaussian upper-tail Q(z) = 0.5*erfc(z/sqrt(2)), elementwise."""
    return 0.5 * erfc_np(z / _SQRT2)


def log_likelihood_np(lik: np.ndarray) -> np.ndarray:
    return np.log(1.0000000001 - lik) / _LOG_DENOM


class BatchAnomalyLikelihood:
    """Likelihood state for G lockstep streams.

    `update(raw [G]) -> (likelihood [G], log_likelihood [G])`.
    """

    # Window mode's [G, W] f64 ring is the one host allocation that scales
    # with BOTH stream count and window size: G=100k x W=8640 = 6.9 GB.
    # Above the soft limit we warn; above the hard limit (env-overridable,
    # GiB) we refuse — streaming mode exists precisely for that regime
    # (SURVEY.md §7 hard part 5).
    RING_WARN_BYTES = 1 << 30

    def __init__(self, cfg: LikelihoodConfig, group_size: int):
        self.cfg = cfg
        self.G = int(group_size)
        self.records = 0
        # per-slot birth record count: slots claimed mid-run (dynamic stream
        # registration, C19 lazy-creation parity) restart THEIR probation
        # clock while the group's lockstep cursor keeps running. age of slot
        # g = records - birth[g]; 0 for original members.
        self.birth = np.zeros(self.G, np.int64)
        # short moving-average ring [G, w]
        self.recent = np.zeros((self.G, cfg.averaging_window), np.float64)
        self.mean = np.zeros(self.G, np.float64)
        self.std = np.ones(self.G, np.float64)
        self.have_distribution = False
        if cfg.mode == "streaming":
            self._s0 = np.zeros(self.G, np.float64)
            self._s1 = np.zeros(self.G, np.float64)
            self._s2 = np.zeros(self.G, np.float64)
            self.scores = None
        else:
            import logging
            import os

            ring_bytes = 8 * self.G * cfg.historic_window_size
            cap_gib = float(os.environ.get("RTAP_MAX_LIKELIHOOD_RING_GB", "8"))
            if ring_bytes > cap_gib * (1 << 30):
                raise ValueError(
                    f"window-mode likelihood ring would be "
                    f"{ring_bytes / (1 << 30):.1f} GiB host RAM for G={self.G} "
                    f"x W={cfg.historic_window_size} (cap {cap_gib:g} GiB; "
                    "RTAP_MAX_LIKELIHOOD_RING_GB to raise). Use "
                    "mode='streaming' at this stream count."
                )
            if ring_bytes > self.RING_WARN_BYTES:
                logging.getLogger(__name__).warning(
                    "window-mode likelihood ring: %.1f GiB host RAM (G=%d, W=%d); "
                    "consider mode='streaming' at scale",
                    ring_bytes / (1 << 30), self.G, cfg.historic_window_size,
                )
            # historic window ring [G, W]; cursor/fill shared (lockstep)
            self.scores = np.zeros((self.G, cfg.historic_window_size), np.float64)

    # ---- dynamic membership ----
    def reset_slot(self, g: int) -> None:
        """Re-initialize one slot for a stream claimed mid-run: fresh
        moments/rings and a probation clock starting NOW. Streaming mode
        reproduces a fresh stream's outputs exactly (per-stream EMA
        moments); window mode masks the slot's pre-birth ring entries out
        of its Gaussian refit (`_refit_window`), so its distribution is
        fit from its OWN scores only — refit *times* stay on the group's
        lockstep clock, the one (documented) difference from a standalone
        fresh stream."""
        self.birth[g] = self.records
        self.recent[g] = 0.0
        self.mean[g] = 0.0
        self.std[g] = 1.0
        if self.scores is None:
            self._s0[g] = self._s1[g] = self._s2[g] = 0.0
        else:
            self.scores[g] = 0.0

    # ---- checkpointing ----
    def state_dict(self) -> dict[str, np.ndarray]:
        d = {
            # 0-d arrays, not numpy scalars: orbax has no TypeHandler for the
            # scalar types (np.bool_/np.int64)
            "records": np.asarray(self.records, np.int64),
            "birth": self.birth,
            "recent": self.recent,
            "mean": self.mean,
            "std": self.std,
            "have_distribution": np.asarray(self.have_distribution),
        }
        if self.scores is not None:
            d["scores"] = self.scores
        else:
            d.update(s0=self._s0, s1=self._s1, s2=self._s2)
        return d

    def load_state_dict(self, d: dict[str, np.ndarray]) -> None:
        self.records = int(d["records"])
        # pre-dynamic-membership checkpoints lack birth: zeros (all slots
        # are founding members) reproduces the old behavior exactly
        self.birth = (np.asarray(d["birth"], np.int64) if "birth" in d
                      else np.zeros(self.G, np.int64))
        self.recent = np.asarray(d["recent"], np.float64)
        self.mean = np.asarray(d["mean"], np.float64)
        self.std = np.asarray(d["std"], np.float64)
        self.have_distribution = bool(d["have_distribution"])
        if self.scores is not None:
            self.scores = np.asarray(d["scores"], np.float64)
        else:
            self._s0, self._s1, self._s2 = (
                np.asarray(d["s0"], np.float64),
                np.asarray(d["s1"], np.float64),
                np.asarray(d["s2"], np.float64),
            )

    # ---- the per-tick update ----
    def _refit_window(self) -> None:
        n = min(self.records, self.cfg.historic_window_size)
        # ring -> chronological [G, n]
        cur = self.records % self.cfg.historic_window_size
        if self.records <= self.cfg.historic_window_size:
            scores = self.scores[:, :n]
        else:
            scores = np.concatenate([self.scores[:, cur:], self.scores[:, :cur]], axis=1)
        # skip records from the model's learning period (oracle._refit_window)
        still_buffered = max(0, self.cfg.learning_period - (self.records - n))
        if still_buffered:
            scores = scores[:, still_buffered:]
        if scores.shape[1] < 2:
            return
        w = self.cfg.averaging_window
        if scores.shape[1] >= w:
            # moving average over trailing window (the oracle's convolve
            # "valid" mode), via cumulative sums
            csum = np.cumsum(np.pad(scores * (1.0 / w), ((0, 0), (1, 0))), axis=1)
            averaged = csum[:, w:] - csum[:, :-w]
        else:
            averaged = scores
        if not self.birth.any():
            # founding-members fast path, bit-identical to the original
            self.mean = averaged.mean(axis=1)
            self.std = np.maximum(averaged.std(axis=1), 1e-6)
            self.have_distribution = True
            return
        # per-slot masking for claimed slots: chronological entries before
        # a slot's birth are reset zeros, and the slot's FIRST
        # learning_period own scores are its untrained model's learning
        # transient (near-1.0 raws) — the oracle excludes exactly that
        # window for a fresh stream ("would inflate sigma"), so the
        # claimed slot must too. An averaged entry is valid iff its whole
        # w-window lies at/after birth + learning_period. For founding
        # members (birth 0) this reduces to <= 0 — identical to the
        # global still_buffered trim, hence the fast path above. Slots
        # with <2 valid entries keep their previous (reset: 0/1) moments —
        # the young mask pins them to 0.5 through probation anyway.
        chrono_start = self.records - n + still_buffered
        p = np.maximum(
            self.birth + self.cfg.learning_period - chrono_start, 0)
        idx = np.arange(averaged.shape[1])[None, :]
        valid = idx >= p[:, None]
        cnt = valid.sum(axis=1)
        safe = np.maximum(cnt, 1)
        mean_new = (averaged * valid).sum(axis=1) / safe
        var = (((averaged - mean_new[:, None]) ** 2) * valid).sum(axis=1) / safe
        std_new = np.maximum(np.sqrt(var), 1e-6)
        ok = cnt >= 2
        self.mean = np.where(ok, mean_new, self.mean)
        self.std = np.where(ok, std_new, self.std)
        self.have_distribution = True

    def _update_streaming(self, avg: np.ndarray) -> None:
        d = self.cfg.streaming_decay
        self._s0 = d * self._s0 + 1.0
        self._s1 = d * self._s1 + avg
        self._s2 = d * self._s2 + avg * avg
        self.mean = self._s1 / self._s0
        var = np.maximum(self._s2 / self._s0 - self.mean**2, 0.0)
        self.std = np.maximum(np.sqrt(var), 1e-6)
        self.have_distribution = self.records >= self.cfg.probationary_period

    def update(self, raw: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Feed one tick of raw scores [G] -> (likelihood [G], log_lik [G])."""
        raw = np.asarray(raw, np.float64)
        w = self.cfg.averaging_window
        self.recent[:, self.records % w] = raw
        self.records += 1
        if not self.birth.any():
            # founding-members fast path: bit-identical to the original
            # lockstep logic (all slots share one age)
            n_recent = min(self.records, w)
            if self.records < w:
                avg = self.recent[:, :n_recent].sum(axis=1) / n_recent
            else:
                avg = self.recent.sum(axis=1) / w
        else:
            # per-slot age: a claimed slot's ring was zeroed at birth, so
            # the full-ring sum is the sum of its own samples; dividing by
            # min(age, w) reproduces a fresh stream's moving average
            # (for birth=0 slots this equals the fast path up to summation
            # order). Same lockstep cursor, per-slot maturity.
            age = np.minimum(self.records - self.birth, w)
            avg = self.recent.sum(axis=1) / np.maximum(age, 1)

        if self.cfg.mode == "streaming":
            self._update_streaming(avg)
        else:
            self.scores[:, (self.records - 1) % self.cfg.historic_window_size] = raw
            if self.records % self.cfg.reestimation_period == 0 or not self.have_distribution:
                if self.records >= self.cfg.probationary_period:
                    self._refit_window()

        if self.records < self.cfg.probationary_period or not self.have_distribution:
            half = np.full(self.G, 0.5)
            return half, log_likelihood_np(half)
        lik = 1.0 - tail_probability_np((avg - self.mean) / self.std)
        # per-slot probation: slots claimed mid-run (birth > 0) are pinned
        # to 0.5 until THEIR OWN age clears the probationary period — a
        # late-joining stream must not be scored against moments it has
        # not yet established (same contract as a founding member's)
        young = (self.records - self.birth) < self.cfg.probationary_period
        if young.any():
            lik = np.where(young, 0.5, lik)
        return lik, log_likelihood_np(lik)
