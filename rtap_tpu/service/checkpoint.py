"""Checkpoint / resume for stream groups (SURVEY.md §5 "Checkpoint/resume").

The reference saves model state via NuPIC's Cap'n Proto serialization
(`model.save()` / `ModelFactory.loadFromCheckpoint`), and the anomaly
-likelihood history must ride along or likelihoods reset. Here a checkpoint
is the group's full resume state: the device state pytree (fetched to host),
the batched-likelihood state, stream ids, tick count, and the model config —
written atomically per group with orbax. A resumed group continues
bit-identically to an uninterrupted run (tests/unit/test_checkpoint.py).
"""

from __future__ import annotations

import json
import shutil
import time
import uuid
from pathlib import Path

import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.obs import get_registry
from rtap_tpu.service.registry import StreamGroup


# rtap: host-boundary — checkpoint save OWNS the device->host
# materialization: it must fetch the full (possibly mesh-sharded) tree
# to write a topology-independent checkpoint, with the pipeline drained
def save_group(grp: StreamGroup, path: str | Path,
               alerts_offset: int | None = None,
               journal_tick: int | None = None) -> None:
    """Write one group's resume state to `path` (a directory, per group).

    Atomic on overwrite: the tree + meta are written to a fresh temp sibling
    directory and swapped in with renames, so a crash mid-save can never leave
    a directory that has meta.json (the completeness marker) but a partially
    rewritten state tree.

    `alerts_offset` is the alert-delivery cursor (ISSUE 5): the alert
    sink's byte size at this save instant. Saves happen with the
    pipeline fully drained and the sink flushed, so every alert for
    ticks <= this checkpoint's `ticks` sits BEFORE the cursor and every
    byte past it belongs to post-checkpoint ticks — on resume, the
    journal replay scans the sink from the cursor and suppresses exactly
    the already-delivered alert ids (exactly-once across a crash;
    docs/RESILIENCE.md durability section).

    `journal_tick` is the GLOBAL journal tick cursor at this save
    instant. It equals `ticks` on a group's original timeline, but a
    mid-run quarantine restore REWINDS the group counter while the
    global clock keeps running — the journal replay must match rows by
    this global cursor, never by the rewindable per-group one.
    """
    import jax
    import orbax.checkpoint as ocp

    obs = get_registry()
    t_save = time.perf_counter()

    path = Path(path).absolute()
    # the forward synapse index (fwd_*) is derived state: never stored —
    # load_group rebuilds it from `presyn` — so the on-disk schema is
    # identical across dendrite modes (ops/fwd_index.py)
    if grp.backend == "tpu":
        model_state = {
            k: np.asarray(v)
            for k, v in jax.device_get(grp.state).items()
            if not k.startswith("fwd_")
        }
        tree = {"model": model_state}
    else:
        # per-stream state dicts include classifier cls_* arrays when enabled
        # (the oracle operates on the shared state layout, like TMOracle)
        tree = {
            "model": {
                f"s{g}": {
                    k: v for k, v in grp._states[g].items() if not k.startswith("fwd_")
                }
                for g in range(grp.G)
            }
        }
    tree["likelihood"] = grp.likelihood.state_dict()
    tree["alert_run"] = np.asarray(grp._alert_run)  # debounce counters

    meta = {
        "backend": grp.backend,
        "stream_ids": grp.stream_ids,
        "ticks": grp.ticks,
        "threshold": grp.threshold,
        "debounce": grp.debounce,
        "predict": int(getattr(grp, "predict", 0)),
        "n_live": getattr(grp, "n_live", grp.G),
        "sharded": grp.mesh is not None,
        "config": grp.cfg.to_dict(),
        "alert_epoch": int(getattr(grp, "alert_epoch", 0)),
    }
    if alerts_offset is not None:
        meta["alerts_offset"] = int(alerts_offset)
    if journal_tick is not None:
        meta["journal_tick"] = int(journal_tick)
    tmp = path.parent / f".{path.name}.tmp-{uuid.uuid4().hex[:8]}"
    swapped = False
    try:
        tmp.mkdir(parents=True)
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(tmp / "state", tree, force=True)
        # meta written AFTER the tree: its presence marks the checkpoint complete
        (tmp / "meta.json").write_text(json.dumps(meta))
        if path.exists():
            old = path.parent / f".{path.name}.old-{uuid.uuid4().hex[:8]}"
            path.rename(old)
            try:
                tmp.rename(path)
                swapped = True
            except BaseException:
                old.rename(path)  # roll the previous checkpoint back in place
                raise
            shutil.rmtree(old, ignore_errors=True)
        else:
            tmp.rename(path)
            swapped = True
    except BaseException:
        # a failed save must leave the previous checkpoint intact (the
        # whole write happened in the temp sibling; the finally below
        # sweeps it) AND be visible: live_loop turns this into a
        # checkpoint_save_failed event and its breaker decides whether to
        # keep trying — a full disk must never kill scoring
        obs.counter(
            "rtap_obs_checkpoint_save_failures_total",
            "group checkpoint saves that raised before landing (previous "
            "checkpoint left intact)").inc()
        raise
    finally:
        if not swapped:
            shutil.rmtree(tmp, ignore_errors=True)
    # Sweep residue from PRIOR interrupted saves only after this save fully
    # landed: a complete `.old-*`/`.tmp-*` sibling is load_group's crash
    # fallback and must never be deleted before a newer complete copy exists.
    # rtap: allow[replay-determinism] — every match is deleted; order-free
    for stale in path.parent.glob(f".{path.name}.tmp-*"):
        if stale != tmp:
            shutil.rmtree(stale, ignore_errors=True)
    # rtap: allow[replay-determinism] — every match is deleted; order-free
    for stale in path.parent.glob(f".{path.name}.old-*"):
        shutil.rmtree(stale, ignore_errors=True)
    obs.counter("rtap_obs_checkpoint_saves_total",
                "atomic per-group checkpoint saves that fully landed").inc()
    obs.histogram("rtap_obs_checkpoint_save_seconds",
                  "wall seconds per group save (state fetch + orbax write + "
                  "swap)").observe(time.perf_counter() - t_save)


def _recover_residue(path: Path) -> Path:
    """If `path` is missing but a complete residue sibling from an
    interrupted save exists (meta.json present), rename it into place and
    return `path`; otherwise return `path` unchanged (load will fail with
    the underlying error)."""
    if (path / "meta.json").exists():
        return path
    # sorted so an mtime TIE between two residue dirs resolves to the
    # same winner on every host (max keeps the first of equal keys)
    candidates = sorted(
        p
        for pattern in (f".{path.name}.old-*", f".{path.name}.tmp-*")
        for p in path.parent.glob(pattern)
        if (p / "meta.json").exists()
    )
    if candidates:
        import logging

        best = max(candidates, key=lambda p: (p / "meta.json").stat().st_mtime)
        logging.getLogger(__name__).warning(
            "checkpoint %s missing; recovering interrupted-save residue %s", path, best
        )
        if not path.exists():
            best.rename(path)
    return path


def load_group(path: str | Path, mesh=None, sparsify: bool = False) -> StreamGroup:
    """Rebuild a StreamGroup from `path`; scoring continues bit-identically.

    A group checkpointed while sharded over a mesh records that fact; pass
    `mesh` to re-shard on resume. Resuming a sharded checkpoint without a mesh
    downgrades to single-device and logs a warning (the state itself is
    topology-independent — only placement changes).

    `sparsify` migrates a DENSE-layout SP pool checkpoint into the sparse
    member-index layout on the way in (models/migrate.py): the resumed
    group's config gains ``sparse_pool=True`` with the migration's exact
    pool width pinned via ``pool_members``, and scoring continues
    BIT-IDENTICALLY to the dense run (the re-layout is lossless — see
    docs/MIGRATION.md). Already-sparse checkpoints are untouched.
    """
    import jax
    import orbax.checkpoint as ocp

    path = _recover_residue(Path(path).absolute())
    meta = json.loads((path / "meta.json").read_text())
    cfg = ModelConfig.from_dict(meta["config"])
    if meta.get("sharded") and mesh is None:
        import logging

        logging.getLogger(__name__).warning(
            "checkpoint %s was saved sharded over a mesh; resuming single-device "
            "(pass mesh= to load_group to restore the sharded topology)", path
        )
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.restore(path / "state")
    if sparsify and not cfg.sp.sparse_pool:
        from rtap_tpu.models.migrate import (
            sparse_pool_width, sparsify_config, sparsify_sp_state)

        n_slots = len(meta["stream_ids"])
        if meta["backend"] == "tpu":
            # batched tree [G, C, n_in]: one migration call, one shared P
            model = {k: np.asarray(v) for k, v in tree["model"].items()}
            P = sparse_pool_width(model["potential"])
            tree["model"] = sparsify_sp_state(model, P)
        else:
            # per-stream dicts share the group's config, so the pool width
            # is the max over all streams (narrower columns pad with -1)
            P = max(
                sparse_pool_width(np.asarray(tree["model"][f"s{g}"]["potential"]))
                for g in range(n_slots))
            for g in range(n_slots):
                tree["model"][f"s{g}"] = sparsify_sp_state(
                    {k: np.asarray(v) for k, v in tree["model"][f"s{g}"].items()}, P)
        cfg = sparsify_config(cfg, P)
    grp = StreamGroup(
        cfg, meta["stream_ids"], backend=meta["backend"], threshold=meta["threshold"],
        mesh=mesh, debounce=int(meta.get("debounce", 1)),
        predict=int(meta.get("predict", 0)),
    )
    if grp.backend == "tpu":
        from rtap_tpu.ops.tm_tpu import dendrite_mode

        model = {k: v for k, v in tree["model"].items() if not k.startswith("fwd_")}
        if dendrite_mode() == "forward":
            # rebuild the derived forward index from the restored pools
            # (per stream; any fanout_cap overflow lands in fwd_of and the
            # service's overflow observability picks it up)
            from functools import partial

            from rtap_tpu.ops.fwd_index import build_fwd_index

            slots, pos, of = jax.vmap(
                partial(
                    build_fwd_index,
                    n_cells=cfg.num_cells,
                    fanout_cap=cfg.tm.fanout_cap,
                )
            )(np.asarray(model["presyn"]))
            model["fwd_slots"] = np.asarray(slots)
            model["fwd_pos"] = np.asarray(pos)
            model["fwd_of"] = np.asarray(of)
        if mesh is not None:
            from rtap_tpu.parallel.sharding import shard_state

            grp.state = shard_state(model, mesh)
        else:
            grp.state = jax.device_put(model)
    else:
        for g in range(grp.G):
            saved = tree["model"][f"s{g}"]
            for k in grp._states[g]:
                if k.startswith("fwd_"):
                    continue  # derived, oracle-unused; fresh arrays stay
                grp._states[g][k] = np.asarray(saved[k])
    grp.likelihood.load_state_dict(tree["likelihood"])
    if "alert_run" in tree:  # pre-debounce checkpoints lack it (zeros then)
        grp._alert_run = np.asarray(tree["alert_run"]).astype(np.int64)
    grp.ticks = int(meta["ticks"])
    # the alert-delivery cursor rides along for resume-time suppression
    # (None for pre-durability checkpoints: the scan falls back to 0)
    grp.resume_alerts_offset = (
        int(meta["alerts_offset"]) if "alerts_offset" in meta else None)
    grp.resume_journal_tick = (
        int(meta["journal_tick"]) if "journal_tick" in meta else None)
    grp.alert_epoch = int(meta.get("alert_epoch", 0))
    # n_live is now derived from stream_ids (pad-prefix count) — the meta
    # field stays written for inspection/back-compat but is not load-bearing
    get_registry().counter(
        "rtap_obs_checkpoint_loads_total",
        "group checkpoints restored (service/replay resume)").inc()
    return grp


def peek_resume_ticks(checkpoint_dir: str | Path) -> int:
    """Max recorded tick cursor across a dir's group checkpoints, read
    from meta.json alone (no state load) — the serve CLI's resume-base
    probe when ``--journal-dir`` treats ``--ticks`` as a total budget
    across restarts. 0 for a missing/empty/unreadable dir."""
    best = 0
    root = Path(checkpoint_dir)
    if not root.is_dir():
        return 0
    for d in sorted(root.iterdir()):
        if not d.name.startswith("group") or not d.is_dir():
            continue
        try:
            best = max(best,
                       int(json.loads((d / "meta.json").read_text())["ticks"]))
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return best


def validate_resume(resumed: StreamGroup, ck_path, grp: StreamGroup,
                    allow_claimed_extras: bool = False) -> None:
    """Shared resume-safety gate for replay_streams and live_loop: a resumed
    group silently carries its checkpoint's model config and alerting
    semantics, so the checkpoint must MATCH what this run would have built —
    mixing them would blend two semantics in one result. Mismatches are
    errors, not surprises. Add new load-bearing fields here, once, so both
    entry points stay in lockstep.

    `allow_claimed_extras` (serve --auto-register): slots this run built as
    PADS may hold real streams in the checkpoint — they were lazily claimed
    in the prior run and rightfully resume live (the caller reconciles
    registry routing). Pad names may differ (released slots get unique
    names). Every REQUESTED stream must still match its slot exactly."""
    from rtap_tpu.service.registry import PAD_PREFIX

    if len(resumed.stream_ids) != len(grp.stream_ids):
        raise ValueError(
            f"checkpoint {ck_path} has {len(resumed.stream_ids)} slots but "
            f"this group was built with {len(grp.stream_ids)}; refusing to "
            "resume")
    for slot, (ck_id, want_id) in enumerate(
            zip(resumed.stream_ids, grp.stream_ids)):
        if ck_id == want_id:
            continue
        ck_pad = ck_id.startswith(PAD_PREFIX)
        want_pad = want_id.startswith(PAD_PREFIX)
        if ck_pad and want_pad:
            continue  # pad naming is not load-bearing (released slots)
        if allow_claimed_extras and want_pad and not ck_pad:
            continue  # a previously auto-registered stream resumes live
        raise ValueError(
            f"checkpoint {ck_path} holds {ck_id!r} at slot {slot} but this "
            f"group expects {want_id!r}; refusing to resume"
            + ("" if allow_claimed_extras else
               " (lazily claimed extras resume under serve"
               " --auto-register, or frozen via serve --freeze)"))
    mismatches = [
        f"{name}: checkpoint={a!r} vs requested={b!r}"
        for name, a, b in (
            ("config", resumed.cfg, grp.cfg),
            ("threshold", resumed.threshold, grp.threshold),
            ("debounce", resumed.debounce, grp.debounce),
            # the predictor leaves live INSIDE the state tree: resuming
            # across a horizon change would need a structural migration,
            # not a silent blend
            ("predict", getattr(resumed, "predict", 0),
             getattr(grp, "predict", 0)),
        )
        if a != b
    ]
    if mismatches:
        raise ValueError(
            f"checkpoint {ck_path} disagrees with this run's parameters "
            f"({'; '.join(mismatches)}); rerun with the checkpointed "
            "settings or use a fresh checkpoint dir")
