"""Checkpoint / resume for stream groups (SURVEY.md §5 "Checkpoint/resume").

The reference saves model state via NuPIC's Cap'n Proto serialization
(`model.save()` / `ModelFactory.loadFromCheckpoint`), and the anomaly
-likelihood history must ride along or likelihoods reset. Here a checkpoint
is the group's full resume state: the device state pytree (fetched to host),
the batched-likelihood state, stream ids, tick count, and the model config —
written atomically per group with orbax. A resumed group continues
bit-identically to an uninterrupted run (tests/unit/test_checkpoint.py).
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.service.registry import StreamGroup


def save_group(grp: StreamGroup, path: str | Path) -> None:
    """Write one group's resume state to `path` (a directory, per group)."""
    import jax
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    if grp.backend == "tpu":
        model_state = {k: np.asarray(v) for k, v in jax.device_get(grp.state).items()}
        tree = {"model": model_state}
    else:
        tree = {"model": {f"s{g}": grp._states[g] for g in range(grp.G)}}
    tree["likelihood"] = grp.likelihood.state_dict()

    meta = {
        "backend": grp.backend,
        "stream_ids": grp.stream_ids,
        "ticks": grp.ticks,
        "threshold": grp.threshold,
        "n_live": getattr(grp, "n_live", grp.G),
        "config": grp.cfg.to_dict(),
    }
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path / "state", tree, force=True)
    # meta written AFTER the tree: its presence marks the checkpoint complete
    (path / "meta.json").write_text(json.dumps(meta))


def load_group(path: str | Path) -> StreamGroup:
    """Rebuild a StreamGroup from `path`; scoring continues bit-identically."""
    import jax
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    meta = json.loads((path / "meta.json").read_text())
    cfg = ModelConfig.from_dict(meta["config"])
    grp = StreamGroup(
        cfg, meta["stream_ids"], backend=meta["backend"], threshold=meta["threshold"]
    )
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.restore(path / "state")
    if grp.backend == "tpu":
        grp.state = jax.device_put(tree["model"])
    else:
        for g in range(grp.G):
            saved = tree["model"][f"s{g}"]
            for k in grp._states[g]:
                grp._states[g][k] = np.asarray(saved[k])
    grp.likelihood.load_state_dict(tree["likelihood"])
    grp.ticks = int(meta["ticks"])
    grp.n_live = int(meta["n_live"])
    return grp
