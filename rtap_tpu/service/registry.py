"""Stream-group registry: many metric streams, few compiled programs.

The reference's stream manager lazily creates one NuPIC model per node-metric
stream and steps each in Python (SURVEY.md C19, §3.3). On TPU that shape is
wrong — thousands of tiny independent programs waste the chip. Here streams
are packed into fixed-capacity groups; all streams of a group share ONE
jitted vmapped step (ops/step.group_step), so a tick costs one device
dispatch per group and XLA compiles once per (config, group size).

`backend="cpu"` keeps the reference's default behavior (per-stream numpy
oracle models, no device) with the same API, preserving the plugin boundary:
CPU default, TPU opt-in per group (BASELINE.json north star).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.service.likelihood_batch import BatchAnomalyLikelihood


@dataclass
class TickResult:
    """Scores for one tick of one group, index-aligned with group.stream_ids."""

    raw: np.ndarray  # [G] f32
    likelihood: np.ndarray  # [G] f64
    log_likelihood: np.ndarray  # [G] f64
    alerts: np.ndarray  # [G] bool
    prediction: np.ndarray | None = None  # [G] f32, when the classifier is on


PAD_PREFIX = "__pad"


class StreamGroup:
    """G lockstep streams sharing one compiled device step (or one oracle loop).

    Slots whose id starts with ``__pad`` are capacity, not streams: they are
    fed NaN, never emitted, and can be CLAIMED mid-run by a new stream
    (:meth:`claim_slot` — the reference's lazy model creation, SURVEY.md C19)
    or returned by a departing one (:meth:`release_slot`). Claiming resets
    the slot's model state, likelihood moments + probation clock, and
    debounce counter, so a claimed slot is indistinguishable from a fresh
    model; the group's compiled program never changes (shapes are static —
    membership is data, not topology).

    ``health=True`` (ISSUE 6) makes every dispatched step additionally
    return the fused per-group model-health leaf (ops/health_tpu.py:
    occupancy/permanence/sparsity/hit-rate/score-histogram aggregates,
    ~200 B/tick); :meth:`collect_chunk` and :meth:`tick` stash it in
    ``self.last_health`` (numpy tree, leading tick axis) for the host
    HealthTracker to fold. Scores and model state are bit-identical with
    health on or off — the leaf is pure reads. Unsupported under a mesh
    (the aggregate would need a cross-shard collective, and
    sharded_chunk_step is collective-free by contract).

    ``predict=k`` > 0 (ISSUE 16) arms the predictive-horizon reducer
    (ops/predict_tpu.py) at horizon k: the state tree gains the
    predictor-owned ring/EWMA leaves and every dispatched step returns
    the per-stream divergence leaf, stashed in ``self.last_predict``
    exactly like health for the host PredictTracker (rtap_tpu/predict/)
    to fold. Model state and scores stay bit-identical with predict on
    or off (the model leaves are pure reads; the predictor leaves exist
    only when armed). Unsupported under a mesh for the same contract
    reason as health.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        stream_ids: list[str],
        seed: int = 0,
        backend: str = "tpu",
        threshold: float = 0.5,
        mesh=None,
        debounce: int = 1,
        health: bool = False,
        predict: int = 0,
    ):
        if debounce < 1:
            raise ValueError(f"debounce must be >= 1, got {debounce}")
        if health and mesh is not None:
            raise ValueError(
                "health reducers are unsupported on meshed groups: the "
                "per-group aggregate would need a cross-shard collective "
                "(sharded_chunk_step is collective-free by contract)")
        if predict < 0:
            raise ValueError(f"predict horizon must be >= 0, got {predict}")
        if predict and mesh is not None:
            raise ValueError(
                "the predictive-horizon reducer is unsupported on meshed "
                "groups (sharded_chunk_step is collective-free by "
                "contract, like health)")
        self.cfg = cfg
        self.stream_ids = list(stream_ids)
        self.G = len(self.stream_ids)
        self.seed = seed  # claim_slot re-inits a slot exactly as creation did
        self.backend = backend
        self.threshold = threshold
        # alert debouncing (SURVEY.md C20; round-4 quality study): a stream
        # alerts only after `debounce` CONSECUTIVE ticks at/above threshold.
        # False episodes are dominated by 1-2-tick likelihood flickers while
        # real faults persist (reports/quality_study.json), so debounce
        # trades a few ticks of latency for episode precision.
        self.debounce = int(debounce)
        self._alert_run = np.zeros(self.G, np.int64)  # consecutive hit count
        self.mesh = mesh
        self.health = bool(health)
        self.predict = int(predict)  # horizon k; 0 = predictor off
        # latest per-tick health leaves [T, ...] (health=True only);
        # kept in sync by collect_chunk and tick like last_predictions
        self.last_health: dict | None = None
        # latest per-tick predictive-horizon leaves [T, G] (predict > 0)
        self.last_predict: dict | None = None
        self.likelihood = BatchAnomalyLikelihood(cfg.likelihood, self.G)
        self.ticks = 0
        # alert-id timeline epoch: 0 for a group's original timeline;
        # bumped when a quarantine restore REWINDS self.ticks mid-run so
        # re-used tick indices never collide with already-delivered
        # alert_ids (docs/TELEMETRY.md alert schema; persisted in
        # checkpoint meta)
        self.alert_epoch = 0
        self._seq = 0  # dispatch sequence number (pipelined replay ordering)
        self._collected = 0
        # latest predicted values [T, G] (classifier only); kept in sync by
        # both run_chunk and tick so it can never serve stale data
        self.last_predictions: np.ndarray | None = None
        if backend == "tpu":
            from rtap_tpu.models.state import init_state

            if mesh is not None:
                # memory-lean: per-shard broadcast views, never the full
                # group on host (54 GiB at the 100k-stream scale)
                from rtap_tpu.parallel.sharding import broadcast_group_state

                self.state = broadcast_group_state(init_state(cfg, seed), self.G, mesh)
            else:
                # one ~0.5 MB transfer + on-chip broadcast, never a [G, ...]
                # host staging (208 s at the G=24k HBM frontier)
                from rtap_tpu.ops.step import replicate_state_device

                self.state = replicate_state_device(
                    init_state(cfg, seed, predict_horizon=self.predict),
                    self.G)
        else:
            from rtap_tpu.models.oracle.temporal_memory import TMOracle
            from rtap_tpu.models.state import init_state

            self._states = [
                init_state(cfg, seed, predict_horizon=self.predict)
                for _ in range(self.G)]
            self._tms = [TMOracle(s, cfg.tm) for s in self._states]
            self._classifiers = None
            if cfg.classifier.enabled:
                from rtap_tpu.models.oracle.classifier import SDRClassifierOracle

                self._classifiers = [
                    SDRClassifierOracle(s, cfg.classifier) for s in self._states
                ]

    # ---- dynamic membership (slots are static, streams are data) ----
    @property
    def n_live(self) -> int:
        return self.G - sum(
            1 for s in self.stream_ids if s.startswith(PAD_PREFIX))

    def live_slots(self) -> np.ndarray:
        """Slot indices holding real streams, ascending. For a group built
        without pads this is arange(G); emission and value routing index
        with it so pad/released slots never surface."""
        return np.array(
            [i for i, s in enumerate(self.stream_ids)
             if not s.startswith(PAD_PREFIX)], np.int64)

    def free_slot_count(self) -> int:
        return self.G - self.n_live

    def claim_slot(self, stream_id: str) -> int:
        """Assign `stream_id` to a pad slot mid-run -> slot index.

        The slot's model state is re-initialized exactly as group creation
        initialized it (same config, same per-group seed), its likelihood
        moments and probation clock restart, and its debounce counter
        clears — a claimed slot behaves bit-for-bit like a stream that was
        registered into a fresh group (pinned by
        tests/unit/test_dynamic_streams.py). The compiled program is
        untouched: shapes are static, membership is data. Works on meshed
        groups too: the donated .at[slot].set lowers to a shard-local
        predicated update under GSPMD (the slot lives on exactly one
        shard), sharding preserved — tests/scale/test_sharded.py pins
        bit-exactness vs the single-device claim.
        """
        if stream_id.startswith(PAD_PREFIX):
            raise ValueError(f"stream id may not start with {PAD_PREFIX!r}")
        if stream_id in self.stream_ids:
            raise KeyError(f"duplicate stream id {stream_id!r}")
        slot = next((i for i, s in enumerate(self.stream_ids)
                     if s.startswith(PAD_PREFIX)), None)
        if slot is None:
            raise RuntimeError(
                f"group is full ({self.G} live streams); capacity comes "
                "from pad slots (group-size rounding or released streams)")
        self._reset_slot_state(slot)
        self.stream_ids[slot] = stream_id
        return slot

    def release_slot(self, stream_id: str) -> int:
        """Return a stream's slot to pad capacity -> freed slot index.

        The slot stops being fed and emitted immediately; its state stays
        in place (harmlessly ticking on NaN) until a future claim resets
        it. The id becomes available for re-registration elsewhere."""
        try:
            slot = self.stream_ids.index(stream_id)
        except ValueError:
            raise KeyError(f"unknown stream id {stream_id!r}") from None
        # unique pad name: a plain __pad<i> could collide with creation pads
        self.stream_ids[slot] = f"{PAD_PREFIX}!released{slot}"
        self._alert_run[slot] = 0
        return slot

    def _reset_slot_state(self, slot: int) -> None:
        from rtap_tpu.models.state import init_state

        fresh = init_state(self.cfg, self.seed, predict_horizon=self.predict)
        if self.predict:
            # the claimed slot's predictor warm-up restarts NOW: its ring
            # is zeroed, and scoring a real tick against a zeroed ring
            # would fake a full-divergence precursor (ops/predict_tpu.py
            # gates scoring on tick >= pred_tick0 + horizon)
            fresh["pred_tick0"] = np.int32(self.ticks)
        if self.backend == "tpu":
            from rtap_tpu.ops.step import set_state_row

            # match the live tree's structure (forward-index mode carries
            # derived fwd_* leaves that init_state also builds)
            self.state = set_state_row(
                self.state, {k: fresh[k] for k in self.state}, slot)
        else:
            from rtap_tpu.models.oracle.temporal_memory import TMOracle

            self._states[slot] = fresh
            self._tms[slot] = TMOracle(fresh, self.cfg.tm)
            if self._classifiers is not None:
                from rtap_tpu.models.oracle.classifier import SDRClassifierOracle

                self._classifiers[slot] = SDRClassifierOracle(
                    fresh, self.cfg.classifier)
        self.likelihood.reset_slot(slot)
        self._alert_run[slot] = 0

    def _raw_cpu(self, values: np.ndarray, ts: np.ndarray, learn: bool = True):
        from rtap_tpu.models.htm_model import oracle_record_step

        if learn and self.cfg.cadence_active:
            # host twin of the device schedule (ops/step.py:_tick): same
            # clock (tm_iter = completed steps, lockstep across the group),
            # same predicate (cfg.learns_on) — without this the CPU backend
            # would silently ignore the learning cadence and backends would
            # diverge (caught by the r4 cadence quality sweep coming back
            # bit-identical across k)
            learn = bool(self.cfg.learns_on(int(self._states[0]["tm_iter"])))
        raw = np.empty(self.G, np.float32)
        pred = np.empty(self.G, np.float32) if self._classifiers else None
        for g in range(self.G):
            out = oracle_record_step(
                self.cfg, self._states[g], self._tms[g], values[g], int(ts[g]), learn,
                classifier=self._classifiers[g] if self._classifiers else None,
            )
            if self._classifiers:
                raw[g], pred[g] = out[0], out[1]
            else:
                raw[g] = out
        return raw, pred

    def _put(self, x: np.ndarray, axis: int = 0):
        """Host array -> device, sharded on the stream axis when meshed.

        For chunked arrays [T, G, ...] the stream axis is 1; sharding is
        expressed on that axis (the leading time axis is replicated)."""
        import jax
        import jax.numpy as jnp

        if self.mesh is None:
            return jnp.asarray(x)
        from rtap_tpu.parallel.sharding import put_sharded

        return put_sharded(np.asarray(x), self.mesh, axis)

    def tick(self, values: np.ndarray, ts: np.ndarray | int, learn: bool = True) -> TickResult:
        """Score one tick. `values` [G] or [G, n_fields]; `ts` scalar or [G]."""
        values = np.asarray(values, np.float32)
        if values.ndim == 1:
            values = values[:, None]
        ts = np.broadcast_to(np.asarray(ts, np.int32), (self.G,))
        pred = None
        if self.backend == "tpu":
            if self.mesh is not None:
                from rtap_tpu.ops.step import sharded_chunk_step

                self.state, out = sharded_chunk_step(
                    self.state, self._put(values[None], axis=1),
                    self._put(ts[None].astype(np.int32), axis=1), self.cfg, self.mesh,
                    learn=learn,
                )
                raw, pred = self._unpack_out(out, time_axis=True)
            else:
                from rtap_tpu.ops.step import group_step

                self.state, out = group_step(
                    self.state, self._put(values), self._put(ts.astype(np.int32)), self.cfg,
                    learn=learn, health=self.health,
                    predict=bool(self.predict),
                )
                if self.predict:  # wraps outermost (ops/step.py _tick)
                    out, pleaf = out
                    self.last_predict = {
                        k: np.asarray(v)[None, ...] for k, v in pleaf.items()}
                if self.health:
                    out, health = out
                    self.last_health = {
                        k: np.asarray(v)[None, ...] for k, v in health.items()}
                raw, pred = self._unpack_out(out, time_axis=False)
        else:
            raw, pred = self._raw_cpu(values, ts, learn)
            if self.health:
                from rtap_tpu.ops.health_tpu import health_from_states

                self.last_health = {
                    k: np.asarray(v)[None, ...] for k, v in
                    health_from_states(self._states, raw, values,
                                       self.cfg).items()}
            if self.predict:
                from rtap_tpu.models.oracle.predict import predict_from_states

                self.last_predict = {
                    k: np.asarray(v)[None, ...] for k, v in
                    predict_from_states(self._states, values,
                                        self.cfg).items()}
        self.last_predictions = None if pred is None else pred[None, :]
        self.ticks += 1
        lik, loglik = self.likelihood.update(raw)
        return TickResult(raw, lik, loglik, self._debounced(loglik), pred)

    def _debounced(self, loglik: np.ndarray) -> np.ndarray:
        """Advance the consecutive-hit counters one tick -> alert mask [G]."""
        hits = loglik >= self.threshold
        self._alert_run = np.where(hits, self._alert_run + 1, 0)
        return self._alert_run >= self.debounce

    def _unpack_out(self, out, time_axis: bool):
        """Device step output -> (raw [G], pred [G]|None); strips the leading
        1-tick time axis of the sharded path when present."""
        if self.cfg.classifier.enabled:
            raw, pred = np.asarray(out[0]), np.asarray(out[1])
        else:
            raw, pred = np.asarray(out), None
        if time_axis:
            raw = raw[0]
            pred = None if pred is None else pred[0]
        return raw, pred

    def dispatch_chunk(self, values: np.ndarray, ts: np.ndarray, learn: bool = True) -> dict:
        """Enqueue T ticks on the device WITHOUT blocking on the result.

        JAX dispatch is asynchronous: this returns as soon as the transfer +
        step program are queued, so the host can overlap the previous chunk's
        likelihood post-process (and the next chunk's staging) with device
        compute — the double-buffered feed of SURVEY.md §7 hard part 3.
        Returns an opaque handle for :meth:`collect_chunk`. Handles MUST be
        collected in dispatch order (the likelihood ring is sequential).

        On the CPU backend there is no async device; the chunk is computed
        here and the handle carries the finished scores.
        """
        values = np.asarray(values, np.float32)
        if values.ndim == 2:
            values = values[..., None]
        T = values.shape[0]
        if self.backend == "tpu":
            if self.mesh is not None:
                from rtap_tpu.ops.step import sharded_chunk_step

                self.state, out = sharded_chunk_step(
                    self.state, self._put(values, axis=1),
                    self._put(ts.astype(np.int32), axis=1), self.cfg, self.mesh,
                    learn=learn,
                )
            else:
                from rtap_tpu.ops.step import chunk_step

                self.state, out = chunk_step(
                    self.state, self._put(values, axis=1), self._put(ts.astype(np.int32), axis=1),
                    self.cfg, learn=learn, health=self.health,
                    predict=bool(self.predict),
                )
            health = None
            predict = None
            if self.predict and self.mesh is None:
                # predict wraps outermost (ops/step.py _tick)
                out, predict = out
            if self.health and self.mesh is None:
                out, health = out
            # seq advances only on successful dispatch: a raise above must
            # leave the pipeline collectable, not permanently desynced
            self._seq += 1
            return {"out": out, "health": health, "predict": predict,
                    "T": T, "seq": self._seq, "device": True}
        outs = []
        hticks = []
        pticks = []
        for i in range(T):
            o = self._raw_cpu(values[i], np.asarray(ts[i]), learn)
            outs.append(o)
            if self.health:
                # host twin of the fused reducer, on the post-tick oracle
                # states (same schema as the device leaf, [T, ...] stacked)
                from rtap_tpu.ops.health_tpu import health_from_states

                hticks.append(health_from_states(
                    self._states, o[0], values[i], self.cfg))
            if self.predict:
                from rtap_tpu.models.oracle.predict import predict_from_states

                pticks.append(predict_from_states(
                    self._states, values[i], self.cfg))
        raw = np.stack([o[0] for o in outs])
        pred = np.stack([o[1] for o in outs]) if self.cfg.classifier.enabled else None
        health = {k: np.stack([h[k] for h in hticks]) for k in hticks[0]} \
            if hticks else None
        predict = {k: np.stack([p[k] for p in pticks]) for k in pticks[0]} \
            if pticks else None
        self._seq += 1
        return {"raw": raw, "pred": pred, "health": health,
                "predict": predict, "T": T, "seq": self._seq,
                "device": False}

    def collect_chunk(self, handle: dict) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Block on a dispatched chunk -> (raw [T,G], log_likelihood [T,G],
        alerts [T,G]); classifier predictions land in `self.last_predictions`."""
        if handle["seq"] != self._collected + 1:
            raise RuntimeError(
                f"collect_chunk out of order: handle seq {handle['seq']}, "
                f"expected {self._collected + 1} (likelihood state is sequential)"
            )
        if handle["device"]:
            # the blocking fetch can surface a device error — only a chunk
            # whose scores actually materialized counts as collected
            raw, pred = self._unpack_out(handle["out"], time_axis=False)
        else:
            raw, pred = handle["raw"], handle["pred"]
        if handle.get("health") is not None:
            # fetch rides the same blocking boundary as the scores — no
            # extra device round trip (the leaf is ~200 B/tick)
            self.last_health = {
                k: np.asarray(v) for k, v in handle["health"].items()}
        if handle.get("predict") is not None:
            # same boundary; 13 B/stream/tick (predict_nbytes)
            self.last_predict = {
                k: np.asarray(v) for k, v in handle["predict"].items()}
        self._collected = handle["seq"]
        T = handle["T"]
        self.last_predictions = pred
        self.ticks += T
        loglik = np.empty((T, self.G))
        alerts = np.empty((T, self.G), bool)
        for i in range(T):
            _, loglik[i] = self.likelihood.update(raw[i])
            alerts[i] = self._debounced(loglik[i])
        return raw, loglik, alerts

    def run_chunk(self, values: np.ndarray, ts: np.ndarray, learn: bool = True) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Replay T ticks in one device dispatch, synchronously.

        `values` [T, G] or [T, G, n_fields], `ts` [T, G] ->
        (raw [T, G], log_likelihood [T, G], alerts [T, G]). When the SDR
        classifier is enabled, per-tick predicted values land in
        `self.last_predictions` [T, G]. For the overlapped replay fast path
        use :meth:`dispatch_chunk` + :meth:`collect_chunk` instead.
        """
        return self.collect_chunk(self.dispatch_chunk(values, ts, learn))


@dataclass(frozen=True)
class SlotAddress:
    """A stream's (shard, group, slot) address — the pod-scale
    addressing the source layer routes by (ROADMAP-1; ISSUE 7).

    ``shard`` is the device-mesh shard that owns the slot's state row
    (0 everywhere on a single device; under a mesh the stream axis is
    block-sharded, so shard = slot * n_shards // G). The binary ingest
    protocol packs this triple into its wire slot code
    (rtap_tpu/ingest/protocol.encode_slot)."""

    shard: int
    group: int
    slot: int


@dataclass
class _Slot:
    group: StreamGroup
    index: int


class StreamGroupRegistry:
    """Lazy stream_id -> (group, slot) assignment, the C19 analog.

    Streams are assigned to the open group until it reaches `group_size`,
    then a new group opens. All groups share one ModelConfig so XLA compiles
    the step once per group size (sizes are padded to `group_size` at
    creation; short groups waste slots, not compilations).
    """

    def __init__(
        self,
        cfg: ModelConfig,
        group_size: int = 1024,
        backend: str = "tpu",
        seed: int = 0,
        threshold: float = 0.5,
        mesh=None,
        debounce: int = 1,
        stagger_learn: bool = False,
        health: bool = False,
        predict: int = 0,
    ):
        self.cfg = cfg
        self.health = bool(health)
        self.predict = int(predict)
        # Stagger the learning-cadence phase across groups (group i learns
        # on ticks where (it - i % learn_every) % learn_every == 0): with
        # every group at phase 0 the whole fleet learns on the SAME ticks,
        # so per-tick device compute spikes to the full-fleet learning cost
        # and idles in between — at 100k streams the spike alone exceeds
        # the 1 s cadence that the AVERAGE load fits comfortably.
        # Per-group semantics are identical up to a <k-tick schedule shift;
        # phases derive deterministically from the group index, so a
        # resumed registry rebuilt with the same flags reproduces them.
        self.stagger_learn = bool(stagger_learn) and cfg.learn_every > 1
        self.group_size = int(group_size)
        self.backend = backend
        self.seed = seed
        self.threshold = threshold
        self.debounce = int(debounce)
        self.mesh = mesh
        self.groups: list[StreamGroup] = []
        self._slots: dict[str, _Slot] = {}
        self._pending: list[str] = []
        self._finalized = False
        # bumped on every post-finalize membership change; live_loop watches
        # it to rebuild value/emission routing without re-deriving per tick
        self.version = 0

    def add_stream(self, stream_id: str) -> None:
        """Register a stream. Before :meth:`finalize`: buffered into the
        next group (the bulk path). After: the stream CLAIMS a free pad
        slot in the first group with capacity — the reference's lazy
        model-per-stream creation (SURVEY.md C19), with no recompile
        (shapes are static). Raises RuntimeError when every slot is live;
        capacity comes from group-size rounding, `reserve` slots, or
        released streams."""
        if stream_id.startswith(PAD_PREFIX):
            # same guard claim_slot enforces: a pad-prefixed id on the bulk
            # path would silently read as pad capacity (never emitted, its
            # slot re-claimable) — two index entries, one slot
            raise ValueError(f"stream id may not start with {PAD_PREFIX!r}")
        if stream_id in self._slots or stream_id in self._pending:
            raise KeyError(f"duplicate stream id {stream_id!r}")
        if self._finalized:
            for grp in self.groups:
                if grp.free_slot_count():
                    slot = grp.claim_slot(stream_id)
                    self._slots[stream_id] = _Slot(grp, slot)
                    self.version += 1
                    return
            raise RuntimeError(
                f"registry at capacity ({len(self._slots)} live streams, 0 "
                "free slots): pre-provision with reserve= or release "
                "departed streams")
        self._pending.append(stream_id)
        if len(self._pending) == self.group_size:
            self._seal()

    def remove_stream(self, stream_id: str) -> None:
        """Release a departed stream's slot back to pad capacity: it stops
        being fed and emitted next tick, and the slot becomes claimable by
        a future add_stream (which resets its state). Post-finalize only —
        before finalize just don't add it."""
        if not self._finalized:
            raise RuntimeError("remove_stream is a post-finalize operation")
        s = self._slots.pop(stream_id, None)
        if s is None:
            raise KeyError(f"unknown stream id {stream_id!r}")
        s.group.release_slot(stream_id)
        self.version += 1

    def _seal(self) -> None:
        if not self._pending:
            return
        ids = self._pending
        # pad to the fixed group size so every group compiles to one program
        padded = ids + [f"__pad{i}" for i in range(self.group_size - len(ids))]
        grp = StreamGroup(
            self._group_cfg(len(self.groups)), padded,
            seed=self.seed + len(self.groups),
            backend=self.backend, threshold=self.threshold, mesh=self.mesh,
            debounce=self.debounce, health=self.health, predict=self.predict,
        )
        for i, sid in enumerate(ids):
            self._slots[sid] = _Slot(grp, i)
        self.groups.append(grp)
        self._pending = []

    def finalize(self, reserve: int = 0) -> None:
        """Seal the last partially-filled group (call once ingestion is
        known). `reserve` adds that many extra pad slots of claimable
        capacity for post-finalize registration (rounded up to whole
        groups of `group_size`; each reserve group is all-pad until
        streams claim into it)."""
        if reserve < 0:
            raise ValueError(f"reserve must be >= 0; got {reserve}")
        # account pads the natural rounding already leaves in the last group
        rounding_pads = (-len(self._pending)) % self.group_size \
            if self._pending else 0
        self._seal()
        extra = max(0, reserve - rounding_pads)
        for _ in range((extra + self.group_size - 1) // self.group_size):
            self._seal_all_pad()
        self._finalized = True

    def _group_cfg(self, gi: int) -> ModelConfig:
        """The config group `gi` is built with: the registry cfg, cadence
        phase-shifted by gi when stagger_learn is on (at most learn_every
        distinct compiled programs fleet-wide — the phase is a static
        config field). With learn_burst=B the schedule's cycle is k*B
        ticks and a useful stagger offsets whole B-tick bursts: phase
        (gi mod k) * B puts exactly 1/k of the groups in their burst on
        any post-maturity tick — the same leveling the spread schedule
        gets from gi mod k."""
        if not self.stagger_learn:
            return self.cfg
        import dataclasses

        return dataclasses.replace(
            self.cfg,
            learn_phase=(gi % self.cfg.learn_every) * self.cfg.learn_burst)

    def _seal_all_pad(self) -> None:
        """Append one all-pad reserve group (claimable capacity)."""
        grp = StreamGroup(
            self._group_cfg(len(self.groups)),
            [f"{PAD_PREFIX}{i}" for i in range(self.group_size)],
            seed=self.seed + len(self.groups), backend=self.backend,
            threshold=self.threshold, mesh=self.mesh, debounce=self.debounce,
            health=self.health, predict=self.predict,
        )
        self.groups.append(grp)

    def lookup(self, stream_id: str) -> tuple[StreamGroup, int]:
        s = self._slots[stream_id]
        return s.group, s.index

    def __contains__(self, stream_id: str) -> bool:
        return stream_id in self._slots or stream_id in self._pending

    def dispatch_ids(self) -> list[str]:
        """Live stream ids in (group, slot) order — the value-vector order
        live_loop's routing and every source snapshot must follow."""
        return [g.stream_ids[i] for g in self.groups for i in g.live_slots()]

    def slot_map(self) -> dict[str, SlotAddress]:
        """Live stream id -> (shard, group, slot) address — what the
        registry hands sources instead of a flat id list (ROADMAP-1).

        Iterating the map in (group, slot) order reproduces
        :meth:`dispatch_ids` exactly (pinned by
        tests/unit/test_ingest_protocol.py), so a source that scatters
        by address and a loop that routes positionally agree by
        construction. Pads/released slots are absent — a wire record
        addressed at one is an unknown, not a write."""
        out: dict[str, SlotAddress] = {}
        for gi, g in enumerate(self.groups):
            n_shards = 1
            if g.mesh is not None:
                n_shards = int(g.mesh.devices.size)
                from rtap_tpu.ingest.protocol import MAX_SHARDS

                if n_shards > MAX_SHARDS:
                    raise ValueError(
                        f"mesh has {n_shards} devices but the ingest "
                        f"slot code carries {MAX_SHARDS} shards max "
                        "(rtap_tpu/ingest/protocol.py SHARD_BITS; a "
                        "wider mesh needs a protocol magic bump)")
            for slot in g.live_slots():
                slot = int(slot)
                out[g.stream_ids[slot]] = SlotAddress(
                    shard=slot * n_shards // g.G, group=gi, slot=slot)
        return out

    @property
    def free_slots(self) -> int:
        return sum(g.free_slot_count() for g in self.groups)

    @property
    def n_streams(self) -> int:
        return len(self._slots) + len(self._pending)
